"""Python side of the C inference API (reference paddle/capi/).

The reference's C API wraps GradientMachine for embedding into C/C++ apps
(capi/gradient_machine.h:36-59); its trainer embeds Python for config
parsing (utils/PythonUtil.cpp).  The TPU-native C API mirrors both ideas:
libpaddle_tpu_capi.so (native/src/capi.cpp) embeds CPython and calls into
this module, which builds the topology from a Python config file and runs
jitted inference on the default JAX device.

The config file is executed and must expose the output layer(s) as a
module-level `predict` LayerOutput (or set `__outputs__` = [layers]).  The
parameter file is a merged model (trainer.checkpoint.merge_model).
"""

import os
import threading
import traceback

import numpy as np


from paddle_tpu._platform import \
    honor_jax_platforms_env as _honor_jax_platforms_env


_machines = {}
_next_id = [1]
_id_lock = threading.Lock()   # handle allocation under concurrent C threads


def _alloc_id():
    with _id_lock:
        nid = _next_id[0]
        _next_id[0] += 1
        return nid


# per-thread error slot: concurrent C threads (pt_capi_clone pattern) must
# each read their OWN failure, not the last one process-wide
_tls = threading.local()


def last_error():
    return getattr(_tls, "err", "")


def _store_error(e):
    _tls.err = "".join(
        traceback.format_exception(type(e), e, e.__traceback__))
    return -1


def create(config_path, params_path):
    """Build an inference machine; returns handle id (>0) or -1."""
    try:
        _honor_jax_platforms_env()
        import jax.numpy as jnp
        from paddle_tpu.layers.graph import LayerOutput
        from paddle_tpu.trainer.checkpoint import load_merged
        from paddle_tpu.trainer.trainer import Inferencer

        ns = {"__name__": "__paddle_tpu_config__"}
        with open(config_path) as f:
            exec(compile(f.read(), config_path, "exec"), ns)
        outs = ns.get("__outputs__")
        if outs is None:
            outs = ns.get("predict")
        if outs is None:
            outs = [v for v in ns.values() if isinstance(v, LayerOutput)][-1:]
        if not outs:
            raise ValueError(
                f"{config_path} defines no output layer (set `predict = "
                "<LayerOutput>` or `__outputs__ = [...]`)")
        params, model_state, _meta = load_merged(params_path)
        inf = Inferencer(outs, params, model_state)
        mid = _alloc_id()
        _machines[mid] = {"inf": inf, "feed": {}, "outs": None}
        return mid
    except Exception as e:  # noqa: BLE001 - crosses the C ABI
        return _store_error(e)


def create_exported(path):
    """Build an inference machine from a serialized StableHLO artifact
    (export.export_inference); the C service needs neither the config file
    nor the merged params — the artifact is self-contained.  Returns
    handle id (>0) or -1."""
    try:
        _honor_jax_platforms_env()
        from paddle_tpu.export import load_inference
        run_fn = load_inference(path)
        mid = _alloc_id()
        _machines[mid] = {"call": run_fn, "feed": {}, "outs": None}
        return mid
    except Exception as e:  # noqa: BLE001 - crosses the C ABI
        return _store_error(e)


def set_input_dense(mid, name, arr):
    try:
        _machines[mid]["feed"][name] = np.asarray(arr, np.float32)
        return 0
    except Exception as e:
        return _store_error(e)


def set_input_sparse_binary(mid, name, dim, col_ids, row_offsets):
    """Sparse-binary input in CSR form (reference capi/matrix.h
    paddle_matrix_create_sparse + paddle_matrix_sparse_copy_from:
    row_offsets has rows+1 entries; col_ids[row_offsets[i]:row_offsets[i+1]]
    are the set columns of row i).  Densified to float32 [rows, dim] — the
    MXU path takes dense rows, same as data/feeder.py's sparse_binary
    handling."""
    try:
        col_ids = np.asarray(col_ids, np.int64)
        row_offsets = np.asarray(row_offsets, np.int64)
        rows = len(row_offsets) - 1
        if (rows < 0 or row_offsets[0] != 0
                or row_offsets[-1] != len(col_ids)
                or (rows > 0 and np.any(np.diff(row_offsets) < 0))):
            raise ValueError(
                f"bad CSR: offsets {row_offsets.tolist()} for "
                f"{len(col_ids)} col ids (must start at 0, end at n_cols, "
                "and be non-decreasing)")
        out = np.zeros((rows, dim), np.float32)
        for i in range(rows):
            cols = col_ids[row_offsets[i]:row_offsets[i + 1]]
            if len(cols) and (cols.min() < 0 or cols.max() >= dim):
                raise ValueError(f"col id out of range [0, {dim}) in row {i}")
            out[i, cols] = 1.0
        _machines[mid]["feed"][name] = out
        return 0
    except Exception as e:
        return _store_error(e)


def clone_shared(mid):
    """New handle sharing the loaded machine's parameters (reference
    capi/gradient_machine.h paddle_gradient_machine_create_shared_param:
    per-thread machines over one parameter set).  The Inferencer — params
    and jitted fn — is shared; only the feed/output slots are per-handle,
    so concurrent threads don't race on inputs."""
    try:
        m = _machines[mid]
        engine = {k: m[k] for k in ("inf", "call") if k in m}
        nid = _alloc_id()
        _machines[nid] = dict(engine, feed={}, outs=None)
        return nid
    except Exception as e:
        return _store_error(e)


def set_input_ids(mid, name, ids, lengths=None):
    try:
        ids = np.asarray(ids, np.int32)
        if lengths is not None:
            from paddle_tpu.core.sequence import SequenceBatch
            import jax.numpy as jnp
            _machines[mid]["feed"][name] = SequenceBatch(
                data=jnp.asarray(ids), lengths=jnp.asarray(
                    np.asarray(lengths, np.int32)))
        else:
            _machines[mid]["feed"][name] = ids
        return 0
    except Exception as e:
        return _store_error(e)


def run(mid):
    """Run forward; returns number of outputs or -1."""
    try:
        m = _machines[mid]
        if "call" in m:   # StableHLO-exported machine (create_exported)
            out = m["call"](dict(m["feed"]))
        else:
            out = m["inf"].infer(dict(m["feed"]))
        outs = out if isinstance(out, tuple) else (out,)
        arrs = []
        for o in outs:
            data = o.data if hasattr(o, "data") else o
            arrs.append(np.asarray(data, np.float32))
        m["outs"] = arrs
        return len(arrs)
    except Exception as e:
        return _store_error(e)


def output_shape(mid, idx):
    """[rows, cols] with trailing dims flattened; 0-d outputs are [1, 1]."""
    try:
        a = _machines[mid]["outs"][idx]
        if a.ndim == 0:
            return [1, 1]
        return [int(a.shape[0]), int(np.prod(a.shape[1:], dtype=np.int64))]
    except Exception as e:
        _store_error(e)
        return [-1, -1]


def get_output(mid, idx):
    """Returns the output as flat float32 bytes."""
    try:
        a = _machines[mid]["outs"][idx]
        return np.ascontiguousarray(a, np.float32).tobytes()
    except Exception as e:
        _store_error(e)
        return b""


def destroy(mid):
    _machines.pop(mid, None)
    return 0
