"""Graphviz model diagrams from a Topology (reference
python/paddle/utils/make_model_diagram.py, which walked the config proto).

  from paddle_tpu.utils.tools import make_diagram
  make_diagram(topology_or_cost_layer, "model.dot")
  # dot -Tpng model.dot -o model.png
"""


def topology_dot(topology, name="model"):
    from paddle_tpu.layers.graph import LayerOutput, Topology
    if isinstance(topology, LayerOutput):
        topology = Topology([topology])
    lines = [f"digraph {name} {{", "  rankdir=BT;",
             '  node [shape=box, fontsize=10];']
    for node in topology.order:
        shape = "ellipse" if node.layer_type == "data" else "box"
        style = ', style=filled, fillcolor="#e8f0fe"' \
            if node.layer_type == "data" else ""
        label = f"{node.name}\\n{node.layer_type} [{node.size}]"
        lines.append(f'  "{node.name}" [label="{label}", shape={shape}{style}];')
    for node in topology.order:
        for src in node.inputs:
            lines.append(f'  "{src.name}" -> "{node.name}";')
    lines.append("}")
    return "\n".join(lines)


def make_diagram(topology, out_path, name="model"):
    dot = topology_dot(topology, name=name)
    with open(out_path, "w") as f:
        f.write(dot + "\n")
    return out_path
