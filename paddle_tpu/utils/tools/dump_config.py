"""Dump a compiled v1 config (reference python/paddle/utils/dump_config.py:1).

The reference printed the parsed TrainerConfig/ModelConfig protobuf; here
parse_config compiles to the graph IR, and this tool prints it in the same
text-proto style (layers/input_layer_names/output_layer_names/parameters)
so config diffs remain greppable.  `--whole` adds the trainer settings and
data sources, like the reference's whole-conf mode.

Usage:
  python -m paddle_tpu.utils.tools.dump_config CONF [CONFIG_ARGS] [--whole]
"""

import sys


def format_model(topology, outputs):
    lines = []
    input_names = [n.name for n in topology.order if n.layer_type == "data"]
    output_names = [o.name for o in outputs]
    for node in topology.order:
        lines.append("layers {")
        lines.append(f'  name: "{node.name}"')
        lines.append(f'  type: "{node.layer_type}"')
        if node.size is not None:
            lines.append(f"  size: {node.size}")
        for src in node.inputs:
            lines.append("  inputs {")
            lines.append(f'    input_layer_name: "{src.name}"')
            lines.append("  }")
        key = topology._param_key(node)
        if node.cfg.get("param_attr") or node.cfg.get("param_name"):
            lines.append(f'  param_key: "{key}"')
        lines.append("}")
    for name in input_names:
        lines.append(f'input_layer_names: "{name}"')
    for name in output_names:
        lines.append(f'output_layer_names: "{name}"')
    return "\n".join(lines)


def format_settings(settings, data_sources):
    lines = ["settings {"]
    for k, v in sorted(settings.items()):
        if v is not None and not k.startswith("_"):
            lines.append(f"  {k}: {v!r}")
    lines.append("}")
    if data_sources:
        lines.append(f"data_sources: {data_sources!r}")
    return "\n".join(lines)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    whole = "--whole" in argv
    argv = [a for a in argv if a != "--whole"]
    if not 1 <= len(argv) <= 2:
        raise SystemExit(
            "usage: dump_config CONF [CONFIG_ARGS] [--whole]")
    conf_path = argv[0]
    config_args = argv[1] if len(argv) > 1 else ""

    from paddle_tpu.compat.config_parser import parse_config
    from paddle_tpu.layers.graph import Topology
    parsed = parse_config(conf_path, config_args)
    outs = list(parsed.outputs or [])
    topo = Topology(outs)
    out = format_model(topo, outs)
    if whole:
        out = format_settings(parsed.settings, parsed.data_sources) \
            + "\n" + out
    print(out)


if __name__ == "__main__":
    main()
