"""Plot train/test cost curves from trainer logs (reference
python/paddle/utils/plotcurve.py: parse 'Pass N ... cost C' lines, plot
keys with matplotlib).

Usage:
  python -m paddle_tpu.utils.tools.plotcurve -i train.log -o curve.png [keys]

Our log lines: "Pass 3 done, mean cost 0.12854" and
"Pass 3 Batch 40 Cost 0.15887 ..." plus "Eval: name=value" suffixes.
Default key: the per-pass mean cost."""

import argparse
import re
import sys

PASS_RE = re.compile(r"Pass (\d+) done, mean cost ([-\d.eE]+)")
EVAL_RE = re.compile(r"(\w+)=([-\d.eE]+)")


def parse_log(lines, keys=("cost",)):
    """-> {key: [(pass_id, value), ...]}"""
    out = {k: [] for k in keys}
    for line in lines:
        m = PASS_RE.search(line)
        if m:
            pass_id, cost = int(m.group(1)), float(m.group(2))
            if "cost" in out:
                out["cost"].append((pass_id, cost))
            for k, v in EVAL_RE.findall(line):
                if k in out and k != "cost":
                    out[k].append((pass_id, float(v)))
    return out


def plot_curves(lines, output, keys=("cost",), fmt="png"):
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    data = parse_log(lines, keys)
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for k, pts in data.items():
        if pts:
            xs, ys = zip(*pts)
            ax.plot(xs, ys, marker="o", markersize=3, label=k)
    ax.set_xlabel("pass")
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(output, format=fmt)
    return data


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-i", "--input", default=None)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--format", default="png")
    p.add_argument("keys", nargs="*", default=["cost"])
    args = p.parse_args(argv)
    lines = open(args.input) if args.input else sys.stdin
    plot_curves(lines, args.output, keys=tuple(args.keys) or ("cost",),
                fmt=args.format)
    if args.input:
        lines.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
