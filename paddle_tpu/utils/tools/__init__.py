"""User tooling (reference python/paddle/utils/): plotcurve,
make_model_diagram, preprocess_img, torch import."""

from paddle_tpu.utils.tools.plotcurve import plot_curves  # noqa: F401
from paddle_tpu.utils.tools.diagram import make_diagram, topology_dot  # noqa: F401
from paddle_tpu.utils.tools.torch_import import from_torch_state_dict  # noqa: F401
