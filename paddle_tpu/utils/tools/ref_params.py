"""Reference binary Parameter-file interop (clean-room from the format
the reference documents in demo/model_zoo/embedding/paraconvert.py:33-55
and writes in parameter/Parameter.cpp:281-307):

    header, 16 bytes little-endian x86 layout:
        version     int32   (0 in every shipped model)
        float_size  int32   sizeof(real): 4 or 8
        para_count  int64   total number of scalars
    body: para_count scalars of float_size bytes

This is the format of every reference checkpoint param file
(pass-%05d/<param_name>) AND of the shipped pretrained model_zoo
artifacts (ResNet weights, baidu.dict embedding table), so reading it is
the migration path for weights trained on the reference.

Functions mirror the reference tooling: read/write single files,
binary<->text (paraconvert.py parity, same text layout), pass-dir bulk
load, and the extract_para.py sub-dict row extraction."""

import os
import struct

import numpy as np

_HEADER = struct.Struct("<iiq")     # version, float_size, para_count


def _parse_header(path):
    """(version, float_size, count) if the file CARRIES a plausible
    reference header, else None — the is-this-a-param-file test."""
    try:
        with open(path, "rb") as f:
            head = f.read(_HEADER.size)
    except OSError:
        return None
    if len(head) != _HEADER.size:
        return None
    version, float_size, count = _HEADER.unpack(head)
    if float_size not in (4, 8) or count < 0:
        return None
    return version, float_size, count


def read_param(path, with_header=False):
    """-> flat np array (f32 or f64 per the file's float_size); with
    with_header=True, (array, (version, float_size)).

    version != 0 is REJECTED, mirroring the reference's
    Parameter.cpp CHECK (every shipped model writes version 0) — a
    nonzero version means either corruption or a format this reader does
    not understand, and silently accepting it would misinterpret the
    body."""
    parsed = _parse_header(path)
    if parsed is None:
        raise ValueError(
            f"{path}: no reference Parameter header (16 bytes: version "
            "i32, float_size i32 in {{4,8}}, count i64)")
    version, float_size, count = parsed
    if version != 0:
        raise ValueError(
            f"{path}: Parameter version {version} unsupported (the "
            "reference CHECKs version == 0 in every shipped file; a "
            "nonzero value here is corruption or a different format)")
    dt = np.float32 if float_size == 4 else np.float64
    with open(path, "rb") as f:
        f.seek(_HEADER.size)
        data = np.fromfile(f, dtype=dt, count=count)
    if data.size != count:
        raise ValueError(f"{path}: body has {data.size} scalars, header "
                         f"promises {count}")
    return (data, (version, float_size)) if with_header else data


def write_param(path, arr, version=0, float_size=None):
    """Write a reference-format binary param file; float_size defaults to
    the array's own width (f64 in -> f64 file)."""
    arr = np.asarray(arr)
    if float_size is None:
        float_size = 8 if arr.dtype == np.float64 else 4
    dt = np.float32 if float_size == 4 else np.float64
    arr = np.ascontiguousarray(arr, dt).reshape(-1)
    with open(path, "wb") as f:
        f.write(_HEADER.pack(version, float_size, arr.size))
        arr.tofile(f)


def binary2text(in_path, out_path, dim):
    """paraconvert.py --b2t: header line 'version,float_size,count', then
    count/dim lines of dim comma-joined values.  Header metadata and
    precision follow the SOURCE file (f64 stays f64 through the round
    trip)."""
    data, (version, float_size) = read_param(in_path, with_header=True)
    if data.size % dim:
        raise ValueError(f"{in_path}: {data.size} scalars not divisible "
                         f"by dim={dim}")
    fmt = "{:.7f}" if float_size == 4 else "{:.17g}"
    with open(out_path, "w") as f:
        f.write(f"{version},{float_size},{data.size}\n")
        for row in data.reshape(-1, dim):
            f.write(",".join(fmt.format(v) for v in row) + "\n")
    return data.size // dim


def text2binary(in_path, out_path):
    """paraconvert.py --t2b: inverse of binary2text (header's version and
    float_size are preserved into the binary)."""
    with open(in_path) as f:
        head = f.readline().strip().split(",")
        version, float_size, count = int(head[0]), int(head[1]), int(head[2])
        dt = np.float32 if float_size == 4 else np.float64
        vals = np.loadtxt(f, delimiter=",", dtype=dt, ndmin=2)
    flat = vals.reshape(-1)
    if flat.size != count:
        raise ValueError(f"{in_path}: {flat.size} values, header "
                         f"promises {count}")
    write_param(out_path, flat, version=version, float_size=float_size)
    return flat.size


def load_pass_dir(pass_dir):
    """Reference checkpoint dir (pass-%05d/ with one binary file per
    parameter) -> {param_name: flat array}.  Entries WITHOUT a parseable
    reference header (done markers, subdirs) are skipped; a file that
    carries the header but fails to read (truncated body, version != 0)
    RAISES — a silently dropped param would fall back to random init
    downstream."""
    out = {}
    for name in sorted(os.listdir(pass_dir)):
        p = os.path.join(pass_dir, name)
        if not os.path.isfile(p) or _parse_header(p) is None:
            continue
        out[name] = read_param(p)
    return out


def extract_rows(emb_path, indices, dim):
    """extract_para.py role: pull the embedding rows of a sub-dict out of
    a full pretrained table.  indices: word ids into the big table, or
    None for every row (one read, no gather)."""
    data = read_param(emb_path)
    if data.size % dim:
        raise ValueError(f"{emb_path}: {data.size} scalars not divisible "
                         f"by dim={dim}")
    table = data.reshape(-1, dim)
    if indices is None:
        return table
    idx = np.asarray(indices, np.int64)
    if idx.size and (idx.min() < 0 or idx.max() >= table.shape[0]):
        raise ValueError(
            f"indices span [{idx.min()}, {idx.max()}] but table has "
            f"{table.shape[0]} rows")
    return table[idx]


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="reference binary Parameter-file converter "
                    "(paraconvert.py parity)")
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--b2t", action="store_true")
    g.add_argument("--t2b", action="store_true")
    ap.add_argument("-i", required=True, help="input file")
    ap.add_argument("-o", required=True, help="output file")
    ap.add_argument("-d", type=int, default=None,
                    help="embedding dim (required for --b2t)")
    args = ap.parse_args(argv)
    if args.b2t:
        if not args.d:
            ap.error("--b2t needs -d DIM")
        n = binary2text(args.i, args.o, args.d)
        print(f"wrote {args.o}: {n} rows x {args.d}")
    else:
        n = text2binary(args.i, args.o)
        print(f"wrote {args.o}: {n} scalars")


if __name__ == "__main__":
    main()
