"""Image-folder preprocessing into the native record format (reference
python/paddle/utils/preprocess_img.py: resize + split + per-channel mean
into batched pickles; here records stream through native.RecordReader and
the mean rides in a sidecar .meta.npz).

  python -m paddle_tpu.utils.tools.preprocess_img \
      --in_dir images/ --out_dir data/ --size 64 [--test_ratio 0.1]

in_dir layout: one subdirectory per class (label = sorted subdir index).
"""

import argparse
import io
import json
import os
import sys

import numpy as np


def _iter_images(in_dir):
    classes = sorted(d for d in os.listdir(in_dir)
                     if os.path.isdir(os.path.join(in_dir, d)))
    for label, cls in enumerate(classes):
        cdir = os.path.join(in_dir, cls)
        for fname in sorted(os.listdir(cdir)):
            yield os.path.join(cdir, fname), label
    return


def preprocess(in_dir, out_dir, size=64, test_ratio=0.1, seed=0):
    from PIL import Image
    from paddle_tpu import native
    os.makedirs(out_dir, exist_ok=True)
    classes = sorted(d for d in os.listdir(in_dir)
                     if os.path.isdir(os.path.join(in_dir, d)))
    rng = np.random.RandomState(seed)
    writers = {
        "train": native.RecordWriter(os.path.join(out_dir, "train.rec")),
        "test": native.RecordWriter(os.path.join(out_dir, "test.rec")),
    }
    mean_acc = np.zeros((3,), np.float64)
    n_train = 0
    counts = {"train": 0, "test": 0}
    for path, label in _iter_images(in_dir):
        try:
            img = Image.open(path).convert("RGB").resize((size, size))
        except Exception:
            continue
        arr = np.asarray(img, np.uint8)               # [H, W, 3]
        split = "test" if rng.rand() < test_ratio else "train"
        payload = io.BytesIO()
        np.savez_compressed(payload, img=arr, label=np.int32(label))
        writers[split].put(payload.getvalue())
        counts[split] += 1
        if split == "train":
            mean_acc += arr.reshape(-1, 3).mean(axis=0)
            n_train += 1
    for w in writers.values():
        w.close()
    mean = (mean_acc / max(n_train, 1)).astype(np.float32)
    np.savez(os.path.join(out_dir, "meta.npz"), mean=mean,
             size=np.int32(size))
    with open(os.path.join(out_dir, "labels.json"), "w") as f:
        json.dump(classes, f)
    return counts, mean


def record_reader(rec_path, meta_path=None):
    """Reader over a preprocessed .rec: yields (normalized [H*W*3] float
    rows, label) like the reference's batched-pickle provider."""
    from paddle_tpu import native
    mean = None
    if meta_path and os.path.exists(meta_path):
        mean = np.load(meta_path)["mean"]

    def reader():
        for payload in native.RecordReader(rec_path):
            z = np.load(io.BytesIO(payload))
            arr = z["img"].astype(np.float32)
            if mean is not None:
                arr = arr - mean
            yield arr.reshape(-1) / 255.0, int(z["label"])
    return reader


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--in_dir", required=True)
    p.add_argument("--out_dir", required=True)
    p.add_argument("--size", type=int, default=64)
    p.add_argument("--test_ratio", type=float, default=0.1)
    args = p.parse_args(argv)
    counts, mean = preprocess(args.in_dir, args.out_dir, args.size,
                              args.test_ratio)
    print(f"wrote {counts} mean={mean}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
