"""Wire-level protobuf dump (reference python/paddle/utils/show_pb.py:1).

The reference printed a binary ModelConfig with generated bindings; this
build carries no protoc output, so the dump decodes the raw proto wire
format instead (reusing data/proto_format's field walker): every field
prints as `<number>: <value>`, length-delimited payloads are recursively
decoded as messages when they parse cleanly, else shown as utf-8/hex.
Works on ANY protobuf file — reference model configs, DataFormat records,
checkpoints from other tools.

Usage:  python -m paddle_tpu.utils.tools.show_pb FILE [--max-bytes N]
"""

import sys

from paddle_tpu.data.proto_format import _fields, _WIRE_LEN
from paddle_tpu.utils.error import ConfigError


def _try_message(buf, depth, max_depth):
    """Decode buf as a message if every field parses; else None."""
    if depth >= max_depth or len(buf) == 0:
        return None
    try:
        fields = list(_fields(bytes(buf)))
    except ConfigError:
        return None
    return fields or None


def format_pb(buf, indent=0, depth=0, max_depth=8, out=None, fields=None):
    """fields: pre-parsed output of _fields for buf (avoids re-walking
    payloads the recursion already decoded)."""
    out = out if out is not None else []
    pad = "  " * indent
    if fields is None:
        try:
            fields = list(_fields(bytes(buf)))
        except ConfigError as e:
            out.append(f"{pad}<unparseable: {e}>")
            return out
    for field, wire, val in fields:
        if wire == _WIRE_LEN:
            sub = _try_message(val, depth + 1, max_depth)
            if sub is not None:
                out.append(f"{pad}{field} {{")
                format_pb(val, indent + 1, depth + 1, max_depth, out,
                          fields=sub)
                out.append(f"{pad}}}")
                continue
            raw = bytes(val)
            try:
                txt = raw.decode("utf-8")
                if txt.isprintable() or txt == "":
                    out.append(f'{pad}{field}: "{txt}"')
                    continue
            except UnicodeDecodeError:
                pass
            shown = raw[:24].hex()
            more = f"... ({len(raw)} bytes)" if len(raw) > 24 else ""
            out.append(f"{pad}{field}: 0x{shown}{more}")
        elif wire == 5:     # fixed32: show both int and float views
            import struct
            i = int.from_bytes(bytes(val), "little")
            f = struct.unpack("<f", bytes(val))[0]
            out.append(f"{pad}{field}: {i} (f32 {f:.6g})")
        elif wire == 1:     # fixed64
            import struct
            i = int.from_bytes(bytes(val), "little")
            d = struct.unpack("<d", bytes(val))[0]
            out.append(f"{pad}{field}: {i} (f64 {d:.6g})")
        else:
            out.append(f"{pad}{field}: {val}")
    return out


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    usage = "usage: show_pb FILE [--max-bytes N]"
    max_bytes = None
    if "--max-bytes" in argv:
        i = argv.index("--max-bytes")
        try:
            max_bytes = int(argv[i + 1])
        except (IndexError, ValueError):
            raise SystemExit(usage)
        del argv[i:i + 2]
    if len(argv) != 1:
        raise SystemExit(usage)
    from paddle_tpu.data.proto_format import _open
    with _open(argv[0]) as f:       # handles .gz like the data providers
        data = f.read(max_bytes) if max_bytes else f.read()
    # a bare serialized message (the reference show_pb case) parses whole;
    # data FILES are varint-delimited message streams (ProtoReader framing)
    lines = format_pb(data)
    if any(l.startswith("<unparseable") for l in lines):
        import io
        from paddle_tpu.data.proto_format import _read_messages
        lines = []
        try:
            for i, msg in enumerate(_read_messages(io.BytesIO(data))):
                lines.append(f"message {i} ({len(msg)} bytes) {{")
                format_pb(msg, indent=1, out=lines)
                lines.append("}")
        except ConfigError as e:
            lines.append(f"<stream truncated: {e}>")
    print("\n".join(lines))


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        # `show_pb file | head` closing the pipe is normal CLI usage;
        # confined here so library callers keep their stderr
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stderr.fileno())
        sys.exit(0)
