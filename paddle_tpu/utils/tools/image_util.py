"""Image preprocessing utilities (reference python/paddle/utils/image_util.py:1).

Same function surface — resize/flip/crop/oversample/mean-subtract and the
ImageTransformer pipeline used by the image demos' providers and the model
zoo's feature extractor.  NHWC note: these helpers keep the reference's CHW
array convention at the boundary (providers emit flat vectors); the layer
stack converts to NHWC internally (layers/vision.py).
"""

import io

import numpy as np
from PIL import Image


def resize_image(img, target_size):
    """Resize a PIL image so the SHORTER edge is target_size."""
    scale = target_size / float(min(img.size))
    new_size = (int(round(img.size[0] * scale)),
                int(round(img.size[1] * scale)))
    return img.resize(new_size, Image.LANCZOS)


def flip(im):
    """Horizontal flip; im is (K, H, W) color or (H, W) gray."""
    return im[..., ::-1]


def crop_img(im, inner_size, color=True, test=True):
    """Crop to inner_size x inner_size: center crop in test mode, random
    crop + random horizontal flip in train mode.  Images smaller than
    inner_size are zero-padded to it first (reference crop_img)."""
    im = im.astype("float32")
    h_axis, w_axis = (1, 2) if color else (0, 1)
    height = max(inner_size, im.shape[h_axis])
    width = max(inner_size, im.shape[w_axis])
    shape = (3, height, width) if color else (height, width)
    padded = np.zeros(shape, "float32")
    y0 = (height - im.shape[h_axis]) // 2
    x0 = (width - im.shape[w_axis]) // 2
    sl = (slice(y0, y0 + im.shape[h_axis]), slice(x0, x0 + im.shape[w_axis]))
    padded[(slice(None),) + sl if color else sl] = im
    if test:
        y, x = (height - inner_size) // 2, (width - inner_size) // 2
    else:
        y = np.random.randint(0, height - inner_size + 1)
        x = np.random.randint(0, width - inner_size + 1)
    sl = (slice(y, y + inner_size), slice(x, x + inner_size))
    pic = padded[(slice(None),) + sl if color else sl]
    if not test and np.random.randint(2) == 0:
        pic = flip(pic)
    return pic


def decode_jpeg(jpeg_string):
    """JPEG bytes -> (K, H, W) ndarray (color) or (H, W) (gray)."""
    arr = np.array(Image.open(io.BytesIO(jpeg_string)))
    if arr.ndim == 3:
        arr = arr.transpose(2, 0, 1)
    return arr


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """Crop (+augment when training), subtract the dataset mean, flatten."""
    pic = crop_img(im.astype("float32"), crop_size, color, test=not is_train)
    return (pic - img_mean).flatten()


def load_meta(meta_path, mean_img_size, crop_size, color=True):
    """Load the dataset-mean .npz ('data_mean' key) and center-crop it to
    crop_size (reference load_meta)."""
    mean = np.load(meta_path)["data_mean"]
    border = (mean_img_size - crop_size) // 2
    if color:
        assert mean_img_size * mean_img_size * 3 == mean.shape[0]
        mean = mean.reshape(3, mean_img_size, mean_img_size)
        sl = (slice(None), slice(border, border + crop_size),
              slice(border, border + crop_size))
    else:
        assert mean_img_size * mean_img_size == mean.shape[0]
        mean = mean.reshape(mean_img_size, mean_img_size)
        sl = (slice(border, border + crop_size),
              slice(border, border + crop_size))
    return mean[sl].astype("float32")


def load_image(img_path, is_color=True):
    img = Image.open(img_path)
    img.load()
    return img.convert("RGB" if is_color else "L")


def oversample(img, crop_dims):
    """Ten crops per image: 4 corners + center, each with its mirror.
    img: iterable of (H, W, K) ndarrays; returns [10*N, ch, cw, K]."""
    im_shape = np.array(img[0].shape)
    crop_dims = np.array(crop_dims)
    center = im_shape[:2] / 2.0
    corners = []
    for i in (0, im_shape[0] - crop_dims[0]):
        for j in (0, im_shape[1] - crop_dims[1]):
            corners.append((i, j, i + crop_dims[0], j + crop_dims[1]))
    corners.append(tuple(np.concatenate(
        [center - crop_dims / 2.0, center + crop_dims / 2.0]).astype(int)))
    crops_ix = np.tile(np.asarray(corners, int), (2, 1))
    crops = np.empty((10 * len(img), crop_dims[0], crop_dims[1],
                      im_shape[-1]), np.float32)
    ix = 0
    for im in img:
        for y0, x0, y1, x1 in crops_ix:
            crops[ix] = im[y0:y1, x0:x1, :]
            ix += 1
        crops[ix - 5:ix] = crops[ix - 5:ix, :, ::-1, :]   # mirrors
    return crops


class ImageTransformer:
    """Channel-order / mean-subtraction pipeline (reference
    image_util.py:183)."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color=True):
        self.is_color = is_color
        self.set_transpose(transpose)
        self.set_channel_swap(channel_swap)
        self.set_mean(mean)

    def set_transpose(self, order):
        if order is not None and self.is_color:
            assert len(order) == 3
        self.transpose = order

    def set_channel_swap(self, order):
        if order is not None and self.is_color:
            assert len(order) == 3
        self.channel_swap = order

    def set_mean(self, mean):
        if mean is not None:
            if mean.ndim == 1:
                mean = mean[:, np.newaxis, np.newaxis]
            elif self.is_color:
                assert mean.ndim == 3
        self.mean = mean

    def transformer(self, data):
        if self.transpose is not None:
            data = data.transpose(self.transpose)
        if self.channel_swap is not None:
            data = data[self.channel_swap, :, :]
        if self.mean is not None:
            data = data - self.mean
        return data
