"""Import torch parameters into a paddle_tpu params pytree (the modern
counterpart of python/paddle/utils/torch2paddle.py, which converted torch7
binary weight files, feeding demo/model_zoo's pretrained-model scripts).

Matching is by explicit mapping {params_path: tensor_name} or, with
mapping=None, positionally over leaves in declaration order.  Layout
conversions are automatic when shapes demand them: 2-D kernels transpose
(torch nn.Linear stores [out, in]; our fc kernels are [in, out]) and 4-D
conv kernels permute (torch [out, in, kh, kw] -> our NHWC [kh, kw, in, out]).

`resnet_mapping(depth)` emits the full torchvision-convention key map for
the ImageNet ResNets (models/resnet.py mirrors torchvision's v1.5 layout:
stride on the 3x3), so real torchvision checkpoints — or anything saved
with their key names — import directly, BN running stats included."""

import numpy as np


def _leaf_paths(tree, prefix=()):
    if isinstance(tree, dict):
        for k in tree:
            yield from _leaf_paths(tree[k], prefix + (k,))
    else:
        yield prefix, tree


def from_torch_state_dict(params, state_dict, mapping=None,
                          transpose_linear=True, always_transpose=()):
    """Return a copy of `params` with values taken from the torch
    state_dict.  Shapes must match exactly (after the optional [out,in] ->
    [in,out] linear transposition).

    always_transpose: param paths ('a/b/c') whose 2-D tensors are KNOWN
    torch nn.Linear kernels and transpose unconditionally.  Shape-driven
    transposition cannot decide SQUARE 2-D tensors (arr.T.shape ==
    arr.shape), so those import as-is with a loud warning unless listed
    here — a silently untransposed square linear is the classic
    wrong-numerics import."""
    import copy
    import jax.numpy as jnp
    from paddle_tpu.utils.logging import logger
    out = copy.deepcopy(params)
    always_transpose = set(always_transpose)

    def to_np(t):
        return t.detach().cpu().numpy() if hasattr(t, "detach") \
            else np.asarray(t)

    if mapping is not None:
        items = [(tuple(k.split("/")), state_dict[v])
                 for k, v in mapping.items()]
    else:
        keys = list(state_dict.keys())
        paths = list(_leaf_paths(out))
        if len(keys) != len(paths):
            raise ValueError(f"positional import needs equal counts: "
                             f"{len(paths)} params vs {len(keys)} tensors")
        items = [(p, state_dict[k]) for (p, _), k in zip(paths, keys)]

    for path, tensor in items:
        arr = to_np(tensor)
        path_s = "/".join(path)
        target = out
        for p in path[:-1]:
            target = target[p]
        cur = np.asarray(target[path[-1]])
        if path_s in always_transpose and arr.ndim == 2:
            arr = arr.T
        elif arr.shape != cur.shape and transpose_linear and arr.ndim == 2 \
                and arr.T.shape == cur.shape:
            arr = arr.T
        elif (transpose_linear and arr.ndim == 2
              and arr.shape == cur.shape
              and arr.shape[0] == arr.shape[1]):
            logger.warning(
                "torch import: %s is a SQUARE 2-D tensor %s — shape alone "
                "cannot tell torch's [out, in] from our [in, out], so it "
                "is imported AS-IS; if it is an nn.Linear kernel, pass "
                "always_transpose={%r} (wrong layout = silently wrong "
                "numerics)", path_s, arr.shape, path_s)
        if arr.shape != cur.shape and arr.ndim == 4 \
                and arr.transpose(2, 3, 1, 0).shape == cur.shape:
            # torch conv [out, in, kh, kw] -> NHWC kernel [kh, kw, in, out]
            arr = arr.transpose(2, 3, 1, 0)
        if arr.shape != cur.shape:
            raise ValueError(f"shape mismatch at {'/'.join(path)}: "
                             f"torch {arr.shape} vs params {cur.shape}")
        target[path[-1]] = jnp.asarray(arr, cur.dtype)
    return out


# known nn.Linear kernels in the torchvision ResNet mapping: the fc head
# is [out, in] in torch and [in, out] here and must ALWAYS transpose —
# when num_classes happens to equal the feature width (square tensor),
# shape-driven transposition cannot decide and would import it wrong
RESNET_ALWAYS_TRANSPOSE = frozenset({"head/w"})


def resnet_mapping(depth=50):
    """Key maps from models/resnet.py's ImageNet pytree to torchvision's
    state_dict convention (conv1/bn1, layer{1-4}.{i}.conv{1-3}/bn{1-3}/
    downsample.{0,1}, fc).  Returns (param_mapping, state_mapping):
    param_mapping feeds from_torch_state_dict on the params pytree,
    state_mapping on the BN-running-stats state pytree.  Pair with
    always_transpose=RESNET_ALWAYS_TRANSPOSE (the fc head is a known
    linear; import_torchvision_resnet wires it)."""
    table = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
    if depth not in table:
        raise ValueError(
            f"resnet_mapping supports bottleneck depths {sorted(table)}; "
            f"got {depth} (18/34 are BasicBlock models with a different "
            "key structure)")
    blocks_per = table[depth]
    pm = {"stem/w": "conv1.weight",
          "stem/bn/gamma": "bn1.weight", "stem/bn/beta": "bn1.bias",
          "head/w": "fc.weight", "head/b": "fc.bias"}
    sm = {"stem/mean": "bn1.running_mean", "stem/var": "bn1.running_var"}
    for si, n in enumerate(blocks_per):
        for bi in range(n):
            ours, theirs = f"s{si}b{bi}", f"layer{si + 1}.{bi}"
            for ci in (1, 2, 3):
                pm[f"{ours}/c{ci}/w"] = f"{theirs}.conv{ci}.weight"
                pm[f"{ours}/c{ci}/bn/gamma"] = f"{theirs}.bn{ci}.weight"
                pm[f"{ours}/c{ci}/bn/beta"] = f"{theirs}.bn{ci}.bias"
                sm[f"{ours}/c{ci}/mean"] = f"{theirs}.bn{ci}.running_mean"
                sm[f"{ours}/c{ci}/var"] = f"{theirs}.bn{ci}.running_var"
            if bi == 0:     # every stage's first block has a downsample
                pm[f"{ours}/proj/w"] = f"{theirs}.downsample.0.weight"
                pm[f"{ours}/proj/bn/gamma"] = f"{theirs}.downsample.1.weight"
                pm[f"{ours}/proj/bn/beta"] = f"{theirs}.downsample.1.bias"
                sm[f"{ours}/proj/mean"] = \
                    f"{theirs}.downsample.1.running_mean"
                sm[f"{ours}/proj/var"] = f"{theirs}.downsample.1.running_var"
    return pm, sm


def import_torchvision_resnet(state_dict, depth=50, num_classes=None):
    """state_dict (torchvision ResNet-50/101/152 key convention) ->
    (params, state) ready for models/resnet.forward(train=False).
    num_classes defaults to the checkpoint's fc rows."""
    import jax
    from paddle_tpu.models import resnet
    if num_classes is None:
        num_classes = int(np.asarray(
            state_dict["fc.bias"].detach().cpu().numpy()
            if hasattr(state_dict["fc.bias"], "detach")
            else state_dict["fc.bias"]).shape[0])
    params, state = resnet.init(jax.random.PRNGKey(0), depth=depth,
                                num_classes=num_classes)
    pm, sm = resnet_mapping(depth)
    params = from_torch_state_dict(params, state_dict, mapping=pm,
                                   always_transpose=RESNET_ALWAYS_TRANSPOSE)
    state = from_torch_state_dict(state, state_dict, mapping=sm)
    return params, state
