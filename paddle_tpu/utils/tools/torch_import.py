"""Import torch parameters into a paddle_tpu params pytree (the modern
counterpart of python/paddle/utils/torch2paddle.py, which converted torch7
binary weight files).

Matching is by explicit mapping {params_path: tensor_name} or, with
mapping=None, positionally over leaves in declaration order with automatic
transposition of 2-D kernels (torch nn.Linear stores [out, in]; our fc
kernels are [in, out])."""

import numpy as np


def _leaf_paths(tree, prefix=()):
    if isinstance(tree, dict):
        for k in tree:
            yield from _leaf_paths(tree[k], prefix + (k,))
    else:
        yield prefix, tree


def from_torch_state_dict(params, state_dict, mapping=None,
                          transpose_linear=True):
    """Return a copy of `params` with values taken from the torch
    state_dict.  Shapes must match exactly (after the optional [out,in] ->
    [in,out] linear transposition)."""
    import copy
    import jax.numpy as jnp
    out = copy.deepcopy(params)

    def to_np(t):
        return t.detach().cpu().numpy() if hasattr(t, "detach") \
            else np.asarray(t)

    if mapping is not None:
        items = [(tuple(k.split("/")), state_dict[v])
                 for k, v in mapping.items()]
    else:
        keys = list(state_dict.keys())
        paths = list(_leaf_paths(out))
        if len(keys) != len(paths):
            raise ValueError(f"positional import needs equal counts: "
                             f"{len(paths)} params vs {len(keys)} tensors")
        items = [(p, state_dict[k]) for (p, _), k in zip(paths, keys)]

    for path, tensor in items:
        arr = to_np(tensor)
        target = out
        for p in path[:-1]:
            target = target[p]
        cur = np.asarray(target[path[-1]])
        if arr.shape != cur.shape and transpose_linear and arr.ndim == 2 \
                and arr.T.shape == cur.shape:
            arr = arr.T
        if arr.shape != cur.shape:
            raise ValueError(f"shape mismatch at {'/'.join(path)}: "
                             f"torch {arr.shape} vs params {cur.shape}")
        target[path[-1]] = jnp.asarray(arr, cur.dtype)
    return out
