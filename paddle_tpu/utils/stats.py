"""Timer/stat registry.

TPU-native equivalent of the reference's ``REGISTER_TIMER`` RAII timers that
accumulate into ``globalStat`` (reference: paddle/utils/Stat.h:70-241,
printed each --log_period in trainer/Trainer.cpp:443-447).  Host-side wall
timers here; device-side profiling goes through jax.profiler (see
paddle_tpu.utils.profiler).
"""

import contextlib
import threading
import time
from collections import OrderedDict


class Stat:
    __slots__ = ("name", "total", "count", "max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.max = 0.0
        self._lock = threading.Lock()

    def add(self, seconds: float):
        with self._lock:
            self.total += seconds
            self.count += 1
            if seconds > self.max:
                self.max = seconds

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def reset(self):
        with self._lock:
            self.total = 0.0
            self.count = 0
            self.max = 0.0

    def __repr__(self):
        return (f"Stat({self.name}: total={self.total * 1e3:.2f}ms "
                f"avg={self.avg * 1e3:.3f}ms max={self.max * 1e3:.3f}ms "
                f"count={self.count})")


class StatRegistry:
    def __init__(self):
        self._stats = OrderedDict()
        self._lock = threading.Lock()

    def get(self, name: str) -> Stat:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = Stat(name)
            return stat

    def reset_all(self):
        for stat in list(self._stats.values()):
            stat.reset()

    def print_all(self, log=None):
        from paddle_tpu.utils.logging import logger
        log = log or logger
        log.info("======= StatSet =======")
        for stat in self._stats.values():
            if stat.count:
                log.info("  %s", stat)

    def items(self):
        return list(self._stats.items())


global_stats = StatRegistry()


@contextlib.contextmanager
def timer(name: str, registry: StatRegistry = None):
    """with timer("forwardBackward"): ...  — REGISTER_TIMER equivalent."""
    stat = (registry or global_stats).get(name)
    start = time.perf_counter()
    try:
        yield stat
    finally:
        stat.add(time.perf_counter() - start)


def print_all_stats():
    global_stats.print_all()


class Histogram:
    """Step-duration histogram with percentile summary (TPU-native stand-in
    for the reference's BarrierStat worker-skew profiling,
    utils/BarrierStat.h:196-273 — in synchronous SPMD the interesting skew
    is the per-step duration distribution).

    keep="first" (default) freezes the first max_samples observations — the
    right bound for a training pass that resets each pass.  keep="last"
    turns the buffer into a ring holding the most recent max_samples — the
    right bound for a long-running server whose recent latency is the one
    that matters (serving/metrics.py).

    clock: optional zero-arg monotonic clock.  When given, every sample
    is timestamped at add() time and ``percentiles(qs, window_s=W)``
    summarizes only the samples observed within the last W seconds of
    ``clock()`` — the SLO windows the autoscaler's control loop tracks
    (serving/autoscaler.py).  Tests inject a simulated clock so window
    expiry is deterministic instead of a wall-clock sleep; with the
    default real clock the un-windowed behavior is unchanged."""

    def __init__(self, name, max_samples=10000, keep="first", clock=None):
        self.name = name
        self.samples = []
        self.max_samples = max_samples
        if keep not in ("first", "last"):
            raise ValueError(f"keep={keep!r} (supported: 'first', 'last')")
        self.keep = keep
        self.count = 0          # total observed, including evicted
        self.clock = clock
        self.times = [] if clock is not None else None

    def add(self, seconds):
        self.count += 1
        t = self.clock() if self.clock is not None else None
        if len(self.samples) < self.max_samples:
            self.samples.append(seconds)
            if self.times is not None:
                self.times.append(t)
        elif self.keep == "last":
            i = (self.count - 1) % self.max_samples
            self.samples[i] = seconds
            if self.times is not None:
                self.times[i] = t

    def recent_samples(self, window_s=None):
        """The retained samples inside the window (all of them when
        window_s is None), filtered in ONE pass — callers that need
        both 'is there a signal' and 'what is its percentile' read this
        once instead of racing two clock reads against window expiry."""
        if window_s is None:
            return list(self.samples)
        if self.times is None:
            raise ValueError(
                f"Histogram {self.name!r} has no clock; window_s "
                "needs Histogram(clock=...)")
        cutoff = self.clock() - float(window_s)
        return [s for s, t in zip(self.samples, self.times)
                if t >= cutoff]

    def n_recent(self, window_s=None):
        """How many retained samples fall inside the window — lets
        callers distinguish 'no signal' from a true 0.0 percentile."""
        return len(self.recent_samples(window_s))

    def percentiles(self, qs=(50, 90, 99), window_s=None):
        import numpy as np
        samples = self.samples
        if window_s is not None:
            if self.times is None:
                raise ValueError(
                    f"Histogram {self.name!r} has no clock; window_s "
                    "needs Histogram(clock=...)")
            cutoff = self.clock() - float(window_s)
            samples = [s for s, t in zip(self.samples, self.times)
                       if t >= cutoff]
        if not samples:
            return {q: 0.0 for q in qs}
        arr = np.asarray(samples)
        return {q: float(np.percentile(arr, q)) for q in qs}

    def summary(self):
        p = self.percentiles()
        return (f"{self.name}: n={len(self.samples)} "
                f"p50={p[50]*1e3:.2f}ms p90={p[90]*1e3:.2f}ms "
                f"p99={p[99]*1e3:.2f}ms")

    def reset(self):
        self.samples = []
        self.count = 0


step_histogram = Histogram("train_step")
