"""Timer/stat registry.

TPU-native equivalent of the reference's ``REGISTER_TIMER`` RAII timers that
accumulate into ``globalStat`` (reference: paddle/utils/Stat.h:70-241,
printed each --log_period in trainer/Trainer.cpp:443-447).  Host-side wall
timers here; device-side profiling goes through jax.profiler (see
paddle_tpu.utils.profiler).
"""

import contextlib
import threading
import time
from collections import OrderedDict


class Stat:
    __slots__ = ("name", "total", "count", "max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.max = 0.0
        self._lock = threading.Lock()

    def add(self, seconds: float):
        with self._lock:
            self.total += seconds
            self.count += 1
            if seconds > self.max:
                self.max = seconds

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def reset(self):
        with self._lock:
            self.total = 0.0
            self.count = 0
            self.max = 0.0

    def __repr__(self):
        return (f"Stat({self.name}: total={self.total * 1e3:.2f}ms "
                f"avg={self.avg * 1e3:.3f}ms max={self.max * 1e3:.3f}ms "
                f"count={self.count})")


class StatRegistry:
    def __init__(self):
        self._stats = OrderedDict()
        self._lock = threading.Lock()

    def get(self, name: str) -> Stat:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = Stat(name)
            return stat

    def reset_all(self):
        for stat in list(self._stats.values()):
            stat.reset()

    def print_all(self, log=None):
        from paddle_tpu.utils.logging import logger
        log = log or logger
        log.info("======= StatSet =======")
        for stat in self._stats.values():
            if stat.count:
                log.info("  %s", stat)

    def items(self):
        return list(self._stats.items())


global_stats = StatRegistry()


@contextlib.contextmanager
def timer(name: str, registry: StatRegistry = None):
    """with timer("forwardBackward"): ...  — REGISTER_TIMER equivalent."""
    stat = (registry or global_stats).get(name)
    start = time.perf_counter()
    try:
        yield stat
    finally:
        stat.add(time.perf_counter() - start)


def print_all_stats():
    global_stats.print_all()


class Histogram:
    """Step-duration histogram with percentile summary (TPU-native stand-in
    for the reference's BarrierStat worker-skew profiling,
    utils/BarrierStat.h:196-273 — in synchronous SPMD the interesting skew
    is the per-step duration distribution).

    keep="first" (default) freezes the first max_samples observations — the
    right bound for a training pass that resets each pass.  keep="last"
    turns the buffer into a ring holding the most recent max_samples — the
    right bound for a long-running server whose recent latency is the one
    that matters (serving/metrics.py)."""

    def __init__(self, name, max_samples=10000, keep="first"):
        self.name = name
        self.samples = []
        self.max_samples = max_samples
        if keep not in ("first", "last"):
            raise ValueError(f"keep={keep!r} (supported: 'first', 'last')")
        self.keep = keep
        self.count = 0          # total observed, including evicted

    def add(self, seconds):
        self.count += 1
        if len(self.samples) < self.max_samples:
            self.samples.append(seconds)
        elif self.keep == "last":
            self.samples[(self.count - 1) % self.max_samples] = seconds

    def percentiles(self, qs=(50, 90, 99)):
        import numpy as np
        if not self.samples:
            return {q: 0.0 for q in qs}
        arr = np.asarray(self.samples)
        return {q: float(np.percentile(arr, q)) for q in qs}

    def summary(self):
        p = self.percentiles()
        return (f"{self.name}: n={len(self.samples)} "
                f"p50={p[50]*1e3:.2f}ms p90={p[90]*1e3:.2f}ms "
                f"p99={p[99]*1e3:.2f}ms")

    def reset(self):
        self.samples = []
        self.count = 0


step_histogram = Histogram("train_step")
