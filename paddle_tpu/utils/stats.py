"""Timer/stat registry.

TPU-native equivalent of the reference's ``REGISTER_TIMER`` RAII timers that
accumulate into ``globalStat`` (reference: paddle/utils/Stat.h:70-241,
printed each --log_period in trainer/Trainer.cpp:443-447).  Host-side wall
timers here; device-side profiling goes through jax.profiler (see
paddle_tpu.utils.profiler).
"""

import contextlib
import threading
import time
from collections import OrderedDict


class Stat:
    __slots__ = ("name", "total", "count", "max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.max = 0.0
        self._lock = threading.Lock()

    def add(self, seconds: float):
        with self._lock:
            self.total += seconds
            self.count += 1
            if seconds > self.max:
                self.max = seconds

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def reset(self):
        with self._lock:
            self.total = 0.0
            self.count = 0
            self.max = 0.0

    def __repr__(self):
        return (f"Stat({self.name}: total={self.total * 1e3:.2f}ms "
                f"avg={self.avg * 1e3:.3f}ms max={self.max * 1e3:.3f}ms "
                f"count={self.count})")


class StatRegistry:
    def __init__(self):
        self._stats = OrderedDict()
        self._lock = threading.Lock()

    def get(self, name: str) -> Stat:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = Stat(name)
            return stat

    def reset_all(self):
        for stat in list(self._stats.values()):
            stat.reset()

    def print_all(self, log=None):
        from paddle_tpu.utils.logging import logger
        log = log or logger
        log.info("======= StatSet =======")
        for stat in self._stats.values():
            if stat.count:
                log.info("  %s", stat)

    def items(self):
        return list(self._stats.items())


global_stats = StatRegistry()


@contextlib.contextmanager
def timer(name: str, registry: StatRegistry = None):
    """with timer("forwardBackward"): ...  — REGISTER_TIMER equivalent."""
    stat = (registry or global_stats).get(name)
    start = time.perf_counter()
    try:
        yield stat
    finally:
        stat.add(time.perf_counter() - start)


def print_all_stats():
    global_stats.print_all()
