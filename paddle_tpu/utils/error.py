"""Errors (reference: paddle/utils/Error.h)."""


class PaddleTpuError(Exception):
    """Base error for paddle_tpu."""


class ConfigError(PaddleTpuError):
    """Invalid model / trainer configuration."""


class ShapeError(PaddleTpuError):
    """Shape/size inference mismatch."""
