from paddle_tpu.utils.logging import logger, get_logger
from paddle_tpu.utils.stats import Stat, global_stats, timer, print_all_stats
from paddle_tpu.utils.flags import FLAGS
from paddle_tpu.utils.error import PaddleTpuError, ConfigError, ShapeError

__all__ = [
    "logger",
    "get_logger",
    "Stat",
    "global_stats",
    "timer",
    "print_all_stats",
    "FLAGS",
    "PaddleTpuError",
    "ConfigError",
    "ShapeError",
]
