"""Code-revision stamp shared by everything that records provenance
(differential dumps, bench cache rows).

The stamp is HEAD plus a digest of any uncommitted diff, so local
iteration (the common revision-mixing case) changes the stamp too.
'unknown' when git is unavailable — consumers treat that as
unverifiable, not as a match.
"""

import hashlib
import os
import subprocess

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def code_revision():
    try:
        head = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=_REPO,
            capture_output=True, text=True, timeout=10).stdout.strip()
        if not head:
            return "unknown"
        diff = subprocess.run(
            ["git", "diff", "HEAD"], cwd=_REPO,
            capture_output=True, text=True, timeout=30).stdout
        if diff:
            return f"{head[:12]}+{hashlib.sha1(diff.encode()).hexdigest()[:8]}"
        return head[:12]
    except Exception:   # noqa: BLE001 — no git in deployment images
        return "unknown"
