"""Device-side profiling: jax.profiler wiring.

TPU-native successor of the reference's host timer dumps (utils/Stat.h,
--log_period prints) for DEVICE time: captures an xprof/TensorBoard trace of
XLA execution — per-op device time, HBM traffic, fusion boundaries — which
is where all the information the reference's REGISTER_TIMER blocks carried
now lives.  Host-side wall timers remain in paddle_tpu.utils.stats.

Usage:
    from paddle_tpu.utils import profiler
    profiler.start("/tmp/xprof")       # or --profile_dir on the CLI
    ... train ...
    profiler.stop()

    with profiler.trace("/tmp/xprof"):     # scoped capture
        trainer.train(...)

    with profiler.annotate("decode_step"):  # named region inside a capture
        ...

View with: tensorboard --logdir /tmp/xprof (Profile tab), or the xprof CLI.
"""

import contextlib

from paddle_tpu.utils.logging import logger

_active_dir = [None]


def start(profile_dir: str):
    """Begin a trace capture writing to profile_dir (idempotent)."""
    import jax
    if _active_dir[0]:
        logger.warning("profiler already tracing to %s", _active_dir[0])
        return
    jax.profiler.start_trace(profile_dir)
    _active_dir[0] = profile_dir
    logger.info("profiler: tracing to %s", profile_dir)


def stop():
    import jax
    if not _active_dir[0]:
        return
    jax.profiler.stop_trace()
    logger.info("profiler: trace written to %s", _active_dir[0])
    _active_dir[0] = None


def is_tracing() -> bool:
    return _active_dir[0] is not None


@contextlib.contextmanager
def trace(profile_dir: str):
    start(profile_dir)
    try:
        yield
    finally:
        stop()


def annotate(name: str):
    """Named region that shows up on the trace timeline (the REGISTER_TIMER
    name, device-side)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def save_device_memory_profile(path: str):
    """Snapshot the device memory profile (pprof format) — the reference's
    closest analog is the GPU memory stat logs."""
    import jax
    jax.profiler.save_device_memory_profile(path)
    logger.info("profiler: device memory profile -> %s", path)
