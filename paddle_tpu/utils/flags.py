"""Global runtime flags.

TPU-native equivalent of the reference's gflags surface
(paddle/utils/Flags.cpp:18-110 and the trainer's DEFINE_* in
trainer/Trainer.cpp / TrainerMain.cpp, documented under
doc/howto/usage/cmd_parameter).  Every reference flag is either carried
over under its own name, renamed to its TPU equivalent, or listed in
`SUBSUMED` with the mechanism that replaces it — so a reference user can
look any flag up here and learn its fate.
"""

import argparse
import dataclasses
from typing import Optional


@dataclasses.dataclass
class Flags:
    # ---- device / precision (reference: use_gpu, gpu_id, trainer_count)
    use_tpu: bool = True            # use_gpu analog; False pins CPU
    dtype: str = "float32"          # parameter dtype ("real" in the reference)
    compute_dtype: str = "bfloat16"  # matmul/conv compute dtype on TPU
    seed: int = 1                   # reference: --seed (0 = time-based)

    # ---- jobs / config (reference: job, config, config_args)
    job: str = "train"              # train | test | checkgrad | merge_model
    config: Optional[str] = None
    config_args: str = ""
    comment: str = ""               # freeform run annotation, logged once

    # ---- training loop (reference names kept)
    log_period: int = 100
    dot_period: int = 1             # reference --dot_period ('.' cadence);
    #                                 kept for config compat, logging is the
    #                                 real progress channel here
    saving_period: int = 1
    saving_period_by_batches: int = 0   # 0 = off (save per pass only)
    test_period: int = 0
    test_pass: Optional[int] = None
    average_test_period: int = 0    # Polyak-averaged eval cadence
    num_passes: int = 1
    start_pass: int = 0
    save_dir: Optional[str] = None
    save_only_one: bool = False
    init_model_path: Optional[str] = None
    load_missing_parameter_strategy: str = "fail"  # fail | rand | zero
    show_parameter_stats_period: int = 0
    show_layer_stat: bool = False   # per-layer output stats each log_period
    checkgrad_eps: float = 1e-3
    prev_batch_state: bool = False  # carry RNN state across batches
    with_cost: bool = True

    # ---- prediction outputs (reference: predict_file, predict_output_dir)
    predict_file: Optional[str] = None
    predict_output_dir: Optional[str] = None

    # ---- parallelism: mesh shape replaces trainer_count/ports/pserver
    # topology (reference: trainer_count, parallel_nn, num_gradient_servers)
    data_parallel: int = 0   # 0 = all devices
    model_parallel: int = 1
    seq_parallel: int = 1
    expert_parallel: int = 1
    # multi-host rendezvous (reference: port/ports_num/nics/trainer_id ->
    # one coordinator address + process indices, parallel/distributed.py)
    coordinator: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    dcn_data_parallel: int = 1      # slices joined over DCN (hybrid mesh)

    # ---- decoding
    beam_size: int = 1

    # ---- data
    async_load_data: bool = True    # reference DoubleBuffer on/off; with
    #                                 prefetch_depth, the CLI default for
    #                                 --prefetch (SGD.train(prefetch=N),
    #                                 data/prefetch.py device pipeline)
    prefetch_depth: int = 2
    # opt-in persistent XLA compilation cache: compiled step executables
    # (incl. SGD.precompile's per-bucket programs) are written here and
    # reused across process restarts — the AOT warm-up then costs a disk
    # read instead of a compile.  None = off (JAX default).
    jax_compilation_cache_dir: Optional[str] = None

    # ---- serving runtime (serving/: dynamic batcher + HTTP front-end;
    # the reference served through C++ services over the C API with no
    # batching layer, so these are TPU-native)
    serving_port: int = 8080
    serving_buckets: str = "1,4,16,64"
    serving_max_batch_size: int = 0     # 0 = the bucket ladder's top
    serving_max_delay_ms: float = 5.0
    serving_queue_size: int = 256
    serving_deadline_ms: float = 0.0    # 0 = no per-request deadline
    # ---- generation serving (serving/decode_engine.py: slot-based
    # continuous batching over a fixed KV-cache slab; docs/serving.md §4)
    serving_gen_slots: int = 8          # concurrent decode slots
    serving_gen_max_len: int = 256      # KV slab length (prompt + output)
    serving_gen_prefill_buckets: str = "32,64"  # prompt-length ladder
    serving_gen_max_tokens: int = 64    # default per-request emission cap
    # ---- paged KV cache (serving/kv_pool.py: block-pool allocator +
    # copy-on-write prefix sharing; docs/serving.md §5)
    serving_kv_layout: str = "slab"     # "slab" | "paged"
    serving_kv_block_size: int = 16     # KV positions per paged block
    serving_kv_num_blocks: int = 0      # pool size incl. scratch block
    #                                     (0 = slab-equivalent bytes)
    serving_kv_prefix_cache: bool = True  # share resident prompt-prefix
    #                                       blocks across requests
    serving_kv_host_bytes: int = 0      # host-RAM spill-tier cap, bytes
    #                                     (hierarchical KV: evicted
    #                                     prefix chains spill and
    #                                     restore instead of
    #                                     recomputing; 0 = tier off)
    # ---- disaggregated serving (serving/transfer.py: cross-replica
    # KV-block handoff over a socket transport; docs/serving.md
    # "Disaggregated serving")
    serving_role: str = "mixed"         # replica role in a disaggregated
    #                                     fleet: "prefill" | "decode" |
    #                                     "mixed"
    serving_handoff: bool = True        # router: hand streams off from
    #                                     the prefill pool to the decode
    #                                     pool at first token (active
    #                                     only when both roles exist)
    serving_handoff_max_bytes: int = 256 << 20  # receive-side bound on
    #                                     ONE handoff blob's bytes (a
    #                                     garbled peer must never OOM
    #                                     the receiver)
    serving_handoff_timeout_s: float = 5.0  # socket timeout for one
    #                                     export fetch (expired =
    #                                     recompute fallback)
    # ---- quantized serving (paddle_tpu/quant/: int8 weights + int8 KV
    # cache with in-register dequant in the fused decode kernels;
    # docs/serving.md "Quantized serving")
    serving_kv_dtype: str = "float32"   # "float32" | "int8" (quantized
    #                                     KV + per-head scale sidecars;
    #                                     paged auto-sizing doubles the
    #                                     block count at equal bytes)
    quant_weights: bool = False         # serve per-channel int8 trunk
    #                                     weights (quant/weights.py)
    quant_train: bool = False           # int8 weight-streaming train
    #                                     step (trainer quant_weights
    #                                     mode: f32 masters optimizer-
    #                                     side, requantize after update)
    # ---- unified chunked prefill (decode_engine.py prefill_chunk:
    # prompt ingestion folded into the ONE jitted decode step as K-lane
    # chunks; docs/serving.md "Chunked prefill").  The serving CLI
    # defaults to chunked; 0 demotes to the legacy per-bucket prefill
    # ladder.
    serving_prefill_chunk: int = 8      # lanes per chunked-prefill step
    #                                     (K; 0 = legacy ladder prefill)
    serving_prefill_chunk_budget: int = 0  # max teacher-forced lanes per
    #                                        step across all slots
    #                                        (0 = unbounded); data, not
    #                                        shape — tuning never
    #                                        retraces
    # ---- speculative decoding (serving/speculative.py: a truncated-
    # trunk draft proposes k tokens per slot, the one chunked step
    # scores every lane; docs/serving.md "Speculative decoding")
    serving_speculate_k: int = 0        # draft tokens per slot per step
    #                                     (k; 0 = speculation off —
    #                                     requires chunked prefill)
    serving_draft_layers: int = 1       # trunk depth of the derived
    #                                     draft (make_draft: first N enc
    #                                     blocks, embedding shared)
    # ---- tensor-parallel sharded decode (parallel/sharding.py +
    # decode_engine mesh=; docs/serving.md "Sharded decode")
    serving_mesh_shards: int = 1        # model-axis mesh size the ONE
    #                                     chunked step spans (heads/KV/
    #                                     vocab striped, streams bit-
    #                                     identical); 0/1 = single-chip
    # ---- fused decode kernels (ops/pallas/decode_attention.py: read
    # the KV cache once per step; docs/perf.md "Fused decode kernels")
    pallas_decode: str = "auto"         # auto (use_pallas(): TPU only) |
    #                                     always (interpret off-TPU) | off
    pallas_decode_block_k: int = 512    # slab kernel k-tile cap
    pallas_prefill: str = "auto"        # route lm_prefill's batched
    #                                     causal pass through the flash
    #                                     kernel (no [Tp, Tp] scores):
    #                                     auto (TPU only) | always | off
    pallas_prefill_quant: str = "auto"  # int8 caches: stream the int8
    #                                     bytes + scale sidecars through
    #                                     flash_attention_quant (no f32
    #                                     widened K/V): auto | always |
    #                                     off
    # ---- replicated serving tier (serving/fleet.py supervisor +
    # serving/router.py health-checked router; docs/serving.md §7)
    router_port: int = 8000             # HTTP port for the router CLI
    router_poll_interval_s: float = 0.25  # /readyz + /metrics poll cadence
    router_unready_grace_s: float = 2.0  # on an all-unready pick miss,
    #                                     probe + wait this long before
    #                                     failing the request (covers the
    #                                     poller's view lag of a freshly
    #                                     restarted replica)
    router_eject_threshold: int = 3     # consecutive dispatch failures
    #                                     that eject a replica (outlier
    #                                     ejection, breaker-style)
    router_eject_cooldown_s: float = 2.0  # ejected -> half-open probe
    router_retry_budget: int = 2        # cross-replica retries/failovers
    router_hedge_ms: float = 0.0        # hedged /v1/infer: 0 off, >0 a
    #                                     fixed delay, <0 p99-derived
    fleet_replicas: int = 2             # replicas the supervisor spawns
    fleet_backoff_base_s: float = 0.5   # crash-restart backoff base
    fleet_backoff_max_s: float = 10.0   # crash-restart backoff cap
    fleet_storm_threshold: int = 5      # crashes within the window that
    #                                     trip the restart-storm breaker
    fleet_storm_window_s: float = 30.0  # the restart-storm window
    # ---- adaptive overload control (serving/overload.py wired into
    # router.py: AIMD concurrency limit, priority shedding, brownout
    # ladder; docs/serving.md §8)
    overload_limit_initial: float = 64.0   # AIMD limit starting point
    overload_limit_min: float = 4.0        # multiplicative-decrease floor
    overload_limit_max: float = 4096.0     # additive-increase ceiling
    overload_aimd_increase: float = 1.0    # +increase/limit per completion
    overload_aimd_decrease: float = 0.5    # limit *= decrease on overload
    overload_slo_ttft_ms: float = 0.0      # brownout SLO target (0 = the
    #                                        ladder is disabled)
    overload_window_s: float = 30.0        # recent window for the SLO
    #                                        p99 + the drain-rate estimate
    overload_brownout_hold_s: float = 3.0  # sustained breach before a
    #                                        rung is entered
    overload_brownout_exit_s: float = 5.0  # sustained health before a
    #                                        rung is exited
    overload_brownout_max_tokens: int = 32  # rung-2 per-request token cap
    # ---- autoscaler (serving/autoscaler.py: trace-driven control loop
    # over the replica fleet; docs/serving.md §8)
    autoscaler_poll_interval_s: float = 1.0  # metrics poll cadence
    autoscaler_target_ttft_ms: float = 500.0  # the SLO the loop tracks
    autoscaler_hysteresis: float = 0.2  # dead band around the target:
    #                                     out above target*(1+h), in below
    #                                     target*(1-h) only
    autoscaler_breach_polls: int = 3    # consecutive breach polls before
    #                                     a scale-out fires
    autoscaler_slack_polls: int = 6     # consecutive slack polls before
    #                                     a scale-in fires
    autoscaler_cooldown_out_s: float = 10.0  # min gap after ANY scale
    #                                          before an out fires
    autoscaler_cooldown_in_s: float = 60.0   # min gap after ANY scale
    #                                          before an in fires
    autoscaler_min_replicas: int = 1
    autoscaler_max_replicas: int = 4
    autoscaler_window_s: float = 30.0   # recent window for the SLO p99
    autoscaler_seed: int = 0            # poll jitter + backoff streams
    # ---- resilience (resilience/: deterministic fault injection +
    # supervised recovery; docs/serving.md §6)
    serving_drain_timeout_s: float = 30.0  # SIGTERM drain hard deadline
    resilience_fault_spec: str = ""     # chaos-only fault plan, e.g.
    #                                     "serving.decode_step:at=5"
    resilience_step_deadline_ms: float = 0.0  # decode watchdog (0 = off)
    resilience_breaker_threshold: int = 5     # consecutive failures -> open
    resilience_breaker_cooldown_s: float = 5.0  # open -> half-open probe
    resilience_retry_budget: int = 3    # transient submit retries

    # ---- static invariant analyzer (paddle_tpu/analysis/: jit-purity,
    # retrace-hazard and lock-order passes gated on every commit;
    # docs/analysis.md)
    analysis_baseline: Optional[str] = None  # allow-list path override
    #                                     (None = the committed
    #                                     paddle_tpu/analysis/
    #                                     baseline.json)
    analysis_strict: bool = False       # stale baseline entries (a
    #                                     documented violation that no
    #                                     longer exists) fail the gate
    #                                     instead of warning

    # ---- observability (new floor; reference had host timers only)
    # request tracing (obs/trace.py: host-side span recorder + cross-
    # process propagation + Chrome-trace export; docs/observability.md)
    obs_trace_enable: bool = False      # off in prod-style runs; tests/
    #                                     smokes turn it on explicitly
    obs_trace_sample: float = 1.0       # deterministic head sampling
    #                                     keyed on the trace_id hash
    obs_trace_ring: int = 4096          # completed spans kept (ring)
    profile_dir: Optional[str] = None   # capture an xprof trace of training
    debug_nans: bool = False            # NaN -> immediate error with op
    #                                     location (reference feenableexcept
    #                                     in TrainerMain.cpp:49)
    memory_profile_path: Optional[str] = None  # dump device memory profile

    def update_from_args(self, args):
        for field in dataclasses.fields(self):
            if hasattr(args, field.name) and getattr(args, field.name) is not None:
                setattr(self, field.name, getattr(args, field.name))

    def add_to_parser(self, parser: argparse.ArgumentParser):
        for field in dataclasses.fields(self):
            name = "--" + field.name
            ftype = str(field.type)
            if field.type is bool or isinstance(field.default, bool):
                parser.add_argument(name, type=lambda v: v.lower() in ("1", "true", "yes"),
                                    default=None)
            elif isinstance(field.default, float) or "float" in ftype:
                parser.add_argument(name, type=float, default=None)
            elif isinstance(field.default, int) or "int" in ftype:
                # covers Optional[int] fields whose default is None
                parser.add_argument(name, type=int, default=None)
            else:
                parser.add_argument(name, type=str, default=None)

    def apply(self):
        """Push flag values into the runtime (dtype policy, debug_nans,
        persistent compilation cache)."""
        from paddle_tpu.core import dtypes
        import jax
        dtypes.set_policy(self.dtype,
                          None if self.compute_dtype in (None, "", "auto")
                          else self.compute_dtype)
        if self.debug_nans:
            jax.config.update("jax_debug_nans", True)
        if self.jax_compilation_cache_dir:
            set_compilation_cache_dir(self.jax_compilation_cache_dir)
        if self.resilience_fault_spec:
            from paddle_tpu.resilience import faults
            faults.install_spec(self.resilience_fault_spec)
        if self.obs_trace_enable:
            from paddle_tpu.obs import trace
            trace.enable(sample=self.obs_trace_sample,
                         capacity=self.obs_trace_ring)


def set_compilation_cache_dir(path):
    """Wire the opt-in persistent XLA compilation cache (docs/
    input_pipeline.md).  min_compile_time is dropped to 0 so every bucket
    executable persists, not just the slow ones — the whole point is a
    cold process skipping ALL bucket compiles."""
    import jax
    jax.config.update("jax_compilation_cache_dir", str(path))
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except AttributeError:      # older jax: the dir alone still works
        pass


# Per-flag documentation: {field name: (help, reference cmd_parameter
# equivalent or "—" for TPU-native flags with no reference twin)}.
# docs/flags.md's flag-reference table is GENERATED from this dict +
# the dataclass defaults (`python -m paddle_tpu.utils.flags`), and
# tests/test_flags_doc.py fails when a Flags field is added without a
# row here or without regenerating the doc.
FLAG_DOCS = {
    "use_tpu": ("use the TPU backend; False pins CPU", "use_gpu"),
    "dtype": ("parameter dtype", 'real ("paddle float")'),
    "compute_dtype": ("matmul/conv compute dtype on TPU; auto = bf16 on "
                      "TPU, f32 on CPU", "—"),
    "seed": ("RNG seed (0 = time-based)", "seed"),
    "job": ("train | test | checkgrad | merge_model", "job"),
    "config": ("model config script (native get_config() or reference v1 "
               "trainer_config_helpers script)", "config"),
    "config_args": ("k=v,k=v passed into the config script", "config_args"),
    "comment": ("freeform run annotation, logged once", "—"),
    "log_period": ("batches between progress lines (0 = pass end only)",
                   "log_period"),
    "dot_period": ("'.'-cadence kept for config compat; logging is the "
                   "progress channel here", "dot_period"),
    "saving_period": ("passes between checkpoints", "saving_period"),
    "saving_period_by_batches": ("also checkpoint every N batches "
                                 "(0 = off)", "saving_period_by_batches"),
    "test_period": ("passes between test() sweeps (0 = every pass)",
                    "test_period"),
    "test_pass": ("load pass N for a test job", "test_pass"),
    "average_test_period": ("Polyak-averaged eval cadence",
                            "average_test_period"),
    "num_passes": ("passes over the data", "num_passes"),
    "start_pass": ("resume from pass K (loads pass K-1)", "start_pass"),
    "save_dir": ("checkpoint directory", "save_dir"),
    "save_only_one": ("keep only the latest checkpoint", "save_only_one"),
    "init_model_path": ("warm-start parameters from a checkpoint dir",
                        "init_model_path"),
    "load_missing_parameter_strategy": ("fail | rand | zero for params "
                                        "absent from the warm-start",
                                        "load_missing_parameter_strategy"),
    "show_parameter_stats_period": ("batches between per-param absmax/"
                                    "absavg dumps",
                                    "show_parameter_stats_period"),
    "show_layer_stat": ("per-layer output stats each log_period",
                        "show_layer_stat"),
    "checkgrad_eps": ("finite-difference epsilon for the checkgrad job",
                      "checkgrad_eps"),
    "prev_batch_state": ("carry RNN state across batches",
                         "prev_batch_state"),
    "with_cost": ("train with a cost layer (off for inference nets)",
                  "with_cost"),
    "predict_file": ("input file for the predict drivers", "predict_file"),
    "predict_output_dir": ("where predict jobs write outputs",
                           "predict_output_dir"),
    "data_parallel": ("data-parallel mesh axis (0 = all devices)",
                      "trainer_count"),
    "model_parallel": ("tensor-parallel mesh axis (megatron rules)",
                       "parallel_nn"),
    "seq_parallel": ("sequence/context-parallel axis (ring attention)",
                     "—"),
    "expert_parallel": ("expert-parallel mesh axis (MoE)", "—"),
    "coordinator": ("multi-host rendezvous address "
                    "(jax.distributed)", "port/ports_num/nics"),
    "num_processes": ("process count for multi-host rendezvous",
                      "num_gradient_servers"),
    "process_id": ("this host's index in the rendezvous", "trainer_id"),
    "dcn_data_parallel": ("slices joined over DCN (hybrid ICI×DCN mesh)",
                          "—"),
    "beam_size": ("beam width for generation jobs", "beam_size"),
    "async_load_data": ("input pipeline overlap on/off; with "
                        "prefetch_depth gives --prefetch its default",
                        "async_load_data (DoubleBuffer)"),
    "prefetch_depth": ("batches converted + H2D-transferred ahead on the "
                       "prefetch thread", "—"),
    "jax_compilation_cache_dir": ("opt-in persistent XLA compile cache "
                                  "(AOT bucket warm-up survives restarts)",
                                  "—"),
    "serving_port": ("HTTP port for python -m paddle_tpu.serving", "—"),
    "serving_buckets": ("batch bucket ladder (comma ints) the serving "
                        "engine AOT-compiles", "—"),
    "serving_max_batch_size": ("largest dynamic batch formed (0 = the "
                               "bucket ladder's top)", "—"),
    "serving_max_delay_ms": ("how long the first queued request waits "
                             "for batch co-riders", "—"),
    "serving_queue_size": ("admission bound; a full queue rejects with "
                           "HTTP 429", "—"),
    "serving_deadline_ms": ("default per-request deadline (0 = none); "
                            "expired requests fail with HTTP 504", "—"),
    "serving_gen_slots": ("decode slots in the continuous-batching KV "
                          "slab (concurrent generations)", "—"),
    "serving_gen_max_len": ("KV-cache slab length; every request needs "
                            "prompt + max_tokens <= this", "—"),
    "serving_gen_prefill_buckets": ("prompt-length ladder (comma ints) "
                                    "the prefill engines AOT-compile; "
                                    "the top bucket caps prompt length",
                                    "—"),
    "serving_gen_max_tokens": ("default per-request emission cap for "
                               "/v1/generate", "—"),
    "serving_kv_layout": ("decode KV-cache layout: slab (max_len "
                          "reserved per slot) or paged (block pool + "
                          "per-slot block tables, prefix sharing)", "—"),
    "serving_kv_block_size": ("KV positions per paged block", "—"),
    "serving_kv_num_blocks": ("paged pool size incl. the reserved "
                              "scratch block (0 = auto: the slab-"
                              "equivalent slots*ceil(max_len/block_size)"
                              "+1)", "—"),
    "serving_kv_prefix_cache": ("share resident prompt-prefix blocks "
                                "across requests (copy-on-write on "
                                "divergence)", "—"),
    "serving_kv_host_bytes": ("host-RAM spill-tier byte cap for the "
                              "hierarchical KV cache: prefix chains "
                              "evicted under pool pressure serialize "
                              "to host buffers and restore "
                              "asynchronously on the next hit when "
                              "perf/analytic predicts restore beats "
                              "recompute (LRU within the cap; 0 = "
                              "tier off; paged + prefix_cache only)",
                              "—"),
    "serving_role": ("replica role in a disaggregated fleet: prefill "
                     "(takes new prompts, exports KV chains), decode "
                     "(receives handoffs, decodes), or mixed (both — "
                     "the single-replica default).  The router routes "
                     "new prompts to the prefill pool and hands "
                     "streams off at first token when both pools "
                     "exist", "—"),
    "serving_handoff": ("router-side switch for cross-replica KV "
                        "handoff: when a prefill pool AND a decode "
                        "pool are both present, new streams prefill "
                        "on one pool and decode on the other, the KV "
                        "chain crossing as a wire-format blob; off = "
                        "roles only affect routing preference and "
                        "every stream recomputes its context on the "
                        "decode replica", "—"),
    "serving_handoff_max_bytes": ("receive-side ceiling on one handoff "
                                  "blob (length prefix AND decoded "
                                  "size are bounded before any "
                                  "allocation); larger exports fall "
                                  "back to recompute", "—"),
    "serving_handoff_timeout_s": ("socket timeout for one KV-export "
                                  "fetch; expiry (e.g. the prefill "
                                  "replica died) falls back to "
                                  "continuation-replay recompute",
                                  "—"),
    "serving_kv_dtype": ("decode KV-cache storage dtype: float32, or "
                         "int8 (quantized K/V + per-(position, head) "
                         "f32 scale sidecars, dequantized in-register "
                         "by the fused decode kernels; the paged "
                         "auto-sizing doubles kv_num_blocks at the "
                         "slab-equivalent byte budget)", "—"),
    "quant_weights": ("serve per-channel symmetric int8 trunk weights "
                      "(quant/weights.py): int8 data + f32 scale "
                      "sidecars are what stays resident; dequant fuses "
                      "into each consuming matmul's operand read", "—"),
    "quant_train": ("int8 weight-streaming training step (trainer "
                    "quant_weights mode): the jitted step is fed the "
                    "{q: int8, s: f32} tree and dequantizes at the "
                    "matmul boundary; f32 master weights live on the "
                    "optimizer side and re-quantize after each update.  "
                    "Checkpoints carry both trees and resume "
                    "bit-identically", "—"),
    "serving_prefill_chunk": ("unified chunked prefill: prompt "
                              "ingestion rides the ONE jitted decode "
                              "step as up-to-K-token chunks per slot "
                              "per step (first token at the last "
                              "chunk); 0 = the legacy per-bucket "
                              "prefill InferenceEngine ladder", "—"),
    "serving_prefill_chunk_budget": ("max teacher-forced chunk lanes "
                                     "one step may feed across all "
                                     "slots (bounds per-step prefill "
                                     "work, hence TPOT jitter; 0 = "
                                     "unbounded).  Fed as data — "
                                     "tuning it never retraces", "—"),
    "serving_speculate_k": ("speculative decoding: a small draft trunk "
                            "proposes k greedy tokens per feeding slot "
                            "and the target's ONE chunked step scores "
                            "every drafted lane at once — each step "
                            "nets 1 + accepted tokens, streams stay "
                            "token-identical to lm_generate (the "
                            "acceptance rule keeps exactly the greedy "
                            "prefix).  0 = off; requires "
                            "serving_prefill_chunk > 0", "—"),
    "serving_mesh_shards": ("tensor-parallel sharded decode: run the "
                            "ONE chunked serving step under an N-chip "
                            "model-axis mesh (decode_mesh) — attention "
                            "heads + the KV pool stripe Hkv/N per chip, "
                            "the embedding stripes vocab/N, wq/wk/wv "
                            "shard their out-feature axis, and the only "
                            "cross-chip seams are the per-layer "
                            "attention-output all-gather, the logits "
                            "all-gather, and the embedding psum.  "
                            "Streams stay BIT-IDENTICAL to the "
                            "single-chip engine; requires "
                            "serving_prefill_chunk > 0 and N dividing "
                            "heads/Hkv/vocab.  0/1 = single-chip", "—"),
    "serving_draft_layers": ("trunk depth of the draft model derived "
                             "from the target (speculative.make_draft: "
                             "the first N enc blocks; embedding / final "
                             "LN / vocab head SHARED with the target, "
                             "so only the truncated trunk adds weight "
                             "bytes)", "—"),
    "pallas_decode": ("fused Pallas decode-attention kernels for the "
                      "slot/paged serving steps: auto = on when the "
                      "backend compiles Pallas natively (TPU), always = "
                      "force (interpret mode off-TPU — tests/smokes), "
                      "off = reference XLA path.  Read at trace time: "
                      "set before constructing the decode engine", "—"),
    "pallas_decode_block_k": ("slab decode kernel k-tile cap (positions "
                              "per KV block streamed through VMEM); the "
                              "kernel picks the largest tileable divisor "
                              "of max_len under this", "—"),
    "pallas_prefill": ("route lm_prefill/lm_generate's batched causal "
                       "pass through ops/pallas/flash_attention (no "
                       "[Tp, Tp] score matrix): auto = TPU only (the "
                       "CPU default stays the masked XLA reference, "
                       "preserving bit-identity discipline), always = "
                       "force (interpret off-TPU), off.  Read at trace "
                       "time", "—"),
    "pallas_prefill_quant": ("int8 caches: stream the just-quantized "
                             "int8 K/V bytes + per-(position, head) "
                             "scale sidecars straight through "
                             "flash_attention_quant, widening in "
                             "registers — no dequantized f32 [Tp, Dkv] "
                             "buffer in the prefill program (the "
                             "analytic postcheck pins its absence): "
                             "auto = TPU only, always = force "
                             "(interpret off-TPU), off.  Read at trace "
                             "time", "—"),
    "router_port": ("HTTP port for python -m paddle_tpu.serving.router",
                    "—"),
    "router_poll_interval_s": ("how often the router polls each "
                               "replica's /readyz + /metrics (readiness "
                               "gating, least-loaded dispatch)", "—"),
    "router_unready_grace_s": ("when no replica looks eligible, the "
                               "router probes /readyz itself and waits "
                               "up to this long before failing the "
                               "request — the health poller's view of "
                               "a freshly restarted replica lags by up "
                               "to a poll interval", "—"),
    "router_eject_threshold": ("consecutive dispatch failures that "
                               "eject a replica from rotation "
                               "(half-open probe readmits)", "—"),
    "router_eject_cooldown_s": ("ejected-replica cooldown before the "
                                "half-open readmission probe", "—"),
    "router_retry_budget": ("bounded cross-replica retries (idempotent "
                            "infer) / mid-stream failovers (generate)",
                            "—"),
    "router_hedge_ms": ("hedged /v1/infer requests: 0 = off, >0 = fire "
                        "the hedge after that fixed delay, <0 = "
                        "p99-derived from recent router latency", "—"),
    "fleet_replicas": ("serving replica subprocesses the fleet "
                       "supervisor spawns", "—"),
    "fleet_backoff_base_s": ("crash-restart exponential-backoff base "
                             "(seeded jitter on top)", "—"),
    "fleet_backoff_max_s": ("crash-restart backoff cap", "—"),
    "fleet_storm_threshold": ("replica crashes within the storm window "
                              "that stop further restarts (restart-"
                              "storm breaker)", "—"),
    "fleet_storm_window_s": ("the restart-storm counting window", "—"),
    "overload_limit_initial": ("router AIMD concurrency limit starting "
                               "point (serving/overload.py)", "—"),
    "overload_limit_min": ("AIMD multiplicative-decrease floor", "—"),
    "overload_limit_max": ("AIMD additive-increase ceiling", "—"),
    "overload_aimd_increase": ("additive increase applied as "
                               "increase/limit per clean completion "
                               "(~ +increase per full window)", "—"),
    "overload_aimd_decrease": ("multiplicative factor on an upstream "
                               "overload signal (replica 429/503), at "
                               "most once per congestion cooldown", "—"),
    "overload_slo_ttft_ms": ("brownout-ladder SLO target on the "
                             "router's recent-window TTFT p99; 0 "
                             "disables the ladder (default)", "—"),
    "overload_window_s": ("recent window for the SLO p99 and the "
                          "drain-rate estimate behind Retry-After", "—"),
    "overload_brownout_hold_s": ("sustained SLO breach before the "
                                 "ladder steps UP one rung", "—"),
    "overload_brownout_exit_s": ("sustained health before the ladder "
                                 "steps DOWN one rung", "—"),
    "overload_brownout_max_tokens": ("per-request max_tokens cap "
                                     "applied at brownout rung 2 "
                                     "(capped streams stay bit-identical "
                                     "prefixes)", "—"),
    "autoscaler_poll_interval_s": ("how often the autoscaler reads the "
                                   "router/replica metrics surface and "
                                   "evaluates the control law", "—"),
    "autoscaler_target_ttft_ms": ("the TTFT p99 target the control "
                                  "loop tracks (serving/autoscaler.py)",
                                  "—"),
    "autoscaler_hysteresis": ("dead band around the target: scale out "
                              "above target*(1+h), scale in below "
                              "target*(1-h) only — flap damping", "—"),
    "autoscaler_breach_polls": ("consecutive breach polls before a "
                                "scale-out fires", "—"),
    "autoscaler_slack_polls": ("consecutive slack polls before a "
                               "scale-in fires", "—"),
    "autoscaler_cooldown_out_s": ("minimum gap after ANY scale action "
                                  "before a scale-out may fire (short: "
                                  "react to load fast)", "—"),
    "autoscaler_cooldown_in_s": ("minimum gap after ANY scale action "
                                 "before a scale-in may fire (long: a "
                                 "scale-in cannot promptly undo a "
                                 "scale-out — flap damping)", "—"),
    "autoscaler_min_replicas": ("fleet size floor the autoscaler may "
                                "never go below", "—"),
    "autoscaler_max_replicas": ("fleet size ceiling the autoscaler may "
                                "never exceed", "—"),
    "autoscaler_window_s": ("recent window for the SLO p99 the control "
                            "law evaluates", "—"),
    "autoscaler_seed": ("seed for the poll-jitter and actuation-retry "
                        "backoff streams (decisions replay bit-for-bit)",
                        "—"),
    "serving_drain_timeout_s": ("hard deadline for the SIGTERM graceful "
                                "drain; a wedged batch can no longer "
                                "hang shutdown (second SIGTERM forces "
                                "exit)", "—"),
    "resilience_fault_spec": ("deterministic fault-injection plan "
                              "(point:at=N/every=K/p=x,seed=S,"
                              "action=error/hang) — chaos testing "
                              "only, strictly no-op when empty", "—"),
    "resilience_step_deadline_ms": ("decode-step watchdog deadline; a "
                                    "hung step is abandoned, the slab "
                                    "rebuilt, slots re-prefilled "
                                    "(0 = off)", "—"),
    "resilience_breaker_threshold": ("consecutive step failures that "
                                     "open the circuit breaker (shed "
                                     "503 + Retry-After)", "—"),
    "resilience_breaker_cooldown_s": ("open-breaker cooldown before the "
                                      "half-open probe", "—"),
    "resilience_retry_budget": ("bounded retries (exp backoff + jitter) "
                                "for transient submit failures", "—"),
    "analysis_baseline": ("static-analyzer allow-list path for `python "
                          "-m paddle_tpu.analysis` (None = the "
                          "committed paddle_tpu/analysis/baseline.json)",
                          "—"),
    "analysis_strict": ("static analyzer: stale baseline entries fail "
                        "the gate (rc 1) instead of warning — keeps the "
                        "allow-list honest in CI", "—"),
    "obs_trace_enable": ("per-request span tracing (obs/trace.py): "
                         "host-side recorder + /debug/traces + Chrome "
                         "export; strictly no-op when off", "—"),
    "obs_trace_sample": ("head-sampling rate, decided deterministically "
                         "from the trace_id hash (every process keeps "
                         "or drops the SAME traces)", "—"),
    "obs_trace_ring": ("completed spans the tracer ring retains "
                       "(oldest overwritten)", "—"),
    "profile_dir": ("capture an xprof/TensorBoard device trace", "—"),
    "debug_nans": ("fail fast on the op producing a NaN",
                   "feenableexcept (TrainerMain.cpp)"),
    "memory_profile_path": ("dump a device memory profile", "—"),
}

_TABLE_BEGIN = ("<!-- BEGIN GENERATED FLAGS TABLE "
                "(python -m paddle_tpu.utils.flags; do not edit) -->")
_TABLE_END = "<!-- END GENERATED FLAGS TABLE -->"


def flags_table_md():
    """The docs/flags.md flag-reference table, generated from the Flags
    dataclass + FLAG_DOCS so the doc can never drift from the code."""
    lines = [_TABLE_BEGIN,
             "",
             "| flag | default | meaning | reference cmd_parameter |",
             "|---|---|---|---|"]
    for field in dataclasses.fields(Flags):
        help_, ref = (s.replace("|", "\\|") for s in FLAG_DOCS[field.name])
        default = "None" if field.default is None else repr(field.default)
        lines.append(f"| `--{field.name}` | `{default}` | {help_} | "
                     f"{ref} |")
    lines += ["", _TABLE_END]
    return "\n".join(lines)


# Reference flags with no runtime role here, and why — the lookup table for
# migrating users (reference Flags.cpp names):
SUBSUMED = {
    "use_gpu": "use_tpu (XLA backend selection)",
    "gpu_id": "device choice is XLA's; use JAX_PLATFORMS / mesh flags",
    "trainer_count": "data_parallel mesh axis",
    "parallel_nn": "model_parallel mesh axis (sharding rules)",
    "port": "coordinator (jax.distributed rendezvous)",
    "ports_num": "single coordinator address suffices",
    "ports_num_for_sparse": "sparse tables shard over the mesh like any param",
    "nics": "ICI/DCN routing is platform-managed",
    "rdma_tcp": "ICI/DCN routing is platform-managed",
    "trainer_id": "process_id",
    "num_gradient_servers": "num_processes",
    "start_pserver": "no parameter server exists",
    "loadsave_parameters_in_pserver": "checkpoints are sharded pytrees",
    "log_period_server": "no parameter server exists",
    "enable_parallel_vector": "XLA vectorizes",
    "distribute_test": "test() runs under the same mesh",
    "test_all_data_in_one_period": "test() always consumes the full reader",
    "test_wait": "no async pserver to wait for",
    "local": "mesh with one host",
    "model_list / feat_file": "model zoo APIs replace the predict drivers",
}


FLAGS = Flags()


if __name__ == "__main__":
    print(flags_table_md())
