"""Global runtime flags.

TPU-native equivalent of the reference's ~60 gflags (paddle/utils/Flags.cpp:18-110);
multi-GPU/pserver topology flags become mesh-shape flags here.
"""

import argparse
import dataclasses
from typing import Optional


@dataclasses.dataclass
class Flags:
    # device / precision
    use_tpu: bool = True
    dtype: str = "float32"          # parameter dtype ("real" in the reference)
    compute_dtype: str = "bfloat16"  # matmul/conv compute dtype on TPU

    # training loop (reference: --log_period, --saving_period, --test_period)
    log_period: int = 100
    saving_period: int = 1
    test_period: int = 0
    num_passes: int = 1
    start_pass: int = 0
    save_dir: Optional[str] = None
    save_only_one: bool = False
    seed: int = 1

    # parallelism (replaces --trainer_count / pserver topology)
    data_parallel: int = 0   # 0 = all devices
    model_parallel: int = 1
    seq_parallel: int = 1
    expert_parallel: int = 1

    # decoding
    beam_size: int = 1

    # data
    async_load_data: bool = True
    prefetch_depth: int = 2

    def update_from_args(self, args):
        for field in dataclasses.fields(self):
            if hasattr(args, field.name) and getattr(args, field.name) is not None:
                setattr(self, field.name, getattr(args, field.name))

    def add_to_parser(self, parser: argparse.ArgumentParser):
        for field in dataclasses.fields(self):
            name = "--" + field.name
            if field.type is bool or isinstance(field.default, bool):
                parser.add_argument(name, type=lambda v: v.lower() in ("1", "true", "yes"),
                                    default=None)
            else:
                typ = int if isinstance(field.default, int) else str
                parser.add_argument(name, type=typ, default=None)


FLAGS = Flags()
