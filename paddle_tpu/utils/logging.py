"""Logging (reference: paddle/utils/Logging.h glog wrapper).

Two formats, chosen by ``PADDLE_TPU_LOG_FORMAT`` (or ``set_format()``):

* ``text`` (default) — the familiar glog-style line;
* ``json`` — one JSON object per line (machine-ingestible).

Both formats append the CONTEXT-LOCAL correlation fields installed by
``log_context(...)`` — the server/router HTTP handlers wrap each request
in ``log_context(trace_id=..., request_id=...)`` (obs/trace.py ids), so
``grep trace_id=<id>`` crosses the router's and every replica's logs for
one request (docs/observability.md).  The text format appends
``trace_id=...`` key=value pairs; the json format carries them both as
top-level fields and in the same greppable ``k=v`` tail.
"""

import contextlib
import contextvars
import json
import logging
import os
import sys

_FMT = "%(levelname).1s %(asctime)s %(name)s] %(message)s"

# context-local correlation fields (per-thread and per-async-context,
# like obs/trace.py's current-span variable)
_log_ctx = contextvars.ContextVar("paddle_tpu_log_ctx", default=None)


@contextlib.contextmanager
def log_context(**fields):
    """Attach correlation fields (request_id=, trace_id=, ...) to every
    log line emitted inside the with-body on this thread/context.
    Falsy values are dropped; nesting merges."""
    merged = dict(_log_ctx.get() or {})
    merged.update({k: str(v) for k, v in fields.items() if v})
    token = _log_ctx.set(merged)
    try:
        yield
    finally:
        _log_ctx.reset(token)


def context_fields():
    """The currently attached correlation fields (read-only copy)."""
    return dict(_log_ctx.get() or {})


def _ctx_tail():
    fields = _log_ctx.get()
    if not fields:
        return ""
    return " " + " ".join(f"{k}={fields[k]}" for k in sorted(fields))


class _TextFormatter(logging.Formatter):
    def format(self, record):
        return super().format(record) + _ctx_tail()


class _JsonFormatter(logging.Formatter):
    def format(self, record):
        out = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S"),
            "level": record.levelname,
            "logger": record.name,
            # the greppable tail rides inside msg too, so one
            # `grep trace_id=<id>` crosses text- and json-format logs
            "msg": record.getMessage() + _ctx_tail(),
        }
        out.update(_log_ctx.get() or {})
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def _make_formatter(fmt=None):
    fmt = fmt or os.environ.get("PADDLE_TPU_LOG_FORMAT", "text")
    if fmt == "json":
        return _JsonFormatter()
    return _TextFormatter(_FMT, datefmt="%m%d %H:%M:%S")


def set_format(fmt):
    """Switch every handler this module installed to ``"text"`` or
    ``"json"`` (the env var sets the initial choice)."""
    for log in _loggers:
        for h in log.handlers:
            h.setFormatter(_make_formatter(fmt))


_loggers = []


def get_logger(name: str = "paddle_tpu", level=None) -> logging.Logger:
    log = logging.getLogger(name)
    if not log.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_make_formatter())
        log.addHandler(handler)
        log.propagate = False
        log.setLevel(level or os.environ.get("PADDLE_TPU_LOG_LEVEL", "INFO"))
        _loggers.append(log)
    return log


logger = get_logger()
