"""Logging (reference: paddle/utils/Logging.h glog wrapper)."""

import logging
import os
import sys

_FMT = "%(levelname).1s %(asctime)s %(name)s] %(message)s"


def get_logger(name: str = "paddle_tpu", level=None) -> logging.Logger:
    log = logging.getLogger(name)
    if not log.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FMT, datefmt="%m%d %H:%M:%S"))
        log.addHandler(handler)
        log.propagate = False
        log.setLevel(level or os.environ.get("PADDLE_TPU_LOG_LEVEL", "INFO"))
    return log


logger = get_logger()
