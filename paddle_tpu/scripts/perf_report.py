"""Render docs/perf.md tables from bench_cache.json.

After a healthy-window sweep fills the cache, this prints the markdown
tables the perf doc wants — BASELINE families vs the K40m reference,
the TPU scaling column, the fused-vs-scan RNN kernel comparison, and the
serving-decode row — each row carrying its measured_at timestamp so
provenance survives the paste.

Usage:  python -m paddle_tpu.scripts.perf_report [--cache bench_cache.json]
"""

import argparse
import json
import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_FAMILY_ORDER = ["lstm256", "lstm", "lstm1280", "smallnet", "alexnet",
                 "googlenet", "resnet50", "seq2seq", "transformer",
                 "transformer_long", "transformer_decode",
                 "transformer_serving"]


def _fmt_mfu(e):
    return f"{e['mfu'] * 100:.1f}%" if e.get("mfu") is not None else "—"


def _fmt_speedup(e):
    return f"{e['vs_baseline']}×" if e.get("vs_baseline") else "—"


def _stamp(e):
    return (e.get("measured_at") or "")[:16]


def families_table(cache):
    lines = ["| model | batch | ref K40m ms | TPU ms | speedup | MFU | "
             "tokens/s | measured |",
             "|---|---|---|---|---|---|---|---|"]
    for name in _FAMILY_ORDER:
        e = cache.get(name)
        if not e or e.get("value") is None:
            continue
        m = re.search(r"bs=(\d+)", e.get("metric", ""))
        batch = m.group(1) if m else "?"
        # the K40m reference ms is recoverable from the cached speedup —
        # one source of truth (bench.py's baselines), nothing re-typed here
        ref = round(e["value"] * e["vs_baseline"], 1) \
            if e.get("vs_baseline") else None
        lines.append(
            f"| {name} | {batch} | {ref if ref else 'n/a'} | "
            f"{e['value']} | {_fmt_speedup(e)} | {_fmt_mfu(e)} | "
            f"{e.get('tokens_per_s') or '—'} | {_stamp(e)} |")
    return "\n".join(lines)


def scaling_table(cache):
    def key(k):
        m = re.search(r"@bs(\d+)", k)
        return (k.split("@")[0], int(m.group(1)) if m else 0)

    rows = sorted((k for k in cache if "@bs" in k and "@scan" not in k
                   and "@bfloat16" not in k and "@float32" not in k),
                  key=key)
    if not rows:
        return "(no scaling rows cached yet)"
    lines = ["| run | TPU ms | MFU | tokens/s | remat | measured |",
             "|---|---|---|---|---|---|"]
    for k in rows:
        e = cache[k]
        if e.get("value") is None:
            continue
        lines.append(f"| {k} | {e['value']} | {_fmt_mfu(e)} | "
                     f"{e.get('tokens_per_s') or '—'} | "
                     f"{'yes' if e.get('remat') else 'no'} | {_stamp(e)} |")
    return "\n".join(lines)


def _suffix_pairs(cache, suffix):
    """[(base_key, base_row, variant_row)] for key+suffix variants whose
    base row exists; both sides value-guarded."""
    pairs = []
    for k, e in cache.items():
        if k.endswith(suffix) and e.get("value") is not None:
            base = cache.get(k[:-len(suffix)])
            if base and base.get("value") is not None:
                pairs.append((k[:-len(suffix)], base, e))
    return sorted(pairs)


def bf16_table(cache):
    """bf16 pairs (phase 2c rows cache under key@bfloat16).  The baseline
    is an explicit @float32 row when one exists; otherwise the bare row,
    which on TPU runs the AUTO policy (bf16 MXU inputs, f32 params/
    activations) — labelled so the delta is not misread as f32-vs-bf16
    compute when it is really the half-width HBM effect."""
    pairs = []
    for name, base, b in _suffix_pairs(cache, "@bfloat16"):
        f32 = cache.get(name + "@float32")
        if f32 and f32.get("value") is not None:
            pairs.append((name, "f32", f32, b))
        else:
            pairs.append((name, "auto", base, b))
    if not pairs:
        return "(no bf16 pairs cached yet)"
    lines = ["| run | baseline | baseline ms | bf16 ms | bf16 speedup | "
             "bf16 MFU | measured |",
             "|---|---|---|---|---|---|---|"]
    for name, kind, base, b in pairs:
        lines.append(
            f"| {name} | {kind} | {base['value']} | {b['value']} | "
            f"{base['value'] / b['value']:.2f}× | {_fmt_mfu(b)} | "
            f"{_stamp(b)} |")
    return "\n".join(lines)


def kernel_table(cache):
    pairs = [(name, base, scan)
             for name, base, scan in _suffix_pairs(cache, "@scan")]
    if not pairs:
        return "(no fused-vs-scan pairs cached yet)"
    lines = ["| model | fused ms | scan ms | kernel speedup | path | "
             "measured |",
             "|---|---|---|---|---|---|"]
    for name, fused, scan in pairs:
        # fused_rnn False on the "fused" row means the dispatcher actually
        # ran the scan (fallback/guard) — flag it rather than implying a
        # kernel win
        path = "kernel" if fused.get("fused_rnn", True) else "scan (!)"
        lines.append(
            f"| {name} | {fused['value']} | {scan['value']} | "
            f"{scan['value'] / fused['value']:.2f}× | {path} | "
            f"{_stamp(fused)} |")
    return "\n".join(lines)


def int8_table(cache):
    """Weight-only int8 serving column (phase 2d rows cache under
    key@int8): the float-vs-int8 latency ratio isolates the weight-stream
    HBM effect — the serving figure of merit docs/serving.md promises."""
    pairs = _suffix_pairs(cache, "@int8")
    if not pairs:
        return "(no int8 pairs cached yet)"
    lines = ["| model | float ms | int8 ms | int8 speedup | measured |",
             "|---|---|---|---|---|"]
    for name, base, q in pairs:
        lines.append(
            f"| {name} | {base['value']} | {q['value']} | "
            f"{base['value'] / q['value']:.2f}× | {_stamp(q)} |")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cache",
                    default=os.path.join(_REPO, "bench_cache.json"))
    args = ap.parse_args(argv)
    with open(args.cache) as f:
        cache = json.load(f)
    print("## Benchmark families (vs BASELINE.md K40m)\n")
    print(families_table(cache))
    print("\n## TPU scaling column\n")
    print(scaling_table(cache))
    print("\n## Mixed-precision (bf16) column\n")
    print(bf16_table(cache))
    print("\n## Fused Pallas RNN kernels vs lax.scan\n")
    print(kernel_table(cache))
    print("\n## Weight-only int8 serving column\n")
    print(int8_table(cache))


if __name__ == "__main__":
    main()
