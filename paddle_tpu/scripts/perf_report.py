"""Render docs/perf.md tables from bench_cache.json + analytic gates.

After a healthy-window sweep fills the cache, this prints the markdown
tables the perf doc wants — BASELINE families vs the K40m reference,
the TPU scaling column, the fused-vs-scan RNN kernel comparison, and the
serving-decode row — each row carrying its measured_at timestamp so
provenance survives the paste.

Analytic mode (round-6, chip-independent):
  --analytic-diff OLD.json NEW.json   structural regression gate between
      two `bench.py --analytic` snapshots: exits non-zero when a family's
      bytes-accessed inflates, its FLOPs inflate, its HLO op mix shows a
      de-fusion (op counts ballooning / fusions collapsing), or a family
      disappears.  Identical snapshots always pass.
  --analytic-table SNAP.json          render the per-family roofline
      markdown table for docs/perf.md "Analytic roofline".

Usage:  python -m paddle_tpu.scripts.perf_report [--cache bench_cache.json]
"""

import argparse
import json
import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_FAMILY_ORDER = ["lstm256", "lstm", "lstm1280", "smallnet", "alexnet",
                 "googlenet", "resnet50", "seq2seq", "transformer",
                 "transformer_long", "transformer_decode",
                 "transformer_serving"]


def _fmt_mfu(e):
    return f"{e['mfu'] * 100:.1f}%" if e.get("mfu") is not None else "—"


def _fmt_speedup(e):
    return f"{e['vs_baseline']}×" if e.get("vs_baseline") else "—"


def _stamp(e):
    return (e.get("measured_at") or "")[:16]


def families_table(cache):
    lines = ["| model | batch | ref K40m ms | TPU ms | speedup | MFU | "
             "tokens/s | measured |",
             "|---|---|---|---|---|---|---|---|"]
    for name in _FAMILY_ORDER:
        e = cache.get(name)
        if not e or e.get("value") is None:
            continue
        m = re.search(r"bs=(\d+)", e.get("metric", ""))
        batch = m.group(1) if m else "?"
        # the K40m reference ms is recoverable from the cached speedup —
        # one source of truth (bench.py's baselines), nothing re-typed here
        ref = round(e["value"] * e["vs_baseline"], 1) \
            if e.get("vs_baseline") else None
        lines.append(
            f"| {name} | {batch} | {ref if ref else 'n/a'} | "
            f"{e['value']} | {_fmt_speedup(e)} | {_fmt_mfu(e)} | "
            f"{e.get('tokens_per_s') or '—'} | {_stamp(e)} |")
    return "\n".join(lines)


def scaling_table(cache):
    def key(k):
        m = re.search(r"@bs(\d+)", k)
        return (k.split("@")[0], int(m.group(1)) if m else 0)

    rows = sorted((k for k in cache if "@bs" in k and "@scan" not in k
                   and "@bfloat16" not in k and "@float32" not in k),
                  key=key)
    if not rows:
        return "(no scaling rows cached yet)"
    lines = ["| run | TPU ms | MFU | tokens/s | remat | measured |",
             "|---|---|---|---|---|---|"]
    for k in rows:
        e = cache[k]
        if e.get("value") is None:
            continue
        lines.append(f"| {k} | {e['value']} | {_fmt_mfu(e)} | "
                     f"{e.get('tokens_per_s') or '—'} | "
                     f"{'yes' if e.get('remat') else 'no'} | {_stamp(e)} |")
    return "\n".join(lines)


def _suffix_pairs(cache, suffix):
    """[(base_key, base_row, variant_row)] for key+suffix variants whose
    base row exists; both sides value-guarded."""
    pairs = []
    for k, e in cache.items():
        if k.endswith(suffix) and e.get("value") is not None:
            base = cache.get(k[:-len(suffix)])
            if base and base.get("value") is not None:
                pairs.append((k[:-len(suffix)], base, e))
    return sorted(pairs)


def bf16_table(cache):
    """bf16 pairs (phase 2c rows cache under key@bfloat16).  The baseline
    is an explicit @float32 row when one exists; otherwise the bare row,
    which on TPU runs the AUTO policy (bf16 MXU inputs, f32 params/
    activations) — labelled so the delta is not misread as f32-vs-bf16
    compute when it is really the half-width HBM effect."""
    pairs = []
    for name, base, b in _suffix_pairs(cache, "@bfloat16"):
        f32 = cache.get(name + "@float32")
        if f32 and f32.get("value") is not None:
            pairs.append((name, "f32", f32, b))
        else:
            pairs.append((name, "auto", base, b))
    if not pairs:
        return "(no bf16 pairs cached yet)"
    lines = ["| run | baseline | baseline ms | bf16 ms | bf16 speedup | "
             "bf16 MFU | measured |",
             "|---|---|---|---|---|---|---|"]
    for name, kind, base, b in pairs:
        lines.append(
            f"| {name} | {kind} | {base['value']} | {b['value']} | "
            f"{base['value'] / b['value']:.2f}× | {_fmt_mfu(b)} | "
            f"{_stamp(b)} |")
    return "\n".join(lines)


def kernel_table(cache):
    pairs = [(name, base, scan)
             for name, base, scan in _suffix_pairs(cache, "@scan")]
    if not pairs:
        return "(no fused-vs-scan pairs cached yet)"
    lines = ["| model | fused ms | scan ms | kernel speedup | path | "
             "measured |",
             "|---|---|---|---|---|---|"]
    for name, fused, scan in pairs:
        # fused_rnn False on the "fused" row means the dispatcher actually
        # ran the scan (fallback/guard) — flag it rather than implying a
        # kernel win
        path = "kernel" if fused.get("fused_rnn", True) else "scan (!)"
        lines.append(
            f"| {name} | {fused['value']} | {scan['value']} | "
            f"{scan['value'] / fused['value']:.2f}× | {path} | "
            f"{_stamp(fused)} |")
    return "\n".join(lines)


def int8_table(cache):
    """Weight-only int8 serving column (phase 2d rows cache under
    key@int8): the float-vs-int8 latency ratio isolates the weight-stream
    HBM effect — the serving figure of merit docs/serving.md promises."""
    pairs = _suffix_pairs(cache, "@int8")
    if not pairs:
        return "(no int8 pairs cached yet)"
    lines = ["| model | float ms | int8 ms | int8 speedup | measured |",
             "|---|---|---|---|---|"]
    for name, base, q in pairs:
        lines.append(
            f"| {name} | {base['value']} | {q['value']} | "
            f"{base['value'] / q['value']:.2f}× | {_stamp(q)} |")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Analytic snapshots (bench.py --analytic): structural diff + doc table.
# The gate's thresholds are deliberately loose enough to ride out XLA-
# version churn in op counts and tight enough that a real de-fusion (a
# matmul split into blocks, an elementwise chain falling out of its
# consumer) trips them — tests/test_perf_analytic.py pins both directions.

DIFF_TOLERANCES = {
    "flops_tol": 0.10,     # relative FLOP inflation allowed
    "bytes_tol": 0.25,     # relative bytes-accessed inflation allowed
    "op_total_tol": 0.30,  # relative HLO op-count growth allowed
    "op_abs_min": 4,       # per-op growth below this many ops is noise
    "op_rel_tol": 0.50,    # per-op relative growth allowed (with abs_min)
    "fusion_tol": 0.50,    # fusion-count collapse allowed (with flat total)
}


def _load_snapshot(path):
    with open(path) as f:
        snap = json.load(f)
    if "families" not in snap:
        raise SystemExit(f"{path}: not an analytic snapshot "
                         "(no 'families' key)")
    return snap


def analytic_diff(old, new, **tols):
    """Structural regressions between two analytic snapshots.

    Returns a list of human-readable regression strings (empty = pass).
    Improvements (fewer bytes, fewer ops, more fusion) never flag; only
    the regression direction does, so the gate stays quiet on wins.
    """
    t = dict(DIFF_TOLERANCES)
    t.update(tols)
    regs = []
    old_fams, new_fams = old["families"], new["families"]
    for name in sorted(old_fams):
        o, n = old_fams[name], new_fams.get(name)
        if o.get("error"):
            continue                 # no structural baseline to regress from
        if n is None:
            regs.append(f"{name}: family missing from new snapshot")
            continue
        if n.get("error"):
            regs.append(f"{name}: now fails to build/compile "
                        f"({n['error']})")
            continue
        def _growth(new, old):
            # cost.extract can report 0 for a metric XLA's table omits on
            # some backend/version; a 0 -> nonzero jump is still a
            # reportable regression, never a ZeroDivisionError
            return f"+{new / old - 1:.0%}" if old else "0 -> nonzero"

        if n["flops"] > o["flops"] * (1 + t["flops_tol"]):
            regs.append(
                f"{name}: flops inflated {o['flops']:.3g} -> "
                f"{n['flops']:.3g} ({_growth(n['flops'], o['flops'])} > "
                f"{t['flops_tol']:.0%})")
        if n["bytes_accessed"] > o["bytes_accessed"] * (1 + t["bytes_tol"]):
            regs.append(
                f"{name}: bytes accessed inflated {o['bytes_accessed']:.3g}"
                f" -> {n['bytes_accessed']:.3g} "
                f"({_growth(n['bytes_accessed'], o['bytes_accessed'])} > "
                f"{t['bytes_tol']:.0%})")
        oh, nh = o["hlo_op_histogram"], n["hlo_op_histogram"]
        o_total, n_total = sum(oh.values()), sum(nh.values())
        if n_total > o_total * (1 + t["op_total_tol"]) \
                and n_total - o_total >= t["op_abs_min"]:
            regs.append(
                f"{name}: HLO op count inflated {o_total} -> {n_total} "
                f"({_growth(n_total, o_total)} > {t['op_total_tol']:.0%})"
                " — likely de-fusion")
        # fusions collapsing with the op total flat: XLA materialized a
        # previously-fused chain (ops moved from fusion bodies to top
        # level, so the total barely moves and bytes may stay under
        # bytes_tol) — the third face of de-fusion.  A genuine
        # simplification shrinks the total too, and stays quiet.
        o_fus, n_fus = oh.get("fusion", 0), nh.get("fusion", 0)
        if o_fus - n_fus >= t["op_abs_min"] \
                and n_fus < o_fus * (1 - t["fusion_tol"]) \
                and n_total >= o_total * (1 - t["fusion_tol"]):
            regs.append(
                f"{name}: fusion count collapsed {o_fus} -> {n_fus} with "
                f"op total flat ({o_total} -> {n_total}) — de-fusion")
        for op in sorted(set(oh) | set(nh)):
            oc, nc = oh.get(op, 0), nh.get(op, 0)
            if nc - oc >= t["op_abs_min"] \
                    and nc > oc * (1 + t["op_rel_tol"]):
                regs.append(f"{name}: '{op}' ops {oc} -> {nc} "
                            "— structural change (split/de-fused kernel?)")
    return regs


def analytic_table(snap):
    """Markdown table for docs/perf.md 'Analytic roofline'.

    Rows follow the canonical analytic.FAMILIES order (the committed doc
    table's order), with any unknown names appended sorted — so the
    regeneration command reproduces the committed layout byte-for-byte."""
    try:
        from paddle_tpu.perf.analytic import FAMILIES
        order = [f[0] for f in FAMILIES]
    except ImportError:
        order = []
    names = [n for n in order if n in snap["families"]] \
        + sorted(n for n in snap["families"] if n not in order)
    lines = ["| family | batch | GFLOP/step | MB accessed | FLOP/B | "
             "v5e predicted ms | predicted MFU ≤ | #1 bottleneck |",
             "|---|---|---|---|---|---|---|---|"]
    for name in names:
        r = snap["families"][name]
        if r.get("error"):
            lines.append(f"| {name} | {r.get('batch', '?')} | "
                         f"(error: {r['error'][:60]}) | | | | | |")
            continue
        ai = r["arithmetic_intensity"]
        lines.append(
            f"| {name} | {r['batch']} | {r['flops'] / 1e9:.1f} | "
            f"{r['bytes_accessed'] / 1e6:.0f} | "
            f"{f'{ai:.0f}' if ai is not None else '—'} | "
            f"{r['predicted_ms']:.2f} | "
            f"{r['predicted_mfu'] * 100:.0f}% | {r['bottleneck']} |")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cache",
                    default=os.path.join(_REPO, "bench_cache.json"))
    ap.add_argument("--analytic-diff", nargs=2,
                    metavar=("OLD", "NEW"), default=None)
    ap.add_argument("--analytic-table", default=None, metavar="SNAP")
    ap.add_argument("--bytes-tol", type=float, default=None)
    ap.add_argument("--flops-tol", type=float, default=None)
    args = ap.parse_args(argv)

    if args.analytic_diff:
        old, new = (_load_snapshot(p) for p in args.analytic_diff)
        tols = {}
        if args.bytes_tol is not None:
            tols["bytes_tol"] = args.bytes_tol
        if args.flops_tol is not None:
            tols["flops_tol"] = args.flops_tol
        regs = analytic_diff(old, new, **tols)
        for r in regs:
            print(f"ANALYTIC REGRESSION: {r}")
        if regs:
            print(f"{len(regs)} analytic regression(s) between "
                  f"{args.analytic_diff[0]} and {args.analytic_diff[1]}")
            return 1
        print(f"analytic diff clean: {len(old['families'])} famil"
              f"{'ies' if len(old['families']) != 1 else 'y'} compared")
        return 0

    if args.analytic_table:
        print(analytic_table(_load_snapshot(args.analytic_table)))
        return 0

    with open(args.cache) as f:
        cache = json.load(f)
    print("## Benchmark families (vs BASELINE.md K40m)\n")
    print(families_table(cache))
    print("\n## TPU scaling column\n")
    print(scaling_table(cache))
    print("\n## Mixed-precision (bf16) column\n")
    print(bf16_table(cache))
    print("\n## Fused Pallas RNN kernels vs lax.scan\n")
    print(kernel_table(cache))
    print("\n## Weight-only int8 serving column\n")
    print(int8_table(cache))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
