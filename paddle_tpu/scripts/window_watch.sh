#!/usr/bin/env bash
# Chip prober + auto-trigger: loop a short real-matmul probe against the
# tunneled TPU; the moment it answers, hand off to healthy_window.sh.
# Run detached from round start so no healthy minute is wasted waiting
# for a human (round-3 verdict: "keep a prober running from minute zero").
#
#   bash paddle_tpu/scripts/window_watch.sh [artifacts_dir]
#
# Log: /tmp/window_watch.log (probe timeline), plus healthy_window's own
# logs once triggered.  A wedge AFTER the handoff is healthy_window's
# problem (its phases are resumable); this script does not re-trigger —
# re-launch it for another window.
set -u
cd "$(dirname "$0")/../.."
ART="${1:-$PWD/artifacts/r5}"
LOG=/tmp/window_watch.log
probe() {
    timeout 75 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
assert float((x @ x).block_until_ready()[0, 0]) == 256.0
assert jax.default_backend() == "tpu"
EOF
}
echo "[watch $(date -u +%H:%M:%S)] prober up (pid $$)" >> "$LOG"
while true; do
    if probe; then
        echo "[watch $(date -u +%H:%M:%S)] chip ANSWERED — launching healthy_window" >> "$LOG"
        exec bash paddle_tpu/scripts/healthy_window.sh "$ART"
    fi
    echo "[watch $(date -u +%H:%M:%S)] wedged" >> "$LOG"
    sleep 150
done
