#!/usr/bin/env bash
# Chip prober + auto-trigger: loop a short real-matmul probe against the
# tunneled TPU; the moment it answers, hand off to healthy_window.sh.
# Run detached from round start so no healthy minute is wasted waiting
# for a human (round-3 verdict: "keep a prober running from minute zero").
#
#   bash paddle_tpu/scripts/window_watch.sh [artifacts_dir]
#
# Log: /tmp/window_watch.log (probe timeline), plus healthy_window's own
# logs once triggered.  The loop re-triggers healthy_window after every
# return (wedge mid-queue OR completed queue) — run ONE instance; a
# second would fight over the same artifacts dir and chip.
set -u
cd "$(dirname "$0")/../.."
ART="${1:-$PWD/artifacts/r5}"
LOG=/tmp/window_watch.log
probe() {
    timeout 75 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
assert float((x @ x).block_until_ready()[0, 0]) == 256.0
assert jax.default_backend() == "tpu"
EOF
}
echo "[watch $(date -u +%H:%M:%S)] prober up (pid $$)" >> "$LOG"
while true; do
    if probe; then
        echo "[watch $(date -u +%H:%M:%S)] chip ANSWERED — launching healthy_window" >> "$LOG"
        # run (not exec): if the window wedges mid-queue or completes,
        # keep probing — a later window resumes the queue (skip-fresh
        # and per-phase caches make re-entry cheap, and a completed
        # queue's re-run is nearly a no-op)
        bash paddle_tpu/scripts/healthy_window.sh "$ART" \
            >> "$LOG" 2>&1
        echo "[watch $(date -u +%H:%M:%S)] healthy_window returned rc=$?; resuming probe" >> "$LOG"
        sleep 150
        continue
    fi
    echo "[watch $(date -u +%H:%M:%S)] wedged" >> "$LOG"
    sleep 150
done
