"""Scaling sweep driver: run bench.py over (model, batch) combos.

The reference's benchmark table sweeps batch sizes per model
(benchmark/README.md:33-120); the TPU equivalent sweeps into MXU-saturating
batches (the round-2 verdict's scaling column: ResNet/GoogleNet at bs
256-1024, transformer at >=32k tokens/batch).  Each combo runs as its own
bench.py subprocess (fresh backend, own watchdog) and lands in
bench_cache.json under model@bsN, so one healthy chip window fills the
whole table and the round-end bench replays it from cache.

Usage:
  python -m paddle_tpu.scripts.bench_sweep [--combos m:b,m:b,...]
      [--steps N] [--timeout S]
  python -m paddle_tpu.scripts.bench_sweep --analytic
      (chip-independent: write the analytic cost/roofline snapshot on the
      CPU backend instead of running live combos — see paddle_tpu/perf/)
Default combos cover the BASELINE.md families at their reference batch
plus the TPU scaling points.
"""

import argparse
import calendar
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fresh_live_row(model, batch, max_age_s, cache_path=None):
    """Return the bench_cache.json row for this combo if it was measured
    LIVE at the current code revision within max_age_s — i.e. re-running it
    would spend healthy-window time reproducing a number we already have.
    Conservative: any parse/import/revision mismatch means 'not fresh'."""
    if max_age_s <= 0:
        return None
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        # a cpu sweep must never report the committed TPU rows as its own
        return None
    try:
        if _REPO not in sys.path:
            sys.path.insert(0, _REPO)
        import bench
        from paddle_tpu.utils.revision import code_revision
        key = bench.cache_key_for(model, batch)
        cache_path = cache_path or os.path.join(_REPO, "bench_cache.json")
        with open(cache_path) as f:
            row = json.load(f).get(key)
        if not row or row.get("value") is None:
            return None
        if row.get("platform") == "cpu":
            # a BENCH_CACHE_CPU row must not suppress the live TPU run
            return None
        rev = code_revision()
        if "+" in rev or rev == "unknown" or row.get("revision") != rev:
            return None
        age = time.time() - calendar.timegm(
            time.strptime(row["measured_at"], "%Y-%m-%dT%H:%M:%SZ"))
        return row if 0 <= age <= max_age_s else None
    except Exception:   # noqa: BLE001
        return None

DEFAULT_COMBOS = [
    # BASELINE.md reference points (bs 64 rows)
    "lstm:64", "lstm256:64", "lstm1280:64",
    "alexnet:64", "googlenet:64", "smallnet:64", "resnet50:32",
    # BASELINE.md batch-scaling rows (benchmark/README.md:33-58,115-135:
    # AlexNet 128/256/512, GoogleNet 128/256, SmallNet 512, LSTM h=256
    # bs128, h=512 bs256) — the TPU column for every published row, not
    # just the 2016 bs-64 points
    "alexnet:128", "alexnet:256", "alexnet:512",
    "googlenet:128", "smallnet:512",
    "lstm256:128", "lstm:256",
    # TPU scaling column
    "resnet50:256", "resnet50:512", "resnet50:1024",
    "googlenet:256", "googlenet:512",
    "lstm1280:256",
    "lstm2048:64",                                # MXU-scale recurrent row
    "transformer_packed_8k:2",                    # 8k-slot packed rows
    "transformer:32", "transformer:128",          # 128*256 = 32768 tok
    "transformer_long:2",                         # 8k-token sequences
    "transformer_packed:16",                      # padding-free packing
    "transformer_moe:16",                         # sparse-expert LM step
    "transformer_decode:32",                      # KV-cached serving path
    "transformer_lm_decode:32",                   # LM sampling throughput
    "transformer_serving:16",                     # bucketed-length stream
    "seq2seq:64",
    "trainer_prefetch:64",                        # input-pipeline overlap
]


def _chip_alive(timeout_s=90):
    """Cheap liveness probe in a fresh subprocess: a 256x256 matmul that
    must land on the TPU backend (jax's silent CPU fallback would read a
    fast-failing wedge as alive — same assert as window_watch.sh).
    Distinguishes 'this combo was slow/oversized' from 'the chip wedged
    mid-window' after a *_timeout failure.  A cpu-forced sweep has no
    chip to probe: vacuously alive."""
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        return True
    code = ("import jax, jax.numpy as jnp;"
            "x = jnp.ones((256, 256));"
            "assert float((x @ x).block_until_ready()[0, 0]) == 256.0;"
            "assert jax.default_backend() == 'tpu'")
    try:
        return subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_combo(model, batch, steps, timeout):
    env = dict(os.environ)
    env["BENCH_MODEL"] = model
    env["BENCH_BATCH"] = str(batch)
    if steps:
        env["BENCH_STEPS"] = str(steps)
    if os.environ.get("BENCH_PROFILE_BASE"):
        # one xprof trace dir per combo, so scripts/xprof_report.py can
        # attribute each family's step time separately
        env["BENCH_PROFILE_DIR"] = os.path.join(
            os.environ["BENCH_PROFILE_BASE"], f"{model}_bs{batch}")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        env=env, cwd=_REPO, timeout=timeout, capture_output=True, text=True)
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        return json.loads(line)
    except ValueError:
        return {"error": "no_json", "rc": proc.returncode,
                "stderr": proc.stderr[-500:]}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--combos", default=",".join(DEFAULT_COMBOS))
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=1500)
    ap.add_argument("--analytic", action="store_true",
                    help="run the chip-independent analytic snapshot "
                         "(paddle_tpu.perf.analytic, CPU backend) instead "
                         "of live combos — the no-chip-window fallback")
    ap.add_argument("--analytic-out", default=None,
                    help="snapshot path for --analytic (default: "
                         "BENCH_ANALYTIC_r06.json at the repo root)")
    args = ap.parse_args(argv)

    if args.analytic:
        if _REPO not in sys.path:
            sys.path.insert(0, _REPO)
        from paddle_tpu.perf import analytic
        return analytic.main(["--out", args.analytic_out]
                             if args.analytic_out else [])

    try:
        skip_fresh_s = float(os.environ.get("BENCH_SWEEP_SKIP_FRESH_S", "0"))
    except ValueError:
        print("[sweep] bad BENCH_SWEEP_SKIP_FRESH_S (want seconds) — "
              "skip-fresh disabled", file=sys.stderr)
        skip_fresh_s = 0.0

    results = {}
    for combo in args.combos.split(","):
        combo = combo.strip()
        if not combo:
            continue
        model, sep, batch = combo.partition(":")
        if not sep or not batch.isdigit() or int(batch) < 1:
            print(f"[sweep] bad combo {combo!r} (want model:batch) — "
                  "skipping", file=sys.stderr, flush=True)
            results[combo] = {"error": "bad_combo"}
            continue
        batch = int(batch)
        # incremental across wedge-interrupted windows: a combo measured
        # live at this exact revision recently enough doesn't get re-run
        # (BENCH_SWEEP_SKIP_FRESH_S=0, the default, disables this)
        fresh = _fresh_live_row(model, batch, skip_fresh_s)
        if fresh is not None:
            row = {k: fresh.get(k) for k in
                   ("value", "unit", "vs_baseline", "mfu", "tokens_per_s")}
            row.update(error=None, cached=True, skipped_fresh=True)
            results[combo] = row
            print(f"[sweep] {combo}: fresh at this revision "
                  f"({fresh.get('measured_at')}) — skipping",
                  file=sys.stderr, flush=True)
            continue
        print(f"[sweep] {model} bs={batch} ...", file=sys.stderr, flush=True)
        try:
            r = run_combo(model, batch, args.steps, args.timeout)
        except subprocess.TimeoutExpired:
            r = {"error": "sweep_timeout"}
        row = {k: r.get(k) for k in
               ("value", "unit", "vs_baseline", "mfu",
                "tokens_per_s", "error", "cached")}
        # keep the diagnostics for failed runs — a crashed combo from a
        # scarce healthy-chip window must stay debuggable.  A cached replay
        # carries its live failure under live_error (bench.py _emit_failure)
        if r.get("error") or r.get("live_error"):
            for k in ("rc", "stderr", "phase", "detail", "live_error",
                      "live_phase", "live_detail"):
                if r.get(k) is not None:
                    row[k] = r[k]
        results[combo] = row
        print(f"[sweep] {combo}: {row}", file=sys.stderr, flush=True)
        # only a true wedge signal stops the sweep; a combo-specific
        # compile/steps/sweep timeout (e.g. an oversized batch) moves on so
        # the remaining combos still use the healthy window.  Cached
        # replays count: the chip is just as wedged, and each further combo
        # would burn the full init-retry budget to replay its cache.
        wedges = ("backend_unavailable_timeout", "backend_unavailable")
        if r.get("error") in wedges or r.get("live_error") in wedges:
            print(f"[sweep] backend wedged "
                  f"({r.get('error') or r.get('live_error')}) — stopping "
                  "sweep", file=sys.stderr)
            break
        # a wedge can also land AFTER backend init (the r4 window died in
        # a build phase): any timeout failure triggers a cheap liveness
        # probe, and a dead probe stops the sweep instead of burning every
        # remaining combo's full deadline budget against a wedged chip
        err = (r.get("error") or r.get("live_error") or "")
        if err.endswith("_timeout") and not _chip_alive():
            print(f"[sweep] liveness probe failed after {combo} ({err}) — "
                  "chip wedged mid-window, stopping sweep", file=sys.stderr)
            results[combo]["wedge_probe"] = "dead"
            break
    print(json.dumps({"sweep": results}), flush=True)
    # a cached replay over a live failure is NOT a measurement: rc 4
    # (mirrors bench.py's PADDLE_TPU_BENCH_STRICT_RC contract) so
    # healthy_window.sh's rc log cannot mistake a wedged-chip sweep for a
    # live one
    live_ok = sum(1 for r in results.values()
                  if r.get("value") is not None and not r.get("error")
                  and not r.get("live_error")
                  and not r.get("skipped_fresh"))
    replays = sum(1 for r in results.values() if r.get("live_error"))
    skipped = sum(1 for r in results.values() if r.get("skipped_fresh"))
    if live_ok:
        return 0
    if replays:
        # a skipped-fresh prefix must not hide that THIS window wedged
        return 4
    if skipped and skipped == len(results):
        # nothing to do: every combo already measured live at this revision
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
