"""xprof post-processor: attribute step time from a jax.profiler trace.

The MFU tuning loop needs to know WHERE a step's time goes before a chip
window opens (round-3 verdict: pre-stage the analysis so the window is
measure-only).  This reads the chrome-trace half of a profile directory
written by `jax.profiler.start_trace` (bench.py's BENCH_PROFILE_DIR /
bench_sweep's BENCH_PROFILE_BASE) — stdlib-only, no tensorboard needed —
and reports, per device track:

  - busy vs idle time over the traced span (MXU starvation shows as idle)
  - time by category: matmul/conv (MXU), fusion (VPU/elementwise),
    copy/layout, collective (ICI/DCN), infeed/outfeed + host transfer,
    scan/control, other
  - top ops by total duration (the concrete fusion names to chase in a
    real xprof UI)

Usage:
  python -m paddle_tpu.scripts.xprof_report PROFILE_DIR [--top N] [--json]
PROFILE_DIR may be a bench profile dir (contains plugins/profile/<run>/),
a run dir itself, or a BENCH_PROFILE_BASE parent of per-combo dirs —
every run found is reported.
"""

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys

# category -> regex over the XLA op/event name (first match wins)
_CATEGORIES = [
    # custom-call first: Pallas kernels lower to it, and the fused-vs-scan
    # trace comparison needs them in their OWN bucket, not scan_control
    ("custom_kernel", re.compile(r"custom-call", re.I)),
    # "convolution" not "conv": the substring would swallow "convert"
    # (dtype casts), inflating the MXU bucket exactly when benching bf16
    ("matmul_conv", re.compile(
        r"dot|convolution|einsum|gemm|mxu", re.I)),
    ("collective", re.compile(
        r"all-reduce|all-gather|reduce-scatter|collective|ppermute|"
        r"all-to-all|send|recv", re.I)),
    ("infeed_host", re.compile(
        r"infeed|outfeed|transfer|h2d|d2h|host", re.I)),
    ("copy_layout", re.compile(
        r"copy|transpose|reshape|bitcast|pad|slice|concatenate", re.I)),
    ("scan_control", re.compile(
        r"while|conditional|\bbody\b|\bcall\b|tuple|scan", re.I)),
    ("fusion_elementwise", re.compile(
        r"fusion|add|multiply|tanh|exp|log|select|compare|reduce|rng|"
        r"broadcast|iota|convert", re.I)),
]

# host-side blocking waits: excluded from the op categories (they nest
# over real op events) but totted up separately — a large host_wait_us is
# the H2D-serialization signal docs/perf.md's tuning table points at
_WAIT = re.compile(
    r"Await|block_until_ready|try_to_block|wait for", re.I)

# host-runtime bookkeeping events that would double-count over the real op
# events nested under them (or alongside them on the same track).
# TfrtCpu* is the newer jax CPU runtime's name for the same executor
# events PjRtCpu* used to carry (TfrtCpuExecutable::Execute nests over
# every real op of the launch — counting it drowned the categories in
# "other" and broke the matmul-attribution assertion on newer jax).
_SKIP = re.compile(
    r"PjitFunction|ExecuteHelper|PjRtCpu|TfrtCpu|ParseArguments|"
    r"CollectGarbage|Handle inputs|holds|ThreadpoolListener|"
    r"CreateOutputs|TransferTo|BufferFromHost|^end: |^Thread |^run_|"
    # python frames ($file:line fn) and executor bookkeeping nest OVER the
    # real op events — counting both would double-book the time and drown
    # the categories in "other"
    r"^\$|ThunkExecutor|toarray",
    re.I)


def categorize(name):
    for cat, rx in _CATEGORIES:
        if rx.search(name):
            return cat
    return "other"


def find_runs(path):
    """Yield every plugins/profile/<run> dir under `path` (which may be the
    run dir itself, a profile dir, or a parent of per-combo profile dirs)."""
    if glob.glob(os.path.join(path, "*.trace.json.gz")):
        return [path]
    runs = sorted(glob.glob(
        os.path.join(path, "**", "plugins", "profile", "*"),
        recursive=True))
    return [r for r in runs if os.path.isdir(r)]


def load_events(run_dir):
    """All chrome-trace events of every host in the run, plus pid->track
    names."""
    events, tracks = [], {}
    for fn in sorted(glob.glob(os.path.join(run_dir, "*.trace.json.gz"))):
        with gzip.open(fn, "rt") as f:
            data = json.load(f)
        for e in data.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                tracks[e["pid"]] = e["args"]["name"]
            elif e.get("ph") == "X" and e.get("dur") is not None:
                events.append(e)
    return events, tracks


def _merged_busy_us(spans):
    """Total covered time of possibly-overlapping [start, end) spans."""
    busy = 0.0
    last_end = None
    for s, e in sorted(spans):
        if last_end is None or s >= last_end:
            busy += e - s
            last_end = e
        elif e > last_end:
            busy += e - last_end
            last_end = e
    return busy


def report_run(run_dir, top=8):
    events, tracks = load_events(run_dir)
    per_track = collections.defaultdict(list)
    wait_us = collections.Counter()
    for e in events:
        name = e.get("name", "")
        if _WAIT.search(name):
            wait_us[e["pid"]] += e["dur"]
            continue
        if _SKIP.search(name):
            continue
        per_track[e["pid"]].append(e)

    out = {"run": run_dir, "tracks": {}}
    for pid, evs in sorted(per_track.items()):
        tname = tracks.get(pid, str(pid))
        spans = [(e["ts"], e["ts"] + e["dur"]) for e in evs]
        t0 = min(s for s, _ in spans)
        t1 = max(e for _, e in spans)
        wall = t1 - t0
        busy = _merged_busy_us(spans)
        by_cat = collections.Counter()
        by_op = collections.Counter()
        for e in evs:
            by_cat[categorize(e["name"])] += e["dur"]
            by_op[e["name"]] += e["dur"]
        out["tracks"][tname] = {
            "wall_us": round(wall, 1),
            "busy_us": round(busy, 1),
            "host_wait_us": round(wait_us.get(pid, 0.0), 1),
            "idle_pct": round(100.0 * max(wall - busy, 0.0)
                              / max(wall, 1e-9), 1),
            "by_category_us": {k: round(v, 1)
                               for k, v in by_cat.most_common()},
            "top_ops_us": {k: round(v, 1)
                           for k, v in by_op.most_common(top)},
        }
    return out


def render(rep):
    lines = [f"== {rep['run']}"]
    for tname, t in rep["tracks"].items():
        lines.append(f"  track {tname}: wall {t['wall_us'] / 1e3:.2f} ms, "
                     f"busy {t['busy_us'] / 1e3:.2f} ms, "
                     f"idle {t['idle_pct']}%, "
                     f"host waits {t.get('host_wait_us', 0) / 1e3:.2f} ms")
        total = sum(t["by_category_us"].values()) or 1.0
        for cat, us in t["by_category_us"].items():
            lines.append(f"    {cat:<20} {us / 1e3:9.2f} ms "
                         f"({100.0 * us / total:5.1f}%)")
        lines.append("    top ops:")
        for op, us in t["top_ops_us"].items():
            lines.append(f"      {us / 1e3:9.2f} ms  {op[:70]}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("profile_dir")
    ap.add_argument("--top", type=int, default=8)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (one JSON object)")
    ap.add_argument("--write", metavar="BASE",
                    help="write BASE.txt and BASE.json in one pass "
                         "(parse each trace once) instead of printing")
    args = ap.parse_args(argv)
    runs = find_runs(args.profile_dir)
    if not runs:
        print(f"no profile runs under {args.profile_dir}", file=sys.stderr)
        return 2
    reports = [report_run(r, args.top) for r in runs]
    if args.write:
        with open(args.write + ".json", "w") as f:
            json.dump({"reports": reports}, f)
        with open(args.write + ".txt", "w") as f:
            f.write("\n".join(render(r) for r in reports) + "\n")
        print(f"wrote {args.write}.txt + .json ({len(reports)} runs)")
    elif args.json:
        print(json.dumps({"reports": reports}))
    else:
        for r in reports:
            print(render(r))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # | head is a normal way to use this
        sys.exit(0)
