#!/usr/bin/env bash
# Healthy-window playbook: the moment the TPU chip answers, run everything
# the round-2 verdict wants hardware evidence for, in priority order, and
# leave committed artifacts behind.  Each phase is independently resumable;
# a re-wedge mid-run keeps whatever already landed.
#
#   bash paddle_tpu/scripts/healthy_window.sh [artifacts_dir]
#
# Dry-run mode (round-6; tests/test_healthy_window.py):
#   HW_DRYRUN=1 bash paddle_tpu/scripts/healthy_window.sh [artifacts_dir]
# executes every phase end-to-end on the CPU backend with smoke-scale
# arguments and short timeouts, so the harness itself (paths, rcs, env
# plumbing, resume markers) is debugged with ZERO chip-window minutes.
# Dry runs never touch bench_cache.json (BENCH_NO_CACHE) nor the
# committed analytic snapshot.
#
# Phases:
#  1. bench.py --smoke-kernels          (Mosaic compile canary, ~minutes)
#  2. bench_sweep                       (BASELINE rows + scaling column ->
#                                        bench_cache.json)
#  3. tpu_diff TPU dump + differential  (CPU-vs-TPU numerics evidence)
#  4. nmt_scale                         (verbatim-config NMT row + golden)
#  5. perf_report render
#  6. analytic snapshot refresh         (chip-INDEPENDENT cost/roofline —
#                                        last so it burns no window time)
#  7. serving runtime smoke             (dynamic batcher + HTTP front-end
#                                        self-test on an ephemeral port)
#  8. generation serving smoke          (continuous-batching decode engine:
#                                        concurrent staggered /v1/generate,
#                                        streaming, EOS early-finish)
#  9. chaos smoke                       (resilience layer: server under an
#                                        injected decode-step fault, slot
#                                        re-prefill recovery bit-identical;
#                                        kill-9 trainer + resume)
# 10. fleet smoke                       (replicated serving tier: 2 replica
#                                        subprocesses behind the router,
#                                        kill-9 one mid-stream, streams
#                                        bit-identical via cross-replica
#                                        failover; supervisor restarts it)
# 11. paged KV smoke                    (paged block-pool KV cache: two
#                                        clients sharing a long system
#                                        prompt + one divergent -> prefix
#                                        hits + CoW fork recorded, streams
#                                        bit-identical to the slab twin)
# 12. trace smoke                       (end-to-end request tracing: 2
#                                        traced replicas behind the router,
#                                        kill -9 one mid-stream -> a single
#                                        trace_id stitches router + both
#                                        replicas; Chrome dump parses)
# 13. fused decode-kernel smoke         (pallas_decode generation drive,
#                                        slab + paged kernels compiled in:
#                                        streams bit-identical to the
#                                        reference-path twin, 0 retraces)
# 14. autoscale smoke                    (SLO-holding control plane: 1
#                                        replica + seeded load spike ->
#                                        scale-out to 2 and p99 TTFT back
#                                        under target, spike ends ->
#                                        rolling scale-in; zero failed
#                                        requests)
# 15. chunked-prefill smoke              (unified step vs legacy ladder:
#                                        long prompt chunked mid-decode,
#                                        in-flight streams keep emitting)
# 16. quantized serving smoke            (int8-KV paged engine within the
#                                        committed quality budget vs the
#                                        fp32 twin, int8+weights exact vs
#                                        the quantized oracle, blocks
#                                        doubled at equal bytes)
# 17. static invariant gate              (python -m paddle_tpu.analysis:
#                                        jit-purity + retrace-hazard +
#                                        lock-order passes vs the
#                                        committed baseline — findings
#                                        FAIL the window, no chip time
#                                        needed)
# 18. speculative serving smoke          (draft-ahead decode engine vs
#                                        its non-spec twin: streams
#                                        bit-identical, acceptance-rate
#                                        evidence in /metrics, zero
#                                        retraces — one JSON line)
# 19. sharded serving smoke              (tensor-parallel decode on an
#                                        n=2 forced host mesh vs the
#                                        single-chip twin: staggered
#                                        concurrent streams
#                                        bit-identical, mesh_shards on
#                                        /metrics, zero retraces — one
#                                        JSON line)
# 20. hierarchical KV smoke              (host-RAM spill tier: churn
#                                        evicts a long shared-prefix
#                                        chain, the returning prompt
#                                        restore-hits with zero chunk
#                                        lanes, bit-identical to the
#                                        tier-less twin, spill/restore
#                                        evidence on /metrics — one
#                                        JSON line)
# 21. disaggregated serving smoke        (prefill+decode replica pools
#                                        behind the router: streams
#                                        prefill on one pool, hand the
#                                        KV chain off over a real
#                                        socket at first token, decode
#                                        on the other — bit-identical
#                                        to the oracle, kill -9 of the
#                                        prefill replica mid-handoff
#                                        falls back to recompute,
#                                        kv_handoff counters on every
#                                        /metrics — one JSON line)
# 22. quantized prefill + int8 trainer   (int8 flash prefill within the
#                                        committed logit budget vs the
#                                        fp32 twin, cache matching Tp
#                                        sequential steps; 3-step int8
#                                        weight-streaming trainer loss
#                                        parity vs its f32 twin — one
#                                        JSON line)
set -u
# make bench.py's exit code distinguish cached-replay-over-failure (rc 4)
# from a live measurement, so the rc=$? logs below mean what they say
export PADDLE_TPU_BENCH_STRICT_RC=1
# windows are short and wedge-prone: when the watcher relaunches this
# script, combos already measured live at this revision within a day are
# not re-paid (bench_sweep skip-fresh)
export BENCH_SWEEP_SKIP_FRESH_S="${BENCH_SWEEP_SKIP_FRESH_S:-86400}"

DRY="${HW_DRYRUN:-0}"
if [ "$DRY" = "1" ]; then
    # smoke-scale everything: cpu backend, 2 timed steps, tiny model/
    # stream shapes, one small tpu_diff case, 200-word NMT; no cache
    # reads OR writes (a cpu dry run must neither replay committed TPU
    # rows as success nor dirty them)
    export BENCH_PLATFORM=cpu JAX_PLATFORMS=cpu
    export BENCH_STEPS=2 BENCH_SERVING_TINY=1 BENCH_NO_CACHE=1
    export BENCH_SWEEP_SKIP_FRESH_S=0
    T_SMOKE=900; T_SWEEP=900; T_COL=600; T_DIFF=600; T_NMT=600
    SWEEP_ARGS=(--combos "smallnet:8,trainer_prefetch:8" --steps 2)
    SCAN_ARGS=(--combos "smallnet:8" --steps 2)
    BF16_ARGS=(--combos "smallnet:8" --steps 2)
    INT8_ARGS=(--combos "transformer_serving:4" --steps 2)
    DIFF_CASES="embedding"
    NMT_ARGS=(--vocab 200 --steps 4 --gen-sents 4 --beam 2 --max-gen-len 20)
    ANALYTIC_FAMILIES="smallnet,trainer_prefetch,serving,serving_generate"
    T_SERVE=600
else
    T_SMOKE=1200; T_SWEEP=14400; T_COL=3600; T_DIFF=7200; T_NMT=7200
    SWEEP_ARGS=()
    SCAN_ARGS=(--combos "lstm:64,lstm256:64,lstm1280:64,seq2seq:64")
    BF16_ARGS=(--combos "resnet50:256,transformer:128,lstm:64,googlenet:256")
    INT8_ARGS=(--combos "transformer_decode:32,transformer_serving:16")
    DIFF_CASES=""
    NMT_ARGS=(--vocab 30000 --steps 300 --gen-sents 32 --beam 5
              --max-gen-len 50)
    ANALYTIC_FAMILIES=""
    T_SERVE=600
fi

# every bench.py combo is a fresh subprocess; a shared persistent XLA
# compile cache means only the FIRST run of each program pays the
# tunnel-slow compile (the r4 window lost its first combo to exactly
# that).  Cache lives outside the tree; harmless if the backend skips it.
# NOT exported yet — phase 1's Mosaic canary must really compile (a cache
# hit would mask exactly the lowering regression it exists to catch), so
# the export happens between phase 1 and phase 2 below.
_JAX_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_compile_cache}"
_JAX_CACHE_MIN="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-1}"
unset JAX_COMPILATION_CACHE_DIR JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS
# an explicit dir resolves against the CALLER's cwd; the default stays
# repo-root-relative (resolved after the cd below)
if [ $# -ge 1 ]; then ART=$(realpath -m "$1"); else ART=""; fi
cd "$(dirname "$0")/../.."
ART="${ART:-$PWD/artifacts/r6}"
mkdir -p "$ART"
log() { echo "[healthy_window $(date -u +%H:%M:%S)] $*" >&2; }
[ "$DRY" = "1" ] && log "DRY RUN: cpu backend, smoke-scale arguments"

log "phase 1: pallas kernel smoke"
timeout "$T_SMOKE" python bench.py --smoke-kernels \
    > "$ART/smoke_kernels.json" 2> "$ART/smoke_kernels.log"
log "smoke rc=$? -> $ART/smoke_kernels.json"

# canary done — from here on, compiles may replay from the shared cache
export JAX_COMPILATION_CACHE_DIR="$_JAX_CACHE_DIR"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="$_JAX_CACHE_MIN"

log "phase 2: bench sweep (BASELINE + scaling; per-combo xprof traces)"
BENCH_PROFILE_BASE="$ART/xprof" timeout "$T_SWEEP" \
    python -m paddle_tpu.scripts.bench_sweep "${SWEEP_ARGS[@]}" \
    > "$ART/bench_sweep.json" 2> "$ART/bench_sweep.log"
log "sweep rc=$? (bench_cache.json updated)"
python -m paddle_tpu.scripts.xprof_report "$ART/xprof" \
    --write "$ART/xprof_report" 2> "$ART/xprof_report.log"
log "xprof attribution rc=$? -> $ART/xprof_report.{txt,json}"

log "phase 2b: scan baselines for the fused-kernel vs-scan column"
PADDLE_TPU_FUSED_RNN=0 BENCH_PROFILE_BASE="$ART/xprof_scan" \
    timeout "$T_COL" python -m paddle_tpu.scripts.bench_sweep \
    "${SCAN_ARGS[@]}" \
    > "$ART/bench_scan_baselines.json" 2> "$ART/bench_scan_baselines.log"
log "scan baselines rc=$? (cached under model@scan)"
python -m paddle_tpu.scripts.xprof_report "$ART/xprof_scan" \
    --write "$ART/xprof_scan_report" 2>> "$ART/xprof_report.log"
log "scan-trace attribution rc=$? (fused-vs-scan comparison inputs ready)"

log "phase 2c: bf16 column for the MFU-critical families"
BENCH_DTYPE=bfloat16 BENCH_PROFILE_BASE="$ART/xprof_bf16" \
    timeout "$T_COL" python -m paddle_tpu.scripts.bench_sweep \
    "${BF16_ARGS[@]}" \
    > "$ART/bench_bf16.json" 2> "$ART/bench_bf16.log"
log "bf16 sweep rc=$? (cached under model@bsN@bfloat16)"
python -m paddle_tpu.scripts.xprof_report "$ART/xprof_bf16" \
    --write "$ART/xprof_bf16_report" 2>> "$ART/xprof_report.log"
log "bf16-trace attribution rc=$?"

log "phase 2d: int8 weight-only serving column (vs the bf16/f32 rows)"
BENCH_QUANT=int8 timeout "$T_COL" python -m paddle_tpu.scripts.bench_sweep \
    "${INT8_ARGS[@]}" \
    > "$ART/bench_int8.json" 2> "$ART/bench_int8.log"
log "int8 sweep rc=$? (cached under model@int8)"

log "phase 3: TPU differential dump + compare"
# resumable per-case dumps; 'default' platform = the axon-routed TPU.
# Retry error/timeout records from earlier partial windows — a wedge
# mid-group leaves TimeoutExpired records for its missing sub-cases
export TPU_DIFF_RETRY_ERRORS=1
timeout "$T_DIFF" python -m paddle_tpu.testing.tpu_diff default \
    "$ART/diff_tpu.npz" $DIFF_CASES 2> "$ART/diff_tpu.log"
log "tpu dump rc=$?"
JAX_PLATFORMS=cpu timeout "$T_COL" python -m paddle_tpu.testing.tpu_diff \
    cpu "$ART/diff_cpu.npz" $DIFF_CASES 2> "$ART/diff_cpu.log"
log "cpu dump rc=$?"
PADDLE_TPU_DIFF="$ART/diff_cpu.npz:$ART/diff_tpu.npz" \
    python -m pytest tests/test_tpu_differential.py -q \
    > "$ART/tpu_differential_pytest.log" 2>&1
log "differential pytest rc=$? -> $ART/tpu_differential_pytest.log"

log "phase 4: reference-scale NMT (verbatim configs, 30k vocab)"
timeout "$T_NMT" python -m paddle_tpu.scripts.nmt_scale \
    --out-dir "$ART/nmt" "${NMT_ARGS[@]}" \
    > "$ART/nmt_scale.json" 2> "$ART/nmt_scale.log"
log "nmt rc=$? -> $ART/nmt_scale.json"

log "phase 5: render the perf report from the refreshed cache"
python -m paddle_tpu.scripts.perf_report > "$ART/perf_report.md" \
    2>> "$ART/perf_report.log" \
    && log "perf report -> $ART/perf_report.md" \
    || log "perf_report rc=$? (see $ART/perf_report.log)"

log "phase 6: analytic cost/roofline snapshot (chip-independent, cpu)"
# the dry run writes into ART (never the committed round snapshot); the
# real window refreshes BENCH_ANALYTIC_r06.json at the repo root AFTER
# the chip phases, so the snapshot never competes for window minutes
if [ "$DRY" = "1" ]; then
    timeout "$T_SWEEP" python bench.py --analytic \
        --families "$ANALYTIC_FAMILIES" --out "$ART/analytic_snapshot.json" \
        > "$ART/analytic.json" 2> "$ART/analytic.log"
else
    timeout 7200 python bench.py --analytic \
        > "$ART/analytic.json" 2> "$ART/analytic.log"
fi
log "analytic rc=$? -> $ART/analytic.json"

log "phase 7: serving runtime smoke (dynamic batcher + HTTP front-end)"
# self-contained: ephemeral port, concurrent requests, a malformed
# request, /healthz + /metrics sanity — one JSON line, nonzero rc on any
# failed check (serving/server.py --smoke)
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke \
    > "$ART/serving_smoke.json" 2> "$ART/serving_smoke.log"
log "serving smoke rc=$? -> $ART/serving_smoke.json"

log "phase 8: generation serving smoke (continuous-batching decode engine)"
# concurrent STAGGERED /v1/generate requests (admissions land mid-decode,
# slots churn), one streaming request, EOS early-finish — one JSON line,
# nonzero rc on any failed check (serving/server.py --smoke-generate)
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-generate \
    > "$ART/serving_gen_smoke.json" 2> "$ART/serving_gen_smoke.log"
log "generation smoke rc=$? -> $ART/serving_gen_smoke.json"

log "phase 9: chaos smoke (fault injection + supervised recovery)"
# serving under an injected decode-step fault (recovered streams must be
# bit-identical to the clean run) + kill-9 trainer resume at smoke scale
# — one JSON line, nonzero rc on any failed check
# (python -m paddle_tpu.resilience --smoke; docs/serving.md §6)
timeout "$T_SERVE" python -m paddle_tpu.resilience --smoke \
    > "$ART/chaos_smoke.json" 2> "$ART/chaos_smoke.log"
log "chaos smoke rc=$? -> $ART/chaos_smoke.json"

log "phase 10: fleet smoke (replica supervisor + health-checked router)"
# 2 tiny replica subprocesses on ephemeral ports behind the router;
# concurrent streaming /v1/generate clients; kill -9 one replica
# MID-STREAM — every stream must finish bit-identical to lm_generate via
# the router's cross-replica continuation failover, /metrics must show
# it, and the supervisor must restart the victim to readiness — one JSON
# line (python -m paddle_tpu.serving.router --smoke; docs/serving.md §7)
timeout "$T_SERVE" python -m paddle_tpu.serving.router --smoke \
    > "$ART/fleet_smoke.json" 2> "$ART/fleet_smoke.log"
log "fleet smoke rc=$? -> $ART/fleet_smoke.json"

log "phase 11: paged KV smoke (block pool + prefix sharing + CoW)"
# kv_layout=paged demo server: one leader client registers a long
# system-prompt chain, an exact-duplicate client must hit + CoW-fork,
# a divergent client must hit the shared prefix — every stream
# bit-identical to the same prompts through a slab-layout twin — one
# JSON line (python -m paddle_tpu.serving --smoke-paged;
# docs/serving.md §5)
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-paged \
    > "$ART/paged_smoke.json" 2> "$ART/paged_smoke.log"
log "paged smoke rc=$? -> $ART/paged_smoke.json"

log "phase 12: trace smoke (end-to-end request tracing across the fleet)"
# 2 tracing-enabled replicas behind the router, concurrent paced streams,
# kill -9 one replica mid-stream: ONE trace_id must stitch router -> the
# dead replica (pre-kill /debug/traces snapshot) -> the continuation on
# the survivor, and the merged Chrome trace-event dump must parse with
# all three process names — one JSON line
# (python -m paddle_tpu.obs --smoke; docs/observability.md)
timeout "$T_SERVE" python -m paddle_tpu.obs --smoke \
    --chrome-out "$ART/trace_chrome.json" \
    > "$ART/trace_smoke.json" 2> "$ART/trace_smoke.log"
log "trace smoke rc=$? -> $ART/trace_smoke.json"

log "phase 13: fused decode-kernel smoke (pallas_decode vs reference twin)"
# the demo generation drive with the Pallas decode-attention kernels
# compiled into the slab AND paged steps (interpret mode on CPU, Mosaic
# on TPU): staggered streams must come back bit-identical to a
# reference-path twin engine with 0 retraces — one JSON line
# (python -m paddle_tpu.serving --smoke-decode-fused; docs/perf.md
# "Fused decode kernels")
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-decode-fused \
    > "$ART/decode_fused_smoke.json" 2> "$ART/decode_fused_smoke.log"
log "decode-fused smoke rc=$? -> $ART/decode_fused_smoke.json"

log "phase 14: autoscale smoke (SLO-holding control plane)"
# 1 tiny replica + router + autoscaler (min 1, max 2): a seeded load
# spike breaches the TTFT target -> the control loop scales out to 2
# (spawn-to-readiness), a post-scale steady drive sits back under
# target, the spike ends -> sustained slack scales back in through the
# rolling drain — ZERO failed requests, every completed stream
# bit-identical to lm_generate — one JSON line
# (python -m paddle_tpu.serving.autoscaler --smoke; docs/serving.md §8)
timeout "$T_SERVE" python -m paddle_tpu.serving.autoscaler --smoke \
    > "$ART/autoscale_smoke.json" 2> "$ART/autoscale_smoke.log"
log "autoscale smoke rc=$? -> $ART/autoscale_smoke.json"

log "phase 15: chunked-prefill smoke (unified step vs legacy ladder)"
# prompt ingestion folded into the ONE jitted decode step: a long prompt
# admitted MID-DECODE must chunk through the shared step while the
# in-flight stream keeps emitting (interleaved tokens >= 1), and every
# stream must be bit-identical to the legacy-ladder twin — one JSON line
# (python -m paddle_tpu.serving --smoke-chunked; docs/serving.md
# "Chunked prefill")
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-chunked \
    > "$ART/chunked_smoke.json" 2> "$ART/chunked_smoke.log"
log "chunked smoke rc=$? -> $ART/chunked_smoke.json"

log "phase 16: quantized serving smoke (int8 KV + int8 weights)"
# int8-KV paged engine (kv_num_blocks auto-DOUBLED at the slab-
# equivalent byte budget) vs a fp32 twin: every HTTP stream inside the
# committed quality budget, the int8-KV+weights engine token-EXACT vs
# the quantized lm_generate oracle, /metrics showing kv_blocks_total
# doubled at equal bytes + kv_cache_int8 1 — one JSON line
# (python -m paddle_tpu.serving --smoke-quant; docs/serving.md
# "Quantized serving")
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-quant \
    > "$ART/quant_smoke.json" 2> "$ART/quant_smoke.log"
log "quant smoke rc=$? -> $ART/quant_smoke.json"

log "phase 17: static invariant gate (jit-purity / retrace / lock-order)"
# chip-independent AST gate (docs/analysis.md): every finding must be
# either fixed or baselined with a reason — a NEW finding fails the
# whole window (rc propagated, WINDOW_DONE withheld) because a step
# that retraces or deadlocks would poison every phase above on the
# next revision.  Same command in dry-run and real windows: the
# analyzer never touches a chip.
timeout "$T_SERVE" python -m paddle_tpu.analysis --check all --json \
    > "$ART/analysis_gate.json" 2> "$ART/analysis_gate.log"
ANALYSIS_RC=$?
log "analysis gate rc=$ANALYSIS_RC -> $ART/analysis_gate.json"
if [ "$ANALYSIS_RC" != 0 ]; then
    log "STATIC INVARIANT GATE FAILED — fix or baseline the findings in"
    log "$ART/analysis_gate.json before trusting this window"
    exit "$ANALYSIS_RC"
fi

log "phase 18: speculative serving smoke (draft-ahead vs non-spec twin)"
# greedy speculative decoding on the slot engine: a k-lane draft rollout
# feeds the ONE chunked verify step; every stream must be bit-identical
# to the non-speculating twin regardless of draft quality, acceptance
# evidence (drafted/accepted counters, acceptance rate, tokens/step)
# must render on /metrics, and both engines must hold at 1 warm-up
# trace / 0 retraces — one JSON line
# (python -m paddle_tpu.serving --smoke-speculative; docs/serving.md
# "Speculative decoding")
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-speculative \
    > "$ART/spec_smoke.json" 2> "$ART/spec_smoke.log"
log "speculative smoke rc=$? -> $ART/spec_smoke.json"

log "phase 19: sharded serving smoke (n=2 host mesh vs single-chip twin)"
# tensor-parallel sharded decode: the ONE chunked step under a 2-chip
# model-axis mesh (head-striped attention + KV pool, vocab-striped
# embedding, speculation riding along) — the probe re-execs itself with
# XLA_FLAGS=--xla_force_host_platform_device_count=2 on a single-device
# machine, drives staggered concurrent clients, and every stream must be
# bit-identical to the single-chip twin at 1 warm-up trace / 0 retraces,
# with the mesh_shards gauge rendered on /metrics — one JSON line
# (python -m paddle_tpu.serving --smoke-sharded; docs/serving.md
# "Sharded decode")
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-sharded \
    > "$ART/sharded_smoke.json" 2> "$ART/sharded_smoke.log"
log "sharded smoke rc=$? -> $ART/sharded_smoke.json"

log "phase 20: hierarchical KV smoke (host spill tier + async restore)"
# tiny paged pool + host-RAM spill tier: churn traffic forces the pool
# to evict (and spill) a long block-aligned system-prompt chain, then
# the prompt RETURNS — the engine must restore-hit from the host tier
# and seat by reference with ZERO prefill chunk lanes, the stream
# bit-identical both to its first serving and to a tier-less twin's
# cold recompute, spill/restore counters + the host_tier_bytes gauge
# on /metrics, 1 warm-up trace — one JSON line
# (python -m paddle_tpu.serving --smoke-spill; docs/serving.md
# "Hierarchical KV")
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-spill \
    > "$ART/spill_smoke.json" 2> "$ART/spill_smoke.log"
log "spill smoke rc=$? -> $ART/spill_smoke.json"

log "phase 21: disaggregated serving smoke (prefill/decode KV handoff)"
# a 2-replica fleet split into a prefill pool and a decode pool behind
# the router: new prompts prefill on one replica, the KV chain crosses
# to the other as a trunk-signed wire blob over POST /v1/kv/export at
# first token, and the decode replica seats it through the existing
# restore pipeline (zero chunk lanes, zero new traces) — streams
# bit-identical to the single-replica oracle, a sub-crossover prompt
# proves the analytic recompute direction, kill -9 of the prefill
# replica mid-handoff falls back to continuation-replay recompute
# bit-identically, kv_handoff counters on both replicas' AND the
# router's /metrics — one JSON line
# (python -m paddle_tpu.serving.router --smoke-disagg; docs/serving.md
# "Disaggregated serving")
timeout "$T_SERVE" python -m paddle_tpu.serving.router --smoke-disagg \
    > "$ART/disagg_smoke.json" 2> "$ART/disagg_smoke.log"
log "disagg smoke rc=$? -> $ART/disagg_smoke.json"

log "phase 22: quantized prefill + int8 trainer smoke (end-to-end low precision)"
# the int8 flash prefill (pallas_prefill_quant forced ON — interpret
# mode off-TPU, the real kernel on-chip) against the fp32 prefill twin
# under the committed logit budget, its int8 cache matching Tp
# sequential decode steps; then 3 steps of the int8 weight-streaming
# trainer (SGD(quant_weights=True)) tracking the f32 twin within
# TRAIN_LOSS_BUDGET — one JSON line
# (python -m paddle_tpu.serving --smoke-quant-prefill; docs/perf.md
# "Int8 flash prefill" / "Int8 weight-streaming trainer")
timeout "$T_SERVE" python -m paddle_tpu.serving --smoke-quant-prefill \
    > "$ART/quant_prefill_smoke.json" 2> "$ART/quant_prefill_smoke.log"
log "quant-prefill smoke rc=$? -> $ART/quant_prefill_smoke.json"

cat > "$ART/WINDOW_DONE" <<EOF2
window completed $(date -u +%Y%m%dT%H%M%SZ) at revision $(git rev-parse --short HEAD 2>/dev/null || echo unknown) (dryrun=$DRY)
bench_cache.json now holds the live rows; README's headline caveat and
docs/perf.md's cached tables should be refreshed from perf_report.md.
EOF2

log "done at $(date -u +%Y%m%dT%H%M%SZ); artifacts in $ART — review, update docs/perf.md, commit"
