"""Reference-scale NMT run: VERBATIM seqToseq configs at real vocab.

The round-2 verdict asked for the reference workflow at reference scale
(30k dicts, demo/seqToseq/translation/{train,gen}.conf executed unchanged):
train a few hundred batches, then beam-decode with the gen config sharing
the trained parameters, recording train ms/batch, decode tokens/sec and a
golden output file.  The reference itself never shipped an NMT benchmark
row (benchmark/README.md:141 "will be added later") — this creates one.

Synthetic parallel corpus (deterministic): target = reversed source with a
fixed token shift, the standard learnable seq2seq toy task, over the full
vocab so the 30k embeddings/softmax run at real shapes.

Usage:
  python -m paddle_tpu.scripts.nmt_scale --out-dir OUT \
      [--vocab 30000] [--steps 300] [--gen-sents 32] [--beam 5]
CPU smoke: --vocab 200 --steps 4 --gen-sents 4 --max-gen-len 20
Prints ONE JSON line; writes OUT/golden_decode.txt.
"""

import argparse
import json
import os
import sys
import time


def _write(path, text):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)


def synth_corpus(root, vocab, n_train, n_gen, seed=7):
    """Reference demo/seqToseq data layout: data/pre-wmt14/{src,trg}.dict
    (<s>/<e>/<unk> first), tab-separated parallel text, train/test/gen
    lists.  Deterministic: trg = reversed src, token ids shifted by 7."""
    import numpy as np
    rng = np.random.RandomState(seed)
    words = [f"w{i}" for i in range(vocab - 3)]
    dict_text = "<s>\n<e>\n<unk>\n" + "\n".join(words) + "\n"
    d = os.path.join(root, "data", "pre-wmt14")
    _write(os.path.join(d, "src.dict"), dict_text)
    _write(os.path.join(d, "trg.dict"), dict_text)

    def sent_ids():
        n = int(rng.randint(5, 16))
        return rng.randint(3, vocab, (n,))

    def to_words(ids):
        return " ".join(f"w{i - 3}" for i in ids)

    def trg_of(ids):
        return [(i - 3 + 7) % (vocab - 3) + 3 for i in ids[::-1]]

    lines = []
    for _ in range(n_train):
        s = sent_ids()
        lines.append(f"{to_words(s)}\t{to_words(trg_of(s))}")
    _write(os.path.join(d, "part-00000"), "\n".join(lines) + "\n")
    _write(os.path.join(d, "train.list"), "data/pre-wmt14/part-00000\n")
    _write(os.path.join(d, "test.list"), "data/pre-wmt14/part-00000\n")

    gen_lines = [to_words(sent_ids()) for _ in range(n_gen)]
    _write(os.path.join(d, "gen-part-00000"), "\n".join(gen_lines) + "\n")
    _write(os.path.join(d, "gen.list"), "data/pre-wmt14/gen-part-00000\n")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--vocab", type=int, default=30000)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--gen-sents", type=int, default=32)
    ap.add_argument("--beam", type=int, default=5)
    ap.add_argument("--max-gen-len", type=int, default=50)
    ap.add_argument("--reference",
                    default=os.environ.get("PADDLE_TPU_REFERENCE",
                                           "/root/reference"))
    args = ap.parse_args(argv)

    # honor JAX_PLATFORMS even where a sitecustomize hook pins the
    # jax_platforms CONFIG at interpreter startup (env var alone is not
    # enough); the shared helper applies the full priority list
    from paddle_tpu._platform import honor_jax_platforms_env
    honor_jax_platforms_env()

    import itertools
    import numpy as np

    out_dir = os.path.abspath(args.out_dir)
    os.makedirs(out_dir, exist_ok=True)
    # corpus must exist BEFORE the config parses (the provider reads the
    # dicts at parse time), so size it generously: 128 samples/step covers
    # any batch_size the reference configs use (train.conf: 50)
    synth_corpus(out_dir, args.vocab, n_train=max(args.steps * 128, 500),
                 n_gen=args.gen_sents)
    os.chdir(out_dir)    # reference configs resolve data/ relative to CWD

    from paddle_tpu.compat.config_parser import parse_config, \
        config_to_runtime
    from paddle_tpu.trainer import SGD
    conf_dir = os.path.join(args.reference, "demo/seqToseq/translation")

    # ---- phase 1: train the verbatim train.conf --------------------------
    t0 = time.time()
    parsed = parse_config(os.path.join(conf_dir, "train.conf"), "")
    cfg = config_to_runtime(parsed)
    batch_size = cfg["batch_size"]
    trainer = SGD(cost=cfg["cost"], update_equation=cfg["optimizer"])
    costs, stamps = [], []

    def on_event(e):
        if type(e).__name__ == "EndIteration":
            costs.append(float(e.cost))
            stamps.append(time.perf_counter())
            i = len(costs) - 1
            if i % 50 == 0:
                print(f"[nmt_scale] step {i}: cost={costs[-1]:.4f}",
                      file=sys.stderr, flush=True)

    print(f"[nmt_scale] training verbatim train.conf: vocab={args.vocab} "
          f"batch={batch_size} steps={args.steps}", file=sys.stderr,
          flush=True)
    # one compiled shape for the whole run: sentences are 5..15 words and
    # the reference provider wraps slots with <s>/<e> markers (max slot
    # length 17), so a single 24-bucket + fixed batch pins every padded
    # feed shape with headroom — no per-batch XLA retraces (the mid-scale
    # CPU run showed p99 step time = recompiles without this) and no
    # truncation (bucket_for caps at the last bound)
    from paddle_tpu.data.feeder import DataFeeder
    feeder = DataFeeder(cfg["feeding"], bucket_bounds=[24],
                        pad_batch_to=batch_size) \
        if cfg.get("feeding") else None
    trainer.train(
        lambda: itertools.islice(cfg["train_reader"](), args.steps),
        num_passes=1, feeding=feeder, event_handler=on_event,
        log_period=0)
    first_cost = costs[0] if costs else None
    last_cost = costs[-1] if costs else None
    # end-to-end step times from event timestamps (includes host data prep);
    # drop the first 2 (jit compiles: padded-shape retraces)
    diffs = np.diff(stamps)
    step_times = diffs[2:] if len(diffs) > 4 else diffs
    train_ms = 1e3 * float(np.median(step_times)) if len(step_times) else None
    # tokens/step ~= batch * mean(src+trg length) (lens 5..15 uniform -> 20)
    train_tok_s = (batch_size * 20) / (train_ms / 1e3) if train_ms else None

    # ---- phase 2: beam decode via the verbatim gen.conf ------------------
    gen_parsed = parse_config(os.path.join(conf_dir, "gen.conf"), "")
    from paddle_tpu.layers.graph import Topology
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.sequence import SequenceBatch
    gen_topo = Topology(list(gen_parsed.outputs))
    # the verbatim config fixes beam_size=3 / max_length=250
    # (seqToseq_net.py:71-72); override the generation node's cfg when the
    # caller asks for a different beam (the verdict's beam-5 row)
    for node in gen_topo.order:
        if "beam_size" in node.cfg:
            if args.beam:
                node.cfg["beam_size"] = args.beam
            if args.max_gen_len:
                node.cfg["max_length"] = args.max_gen_len
    # enumerate gen-graph parameter KEYS without materializing 30k-vocab
    # weights on device (init would allocate the real arrays)
    gen_keys = set(jax.eval_shape(
        lambda k: gen_topo.init(k), jax.random.PRNGKey(0)))
    trained = trainer.parameters
    missing = gen_keys - set(trained)
    if missing:
        raise RuntimeError(
            f"gen.conf parameters not produced by train.conf: {missing}")
    gen_params = {k: trained[k] for k in gen_keys}

    src_lines = open("data/pre-wmt14/gen-part-00000").read().splitlines()
    src_ids = [[int(w[1:]) + 3 for w in line.split()] for line in src_lines]
    maxlen = max(len(s) for s in src_ids)
    ids = np.full((len(src_ids), maxlen), 0, np.int32)
    lens = np.zeros((len(src_ids),), np.int32)
    for i, s in enumerate(src_ids):
        ids[i, :len(s)] = s
        lens[i] = len(s)
    feed = {"source_language_word": SequenceBatch(
        data=jnp.asarray(ids), lengths=jnp.asarray(lens))}

    decode = jax.jit(lambda p, f: gen_topo.apply(p, f, mode="test"))
    res = decode(gen_params, feed)     # compile
    jax.block_until_ready(res.tokens)
    t1 = time.perf_counter()
    res = decode(gen_params, feed)
    jax.block_until_ready(res.tokens)
    decode_s = time.perf_counter() - t1

    toks = np.asarray(res.tokens)      # [B, beam, L]
    scores = np.asarray(res.scores)
    out_lens = np.asarray(res.lengths)
    gen_tokens = int(out_lens[:, 0].sum())
    decode_tok_s = gen_tokens / decode_s if decode_s > 0 else None

    golden = os.path.join(out_dir, "golden_decode.txt")
    with open(golden, "w") as f:
        for b in range(toks.shape[0]):
            f.write(f"src: {src_lines[b]}\n")
            for k in range(toks.shape[1]):
                seq = toks[b, k, :out_lens[b, k]].tolist()
                f.write(f"  beam{k} score={scores[b, k]:.4f} "
                        f"ids={seq}\n")

    out = {
        "metric": "seqToseq verbatim-config NMT (train.conf + gen.conf)",
        "vocab": args.vocab, "batch_size": batch_size,
        "steps": len(costs),
        "train_ms_per_batch": round(train_ms, 2) if train_ms else None,
        "train_tokens_per_s": round(train_tok_s) if train_tok_s else None,
        "first_cost": round(first_cost, 4) if first_cost else None,
        "last_cost": round(last_cost, 4) if last_cost else None,
        "beam_size": int(toks.shape[1]),
        "decode_sentences": len(src_ids),
        "decode_tokens_per_s": round(decode_tok_s) if decode_tok_s else None,
        "decode_s": round(decode_s, 3),
        "golden_file": golden,
        "device": str(getattr(jax.devices()[0], "device_kind", "unknown")),
        "total_s": round(time.time() - t0, 1),
    }
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    main()
