"""Multi-host cluster launcher.

Reference: paddle/scripts/cluster_train/paddle.py:101-176 — a fabric/SSH
launcher that started `paddle pserver` on every node then `paddle train
--trainer_id=i --pservers=...`.  The TPU-native launcher has no pserver
role: it starts the SAME training command on every host with the
PADDLE_TPU_* rendezvous env vars (parallel.distributed contract); host 0
is the coordinator.  On Cloud-TPU-style deployments where each host
already knows the pod topology, prefer the platform's own fan-out
(gcloud ... --worker=all / GKE JobSet) and skip this launcher entirely —
jax.distributed autodetects there.

Usage:
  python -m paddle_tpu.scripts.launch_cluster \
      --hosts host1,host2,host3,host4 --port 8476 \
      -- python -m paddle_tpu.trainer.cli train --config conf.py ...

Requires passwordless ssh to each host and the repo available at the same
path everywhere (reference conf.py HOSTS assumption).

`--local N` fans out N ranks as plain subprocesses on THIS machine instead
of ssh — the single-machine bring-up / debugging mode (and what the
multi-process distributed test drives).
"""

import argparse
import os
import shlex
import signal
import subprocess
import sys


def rendezvous_env(coordinator_host, port, world_size, rank):
    return {
        "PADDLE_TPU_COORDINATOR": f"{coordinator_host}:{port}",
        "PADDLE_TPU_NUM_PROCESSES": str(world_size),
        "PADDLE_TPU_PROCESS_ID": str(rank),
    }


def build_ssh_cmd(host, rank, args, command):
    env = rendezvous_env(args.hosts[0], args.port, len(args.hosts), rank)
    env_str = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    remote = f"cd {shlex.quote(args.workdir)} && {env_str} {command}"
    # -tt: force a remote tty so killing the LOCAL ssh client (fail-fast,
    # ^C) delivers SIGHUP to the remote rank — without it the remote
    # python would survive the teardown blocked in a collective, holding
    # the coordinator port (the reference launcher killed jobs over ssh
    # for the same reason, paddle.py:52-60)
    return ["ssh", "-tt", "-o", "BatchMode=yes", host, remote]


def wait_fail_fast(procs, poll_s=0.2):
    """Wait for every rank; if one dies nonzero, SIGTERM the rest and
    return its rc.  Without this, a crashed rank leaves the others blocked
    forever inside a collective (jax.distributed has no dead-peer timeout
    at this layer) and the launcher would never return — the reference
    launcher killed the whole job on any node failure too
    (scripts/cluster_train/paddle.py:52-60)."""
    import time
    while True:
        rcs = [p.poll() for p in procs]
        bad = [rc for rc in rcs if rc not in (None, 0)]
        if bad:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            deadline = time.time() + 10
            for p in procs:
                try:
                    p.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
            return bad[0]
        if all(rc == 0 for rc in rcs):
            return 0
        time.sleep(poll_s)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.launch_cluster",
        usage="%(prog)s --hosts h1,h2 [--port P] [--workdir D] -- command…")
    parser.add_argument("--hosts",
                        help="comma-separated host list; first = coordinator")
    parser.add_argument("--local", type=int, metavar="N",
                        help="run N ranks as local subprocesses (no ssh)")
    parser.add_argument("--port", type=int, default=8476)
    parser.add_argument("--workdir", default=os.getcwd())
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command to run on every host")
    args = parser.parse_args(argv)
    if (args.hosts is None) == (args.local is None):
        parser.error("exactly one of --hosts / --local N is required")
    if args.local is not None and args.local < 1:
        parser.error(f"--local needs a positive rank count, got {args.local}")
    cmd_parts = list(args.command)
    if cmd_parts and cmd_parts[0] == "--":
        cmd_parts = cmd_parts[1:]
    command = " ".join(shlex.quote(c) for c in cmd_parts)
    if not command:
        parser.error("missing training command after --")

    procs = []

    def _terminate(signum, frame):
        # SIGTERM must reap the ranks like ^C does, or a killed launcher
        # orphans every worker (they re-parent and hold the coordinator port)
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait()
        sys.exit(128 + signum)

    prev_sigterm = signal.signal(signal.SIGTERM, _terminate)
    try:
        if args.local:
            for rank in range(args.local):
                env = dict(os.environ)
                env.update(rendezvous_env("127.0.0.1", args.port,
                                          args.local, rank))
                print(f"[launch] local rank {rank}: {command}", flush=True)
                procs.append(subprocess.Popen(
                    cmd_parts, env=env, cwd=args.workdir))
        else:
            args.hosts = [h.strip() for h in args.hosts.split(",")
                          if h.strip()]
            for rank, host in enumerate(args.hosts):
                cmd = build_ssh_cmd(host, rank, args, command)
                print(f"[launch] rank {rank} @ {host}: {command}",
                      flush=True)
                procs.append(subprocess.Popen(cmd))
        return wait_fail_fast(procs)
    except KeyboardInterrupt:
        # reference launcher killed jobs over SSH (paddle.py:52-60)
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait()
        return 130
    finally:
        # don't leak the handler into an embedding process (tests import
        # main() in-process)
        signal.signal(signal.SIGTERM, prev_sigterm)


if __name__ == "__main__":
    sys.exit(main())
