"""Dual-backend differential runner (SURVEY §4 pattern 1).

The reference's strongest numeric tool runs every op on CpuMatrix and
GpuMatrix and compares results within epsilon (math/tests/
test_matrixCompare.cpp, TensorCheck.h).  The TPU-native equivalent: execute
the SAME jitted forward + gradient for every case in the registry-driven
layer sweep (tests/test_layer_grad_sweep.py CASES) on one backend per
process and dump the arrays; a comparing test diffs a CPU dump against a
TPU dump.

Run (one process per platform — the platform must be pinned before any
backend touch, and a sitecustomize hook on dev boxes overrides the env var,
hence the explicit jax.config.update):

    python -m paddle_tpu.testing.tpu_diff cpu /tmp/diff_cpu.npz
    python -m paddle_tpu.testing.tpu_diff tpu /tmp/diff_tpu.npz

Determinism across platforms: param init uses jax.random (threefry —
platform-invariant), case inputs use seeded numpy, and matmul precision is
forced to HIGHEST so the MXU does full-f32 passes instead of bf16x3.
"""

import os
import sys
import zlib


def _pin_platform(platform):
    # "default" = let the environment route (on axon-tunneled boxes that IS
    # the TPU; the plugin's platform name is not "tpu", so an explicit pin
    # would fail to init)
    import jax
    if platform != "default":
        os.environ["JAX_PLATFORMS"] = platform
        try:
            jax.config.update("jax_platforms", platform)
        except Exception:
            pass
    jax.config.update("jax_default_matmul_precision", "highest")


def run_cases(only=None, out_dir=None):
    """Build every sweep case, run forward (mode='test') + grads of the
    scalar loss wrt all float params, return {name: {label: np.ndarray}}.
    With out_dir, each case is written to <out_dir>/<case>.npz as it
    completes and already-present cases are skipped (resumable — remote TPU
    compiles make a full cold sweep take tens of minutes)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, repo)
    from tests.test_layer_grad_sweep import CASES, B0, T0
    from paddle_tpu.layers.graph import Topology, reset_names, value_data

    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    results = {}
    for name in sorted(CASES):
        if only and name not in only:
            continue
        if out_dir and os.path.exists(os.path.join(out_dir, name + ".npz")):
            print(f"[tpu_diff] {name}: cached", file=sys.stderr, flush=True)
            continue
        build, _ = CASES[name]
        reset_names()
        r = np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))
        outs, feed = build(r, B0, T0)
        outs = outs if isinstance(outs, list) else [outs]
        topo = Topology(outs)
        params = topo.init(jax.random.PRNGKey(0))
        # device arrays, not numpy: a numpy feed closed over by jit breaks
        # ops that numpy-index the feed with a traced array (conv_shift)
        feed = jax.tree_util.tree_map(jnp.asarray, feed)

        def fwd(p):
            out = topo.apply(p, feed, mode="test", rng=jax.random.PRNGKey(7))
            vals = out if isinstance(out, tuple) else (out,)
            return [value_data(v) for v in vals]

        def loss(p):
            return sum(jnp.mean(d.astype(jnp.float32)) for d in fwd(p))

        def thunk(fwd=fwd, loss=loss, params=params):
            vals = jax.jit(fwd)(params)
            rec = {f"out{i}": np.asarray(v, np.float32)
                   for i, v in enumerate(vals)}
            rec.update(_grad_arrays(jax.jit(jax.grad(loss))(params)))
            return rec
        _run_case(name, thunk, out_dir, results)
    return results


def _grad_arrays(grads):
    """Float grad leaves as {gradPATH: f32 array} — the one flattening
    every runner shares."""
    import numpy as np
    import jax
    out = {}
    for path, g in jax.tree_util.tree_flatten_with_path(grads)[0]:
        if np.issubdtype(np.asarray(g).dtype, np.floating):
            out["grad" + jax.tree_util.keystr(path)] = (
                np.asarray(g, np.float32))
    return out


def _run_case(cname, thunk, out_dir, results):
    """Shared per-case scaffolding (cache skip, __error__ capture in the
    format test_tpu_differential parses, save, progress print) — one
    definition for all three runners so the dump format cannot diverge.
    Assumes the caller already checked the cache when it needed to skip
    building inputs too; a second check here is cheap and keeps direct
    callers safe."""
    import numpy as np
    if out_dir and os.path.exists(os.path.join(out_dir, cname + ".npz")):
        print(f"[tpu_diff] {cname}: cached", file=sys.stderr, flush=True)
        return
    try:
        rec = thunk()
    except Exception as e:   # record, don't abort the sweep
        rec = {"__error__": np.frombuffer(
            f"{type(e).__name__}: {e}"[:500].encode(), np.uint8)}
    results[cname] = rec
    if out_dir:
        np.savez_compressed(os.path.join(out_dir, cname + ".npz"), **rec)
    print(f"[tpu_diff] {cname}: {len(rec)} arrays", file=sys.stderr,
          flush=True)


# name -> zero-arg ctor; the supervisor derives the __optim__ resume marker
# from the LAST sorted name, so additions stay resume-safe automatically
_OPTIM_CTORS = {
    "momentum": lambda: _optim().Momentum(0.1, momentum=0.9),
    "nesterov": lambda: _optim().Momentum(0.1, momentum=0.9, nesterov=True),
    "adagrad": lambda: _optim().AdaGrad(0.1),
    "adadelta": lambda: _optim().AdaDelta(rho=0.95),
    "rmsprop": lambda: _optim().RMSProp(0.01),
    "decayed_adagrad": lambda: _optim().DecayedAdaGrad(0.1),
    "adam": lambda: _optim().Adam(0.01),
    "adamax": lambda: _optim().AdaMax(0.01),
}


def _optim():
    from paddle_tpu import optim
    return optim


def _optim_marker():
    return "optim_" + sorted(_OPTIM_CTORS)[-1]


def run_optimizer_cases(out_dir=None):
    """Differential coverage for the optimizer zoo (reference
    math/tests/test_TrainingAlgorithm.cpp compares each update kernel
    CPU-vs-GPU): run 5 chained updates of every optimizer on seeded
    params/grads and dump the resulting params + slots."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    mk = _OPTIM_CTORS
    r = np.random.RandomState(11)
    params = {"w": jnp.asarray(r.randn(17, 9), jnp.float32),
              "b": jnp.asarray(r.randn(9), jnp.float32)}
    grad_seq = [jax.tree_util.tree_map(
        lambda x, i=i: jnp.asarray(
            np.random.RandomState(100 + i).randn(*x.shape), jnp.float32),
        params) for i in range(5)]

    results = {}
    for name, ctor in sorted(mk.items()):
        def thunk(ctor=ctor):
            opt = ctor()
            state = opt.init(params)

            @jax.jit
            def chain(p, s):
                for g in grad_seq:
                    p, s = opt.update(g, s, p)
                return p, s

            p, s = chain(params, state)
            rec = {}
            for k, v in jax.tree_util.tree_flatten_with_path(
                    {"p": p, "s": s})[0]:
                if np.issubdtype(np.asarray(v).dtype, np.floating):
                    rec[jax.tree_util.keystr(k)] = np.asarray(v, np.float32)
            return rec
        _run_case(f"optim_{name}", thunk, out_dir, results)
    return results


def _model_case_packed_lm():
    """Packed causal LM (transformer.lm_loss): segments + within-segment
    positions + causal attention + tied projection, fwd + grads."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.sequence import SequenceBatch, pack_sequences
    from paddle_tpu.models import transformer
    r = np.random.RandomState(3)
    seqs = [r.randint(3, 48, n) for n in (5, 9, 7, 3, 12, 4)]
    data, seg, pos = pack_sequences(seqs, max_len=16)
    b = data.shape[0]
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=48,
                              trg_vocab=1, d_model=16, dff=32,
                              enc_layers=2, dec_layers=0, max_len=16)
    tokens = SequenceBatch(jnp.asarray(data),
                           jnp.full((b,), 16, jnp.int32))
    seg, pos = jnp.asarray(seg), jnp.asarray(pos)

    def loss(p):
        return transformer.lm_loss(p, tokens, 2, segment_ids=seg,
                                   positions=pos)
    return params, loss


def _model_case_chunked_segment_attn():
    """chunked_attention with segment ids (the O(T) packed-attention
    numerics core), fwd + grads wrt the inputs."""
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.core.sequence import pack_sequences
    from paddle_tpu.ops import attention as att
    r = np.random.RandomState(5)
    seqs = [r.randint(0, 9, n) for n in (11, 7, 13, 5, 9, 18)]
    _, seg, _ = pack_sequences(seqs, max_len=32)
    b = seg.shape[0]
    x = jnp.asarray(r.randn(b, 2, 32, 8) * 0.5, jnp.float32)
    segj = jnp.asarray(seg)
    m = (segj > 0).astype(jnp.float32)

    def loss(p):
        out = att.chunked_attention(p["x"], p["x"], p["x"], causal=True,
                                    q_segment_ids=segj, q_chunk=8,
                                    k_chunk=8, key_mask=m)
        return jnp.sum((out * m[:, None, :, None]) ** 2)
    return {"x": x}, loss


def _model_case_mt_loss():
    """transformer.loss (encoder + causal decoder + cross-attention +
    label smoothing): the flagship MT train objective."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer
    r = np.random.RandomState(7)
    params = transformer.init(jax.random.PRNGKey(1), src_vocab=48,
                              trg_vocab=48, d_model=16, dff=32,
                              enc_layers=1, dec_layers=1, max_len=12)
    mk = lambda: SequenceBatch(
        jnp.asarray(r.randint(3, 48, (3, 12)), jnp.int32),
        jnp.asarray(r.randint(6, 13, (3,)), jnp.int32))
    src, trg_in, trg_next = mk(), mk(), mk()

    def loss(p):
        return transformer.loss(p, src, trg_in, trg_next, num_heads=2)
    return params, loss


def _model_case_ring1_attention():
    """ring_attention on a 1-device mesh: compiles the shard_map +
    ppermute + online-softmax rotation on the real backend (the
    multi-chip numerics core, single-chip-verifiable half)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.parallel.ring_attention import ring_attention
    r = np.random.RandomState(9)
    q = jnp.asarray(r.randn(2, 2, 16, 8) * 0.5, jnp.float32)
    k = jnp.asarray(r.randn(2, 2, 16, 8) * 0.5, jnp.float32)
    v = jnp.asarray(r.randn(2, 2, 16, 8) * 0.5, jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("seq",))

    def loss(p):
        out = ring_attention(p["q"], p["k"], p["v"], mesh, causal=True)
        return jnp.sum(out ** 2)
    return {"q": q, "k": k, "v": v}, loss


_MODEL_CASES = {
    "packed_lm": _model_case_packed_lm,
    "chunked_segment_attn": _model_case_chunked_segment_attn,
    "mt_loss": _model_case_mt_loss,
    "ring1_attention": _model_case_ring1_attention,
}


def _model_marker():
    return "model_" + sorted(_MODEL_CASES)[-1]


def run_model_cases(out_dir=None):
    """Differential coverage for the model-level paths the layer sweep
    can't reach: packed causal LM, segment-packed chunked attention, the
    flagship MT loss, and the ring rotation (1-device)."""
    import numpy as np
    import jax

    results = {}
    for name, build in sorted(_MODEL_CASES.items()):
        def thunk(build=build):
            params, loss = build()
            val, grads = jax.jit(jax.value_and_grad(loss))(params)
            rec = {"out0": np.asarray(val, np.float32)}
            rec.update(_grad_arrays(grads))
            return rec
        _run_case(f"model_{name}", thunk, out_dir, results)
    return results


def _code_revision():
    from paddle_tpu.utils.revision import code_revision
    return code_revision()


def consolidate(out_dir, out_path):
    import numpy as np
    flat = {}
    for fn in sorted(os.listdir(out_dir)):
        if not fn.endswith(".npz"):
            continue
        case = fn[:-4]
        with np.load(os.path.join(out_dir, fn)) as z:
            for label in z.files:
                flat[f"{case}::{label}"] = z[label]
    # stamp with the revision the CACHE was produced at (REVISION is
    # written by supervise before any case runs) — not the possibly-moved
    # current HEAD
    rev_file = os.path.join(out_dir, "REVISION")
    rev = open(rev_file).read().strip() if os.path.exists(rev_file) \
        else _code_revision()
    flat["__revision__"] = np.frombuffer(rev.encode(), np.uint8).copy()
    np.savez_compressed(out_path, **flat)
    return len(flat)


def _is_error_record(path):
    import numpy as np
    try:
        with np.load(path) as z:
            return list(z.files) == ["__error__"]
    except Exception:   # unreadable/corrupt record: treat as retryable
        return True


def _case_names():
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, repo)
    from tests.test_layer_grad_sweep import CASES
    return sorted(CASES)


def supervise(platform, out_path, case_timeout=150.0, max_consec_fail=4):
    """One worker subprocess per case with a hard timeout — a wedged remote
    TPU compile can only be killed from outside the process (it blocks in
    C++ where no Python signal lands).  Consecutive-failure cap aborts the
    sweep when the chip/tunnel itself is down rather than one case."""
    import shutil
    import subprocess
    import numpy as np
    out_dir = out_path + ".d"
    # the per-case resume cache is only valid for the code that wrote it:
    # a resumed dump mixing revisions would make the cross-platform compare
    # diff two different programs
    rev = _code_revision()
    rev_file = os.path.join(out_dir, "REVISION")
    keep_stamp = None
    if os.path.isdir(out_dir):
        old = open(rev_file).read().strip() \
            if os.path.exists(rev_file) else None
        if old is None:
            # pre-stamping cache: adopt it rather than destroy tens of
            # minutes of TPU compiles (its provenance is the operator's
            # responsibility; from now on changes invalidate it properly)
            print("[tpu_diff] adopting unstamped case cache as current "
                  "revision", file=sys.stderr, flush=True)
        elif rev == "unknown":
            # can't VERIFY the cache ('unknown' means git is unavailable,
            # not a different revision) — keep it and its concrete stamp
            print("[tpu_diff] code revision unverifiable (no git); "
                  "keeping existing case cache", file=sys.stderr,
                  flush=True)
            keep_stamp = old
        elif old != rev:
            print(f"[tpu_diff] clearing stale case cache ({old} != "
                  f"{rev})", file=sys.stderr, flush=True)
            shutil.rmtree(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    with open(rev_file, "w") as f:
        f.write((keep_stamp or rev) + "\n")
    retry_errors = os.environ.get("TPU_DIFF_RETRY_ERRORS", "0") == "1"
    consec = 0
    names = _case_names() + ["__optim__", "__models__"]
    group_markers = {"__optim__": _optim_marker,
                     "__models__": _model_marker}
    group_subcases = {
        "__optim__": lambda: [f"optim_{n}" for n in _OPTIM_CTORS],
        "__models__": lambda: [f"model_{n}" for n in _MODEL_CASES]}
    for name in names:
        # marker must be the LAST file the worker writes (sorted order), or
        # a mid-sweep kill would make resume skip the remainder
        marker = os.path.join(
            out_dir,
            (group_markers[name]() if name in group_markers else name)
            + ".npz")
        deleted_stale = False
        if retry_errors:
            # drop error-only records so the worker recomputes them; for
            # a group that means ANY sub-case record, not just the marker
            # (the worker skips per-sub-case caches)
            stale = ([os.path.join(out_dir, f"{c}.npz")
                      for c in group_subcases[name]()]
                     if name in group_subcases else [marker])
            for p in stale:
                if os.path.exists(p) and _is_error_record(p):
                    os.unlink(p)
                    deleted_stale = True
        # a healthy marker must not suppress the rerun that recomputes a
        # just-deleted stale record
        if os.path.exists(marker) and not deleted_stale:
            continue
        cmd = [sys.executable, "-m", "paddle_tpu.testing.tpu_diff",
               platform, out_path, name, "--worker"]
        try:
            subprocess.run(cmd, timeout=case_timeout, check=True,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
            consec = 0
        except subprocess.TimeoutExpired:
            # record the timeout so the comparing test FAILS on it instead
            # of silently skipping (the test enumerates cases from the CPU
            # dump, so a missing record means the case never gets compared
            # at all); TPU_DIFF_RETRY_ERRORS=1 deletes these on the next
            # run.  Group cases get a record per MISSING sub-case —
            # completed sub-cases keep their caches (healthy results AND
            # genuine __error__ records the worker wrote before wedging:
            # a real error message beats a generic timeout), so a retried
            # group resumes from where the kill landed.
            timeout_rec = np.frombuffer(
                f"TimeoutExpired: worker exceeded {case_timeout}s "
                f"(wedged backend?)".encode(), np.uint8)
            missing = ([os.path.join(out_dir, c + ".npz")
                        for c in group_subcases[name]()]
                       if name in group_subcases else [marker])
            for p in missing:
                if not os.path.exists(p):
                    np.savez_compressed(p, __error__=timeout_rec)
            consec += 1
            print(f"[tpu_diff] {name}: TIMEOUT ({case_timeout}s)",
                  file=sys.stderr, flush=True)
        except subprocess.CalledProcessError as e:
            consec += 1
            print(f"[tpu_diff] {name}: worker rc={e.returncode}",
                  file=sys.stderr, flush=True)
        else:
            print(f"[tpu_diff] {name}: done", file=sys.stderr, flush=True)
        if consec >= max_consec_fail:
            print(f"[tpu_diff] aborting: {consec} consecutive failures "
                  "(backend down?)", file=sys.stderr, flush=True)
            return False
    n = consolidate(out_dir, out_path)
    print(f"[tpu_diff] wrote {n} arrays to {out_path}", file=sys.stderr)
    return True


def main():
    platform, out_path = sys.argv[1], sys.argv[2]
    rest = [a for a in sys.argv[3:] if a != "--worker"]
    worker = "--worker" in sys.argv
    only = set(rest[0].split(",")) if rest else None

    if not worker:
        ok = supervise(platform, out_path,
                       case_timeout=float(
                           os.environ.get("TPU_DIFF_CASE_TIMEOUT", "150")))
        sys.exit(0 if ok else 3)

    _pin_platform(platform)
    out_dir = out_path + ".d"
    if only == {"__optim__"}:
        run_optimizer_cases(out_dir=out_dir)
    elif only == {"__models__"}:
        run_model_cases(out_dir=out_dir)
    else:
        run_cases(only, out_dir=out_dir)


if __name__ == "__main__":
    main()
