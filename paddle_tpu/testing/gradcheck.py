"""Finite-difference gradient checking.

Reference: the `--job=checkgrad` trainer mode (trainer/Trainer.cpp
checkGradient) and the per-layer numerical sweeps of
gserver/tests/test_LayerGrad.cpp + LayerGradUtil.{h,cpp} testLayerGrad:266
(perturb parameters, compare analytic vs (f(x+h)-f(x-h))/2h).

Here autodiff replaces hand-written backward passes, so this is a sanity
harness for custom kernels/custom_vjp rules rather than a per-layer
necessity — but the capability (and CLI job) is preserved.
"""

import numpy as np
import jax
import jax.numpy as jnp


def check_grads(loss_fn, params, eps=1e-3, rtol=2e-2, atol=1e-4,
                max_elems_per_leaf=4, rng=None, raise_on_fail=True):
    """Compare jax.grad(loss_fn)(params) against central differences on a
    random subset of elements per parameter leaf.

    Returns [(path, max_rel_err, ok)] covering EVERY leaf (the reference
    checkgrad reports diffs across the whole model); with raise_on_fail an
    AssertionError listing all failures is raised at the end."""
    rng = rng or np.random.RandomState(0)
    analytic = jax.grad(loss_fn)(params)
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    aflat = jax.tree_util.tree_leaves(analytic)
    results = []
    failures = []
    for (path, leaf), g in zip(flat, aflat):
        orig_dtype = np.asarray(leaf).dtype
        leaf = np.asarray(leaf, np.float64)
        g = np.asarray(g)
        n = leaf.size
        idxs = rng.choice(n, size=min(max_elems_per_leaf, n), replace=False)
        max_err = 0.0
        for idx in idxs:
            delta = np.zeros(n)
            delta[idx] = eps
            delta = delta.reshape(leaf.shape)

            # rebuild params with this leaf perturbed (keep the leaf's own
            # dtype: f64 sweeps stay f64, f32 models stay f32)
            def with_leaf(value):
                return jax.tree_util.tree_unflatten(
                    treedef, [value if p2 == path else l2
                              for (p2, l2) in flat])

            plus = with_leaf(jnp.asarray(leaf + delta, orig_dtype))
            minus = with_leaf(jnp.asarray(leaf - delta, orig_dtype))
            num = (float(loss_fn(plus)) - float(loss_fn(minus))) / (2 * eps)
            ana = float(g.reshape(-1)[idx])
            err = abs(num - ana) / max(abs(num), abs(ana), atol)
            max_err = max(max_err, err)
            if not (err < rtol or abs(num - ana) < atol):
                failures.append(
                    f"{jax.tree_util.keystr(path)}[{idx}]: "
                    f"analytic={ana:.6g} numeric={num:.6g} rel={err:.3g}")
        ok = not any(f.startswith(jax.tree_util.keystr(path) + "[")
                     for f in failures)
        results.append((jax.tree_util.keystr(path), max_err, ok))
    if failures and raise_on_fail:
        raise AssertionError("gradient mismatches:\n  "
                             + "\n  ".join(failures))
    return results


def check_topology_grads(topology, feed, rng_key=None, **kw):
    """checkgrad over a Topology's mean cost (the --job=checkgrad flow)."""
    rng_key = rng_key if rng_key is not None else jax.random.PRNGKey(0)
    params = topology.init(rng_key)

    def loss_fn(p):
        out = topology.apply(p, feed, mode="test")
        outs = out if isinstance(out, tuple) else (out,)
        return sum(jnp.mean(o if not hasattr(o, "data") else o.data)
                   for o in outs)

    return check_grads(loss_fn, params, **kw)
