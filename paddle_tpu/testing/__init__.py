"""Testing utilities (reference paddle/testing/ + the --job=checkgrad
trainer mode and gserver/tests/LayerGradUtil.h discipline)."""

from paddle_tpu.testing.gradcheck import check_topology_grads, check_grads

__all__ = ["check_topology_grads", "check_grads"]
