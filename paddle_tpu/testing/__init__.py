"""Testing utilities (reference paddle/testing/ + the --job=checkgrad
trainer mode and gserver/tests/LayerGradUtil.h discipline)."""

from paddle_tpu.testing.gradcheck import check_topology_grads, check_grads
from paddle_tpu.testing.trace import (assert_no_retrace, counting,
                                      expect_traces, forbid_retrace)

__all__ = ["check_topology_grads", "check_grads", "assert_no_retrace",
           "expect_traces", "forbid_retrace", "counting"]
