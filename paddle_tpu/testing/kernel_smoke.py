"""Pallas kernel smoke checks: compile every kernel on the LIVE backend and
verify numerics against the pure-XLA oracle.

Motivation (round-2 verdict): interpret-mode passing is not a compile proof —
round 1's flash-attention lse layout was rejected by Mosaic only on first
real-TPU contact.  This module gives `bench.py --smoke-kernels` (and
tests/test_kernel_smoke.py) a seconds-long canary that exercises every
custom kernel's forward AND backward through a real Mosaic compile.

Each case returns the max abs error vs the oracle and raises AssertionError
if it exceeds the case tolerance.  Mirrors the reference's per-kernel unit
tests (test_LstmLayer / test_MatrixCompare pattern, SURVEY §4), but backend-
aware: on CPU the kernels run in interpret mode, on TPU through Mosaic.
"""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.sequence import SequenceBatch


@contextlib.contextmanager
def _fused_mode(mode):
    """Temporarily force the fused-RNN dispatch mode ('always' | '0')."""
    from paddle_tpu.ops import rnn
    old = rnn.FUSED_LSTM
    rnn.FUSED_LSTM = mode
    try:
        yield
    finally:
        rnn.FUSED_LSTM = old


def _max_err(a, b):
    return float(jnp.max(jnp.abs(jnp.asarray(a, jnp.float32)
                                 - jnp.asarray(b, jnp.float32))))


def _rnn_case(kind, tol=1e-2):
    """Fused-vs-scan equality (fwd + full BPTT grads) through the public
    rnn.{lstm,gru,simple_rnn} dispatch, on whatever backend is live."""
    from paddle_tpu.ops import rnn

    b, t, d = 8, 12, 128
    gates = {"lstm": 4, "gru": 3, "simple_rnn": 1}[kind]
    rng = np.random.RandomState(7)
    data = jnp.asarray(rng.randn(b, t, gates * d) * 0.3, jnp.float32)
    lengths = jnp.asarray(rng.randint(1, t + 1, (b,)), jnp.int32)
    probe = jnp.asarray(rng.randn(b, t, d), jnp.float32)

    if kind == "lstm":
        w = jnp.asarray(rng.randn(d, 4 * d) * 0.05, jnp.float32)
        checks = [jnp.asarray(rng.randn(d) * 0.1, jnp.float32)
                  for _ in range(3)]

        def loss(data, w):
            out, final = rnn.lstm(SequenceBatch(data=data, lengths=lengths),
                                  w, check_i=checks[0], check_f=checks[1],
                                  check_o=checks[2])
            return (jnp.sum(out.data * probe) + jnp.sum(final.h)
                    + jnp.sum(final.c))
    elif kind == "gru":
        wg = jnp.asarray(rng.randn(d, 2 * d) * 0.05, jnp.float32)
        ws = jnp.asarray(rng.randn(d, d) * 0.05, jnp.float32)

        def loss(data, w):
            out, final = rnn.gru(SequenceBatch(data=data, lengths=lengths),
                                 w, ws)
            return jnp.sum(out.data * probe) + jnp.sum(final)
        w = wg
    else:
        w = jnp.asarray(rng.randn(d, d) * 0.05, jnp.float32)

        def loss(data, w):
            out, final = rnn.simple_rnn(
                SequenceBatch(data=data, lengths=lengths), w)
            return jnp.sum(out.data * probe) + jnp.sum(final)

    # fresh jit wrapper per mode: the dispatch flag is read at TRACE time,
    # so a shared wrapper would silently reuse the first mode's trace
    with _fused_mode("always"):
        l_k, (gx_k, gw_k) = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1)))(data, w)
        jax.block_until_ready(l_k)
    with _fused_mode("0"):
        l_o, (gx_o, gw_o) = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1)))(data, w)
        jax.block_until_ready(l_o)

    err = max(_max_err(l_k, l_o),
              _max_err(gx_k, gx_o),
              _max_err(gw_k, gw_o) / max(1.0, float(jnp.abs(gw_o).max())))
    assert err <= tol, f"{kind} fused-vs-scan max err {err:.3e} > tol {tol}"
    return err


def _flash_case(causal, tol=0.05):
    """Flash attention fwd+bwd vs materialized-softmax oracle."""
    import importlib
    # the pallas package re-exports the flash_attention FUNCTION under the
    # module's name; import the module itself explicitly
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")
    from paddle_tpu.ops import attention as attn

    b, h, t, d = 2, 2, 512, 128
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.randn(b, h, t, d) * 0.5, jnp.float32)
               for _ in range(3))
    probe = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, causal=causal,
                               block_q=256, block_k=256)
        return jnp.sum(o * probe)

    def loss_oracle(q, k, v):
        o = attn.dot_product_attention(q, k, v, scale=1.0 / np.sqrt(d),
                                       causal=causal, use_flash=False)
        return jnp.sum(o * probe)

    lf, gf = jax.jit(jax.value_and_grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    jax.block_until_ready(lf)
    lo, go = jax.jit(jax.value_and_grad(loss_oracle, argnums=(0, 1, 2)))(q, k, v)
    jax.block_until_ready(lo)

    err = max(_max_err(lf, lo) / max(1.0, abs(float(lo))),
              max(_max_err(a, b) for a, b in zip(gf, go)))
    assert err <= tol, (f"flash(causal={causal}) max err {err:.3e} "
                        f"> tol {tol}")
    return err


def _lstm_blocked_case(tol=1e-2):
    """Gate-blocked over-VMEM LSTM forward (lstm_blocked.py) + its
    saved-activation BPTT vs the scan oracle, via direct kernel call (the
    dispatch would prefer the resident kernel at this small shape)."""
    from paddle_tpu.ops import rnn
    from paddle_tpu.ops.pallas import lstm_blocked as blk

    b, t, d = 8, 9, 256          # odd T exercises the parity pad
    rng = np.random.RandomState(11)
    data = jnp.asarray(rng.randn(b, t, 4 * d) * 0.3, jnp.float32)
    lengths = jnp.asarray(rng.randint(1, t + 1, (b,)), jnp.int32)
    probe = jnp.asarray(rng.randn(b, t, d), jnp.float32)
    w = jnp.asarray(rng.randn(d, 4 * d) * 0.05, jnp.float32)
    checks = [jnp.asarray(rng.randn(d) * 0.1, jnp.float32)
              for _ in range(3)]
    seq = SequenceBatch(data=data, lengths=lengths)
    ms = seq.mask().transpose(1, 0)

    def loss_blk(data, w):
        hs, (fh, fc) = blk.lstm_fused_blocked(
            data.transpose(1, 0, 2), ms, w, *checks)
        out = hs.transpose(1, 0, 2) * seq.mask(hs.dtype)[..., None]
        return jnp.sum(out * probe) + jnp.sum(fh) + jnp.sum(fc)

    def loss_scan(data, w):
        with _fused_mode("0"):
            out, final = rnn.lstm(SequenceBatch(data=data, lengths=lengths),
                                  w, check_i=checks[0], check_f=checks[1],
                                  check_o=checks[2])
        return (jnp.sum(out.data * probe) + jnp.sum(final.h)
                + jnp.sum(final.c))

    l_k, (gx_k, gw_k) = jax.jit(
        jax.value_and_grad(loss_blk, argnums=(0, 1)))(data, w)
    jax.block_until_ready(l_k)
    l_o, (gx_o, gw_o) = jax.jit(
        jax.value_and_grad(loss_scan, argnums=(0, 1)))(data, w)
    jax.block_until_ready(l_o)
    err = max(_max_err(l_k, l_o),
              _max_err(gx_k, gx_o),
              _max_err(gw_k, gw_o) / max(1.0, float(jnp.abs(gw_o).max())))
    assert err <= tol, f"lstm_blocked max err {err:.3e} > tol {tol}"
    return err


def build_private_tables(positions, nb_row, block_size, num_blocks):
    """Per-row PRIVATE block chains for decode-kernel drives: row r owns
    ``pos // block_size + 1`` distinct block ids from 1..num_blocks-1,
    unowned table slots stay 0 (the reserved scratch block) — the layout
    serving/kv_pool.py's allocator produces.  One definition for the
    smoke case here, bench.py's serving_decode_fused inputs, and
    tests/test_pallas_decode.py."""
    tables = np.zeros((len(positions), nb_row), np.int32)
    nxt = 1
    for r, p in enumerate(positions):
        for j in range(int(p) // block_size + 1):
            if nxt >= num_blocks:
                raise ValueError(
                    f"pool of {num_blocks} blocks cannot hold private "
                    f"chains for positions {list(positions)}")
            tables[r, j] = nxt
            nxt += 1
    return tables


def _decode_slab_case(tol=1e-4):
    """Fused slab decode-attention kernel vs the masked-XLA oracle
    (models/transformer._attend) — forward only (the decode hot path has
    no backward), through a real Mosaic compile on TPU / interpret mode
    on CPU.  GQA widths (Hkv < H) included: the in-register group
    expansion is the subtle Mosaic surface."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode_attention as dk

    errs = []
    for h, hkv, dh, s, t in ((8, 8, 128, 16, 256), (8, 2, 128, 16, 256)):
        d, dkv = h * dh, hkv * dh
        rng = np.random.RandomState(h * 10 + hkv)
        q = jnp.asarray(rng.randn(s, d) * 0.5, jnp.float32)
        k = jnp.asarray(rng.randn(s, t, dkv) * 0.5, jnp.float32)
        v = jnp.asarray(rng.randn(s, t, dkv) * 0.5, jnp.float32)
        pos = jnp.asarray(rng.randint(0, t, s), jnp.int32)
        with dk.forced_mode("always"):
            out = jax.jit(lambda q, k, v, pos: dk.maybe_slab(
                q, k, v, pos, h))(q, k, v, pos)
        assert out is not None, "slab kernel declined a supported shape"
        pm = jnp.arange(t)[None, :] <= pos[:, None]
        want = transformer._attend(q[:, None], k, v, h,
                                   jnp.broadcast_to(pm, (s, t)))[:, 0]
        errs.append(_max_err(out, want))
    err = max(errs)
    assert err <= tol, f"decode_slab max err {err:.3e} > tol {tol}"
    return err


def _decode_paged_case(tol=1e-4):
    """Fused paged decode-attention kernel (block-table scalar prefetch)
    vs the chain-gather oracle, real Mosaic compile on TPU."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode_attention as dk

    h, hkv, dh, s, bs, nb_row = 8, 2, 128, 16, 16, 8
    d, dkv = h * dh, hkv * dh
    nb = s * nb_row + 1
    t = nb_row * bs
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(s, d) * 0.5, jnp.float32)
    kp = jnp.asarray(rng.randn(nb, bs, dkv) * 0.5, jnp.float32)
    vp = jnp.asarray(rng.randn(nb, bs, dkv) * 0.5, jnp.float32)
    pos = np.asarray(rng.randint(0, t, s), np.int32)
    tables = build_private_tables(pos, nb_row, bs, nb)
    with dk.forced_mode("always"):
        out = jax.jit(lambda q, kp, vp, pos, tbl: dk.maybe_paged(
            q, kp, vp, pos, tbl, h))(q, kp, vp, jnp.asarray(pos),
                                     jnp.asarray(tables))
    assert out is not None, "paged kernel declined a supported shape"
    k_rows = kp[jnp.asarray(tables)].reshape(s, -1, dkv)
    v_rows = vp[jnp.asarray(tables)].reshape(s, -1, dkv)
    pm = jnp.asarray(np.arange(t)[None, :] <= pos[:, None])
    want = transformer._attend(q[:, None], k_rows, v_rows, h, pm)[:, 0]
    err = _max_err(out, want)
    assert err <= tol, f"decode_paged max err {err:.3e} > tol {tol}"
    return err


def _chunk_lanes_ref(positions, lengths, kk):
    li = np.minimum(np.arange(kk)[None, :], lengths[:, None] - 1)
    return (positions[:, None] + li).astype(np.int32)


def _live_lane_err(out, want, lengths):
    """Max error over LIVE lanes only (lane index < the row's length).
    Dead tail lanes repeat the last live qpos and their output is
    UNSPECIFIED: the decode-row fast path skips them on one-live-lane
    rows (engine cache writes / acceptance never read a dead lane)."""
    live = jnp.asarray(np.arange(out.shape[1])[None, :]
                       < lengths[:, None])
    return _max_err(out[live], want[live])


def _decode_slab_chunk_case(tol=1e-4):
    """Tq=chunk slab kernel (the unified chunked-prefill step's
    attention) vs the per-lane masked-XLA oracle: mixed decode rows
    (1 lane) and chunking rows (full K lanes), GQA width included."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode_attention as dk

    errs = []
    for h, hkv, dh, s, t, kk in ((8, 8, 128, 8, 256, 4),
                                 (8, 2, 128, 8, 256, 8)):
        d, dkv = h * dh, hkv * dh
        rng = np.random.RandomState(h * 10 + hkv + kk)
        q = jnp.asarray(rng.randn(s, kk, d) * 0.5, jnp.float32)
        k = jnp.asarray(rng.randn(s, t, dkv) * 0.5, jnp.float32)
        v = jnp.asarray(rng.randn(s, t, dkv) * 0.5, jnp.float32)
        pos = rng.randint(0, t - kk, s).astype(np.int32)
        lens = rng.randint(1, kk + 1, s).astype(np.int32)
        lens[0], lens[-1] = 1, kk       # pin both extremes
        qpos = _chunk_lanes_ref(pos, lens, kk)
        with dk.forced_mode("always"):
            out = jax.jit(lambda q, k, v, qp: dk.maybe_slab_chunk(
                q, k, v, qp, h))(q, k, v, jnp.asarray(qpos))
        assert out is not None, \
            "slab chunk kernel declined a supported shape"
        pm = jnp.asarray(np.arange(t)[None, None, :]
                         <= qpos[:, :, None])
        want = transformer._attend(q, k, v, h, pm)
        errs.append(_live_lane_err(out, want, lens))
    err = max(errs)
    assert err <= tol, f"decode_slab_chunk max err {err:.3e} > tol {tol}"
    return err


def _decode_paged_chunk_case(tol=1e-4):
    """Tq=chunk paged kernel (block-table scalar prefetch, chunk lanes
    sharing each streamed block) vs the chain-gather oracle."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode_attention as dk

    h, hkv, dh, s, bs, nb_row, kk = 8, 2, 128, 8, 16, 8, 8
    d, dkv = h * dh, hkv * dh
    nb = s * nb_row + 1
    t = nb_row * bs
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(s, kk, d) * 0.5, jnp.float32)
    kp = jnp.asarray(rng.randn(nb, bs, dkv) * 0.5, jnp.float32)
    vp = jnp.asarray(rng.randn(nb, bs, dkv) * 0.5, jnp.float32)
    pos = rng.randint(0, t - kk, s).astype(np.int32)
    lens = rng.randint(1, kk + 1, s).astype(np.int32)
    qpos = _chunk_lanes_ref(pos, lens, kk)
    tables = build_private_tables(qpos[:, -1], nb_row, bs, nb)
    with dk.forced_mode("always"):
        out = jax.jit(lambda q, kp, vp, qp, tbl: dk.maybe_paged_chunk(
            q, kp, vp, qp, tbl, h))(q, kp, vp, jnp.asarray(qpos),
                                    jnp.asarray(tables))
    assert out is not None, "paged chunk kernel declined a supported shape"
    k_rows = kp[jnp.asarray(tables)].reshape(s, -1, dkv)
    v_rows = vp[jnp.asarray(tables)].reshape(s, -1, dkv)
    pm = jnp.asarray(np.arange(t)[None, None, :] <= qpos[:, :, None])
    want = transformer._attend(q, k_rows, v_rows, h, pm)
    err = _live_lane_err(out, want, lens)
    assert err <= tol, f"decode_paged_chunk max err {err:.3e} > tol {tol}"
    return err


def _quantize_kv(arr, hkv, seed):
    """Random f32 K/V quantized to (int8, per-(position, head) scales)
    — the int8 smoke cases' shared input builder (quant/kv.py math)."""
    from paddle_tpu.quant import kv as kvq
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(*arr) * 0.5, jnp.float32)
    return kvq.quantize_heads(x, hkv)


def _decode_slab_int8_case(tol=1e-4):
    """Int8-KV slab decode kernel (scale-sidecar operands, in-register
    dequant) vs the dequantize-then-attend oracle — the quantized twin
    of ``_decode_slab_case``, GQA width included.  Note the compiled
    backend wants 32-sublane int8 tiles: t is a multiple of 32."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode_attention as dk
    from paddle_tpu.quant import kv as kvq

    errs = []
    # GQA width only: the per-group scale panels are the subtle surface
    # (the full-width case shares every code path with hkv=2)
    for h, hkv, dh, s, t in ((8, 2, 128, 16, 256),):
        d, dkv = h * dh, hkv * dh
        rng = np.random.RandomState(h * 10 + hkv + 1)
        q = jnp.asarray(rng.randn(s, d) * 0.5, jnp.float32)
        qk, sk = _quantize_kv((s, t, dkv), hkv, seed=h + hkv)
        qv, sv = _quantize_kv((s, t, dkv), hkv, seed=h + hkv + 1)
        pos = jnp.asarray(rng.randint(0, t, s), jnp.int32)
        with dk.forced_mode("always"):
            out = jax.jit(lambda q, k, v, ks, vs, pos: dk.maybe_slab(
                q, k, v, pos, h, kscale=ks, vscale=vs))(
                    q, qk, qv, sk, sv, pos)
        assert out is not None, "int8 slab kernel declined a supported shape"
        pm = jnp.arange(t)[None, :] <= pos[:, None]
        want = transformer._attend(
            q[:, None], kvq.dequantize_heads(qk, sk),
            kvq.dequantize_heads(qv, sv), h,
            jnp.broadcast_to(pm, (s, t)))[:, 0]
        errs.append(_max_err(out, want))
    err = max(errs)
    assert err <= tol, f"decode_slab_int8 max err {err:.3e} > tol {tol}"
    return err


def _decode_paged_int8_case(tol=1e-4):
    """Int8-KV paged decode kernel: the scale-sidecar pools ride the
    same block-table-walked DMA stream as the int8 K/V pools."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode_attention as dk
    from paddle_tpu.quant import kv as kvq

    h, hkv, dh, s, bs, nb_row = 8, 2, 128, 16, 32, 4
    d, dkv = h * dh, hkv * dh
    nb = s * nb_row + 1
    t = nb_row * bs
    rng = np.random.RandomState(21)
    q = jnp.asarray(rng.randn(s, d) * 0.5, jnp.float32)
    qk, sk = _quantize_kv((nb, bs, dkv), hkv, seed=3)
    qv, sv = _quantize_kv((nb, bs, dkv), hkv, seed=4)
    pos = np.asarray(rng.randint(0, t, s), np.int32)
    tables = build_private_tables(pos, nb_row, bs, nb)
    with dk.forced_mode("always"):
        out = jax.jit(lambda q, k, v, ks, vs, pos, tbl: dk.maybe_paged(
            q, k, v, pos, tbl, h, kscale=ks, vscale=vs))(
                q, qk, qv, sk, sv, jnp.asarray(pos),
                jnp.asarray(tables))
    assert out is not None, "int8 paged kernel declined a supported shape"
    kf = kvq.dequantize_heads(qk, sk)
    vf = kvq.dequantize_heads(qv, sv)
    k_rows = kf[jnp.asarray(tables)].reshape(s, -1, dkv)
    v_rows = vf[jnp.asarray(tables)].reshape(s, -1, dkv)
    pm = jnp.asarray(np.arange(t)[None, :] <= pos[:, None])
    want = transformer._attend(q[:, None], k_rows, v_rows, h, pm)[:, 0]
    err = _max_err(out, want)
    assert err <= tol, f"decode_paged_int8 max err {err:.3e} > tol {tol}"
    return err


def _decode_slab_chunk_int8_case(tol=1e-4):
    """Int8-KV Tq=chunk slab kernel: every lane shares each streamed
    int8 block's in-register dequant panels."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode_attention as dk
    from paddle_tpu.quant import kv as kvq

    h, hkv, dh, s, t, kk = 8, 2, 128, 8, 256, 8
    d, dkv = h * dh, hkv * dh
    rng = np.random.RandomState(31)
    q = jnp.asarray(rng.randn(s, kk, d) * 0.5, jnp.float32)
    qk, sk = _quantize_kv((s, t, dkv), hkv, seed=5)
    qv, sv = _quantize_kv((s, t, dkv), hkv, seed=6)
    pos = rng.randint(0, t - kk, s).astype(np.int32)
    lens = rng.randint(1, kk + 1, s).astype(np.int32)
    lens[0], lens[-1] = 1, kk       # pin both extremes
    qpos = _chunk_lanes_ref(pos, lens, kk)
    with dk.forced_mode("always"):
        out = jax.jit(lambda q, k, v, ks, vs, qp: dk.maybe_slab_chunk(
            q, k, v, qp, h, kscale=ks, vscale=vs))(
                q, qk, qv, sk, sv, jnp.asarray(qpos))
    assert out is not None, \
        "int8 slab chunk kernel declined a supported shape"
    pm = jnp.asarray(np.arange(t)[None, None, :] <= qpos[:, :, None])
    want = transformer._attend(q, kvq.dequantize_heads(qk, sk),
                               kvq.dequantize_heads(qv, sv), h, pm)
    err = _live_lane_err(out, want, lens)
    assert err <= tol, \
        f"decode_slab_chunk_int8 max err {err:.3e} > tol {tol}"
    return err


def _decode_paged_chunk_int8_case(tol=1e-4):
    """Int8-KV Tq=chunk paged kernel — the full quantized unified-step
    attention surface."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import decode_attention as dk
    from paddle_tpu.quant import kv as kvq

    h, hkv, dh, s, bs, nb_row, kk = 8, 2, 128, 8, 32, 4, 8
    d, dkv = h * dh, hkv * dh
    nb = s * nb_row + 1
    t = nb_row * bs
    rng = np.random.RandomState(41)
    q = jnp.asarray(rng.randn(s, kk, d) * 0.5, jnp.float32)
    qk, sk = _quantize_kv((nb, bs, dkv), hkv, seed=7)
    qv, sv = _quantize_kv((nb, bs, dkv), hkv, seed=8)
    pos = rng.randint(0, t - kk, s).astype(np.int32)
    lens = rng.randint(1, kk + 1, s).astype(np.int32)
    qpos = _chunk_lanes_ref(pos, lens, kk)
    tables = build_private_tables(qpos[:, -1], nb_row, bs, nb)
    with dk.forced_mode("always"):
        out = jax.jit(
            lambda q, k, v, ks, vs, qp, tbl: dk.maybe_paged_chunk(
                q, k, v, qp, tbl, h, kscale=ks, vscale=vs))(
                    q, qk, qv, sk, sv, jnp.asarray(qpos),
                    jnp.asarray(tables))
    assert out is not None, \
        "int8 paged chunk kernel declined a supported shape"
    kf = kvq.dequantize_heads(qk, sk)
    vf = kvq.dequantize_heads(qv, sv)
    k_rows = kf[jnp.asarray(tables)].reshape(s, -1, dkv)
    v_rows = vf[jnp.asarray(tables)].reshape(s, -1, dkv)
    pm = jnp.asarray(np.arange(t)[None, None, :] <= qpos[:, :, None])
    want = transformer._attend(q, k_rows, v_rows, h, pm)
    err = _live_lane_err(out, want, lens)
    assert err <= tol, \
        f"decode_paged_chunk_int8 max err {err:.3e} > tol {tol}"
    return err


def _flash_int8_case(tol=1e-4):
    """Int8 flash prefill kernel (flash_attention_quant): int8 K/V with
    their per-(position, head) scale sidecars riding the same
    block-indexed stream, widened in registers, vs the dequantize-then-
    attend oracle — GQA width, causal, multi-position.  Note the
    compiled backend wants 32-sublane int8 k-tiles: t is a multiple of
    32 (interpret mode relaxes to 8)."""
    import importlib
    from paddle_tpu.models import transformer
    from paddle_tpu.quant import kv as kvq
    fa = importlib.import_module(
        "paddle_tpu.ops.pallas.flash_attention")

    b, h, hkv, dh, t = 2, 8, 2, 128, 256
    d, dkv = h * dh, hkv * dh
    rng = np.random.RandomState(51)
    q = jnp.asarray(rng.randn(b, t, d) * 0.5, jnp.float32)
    qk, sk = _quantize_kv((b, t, dkv), hkv, seed=9)
    qv, sv = _quantize_kv((b, t, dkv), hkv, seed=10)
    out = jax.jit(lambda q, k, v, ks, vs: fa.flash_attention_quant(
        q, k, v, ks, vs, h, causal=True))(q, qk, qv, sk, sv)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, d)
    pm = jnp.asarray(np.tril(np.ones((t, t), bool)))[None]
    want = transformer._attend(q, kvq.dequantize_heads(qk, sk),
                               kvq.dequantize_heads(qv, sv), h,
                               jnp.broadcast_to(pm, (b, t, t)))
    err = _max_err(out, want)
    assert err <= tol, f"flash_int8 max err {err:.3e} > tol {tol}"
    return err


CASES = {
    "lstm_fused": lambda: _rnn_case("lstm"),
    "lstm_blocked": _lstm_blocked_case,
    "gru_fused": lambda: _rnn_case("gru"),
    "simple_rnn_fused": lambda: _rnn_case("simple_rnn"),
    "flash_attention": lambda: _flash_case(causal=False),
    "flash_attention_causal": lambda: _flash_case(causal=True),
    "flash_attention_int8": _flash_int8_case,
    "decode_attention_slab": _decode_slab_case,
    "decode_attention_paged": _decode_paged_case,
    "decode_attention_slab_chunk": _decode_slab_chunk_case,
    "decode_attention_paged_chunk": _decode_paged_chunk_case,
    "decode_attention_slab_int8": _decode_slab_int8_case,
    "decode_attention_paged_int8": _decode_paged_int8_case,
    "decode_attention_slab_chunk_int8": _decode_slab_chunk_int8_case,
    "decode_attention_paged_chunk_int8": _decode_paged_chunk_int8_case,
}
