"""Trace-count discipline, shared.

Three AOT surfaces make the same promise — warm-up traces the step's
Python body exactly once per compiled shape, and steady state never
retraces: ``SGD.precompile`` (trainer/trainer.py), the serving
``InferenceEngine.warmup`` bucket ladder, and the continuous-batching
``DecodeEngine`` slab step (serving/decode_engine.py).  Each keeps a
counter that increments ONLY inside the traced function's Python body
(so it moves iff JAX is staging the function); this module holds the one
assertion they all share, so the promise is phrased — and its failure
message reads — the same everywhere.
"""

import contextlib


@contextlib.contextmanager
def expect_traces(get_count, expected, what, hint=None):
    """Assert the wrapped block traces exactly ``expected`` times.

    ``get_count``: zero-arg callable returning the current trace counter
    (e.g. ``lambda: engine.trace_count``).  ``what`` names the operation
    for the failure message; ``hint`` (optional) names the likely cause.

        with expect_traces(lambda: tr.trace_count, 0,
                           "train() over precompiled buckets"):
            tr.train(...)
    """
    before = get_count()
    yield
    actual = get_count() - before
    if actual != expected:
        msg = (f"{what}: traced {actual} time(s) "
               f"(expected exactly {expected})")
        if hint:
            msg += f" — {hint}"
        raise AssertionError(msg)


def assert_no_retrace(get_count, what, hint="the compiled path retraced"):
    """``expect_traces(..., 0, ...)`` — the steady-state half of the
    discipline, named for readability at call sites."""
    return expect_traces(get_count, 0, what, hint=hint)


def _as_counter(c):
    """A trace-count source: a zero-arg callable, or any object exposing
    ``step_trace_count`` / ``trace_count`` (the engines' counters)."""
    if callable(c) and not hasattr(c, "step_trace_count") \
            and not hasattr(c, "trace_count"):
        return c
    for attr in ("step_trace_count", "trace_count"):
        if hasattr(c, attr):
            return lambda o=c, a=attr: getattr(o, a)
    raise TypeError(f"{c!r} is neither a callable counter nor an object "
                    "with step_trace_count/trace_count")


@contextlib.contextmanager
def forbid_retrace(*counters, what="the compiled path", hint=None):
    """Assert NONE of the given trace counters move inside the block —
    the multi-surface replacement for the hand-rolled
    ``t0 = eng.step_trace_count; ...; assert eng.step_trace_count - t0
    == 0`` spies.  Counters may be zero-arg callables or engine-like
    objects (``step_trace_count``/``trace_count`` read directly):

        with forbid_retrace(eng, peng):   # churn must retrace NOTHING
            drive(eng, peng)

    Also the runtime half of the static retrace gate
    (tests/test_analysis.py): the analyzer flags a hazard statically,
    and forbid_retrace proves the same shape really retraces live.
    """
    getters = [_as_counter(c) for c in counters]
    if not getters:
        raise TypeError("forbid_retrace() needs at least one counter")
    before = [g() for g in getters]
    yield
    for i, g in enumerate(getters):
        actual = g() - before[i]
        if actual:
            msg = (f"{what}: counter #{i} traced {actual} time(s) "
                   f"(expected 0)")
            msg += f" — {hint or 'the compiled path retraced'}"
            raise AssertionError(msg)


def counting(fn):
    """Wrap ``fn`` so each execution of its PYTHON BODY increments
    ``wrapper.trace_count`` — under ``jax.jit`` the body runs only when
    JAX stages the function, so the counter counts traces (the same
    convention every engine's built-in counter follows).  For test
    functions that have no engine counter:

        step = counting(lambda x: x * 2)
        jitted = jax.jit(step)
        with forbid_retrace(step):
            jitted(a); jitted(b)          # same shape: no retrace
    """
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        wrapper.trace_count += 1
        return fn(*args, **kwargs)
    wrapper.trace_count = 0
    return wrapper
