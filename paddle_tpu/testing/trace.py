"""Trace-count discipline, shared.

Three AOT surfaces make the same promise — warm-up traces the step's
Python body exactly once per compiled shape, and steady state never
retraces: ``SGD.precompile`` (trainer/trainer.py), the serving
``InferenceEngine.warmup`` bucket ladder, and the continuous-batching
``DecodeEngine`` slab step (serving/decode_engine.py).  Each keeps a
counter that increments ONLY inside the traced function's Python body
(so it moves iff JAX is staging the function); this module holds the one
assertion they all share, so the promise is phrased — and its failure
message reads — the same everywhere.
"""

import contextlib


@contextlib.contextmanager
def expect_traces(get_count, expected, what, hint=None):
    """Assert the wrapped block traces exactly ``expected`` times.

    ``get_count``: zero-arg callable returning the current trace counter
    (e.g. ``lambda: engine.trace_count``).  ``what`` names the operation
    for the failure message; ``hint`` (optional) names the likely cause.

        with expect_traces(lambda: tr.trace_count, 0,
                           "train() over precompiled buckets"):
            tr.train(...)
    """
    before = get_count()
    yield
    actual = get_count() - before
    if actual != expected:
        msg = (f"{what}: traced {actual} time(s) "
               f"(expected exactly {expected})")
        if hint:
            msg += f" — {hint}"
        raise AssertionError(msg)


def assert_no_retrace(get_count, what, hint="the compiled path retraced"):
    """``expect_traces(..., 0, ...)`` — the steady-state half of the
    discipline, named for readability at call sites."""
    return expect_traces(get_count, 0, what, hint=hint)
