"""Multi-process distributed bring-up worker (test fixture).

Run as `python -m paddle_tpu.testing.dist_worker OUT_DIR [options]` under
the PADDLE_TPU_* rendezvous env vars (parallel/distributed.py:12-18).
Each process connects through jax.distributed.initialize, builds a global
mesh over every process's devices, and trains a tiny model.  Every
process materializes the full (deterministically seeded) host batch and
jax.make_array_from_callback hands each device its addressable shard —
mesh-shape-agnostic, which the 2x2 data,model mode needs; the stricter
process-local-ingestion path (jax.make_array_from_process_local_data,
where a process never holds peers' data) is covered by
tests/test_parallel_matrix.py.  The final loss
and a parameter checksum are written to OUT_DIR/rank{i}.json so tests can
assert multi-process == single-process numerics (the reference proved its
distributed plane the same way: test_CompareSparse.cpp:66-87 trains
against in-process pservers and compares with local training).

Modes:
  --mesh data        1-axis data-parallel mesh over all devices (default)
  --mesh data,model  2x2 mesh: data axis AND model (tensor) axis both >1
                     with parameters sharded over `model` — the reference
                     distributed plane had the same two splits
                     (num_gradient_servers x parallel_nn model split)
  --mesh stage       GPipe pipeline across processes: each rank's device
                     owns one stage, the stage-to-stage ppermute rides
                     the inter-process transport
Failure/restart drill (the reference's fault story was pserver
checkpointing; here it's coordinator checkpoints + whole-job relaunch):
  --ckpt-dir D       rank 0 checkpoints params at step --ckpt-step;
                     on startup, if D holds a checkpoint, RESUME from it
  --crash-rank R --crash-step S   rank R calls os._exit(3) before
                     running step S (simulates a dying host mid-pass)
"""

import argparse
import json
import os
import sys


def _global_array(sharding, host_value):
    """Build a process-spanning global array from an identical-per-process
    host value: each device picks its addressable shard via the callback
    (mesh-shape-agnostic — works for data, tensor, and stage shardings)."""
    import jax
    return jax.make_array_from_callback(
        host_value.shape, sharding, lambda idx: host_value[idx])


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--mesh", default="data",
                    choices=["data", "data,model", "stage"])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--ckpt-step", type=int, default=10)
    ap.add_argument("--crash-rank", type=int, default=None)
    ap.add_argument("--crash-step", type=int, default=None)
    ap.add_argument("--trainer-sparse", action="store_true",
                    help="train the sparse-embedding model through the "
                         "REAL layers+SGD trainer API on the global mesh "
                         "(reference test_CompareSparse: multi-trainer "
                         "sparse vs local numerics)")
    args = ap.parse_args(argv)
    os.makedirs(args.out_dir, exist_ok=True)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    # a sitecustomize hook may pin jax_platforms to the TPU tunnel at
    # interpreter startup; the env var alone does not override it
    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.parallel import distributed as dist
    dist.init_distributed()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    nproc = jax.process_count()
    rank = jax.process_index()
    assert nproc == int(os.environ["PADDLE_TPU_NUM_PROCESSES"])

    if args.trainer_sparse:
        return _trainer_sparse(args, nproc, rank)

    devices = np.asarray(jax.devices())
    if args.mesh == "stage":
        return _pipeline_stage(args, nproc, rank, devices)
    if args.mesh == "data,model":
        assert devices.size % 2 == 0, \
            "data,model mesh needs an even device count"
        mesh = Mesh(devices.reshape(devices.size // 2, 2),
                    ("data", "model"))
        # tensor-parallel parameter layout: hidden dim split over `model`
        pspec = {"w1": P(None, "model"), "b1": P("model"),
                 "w2": P("model", None)}
    else:
        mesh = Mesh(devices, ("data",))
        pspec = {"w1": P(), "b1": P(), "w2": P()}
    param_sh = {k: NamedSharding(mesh, s) for k, s in pspec.items()}
    batch_sh = NamedSharding(mesh, P("data"))

    # identical init on every process (SPMD: same program, same params)
    rng = np.random.RandomState(0)
    init = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.5, jnp.float32),
        "b1": jnp.zeros((16,), jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 1) * 0.5, jnp.float32),
    }

    B, STEPS = 32, args.steps
    xs = rng.randn(STEPS, B, 8).astype(np.float32)
    ys = (xs[..., :3].sum(-1, keepdims=True) > 0).astype(np.float32)

    start_step = 0
    if args.ckpt_dir and os.path.isdir(args.ckpt_dir) \
            and any(n.startswith("pass-")
                    for n in os.listdir(args.ckpt_dir)):
        from paddle_tpu.trainer.checkpoint import load_checkpoint
        params_host, _opt, _ms, meta = load_checkpoint(args.ckpt_dir)
        init = {k: jnp.asarray(v) for k, v in params_host.items()}
        start_step = int(meta["step"])
        print(f"[dist_worker] rank {rank} resuming from step {start_step}",
              flush=True)

    # every process holds the full host value (deterministic seed /
    # checkpoint); _global_array shards it per device
    global_array = _global_array

    params = {k: global_array(param_sh[k], np.asarray(v))
              for k, v in init.items()}

    def loss_fn(p, x, y):
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        pred = jax.nn.sigmoid(h @ p["w2"])
        return jnp.mean((pred - y) ** 2)

    @jax.jit
    def step(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        p = jax.tree_util.tree_map(lambda w, gw: w - 0.5 * gw, p, g)
        return p, loss

    loss = first_loss = None
    for t in range(start_step, STEPS):
        if args.crash_rank == rank and args.crash_step == t:
            print(f"[dist_worker] rank {rank} CRASHING at step {t}",
                  flush=True)
            os._exit(3)
        x = global_array(batch_sh, xs[t])
        y = global_array(batch_sh, ys[t])
        params, loss = step(params, x, y)
        if first_loss is None:
            first_loss = float(loss)
        if args.ckpt_dir and t + 1 == args.ckpt_step:
            # replicate, then fetch: model-sharded params are not
            # rank-0-addressable, so rejit to P() makes every process hold
            # the full value; only rank 0 writes
            repl = NamedSharding(mesh, P())
            gather = jax.jit(lambda a: a, out_shardings=repl)
            host = {k: np.asarray(jax.device_get(gather(v)))
                    for k, v in params.items()}
            if rank == 0:
                from paddle_tpu.trainer.checkpoint import save_checkpoint
                save_checkpoint(args.ckpt_dir, 0, host,
                                extra={"step": t + 1})
            # nobody crosses the checkpoint boundary until it's on disk —
            # a crash after this barrier can always resume from it
            dist.barrier(f"ckpt{t}")

    dist.barrier("final")
    checksum = float(sum(jnp.sum(jnp.abs(v)) for v in
                         jax.tree_util.tree_leaves(params)))
    out = {"rank": rank, "nproc": nproc, "loss": float(loss),
           "first_loss": first_loss, "checksum": checksum,
           "global_devices": jax.device_count(),
           "mesh": args.mesh, "start_step": start_step,
           "coordinator": dist.is_coordinator()}
    with open(os.path.join(args.out_dir, f"rank{rank}.json"), "w") as f:
        json.dump(out, f)
    print(f"[dist_worker] rank {rank}/{nproc} loss={out['loss']:.6f} "
          f"checksum={checksum:.6f}", flush=True)


def _pipeline_stage(args, nproc, rank, devices):
    """Pipeline parallelism ACROSS PROCESSES: each rank's device owns one
    GPipe stage; the stage-to-stage ppermute rides the inter-process
    transport.  The test compares against an in-process sequential run of
    the same blocks (the reference's config-pair equivalence discipline)."""
    import json as _json
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel import distributed as dist
    from paddle_tpu.parallel.pipeline import (gpipe, microbatch,
                                              unmicrobatch)

    mesh = Mesh(devices, ("stage",))
    s = devices.size
    rng = np.random.RandomState(0)
    host_stacked = {
        "w": np.stack([rng.randn(8, 8).astype(np.float32) * 0.4
                       for _ in range(s)]),
        "b": np.zeros((s, 8), np.float32)}
    B, STEPS = 16, args.steps
    xs = rng.randn(STEPS, B, 8).astype(np.float32)
    ys = np.tanh(rng.randn(STEPS, B, 8)).astype(np.float32)

    ga = _global_array
    psh = {k: NamedSharding(mesh, P("stage")) for k in host_stacked}
    repl = NamedSharding(mesh, P())
    params = {k: ga(psh[k], v) for k, v in host_stacked.items()}

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    @jax.jit
    def step(sp, x, y):
        def loss_fn(sp):
            out = unmicrobatch(gpipe(stage_fn, sp, microbatch(x, 4),
                                     mesh=mesh))
            return jnp.mean((out - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(sp)
        return jax.tree_util.tree_map(
            lambda w, gw: w - 0.3 * gw, sp, g), loss

    loss = first_loss = None
    for t in range(STEPS):
        params, loss = step(params, ga(repl, xs[t]), ga(repl, ys[t]))
        if first_loss is None:
            first_loss = float(loss)

    dist.barrier("final")
    checksum = float(sum(jnp.sum(jnp.abs(v)) for v in
                         jax.tree_util.tree_leaves(params)))
    out = {"rank": rank, "nproc": nproc, "loss": float(loss),
           "first_loss": first_loss, "checksum": checksum,
           "global_devices": jax.device_count(), "mesh": args.mesh,
           "start_step": 0, "coordinator": dist.is_coordinator()}
    with open(os.path.join(args.out_dir, f"rank{rank}.json"), "w") as f:
        _json.dump(out, f)
    print(f"[dist_worker] rank {rank}/{nproc} pipeline loss="
          f"{out['loss']:.6f} checksum={checksum:.6f}", flush=True)


def _trainer_sparse(args, nproc, rank):
    """The user-facing path at multi-process scale: layers DSL model with a
    sparse_update embedding trained through trainer.SGD(mesh=global mesh).
    Deterministic batches (same stream every process — SPMD); final cost +
    parameter checksums land in rank{i}.json for the numerics compare."""
    import json as _json
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import paddle_tpu.layers as L
    from paddle_tpu import optim
    from paddle_tpu.core.sequence import pad_sequences
    from paddle_tpu.layers.graph import reset_names
    from paddle_tpu.parallel import distributed as dist
    from paddle_tpu.trainer.trainer import SGD
    from paddle_tpu.trainer import events

    vocab, emb_dim, b, t = 64, 8, 8, 5
    reset_names()
    w = L.data_layer("w", size=vocab, is_seq=True)
    emb = L.embedding_layer(w, size=emb_dim, sparse_update=True,
                            param_attr={"initial_std": 0.1, "name": "emb"})
    pooled = L.pooling_layer(emb, pooling_type="sum")
    out = L.fc_layer(pooled, size=2, act="softmax",
                     param_attr={"initial_std": 0.1, "name": "fc"})
    lab = L.data_layer("lab", size=1)
    cost = L.classification_cost(input=out, label=lab)

    rng = np.random.RandomState(5)
    batches = []
    for _ in range(12):
        seqs = [rng.randint(0, vocab, (rng.randint(2, t + 1),))
                for _ in range(b)]
        # learnable labels ("does any low token appear") so the test can
        # assert progress, not just numerics agreement
        labs = np.asarray([[int((s < vocab // 4).any())] for s in seqs],
                          np.int32)
        batches.append({"w": pad_sequences(seqs, max_len=t), "lab": labs})

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    tr = SGD(cost=cost, update_equation=optim.Momentum(learning_rate=0.1,
                                                       momentum=0.0),
             mesh=mesh, seed=3, donate=False)
    costs = []
    # the cross-rank straggler report fires once per PASS END (over all
    # 12 batches' step times); exported below for the test to assert on
    tr.train(lambda: iter(batches), num_passes=2, log_period=6,
             event_handler=lambda e: costs.append(float(e.cost))
             if isinstance(e, events.EndIteration) else None)

    dist.barrier("final")
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

    def subtree_checksum(key):
        leaves = jax.tree_util.tree_leaves(tr.parameters[key])
        total = 0.0
        for v in leaves:
            g = jax.device_get(jax.jit(lambda a: a, out_shardings=repl)(v))
            total += float(np.abs(g).sum())
        return total

    out_rec = {"rank": rank, "nproc": nproc,
               "loss": costs[-1], "first_loss": costs[0],
               "emb_checksum": subtree_checksum("emb"),
               "fc_checksum": subtree_checksum("fc"),
               "global_devices": jax.device_count(),
               "skew_report": tr.last_skew_report,
               "mode": "trainer-sparse"}
    with open(os.path.join(args.out_dir, f"rank{rank}.json"), "w") as f:
        _json.dump(out_rec, f)
    print(f"[dist_worker] trainer-sparse rank {rank}/{nproc} "
          f"loss={costs[-1]:.6f}", flush=True)


if __name__ == "__main__":
    main()
