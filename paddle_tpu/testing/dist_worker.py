"""Multi-process distributed bring-up worker (test fixture).

Run as `python -m paddle_tpu.testing.dist_worker OUT_DIR` under the
PADDLE_TPU_* rendezvous env vars (parallel/distributed.py:12-18).  Each
process connects through jax.distributed.initialize, builds a global mesh
over every process's devices, and trains a tiny data-parallel model where
each process feeds ONLY its own shard of the global batch — the
multi-controller SPMD shape of a real multi-host TPU job.  The final loss
and a parameter checksum are written to OUT_DIR/rank{i}.json so the test
can assert 2-process == 1-process numerics (the reference proved its
distributed plane the same way: test_CompareSparse.cpp:66-87 trains
against in-process pservers and compares with local training).
"""

import json
import os
import sys


def main(out_dir):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    # a sitecustomize hook may pin jax_platforms to the TPU tunnel at
    # interpreter startup; the env var alone does not override it
    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.parallel import distributed as dist
    dist.init_distributed()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    nproc = jax.process_count()
    rank = jax.process_index()
    assert nproc == int(os.environ["PADDLE_TPU_NUM_PROCESSES"])

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("data"))

    # identical init on every process (replicated params)
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.5, jnp.float32),
        "b1": jnp.zeros((16,), jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 1) * 0.5, jnp.float32),
    }
    params = jax.device_put(params, repl)

    B, STEPS = 32, 20
    xs = rng.randn(STEPS, B, 8).astype(np.float32)
    ys = (xs[..., :3].sum(-1, keepdims=True) > 0).astype(np.float32)

    def loss_fn(p, x, y):
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        pred = jax.nn.sigmoid(h @ p["w2"])
        return jnp.mean((pred - y) ** 2)

    @jax.jit
    def step(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        p = jax.tree_util.tree_map(lambda w, gw: w - 0.5 * gw, p, g)
        return p, loss

    per = B // nproc
    loss = first_loss = None
    for t in range(STEPS):
        # each process contributes ONLY its slice of the global batch
        lo = rank * per
        x = jax.make_array_from_process_local_data(
            shard, xs[t, lo:lo + per], (B, 8))
        y = jax.make_array_from_process_local_data(
            shard, ys[t, lo:lo + per], (B, 1))
        params, loss = step(params, x, y)
        if first_loss is None:
            first_loss = float(loss)

    dist.barrier("final")
    checksum = float(sum(jnp.sum(jnp.abs(v)) for v in
                         jax.tree_util.tree_leaves(params)))
    out = {"rank": rank, "nproc": nproc, "loss": float(loss),
           "first_loss": first_loss, "checksum": checksum,
           "global_devices": jax.device_count(),
           "coordinator": dist.is_coordinator()}
    with open(os.path.join(out_dir, f"rank{rank}.json"), "w") as f:
        json.dump(out, f)
    print(f"[dist_worker] rank {rank}/{nproc} loss={out['loss']:.6f} "
          f"checksum={checksum:.6f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
