"""Extract XLA's cost model + an HLO op histogram from a compiled step.

Works on any backend: `jax.jit(step).lower(*args).compile()` never
executes the program, so the CPU backend yields the structural numbers
(FLOPs, bytes accessed, op mix, fusion count) even when the TPU is
wedged.  The histogram is parsed from the post-optimization HLO text —
the same program XLA would schedule — so a change that de-fuses a kernel
or splits a matmul shows up as op-count / bytes deltas here before any
chip ever times it.
"""

import collections
import re

_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%\S+ = (.*)$")
# op name = first bare identifier followed by '(' after the result type.
# Non-tuple types ("f32[128,512]{1,0}") are one whitespace-free token;
# tuple types start with '(' and are skipped by paren balancing below.
_OP_RE = re.compile(r"^\S*\s+([a-z][a-z0-9\-]*)\(")

# bookkeeping pseudo-ops: structurally meaningless for a regression diff
# (parameter count changes with donation plumbing, constants with literal
# folding) — kept OUT of the histogram so diffs track real work.
_SKIP_OPS = frozenset({"parameter", "constant"})


def _op_of(rhs):
    """HLO opcode of one instruction's right-hand side."""
    if rhs.startswith("("):          # tuple-typed result: skip balanced ()
        depth = 0
        for i, ch in enumerate(rhs):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    rhs = rhs[i + 1:].lstrip()
                    break
        m = re.match(r"([a-z][a-z0-9\-]*)\(", rhs)
        return m.group(1) if m else None
    m = _OP_RE.match(rhs)
    return m.group(1) if m else None


def op_histogram(hlo_text):
    """{opcode: count} over every instruction in the HLO module text."""
    hist = collections.Counter()
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        op = _op_of(m.group(1))
        if op and op not in _SKIP_OPS:
            hist[op] += 1
    return dict(sorted(hist.items()))


def normalize_cost_analysis(ca):
    """compiled.cost_analysis() returns a dict or a 1-list of dicts
    depending on jax version; normalize to one flat dict."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def extract(compiled):
    """Structural cost record for one compiled executable.

    Keys: flops, bytes_accessed, transcendentals, arithmetic_intensity,
    hlo_op_histogram, hlo_op_total, fusion_count, dot_count,
    convolution_count.  All pure numbers / plain dicts — JSON-ready.
    """
    ca = normalize_cost_analysis(compiled.cost_analysis())
    hist = op_histogram(compiled.as_text())
    flops = float(ca.get("flops", 0.0))
    bytes_accessed = float(ca.get("bytes accessed", 0.0))
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "transcendentals": float(ca.get("transcendentals", 0.0)),
        "arithmetic_intensity": (flops / bytes_accessed)
        if bytes_accessed else None,
        "hlo_op_histogram": hist,
        "hlo_op_total": sum(hist.values()),
        "fusion_count": hist.get("fusion", 0),
        "dot_count": hist.get("dot", 0),
        "convolution_count": hist.get("convolution", 0),
    }
