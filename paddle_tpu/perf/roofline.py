"""Roofline model: (FLOPs, bytes accessed) -> predicted step time / MFU.

The classic two-ceiling roofline (Williams et al.): a step whose
arithmetic intensity (FLOPs per HBM byte) sits below the chip's ridge
point is bandwidth-bound, above it compute-bound; predicted time is

    t = max(flops / peak_flops, bytes / hbm_bw)

and predicted MFU = (flops / peak_flops) / t = min(1, intensity/ridge).
This is an UPPER BOUND on achievable MFU — it assumes perfect overlap of
compute and HBM traffic and ignores per-step dispatch overhead, so tiny
steps (SmallNet at 2 ms/batch) will measure well below their prediction.
The bytes input comes from XLA's cost model on whatever backend compiled
the program (the CPU backend in the no-chip-window case), so it reflects
f32 traffic unless the program itself casts; on TPU the auto bf16 policy
roughly halves matmul operand bytes — the prediction is conservative for
bandwidth-bound families.

Spec sources: public TPU system spec sheets / the jax-ml scaling book;
the v5e peak matches bench.py's `_PEAK_TFLOPS` table so measured MFU and
predicted MFU share a denominator.
"""

import dataclasses


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_flops: float       # dense bf16 FLOP/s (f32 for the cpu row)
    hbm_bytes_per_s: float  # HBM (DRAM for cpu) bandwidth, bytes/s
    # host<->device link (PCIe) bandwidth: the third roofline ceiling
    # the hierarchical KV tier lives under (a restore streams spilled
    # bytes over THIS link instead of recomputing over HBM+MXU).  The
    # public TPU spec sheets don't quote it; PCIe Gen3 x16 (~16 GB/s
    # effective) is the conservative fleet floor, so restore-vs-
    # recompute routing errs toward recompute.
    host_link_bytes_per_s: float = 16e9

    @property
    def ridge_intensity(self):
        """FLOPs/byte where the roofline's two ceilings meet."""
        return self.peak_flops / self.hbm_bytes_per_s


# Keyed by the short names the snapshot JSON uses.  The cpu row is a
# sanity anchor only (one NUMA node, AVX-512 class) — wall-clock on the
# shared CI hosts is far noisier than the TPU rows.
SPECS = {
    "v5e": ChipSpec("TPU v5e", 197e12, 819e9),
    "v5p": ChipSpec("TPU v5p", 459e12, 2765e9),
    "v4": ChipSpec("TPU v4", 275e12, 1228e9),
    "cpu": ChipSpec("cpu (sanity anchor)", 1e11, 50e9),
}


def predict(flops, bytes_accessed, spec):
    """Roofline prediction for one compiled step on one chip spec.

    Returns a dict with compute_ms / memory_ms (the two ceilings),
    predicted_ms (their max), predicted_mfu, the step's arithmetic
    intensity vs the chip's ridge point, and the named bottleneck.
    """
    if isinstance(spec, str):
        spec = SPECS[spec]
    if flops < 0 or bytes_accessed < 0:
        raise ValueError("flops/bytes_accessed must be non-negative")
    compute_s = flops / spec.peak_flops
    memory_s = bytes_accessed / spec.hbm_bytes_per_s
    t = max(compute_s, memory_s)
    intensity = (flops / bytes_accessed) if bytes_accessed else float("inf")
    return {
        "chip": spec.name,
        "compute_ms": compute_s * 1e3,
        "memory_ms": memory_s * 1e3,
        "predicted_ms": t * 1e3,
        "predicted_mfu": (compute_s / t) if t > 0 else 0.0,
        "arithmetic_intensity": intensity,
        "ridge_intensity": spec.ridge_intensity,
        "bottleneck": "compute" if compute_s >= memory_s else "memory",
    }
