"""Analytic bench runner: cost + roofline snapshot for every bench family.

For each family in bench.py the factory's AOT hook (`extras["lower"]`,
a zero-arg callable returning the jitted step's `jax.stages.Lowered`) is
compiled on the CURRENT backend — the CPU backend when no TPU answers —
and fed through `perf.cost.extract` and `perf.roofline.predict`.  The
result is one JSON snapshot (`BENCH_ANALYTIC_r06.json`) holding, per
family: XLA-model FLOPs, bytes accessed, arithmetic intensity, the HLO
op histogram / fusion count, and the v5e-roofline predicted step time,
predicted MFU and named bottleneck.  No program is ever executed, so a
wedged chip cannot block the snapshot ("no chip window -> partial
evidence").

`scripts/perf_report.py --analytic-diff old.json new.json` diffs two
snapshots structurally and exits non-zero when a change de-fuses a step
or inflates bytes-accessed beyond threshold (see `analytic_diff` there).

Usage:
  python bench.py --analytic [--families a,b] [--out PATH]
  python -m paddle_tpu.perf.analytic [...]
  python -m paddle_tpu.scripts.bench_sweep --analytic   (same snapshot)
"""

import argparse
import gc
import json
import os
import sys
import time

from paddle_tpu.perf import cost, roofline

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_OUT = os.path.join(_REPO, "BENCH_ANALYTIC_r06.json")

# The family registry moved to paddle_tpu/analysis/roots.py — ONE list
# shared with the static invariant analyzer, so a new bench family
# cannot add a jitted step the analyzer doesn't see (FAMILY_ROOTS maps
# every family to the jit roots its extras["lower"] traces; the drift
# test in tests/test_analysis.py keeps registry and code joined).  The
# name stays importable from here for every existing consumer
# (scripts/perf_report.py, tests/test_perf_analytic.py).
from paddle_tpu.analysis.roots import FAMILIES  # noqa: E402,F401


def _log(msg):
    print(f"[analytic] {msg}", file=sys.stderr, flush=True)


# ----------------------------------------------------- fusion-proof gate

def chain_buffer_instrs(hlo_text, num_rows, t_span, dkv):
    """Instructions whose RESULT materializes a full-chain KV buffer —
    the PR-3 de-fusion detector run in REVERSE.

    The reference paged-decode step gathers every row's block chain into
    a contiguous ``[S, blocks_per_row, bs, Dkv]`` HBM buffer (and its
    ``[S, T, Dkv]`` reshape) before attending; the fused Pallas kernel
    walks the block table in place and that buffer must not exist.  An
    instruction matches when its result shape leads with ``num_rows``
    and holds exactly ``num_rows * t_span * dkv`` elements — the chain
    buffer's signature under any dim factoring (the per-layer block
    POOL never matches: it leads with num_blocks, not S).  Returns the
    offending instruction lines (empty = fusion proven).
    """
    import re
    from paddle_tpu.perf import cost as _cost
    target = int(num_rows) * int(t_span) * int(dkv)
    shape_re = re.compile(r"\b[a-z][a-z0-9]*\[([0-9,]+)\]")
    hits = []
    for line in hlo_text.splitlines():
        m = _cost._INSTR_RE.match(line)
        if not m:
            continue
        rhs = m.group(1)
        op = _cost._op_of(rhs)
        if op is None or op in _cost._SKIP_OPS:
            continue
        # result type: the leading whitespace-free token, or the
        # balanced-paren tuple type for multi-result instructions
        if rhs.startswith("("):
            depth, ty = 0, rhs
            for i, ch in enumerate(rhs):
                depth += ch == "("
                depth -= ch == ")"
                if depth == 0:
                    ty = rhs[:i + 1]
                    break
        else:
            ty = rhs.split(None, 1)[0]
        for dims in shape_re.findall(ty):
            shape = [int(d) for d in dims.split(",")]
            n = 1
            for d in shape:
                n *= d
            if shape and shape[0] == int(num_rows) and n == target:
                hits.append(line.strip())
                break
    return hits


def score_matrix_instrs(hlo_text, tq, tk):
    """Instructions whose RESULT materializes an attention SCORE matrix:
    a float-typed buffer whose trailing two dims are exactly
    ``(tq, tk)`` — ``[.., Tp, Tp]`` for the batched causal prefill,
    ``[.., K, T]`` for the unified chunked step's reference path.  The
    flash/chunk kernels compute scores block-by-block in VMEM, so with
    them engaged NO such buffer may exist in the HLO (and the reference
    path must trip this same detector — the gate is tested in reverse).
    Returns the offending instruction lines (empty = proven)."""
    import re
    from paddle_tpu.perf import cost as _cost
    shape_re = re.compile(r"\b(f32|bf16|f16|f64)\[([0-9,]+)\]")
    hits = []
    for line in hlo_text.splitlines():
        m = _cost._INSTR_RE.match(line)
        if not m:
            continue
        rhs = m.group(1)
        op = _cost._op_of(rhs)
        if op is None or op in _cost._SKIP_OPS:
            continue
        if rhs.startswith("("):
            depth, ty = 0, rhs
            for i, ch in enumerate(rhs):
                depth += ch == "("
                depth -= ch == ")"
                if depth == 0:
                    ty = rhs[:i + 1]
                    break
        else:
            ty = rhs.split(None, 1)[0]
        for _dt, dims in shape_re.findall(ty):
            shape = [int(d) for d in dims.split(",")]
            if len(shape) >= 2 and shape[-2] == int(tq) \
                    and shape[-1] == int(tk):
                hits.append(line.strip())
                break
    return hits


def assert_prefill_flash(hlo_text, tp):
    """Raise AssertionError when a batched causal prefill HLO still
    materializes the ``[Tp, Tp]`` score matrix (the flash routing was
    supposed to be ON)."""
    hits = score_matrix_instrs(hlo_text, tp, tp)
    if hits:
        raise AssertionError(
            f"prefill materializes a [{tp}, {tp}] score matrix — the "
            f"flash routing did not engage:\n  " + "\n  ".join(hits[:4]))


def assert_decode_fused(hlo_text, num_rows, t_span, dkv):
    """Raise AssertionError when the paged-decode HLO still materializes
    the full-chain gather buffer (kernels were supposed to be ON)."""
    hits = chain_buffer_instrs(hlo_text, num_rows, t_span, dkv)
    if hits:
        raise AssertionError(
            f"paged decode step materializes a full-chain "
            f"[{num_rows}, {t_span}, {dkv}]-element KV buffer — the "
            f"fused kernel did not engage:\n  " + "\n  ".join(hits[:4]))


# -------------------------------------------------- quantized-serving gates

def widened_kv_instrs(hlo_text, num_rows, t_span, dkv):
    """Instructions whose RESULT materializes a widened (FLOAT) full
    KV view of an int8 cache: a float-typed buffer leading with
    ``num_rows`` and holding exactly ``num_rows * t_span * dkv``
    elements.  The int8-KV reference path dequantizes the whole
    gathered stripe into exactly such a buffer before attending; the
    fused kernels widen block-by-block in registers, so with them
    engaged NO such buffer may exist.  (The int8 cache itself never
    matches: the dtype filter is float-only, and the paged pool leads
    with num_blocks, not S.)  Returns the offending lines."""
    import re
    from paddle_tpu.perf import cost as _cost
    target = int(num_rows) * int(t_span) * int(dkv)
    shape_re = re.compile(r"\b(f32|bf16|f16|f64)\[([0-9,]+)\]")
    hits = []
    for line in hlo_text.splitlines():
        m = _cost._INSTR_RE.match(line)
        if not m:
            continue
        rhs = m.group(1)
        op = _cost._op_of(rhs)
        if op is None or op in _cost._SKIP_OPS:
            continue
        if rhs.startswith("("):
            depth, ty = 0, rhs
            for i, ch in enumerate(rhs):
                depth += ch == "("
                depth -= ch == ")"
                if depth == 0:
                    ty = rhs[:i + 1]
                    break
        else:
            ty = rhs.split(None, 1)[0]
        for _dt, dims in shape_re.findall(ty):
            shape = [int(d) for d in dims.split(",")]
            n = 1
            for d in shape:
                n *= d
            if shape and shape[0] == int(num_rows) and n == target:
                hits.append(line.strip())
                break
    return hits


def assert_kv_quantized(hlo_text, num_rows, t_span, dkv):
    """Raise AssertionError when an int8-KV decode HLO still widens the
    whole cache into a float [num_rows, t_span, dkv]-element buffer
    (the kernels were supposed to dequantize in registers)."""
    hits = widened_kv_instrs(hlo_text, num_rows, t_span, dkv)
    if hits:
        raise AssertionError(
            f"int8-KV decode step materializes a widened float "
            f"[{num_rows}, {t_span}, {dkv}]-element KV buffer — the "
            f"in-register dequant did not engage:\n  "
            + "\n  ".join(hits[:4]))


def widened_prefill_kv_instrs(hlo_text, b, tp, dkv):
    """``convert`` instructions that widen the WHOLE just-quantized
    prefill cache back to float: an f32-result convert with an s8
    operand holding exactly ``b * tp * dkv`` elements and leading with
    ``b``.  The int8-KV reference prefill dequantizes each layer's full
    K and V set (``_kv_view``) into exactly such a buffer before
    attending; ``flash_attention_quant`` widens int8 blocks in
    registers, so with it engaged NO such convert may exist.  (The
    quantize direction never matches — those converts RESULT in s8; the
    in-kernel interpret-mode converts never match — they are
    block-shaped, leading with 1, holding blk_k * dh < b * tp * dkv
    elements.)  Returns the offending lines."""
    import re
    from paddle_tpu.perf import cost as _cost
    target = int(b) * int(tp) * int(dkv)
    shape_re = re.compile(r"^f32\[([0-9,]+)\]")
    hits = []
    for line in hlo_text.splitlines():
        m = _cost._INSTR_RE.match(line)
        if not m:
            continue
        rhs = m.group(1)
        if _cost._op_of(rhs) != "convert" or "s8[" not in rhs:
            continue
        sm = shape_re.match(rhs)
        if not sm:
            continue
        shape = [int(d) for d in sm.group(1).split(",")]
        n = 1
        for d in shape:
            n *= d
        if shape[0] == int(b) and n == target:
            hits.append(line.strip())
    return hits


def assert_prefill_kv_quantized(hlo_text, b, tp, dkv):
    """Raise AssertionError when an int8-KV batched prefill HLO still
    widens the whole per-layer cache into a float [b, tp, dkv]-element
    buffer (``flash_attention_quant`` was supposed to stream the int8
    bytes and widen block-by-block in registers)."""
    hits = widened_prefill_kv_instrs(hlo_text, b, tp, dkv)
    if hits:
        raise AssertionError(
            f"int8-KV prefill widens the whole cache into float "
            f"[{b}, {tp}, {dkv}]-element buffers before attending — "
            f"the quantized flash prefill did not engage:\n  "
            + "\n  ".join(hits[:4]))


def entry_param_types(hlo_text):
    """(dtype, dims-tuple) of every ENTRY parameter, parsed from the
    module's ``entry_computation_layout`` — the program's resident
    interface (what is fed and carried between steps)."""
    import re
    m = re.search(r"entry_computation_layout=\{\((.*?)\)->", hlo_text,
                  re.S)
    if not m:
        return []
    out = []
    for dt, dims in re.findall(r"([a-z][a-z0-9]*)\[([0-9,]*)\]",
                               m.group(1)):
        out.append((dt, tuple(int(d) for d in dims.split(",") if d)))
    return out


def assert_weights_quantized(hlo_text, weight_shapes, float_shapes=()):
    """Raise AssertionError unless every quantized weight enters the
    compiled step as an s8 ENTRY PARAMETER and no EXTRA float parameter
    of that shape exists — i.e. no fp32 (or bf16) weight copy is ever
    RESIDENT across steps; the dequantized view lives only inside the
    step, fused into each consuming matmul's operand read on TPU.
    COUNT-based per shape: ``weight_shapes``
    (quant.weights.quantized_weight_shapes) sets how many s8 params a
    shape needs, and ``float_shapes``
    (quant.weights.float_leaf_shapes) allows the tree's legitimate
    float leaves — so a non-weight f32 param whose shape collides with
    a quantized weight's (e.g. the positional table vs an FFN weight
    at max_len == dff) never reads as a widened copy.  The fp32 twin
    step must FAIL this gate (its weights enter f32, no s8 params) —
    the reverse test the serving_quant postcheck runs."""
    import collections
    params = entry_param_types(hlo_text)
    s8 = collections.Counter(dims for dt, dims in params if dt == "s8")
    fl = collections.Counter(dims for dt, dims in params
                             if dt in ("f32", "bf16", "f16", "f64"))
    need = collections.Counter(tuple(int(d) for d in s)
                               for s in weight_shapes)
    allow = collections.Counter(tuple(int(d) for d in s)
                                for s in float_shapes)
    for shape, n in need.items():
        if s8[shape] < n:
            raise AssertionError(
                f"only {s8[shape]} of {n} quantized weights of shape "
                f"{list(shape)} enter the step as s8 parameters — the "
                "int8 tree was not threaded through")
        if fl[shape] > allow[shape]:
            raise AssertionError(
                f"{fl[shape]} float parameter(s) of quantized-weight "
                f"shape {list(shape)} exist but only {allow[shape]} "
                "float leaf(s) of that shape are in the tree — a "
                "widened weight copy is being fed to the step")


def predicted_decode_step_bytes(params, s, t_span, num_heads,
                                kv_dtype="float32"):
    """First-principles HBM traffic of ONE serving decode step — the
    quantized-serving bytes model (the XLA-CPU cost model cannot show
    the int8 win: it materializes the dequant converts the TPU backend
    fuses into the MXU/kernel operand reads, so like PR 10's fused-
    kernel row the prediction composes declared traffic instead).

    Terms, each read/written exactly once per step on the memory-bound
    path: every trunk weight as STORED (int8 data + f32 scales for a
    quantized tree — quant.weights.param_bytes), each of the S rows'
    K/V stripe streamed once per layer (the fused kernels' declared
    stream, including the int8 scale sidecar), one position's K/V
    written per row per layer, the inter-layer activations, and the
    token-ids-in / logits-out io.  Returns the byte total; the
    serving_quant postcheck gates int8 vs f32 at >= 35% reduction."""
    from paddle_tpu.quant import kv as kvq
    from paddle_tpu.quant import weights as qw
    enc = params["enc"]
    layers = len(enc)
    vocab, d = qw.weight_shape(params["src_emb"])
    dkv = qw.weight_shape(enc[0]["attn"]["wk"])[1]
    hkv = dkv // (d // num_heads)
    kv_isz = 1 if kv_dtype == "int8" else 4
    sidecar = 2 * s * t_span * hkv * 4 if kv_dtype == "int8" else 0
    kv_read = layers * (2 * s * t_span * dkv * kv_isz + sidecar)
    kv_write = layers * s * kvq.kv_bytes_per_position(dkv, hkv, kv_dtype)
    acts = layers * 2 * s * d * 4          # residual stream in/out
    io = s * 4 + s * vocab * 4             # ids in, logits out
    return qw.param_bytes(params) + kv_read + kv_write + acts + io


def predicted_prefill_bytes(params, b, tp, num_heads,
                            kv_dtype="float32"):
    """First-principles HBM traffic of ONE batched causal prefill of
    ``b`` prompts x ``tp`` positions — the serving_quant_prefill bytes
    model, ``predicted_decode_step_bytes``'s ingestion-side twin.

    Terms: every trunk weight as STORED (int8 data + f32 scales for a
    quantized tree), each layer's freshly written K/V set streamed back
    through attention once per QUERY head (the flash kernels' declared
    stream — GQA re-reads the kv head's stripe per group member; int8
    streams 1 byte/value + the f32 per-(position, head) scale sidecar
    per block row, f32 streams 4), the per-position K/V cache write as
    stored, the inter-layer activations, and the ids-in / hidden-out
    io.  The int8 win the >= 35% acceptance bar gates: the attention
    re-stream — the term that grows with Tp^0 * heads — drops ~4x, and
    the cache write drops ~4x, while weights (int8 tree) drop ~4x too.
    (The XLA-CPU cost model cannot show any of this: it materializes
    the widened converts the quant kernel keeps in registers.)"""
    from paddle_tpu.quant import kv as kvq
    from paddle_tpu.quant import weights as qw
    enc = params["enc"]
    layers = len(enc)
    _vocab, d = qw.weight_shape(params["src_emb"])
    dkv = qw.weight_shape(enc[0]["attn"]["wk"])[1]
    dh = d // num_heads
    hkv = dkv // dh
    # per query head, per position: int8 value bytes + the f32 scale
    # rides the same block stream (flash_attention_quant CostEstimate)
    per_pos = (dh * 1 + 4) if kv_dtype == "int8" else dh * 4
    kv_stream = layers * 2 * b * num_heads * tp * per_pos
    kv_write = layers * b * tp * kvq.kv_bytes_per_position(
        dkv, hkv, kv_dtype)
    acts = layers * 2 * b * tp * d * 4     # residual stream in/out
    io = b * tp * 4 + b * tp * d * 4       # ids in, hidden out
    return qw.param_bytes(params) + kv_stream + kv_write + acts + io


def predicted_spec_bytes_per_token(layers, d, dff, vocab, s, t_span,
                                   num_heads, draft_layers, k,
                                   acceptance, dkv=None):
    """First-principles HBM traffic per EMITTED token, speculative vs
    plain decode — the serving_speculative bytes model (docs/serving.md
    "Speculative decoding").  Returns ``(spec, nonspec)`` byte totals.

    The target's verify step streams each row's K/V stripe ONCE no
    matter how many query lanes ride it (the Tq=chunk kernels —
    ``kernel_cost(tq=k+1)`` differs from ``tq=1`` only by the extra
    q/o lanes and the all-lanes vocab projection), so verifying k
    drafts costs nearly the same bytes as decoding one token.  The
    draft rollout is the price: k sequential passes, each streaming
    the draft's weights and its own K/V.  With expected emitted tokens
    ``E = sum(a^i, i=0..k) = (1 - a^(k+1)) / (1 - a)`` per verify
    step, spec wins iff ``(target_step + k * draft_pass) / E <
    target_step`` — a cheap-enough draft and a real acceptance rate,
    which is why the adversarial direction (a = 0, E = 1) must predict
    a REGRESSION: the model is gated in both directions by the
    serving_speculative postcheck."""
    from paddle_tpu.ops.pallas.decode_attention import kernel_cost
    dkv = d if dkv is None else dkv

    def weight_bytes(n_layers, with_embed=True):
        trunk = n_layers * (4 * d * d + 2 * d * dff + 9 * d) * 4
        emb = (2 * vocab * d + t_span * d + 2 * d) * 4 if with_embed \
            else 0
        return trunk + emb

    def step_bytes(n_layers, tq, vocab_lanes):
        attn = n_layers * kernel_cost(s, t_span, d, dkv,
                                      tq=tq).bytes_accessed
        kv_write = n_layers * 2 * s * tq * dkv * 4
        acts = n_layers * 2 * s * tq * d * 4
        io = s * tq * 4 + s * vocab_lanes * vocab * 4
        return weight_bytes(n_layers) + attn + kv_write + acts + io

    a = min(max(float(acceptance), 0.0), 1.0 - 1e-9)
    emitted = (1.0 - a ** (k + 1)) / (1.0 - a)
    verify = step_bytes(layers, k + 1, k + 1)
    draft = k * step_bytes(draft_layers, 1, 1)
    nonspec = step_bytes(layers, 1, 1)
    return (verify + draft) / emitted, float(nonspec)


def predicted_sharded_step_bytes(layers, d, dff, vocab, s, t_span,
                                 num_heads, shards, dkv=None,
                                 kv_dtype="float32",
                                 weight_dtype="float32", chunk=1,
                                 replicate_weights=False):
    """First-principles PER-CHIP HBM traffic of one tensor-parallel
    chunked decode step — the serving_sharded bytes model
    (docs/serving.md "Sharded decode").  Returns a breakdown dict:
    ``total`` (per-chip bytes), ``weights``, ``kv``, ``acts_io``, and
    ``collective`` (the wire bytes of the gather seams).

    The sharding policy is ``parallel.sharding.lm_decode_param_specs``'s,
    priced term by term: wq/wk/wv shard their out-feature axis and
    src_emb its vocab axis (each chip streams 1/n of those weights);
    the K/V pool shards its trailing Dkv axis (1/n of the read/write
    stream per chip).  Everything bit-exactness forces to stay
    REPLICATED — wo, the FFN, LNs/biases, the positional table — is
    streamed in full on every chip: the model never pretends the whole
    step scales 1/n.  The collective term prices the seams honestly as
    ring traffic (in + out ~= 2 * (n-1)/n * payload per chip): one
    attention-output all-gather of [s, chunk, d] per layer, one logits
    all-gather of [s, vocab], one embedding psum of [s, chunk, d].

    ``replicate_weights=True`` is the adversarial twin: same mesh, same
    collectives, but every weight streamed in full on every chip — the
    serving_sharded postcheck requires THAT prediction to FAIL the
    reduction gate (weight replication must never look like a win), and
    ``shards=1`` collapses to the single-chip step (no collectives) the
    sharded prediction is gated against in the other direction."""
    n = max(1, int(shards))
    dkv = d if dkv is None else dkv
    hkv = dkv // (d // num_heads)
    wsz = 1 if weight_dtype == "int8" else 4
    # int8 weights carry a per-out-channel f32 scale; the scale shards
    # with its weight's out axis (the emb scale [1, d] is replicated)
    ssz = 4 if weight_dtype == "int8" else 0
    w_shard = layers * ((d * d + 2 * d * dkv) * wsz
                        + (d + 2 * dkv) * ssz) \
        + vocab * d * wsz + vocab * 0 * ssz
    w_repl = layers * ((d * d + 2 * d * dff) * wsz
                       + (d + 2 * dff) * ssz + 9 * d * 4) \
        + t_span * d * 4 + 2 * d * 4 + d * ssz
    if replicate_weights or n == 1:
        weights = w_shard + w_repl
    else:
        weights = w_shard / n + w_repl
    kv_isz = 1 if kv_dtype == "int8" else 4
    sidecar = 2 * s * t_span * hkv * 4 if kv_dtype == "int8" else 0
    kv_read = layers * (2 * s * t_span * dkv * kv_isz + sidecar)
    kv_write = layers * s * chunk * (2 * dkv * kv_isz
                                     + (2 * hkv * 4 if kv_isz == 1
                                        else 0))
    kv = (kv_read + kv_write) / n      # the pool ALWAYS shards its Dkv
    acts = layers * 2 * s * chunk * d * 4
    io = s * chunk * 4 + s * vocab * 4
    ring = 2.0 * (n - 1) / n if n > 1 else 0.0
    collective = ring * (layers * s * chunk * d * 4      # att gathers
                         + s * vocab * 4                 # logits gather
                         + s * chunk * d * 4)            # embed psum
    total = weights + kv + acts + io + collective
    return {"total": float(total), "weights": float(weights),
            "kv": float(kv), "acts_io": float(acts + io),
            "collective": float(collective)}


# ------------------------------------------------ hierarchical-KV model

# Scheduling cycles a host-tier restore spends off the device: the
# probe-and-claim admission pass that defers the request, the transfer
# landing between two steps, and the commit-and-reseat pass.  Priced in
# dispatch floors (below) — the restore never runs device compute.
RESTORE_CYCLES = 3
# Per-step host dispatch floor (ms): the irreducible Python/runtime cost
# of launching one jitted step, which the pure FLOPs/bytes roofline
# ignores.  Dominant for tiny chunk steps, noise for real trunks — which
# is exactly why a SHORT prefix should recompute (a couple of cheap
# chunk steps) while a LONG one should restore (dozens of steps vs one
# host-link stream).
STEP_DISPATCH_MS = 0.05


def predicted_restore_ms(covered, layers, dkv, kv_heads,
                         kv_dtype="float32", chip="v5e"):
    """First-principles wall cost of restoring a ``covered``-position
    spilled prefix chain from the host tier (docs/serving.md
    "Hierarchical KV"): the chain's serialized payload — int8 data plus
    f32 scale sidecars on a quantized engine
    (``quant.kv.kv_bytes_per_position``), times ``layers`` — streamed
    once over the host link (``ChipSpec.host_link_bytes_per_s``), plus
    ``RESTORE_CYCLES`` scheduling cycles at the dispatch floor.  The
    restore-vs-recompute router compares this against
    ``predicted_recompute_ms`` at the SAME chip spec; the
    serving_kv_spill postcheck gates the comparison in both
    directions."""
    from paddle_tpu.quant import kv as kvq
    spec = roofline.SPECS[chip] if isinstance(chip, str) else chip
    payload = float(covered) * int(layers) \
        * kvq.kv_bytes_per_position(dkv, kv_heads, kv_dtype)
    return RESTORE_CYCLES * STEP_DISPATCH_MS \
        + payload / spec.host_link_bytes_per_s * 1e3


# Effective socket bandwidth for a cross-replica KV handoff blob
# (serving/transfer.py).  Datacenter 25GbE at ~realistic goodput is the
# conservative fleet floor (loopback in the smoke is far faster), so
# the handoff-vs-recompute router errs toward recompute — same bias the
# host-link constant gives the local restore pair.
HANDOFF_LINK_BYTES_PER_S = 3e9
# Scheduling cycles a handoff spends beyond the restore's three: the
# source-side export waiting for its between-steps seam, and the HTTP
# round trip's request leg.
HANDOFF_CYCLES = RESTORE_CYCLES + 2


def predicted_handoff_ms(covered, layers, dkv, kv_heads,
                         kv_dtype="float32", chip="v5e"):
    """First-principles wall cost of HANDING OFF a ``covered``-position
    prefix chain from a peer replica (docs/serving.md "Disaggregated
    serving"): the same serialized payload as a local restore, streamed
    once over the handoff socket (``HANDOFF_LINK_BYTES_PER_S``) AND
    once over the receiver's host link, plus ``HANDOFF_CYCLES``
    scheduling cycles at the dispatch floor.  The receive path compares
    this against ``predicted_recompute_ms`` at the SAME chip spec
    before fetching anything — the serving_disagg postcheck gates the
    comparison in both directions, exactly as serving_kv_spill gates
    the local restore pair."""
    from paddle_tpu.quant import kv as kvq
    spec = roofline.SPECS[chip] if isinstance(chip, str) else chip
    payload = float(covered) * int(layers) \
        * kvq.kv_bytes_per_position(dkv, kv_heads, kv_dtype)
    return HANDOFF_CYCLES * STEP_DISPATCH_MS \
        + payload / HANDOFF_LINK_BYTES_PER_S * 1e3 \
        + payload / spec.host_link_bytes_per_s * 1e3


def predicted_recompute_ms(covered, param_count, param_bytes,
                           prefill_chunk, chip="v5e"):
    """First-principles wall cost of RECOMPUTING a ``covered``-position
    prefix through the unified chunked-prefill step: ``ceil(covered /
    (K-1))`` chunk steps, each streaming the trunk's stored weight
    bytes (``param_bytes`` — int8 data + scales on a quantized tree)
    and together spending ``2 * covered * param_count`` FLOPs, priced
    by the roofline's two ceilings plus the per-step dispatch floor.
    The companion term ``predicted_restore_ms`` replaces all of this
    with one host-link stream — long prefixes amortize the restore's
    fixed cycles over dozens of avoided chunk steps, short ones
    don't."""
    lanes = max(1, int(prefill_chunk) - 1)
    steps = -(-int(covered) // lanes)
    r = roofline.predict(2.0 * float(covered) * float(param_count),
                         float(steps) * float(param_bytes), chip)
    return steps * STEP_DISPATCH_MS + r["predicted_ms"]


def _import_bench():
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import bench
    return bench


# Families whose capture needs a multi-device host platform (the
# sharded-serving mesh).  XLA's CPU device count is fixed at backend
# init, and forcing it for the WHOLE snapshot perturbs every
# single-device family's HLO (the CPU backend re-partitions its thread
# pool per device — alexnet grows `call` ops under a 2-device flag), so
# when THIS process lacks the devices these families are captured in a
# subprocess that sets the flag for itself alone.
MESH_FAMILIES = {"serving_sharded": 2}


def _capture_subprocess(name, model, batch, devices):
    """Run one family's capture under a forced ``devices``-way host
    platform in a child ``bench.py --analytic`` process and return its
    row (an error row on any child failure — same isolation contract
    as ``capture``)."""
    import subprocess
    import tempfile
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count="
                        f"{devices}").strip()
    fd, out = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py"),
             "--analytic", "--families", name, "--out", out],
            env=env, capture_output=True, text=True, timeout=1800)
        with open(out) as f:
            snap = json.load(f)
        return snap["families"][name]
    except Exception as e:   # noqa: BLE001 — per-family isolation
        tail = ""
        try:
            tail = proc.stderr[-300:]
        except Exception:    # noqa: BLE001
            pass
        return {"model": model, "batch": batch,
                "error": f"mesh-capture subprocess failed: "
                         f"{type(e).__name__}: {e} {tail}"[:500]}
    finally:
        if os.path.exists(out):
            os.unlink(out)


def capture(name, model, batch=None, chips=("v5e", "v5p")):
    """Build one bench family, AOT-compile its step, extract cost +
    roofline rows.  Returns the snapshot row (with an "error" key instead
    of numbers if the family fails — partial evidence beats none)."""
    bench = _import_bench()
    factory, default_batch = bench._BENCHES[model]
    batch = int(batch if batch is not None else default_batch)
    t0 = time.perf_counter()
    # tell build-time-measuring factories (trainer_prefetch) that only the
    # AOT hook will be consumed — nothing may execute during the snapshot
    prev = os.environ.get("BENCH_ANALYTIC_BUILD")
    os.environ["BENCH_ANALYTIC_BUILD"] = "1"
    try:
        built = factory(batch)
        run, model_flops, _baseline, metric = built[:4]
        extras = built[4] if len(built) > 4 else {}
        lower = extras.get("lower")
        if lower is None:
            raise RuntimeError(f"bench family {model!r} exposes no "
                               "extras['lower'] AOT hook")
        compiled = lower().compile()
        # inside the isolation net: cost_analysis()/as_text() raise
        # Unimplemented on some backend/jax combinations (the documented
        # BENCH_PLATFORM override), and one family's extraction failure
        # must degrade to an error row, not kill the snapshot
        row = cost.extract(compiled)
        # structural acceptance gate hook: a family may ship a
        # postcheck(compiled) -> dict that ASSERTS on the compiled
        # program (e.g. serving_decode_fused's fusion proof) and
        # returns extra row fields; a failed assertion degrades this
        # family to an error row like any other capture failure
        postcheck = extras.get("postcheck")
        if postcheck is not None:
            row.update(postcheck(compiled))
    except Exception as e:    # noqa: BLE001 — per-family isolation
        return {"model": model, "batch": batch,
                "error": f"{type(e).__name__}: {e}"[:500]}
    finally:
        if prev is None:
            os.environ.pop("BENCH_ANALYTIC_BUILD", None)
        else:
            os.environ["BENCH_ANALYTIC_BUILD"] = prev
    row.update(model=model, batch=batch, metric=metric,
               compile_s=round(time.perf_counter() - t0, 1))
    # bench.py's hand-derived FLOPs model, normalized to the same scope
    # as the lowered program (one step); trainer_prefetch's model covers
    # a whole pass, the serving families' covers the whole request
    # stream/burst — the lowered program there is one batch, so scopes
    # differ and the cross-check is omitted for them.
    bps = extras.get("batches_per_step")
    if model in ("transformer_serving", "serving", "serving_generate",
                 "serving_fleet", "serving_paged",
                 "serving_decode_fused", "serving_autoscale",
                 "serving_chunked_prefill", "serving_quant",
                 "serving_quant_prefill",
                 "serving_speculative", "serving_sharded",
                 "serving_kv_spill", "serving_disagg"):
        # the lowered program is one batch/slab step while the bench FLOPs
        # model covers the whole stream/burst — scopes differ, no cross-check
        row["bench_model_flops"] = None
    else:
        row["bench_model_flops"] = model_flops / (bps or 1)
    row["roofline"] = {c: roofline.predict(row["flops"],
                                           row["bytes_accessed"], c)
                       for c in chips}
    head = row["roofline"][chips[0]]
    row["predicted_ms"] = head["predicted_ms"]
    row["predicted_mfu"] = head["predicted_mfu"]
    row["bottleneck"] = head["bottleneck"]
    return row


def snapshot(families=None, chips=("v5e", "v5p")):
    """Full snapshot dict for the given family names (default: all)."""
    import jax
    sel = [f for f in FAMILIES if families is None or f[0] in families]
    unknown = set(families or ()) - {f[0] for f in sel}
    if unknown:
        raise SystemExit(f"unknown analytic families: {sorted(unknown)} "
                         f"(known: {[f[0] for f in FAMILIES]})")
    rows = {}
    for name, model, batch in sel:
        _log(f"{name} (model={model} batch={batch or 'default'}) ...")
        need = MESH_FAMILIES.get(name, 0)
        if need and len(jax.devices()) < need:
            _log(f"{name}: needs a {need}-device mesh, forcing it in a "
                 "subprocess (this process stays single-device)")
            rows[name] = _capture_subprocess(name, model, batch, need)
        else:
            rows[name] = capture(name, model, batch, chips=chips)
        if "error" in rows[name]:
            _log(f"{name}: FAILED {rows[name]['error']}")
        else:
            _log(f"{name}: {rows[name]['flops'] / 1e9:.1f} GFLOP, "
                 f"{rows[name]['bytes_accessed'] / 1e6:.0f} MB, "
                 f"predicted {rows[name]['predicted_ms']:.2f} ms "
                 f"({rows[name]['bottleneck']}-bound, "
                 f"MFU<={rows[name]['predicted_mfu'] * 100:.0f}%)")
        gc.collect()
    try:
        from paddle_tpu.utils.revision import code_revision
        rev = code_revision()
    except Exception:   # noqa: BLE001
        rev = "unknown"
    return {
        "schema": 1,
        "kind": "paddle_tpu analytic perf snapshot",
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "revision": rev,
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "roofline_chips": list(chips),
        "families": rows,
    }


def write(path, snap):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="chip-independent analytic perf snapshot")
    ap.add_argument("--analytic", action="store_true",
                    help="accepted for bench.py passthrough; implied")
    ap.add_argument("--families", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--out", default=os.environ.get("BENCH_ANALYTIC_OUT",
                                                    DEFAULT_OUT))
    args = ap.parse_args(argv)

    # the snapshot is defined on the CPU backend (works every round); an
    # explicit BENCH_PLATFORM still overrides for A/B-ing backends
    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    os.environ["JAX_PLATFORMS"] = platform
    import jax
    jax.config.update("jax_platforms", platform)

    fams = ([f.strip() for f in args.families.split(",") if f.strip()]
            if args.families else None)
    snap = snapshot(families=fams)
    write(args.out, snap)
    errors = sorted(n for n, r in snap["families"].items() if "error" in r)
    out = {"metric": "analytic perf snapshot (roofline v5e)",
           "value": len(snap["families"]) - len(errors),
           "unit": f"families_ok/{len(snap['families'])}",
           "vs_baseline": None, "out": args.out, "backend": snap["backend"]}
    if errors:
        out["errors"] = errors
    print(json.dumps(out), flush=True)
    return 2 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
