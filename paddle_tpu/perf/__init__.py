"""Chip-independent analytic performance layer.

Round 5 found every on-chip number stale because the single tunneled TPU
chip wedges for days at a time ("no chip window -> no evidence").  This
package converts that into "no chip window -> partial evidence":

- `cost`     — extract XLA's own cost model (FLOPs, bytes accessed,
               arithmetic intensity) plus an HLO op histogram from any
               `jax.jit(...).lower(...).compile()` executable, on ANY
               backend (the CPU backend works every round).
- `roofline` — map (flops, bytes) through a peak-FLOP/s x HBM-bandwidth
               roofline parameterized by public TPU spec tables (v5e,
               v5p, v4, cpu) into a predicted step time / predicted MFU
               and the named bottleneck (compute- vs memory-bound).
- `analytic` — run the extraction over every bench.py family and write
               the round's `BENCH_ANALYTIC_r06.json` snapshot;
               `scripts/perf_report.py --analytic-diff old new` then
               diffs two snapshots structurally and fails loudly on
               de-fusion / bytes-inflation regressions.

Entry points: `python bench.py --analytic`, `python -m
paddle_tpu.perf.analytic`, `python -m paddle_tpu.scripts.bench_sweep
--analytic`.  See docs/perf.md "Analytic roofline".
"""

from paddle_tpu.perf import cost, roofline  # noqa: F401
