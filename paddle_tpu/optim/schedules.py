"""Learning-rate schedules.

Reference: parameter/LearningRateScheduler.cpp — constant, poly, exp,
discexp, linear, manual, pass_manual (plus the trainer's warmup-free
defaults).  Each returns fn(step) -> lr multiplier-applied rate; pure so it
traces into the jitted train step.
"""

import jax.numpy as jnp


def constant(learning_rate):
    def sched(step):
        return jnp.asarray(learning_rate, jnp.float32)
    return sched


def poly(learning_rate, decay_a, decay_b):
    """lr * (1 + a*t)^(-b) (reference 'poly')."""
    def sched(step):
        t = jnp.asarray(step, jnp.float32)
        return learning_rate * (1.0 + decay_a * t) ** (-decay_b)
    return sched


def exp(learning_rate, decay_a, decay_b):
    """lr * a^(t/b) (reference 'exp')."""
    def sched(step):
        t = jnp.asarray(step, jnp.float32)
        return learning_rate * decay_a ** (t / decay_b)
    return sched


def discexp(learning_rate, decay_a, decay_b):
    """lr * a^floor(t/b) (reference 'discexp')."""
    def sched(step):
        t = jnp.asarray(step, jnp.float32)
        return learning_rate * decay_a ** jnp.floor(t / decay_b)
    return sched


def linear(learning_rate, decay_a, decay_b):
    """max(lr - a*t, b) (reference 'linear')."""
    def sched(step):
        t = jnp.asarray(step, jnp.float32)
        return jnp.maximum(learning_rate - decay_a * t, decay_b)
    return sched


def manual(learning_rate, segments):
    """Piecewise-constant by sample/batch count (reference 'manual',
    LearningRateScheduler.cpp ManualLRS: lr * rate of the first segment
    whose boundary >= progress): segments = [(boundary, multiplier), ...].
    The reference keys on samples processed; here the optimizer's step
    counter (batches) is the progress unit."""
    bounds = jnp.asarray([b for b, _ in segments], jnp.float32)
    mults = jnp.asarray([m for _, m in segments] + [segments[-1][1]], jnp.float32)

    def sched(step):
        # reference: num <= boundary keeps the segment -> side="left"
        idx = jnp.searchsorted(bounds, jnp.asarray(step, jnp.float32),
                               side="left")
        return learning_rate * mults[idx]
    return sched


def pass_manual(learning_rate, segments, steps_per_pass):
    """Piecewise-constant by PASS number (reference 'pass_manual',
    LearningRateScheduler.cpp PassManualLRS: calc(pass)): segments =
    [(pass_boundary, multiplier), ...].  The jitted step only carries a
    batch counter, so the pass index is derived as step // steps_per_pass —
    pass steps_per_pass = ceil(len(dataset) / batch_size)."""
    if not steps_per_pass or steps_per_pass < 1:
        raise ValueError("pass_manual needs steps_per_pass >= 1 (batches "
                         "per pass) to derive the pass index under jit")
    base = manual(learning_rate, segments)

    def sched(step):
        return base(jnp.asarray(step, jnp.int32) // steps_per_pass)
    return sched


def warmup_cosine(learning_rate, warmup_steps, total_steps, min_ratio=0.0):
    """TPU-era addition for the transformer family."""
    def sched(step):
        t = jnp.asarray(step, jnp.float32)
        warm = t / jnp.maximum(warmup_steps, 1)
        progress = jnp.clip((t - warmup_steps) / jnp.maximum(total_steps - warmup_steps, 1), 0, 1)
        cos = min_ratio + (1 - min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * progress))
        return learning_rate * jnp.where(t < warmup_steps, warm, cos)
    return sched


def get(name, learning_rate, decay_a=0.0, decay_b=0.0, segments=None, **kw):
    """Reference config: learning_rate_schedule string in OptimizationConfig."""
    if name in (None, "constant"):
        return constant(learning_rate)
    if name == "poly":
        return poly(learning_rate, decay_a, decay_b)
    if name == "exp":
        return exp(learning_rate, decay_a, decay_b)
    if name == "discexp":
        return discexp(learning_rate, decay_a, decay_b)
    if name == "linear":
        return linear(learning_rate, decay_a, decay_b)
    if name == "manual":
        return manual(learning_rate, segments)
    if name == "pass_manual":
        return pass_manual(learning_rate, segments,
                           kw.get("steps_per_pass"))
    if name == "warmup_cosine":
        return warmup_cosine(learning_rate, **kw)
    raise KeyError(f"unknown lr schedule {name!r}")
