"""First-order optimizer zoo.

Reference: parameter/FirstOrderOptimizer.{h,cpp} — SGD-momentum,
SparseMomentum, AdaGrad, AdaDelta, RMSProp, DecayedAdaGrad, Adam, AdaMax —
plus decorator optimizers OptimizerWithRegularizer (L1/L2) and
OptimizerWithGradientClipping, and AverageOptimizer (Polyak) in averaging.py.
The reference's multi-buffer Parameter (MOMENTUM, SUM1-3... GlobalConstants.h)
becomes an explicit state pytree here; the same update math runs inside the
jitted SPMD train step (so in the sharded setting the optimizer runs
"in-pserver" and "in-trainer" at once — there is no separate server).

API: factory(**cfg) -> Optimizer(init, update) where
  init(params) -> state
  update(grads, state, params) -> (new_params, new_state)
Everything is a pure pytree function; lr schedules thread via a step counter
held in state.
"""

import math
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from paddle_tpu.optim import schedules


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Any]
    # sparse-row interface (reference SparseRowMatrix / sgdUpdateSparse
    # semantics): the same update rule applied to a GATHERED subtree of
    # touched embedding rows only — step time scales with touched rows, not
    # vocab.  row_init(rows_tree) -> slot subtree; row_update(grads, slots,
    # rows, step) -> (new_rows, new_slots).
    row_init: Callable[[Any], Any] = None
    row_update: Callable[[Any, Any, Any, Any], Any] = None
    # clip config, exposed so a caller splitting the grad tree (the sparse
    # path) can compute ONE global norm and pass clip_scale= to both update
    # calls instead of letting each clip its own partition
    clip_norm: float = None
    clip_threshold: float = None


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _resolve_sched(learning_rate, learning_rate_schedule, **kw):
    if callable(learning_rate):
        return learning_rate
    return schedules.get(learning_rate_schedule, learning_rate, **kw)


def _apply_decay(updates, params, grads, l2=0.0, l1=0.0):
    """Reference OptimizerWithRegularizer folds decay into the gradient:
    g <- g + l2*w  (+ l1 sign term)."""
    if l2 == 0.0 and l1 == 0.0:
        return grads
    def fold(g, p):
        out = g
        if l2:
            out = out + l2 * p
        if l1:
            out = out + l1 * jnp.sign(p)
        return out
    return _tmap(fold, grads, params)


def _clip(grads, clip_threshold=None, clip_norm=None, clip_scale=None):
    """Reference OptimizerWithGradientClipping: per-element value clip at
    gradient_clipping_threshold.  clip_norm additionally offers global-norm
    clipping (TPU-era standard for RNN/transformer training).  clip_scale
    overrides the norm computation with a caller-supplied global scale (used
    when the grad tree is split across update calls)."""
    if clip_threshold:
        grads = _tmap(lambda g: jnp.clip(g, -clip_threshold, clip_threshold), grads)
    if clip_scale is not None:
        grads = _tmap(lambda g: g * clip_scale, grads)
    elif clip_norm:
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                          for g in jax.tree_util.tree_leaves(grads)) + 1e-12)
        scale = jnp.minimum(1.0, clip_norm / gn)
        grads = _tmap(lambda g: g * scale, grads)
    return grads


def _make(update_one, extra_state_fn, learning_rate, learning_rate_schedule,
          l1=0.0, l2=0.0, clip_threshold=None, clip_norm=None, sched_kw=None):
    sched = _resolve_sched(learning_rate, learning_rate_schedule, **(sched_kw or {}))

    def init(params):
        return {"step": jnp.zeros((), jnp.int32),
                "slots": extra_state_fn(params)}

    def update(grads, state, params, clip_scale=None):
        step = state["step"]
        lr = sched(step)
        grads = _clip(grads, clip_threshold, clip_norm, clip_scale)
        grads = _apply_decay(None, params, grads, l2=l2, l1=l1)
        new_params, new_slots = update_one(grads, state["slots"], params, lr,
                                           step)
        return new_params, {"step": step + 1, "slots": new_slots}

    def row_update(grads, slot_rows, rows, step, clip_scale=None):
        lr = sched(step)
        grads = _clip(grads, clip_threshold, clip_norm, clip_scale)
        grads = _apply_decay(None, rows, grads, l2=l2, l1=l1)
        return update_one(grads, slot_rows, rows, lr, step)

    return Optimizer(init=init, update=update, row_init=extra_state_fn,
                     row_update=row_update, clip_norm=clip_norm,
                     clip_threshold=clip_threshold)


# ---------------------------------------------------------------- momentum

def Momentum(learning_rate=0.01, momentum=0.9, nesterov=False,
             learning_rate_schedule=None, **kw):
    """SGD with momentum (reference SgdOptimizer/sgdUpdate,
    parameter/ParameterUpdateFunctions.cpp:33: mom = m*mom - lr*g;
    w += mom)."""
    def slots(params):
        return {"mom": _tmap(jnp.zeros_like, params)}

    def upd(grads, s, params, lr, step):
        new_mom = _tmap(lambda m, g: momentum * m - lr * g, s["mom"], grads)
        if nesterov:
            new_p = _tmap(lambda p, m, g: p + momentum * m - lr * g,
                          params, new_mom, grads)
        else:
            new_p = _tmap(lambda p, m: p + m, params, new_mom)
        return new_p, {"mom": new_mom}

    return _make(upd, slots, learning_rate, learning_rate_schedule, **kw)


def AdaGrad(learning_rate=0.01, epsilon=1e-6, learning_rate_schedule=None, **kw):
    """Reference AdagradParameterOptimizer: accum += g^2;
    w -= lr * g / (sqrt(accum) + eps)."""
    def slots(params):
        return {"accum": _tmap(jnp.zeros_like, params)}

    def upd(grads, s, params, lr, step):
        accum = _tmap(lambda a, g: a + g * g, s["accum"], grads)
        new_p = _tmap(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + epsilon),
                      params, grads, accum)
        return new_p, {"accum": accum}

    return _make(upd, slots, learning_rate, learning_rate_schedule, **kw)


def AdaDelta(learning_rate=1.0, rho=0.95, epsilon=1e-6,
             learning_rate_schedule=None, **kw):
    """Reference AdaDeltaParameterOptimizer:
    E[g2] = rho*E[g2] + (1-rho)g2; dx = g*sqrt((E[dx2]+eps)/(E[g2]+eps));
    E[dx2] = rho*E[dx2] + (1-rho)dx^2; w -= lr*dx."""
    def slots(params):
        z = _tmap(jnp.zeros_like, params)
        return {"eg2": z, "edx2": _tmap(jnp.zeros_like, params)}

    def upd(grads, s, params, lr, step):
        eg2 = _tmap(lambda a, g: rho * a + (1 - rho) * g * g, s["eg2"], grads)
        dx = _tmap(lambda g, a, d: g * jnp.sqrt((d + epsilon) / (a + epsilon)),
                   grads, eg2, s["edx2"])
        edx2 = _tmap(lambda d, x: rho * d + (1 - rho) * x * x, s["edx2"], dx)
        new_p = _tmap(lambda p, x: p - lr * x, params, dx)
        return new_p, {"eg2": eg2, "edx2": edx2}

    return _make(upd, slots, learning_rate, learning_rate_schedule, **kw)


def RMSProp(learning_rate=0.01, rho=0.95, epsilon=1e-6,
            learning_rate_schedule=None, **kw):
    """Reference RMSPropParameterOptimizer (the centered variant):
    E[g2] = rho*E[g2]+(1-rho)g2;  E[g] = rho*E[g]+(1-rho)g;
    w -= lr * g / sqrt(E[g2] - E[g]^2 + eps)."""
    def slots(params):
        return {"eg2": _tmap(jnp.zeros_like, params),
                "eg": _tmap(jnp.zeros_like, params)}

    def upd(grads, s, params, lr, step):
        eg2 = _tmap(lambda a, g: rho * a + (1 - rho) * g * g, s["eg2"], grads)
        eg = _tmap(lambda a, g: rho * a + (1 - rho) * g, s["eg"], grads)
        new_p = _tmap(
            lambda p, g, a, m: p - lr * g / jnp.sqrt(a - m * m + epsilon),
            params, grads, eg2, eg)
        return new_p, {"eg2": eg2, "eg": eg}

    return _make(upd, slots, learning_rate, learning_rate_schedule, **kw)


def DecayedAdaGrad(learning_rate=0.01, rho=0.95, epsilon=1e-6,
                   learning_rate_schedule=None, **kw):
    """Reference DecayedAdagradParameterOptimizer: like RMSProp without the
    mean term."""
    def slots(params):
        return {"accum": _tmap(jnp.zeros_like, params)}

    def upd(grads, s, params, lr, step):
        accum = _tmap(lambda a, g: rho * a + (1 - rho) * g * g, s["accum"], grads)
        new_p = _tmap(lambda p, g, a: p - lr * g / jnp.sqrt(a + epsilon),
                      params, grads, accum)
        return new_p, {"accum": accum}

    return _make(upd, slots, learning_rate, learning_rate_schedule, **kw)


def Adam(learning_rate=1e-3, beta1=0.9, beta2=0.999, epsilon=1e-8,
         learning_rate_schedule=None, **kw):
    """Reference AdamParameterOptimizer (with bias correction)."""
    def slots(params):
        return {"m": _tmap(jnp.zeros_like, params),
                "v": _tmap(jnp.zeros_like, params)}

    def upd(grads, s, params, lr, step):
        t = (step + 1).astype(jnp.float32)
        m = _tmap(lambda a, g: beta1 * a + (1 - beta1) * g, s["m"], grads)
        v = _tmap(lambda a, g: beta2 * a + (1 - beta2) * g * g, s["v"], grads)
        mhat_scale = 1.0 / (1.0 - beta1 ** t)
        vhat_scale = 1.0 / (1.0 - beta2 ** t)
        new_p = _tmap(
            lambda p, mm, vv: p - lr * (mm * mhat_scale)
            / (jnp.sqrt(vv * vhat_scale) + epsilon),
            params, m, v)
        return new_p, {"m": m, "v": v}

    return _make(upd, slots, learning_rate, learning_rate_schedule, **kw)


def AdaMax(learning_rate=2e-3, beta1=0.9, beta2=0.999,
           learning_rate_schedule=None, **kw):
    """Reference AdamaxParameterOptimizer: u = max(beta2*u, |g|);
    w -= lr/(1-beta1^t) * m / u."""
    def slots(params):
        return {"m": _tmap(jnp.zeros_like, params),
                "u": _tmap(jnp.zeros_like, params)}

    def upd(grads, s, params, lr, step):
        t = (step + 1).astype(jnp.float32)
        m = _tmap(lambda a, g: beta1 * a + (1 - beta1) * g, s["m"], grads)
        u = _tmap(lambda a, g: jnp.maximum(beta2 * a, jnp.abs(g)), s["u"], grads)
        new_p = _tmap(
            lambda p, mm, uu: p - (lr / (1 - beta1 ** t)) * mm / (uu + 1e-12),
            params, m, u)
        return new_p, {"m": m, "u": u}

    return _make(upd, slots, learning_rate, learning_rate_schedule, **kw)


_REGISTRY = {
    "momentum": Momentum, "sgd": Momentum, "adagrad": AdaGrad,
    "adadelta": AdaDelta, "rmsprop": RMSProp,
    "decayed_adagrad": DecayedAdaGrad, "adam": Adam, "adamax": AdaMax,
}


def get(name, **kw):
    try:
        return _REGISTRY[name.lower()](**kw)
    except KeyError:
        raise KeyError(f"unknown optimizer {name!r}; have {sorted(_REGISTRY)}")
