"""Optimizers, LR schedules, regularization, Polyak averaging —
the TPU-native equivalent of reference §2.4 (parameter/FirstOrderOptimizer
zoo + LearningRateScheduler + AverageOptimizer + updater semantics)."""

from paddle_tpu.optim.optimizers import (
    Optimizer, Momentum, AdaGrad, AdaDelta, RMSProp, DecayedAdaGrad,
    Adam, AdaMax, get,
)
from paddle_tpu.optim import schedules
from paddle_tpu.optim import averaging

__all__ = [
    "Optimizer", "Momentum", "AdaGrad", "AdaDelta", "RMSProp",
    "DecayedAdaGrad", "Adam", "AdaMax", "get", "schedules", "averaging",
]
