"""Polyak parameter averaging with apply/restore.

Reference: parameter/AverageOptimizer.{h,cpp} — maintains an accumulated sum
of parameter values (SUM1-3 buffers) over a moving window
(average_window * num_batches), and the Trainer/Tester temporarily *apply*
the averaged value for evaluation then *restore* the live value
(trainer/Tester.cpp, ParameterUpdaterBase apply/restore).

Functional design: AveragerState rides next to the optimizer state; apply()
returns the averaged params (no mutation), so "apply/restore" is just using
a different pytree for eval.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class AveragerState(NamedTuple):
    sum_: object      # pytree: windowed running sum
    count: jnp.ndarray


def init(params):
    return AveragerState(
        sum_=jax.tree_util.tree_map(jnp.zeros_like, params),
        count=jnp.zeros((), jnp.float32))


def accumulate(state: AveragerState, params, decay=None) -> AveragerState:
    """Call once per batch after the optimizer update.  With decay=d the
    window is exponential (reference's moving-average mode); otherwise a
    plain running sum."""
    if decay is None:
        new_sum = jax.tree_util.tree_map(lambda s, p: s + p, state.sum_, params)
        return AveragerState(sum_=new_sum, count=state.count + 1.0)
    new_sum = jax.tree_util.tree_map(
        lambda s, p: decay * s + (1.0 - decay) * p, state.sum_, params)
    return AveragerState(sum_=new_sum, count=jnp.ones((), jnp.float32))


def apply(state: AveragerState, params):
    """Averaged parameters for eval (reference apply()); falls back to live
    params when nothing accumulated yet."""
    def avg(s, p):
        return jnp.where(state.count > 0, s / jnp.maximum(state.count, 1.0), p)
    return jax.tree_util.tree_map(avg, state.sum_, params)


def reset(state: AveragerState, params):
    """Start a new window (reference startPass/window roll-over)."""
    return init(params)
