from paddle_tpu.trainer.cli import main
import sys

sys.exit(main())
