"""paddle_tpu: a TPU-native deep-learning framework.

A ground-up JAX/XLA/pjit/Pallas re-design with the capability surface of
classic (pre-Fluid) PaddlePaddle: the layer/projection model zoo, the Python
config DSL and v2 trainer API, padding-free variable-length sequence training
with ``recurrent_group`` and beam-search generation, the optimizer /
regularizer / evaluator suites, data providers, checkpoint/resume, and
SPMD distributed training over TPU meshes.

Reference capability map: see SURVEY.md at the repo root.
"""

from paddle_tpu._platform import honor_jax_platforms_env as _honor_env

_honor_env()    # JAX_PLATFORMS env beats any sitecustomize config pin

from paddle_tpu.version import __version__

from paddle_tpu.core import dtypes
from paddle_tpu.core.sequence import SequenceBatch

from paddle_tpu import ops
from paddle_tpu import layers
from paddle_tpu import optim
from paddle_tpu import data
from paddle_tpu import parallel
from paddle_tpu import evaluators
from paddle_tpu import models
from paddle_tpu import trainer

# v2-style convenience namespace:  paddle_tpu.init(), .layer, .optimizer ...
from paddle_tpu.trainer.api import init, infer
from paddle_tpu.data import reader

layer = layers  # paddle.v2.layer equivalent
optimizer = optim  # paddle.v2.optimizer equivalent

__all__ = [
    "__version__",
    "dtypes",
    "SequenceBatch",
    "ops",
    "layers",
    "layer",
    "optim",
    "optimizer",
    "data",
    "reader",
    "parallel",
    "evaluators",
    "models",
    "trainer",
    "init",
    "infer",
]
