"""v1 config-file compatibility (the reference's config compiler).

`paddle_tpu.compat.v1` exports the trainer_config_helpers surface the
reference's demo/benchmark config scripts import (`from
paddle.trainer_config_helpers import *`); `paddle_tpu.compat.config_parser`
executes such a script (reference config_parser.py:3558 parse_config) and
lowers it to the runtime contract the CLI trainer consumes.  The root-level
`paddle/` shim package maps the reference import paths onto these modules so
reference configs run UNCHANGED.
"""

from paddle_tpu.compat.config_parser import parse_config, config_to_runtime
