"""Execute reference-style v1 trainer config scripts.

Reference: python/paddle/trainer/config_parser.py:3558 `parse_config(
config_file, config_arg_str)` executes the user's config script against the
trainer_config_helpers DSL and returns the assembled proto; the C++ trainer
then builds data providers from the recorded PyDataProvider2 sources
(TrainerConfigHelper.cpp:33-54).

Here the script executes against the SAME paddle_tpu DSL the native API
uses (the layer ctors build LayerOutput graphs directly), so "parsing" a
config yields a ready Topology + optimizer + reader spec — there is no
intermediate proto.  parse_config returns a ParsedConfig; config_to_runtime
lowers it to the {cost, optimizer, train_reader, feeding, ...} contract the
CLI trainer consumes.
"""

import builtins
import importlib
import os
import sys

from paddle_tpu.utils.error import ConfigError

# The reference config scripts/providers are python-2 era; give them the py2
# builtins they expect (only where py3 doesn't define them already).
for _name, _val in (("xrange", range), ("unicode", str),
                    ("basestring", (str, bytes))):
    if not hasattr(builtins, _name):
        setattr(builtins, _name, _val)

# py2 module names some providers import
import pickle as _pickle  # noqa: E402

sys.modules.setdefault("cPickle", _pickle)


def _py2_map(*a):
    return list(map(*a))


def _py2_filter(*a):
    return list(filter(*a))


# Module-global shadows for the py2 list-returning builtins; seeded into the
# executing namespace (globals win over builtins) rather than injected into
# the source, so line numbers and the module docstring are untouched.
_PY2_GLOBALS = {"map": _py2_map, "filter": _py2_filter}


def _py2_rewrite(src: str) -> str:
    """Textual py2 idioms the reference demo helpers use (dict.iteritems in
    seqToseq_net.py:83, f.next(), sys.maxint, list-returning map/filter in
    traffic_prediction/dataprovider.py); py3 equivalents are drop-in.  Pure
    same-length-line replaces: tracebacks still point at the file on disk."""
    return (src.replace(".iteritems()", ".items()")
               .replace(".itervalues()", ".values()")
               .replace(".iterkeys()", ".keys()")
               .replace(".next()", ".__next__()")
               .replace("sys.maxint", "sys.maxsize"))


class _Py2SourceLoader(importlib.machinery.SourceFileLoader):
    def get_data(self, path):
        if str(path).endswith(".py"):
            with open(path, "r") as f:
                return _py2_rewrite(f.read()).encode()
        return super().get_data(path)

    def get_code(self, fullname):
        # bypass the bytecode cache (it would hold the UN-rewritten code)
        source = self.get_data(self.get_filename(fullname))
        return compile(source, self.get_filename(fullname), "exec")

    def exec_module(self, module):
        module.__dict__.update(_PY2_GLOBALS)
        super().exec_module(module)


class _Py2ConfigDirFinder:
    """While a v1 config parses, sibling imports from its directory
    (`from seqToseq_net import *`) load through the py2 rewrite.  Loaded
    names are recorded so parse_config can evict them afterwards — two
    demos both importing a sibling called `seqToseq_net` must not share a
    cached module."""

    def __init__(self, config_dir):
        self.config_dir = config_dir
        self.loaded = []

    def find_spec(self, name, path=None, target=None):
        # config dir first, then its parent (the reference demos do
        # sys.path.append('..') to share helpers like seqToseq_net.py)
        base = name.split(".")[-1] + ".py"
        for d in (self.config_dir, os.path.dirname(self.config_dir)):
            cand = os.path.join(d, base)
            if os.path.exists(cand):
                self.loaded.append(name)
                return importlib.util.spec_from_file_location(
                    name, cand, loader=_Py2SourceLoader(name, cand))
        return None


class ParseContext:
    def __init__(self, config_args=None, config_dir="."):
        self.config_args = dict(config_args or {})
        self.config_dir = config_dir
        self.settings = {"batch_size": 256, "learning_rate": 1e-3}
        self.data_sources = {}
        self.outputs = []
        self.input_order = []       # data layers in declaration order
        self.explicit_inputs = False    # inputs(...) was called
        self.evaluators = []


_ACTIVE = []


def active_context() -> ParseContext:
    if not _ACTIVE:
        raise ConfigError(
            "no active config parse (settings()/define_py_data_sources2 must "
            "run inside parse_config, i.e. from a --config script)")
    return _ACTIVE[-1]


def in_parse():
    return bool(_ACTIVE)


def _dfs_input_order(outputs):
    """Data layers in DFS-LRV order over the output graph — the
    reference's `outputs()` input-order rule
    (trainer_config_helpers/networks.py:1410-1490): provider slots pair
    with data layers AS REACHED FROM THE OUTPUTS, not as declared.  The
    two orders differ when a config declares its label layer first
    (benchmark/paddle/image/googlenet.py:146 declares `label` before
    `input`, but the provider yields (img, label)).  Memoized traversal
    (Topology's walker) yields the reference's first-occurrence order
    without its exponential revisits on diamond graphs."""
    from paddle_tpu.layers.graph import Topology
    order = []
    for node in Topology._topo_sort(outputs):
        if getattr(node, "layer_type", None) == "data" \
                and node.name not in order:
            order.append(node.name)
    return order


class ParsedConfig:
    def __init__(self, ctx: ParseContext, namespace):
        self.settings = ctx.settings
        self.data_sources = ctx.data_sources
        self.outputs = ctx.outputs
        if getattr(ctx, "explicit_inputs", False):
            # reference: an explicit inputs() wins outright
            # (HasInputsSet() early-return, networks.py:1449)
            self.input_order = list(ctx.input_order)
        else:
            # reference semantics: input order derives from the outputs'
            # graph; declaration order only covers data layers the
            # outputs never reach (kept as a tail so nothing is dropped)
            dfs = _dfs_input_order(ctx.outputs)
            order = dfs + [n for n in ctx.input_order if n not in dfs]
            self._check_seqness_stable(ctx, order)
            self.input_order = order
        self.evaluators = ctx.evaluators
        self.config_dir = ctx.config_dir
        self.namespace = namespace   # the script's globals (for tooling)

    @staticmethod
    def _check_seqness_stable(ctx, final_order):
        """data_layer resolved each layer's seq-ness at DECLARATION index
        into list-style input_types; feeding pairs types by FINAL order.
        When the two orders differ, that is only sound if every layer's
        seq-ness is the same under both pairings (true for the common
        dense/int image configs) — otherwise fail loud instead of
        silently scrambling sequence flags."""
        types = getattr(ctx, "_resolved_types", None)
        if not isinstance(types, (list, tuple)) \
                or final_order == ctx.input_order:
            return
        decl_idx = {n: i for i, n in enumerate(ctx.input_order)}

        def seqness(i):
            if i is None or i >= len(types):
                return None
            return getattr(types[i], "seq_type", 0)

        for fi, name in enumerate(final_order):
            if seqness(fi) != seqness(decl_idx.get(name)):
                raise ConfigError(
                    f"data layer {name!r}: declaration order and the "
                    "outputs-derived input order assign different "
                    "sequence types from the provider's list-style "
                    "input_types; declare data layers in input order, "
                    "call inputs(...) explicitly, or use dict-style "
                    "input_types")


def _import_provider(module, config_dir):
    """Import a data-provider module from the config's directory.  Loaded
    under a config-dir-qualified module key so same-named providers from
    different demos (every demo calls its module 'dataprovider') don't
    collide in sys.modules; the config dir goes on sys.path during exec so
    sibling imports (mnist_provider -> mnist_util) resolve."""
    rel = module.replace(".", os.sep) + ".py"
    # config dir, then its parent (demos share providers one level up via
    # sys.path.append('..'), e.g. seqToseq/translation -> seqToseq)
    path = next((p for p in (os.path.join(config_dir, rel),
                             os.path.join(os.path.dirname(config_dir), rel))
                 if os.path.exists(p)), None)
    if path is not None:
        key = f"_ptpu_provider_{abs(hash(os.path.dirname(path)))}_{module}"
        if key in sys.modules:
            return sys.modules[key]
        # providers are py2-era too: load through the rewrite
        spec = importlib.util.spec_from_file_location(
            key, path, loader=_Py2SourceLoader(key, path))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[key] = mod
        added = False
        if config_dir not in sys.path:
            sys.path.insert(0, config_dir)
            added = True
        try:
            spec.loader.exec_module(mod)
        finally:
            if added:
                sys.path.remove(config_dir)
        return mod
    added = False
    if config_dir not in sys.path:
        sys.path.insert(0, config_dir)
        added = True
    try:
        return importlib.import_module(module)
    finally:
        if added:
            sys.path.remove(config_dir)


def resolve_input_types(ctx: ParseContext):
    """Input types from the recorded data sources, resolved AT PARSE TIME so
    data_layer can infer sequence-ness (the reference carries seq-ness in the
    provider's input_types, not the layer config).  Builds the provider with
    an empty file list — generators are lazy, only init_hook runs."""
    if hasattr(ctx, "_resolved_types"):
        return ctx._resolved_types
    types = None
    for key in ("train", "test"):
        src = ctx.data_sources.get(key)
        if not src:
            continue
        try:
            mod = _import_provider(src["module"], ctx.config_dir)
            factory = getattr(mod, src["obj"]) if isinstance(src["obj"], str) \
                else src["obj"]
            reader = factory([], **(src.get("args") or {}))
            types = getattr(reader, "input_types", None)
            if types:
                break
        except Exception:   # noqa: BLE001 — provider may need real files
            continue
    ctx._resolved_types = types
    return types


def _parse_config_arg_str(s):
    out = {}
    if not s:
        return out
    for kv in s.split(","):
        if not kv.strip():
            continue
        k, _, v = kv.partition("=")
        out[k.strip()] = v.strip()
    return out


def parse_config(config_file, config_arg_str="") -> ParsedConfig:
    """Execute a v1 config script (reference parse_config signature).

    config_arg_str: "k=v,k2=v2" (or an already-parsed dict)."""
    args = (config_arg_str if isinstance(config_arg_str, dict)
            else _parse_config_arg_str(config_arg_str))
    config_dir = os.path.dirname(os.path.abspath(config_file))
    ctx = ParseContext(args, config_dir)
    from paddle_tpu.layers.graph import reset_names
    reset_names()
    _ACTIVE.append(ctx)
    added_path = False
    try:
        # the provider module named by define_py_data_sources2 lives next to
        # the config script (reference trainer behavior)
        if config_dir not in sys.path:
            sys.path.insert(0, config_dir)
            added_path = True
        finder = _Py2ConfigDirFinder(config_dir)
        sys.meta_path.insert(0, finder)
        src = _py2_rewrite(open(config_file).read())
        ns = {"__file__": os.path.abspath(config_file),
              "__name__": "__paddle_tpu_config__", **_PY2_GLOBALS}
        code = compile(src, config_file, "exec")
        exec(code, ns)
    finally:
        try:
            sys.meta_path.remove(finder)
            for name in finder.loaded:
                sys.modules.pop(name, None)
        except ValueError:
            pass
        _ACTIVE.pop()
        if added_path:
            sys.path.remove(config_dir)
    if not ctx.outputs:
        raise ConfigError(f"{config_file} declared no outputs(); nothing to "
                          "train or infer")
    return ParsedConfig(ctx, ns)


# ------------------------------------------------------------ lowering


def _make_optimizer(settings):
    from paddle_tpu import optim
    from paddle_tpu.compat import v1

    method = settings.get("learning_method") or v1.MomentumOptimizer(0.0)
    lr = settings.get("learning_rate", 1e-3)
    kw = dict(method.kw)

    reg = settings.get("regularization")
    if reg is not None:
        if getattr(reg, "l2", 0.0):
            kw["l2"] = reg.l2
        if getattr(reg, "l1", 0.0):
            kw["l1"] = reg.l1
    clip = settings.get("gradient_clipping_threshold")
    if clip:
        clip = clip.threshold if hasattr(clip, "threshold") else clip
        kw["clip_threshold"] = clip

    # reference LearningRateScheduler: 'poly' with decay_a/b == 0 is constant
    sched_name = settings.get("learning_rate_schedule", "poly")
    da = settings.get("learning_rate_decay_a", 0.0)
    db = settings.get("learning_rate_decay_b", 0.0)
    schedule = None
    if sched_name and sched_name != "constant" and (da or db):
        from paddle_tpu.optim import schedules
        fns = {"poly": schedules.poly, "exp": schedules.exp,
               "discexp": schedules.discexp, "linear": schedules.linear}
        if sched_name in fns:
            schedule = fns[sched_name](lr, da, db)

    names = {"momentum": optim.Momentum, "adam": optim.Adam,
             "adamax": optim.AdaMax, "adagrad": optim.AdaGrad,
             "decayed_adagrad": optim.DecayedAdaGrad,
             "adadelta": optim.AdaDelta, "rmsprop": optim.RMSProp}
    ctor = names[method.optim_name]
    if schedule is not None:
        kw["learning_rate_schedule"] = schedule
    return ctor(learning_rate=lr, **kw)


def _expand_file_list(file_list, config_dir):
    """A train/test list is a text file of data-file paths (one per line,
    reference convention), resolved against the cwd then the config dir; a
    list/tuple of paths is passed through."""
    if isinstance(file_list, (list, tuple)):
        return list(file_list)
    path = file_list
    if not os.path.exists(path):
        alt = os.path.join(config_dir, file_list)
        if os.path.exists(alt):
            path = alt
        else:
            raise ConfigError(f"data source list file not found: {file_list}")
    base = os.path.dirname(os.path.abspath(path))
    files = []
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        if not os.path.exists(line):
            alt = os.path.join(base, line)
            line = alt if os.path.exists(alt) else line
        files.append(line)
    return files


def _make_reader(src, config_dir, batch_size):
    """Build a batched reader + feeding dict from a recorded data source."""
    mod = _import_provider(src["module"], config_dir)
    factory = getattr(mod, src["obj"]) if isinstance(src["obj"], str) \
        else src["obj"]
    files = _expand_file_list(src["file_list"], config_dir)
    sample_reader = factory(files, **(src.get("args") or {}))
    input_types = getattr(sample_reader, "input_types", None)

    def batched():
        batch = []
        for sample in sample_reader():
            batch.append(sample)
            if len(batch) == batch_size:
                yield batch
                batch = []
        if batch:
            yield batch

    return batched, input_types


def _feeding_dict(input_types, input_order):
    """{name: InputType} in data-layer declaration order (list-style
    input_types pair positionally with the declared data layers, the
    reference's inputs() ordering)."""
    if input_types is None:
        return None
    if isinstance(input_types, dict):
        if input_order:
            ordered = {n: input_types[n] for n in input_order
                       if n in input_types}
            if len(ordered) == len(input_types):
                return ordered
        return dict(input_types)
    pairs = zip(input_order, list(input_types))
    return dict(pairs)


def config_to_runtime(parsed: ParsedConfig, for_test=False):
    """Lower a ParsedConfig to the CLI trainer's cfg-dict contract."""
    batch_size = parsed.settings.get("batch_size", 256)
    cfg = {
        "cost": (parsed.outputs[0] if len(parsed.outputs) == 1
                 else list(parsed.outputs)),
        "optimizer": _make_optimizer(parsed.settings),
        "batch_size": batch_size,
        "evaluators": list(parsed.evaluators),
    }
    feeding = None
    if "train" in parsed.data_sources:
        reader, input_types = _make_reader(parsed.data_sources["train"],
                                           parsed.config_dir, batch_size)
        cfg["train_reader"] = reader
        feeding = _feeding_dict(input_types, parsed.input_order)
    if "test" in parsed.data_sources:
        reader, input_types = _make_reader(parsed.data_sources["test"],
                                           parsed.config_dir, batch_size)
        cfg["test_reader"] = reader
        if feeding is None:
            feeding = _feeding_dict(input_types, parsed.input_order)
    if feeding:
        cfg["feeding"] = feeding
    return cfg
