"""The v1 trainer_config_helpers name surface for unchanged config scripts.

Reference: python/paddle/trainer_config_helpers/{activations,attrs,poolings,
optimizers,data_sources}.py.  Design notes:

- Activation classes subclass `str` with their registry value, so
  `fc_layer(act=SoftmaxActivation())` flows through the existing DSL (which
  compares/act-looks-up strings) with zero changes.
- ParameterAttribute / ExtraLayerAttribute subclass `dict`, matching the
  DSL's duck-typed `param_attr`/`layer_attr` dicts.
- Pooling classes carry `.name` (pooling_layer already reads `.name`).
- Optimizer/regularization classes + settings() record into the active
  parse context (`paddle_tpu.compat.config_parser`), mirroring the
  reference's settings(...) mutating a global trainer proto.
"""

from paddle_tpu.data import provider as _prov

__all__ = [
    # activations
    "BaseActivation", "TanhActivation", "SigmoidActivation",
    "SoftmaxActivation", "IdentityActivation", "LinearActivation",
    "SequenceSoftmaxActivation", "ExpActivation", "ReluActivation",
    "BReluActivation", "SoftReluActivation", "STanhActivation",
    "AbsActivation", "SquareActivation", "LogActivation",
    # attrs
    "ParameterAttribute", "ParamAttr", "ExtraLayerAttribute", "ExtraAttr",
    "HookAttribute", "HookAttr",
    # poolings
    "BasePoolingType", "MaxPooling", "AvgPooling", "SumPooling",
    "SquareRootNPooling", "CudnnMaxPooling", "CudnnAvgPooling",
    "MaxWithIdPooling",
    # optimizers / settings
    "BaseSGDOptimizer", "MomentumOptimizer", "AdamOptimizer",
    "AdamaxOptimizer", "AdaGradOptimizer", "DecayedAdaGradOptimizer",
    "AdaDeltaOptimizer", "RMSPropOptimizer", "settings",
    "BaseRegularization", "L2Regularization", "L1Regularization",
    "ModelAverage", "GradientClippingThreshold",
    # data sources + config args
    "define_py_data_sources2", "define_py_data_sources", "get_config_arg",
    "get_batch_size",
]


# ------------------------------------------------------------- activations

class BaseActivation(str):
    """str subclass: instances ARE the activation-registry key."""
    _value = ""

    def __new__(cls):
        return str.__new__(cls, cls._value)

    @property
    def name(self):
        return str(self)


def _act(name, value):
    cls = type(name, (BaseActivation,), {"_value": value})
    return cls


TanhActivation = _act("TanhActivation", "tanh")
SigmoidActivation = _act("SigmoidActivation", "sigmoid")
SoftmaxActivation = _act("SoftmaxActivation", "softmax")
IdentityActivation = _act("IdentityActivation", "linear")
LinearActivation = IdentityActivation
SequenceSoftmaxActivation = _act("SequenceSoftmaxActivation",
                                 "sequence_softmax")
ExpActivation = _act("ExpActivation", "exponential")
ReluActivation = _act("ReluActivation", "relu")
BReluActivation = _act("BReluActivation", "brelu")
SoftReluActivation = _act("SoftReluActivation", "softrelu")
STanhActivation = _act("STanhActivation", "stanh")
AbsActivation = _act("AbsActivation", "abs")
SquareActivation = _act("SquareActivation", "square")
LogActivation = _act("LogActivation", "log")


# ------------------------------------------------------------------- attrs

class ParameterAttribute(dict):
    """Reference attrs.py ParameterAttribute -> the DSL's param_attr dict."""

    def __init__(self, name=None, is_static=False, initial_std=None,
                 initial_mean=None, initial_max=None, initial_min=None,
                 l1_rate=None, l2_rate=None, learning_rate=None,
                 momentum=None, gradient_clipping_threshold=None,
                 sparse_update=False, initial_strategy=0,
                 update_hooks=None):
        d = {}
        if name is not None:
            d["name"] = name
        if initial_std is not None:
            d["initial_std"] = initial_std
        if initial_mean is not None:
            d["initial_mean"] = initial_mean
        if initial_max is not None and initial_min is not None:
            # uniform in [min, max]
            d["initial_strategy"] = 1
            d["initial_std"] = (initial_max - initial_min) / 2.0
            d["initial_mean"] = (initial_max + initial_min) / 2.0
        if initial_strategy:
            d["initial_strategy"] = initial_strategy
        if is_static:
            d["is_static"] = True
        if l1_rate is not None:
            d["l1_rate"] = l1_rate
        if l2_rate is not None:
            d["l2_rate"] = l2_rate
        if learning_rate is not None:
            d["learning_rate"] = learning_rate
        if momentum is not None:
            d["momentum"] = momentum
        if gradient_clipping_threshold is not None:
            d["gradient_clipping_threshold"] = gradient_clipping_threshold
        if sparse_update:
            d["sparse_update"] = True
        if update_hooks is not None:
            d["update_hooks"] = update_hooks
        super().__init__(d)

    @staticmethod
    def to_bias(bias_attr):
        if isinstance(bias_attr, ParameterAttribute):
            return bias_attr
        return False if bias_attr is False else bias_attr


ParamAttr = ParameterAttribute


class ExtraLayerAttribute(dict):
    """Reference ExtraLayerAttribute -> layer_attr dict merged into cfg."""

    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        d = {}
        if drop_rate is not None:
            d["drop_rate"] = drop_rate
        if error_clipping_threshold is not None:
            d["error_clipping_threshold"] = error_clipping_threshold
        # device placement is XLA's job; accepted and ignored
        super().__init__(d)

    @staticmethod
    def to_kwargs(attr):
        return dict(attr) if attr else {}


ExtraAttr = ExtraLayerAttribute


class HookAttribute(dict):
    """Static pruning hook (reference ParameterUpdaterHook.cpp:36).

    sparsity_ratio=r prunes the r fraction of smallest-|w| weights at init;
    mask_filename loads the reference's packed-bit mask file.  Attach via
    ParameterAttribute(update_hooks=...); the trainer masks the parameter
    value at init and its gradient every step (trainer/hooks.py)."""

    def __init__(self, type="pruning", sparsity_ratio=None,
                 mask_filename=None):
        d = dict(type=type)
        if sparsity_ratio is not None:
            if not 0.0 <= sparsity_ratio <= 1.0:
                raise ValueError(
                    f"sparsity_ratio must be in [0, 1], got {sparsity_ratio}")
            d["sparsity_ratio"] = sparsity_ratio
        if mask_filename is not None:
            d["mask_filename"] = mask_filename
        super().__init__(d)


HookAttr = HookAttribute


# ---------------------------------------------------------------- poolings

class BasePoolingType:
    name = "max"

    def __repr__(self):
        return self.name


def _pool(clsname, value):
    # reference pooling types take optional args (MaxPooling
    # (output_max_index=...), SquareRootNPooling()) — accept and ignore
    return type(clsname, (BasePoolingType,),
                {"name": value,
                 "__init__": lambda self, *a, **kw: None})


MaxPooling = _pool("MaxPooling", "max")
CudnnMaxPooling = _pool("CudnnMaxPooling", "max")
AvgPooling = _pool("AvgPooling", "avg")
CudnnAvgPooling = _pool("CudnnAvgPooling", "avg")
SumPooling = _pool("SumPooling", "sum")
SquareRootNPooling = _pool("SquareRootNPooling", "sqrtn")
MaxWithIdPooling = _pool("MaxWithIdPooling", "max")


# ----------------------------------------------- optimizers + settings()

class BaseSGDOptimizer:
    """Carries the reference optimizer name + kwargs; lowered to a
    paddle_tpu.optim optimizer by config_parser.config_to_runtime."""

    optim_name = "momentum"

    def __init__(self, **kw):
        self.kw = kw


class MomentumOptimizer(BaseSGDOptimizer):
    optim_name = "momentum"

    def __init__(self, momentum=0.9, sparse=False):
        super().__init__(momentum=momentum)


class AdamOptimizer(BaseSGDOptimizer):
    optim_name = "adam"

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        super().__init__(beta1=beta1, beta2=beta2, epsilon=epsilon)


class AdamaxOptimizer(BaseSGDOptimizer):
    optim_name = "adamax"

    def __init__(self, beta1=0.9, beta2=0.999):
        super().__init__(beta1=beta1, beta2=beta2)


class AdaGradOptimizer(BaseSGDOptimizer):
    optim_name = "adagrad"

    def __init__(self):
        super().__init__()


class DecayedAdaGradOptimizer(BaseSGDOptimizer):
    optim_name = "decayed_adagrad"

    def __init__(self, rho=0.95, epsilon=1e-6):
        super().__init__(rho=rho, epsilon=epsilon)


class AdaDeltaOptimizer(BaseSGDOptimizer):
    optim_name = "adadelta"

    def __init__(self, rho=0.95, epsilon=1e-6):
        super().__init__(rho=rho, epsilon=epsilon)


class RMSPropOptimizer(BaseSGDOptimizer):
    optim_name = "rmsprop"

    def __init__(self, rho=0.95, epsilon=1e-6):
        super().__init__(rho=rho, epsilon=epsilon)


class BaseRegularization:
    l1 = 0.0
    l2 = 0.0


class L2Regularization(BaseRegularization):
    def __init__(self, rate):
        self.l2 = rate


class L1Regularization(BaseRegularization):
    def __init__(self, rate):
        self.l1 = rate


class ModelAverage:
    def __init__(self, average_window, max_average_window=None):
        self.average_window = average_window
        self.max_average_window = max_average_window


class GradientClippingThreshold:
    def __init__(self, threshold):
        self.threshold = threshold


def _ctx():
    from paddle_tpu.compat import config_parser
    return config_parser.active_context()


def settings(batch_size=256, learning_rate=1e-3, learning_method=None,
             regularization=None, is_async=False, model_average=None,
             gradient_clipping_threshold=None, learning_rate_decay_a=0.0,
             learning_rate_decay_b=0.0, learning_rate_schedule="poly",
             learning_rate_args="", average_window=0,
             max_average_window=None, **kw):
    """Reference trainer_config_helpers.optimizers.settings -> records the
    optimization config on the active parse context."""
    ctx = _ctx()
    ctx.settings.update(
        batch_size=batch_size, learning_rate=learning_rate,
        learning_method=learning_method or MomentumOptimizer(momentum=0.0),
        regularization=regularization,
        gradient_clipping_threshold=gradient_clipping_threshold,
        learning_rate_decay_a=learning_rate_decay_a,
        learning_rate_decay_b=learning_rate_decay_b,
        learning_rate_schedule=learning_rate_schedule,
        learning_rate_args=learning_rate_args,
        model_average=model_average,
        average_window=average_window,
        max_average_window=max_average_window,
        is_async=is_async)
    ctx.settings.update(kw)


def get_config_arg(name, type_=str, default=None, **_):
    """Reference get_config_arg: typed lookup in --config_args."""
    ctx = _ctx()
    if name not in ctx.config_args:
        return default
    v = ctx.config_args[name]
    if type_ is bool and isinstance(v, str):
        return v.lower() in ("1", "true", "yes", "on")
    return type_(v)


def get_batch_size():
    return _ctx().settings.get("batch_size", 256)


def define_py_data_sources2(train_list, test_list, module, obj, args=None,
                            train_async=False, data_cls=None):
    """Reference data_sources.define_py_data_sources2: record the provider
    module/obj/args + file lists; the runtime builder imports the module
    (config dir on sys.path) and drives the @provider reader."""
    ctx = _ctx()
    if isinstance(obj, (list, tuple)):
        train_obj, test_obj = obj
    else:
        train_obj = test_obj = obj
    if isinstance(module, (list, tuple)):
        train_mod, test_mod = module
    else:
        train_mod = test_mod = module
    if isinstance(args, (list, tuple)) and len(args) == 2 and all(
            isinstance(a, dict) for a in args):
        train_args, test_args = args
    else:
        train_args = test_args = args or {}
    if train_list:
        ctx.data_sources["train"] = dict(file_list=train_list,
                                         module=train_mod, obj=train_obj,
                                         args=train_args)
    if test_list:
        ctx.data_sources["test"] = dict(file_list=test_list, module=test_mod,
                                        obj=test_obj, args=test_args)


def define_py_data_sources(train_list, test_list, module, obj, args=None,
                           train_async=False, data_cls=None):
    # the v1 (PyDataProvider1) variant; same recording, providers are
    # expected in PyDataProvider2 style here
    return define_py_data_sources2(train_list, test_list, module, obj, args)
