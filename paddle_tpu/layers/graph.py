"""Layer-graph IR + compiler.

The reference's front-end is a Python DSL whose ctors register layer configs
into a global proto (config_parser.py:166-184 @config_layer registries,
emitting ModelConfig — "the protobuf IS the IR", SURVEY.md §1).  The
TPU-native redesign keeps the DSL surface but compiles to a *functional* IR:

  ctor (fc_layer, lstmemory, ...) -> LayerOutput node (name, type, size, inputs)
  Topology(outputs)               -> topological order over nodes
  Topology.init(rng)              -> params pytree {layer_name: {param: array}}
  Topology.apply(params, feed)    -> pure function, jit/grad/pjit-able

Values flowing between layers are either plain arrays [B, D] (one row per
sample) or SequenceBatch (padded [B, T, D] + lengths) — the reference's
Argument with sequenceStartPositions.  Layer kernels accept both via
row-mapping (the reference's layers see a flat row matrix either way).

Each layer type registers a LayerImpl:
  infer(cfg, in_sizes) -> output size
  init(rng, cfg, in_sizes) -> param dict (may be {})
  apply(ctx, cfg, params, *inputs) -> output value
"""

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import NestedSequenceBatch, SequenceBatch
from paddle_tpu.utils.error import ConfigError

_LAYER_IMPLS: Dict[str, "LayerImpl"] = {}
_NAME_COUNTERS: Dict[str, int] = {}

# observers notified of every LayerOutput constructed — the recurrent_group
# tracer uses this to see step-graph nodes that are CONSUMERS of the step
# outputs (e.g. `last_seq(inner_out, name="outer_rnn_state")` as a memory
# link target, the reference sequence_nest_rnn.conf pattern)
_NODE_OBSERVERS: List[Callable] = []


@dataclasses.dataclass
class LayerImpl:
    type: str
    infer: Callable            # (cfg, in_sizes) -> int
    init: Callable             # (rng, cfg, in_sizes) -> dict
    apply: Callable            # (ctx, cfg, params, *inputs) -> value


def register_layer(type_name):
    def deco(cls_or_fns):
        impl = cls_or_fns() if isinstance(cls_or_fns, type) else cls_or_fns
        _LAYER_IMPLS[type_name] = LayerImpl(
            type=type_name,
            infer=getattr(impl, "infer"),
            init=getattr(impl, "init", lambda rng, cfg, in_sizes: {}),
            apply=getattr(impl, "apply"))
        return cls_or_fns
    return deco


def get_impl(type_name) -> LayerImpl:
    try:
        return _LAYER_IMPLS[type_name]
    except KeyError:
        raise ConfigError(f"no layer impl registered for type {type_name!r}")


def auto_name(prefix):
    n = _NAME_COUNTERS.get(prefix, 0)
    _NAME_COUNTERS[prefix] = n + 1
    return f"__{prefix}_{n}__"


def reset_names():
    _NAME_COUNTERS.clear()


class LayerOutput:
    """A node in the layer graph (reference: the LayerOutput returned by every
    trainer_config_helpers ctor, wrapping a config_parser Layer)."""

    __slots__ = ("name", "layer_type", "size", "inputs", "cfg", "is_seq",
                 "num_filters", "img_shape")

    def __init__(self, name, layer_type, size, inputs=(), cfg=None,
                 is_seq=None, num_filters=None, img_shape=None):
        self.name = name
        self.layer_type = layer_type
        self.size = int(size)
        self.inputs: List[LayerOutput] = list(inputs)
        self.cfg = dict(cfg or {})
        # sequence-ness propagates: seq in -> seq out unless overridden
        if is_seq is None:
            is_seq = any(getattr(i, "is_seq", False) for i in self.inputs)
        self.is_seq = is_seq
        self.num_filters = num_filters      # conv image metadata
        self.img_shape = img_shape          # (h, w) after this layer
        for obs in _NODE_OBSERVERS:
            obs(self)

    def __repr__(self):
        return (f"LayerOutput({self.name}, {self.layer_type}, size={self.size}"
                f"{', seq' if self.is_seq else ''})")

    # arithmetic operators are installed by paddle_tpu.layers.layer_math
    # (the reference layer_math.py monkeypatches +,-,* the same way)


class Context:
    """Per-apply execution context: mode, rng, mutable-state collection
    (batch-norm moving stats thread through here, functionally)."""

    def __init__(self, mode="train", rng=None, state=None, params=None):
        self.mode = mode                  # "train" | "test"
        self.rng = rng
        self.state_in = state or {}       # {layer_name: pytree} (e.g. BN stats)
        self.state_out = {}
        self.aux = {}                     # scratch (e.g. recurrent_group outputs)
        # full top-level params dict: container layers (recurrent_group,
        # beam_search) apply their step sub-graphs against this, so step-layer
        # params live at top level under their own param-sharing keys and flow
        # between training groups and generation (reference shares by layer
        # name across sub-models the same way, config_parser.py sub_models)
        self.params = params

    def is_train(self):
        return self.mode == "train"

    def next_rng(self):
        if self.rng is None:
            raise ConfigError("this graph needs an rng (dropout/sampling); "
                              "pass rng= to Topology.apply")
        self.rng, sub = jax.random.split(self.rng)
        return sub

    def get_state(self, name, default_fn):
        if name in self.state_in:
            return self.state_in[name]
        return default_fn()

    def put_state(self, name, value):
        self.state_out[name] = value


# ---------------------------------------------------------------- helpers

from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _error_clip(x, threshold):
    """Identity forward; backward clips the incoming gradient to
    [-threshold, threshold] elementwise (reference ExtraLayerAttribute
    error_clipping_threshold, Layer.cpp backwardActivation clipping)."""
    return x


def _error_clip_fwd(x, threshold):
    return x, None


def _error_clip_bwd(threshold, _, g):
    return (jnp.clip(g, -threshold, threshold),)


_error_clip.defvjp(_error_clip_fwd, _error_clip_bwd)


def value_data(v):
    return v.data if isinstance(v, (SequenceBatch, NestedSequenceBatch)) \
        else v


def map_rows(fn, *values):
    """Apply a row-wise fn to values that may be SequenceBatch,
    NestedSequenceBatch, or arrays.  If any input is a (nested) sequence,
    output keeps its lengths structure."""
    seq = next((v for v in values
                if isinstance(v, (SequenceBatch, NestedSequenceBatch))), None)
    datas = [value_data(v) for v in values]
    out = fn(*datas)
    if isinstance(seq, NestedSequenceBatch):
        return NestedSequenceBatch(data=out,
                                   outer_lengths=seq.outer_lengths,
                                   inner_lengths=seq.inner_lengths)
    if isinstance(seq, SequenceBatch):
        return SequenceBatch(data=out, lengths=seq.lengths)
    return out


def as_seq(v) -> SequenceBatch:
    if not isinstance(v, SequenceBatch):
        raise ConfigError(f"expected a sequence input, got array {getattr(v, 'shape', v)}")
    return v


# ---------------------------------------------------------------- topology

class Topology:
    """Compiled graph over one or more output layers (reference:
    v2/topology.py Topology walking cost layers -> ModelConfig)."""

    def __init__(self, outputs, extra_feeds=()):
        if isinstance(outputs, LayerOutput):
            outputs = [outputs]
        self.outputs = list(outputs)
        self.order = self._topo_sort(self.outputs)
        self.data_layers = {n.name: n for n in self.order
                            if n.layer_type == "data"}
        for feed in extra_feeds:
            self.data_layers.setdefault(feed.name, feed)

    @staticmethod
    def _topo_sort(outputs):
        seen, order = set(), []

        def visit(node, stack):
            if id(node) in seen:
                return
            if id(node) in stack:
                raise ConfigError(f"cycle through layer {node.name}")
            stack = stack | {id(node)}
            for dep in node.inputs:
                visit(dep, stack)
            seen.add(id(node))
            order.append(node)

        for out in outputs:
            visit(out, frozenset())
        return order

    def init(self, rng):
        """Initialize all parameters: {layer_name: {param_name: array}}.

        Layers with shared parameters (cfg['param_name']) alias the same
        entry keyed by that shared name.  Step sub-graphs of container layers
        (recurrent_group / beam_search) are initialized INTO the same
        top-level dict under their own param-sharing keys, so a decoder
        trained via recurrent_group and its generation-mode beam_search read
        the same weights when their step layers share names."""
        params = {}
        self._init_into(params, rng)
        return params

    def _init_into(self, params, rng):
        for node in self.order:
            sub = node.cfg.get("sub_topo")
            if isinstance(sub, Topology):
                rng, sk = jax.random.split(rng)
                sub._init_into(params, sk)
            impl = get_impl(node.layer_type)
            in_sizes = [i.size for i in node.inputs]
            rng, sub_rng = jax.random.split(rng)
            p = impl.init(sub_rng, node.cfg, in_sizes)
            if p:
                key = self._param_key(node)
                if key not in params:
                    params[key] = p
        return rng

    def _param_key(self, node):
        """Parameter-sharing key: explicit cfg['param_name'], else a
        ParamAttr name (the reference's ParameterAttribute(name=...) sharing
        mechanism), else the layer name."""
        if "param_name" in node.cfg:
            return node.cfg["param_name"]
        pa = node.cfg.get("param_attr")
        if isinstance(pa, dict) and pa.get("name"):
            return pa["name"]
        return node.name

    def apply(self, params, feed, mode="train", rng=None, state=None,
              return_state=False, extra_outputs=(), precomputed=None):
        """Run the graph.  feed: {data_layer_name: array|SequenceBatch}.
        precomputed: {node_name: value} — nodes whose values were computed
        elsewhere (the recurrent_group scan-invariant hoist) are taken as-is
        instead of re-applied."""
        ctx = Context(mode=mode, rng=rng, state=state, params=params)
        cache = {}
        for node in self.order:
            if precomputed and node.name in precomputed:
                cache[id(node)] = precomputed[node.name]
                continue
            if node.layer_type == "data":
                if node.name not in feed:
                    raise ConfigError(f"missing feed for data layer {node.name!r}")
                cache[id(node)] = feed[node.name]
                continue
            # recurrent_group feeds its step/memory/static placeholders by
            # name on each scan step
            if node.layer_type.startswith("__") and node.name in feed:
                cache[id(node)] = feed[node.name]
                continue
            impl = get_impl(node.layer_type)
            ins = [cache[id(i)] for i in node.inputs]
            p = params.get(self._param_key(node), {})
            try:
                val = impl.apply(ctx, node.cfg, p, *ins)
                # reference ExtraLayerAttribute(drop_rate=...) applies to any
                # layer's output; fc/mixed/dropout handle it inside their
                # impls, everything else gets it here
                rate = node.cfg.get("drop_rate", 0.0)
                if (rate and ctx.is_train()
                        and node.layer_type not in ("fc", "mixed", "dropout")):
                    def _drop(x, rate=rate):
                        keep = jax.random.bernoulli(ctx.next_rng(),
                                                    1.0 - rate, x.shape)
                        return jnp.where(keep, x / (1.0 - rate), 0.0)
                    val = map_rows(_drop, val)
                ect = node.cfg.get("error_clipping_threshold")
                if ect:
                    val = map_rows(lambda d: _error_clip(d, float(ect)), val)
                cache[id(node)] = val
            except Exception as e:
                # the reference dumps the active layer-name stack on FATAL
                # (utils/CustomStackTrace.h, pushed NeuralNetwork.cpp:247);
                # name the failing layer the same way
                if hasattr(e, "add_note"):
                    e.add_note(f"while applying layer {node.name!r} "
                               f"(type {node.layer_type!r})")
                raise
        outs = [cache[id(o)] for o in self.outputs]
        outs += [cache[id(o)] for o in extra_outputs if id(o) in cache]
        result = outs[0] if len(outs) == 1 else tuple(outs)
        if return_state:
            return result, ctx.state_out
        return result

    def init_state(self):
        """Initial mutable state (BN moving stats) for all layers that need it."""
        state = {}
        for node in self.order:
            if node.layer_type == "batch_norm":
                size = node.cfg["size"]
                state[node.name] = (jnp.zeros((size,)), jnp.ones((size,)))
        return state
