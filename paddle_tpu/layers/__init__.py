"""Layer DSL (the reference's trainer_config_helpers surface) + graph IR."""

from paddle_tpu.layers.graph import LayerOutput, Topology, Context
from paddle_tpu.layers.api import *          # noqa: F401,F403
from paddle_tpu.layers.vision import *       # noqa: F401,F403
from paddle_tpu.layers.recurrent import *    # noqa: F401,F403
from paddle_tpu.layers import networks
from paddle_tpu.layers import api as _api
from paddle_tpu.layers import vision as _vision
from paddle_tpu.layers import recurrent as _recurrent

__all__ = (["LayerOutput", "Topology", "Context", "networks"]
           + _api.__all__ + _vision.__all__ + _recurrent.__all__)
