"""Layer DSL (the reference's trainer_config_helpers surface) + graph IR."""

from paddle_tpu.layers.graph import LayerOutput, Topology, Context
from paddle_tpu.layers.api import *          # noqa: F401,F403
from paddle_tpu.layers.vision import *       # noqa: F401,F403
from paddle_tpu.layers.recurrent import *    # noqa: F401,F403
from paddle_tpu.layers.generation import *   # noqa: F401,F403
from paddle_tpu.layers import networks
from paddle_tpu.layers.networks import *     # noqa: F401,F403
from paddle_tpu.layers import recurrent_units
from paddle_tpu.layers.recurrent_units import *  # noqa: F401,F403
# installs the LayerOutput arithmetic operators (reference layer_math.py)
from paddle_tpu.layers import layer_math
from paddle_tpu.layers import api as _api
from paddle_tpu.layers import vision as _vision
from paddle_tpu.layers import recurrent as _recurrent
from paddle_tpu.layers import generation as _generation


class LayerType:
    """Reference LayerType string constants (trainer_config_helpers
    layers.py); config compatibility only — the functional IR dispatches on
    these type strings directly."""
    DATA = "data"
    FC_LAYER = "fc"
    MIXED_LAYER = "mixed"
    EMBEDDING_LAYER = "embedding"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "grumemory"
    RECURRENT_LAYER = "recurrent"
    CONV_LAYER = "conv"
    CONVTRANS_LAYER = "conv"
    CUDNNCONV_LAYER = "conv"        # plain/cudnn variants collapse into XLA
    POOL_LAYER = "pool"
    BATCH_NORM_LAYER = "batch_norm"
    CRF_LAYER = "crf"
    CTC_LAYER = "ctc"
    COST = "classification_cost"


def layer_support(*attrs):
    """Reference layer_support decorator (declares ERROR_CLIPPING/DROPOUT
    support per ctor); attribute plumbing is handled by layer_attr cfg here,
    so this is an identity decorator kept for config compatibility."""
    def deco(fn):
        return fn
    return deco


__all__ = (["LayerOutput", "Topology", "Context", "networks", "LayerType",
            "layer_support"]
           + _api.__all__ + _vision.__all__ + _recurrent.__all__
           + _generation.__all__ + networks.__all__
           + recurrent_units.__all__)
