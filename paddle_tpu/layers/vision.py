"""Vision layer ctors: conv / pool / norm / image utility layers.

Reference: SURVEY.md §2.2 "Conv/vision" — ExpandConvLayer/CudnnConvLayer,
PoolLayer/CudnnPoolLayer, BatchNorm family, NormProjectionLayer (LRN),
MaxOutLayer, BilinearInterpLayer, BlockExpandLayer, SpatialPyramidPoolLayer,
PadLayer, PriorBox; size calc from math/MathUtils.cpp outputSize.

Row convention: like the reference, inter-layer values are flat rows
[B, C*H*W] (channel-major).  Impls reshape to NHWC for XLA/MXU convs and
flatten back; the (h, w) metadata rides on LayerOutput.img_shape.
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes
from paddle_tpu.layers.graph import (
    LayerOutput, register_layer, auto_name, map_rows, value_data)
from paddle_tpu.layers.api import _winit, _maybe_bias
from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import activations
from paddle_tpu.ops.norm import batch_norm_train, batch_norm_infer
from paddle_tpu.utils.error import ConfigError

__all__ = [
    "img_conv_layer", "img_pool_layer", "batch_norm_layer",
    "img_cmrnorm_layer", "cross_channel_norm_layer", "maxout_layer",
    "bilinear_interp_layer", "block_expand_layer", "spp_layer", "pad_layer",
    "priorbox_layer", "data_norm_layer", "conv_projection", "conv_operator",
]


def _pair(v):
    return v if isinstance(v, (tuple, list)) else (v, v)


def _to_nhwc(d, c, h, w):
    return d.reshape(d.shape[0], c, h, w).transpose(0, 2, 3, 1)


def _to_rows(x):
    return x.transpose(0, 3, 1, 2).reshape(x.shape[0], -1)


def _channels(input, num_channels):
    """Channel inference (reference v1 semantics: num_channels defaults to
    the input layer's num_filters; a raw data layer with height/width set
    implies channels = size / (h*w))."""
    if num_channels:
        return num_channels
    if input.num_filters:
        return input.num_filters
    if input.img_shape:
        h, w = input.img_shape
        if h * w and input.size % (h * w) == 0:
            return input.size // (h * w)
    return 1


def _img_shape(node, channels):
    if node.img_shape is not None:
        return node.img_shape
    hw = int(round(math.sqrt(node.size // channels)))
    if hw * hw * channels != node.size:
        raise ConfigError(
            f"cannot infer square image shape for layer {node.name} "
            f"(size {node.size}, channels {channels}); pass height/width")
    return (hw, hw)


class _ConvImpl:
    def infer(self, cfg, in_sizes):
        return cfg["out_size"]

    def init(self, rng, cfg, in_sizes):
        kh, kw = cfg["filter"]
        cin, cout, groups = cfg["channels"], cfg["num_filters"], cfg["groups"]
        fan_in = (cin // groups) * kh * kw
        r1, r2 = jax.random.split(rng)
        std = (cfg.get("param_attr") or {}).get("initial_std",
                                                1.0 / math.sqrt(fan_in))
        w = std * jax.random.normal(r1, (kh, kw, cin // groups, cout),
                                    dtypes.param_dtype())
        p = {"w": w}
        b = _maybe_bias(r2, cfg.get("bias_attr", True), cout)
        if b is not None:
            p["b"] = b
        return p

    def apply(self, ctx, cfg, params, x):
        c, (h, w) = cfg["channels"], cfg["in_shape"]
        def fn(d):
            img = _to_nhwc(d, c, h, w)
            fn_ = conv_ops.conv2d_transpose if cfg.get("trans") else conv_ops.conv2d
            kw_ = {} if cfg.get("trans") else {"groups": cfg["groups"]}
            y = fn_(img, params["w"], params.get("b"),
                    stride=cfg["stride"], padding=cfg["padding"], **kw_)
            return _to_rows(activations.get(cfg.get("act"))(y))
        return map_rows(fn, x)


register_layer("conv")(_ConvImpl)


def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, groups=1, act="relu", name=None,
                   bias_attr=True, param_attr=None, trans=False,
                   filter_size_y=None, stride_y=None, padding_y=None,
                   layer_attr=None, shared_biases=True, layer_type=None):
    """Reference img_conv_layer (ExpandConvLayer/CudnnConvLayer merged —
    one XLA conv path)."""
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    # reference semantics (layers.py:2085-2136): filter_size/stride/padding
    # are the X (width) dimension — tuple form is (x, y) — and *_y is the
    # height
    if isinstance(filter_size, (tuple, list)):
        filter_size, filter_size_y = filter_size
    if isinstance(stride, (tuple, list)):
        stride, stride_y = stride
    if isinstance(padding, (tuple, list)):
        padding, padding_y = padding
    fh, fw = filter_size_y or filter_size, filter_size
    sh, sw = stride_y or stride, stride
    ph, pw = (padding_y if padding_y is not None else padding), padding
    if trans:
        oh = (in_shape[0] - 1) * sh - 2 * ph + fh
        ow = (in_shape[1] - 1) * sw - 2 * pw + fw
    else:
        oh = conv_ops.conv_output_size(in_shape[0], fh, sh, ph)
        ow = conv_ops.conv_output_size(in_shape[1], fw, sw, pw)
    out_size = num_filters * oh * ow
    cfg = {"filter": (fh, fw), "stride": (sh, sw), "padding": (ph, pw),
           "groups": groups, "channels": channels, "num_filters": num_filters,
           "in_shape": in_shape, "out_size": out_size, "act": act,
           "bias_attr": bias_attr, "param_attr": param_attr, "trans": trans}
    return LayerOutput(name or auto_name("conv"), "conv", out_size, [input],
                       cfg, num_filters=num_filters, img_shape=(oh, ow))


class _PoolImpl:
    def infer(self, cfg, in_sizes):
        return cfg["out_size"]

    def apply(self, ctx, cfg, params, x):
        c, (h, w) = cfg["channels"], cfg["in_shape"]
        (ph, pw), (eh, ew) = cfg["padding"], cfg["extra_pad"]
        pad = ((ph, ph + eh), (pw, pw + ew))
        def fn(d):
            img = _to_nhwc(d, c, h, w)
            if cfg["pool_type"] == "max":
                y = conv_ops.max_pool2d(img, cfg["window"], cfg["stride"], pad)
            else:
                y = conv_ops.avg_pool2d(img, cfg["window"], cfg["stride"], pad)
            return _to_rows(y)
        return map_rows(fn, x)


register_layer("pool")(_PoolImpl)


def img_pool_layer(input, pool_size, stride=1, num_channels=None,
                   pool_type="max", padding=0, name=None, pool_size_y=None,
                   stride_y=None, padding_y=None, ceil_mode=True):
    """Reference img_pool_layer.  ceil_mode matches the reference's
    outputSize with caffeMode=False (ceil division)."""
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    # same (x, y) convention as img_conv_layer
    if isinstance(pool_size, (tuple, list)):
        pool_size, pool_size_y = pool_size
    if isinstance(stride, (tuple, list)):
        stride, stride_y = stride
    if isinstance(padding, (tuple, list)):
        padding, padding_y = padding
    wh, ww = pool_size_y or pool_size, pool_size
    sh, sw = stride_y or stride, stride
    ph, pw = (padding_y if padding_y is not None else padding), padding
    pt = getattr(pool_type, "name", pool_type)
    pt = "avg" if "avg" in str(pt) else "max"

    def osize(insz, k, s, p):
        if ceil_mode:
            return int(math.ceil((insz + 2 * p - k) / s)) + 1
        return (insz + 2 * p - k) // s + 1

    oh, ow = osize(in_shape[0], wh, sh, ph), osize(in_shape[1], ww, sw, pw)
    out_size = channels * oh * ow
    # reduce_window needs explicit lo/hi padding; the ceil-mode overhang
    # is whatever the output size requires BEYOND the symmetric 2*p
    # (subtracting p only once would double-pad the high side whenever
    # base padding is nonzero — inception's 3x3 s1 p1 pools hit this)
    eh = (oh - 1) * sh + wh - in_shape[0] - 2 * ph
    ew = (ow - 1) * sw + ww - in_shape[1] - 2 * pw
    cfg = {"window": (wh, ww), "stride": (sh, sw),
           "padding": (ph, pw), "extra_pad": (max(eh, 0), max(ew, 0)),
           "channels": channels, "pool_type": pt, "in_shape": in_shape,
           "out_size": out_size}
    return LayerOutput(name or auto_name("pool"), "pool", out_size, [input],
                       cfg, num_filters=channels, img_shape=(oh, ow))


class _BatchNormImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def init(self, rng, cfg, in_sizes):
        n = cfg["size"]
        return {"gamma": jnp.ones((n,), dtypes.param_dtype()),
                "beta": jnp.zeros((n,), dtypes.param_dtype())}

    def apply(self, ctx, cfg, params, x):
        n = cfg["size"]
        name = cfg["name"]
        mean0 = lambda: (jnp.zeros((n,)), jnp.ones((n,)))
        mmean, mvar = ctx.get_state(name, mean0)
        c = cfg.get("channels")

        def fn(d):
            if c and c != d.shape[-1]:
                # image batch norm: normalize per channel over B,H,W
                b = d.shape[0]
                img = d.reshape(b, c, -1).transpose(0, 2, 1).reshape(-1, c)
                g, bt = params["gamma"][:c], params["beta"][:c]
                if ctx.is_train() and not cfg.get("use_global_stats"):
                    y, (nm, nv) = batch_norm_train(
                        img, g, bt, mmean[:c], mvar[:c],
                        momentum=cfg.get("moving_average_fraction", 0.9))
                    ctx.put_state(name, (mmean.at[:c].set(nm),
                                         mvar.at[:c].set(nv)))
                else:
                    y = batch_norm_infer(img, g, bt, mmean[:c], mvar[:c])
                y = y.reshape(b, -1, c).transpose(0, 2, 1).reshape(b, -1)
                return activations.get(cfg.get("act"))(y)
            if ctx.is_train() and not cfg.get("use_global_stats"):
                y, st = batch_norm_train(
                    d.reshape(-1, d.shape[-1]), params["gamma"], params["beta"],
                    mmean, mvar,
                    momentum=cfg.get("moving_average_fraction", 0.9))
                ctx.put_state(name, st)
                y = y.reshape(d.shape)
            else:
                y = batch_norm_infer(d, params["gamma"], params["beta"],
                                     mmean, mvar)
            return activations.get(cfg.get("act"))(y)
        return map_rows(fn, x)


register_layer("batch_norm")(_BatchNormImpl)


def batch_norm_layer(input, act=None, name=None, num_channels=None,
                     bias_attr=True, param_attr=None, use_global_stats=None,
                     moving_average_fraction=0.9, layer_attr=None):
    """Reference batch_norm_layer.  For conv inputs stats are per-channel
    (channels = input.num_filters); for fc inputs per-feature."""
    nm = name or auto_name("batch_norm")
    channels = num_channels or input.num_filters
    size = input.size
    stat_size = channels if (channels and input.img_shape) else size
    cfg = {"size": stat_size, "name": nm, "act": act,
           "use_global_stats": use_global_stats,
           "moving_average_fraction": moving_average_fraction,
           "channels": channels if input.img_shape else None}
    return LayerOutput(nm, "batch_norm", size, [input], cfg,
                       num_filters=input.num_filters, img_shape=input.img_shape)


class _CmrNormImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def apply(self, ctx, cfg, params, x):
        c, (h, w) = cfg["channels"], cfg["in_shape"]

        def fn(d):
            img = _to_nhwc(d, c, h, w)
            y = conv_ops.lrn_cross_map(img, cfg["norm_size"], cfg["scale"],
                                       cfg["power"])
            return _to_rows(y)
        return map_rows(fn, x)


register_layer("cmrnorm")(_CmrNormImpl)


def img_cmrnorm_layer(input, size=5, scale=0.0128, power=0.75,
                      num_channels=None, name=None):
    """Reference img_cmrnorm_layer (cross-map LRN; default scale matches
    trainer_config_helpers)."""
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    cfg = {"norm_size": size, "scale": scale, "power": power,
           "channels": channels, "in_shape": in_shape}
    return LayerOutput(name or auto_name("cmrnorm"), "cmrnorm", input.size,
                       [input], cfg, num_filters=channels, img_shape=in_shape)


class _CrossChannelNormImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def init(self, rng, cfg, in_sizes):
        return {"scale": jnp.ones((cfg["channels"],), dtypes.param_dtype())}

    def apply(self, ctx, cfg, params, x):
        c, (h, w) = cfg["channels"], cfg["in_shape"]

        def fn(d):
            img = _to_nhwc(d, c, h, w)
            return _to_rows(conv_ops.cross_channel_norm(img, params["scale"]))
        return map_rows(fn, x)


register_layer("cross_channel_norm")(_CrossChannelNormImpl)


def cross_channel_norm_layer(input, num_channels=None, name=None,
                             param_attr=None):
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    return LayerOutput(name or auto_name("ccn"), "cross_channel_norm",
                       input.size, [input],
                       {"channels": channels, "in_shape": in_shape},
                       num_filters=channels, img_shape=in_shape)


class _MaxoutImpl:
    def infer(self, cfg, in_sizes):
        return cfg["out_size"]

    def apply(self, ctx, cfg, params, x):
        c, (h, w) = cfg["channels"], cfg["in_shape"]

        def fn(d):
            img = _to_nhwc(d, c, h, w)
            return _to_rows(conv_ops.maxout(img, cfg["groups"]))
        return map_rows(fn, x)


register_layer("maxout")(_MaxoutImpl)


def maxout_layer(input, groups, num_channels=None, name=None):
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    out_size = input.size // groups
    return LayerOutput(name or auto_name("maxout"), "maxout", out_size,
                       [input], {"groups": groups, "channels": channels,
                                 "in_shape": in_shape},
                       num_filters=channels // groups, img_shape=in_shape)


class _BilinearImpl:
    def infer(self, cfg, in_sizes):
        return cfg["out_size"]

    def apply(self, ctx, cfg, params, x):
        c, (h, w) = cfg["channels"], cfg["in_shape"]

        def fn(d):
            img = _to_nhwc(d, c, h, w)
            return _to_rows(conv_ops.bilinear_interp(img, *cfg["out_shape"]))
        return map_rows(fn, x)


register_layer("bilinear_interp")(_BilinearImpl)


def bilinear_interp_layer(input, out_size_x, out_size_y, num_channels=None,
                          name=None):
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    out_size = channels * out_size_x * out_size_y
    return LayerOutput(name or auto_name("bilinear"), "bilinear_interp",
                       out_size, [input],
                       {"channels": channels, "in_shape": in_shape,
                        "out_shape": (out_size_y, out_size_x),
                        "out_size": out_size},
                       num_filters=channels, img_shape=(out_size_y, out_size_x))


class _BlockExpandImpl:
    def infer(self, cfg, in_sizes):
        return cfg["out_size"]

    def apply(self, ctx, cfg, params, x):
        from paddle_tpu.core.sequence import SequenceBatch
        c, (h, w) = cfg["channels"], cfg["in_shape"]
        d = value_data(x)
        img = _to_nhwc(d, c, h, w)
        patches = conv_ops.block_expand(img, cfg["block"], cfg["stride"],
                                        cfg["padding"])
        n = patches.shape[1]
        return SequenceBatch(data=patches,
                             lengths=jnp.full((d.shape[0],), n, jnp.int32))


register_layer("block_expand")(_BlockExpandImpl)


def block_expand_layer(input, block_x, block_y, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, num_channels=None, name=None):
    """im2col as a sequence: output is a sequence of patch rows (reference
    BlockExpandLayer -> OCR pipelines feeding CTC)."""
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    out_size = block_x * block_y * channels
    return LayerOutput(name or auto_name("block_expand"), "block_expand",
                       out_size, [input],
                       {"channels": channels, "in_shape": in_shape,
                        "block": (block_y, block_x),
                        "stride": (stride_y, stride_x),
                        "padding": (padding_y, padding_x),
                        "out_size": out_size}, is_seq=True)


class _SppImpl:
    def infer(self, cfg, in_sizes):
        return cfg["out_size"]

    def apply(self, ctx, cfg, params, x):
        c, (h, w) = cfg["channels"], cfg["in_shape"]

        def fn(d):
            img = _to_nhwc(d, c, h, w)
            return conv_ops.spatial_pyramid_pool(img, cfg["pyramid_height"],
                                                 cfg["pool_type"])
        return map_rows(fn, x)


register_layer("spp")(_SppImpl)


def spp_layer(input, pyramid_height, num_channels=None, pool_type="max",
              name=None):
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    pt = "avg" if "avg" in str(getattr(pool_type, "name", pool_type)) else "max"
    out_size = channels * sum(4 ** i for i in range(pyramid_height))
    return LayerOutput(name or auto_name("spp"), "spp", out_size, [input],
                       {"channels": channels, "in_shape": in_shape,
                        "pyramid_height": pyramid_height, "pool_type": pt,
                        "out_size": out_size}, is_seq=False)


class _PadImpl:
    def infer(self, cfg, in_sizes):
        return cfg["out_size"]

    def apply(self, ctx, cfg, params, x):
        c, (h, w) = cfg["channels"], cfg["in_shape"]

        def fn(d):
            img = _to_nhwc(d, c, h, w)
            return _to_rows(conv_ops.pad_chw(img, cfg["pad_c"], cfg["pad_h"],
                                             cfg["pad_w"]))
        return map_rows(fn, x)


register_layer("pad")(_PadImpl)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, num_channels=None,
              name=None):
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    pc, ph, pw = tuple(pad_c or (0, 0)), tuple(pad_h or (0, 0)), tuple(pad_w or (0, 0))
    oc = channels + pc[0] + pc[1]
    oh = in_shape[0] + ph[0] + ph[1]
    ow = in_shape[1] + pw[0] + pw[1]
    return LayerOutput(name or auto_name("pad"), "pad", oc * oh * ow, [input],
                       {"channels": channels, "in_shape": in_shape,
                        "pad_c": pc, "pad_h": ph, "pad_w": pw,
                        "out_size": oc * oh * ow},
                       num_filters=oc, img_shape=(oh, ow))


class _PriorBoxImpl:
    def infer(self, cfg, in_sizes):
        return cfg["out_size"]

    def apply(self, ctx, cfg, params, x, img):
        boxes = conv_ops.prior_box(cfg["in_shape"], cfg["image_shape"],
                                   cfg["min_sizes"], cfg["max_sizes"],
                                   cfg["aspect_ratios"], cfg["variance"])
        return boxes.reshape(1, -1)


register_layer("priorbox")(_PriorBoxImpl)


def priorbox_layer(input, image, min_size, max_size=None, aspect_ratio=(2.0,),
                   variance=(0.1, 0.1, 0.2, 0.2), num_channels=None,
                   name=None):
    channels = _channels(input, num_channels)
    in_shape = _img_shape(input, channels)
    img_channels = image.num_filters or 3
    image_shape = _img_shape(image, img_channels)
    n_prior = len(min_size) * (2 if max_size else 1) + 2 * len(aspect_ratio)
    out_size = in_shape[0] * in_shape[1] * n_prior * 8
    return LayerOutput(name or auto_name("priorbox"), "priorbox", out_size,
                       [input, image],
                       {"in_shape": in_shape, "image_shape": image_shape,
                        "min_sizes": list(min_size),
                        "max_sizes": list(max_size or []),
                        "aspect_ratios": list(aspect_ratio),
                        "variance": tuple(variance), "out_size": out_size},
                       is_seq=False)


class _DataNormImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def init(self, rng, cfg, in_sizes):
        n = in_sizes[0]
        return {"mean": jnp.zeros((n,)), "std_inv": jnp.ones((n,)),
                "min": jnp.zeros((n,)), "span_inv": jnp.ones((n,))}

    def apply(self, ctx, cfg, params, x):
        from paddle_tpu.ops import math_ops
        return map_rows(
            lambda d: math_ops.data_norm(d, params["mean"], params["std_inv"],
                                         cfg.get("strategy", "z-score"),
                                         params["min"], params["span_inv"]), x)


register_layer("data_norm")(_DataNormImpl)


def data_norm_layer(input, strategy="z-score", name=None):
    return LayerOutput(name or auto_name("data_norm"), "data_norm", input.size,
                       [input], {"strategy": strategy})


# ----------------------------------------------- conv projection/operator
# (mixed_layer parts; reference ConvProjection / ConvOperator.cpp:58)

def _xy(x_val, y_val):
    """Reference conv-geometry convention: the scalar/first-tuple-element is
    the X (width) dimension, *_y (or second element) the height -> (h, w)."""
    if isinstance(x_val, (tuple, list)):
        x_val, y_val = x_val
    return (y_val if y_val is not None else x_val), x_val


def _conv_part_spec(img, filter_size, num_filters, num_channels, stride,
                    padding, filter_size_y=None, stride_y=None,
                    padding_y=None):
    from paddle_tpu.layers.api import _Part  # local: avoid import cycle
    channels = _channels(img, num_channels)
    in_shape = _img_shape(img, channels)
    fh, fw = _xy(filter_size, filter_size_y)
    sh, sw = _xy(stride, stride_y)
    ph, pw = _xy(padding, padding_y)
    oh = conv_ops.conv_output_size(in_shape[0], fh, sh, ph)
    ow = conv_ops.conv_output_size(in_shape[1], fw, sw, pw)
    spec = {"filter_size": (fh, fw), "stride": (sh, sw), "padding": (ph, pw),
            "channels": channels, "num_filters": num_filters,
            "in_shape": in_shape}
    return _Part, spec, num_filters * oh * ow


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, param_attr=None,
                    filter_size_y=None, stride_y=None, padding_y=None,
                    groups=1, trans=False):
    """Learned-filter conv as a mixed_layer projection (reference
    ConvProjection / ConvTransProjection via trans=)."""
    if trans:
        from paddle_tpu.utils.logging import logger
        logger.warning("conv_projection(trans=True): transposed projection "
                       "runs as a standard conv projection; numerics differ "
                       "until ConvTransProjection lands")
    _Part, spec, out = _conv_part_spec(input, filter_size, num_filters,
                                       num_channels, stride, padding,
                                       filter_size_y, stride_y, padding_y)
    spec["param_attr"] = param_attr
    spec["groups"] = groups
    return _Part("conv_proj", [input], spec, out)


def conv_operator(img, filter, filter_size, num_filters, num_channels=None,
                  stride=1, padding=0, filter_size_y=None, stride_y=None,
                  padding_y=None, trans=False):
    """Per-sample conv where each row of `filter` is that sample's own
    filter bank (reference ConvOperator.cpp:58-83 loops over batchId).
    trans=True is accepted for config parity (ConvTransOperator); the
    transposed per-sample path is not yet implemented."""
    if trans:
        from paddle_tpu.utils.logging import logger
        logger.warning("conv_operator(trans=True): transposed per-sample "
                       "conv runs as a standard conv_operator graph node; "
                       "numerics differ until ConvTransOperator lands")
    _Part, spec, out = _conv_part_spec(img, filter_size, num_filters,
                                       num_channels, stride, padding,
                                       filter_size_y, stride_y, padding_y)
    return _Part("conv_op", [img, filter], spec, out)
