"""Composite networks.

Reference: trainer_config_helpers/networks.py — simple_img_conv_pool,
img_conv_group, vgg_16_network, simple_lstm, simple_gru, bidirectional_lstm,
simple_attention (:1273), text_conv_pool, sequence_conv_pool.
"""

import jax.numpy as jnp

from paddle_tpu.layers import api, vision, recurrent
from paddle_tpu.layers.api import (
    fc_layer, mixed_layer, full_matrix_projection, concat_layer,
    pooling_layer, pooling, dropout_layer)
from paddle_tpu.layers.graph import LayerOutput, auto_name
from paddle_tpu.layers.vision import img_conv_layer, img_pool_layer, batch_norm_layer
from paddle_tpu.layers.recurrent import lstmemory, grumemory

__all__ = [
    "simple_img_conv_pool", "img_conv_group", "img_conv_bn_pool",
    "vgg_16_network", "small_vgg",
    "simple_lstm", "simple_gru", "simple_gru2", "gru_unit", "gru_group",
    "lstmemory_unit", "lstmemory_group",
    "bidirectional_lstm", "bidirectional_gru", "simple_attention",
    "text_conv_pool", "sequence_conv_pool", "inputs", "outputs",
]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         num_channels=None, pool_stride=1, act="relu",
                         conv_padding=0, pool_type="max", name=None):
    conv = img_conv_layer(input, filter_size=filter_size,
                          num_filters=num_filters, num_channels=num_channels,
                          padding=conv_padding, act=act,
                          name=name and f"{name}_conv")
    return img_pool_layer(conv, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type, name=name and f"{name}_pool")


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act="relu",
                   conv_with_batchnorm=False, pool_stride=2,
                   pool_type="max", conv_batchnorm_drop_rate=None):
    """VGG-style conv block (reference img_conv_group)."""
    tmp = input
    drops = conv_batchnorm_drop_rate or [0.0] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        tmp = img_conv_layer(tmp, filter_size=conv_filter_size,
                             num_filters=nf,
                             num_channels=num_channels if i == 0 else None,
                             padding=conv_padding,
                             act=None if conv_with_batchnorm else conv_act)
        if conv_with_batchnorm:
            tmp = batch_norm_layer(tmp, act=conv_act)
            if drops[i]:
                tmp = dropout_layer(tmp, drops[i])
    return img_pool_layer(tmp, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type)


def vgg_16_network(input_image, num_channels, num_classes=1000):
    """Reference vgg_16_network."""
    tmp = img_conv_group(input_image, [64, 64], 2, num_channels=num_channels)
    tmp = img_conv_group(tmp, [128, 128], 2)
    tmp = img_conv_group(tmp, [256, 256, 256], 2)
    tmp = img_conv_group(tmp, [512, 512, 512], 2)
    tmp = img_pool_layer(tmp, pool_size=2, stride=2)
    tmp = fc_layer(tmp, size=4096, act="relu")
    tmp = dropout_layer(tmp, 0.5)
    tmp = fc_layer(tmp, size=4096, act="relu")
    tmp = dropout_layer(tmp, 0.5)
    return fc_layer(tmp, size=num_classes, act="softmax")


def simple_lstm(input, size, reverse=False, act="tanh", gate_act="sigmoid",
                state_act="tanh", name=None, mat_param_attr=None,
                bias_param_attr=True, inner_param_attr=None):
    """Reference simple_lstm: fc (4*size) -> lstmemory."""
    mix = fc_layer(input, size=size * 4, act=None, bias_attr=False,
                   param_attr=mat_param_attr,
                   name=name and f"{name}_transform")
    return lstmemory(mix, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, state_act=state_act, name=name,
                     bias_attr=bias_param_attr, param_attr=inner_param_attr)


def simple_gru(input, size, reverse=False, act="tanh", gate_act="sigmoid",
               name=None, mixed_param_attr=None, mixed_bias_param_attr=None,
               mixed_layer_attr=None, gru_bias_attr=True,
               gru_param_attr=None, gru_layer_attr=None, naive=False):
    """Reference simple_gru: fc (3*size) -> grumemory.  `naive` selects the
    reference's gru_step_naive (mixed-layer formulation so attrs apply); XLA
    fuses both formulations identically, so it only affects attrs here."""
    mix = fc_layer(input, size=size * 3, act=None,
                   bias_attr=(mixed_bias_param_attr
                              if mixed_bias_param_attr is not None else False),
                   param_attr=mixed_param_attr,
                   layer_attr=mixed_layer_attr,
                   name=name and f"{name}_transform")
    node = grumemory(mix, size=size, reverse=reverse, act=act or "tanh",
                     gate_act=gate_act or "sigmoid", name=name,
                     bias_attr=gru_bias_attr, param_attr=gru_param_attr)
    if gru_layer_attr:
        node.cfg.update(gru_layer_attr)
    return node


def bidirectional_lstm(input, size, name=None, return_seq=False):
    """Reference bidirectional_lstm: concat(fwd lstm, bwd lstm)."""
    fwd = simple_lstm(input, size, reverse=False, name=name and f"{name}_fwd")
    bwd = simple_lstm(input, size, reverse=True, name=name and f"{name}_bwd")
    if return_seq:
        return concat_layer([fwd, bwd])
    f_last = api.last_seq(fwd)
    b_first = api.first_seq(bwd)
    return concat_layer([f_last, b_first])


def text_conv_pool(input, context_len, hidden_size, context_start=None,
                   pool_type=None, act="relu", name=None):
    """Reference sequence_conv_pool / text_conv_pool: context window fc +
    sequence max pool."""
    ctx_proj = api.context_projection(input, context_len=context_len,
                                      context_start=context_start)
    conv = mixed_layer(size=hidden_size, input=[ctx_proj], act=act,
                       bias_attr=True, name=name and f"{name}_conv")
    return pooling_layer(conv, pooling_type=pool_type or pooling.Max,
                         name=name and f"{name}_pool")


sequence_conv_pool = text_conv_pool


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     name=None):
    """Bahdanau attention (reference networks.py:1273 simple_attention):
    score_t = v . tanh(enc_proj_t + W s);  context = sum softmax * enc.

    Used inside a recurrent_group with StaticInput encoder outputs.
    """
    decoder_proj = fc_layer(decoder_state, size=encoded_proj.size, act=None,
                            bias_attr=False, param_attr=transform_param_attr,
                            name=name and f"{name}_transform")
    return attention_context_layer(encoded_sequence, encoded_proj,
                                   decoder_proj,
                                   param_attr=softmax_param_attr, name=name)


# attention context as a first-class layer ---------------------------------

from paddle_tpu.layers.graph import register_layer, as_seq, value_data
from paddle_tpu.ops import attention as attn_ops
from paddle_tpu.layers.api import _winit


class _AttnContextImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def init(self, rng, cfg, in_sizes):
        return {"v": _winit(cfg.get("param_attr"))(rng, (cfg["att_size"],))}

    def apply(self, ctx, cfg, params, enc, enc_proj, dec_proj):
        enc_sb, proj_sb = as_seq(enc), as_seq(enc_proj)
        scores = attn_ops.additive_attention_scores(
            proj_sb, value_data(dec_proj), params["v"])
        return attn_ops.attention_context(scores, enc_sb)


register_layer("attention_context")(_AttnContextImpl)


def attention_context_layer(encoded_sequence, encoded_proj, decoder_proj,
                            param_attr=None, name=None):
    return LayerOutput(name or auto_name("attention"), "attention_context",
                       encoded_sequence.size,
                       [encoded_sequence, encoded_proj, decoder_proj],
                       {"att_size": encoded_proj.size, "param_attr": param_attr},
                       is_seq=False)


# ------------------------------------------------- remaining reference
# composites (networks.py:41-1410)

def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     num_channels=None, pool_stride=1, act="relu",
                     conv_padding=0, pool_type=None, name=None):
    """conv -> batch_norm -> pool (reference img_conv_bn_pool)."""
    conv = img_conv_layer(input, filter_size=filter_size,
                          num_filters=num_filters, num_channels=num_channels,
                          padding=conv_padding, act=None, bias_attr=False,
                          name=name and f"{name}_conv")
    bn = batch_norm_layer(conv, act=act, name=name and f"{name}_bn")
    return img_pool_layer(bn, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type, name=name and f"{name}_pool")


def small_vgg(input_image, num_channels, num_classes=10):
    """Reference small_vgg (CIFAR configs): 4 conv groups then fc."""
    def group(ipt, num_filter, times):
        return img_conv_group(ipt, conv_num_filter=[num_filter] * times,
                              pool_size=2, num_channels=None,
                              conv_filter_size=3, conv_act="relu",
                              conv_with_batchnorm=True, pool_stride=2)
    tmp = img_conv_group(input_image, conv_num_filter=[64, 64], pool_size=2,
                         num_channels=num_channels, conv_filter_size=3,
                         conv_act="relu", conv_with_batchnorm=True,
                         pool_stride=2)
    tmp = group(tmp, 128, 2)
    tmp = group(tmp, 256, 3)
    tmp = group(tmp, 512, 3)
    tmp = dropout_layer(tmp, 0.5)
    tmp = fc_layer(tmp, size=512, act=None)
    tmp = batch_norm_layer(tmp, act="relu")
    tmp = fc_layer(tmp, size=512, act="relu")
    return fc_layer(tmp, size=num_classes, act="softmax")


def simple_gru2(input, size, reverse=False, act="tanh", gate_act="sigmoid",
                name=None, mixed_param_attr=None, gru_param_attr=None):
    """Reference simple_gru2: same math as simple_gru with the reference's
    original parameter layout/attr split."""
    mix = fc_layer(input, size=size * 3, act=None, bias_attr=False,
                   param_attr=mixed_param_attr,
                   name=name and f"{name}_transform")
    return grumemory(mix, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, param_attr=gru_param_attr, name=name)


def gru_unit(input, size=None, name=None, act="tanh", gate_act="sigmoid",
             memory_boot=None, gru_bias_attr=None, gru_param_attr=None,
             naive=False, gru_layer_attr=None):
    """One GRU step for custom recurrent groups (reference gru_unit):
    creates the output memory link itself."""
    size = size or input.size // 3
    mem = recurrent.memory(name=name or "gru_unit_out", size=size,
                           boot_layer=memory_boot)
    step = (recurrent.gru_step_naive_layer if naive
            else recurrent.gru_step_layer)
    return step(input, mem, size=size, act=act, gate_act=gate_act,
                bias_attr=True if gru_bias_attr is None else gru_bias_attr,
                param_attr=gru_param_attr, name=name or "gru_unit_out")


def gru_group(input, size=None, name=None, reverse=False, act="tanh",
              gate_act="sigmoid", memory_boot=None, gru_bias_attr=None,
              gru_param_attr=None, naive=False, gru_layer_attr=None):
    """GRU as an explicit recurrent_group (reference gru_group) — same
    numbers as grumemory, built from the step primitive."""
    def step(x3):
        return gru_unit(x3, size=size, name=name and f"{name}_out",
                        act=act, gate_act=gate_act, memory_boot=memory_boot,
                        gru_bias_attr=gru_bias_attr,
                        gru_param_attr=gru_param_attr, naive=naive)
    return recurrent.recurrent_group(step, input=input, reverse=reverse,
                                     name=name)


def lstmemory_unit(input, size=None, name=None, act="tanh",
                   gate_act="sigmoid", state_act="tanh", memory_boot=None,
                   param_attr=None, mixed_bias_attr=None,
                   lstm_bias_attr=None, mixed_layer_attr=None,
                   lstm_layer_attr=None, get_output_layer_attr=None):
    """One LSTM step for custom groups (reference lstmemory_unit,
    networks.py:616-723): gates = identity(input) + W_r @ h_prev via a step
    mixed layer (param_attr names/shares W_r), then one lstm_step.  The
    [h|c] pair rides in one memory of width 2*size.  A reference-style
    memory_boot of width `size` boots h; c boots to zero (matching the
    reference, whose state memory boots zero unless given its own layer)."""
    size = size or input.size // 4
    nm = name or "lstm_unit"
    state_name = nm + "_state"
    if memory_boot is not None and memory_boot.size == size:
        # widen [B, size] h-boot to [B, 2*size] = [h | 0]
        zeros = api.slope_intercept_layer(memory_boot, slope=0.0,
                                          intercept=0.0)
        memory_boot = concat_layer([memory_boot, zeros])
    state = recurrent.memory(name=state_name, size=2 * size,
                             boot_layer=memory_boot)
    h_prev = mixed_layer(size=size,
                         input=[api.identity_projection(state, offset=0,
                                                        size=size)],
                         act=None, bias_attr=False, name=nm + "_prev_h")
    # the recurrent projection the reference puts in "%s_input_recurrent"
    gates = mixed_layer(
        size=4 * size,
        input=[api.identity_projection(input),
               api.full_matrix_projection(h_prev, param_attr=param_attr)],
        act=None,
        bias_attr=False if mixed_bias_attr is None else mixed_bias_attr,
        name=nm + "_input_recurrent")
    hc = recurrent.lstm_step_layer(
        gates, state, size=size, act=act, gate_act=gate_act,
        state_act=state_act,
        bias_attr=True if lstm_bias_attr is None else lstm_bias_attr,
        name=state_name)
    return mixed_layer(size=size,
                       input=[api.identity_projection(hc, offset=0,
                                                      size=size)],
                       act=None, bias_attr=False, name=nm)


def lstmemory_group(input, size=None, name=None, reverse=False, act="tanh",
                    gate_act="sigmoid", state_act="tanh", memory_boot=None,
                    param_attr=None, mixed_bias_attr=None,
                    lstm_bias_attr=None, mixed_layer_attr=None,
                    lstm_layer_attr=None, get_output_layer_attr=None):
    """LSTM as an explicit recurrent_group (reference lstmemory_group) —
    exactly the lstmemory math with per-step state access."""
    def step(x4):
        return lstmemory_unit(x4, size=size, name=name and f"{name}_unit",
                              act=act, gate_act=gate_act,
                              state_act=state_act, memory_boot=memory_boot,
                              param_attr=param_attr,
                              mixed_bias_attr=mixed_bias_attr,
                              lstm_bias_attr=lstm_bias_attr)
    return recurrent.recurrent_group(step, input=input, reverse=reverse,
                                     name=name)


def bidirectional_gru(input, size, name=None, return_seq=False):
    """Reference bidirectional_gru: concat(fwd gru, bwd gru)."""
    fwd = simple_gru(input, size, reverse=False, name=name and f"{name}_fwd")
    bwd = simple_gru(input, size, reverse=True, name=name and f"{name}_bwd")
    if return_seq:
        return concat_layer([fwd, bwd])
    return concat_layer([api.last_seq(fwd), api.first_seq(bwd)])


def inputs(layers, *args):
    """Reference inputs(): declares data-layer order; with the functional
    feed-dict API this is a no-op kept for config compatibility."""
    return None


def outputs(layers, *args):
    """Reference outputs(): marks output layers; return them so configs can
    end with `return outputs(...)`."""
    out = list(layers if isinstance(layers, (list, tuple)) else [layers])
    out += list(args)
    return out[0] if len(out) == 1 else out
