"""Sequence-generation DSL: GeneratedInput + beam_search layer.

Reference: the v1 DSL's beam_search/GeneratedInput sugar
(trainer_config_helpers/layers.py BaseGeneratedInput/GeneratedInput and
beam_search), lowered there to a recurrent layer group in generation mode and
executed by RecurrentGradientMachine::generateSequence/beamSearch
(gserver/gradientmachines/RecurrentGradientMachine.cpp:823,1248).

TPU design: the step sub-graph is traced once (like recurrent_group) and
driven by the functional beam decoder in ops/beam.py — one lax.scan with
static beam_size/max_length, finished-lane masking, and state reordering by
take_along_axis instead of the reference's machineIdVec scatter copies.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.layers.graph import (
    LayerOutput, Topology, auto_name, register_layer, value_data)
from paddle_tpu.layers import recurrent as rec
from paddle_tpu.layers.api import _winit
from paddle_tpu.ops import beam as beam_ops
from paddle_tpu.ops import embedding as emb_ops
from paddle_tpu.utils.error import ConfigError

__all__ = ["BaseGeneratedInput", "GeneratedInput", "SubsequenceInput",
           "beam_search", "greedy_generation"]


class BaseGeneratedInput:
    pass


class GeneratedInput(BaseGeneratedInput):
    """The previously generated token, embedded (reference GeneratedInput:
    size = vocab, embedding_name/embedding_size select the lookup table,
    shared by name with the training graph's target embedding)."""

    def __init__(self, size, embedding_name, embedding_size,
                 bos_id=0, eos_id=1):
        self.size = size                      # vocab
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size
        self.bos_id = bos_id
        self.eos_id = eos_id


from paddle_tpu.layers.recurrent import SubsequenceInput  # noqa: E402,F401
# (re-exported here for the reference's import shape; the class lives with
# the recurrent_group engine)


class _SharedTableImpl:
    """Parameter-only node holding the generated-word embedding table, keyed
    by GeneratedInput.embedding_name via cfg['param_name'] — so the training
    graph's target embedding (embedding_layer with
    param_attr={'name': embedding_name}) and the decoder share one table."""

    def infer(self, cfg, in_sizes):
        return cfg["emb_size"]

    def init(self, rng, cfg, in_sizes):
        return {"w": _winit(cfg.get("param_attr"),
                            1.0 / cfg["vocab"] ** 0.5)(
            rng, (cfg["vocab"], cfg["emb_size"]))}

    def apply(self, ctx, cfg, params):
        return params["w"]


register_layer("shared_table")(_SharedTableImpl)


class _BeamSearchImpl:
    def infer(self, cfg, in_sizes):
        return 1   # value rows are generated token ids

    def init(self, rng, cfg, in_sizes):
        # step-layer params are hoisted to the top level by
        # Topology._init_into, keyed by their own param-sharing names — a
        # decoder trained via recurrent_group feeds its weights straight
        # into generation when the step layers share names (the reference's
        # RecurrentGradientMachine generation mode shares all step-layer
        # params by name the same way)
        return {}

    def apply(self, ctx, cfg, params, emb_w, *inputs):
        gen: GeneratedInput = cfg["gen"]
        sub_topo: Topology = cfg["sub_topo"]
        statics = list(inputs[:cfg["n_static"]])
        boots = list(inputs[cfg["n_static"]:])
        sub_params = ctx.params

        if statics:
            bsz = value_data(statics[0]).shape[0]
        elif boots:
            bsz = value_data(boots[0]).shape[0]
        else:
            raise ConfigError("beam_search needs at least one StaticInput or "
                              "boot memory to derive the batch size")
        k = cfg["beam_size"]

        def tile(v):
            if isinstance(v, SequenceBatch):
                return SequenceBatch(
                    data=jnp.repeat(v.data, k, axis=0),
                    lengths=jnp.repeat(v.lengths, k, axis=0))
            return jnp.repeat(v, k, axis=0)

        statics_t = [tile(s) for s in statics]

        boot_vals = []
        bi = 0
        for ph, link_node, boot, boot_const in cfg["links"]:
            if isinstance(boot, LayerOutput):
                boot_vals.append(tile(value_data(boots[bi])))
                bi += 1
            elif boot_const is not None:
                boot_vals.append(jnp.full((bsz * k, ph.size),
                                          float(boot_const)))
            else:
                boot_vals.append(jnp.zeros((bsz * k, ph.size)))

        mode, rng_ = ctx.mode, ctx.rng
        link_nodes = [ln for _, ln, _, _ in cfg["links"]]
        n_out = len(cfg["outs"])

        def step_fn(mems, prev_ids):
            word_emb = emb_ops.embedding_lookup(emb_w, prev_ids)
            feed = {cfg["gen_ph"].name: word_emb}
            for ph, s in zip(cfg["static_phs"], statics_t):
                feed[ph.name] = s
            for (ph, _, _, _), m in zip(cfg["links"], mems):
                feed[ph.name] = m
            # memory-link values come back as extra outputs of the SAME
            # apply — no per-link re-evaluation of the sub-graph
            vals = sub_topo.apply(sub_params, feed, mode=mode, rng=rng_,
                                  extra_outputs=link_nodes)
            vals = vals if isinstance(vals, tuple) else (vals,)
            outs = vals[:n_out]
            new_mems = [value_data(v) for v in vals[n_out:]]
            probs = value_data(outs[0])
            log_probs = jnp.log(jnp.maximum(probs, 1e-20))
            return log_probs, tuple(new_mems)

        # adapt to beam_ops signature: step(state, prev) -> (logp, state)
        def beam_step(state, prev_ids):
            lp, new_state = step_fn(state, prev_ids)
            return lp, new_state

        result = beam_ops.beam_search(
            beam_step, tuple(boot_vals), batch_size=bsz, beam_size=k,
            max_len=cfg["max_length"], bos_id=gen.bos_id, eos_id=gen.eos_id,
            length_penalty=cfg.get("length_penalty", 0.0),
            candidate_adjust=cfg.get("candidate_adjust"),
            drop_callback=cfg.get("drop_callback"))
        ctx.aux[cfg["self_name"] + "/result"] = result
        return result


register_layer("beam_search_gen")(_BeamSearchImpl)


def _trace_step(step, input, bos_id, eos_id):
    """Shared step-graph tracing for beam_search/greedy_generation."""
    ins = input if isinstance(input, (list, tuple)) else [input]
    gen = None
    static_inputs, step_args = [], []
    gen_ph = None
    for item in ins:
        if isinstance(item, BaseGeneratedInput):
            if gen is not None:
                raise ConfigError("beam_search takes exactly one GeneratedInput")
            gen = item
            gen_ph = LayerOutput(auto_name("gen_word"), "__step_input__",
                                 item.embedding_size, [], {}, is_seq=False)
            step_args.append(gen_ph)
        elif isinstance(item, rec.StaticInput):
            ph = LayerOutput(auto_name("static_in"), "__static__",
                             item.input.size, [], {}, is_seq=item.is_seq)
            static_inputs.append((ph, item))
            step_args.append(ph)
        else:  # bare layer = static
            ph = LayerOutput(auto_name("static_in"), "__static__",
                             item.size, [], {}, is_seq=item.is_seq)
            static_inputs.append((ph, rec.StaticInput(item, item.is_seq)))
            step_args.append(ph)
    if gen is None:
        raise ConfigError("beam_search needs a GeneratedInput")
    # explicit beam_search(bos_id=/eos_id=) overrides; None keeps the
    # GeneratedInput's own ids (do not clobber with wrapper defaults)
    if bos_id is not None:
        gen.bos_id = bos_id
    if eos_id is not None:
        gen.eos_id = eos_id

    g = rec._GroupBuildCtx()
    prev = rec._GroupBuildCtx.current
    rec._GroupBuildCtx.current = g
    try:
        outs = step(*step_args)
    finally:
        rec._GroupBuildCtx.current = prev
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]

    sub_topo = Topology(outs)
    links = rec.resolve_memory_links(sub_topo, g.memories)

    # first input = the shared embedding table node (param keyed by
    # embedding_name so trained weights flow into decoding)
    table = LayerOutput(auto_name(f"table_{gen.embedding_name}"),
                        "shared_table", gen.embedding_size, [],
                        {"vocab": gen.size, "emb_size": gen.embedding_size,
                         "param_name": gen.embedding_name})
    group_inputs = ([table]
                    + [s.input for _, s in static_inputs]
                    + [b for _, _, b, _ in links if isinstance(b, LayerOutput)])
    return {
        "gen": gen, "gen_ph": gen_ph, "sub_topo": sub_topo, "outs": outs,
        "static_phs": [ph for ph, _ in static_inputs],
        "links": links, "n_static": len(static_inputs),
    }, group_inputs


def beam_search(step, input, bos_id=None, eos_id=None, beam_size=5,
                max_length=100, length_penalty=0.0, name=None,
                candidate_adjust=None, drop_callback=None):
    """DSL beam search (reference layers.py beam_search).

    step(generated_word_embedding, *statics) -> softmax LayerOutput over the
    vocab; decoder state carried with L.memory links, exactly as in
    recurrent_group.  Returns a layer whose value is a BeamResult
    (tokens [B, K, T] best-first, scores, lengths); its .size is 1 (token-id
    rows).  bos/eos default to the GeneratedInput's ids.

    candidate_adjust(log_probs) and drop_callback(tokens, t, cand) are the
    reference RecurrentGradientMachine user hooks
    (RecurrentGradientMachine.h:87-177): per-step score rewriting and
    per-node drop/renormalize over the expanded candidates.
    """
    cfg, group_inputs = _trace_step(step, input, bos_id, eos_id)
    cfg.update({"beam_size": beam_size, "max_length": max_length,
                "length_penalty": length_penalty,
                "candidate_adjust": candidate_adjust,
                "drop_callback": drop_callback})
    node = LayerOutput(name or auto_name("beam_search"), "beam_search_gen",
                       1, group_inputs, cfg, is_seq=True)
    node.cfg["self_name"] = node.name
    return node


class _GreedyGenImpl(_BeamSearchImpl):
    def apply(self, ctx, cfg, params, emb_w, *inputs):
        cfg = dict(cfg)
        cfg["beam_size"] = 1
        res = super().apply(ctx, cfg, params, emb_w, *inputs)
        return SequenceBatch(data=res.tokens[:, 0, :],
                             lengths=res.lengths[:, 0])


register_layer("greedy_gen")(_GreedyGenImpl)


def greedy_generation(step, input, bos_id=None, eos_id=None, max_length=100,
                      name=None):
    """Reference oneWaySearch (greedy) as a layer; value is a SequenceBatch
    of generated token ids (layer .size = 1); bos/eos default to the
    GeneratedInput's ids."""
    cfg, group_inputs = _trace_step(step, input, bos_id, eos_id)
    cfg.update({"max_length": max_length})
    node = LayerOutput(name or auto_name("greedy_gen"), "greedy_gen",
                       1, group_inputs, cfg, is_seq=True)
    node.cfg["self_name"] = node.name
    return node
