"""Pre-built recurrent step units for custom recurrent groups.

Reference: python/paddle/trainer/recurrent_units.py — LstmRecurrentUnit /
GatedRecurrentUnit assemble an LSTM/GRU step out of mixed projections +
step layers, for use INSIDE a recurrent group (sharing parameters via
para_prefix), and the *LayerGroup wrappers build the whole group (equivalent
to lstmemory/grumemory, the reference's own equivalence claim).

TPU design: same decomposition over this DSL — the input transform is one
mixed projection hoisted OUTSIDE the scan (one big MXU matmul over all
timesteps), only the recurrent projection and the fused cell run per-step.
"""

from paddle_tpu.layers.api import (full_matrix_projection,
                                   identity_projection, mixed_layer)
from paddle_tpu.layers.recurrent import (gru_step_layer, lstm_step_layer,
                                         memory, recurrent_group)

__all__ = [
    "lstm_recurrent_unit", "lstm_recurrent_layer_group",
    "gated_recurrent_unit", "gated_recurrent_layer_group",
]


def _as_parts(inputs, prefix, what):
    """LayerOutputs become full-matrix projections with prefix-shared
    parameter names; projections pass through."""
    parts = []
    for i, item in enumerate(inputs if isinstance(inputs, (list, tuple))
                             else [inputs]):
        if hasattr(item, "kind"):          # already a projection (_Part)
            parts.append(item)
        else:
            parts.append(full_matrix_projection(
                item, param_attr={"name": f"{prefix}_{what}{i}.w"}))
    return parts


def lstm_recurrent_unit(name, size, input, act="tanh", gate_act="sigmoid",
                        state_act="tanh", para_prefix=None, bias_attr=True):
    """One LSTM step assembled from DSL pieces (reference LstmRecurrentUnit):
    mixed(inputs + W_r @ h_prev) -> lstm_step_layer carrying [h | c].
    Call inside a recurrent_group step; returns the step's h [B, size].

    Parameter layout matches lstmemory: the step bias is [4*size gate bias |
    3*size peepholes], the recurrent projection is [size, 4*size]."""
    prefix = para_prefix or name
    hc = memory(name=name + "_hc", size=2 * size)
    h_prev = mixed_layer(size=size,
                         input=[identity_projection(hc, offset=0, size=size)],
                         act=None, bias_attr=False,
                         name=name + "_prev_h")
    x4 = mixed_layer(
        size=4 * size,
        input=_as_parts(input, prefix, "input_recurrent") + [
            full_matrix_projection(
                h_prev,
                param_attr={"name": prefix + "_input_recurrent.w"})],
        act=None, bias_attr=False, name=name + "_input_recurrent")
    hc_next = lstm_step_layer(x4, hc, size=size, act=act, gate_act=gate_act,
                              state_act=state_act, bias_attr=bias_attr,
                              name=name + "_hc")
    return mixed_layer(size=size,
                       input=[identity_projection(hc_next, offset=0,
                                                  size=size)],
                       act=None, bias_attr=False, name=name)


def gated_recurrent_unit(name, size, input, act="tanh", gate_act="sigmoid",
                         para_prefix=None, bias_attr=True, out_memory=None):
    """One GRU step (reference GatedRecurrentUnit): gru_step_layer over the
    3*size transformed input and the output memory."""
    prefix = para_prefix or name
    mem = out_memory if out_memory is not None \
        else memory(name=name, size=size)
    parts = _as_parts(input, prefix, "transform_input")
    if len(parts) == 1 and getattr(parts[0], "kind", "") == "identity" \
            and parts[0].out_size == 3 * size:
        x3 = parts[0].inputs[0]
    else:
        x3 = mixed_layer(size=3 * size, input=parts, act=None,
                         bias_attr=False, name=name + "_transform_input")
    return gru_step_layer(x3, mem, size=size, act=act, gate_act=gate_act,
                          bias_attr=bias_attr,
                          param_attr={"name": prefix + "_gate.w"}, name=name)


def lstm_recurrent_layer_group(name, size, input, act="tanh",
                               gate_act="sigmoid", state_act="tanh",
                               para_prefix=None, seq_reversed=False,
                               bias_attr=True):
    """Whole-sequence LSTM built as a layer group (reference
    LstmRecurrentLayerGroup — equivalent to lstmemory).  The input transform
    runs once over the whole sequence outside the scan."""
    prefix = para_prefix or name
    proj = mixed_layer(
        size=4 * size, input=_as_parts(input, prefix, "transform_input"),
        act=None, bias_attr=False, name=name + "_transform_input")

    def step(x):
        return lstm_recurrent_unit(
            name=name, size=size, input=[identity_projection(x)],
            act=act, gate_act=gate_act, state_act=state_act,
            para_prefix=prefix, bias_attr=bias_attr)

    return recurrent_group(step, proj, reverse=seq_reversed,
                           name=name + "_group")


def gated_recurrent_layer_group(name, size, input, act="tanh",
                                gate_act="sigmoid", para_prefix=None,
                                seq_reversed=False, bias_attr=True):
    """Whole-sequence GRU layer group (reference GatedRecurrentLayerGroup —
    equivalent to grumemory)."""
    prefix = para_prefix or name
    proj = mixed_layer(
        size=3 * size, input=_as_parts(input, prefix, "transform_input"),
        act=None, bias_attr=False, name=name + "_transform_input")

    def step(x):
        return gated_recurrent_unit(
            name=name, size=size, input=[identity_projection(x)],
            act=act, gate_act=gate_act, para_prefix=prefix,
            bias_attr=bias_attr)

    return recurrent_group(step, proj, reverse=seq_reversed,
                           name=name + "_group")
