"""Recurrent DSL: lstmemory / grumemory / recurrent_layer whole-sequence
layers, and the recurrent_group / memory engine.

Reference surface: trainer_config_helpers layers.py lstmemory/grumemory/
recurrent_layer/recurrent_group/memory/lstm_step_layer/gru_step_layer/
get_output_layer + RecurrentLayerGroup lowering (config_parser.py sub_models,
gserver RecurrentLayerGroup.cpp:23-60, RecurrentGradientMachine engine).

TPU design: a recurrent_group's step sub-graph is built once at config time
(placeholders for step inputs and memories), compiled to a pure step
function, and driven by ops.rnn.recurrent_group — one lax.scan, static
shapes, masked carries (vs the reference's per-frame network instantiation
with batch shrinking).
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes
from paddle_tpu.core.sequence import NestedSequenceBatch, SequenceBatch
from paddle_tpu.layers.graph import (
    LayerOutput, Topology, register_layer, auto_name, as_seq, value_data,
    Context, get_impl)
from paddle_tpu.layers.api import _winit, _maybe_bias
from paddle_tpu.ops import rnn as rnn_ops
from paddle_tpu.utils.error import ConfigError

__all__ = [
    "lstmemory", "grumemory", "recurrent_layer", "recurrent_group", "memory",
    "StaticInput", "SubsequenceInput", "lstm_step_layer", "gru_step_layer",
    "gru_step_naive_layer", "get_output_layer", "mdlstmemory",
]


# ----------------------------------------------------- whole-sequence RNNs

def _prev_batch_carry(ctx, cfg):
    """Reference --prev_batch_state (Flags.cpp:73: "batch is continue with
    next batch"): carry the RNN's final state into the next batch via the
    trainer's functional model_state thread (same channel as BN stats)."""
    if not cfg.get("prev_batch_state"):
        from paddle_tpu.utils.flags import FLAGS
        if not FLAGS.prev_batch_state:
            return False
    if cfg.get("reverse", False):
        if cfg.get("prev_batch_state"):
            # explicit per-layer request on a reversed scan is a config
            # contradiction — fail loudly instead of silently dropping it
            raise ConfigError(
                f"{cfg.get('name', '?')}: prev_batch_state cannot carry "
                "state for a reverse RNN (the final state of a reversed "
                "scan is the sequence START)")
        return False  # global flag: skip reversed layers, carry the rest
    return True


def _prev_batch_init(ctx, cfg):
    if not _prev_batch_carry(ctx, cfg):
        return None
    return ctx.state_in.get(cfg["name"] + "/carry")


def _prev_batch_save(ctx, cfg, final):
    if _prev_batch_carry(ctx, cfg):
        ctx.put_state(cfg["name"] + "/carry", final)

class _LstmImpl:
    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        d = cfg["size"]
        if in_sizes[0] != 4 * d:
            raise ConfigError(
                f"lstmemory input must be 4*size={4 * d} wide (a mixed/fc "
                f"projection), got {in_sizes[0]} — reference LstmLayer "
                "semantics")
        r1, r2 = jax.random.split(rng)
        p = {"w": _winit(cfg.get("param_attr"), 1.0 / math.sqrt(d))(r1, (d, 4 * d))}
        # bias layout (reference LstmLayer): 4*size gate bias + 3*size peepholes
        if cfg.get("bias_attr", True) is not False:
            p["b"] = jnp.zeros((7 * d,), dtypes.param_dtype())
        return p

    def apply(self, ctx, cfg, params, x):
        d = cfg["size"]
        b = params.get("b")
        bias = b[:4 * d] if b is not None else None
        ci = b[4 * d:5 * d] if b is not None else None
        cf = b[5 * d:6 * d] if b is not None else None
        co = b[6 * d:] if b is not None else None
        init = _prev_batch_init(ctx, cfg)
        if init is not None:
            init = rnn_ops.LstmState(h=init[..., :d], c=init[..., d:])
        out, final = rnn_ops.lstm(as_seq(x), params["w"], bias=bias,
                                  check_i=ci, check_f=cf, check_o=co,
                                  init_state=init,
                                  reverse=cfg.get("reverse", False),
                                  act=cfg.get("act", "tanh"),
                                  gate_act=cfg.get("gate_act", "sigmoid"),
                                  state_act=cfg.get("state_act", "tanh"))
        _prev_batch_save(ctx, cfg,
                         jnp.concatenate([final.h, final.c], axis=-1))
        return out


register_layer("lstmemory")(_LstmImpl)


def lstmemory(input, size=None, reverse=False, act="tanh",
              gate_act="sigmoid", state_act="tanh", name=None,
              bias_attr=True, param_attr=None, prev_batch_state=False):
    d = size or input.size // 4
    nm = name or auto_name("lstmemory")
    return LayerOutput(nm, "lstmemory", d, [input],
                       {"size": d, "name": nm, "reverse": reverse,
                        "act": act, "gate_act": gate_act,
                        "state_act": state_act, "bias_attr": bias_attr,
                        "param_attr": param_attr,
                        "prev_batch_state": prev_batch_state},
                       is_seq=True)


class _GruImpl:
    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        d = cfg["size"]
        if in_sizes[0] != 3 * d:
            raise ConfigError(
                f"grumemory input must be 3*size={3 * d} wide, got {in_sizes[0]}")
        r1, r2, r3 = jax.random.split(rng, 3)
        wi = _winit(cfg.get("param_attr"), 1.0 / math.sqrt(d))
        p = {"w_gate": wi(r1, (d, 2 * d)), "w_state": wi(r2, (d, d))}
        if cfg.get("bias_attr", True) is not False:
            p["b"] = jnp.zeros((3 * d,), dtypes.param_dtype())
        return p

    def apply(self, ctx, cfg, params, x):
        out, final = rnn_ops.gru(as_seq(x), params["w_gate"],
                                 params["w_state"], bias=params.get("b"),
                                 init_state=_prev_batch_init(ctx, cfg),
                                 reverse=cfg.get("reverse", False),
                                 act=cfg.get("act", "tanh"),
                                 gate_act=cfg.get("gate_act", "sigmoid"))
        _prev_batch_save(ctx, cfg, final)
        return out


register_layer("grumemory")(_GruImpl)


def grumemory(input, size=None, reverse=False, act="tanh",
              gate_act="sigmoid", name=None, bias_attr=True, param_attr=None,
              prev_batch_state=False):
    d = size or input.size // 3
    nm = name or auto_name("grumemory")
    return LayerOutput(nm, "grumemory", d, [input],
                       {"size": d, "name": nm, "reverse": reverse,
                        "act": act, "gate_act": gate_act,
                        "bias_attr": bias_attr, "param_attr": param_attr,
                        "prev_batch_state": prev_batch_state}, is_seq=True)


class _SimpleRnnImpl:
    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        d = cfg["size"]
        p = {"w": _winit(cfg.get("param_attr"), 1.0 / math.sqrt(d))(rng, (d, d))}
        if cfg.get("bias_attr", True) is not False:
            p["b"] = jnp.zeros((d,), dtypes.param_dtype())
        return p

    def apply(self, ctx, cfg, params, x):
        out, final = rnn_ops.simple_rnn(as_seq(x), params["w"],
                                        bias=params.get("b"),
                                        init_state=_prev_batch_init(ctx, cfg),
                                        reverse=cfg.get("reverse", False),
                                        act=cfg.get("act", "tanh"))
        _prev_batch_save(ctx, cfg, final)
        return out


register_layer("recurrent")(_SimpleRnnImpl)


def recurrent_layer(input, act="tanh", reverse=False, name=None,
                    bias_attr=True, param_attr=None, prev_batch_state=False):
    """Reference RecurrentLayer: h_t = act(x_t + W h_{t-1})."""
    nm = name or auto_name("recurrent")
    return LayerOutput(nm, "recurrent", input.size, [input],
                       {"size": input.size, "name": nm, "act": act,
                        "reverse": reverse, "bias_attr": bias_attr,
                        "param_attr": param_attr,
                        "prev_batch_state": prev_batch_state},
                       is_seq=True)


# ----------------------------------------------------- recurrent_group

class StaticInput:
    """Whole-layer input visible unchanged at every step (reference
    StaticInput for recurrent_group; used for the encoder context in
    simple_attention)."""

    def __init__(self, input, is_seq=False):
        self.input = input
        self.is_seq = is_seq  # True: the step sees the whole sequence


class SubsequenceInput:
    """Marks a two-level sequence input for a nested recurrent_group
    (reference SubsequenceInput, RecurrentGradientMachine.cpp:642-712): the
    outer group iterates SUBSEQUENCES — the step function sees each
    subsequence as a whole SequenceBatch and can run an inner
    recurrent_group over it."""

    def __init__(self, input):
        self.input = input


def _in_v1_parse():
    """True while a reference v1 config script is being executed by the
    config compiler (there sequence-ness is a DataProvider property, not a
    layer property)."""
    try:
        from paddle_tpu.compat import config_parser
        return config_parser.in_parse()
    except Exception:
        return False


def _promote_seq(node, _seen=None):
    """Mark a layer chain as sequence-valued (v1 compat promotion)."""
    _seen = _seen if _seen is not None else set()
    if id(node) in _seen:
        return
    _seen.add(id(node))
    node.is_seq = True
    for dep in node.inputs:
        _promote_seq(dep, _seen)


class _GroupBuildCtx:
    current = None

    def __init__(self):
        self.memories = []  # list of (placeholder, link_name, boot, init_zero)


def resolve_memory_links(sub_topo, memories, extra_nodes=()):
    """Match memory() links to step-graph layers by name (shared by
    recurrent_group and the generation DSL).  extra_nodes: nodes created
    during step tracing that are NOT ancestors of the step outputs — the
    reference allows a memory to link a CONSUMER of the output (e.g.
    last_seq(inner_out, name="outer_rnn_state"), sequence_nest_rnn.conf)."""
    by_name = {n.name: n for n in extra_nodes}
    by_name.update({n.name: n for n in sub_topo.order})
    links = []
    for ph, link_name, boot, boot_const in memories:
        if link_name not in by_name:
            raise ConfigError(
                f"memory(name={link_name!r}) has no matching layer in the "
                f"step function (have {sorted(by_name)})")
        links.append((ph, by_name[link_name], boot, boot_const))
    return links


class _MemoryPlaceholder(LayerOutput):
    """memory() return value; supports the reference's late-link form
    `m = memory(name=None, size=...); ...; m.set_input(layer)`."""

    def set_input(self, layer):
        g = _GroupBuildCtx.current
        if g is None:
            raise ConfigError("set_input() must be called inside the step")
        for i, (ph, link, boot, boot_const) in enumerate(g.memories):
            if ph is self:
                g.memories[i] = (ph, layer.name, boot, boot_const)
                return
        raise ConfigError("set_input on a memory not in this group")


def memory(name, size, boot_layer=None, boot_with_const_id=None,
           is_seq=False):
    """Previous-step output of the layer called `name` (reference memory()
    with boot layers, RecurrentGradientMachine memory frames :715).  With
    name=None the link is bound later via .set_input(layer) (reference
    memory(name=None) + set_input)."""
    g = _GroupBuildCtx.current
    if g is None:
        raise ConfigError("memory() must be called inside recurrent_group's step")
    ph = _MemoryPlaceholder(auto_name(f"mem_{name}"), "__memory__", size, [],
                            {"link": name}, is_seq=False)
    g.memories.append((ph, name, boot_layer, boot_with_const_id))
    return ph


def recurrent_group(step, input, reverse=False, name=None):
    """Build the step sub-graph once, compile to a scan (see module doc).

    input: one or a list of sequence LayerOutputs and/or StaticInputs.
    step: fn(*step_inputs) -> LayerOutput or tuple of LayerOutputs.
    """
    ins = input if isinstance(input, (list, tuple)) else [input]
    seq_inputs, static_inputs, sub_inputs = [], [], []
    step_args = []
    for item in ins:
        if isinstance(item, StaticInput):
            ph = LayerOutput(auto_name("static_in"), "__static__",
                             item.input.size, [], {}, is_seq=item.is_seq)
            static_inputs.append((ph, item))
            step_args.append(ph)
        elif isinstance(item, SubsequenceInput):
            # the step sees each SUBSEQUENCE as a whole SequenceBatch
            ph = LayerOutput(auto_name("subseq_in"), "__step_input__",
                             item.input.size, [], {}, is_seq=True)
            sub_inputs.append((ph, item))
            step_args.append(ph)
        else:
            if not item.is_seq:
                if _in_v1_parse():
                    # v1 configs declare sequence-ness in the DataProvider,
                    # not on the layer (reference defers to runtime): a
                    # layer fed to a recurrent_group IS a sequence there.
                    # The native DSL keeps the strict check — its data
                    # layers carry is_seq explicitly.
                    _promote_seq(item)
                else:
                    raise ConfigError(
                        f"recurrent_group input {item.name} is not a "
                        "sequence; wrap non-sequence inputs in StaticInput")
            ph = LayerOutput(auto_name("step_in"), "__step_input__",
                             item.size, [], {}, is_seq=False)
            seq_inputs.append((ph, item))
            step_args.append(ph)
    if sub_inputs and seq_inputs:
        raise ConfigError("recurrent_group cannot mix SubsequenceInput with "
                          "flat sequence inputs (reference nested groups "
                          "iterate subsequences only)")

    from paddle_tpu.layers import graph as _graph
    g = _GroupBuildCtx()
    prev = _GroupBuildCtx.current
    _GroupBuildCtx.current = g
    created = []
    _graph._NODE_OBSERVERS.append(created.append)
    try:
        outs = step(*step_args)
    finally:
        _GroupBuildCtx.current = prev
        _graph._NODE_OBSERVERS.remove(created.append)
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]

    # resolve memory links: each memory's `link` names a layer created
    # during the step trace (ancestor of the outputs or not)
    sub_topo = Topology(outs)
    links = resolve_memory_links(sub_topo, g.memories, extra_nodes=created)

    # link targets that are NOT ancestors of the outputs must still be
    # computed each step: make them additional sub-graph outputs
    in_graph = {id(n) for n in sub_topo.order}
    link_nodes = [ln for _, ln, _, _ in links]
    extra_outs = []
    for ln in link_nodes:
        if id(ln) not in in_graph and all(ln is not e for e in extra_outs):
            extra_outs.append(ln)
    if extra_outs:
        sub_topo = Topology(outs + extra_outs)

    group_inputs = ([real for _, real in seq_inputs]
                    + [s.input for _, s in sub_inputs]
                    + [s.input for _, s in static_inputs]
                    + [b for _, _, b, _ in links if isinstance(b, LayerOutput)])

    cfg = {
        "sub_topo": sub_topo,
        "outs": outs,
        "seq_phs": [ph for ph, _ in seq_inputs],
        "sub_phs": [ph for ph, _ in sub_inputs],
        "static_phs": [ph for ph, _ in static_inputs],
        "links": links,
        "reverse": reverse,
        "n_seq": len(seq_inputs),
        "n_sub": len(sub_inputs),
        "n_static": len(static_inputs),
    }
    node = LayerOutput(name or auto_name("recurrent_group"),
                       "recurrent_group", outs[0].size, group_inputs, cfg,
                       is_seq=True)
    node.cfg["self_name"] = node.name
    return node


# scan-invariant hoisting: step-graph layers that depend only on the
# per-step sequence inputs (not on memories/statics) and are row-wise can
# be computed ONCE over the whole padded sequence before the scan — one big
# MXU matmul instead of T small ones (the same trick the reference's
# SequenceToBatch plays for whole-sequence RNN layers, generalized to
# arbitrary step graphs).  Disable for A/B testing via this flag.
HOIST_SCAN_INVARIANTS = True

# layer types whose apply maps rows independently (safe on [B, T, ...] data
# exactly as on [B, ...] rows).  Anything sequence-aware (pooling, context,
# seq ops) must stay inside the scan.
_ROW_WISE_TYPES = {"fc", "embedding", "mixed", "addto", "concat",
                   "slope_intercept"}
_ROW_WISE_MIXED_PARTS = {"full_matrix", "trans_full_matrix", "identity",
                         "dotmul", "scaling", "table"}


def _hoistable_frontier(sub_topo, seq_phs, mode):
    """Maximal step-graph nodes computable before the scan: every ancestor
    path bottoms out in a per-step sequence placeholder and every node on it
    is row-wise (and dropout-free in train mode, so randomness stays
    per-step)."""
    seq_ph_ids = {id(ph) for ph in seq_phs}
    ok = {}
    for node in sub_topo.order:
        if id(node) in seq_ph_ids:
            ok[id(node)] = True
            continue
        if node.layer_type.startswith("__") or node.layer_type == "data":
            ok[id(node)] = False
            continue
        if not node.inputs or not all(ok.get(id(i), False)
                                      for i in node.inputs):
            ok[id(node)] = False
            continue
        row_wise = node.layer_type in _ROW_WISE_TYPES
        if node.layer_type == "mixed":
            row_wise = all(kind in _ROW_WISE_MIXED_PARTS
                           for kind, _ in node.cfg["parts"])
        if mode == "train" and (node.cfg.get("drop_rate")
                                or node.layer_type == "dropout"):
            row_wise = False
        ok[id(node)] = row_wise
    # frontier: hoistable nodes consumed by a non-hoistable node (or an
    # output) — computing deeper ancestors too would be redundant
    consumed_by_live = set()
    for node in sub_topo.order:
        if not ok.get(id(node), False):
            for i in node.inputs:
                consumed_by_live.add(id(i))
    for out in sub_topo.outputs:
        consumed_by_live.add(id(out))
    return [n for n in sub_topo.order
            if ok.get(id(n), False) and id(n) in consumed_by_live
            and id(n) not in seq_ph_ids]


class _RecurrentGroupImpl:
    def infer(self, cfg, in_sizes):
        return cfg["outs"][0].size

    def init(self, rng, cfg, in_sizes):
        # step-layer params are hoisted to the top level by
        # Topology._init_into (shared with generation mode by name)
        return {}

    def apply(self, ctx, cfg, params, *inputs):
        sub_topo: Topology = cfg["sub_topo"]
        n_seq, n_static = cfg["n_seq"], cfg["n_static"]
        n_sub = cfg.get("n_sub", 0)
        nested = n_sub > 0
        if nested:
            subs = []
            for v in inputs[:n_sub]:
                if not isinstance(v, NestedSequenceBatch):
                    raise ConfigError(
                        "SubsequenceInput needs a NestedSequenceBatch feed "
                        f"(got {type(v).__name__})")
                subs.append(v)
            n_lead = n_sub
        else:
            seqs = [as_seq(v) for v in inputs[:n_seq]]
            n_lead = n_seq
        statics = list(inputs[n_lead:n_lead + n_static])
        boots = list(inputs[n_lead + n_static:])
        sub_params = ctx.params

        ref = subs[0] if nested else seqs[0]
        bsz = ref.data.shape[0]

        # boot memories
        boot_vals = []
        bi = 0
        for ph, link_node, boot, boot_const in cfg["links"]:
            if isinstance(boot, LayerOutput):
                boot_vals.append(value_data(boots[bi]))
                bi += 1
            elif boot_const is not None:
                boot_vals.append(jnp.full((bsz, ph.size), float(boot_const)))
            else:
                boot_vals.append(jnp.zeros((bsz, ph.size)))

        mode = ctx.mode
        # independent key per scan step (folded in by rnn_ops.recurrent_group)
        # so per-step dropout masks decorrelate across time
        group_rng = ctx.next_rng() if ctx.rng is not None else None
        link_nodes = [ln for _, ln, _, _ in cfg["links"]]
        n_out = len(cfg["outs"])

        frame_phs = cfg["sub_phs"] if nested else cfg["seq_phs"]

        # scan-invariant hoist (flat groups): compute the memory-free,
        # row-wise prefix of the step graph over the WHOLE padded sequence
        # before the scan — big MXU matmuls instead of T small ones
        hoisted_names = []
        if not nested and HOIST_SCAN_INVARIANTS and seqs:
            frontier = _hoistable_frontier(sub_topo, cfg["seq_phs"], mode)
            if frontier:
                pre_topo = Topology(frontier)
                full_feed = {ph.name: s
                             for ph, s in zip(cfg["seq_phs"], seqs)}
                # no rng: the frontier is dropout-free by construction, and
                # skipping the split keeps the per-step rng stream identical
                # to the unhoisted graph
                pre_vals = pre_topo.apply(sub_params, full_feed, mode=mode)
                pre_vals = (pre_vals if isinstance(pre_vals, tuple)
                            and not isinstance(pre_vals, SequenceBatch)
                            else (pre_vals,))
                hoisted_names = [n.name for n in frontier]
                # hoisted values join the scanned inputs (engine slices
                # their time axis alongside the placeholders)
                seqs = list(seqs) + [as_seq(v) for v in pre_vals]

        def step_fn(mems, frames, step_rng=None):
            feed = {}
            for ph, frame in zip(frame_phs, frames):
                feed[ph.name] = frame
            pre = {name: frame for name, frame in
                   zip(hoisted_names, frames[len(frame_phs):])}
            for ph, s in zip(cfg["static_phs"], statics):
                feed[ph.name] = s
            for (ph, _, _, _), m in zip(cfg["links"], mems):
                feed[ph.name] = m
            # memory-link values come back as extra outputs of the SAME
            # apply — no per-link re-evaluation of the sub-graph
            vals = sub_topo.apply(sub_params, feed, mode=mode, rng=step_rng,
                                  extra_outputs=link_nodes, precomputed=pre)
            # NB: SequenceBatch/NestedSequenceBatch are NamedTuples — a
            # single sequence-valued output must not be unpacked fieldwise
            if not isinstance(vals, tuple) or isinstance(
                    vals, (SequenceBatch, NestedSequenceBatch)):
                vals = (vals,)
            # layout: [step outputs | consumer-link topo outputs (if any) |
            # link values appended by extra_outputs] — memories are always
            # the LAST len(links) entries
            n_links = len(cfg["links"])
            out_vals = vals[:n_out]
            new_mems = [value_data(v)
                        for v in (vals[len(vals) - n_links:]
                                  if n_links else ())]
            # nested groups keep sequence-valued step outputs whole so the
            # engine can stack them into a NestedSequenceBatch; flat groups
            # emit per-step rows
            if nested:
                outs_keep = tuple(v if isinstance(v, SequenceBatch)
                                  else value_data(v) for v in out_vals)
            else:
                outs_keep = tuple(value_data(v) for v in out_vals)
            return tuple(new_mems), outs_keep

        if group_rng is None:
            step = lambda mems, frames: step_fn(mems, frames)  # noqa: E731
        else:
            step = step_fn
        engine = (rnn_ops.nested_recurrent_group if nested
                  else rnn_ops.recurrent_group)
        outs, _ = engine(step, tuple(subs if nested else seqs),
                         tuple(boot_vals),
                         reverse=cfg["reverse"], rng=group_rng)
        # rnn_ops.recurrent_group maps over the input pytree; our step_fn
        # consumed a tuple of SequenceBatches and returned a tuple of outputs.
        # NB: SequenceBatch is itself a (named) tuple — test explicitly.
        def is_plain_tuple(v):
            return (isinstance(v, tuple)
                    and not isinstance(v, (SequenceBatch,
                                           NestedSequenceBatch)))

        result = outs[0] if (is_plain_tuple(outs) and len(outs) == 1) else outs
        ctx.aux[cfg["self_name"] + "/outputs"] = result
        return result[0] if is_plain_tuple(result) else result


register_layer("recurrent_group")(_RecurrentGroupImpl)


class _MemoryPlaceholderImpl:
    def infer(self, cfg, in_sizes):
        return 0

    def apply(self, ctx, cfg, params, *ins):
        raise RuntimeError("memory placeholders are fed by the group engine")


register_layer("__memory__")(_MemoryPlaceholderImpl)
register_layer("__step_input__")(_MemoryPlaceholderImpl)
register_layer("__static__")(_MemoryPlaceholderImpl)


def get_output_layer(input, arg_name=None, name=None, index=1):
    """Fetch a secondary output of a recurrent_group (reference
    GetOutputLayer).  index selects among the step function's outputs."""
    return LayerOutput(name or auto_name("get_output"), "get_output",
                       input.cfg["outs"][index].size, [input],
                       {"index": index, "group": input.cfg["self_name"]},
                       is_seq=True)


class _GetOutputImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def apply(self, ctx, cfg, params, group_out):
        outs = ctx.aux.get(cfg["group"] + "/outputs")
        if not isinstance(outs, tuple):
            raise ConfigError("get_output_layer: group has a single output")
        return outs[cfg["index"]]


register_layer("get_output")(_GetOutputImpl)


# ----------------------------------------------------- step layers

class _LstmStepImpl:
    """One LSTM step as a layer (reference LstmStepLayer), for custom
    recurrent groups: inputs = (gate_input [B,4D], prev_state [B,D]);
    outputs h (primary); the cell state is exposed as output index 1 via
    a paired state node."""

    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        d = cfg["size"]
        if cfg.get("bias_attr", True) is False:
            return {}
        return {"b": jnp.zeros((7 * d,), dtypes.param_dtype())}

    def apply(self, ctx, cfg, params, x4, prev_state):
        d = cfg["size"]
        b = params.get("b")
        x4d, prev = value_data(x4), value_data(prev_state)
        if b is not None:
            x4d = x4d + b[:4 * d]
        ci = b[4 * d:5 * d] if b is not None else None
        cf = b[5 * d:6 * d] if b is not None else None
        co = b[6 * d:] if b is not None else None
        # prev_state carries [h | c] concatenated (2D wide)
        h_prev, c_prev = prev[..., :d], prev[..., d:]
        st = rnn_ops.lstm_cell(
            x4d, rnn_ops.LstmState(h=h_prev, c=c_prev),
            jnp.zeros((d, 4 * d), x4d.dtype),  # recurrence is in the mixed input
            check_i=ci, check_f=cf, check_o=co,
            act=cfg.get("act", "tanh"), gate_act=cfg.get("gate_act", "sigmoid"),
            state_act=cfg.get("state_act", "tanh"))
        return jnp.concatenate([st.h, st.c], axis=-1)


register_layer("lstm_step")(_LstmStepImpl)


def lstm_step_layer(input, state, size=None, act="tanh", gate_act="sigmoid",
                    state_act="tanh", name=None, bias_attr=True):
    d = size or input.size // 4
    return LayerOutput(name or auto_name("lstm_step"), "lstm_step", 2 * d,
                       [input, state],
                       {"size": d, "act": act, "gate_act": gate_act,
                        "state_act": state_act, "bias_attr": bias_attr})


class _GruStepImpl:
    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        d = cfg["size"]
        r1, r2 = jax.random.split(rng)
        wi = _winit(cfg.get("param_attr"), 1.0 / math.sqrt(d))
        p = {"w_gate": wi(r1, (d, 2 * d)), "w_state": wi(r2, (d, d))}
        if cfg.get("bias_attr", True) is not False:
            p["b"] = jnp.zeros((3 * d,), dtypes.param_dtype())
        return p

    def apply(self, ctx, cfg, params, x3, prev):
        x3d, h_prev = value_data(x3), value_data(prev)
        if "b" in params:
            x3d = x3d + params["b"]
        return rnn_ops.gru_cell(x3d, h_prev, params["w_gate"],
                                params["w_state"], act=cfg.get("act", "tanh"),
                                gate_act=cfg.get("gate_act", "sigmoid"))


register_layer("gru_step")(_GruStepImpl)


def gru_step_layer(input, output_mem, size=None, act="tanh",
                   gate_act="sigmoid", name=None, bias_attr=True,
                   param_attr=None):
    d = size or input.size // 3
    return LayerOutput(name or auto_name("gru_step"), "gru_step", d,
                       [input, output_mem],
                       {"size": d, "act": act, "gate_act": gate_act,
                        "bias_attr": bias_attr, "param_attr": param_attr})


def gru_step_naive_layer(input, output_mem, size=None, act="tanh",
                         gate_act="sigmoid", name=None, bias_attr=True,
                         param_attr=None, layer_attr=None):
    """Reference gru_step_naive_layer: gru_step built from mixed layers so
    error-clipping/dropout attrs apply.  XLA fuses the fused and naive
    formulations identically, so this is the same computation here."""
    return gru_step_layer(input, output_mem, size=size, act=act,
                          gate_act=gate_act, name=name, bias_attr=bias_attr,
                          param_attr=param_attr)


class _MDLstmImpl:
    """2-D multi-dimensional LSTM over image-shaped sequences (reference
    MDLstmLayer, REGISTER_LAYER(mdlstmemory); config_parser.py:3018)."""

    def infer(self, cfg, in_sizes):
        return cfg["size"] * cfg["h"] * cfg["w"]

    def init(self, rng, cfg, in_sizes):
        d = cfg["size"]
        r1, r2 = jax.random.split(rng)
        wi = _winit(cfg.get("param_attr"), 1.0 / math.sqrt(d))
        p = {"w_row": wi(r1, (d, 5 * d)), "w_col": wi(r2, (d, 5 * d))}
        if cfg.get("bias_attr", True) is not False:
            # 5d gate bias + 5d peepholes (i_row, i_col, f_row, f_col, o)
            p["b"] = jnp.zeros((10 * d,), dtypes.param_dtype())
        return p

    def apply(self, ctx, cfg, params, x):
        d, h, w = cfg["size"], cfg["h"], cfg["w"]
        xd = value_data(x).reshape(-1, h, w, 5 * d)
        b5 = params.get("b")
        checks = [None] * 5
        if b5 is not None:
            xd = xd + b5[:5 * d]
            checks = [b5[5 * d + k * d: 5 * d + (k + 1) * d]
                      for k in range(5)]
        out = rnn_ops.md_lstm_2d(
            xd, params["w_row"], params["w_col"],
            check_i_row=checks[0], check_i_col=checks[1],
            check_f_row=checks[2], check_f_col=checks[3], check_o=checks[4],
            act=cfg.get("act", "tanh"), gate_act=cfg.get("gate_act",
                                                         "sigmoid"),
            state_act=cfg.get("state_act", "tanh"))
        return out.reshape(out.shape[0], -1)


register_layer("mdlstmemory")(_MDLstmImpl)


def mdlstmemory(input, size=None, height=None, width=None, act="tanh",
                gate_act="sigmoid", state_act="tanh", name=None,
                bias_attr=True, param_attr=None):
    """input: image-shaped layer of 5*size channels (pre-projected gates);
    height/width default to the input's img_shape."""
    if height is None or width is None:
        if input.img_shape is None:
            raise ConfigError("mdlstmemory needs height/width (or an input "
                              "with img_shape)")
        height, width = input.img_shape
    d = size or input.size // (5 * height * width)
    node = LayerOutput(name or auto_name("mdlstm"), "mdlstmemory",
                       d * height * width, [input],
                       {"size": d, "h": height, "w": width, "act": act,
                        "gate_act": gate_act, "state_act": state_act,
                        "bias_attr": bias_attr, "param_attr": param_attr},
                       is_seq=False, num_filters=d, img_shape=(height, width))
    return node
