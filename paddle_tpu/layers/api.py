"""v1-DSL layer constructors (dense / sequence / cost / util layers).

Reference surface: python/paddle/trainer_config_helpers/layers.py (~100
ctors, __all__ at :33-122) with size-inference semantics from
python/paddle/trainer/config_parser.py's @config_layer classes.  Vision
layers live in vision.py, recurrent machinery in recurrent.py.

Every ctor returns a LayerOutput graph node; compilation/execution is in
graph.py.  Hand-written C++ backward passes are replaced by jax.grad.
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.layers.graph import (
    LayerOutput, register_layer, auto_name, map_rows, as_seq, value_data)
from paddle_tpu.ops import (activations, linear, losses, math_ops, embedding as
                            emb_ops, sequence as seq_ops, crf as crf_ops,
                            ctc as ctc_ops, sampling as sampling_ops)
from paddle_tpu.utils.error import ConfigError

__all__ = [
    "data_layer", "fc_layer", "embedding_layer", "dropout_layer",
    "addto_layer", "concat_layer", "interpolation_layer", "power_layer",
    "scaling_layer", "slope_intercept_layer", "linear_comb_layer",
    "convex_comb_layer", "sum_to_one_norm_layer", "cos_sim",
    "out_prod_layer", "trans_layer", "rotate_layer", "tensor_layer",
    "multiplex_layer", "conv_shift_layer", "featmap_expand_layer",
    "resize_layer", "prelu_layer", "selective_fc_layer",
    "pooling_layer", "last_seq", "first_seq", "expand_layer",
    "seq_concat_layer", "seq_reshape_layer", "sub_seq_layer",
    "seq_slice_layer", "maxid_layer", "eos_layer", "sampling_id_layer",
    "print_layer", "mixed_layer", "full_matrix_projection",
    "trans_full_matrix_projection", "identity_projection", "table_projection",
    "dotmul_projection", "scaling_projection", "context_projection",
    "dotmul_operator",
    "classification_cost", "regression_cost", "mse_cost", "cross_entropy",
    "cross_entropy_with_selfnorm", "soft_binary_class_cross_entropy",
    "multi_binary_label_cross_entropy", "rank_cost", "lambda_cost",
    "huber_cost", "smooth_l1_cost", "sum_cost", "crf_layer",
    "crf_decoding_layer", "ctc_layer", "warp_ctc_layer", "nce_layer",
    "hsigmoid", "pooling", "slice_projection",
    "AggregateLevel", "ExpandLevel", "repeat_layer",
    "moe_layer",
]


# ---------------------------------------------------------------- helpers

def _winit(param_attr, default_std=None):
    """Weight initializer from a ParamAttr-style dict (reference
    ParameterAttribute: initial_mean/initial_std, default std=1/sqrt(fan_in)
    per config_parser Parameter defaults)."""
    attr = param_attr or {}
    if callable(attr.get("init")):
        return attr["init"]

    def init(rng, shape, dtype=None):
        dtype = dtype or dtypes.param_dtype()
        std = attr.get("initial_std", default_std)
        mean = attr.get("initial_mean", 0.0)
        if std is None:
            std = 1.0 / math.sqrt(max(shape[0], 1))
        if attr.get("initial_strategy", 0) == 1:  # uniform
            return jax.random.uniform(rng, shape, dtype, -std, std) + mean
        return mean + std * jax.random.normal(rng, shape, dtype)
    return init


def _maybe_bias(rng, bias_attr, size):
    if bias_attr is False or bias_attr is None:
        return None
    attr = bias_attr if isinstance(bias_attr, dict) else {}
    std = attr.get("initial_std", 0.0)
    mean = attr.get("initial_mean", 0.0)
    b = jnp.full((size,), mean, dtypes.param_dtype())
    if std:
        b = b + std * jax.random.normal(rng, (size,), dtypes.param_dtype())
    return b


def _dropout(ctx, cfg, value):
    rate = cfg.get("drop_rate", 0.0)
    if not rate or not ctx.is_train():
        return value
    def drop(x):
        keep = jax.random.bernoulli(ctx.next_rng(), 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), 0.0)
    return map_rows(drop, value)


def _inputs_list(input):
    return list(input) if isinstance(input, (list, tuple)) else [input]


# ---------------------------------------------------------------- data

class _DataImpl:
    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def apply(self, ctx, cfg, params):
        raise RuntimeError("data layers are fed, not applied")


register_layer("data")(_DataImpl)


def data_layer(name, size, is_seq=False, height=None, width=None):
    """Reference: data_layer(name, size) (layers.py DataLayer); height/width
    carry image shape for the conv stack."""
    img = (height, width) if height and width else None
    return LayerOutput(name, "data", size, cfg={"size": size}, is_seq=is_seq,
                       img_shape=img)


# ---------------------------------------------------------------- fc

class _FcImpl:
    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        p = {}
        rngs = jax.random.split(rng, len(in_sizes) + 1)
        pa = cfg.get("param_attr")
        # reference fc_layer accepts one ParamAttr per input (sentiment's
        # stacked_lstm_net passes [fc_attr, lstm_attr])
        pas = (list(pa) if isinstance(pa, (list, tuple))
               else [pa] * len(in_sizes))
        for i, isz in enumerate(in_sizes):
            p[f"w{i}"] = _winit(pas[i % len(pas)])(rngs[i],
                                                   (isz, cfg["size"]))
        b = _maybe_bias(rngs[-1], cfg.get("bias_attr", True), cfg["size"])
        if b is not None:
            p["b"] = b
        return p

    def apply(self, ctx, cfg, params, *inputs):
        def fn(*datas):
            y = linear.matmul(datas[0], params["w0"])
            for i in range(1, len(datas)):
                y = y + linear.matmul(datas[i], params[f"w{i}"])
            if "b" in params:
                y = y + params["b"]
            return activations.get(cfg.get("act"))(y)
        return _dropout(ctx, cfg, map_rows(fn, *inputs))


register_layer("fc")(_FcImpl)


# ---------------------------------------------------------------- moe

class _MoeImpl:
    """Mixture-of-experts FFN over the row dimension (ops/moe.py) — a
    post-reference capability layer; experts shard over the 'expert' mesh
    axis under a mesh trainer (moe.expert_shardings)."""

    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def init(self, rng, cfg, in_sizes):
        from paddle_tpu.ops import moe
        return moe.init_moe(rng, in_sizes[0], cfg["expert_dim"],
                            cfg["n_experts"])

    def apply(self, ctx, cfg, params, x):
        from paddle_tpu.ops import moe

        def fn(d):
            # moe_ffn wants [B, T, D]; flatten any leading dims (dense
            # [B, D] and nested-sequence [B, S, T, D] included) and restore
            lead = d.shape[:-1]
            out = moe.moe_ffn(d.reshape(1, -1, d.shape[-1]), params,
                              top_k=cfg["top_k"])
            return out.reshape(*lead, d.shape[-1])
        return map_rows(fn, x)


register_layer("moe")(_MoeImpl)


def moe_layer(input, n_experts, expert_dim=None, top_k=2, name=None):
    """Gated mixture-of-experts FFN: `n_experts` experts of hidden width
    `expert_dim` (default 4x the input size), top_k-gated, residual-free
    (compose with addto_layer for a residual block).  Output size ==
    input size."""
    ins = _inputs_list(input)
    if len(ins) != 1:
        from paddle_tpu.utils.error import ConfigError
        raise ConfigError("moe_layer takes a single input (got "
                          f"{len(ins)}); concat upstream if needed")
    cfg = {"n_experts": n_experts, "top_k": top_k,
           "expert_dim": expert_dim or 4 * ins[0].size}
    return LayerOutput(name or auto_name("moe"), "moe", ins[0].size, ins, cfg)


def fc_layer(input, size, act="tanh", name=None, bias_attr=True,
             param_attr=None, layer_attr=None):
    ins = _inputs_list(input)
    cfg = {"size": size, "act": act, "bias_attr": bias_attr,
           "param_attr": param_attr}
    cfg.update(layer_attr or {})
    return LayerOutput(name or auto_name("fc"), "fc", size, ins, cfg)


# ---------------------------------------------------------------- embedding

class _EmbeddingImpl:
    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        return {"w": _winit(cfg.get("param_attr"),
                            default_std=1.0 / math.sqrt(cfg["vocab"]))(
            rng, (cfg["vocab"], cfg["size"]))}

    def apply(self, ctx, cfg, params, ids):
        def fn(d):
            return emb_ops.embedding_lookup(params["w"], d.astype(jnp.int32))
        return map_rows(fn, ids)


register_layer("embedding")(_EmbeddingImpl)


def embedding_layer(input, size, name=None, param_attr=None,
                    sparse_update=None, sparse_budget=None):
    """input: a data layer of integer ids (its .size = vocab size).

    sparse_update=True (reference ParameterAttribute sparse_update /
    SparseRowMatrix): the trainer gathers only the rows touched this batch,
    differentiates and optimizer-updates that [budget, D] block, and
    scatters it back — step cost scales with touched rows, not vocab.
    sparse_budget: static unique-row cap (default: batch token count rounded
    up to a power of two)."""
    if sparse_update is None and isinstance(param_attr, dict):
        sparse_update = param_attr.get("sparse_update", False)
    return LayerOutput(name or auto_name("embedding"), "embedding", size,
                       [input],
                       cfg={"size": size, "vocab": input.size,
                            "param_attr": param_attr,
                            "sparse_update": bool(sparse_update),
                            "sparse_budget": sparse_budget})


def table_projection(input, size=0, param_attr=None):
    """size=0 takes the enclosing mixed layer's width (reference
    table_projection default)."""
    return _Part("table", [input], {"param_attr": param_attr}, size)


# ---------------------------------------------------------------- mixed

class _MixedImpl:
    """MixedLayer: sum of projections/operators (reference MixedLayer.cpp).
    cfg['parts']: list of (kind, spec) aligned with the node's inputs list
    (one input per part; operators consume two)."""

    def infer(self, cfg, in_sizes):
        # config-time width check (reference MixedLayer asserts every
        # projection's output height/width against the layer size)
        size, idx = cfg["size"], 0
        for kind, spec in cfg["parts"]:
            isz = in_sizes[idx] if idx < len(in_sizes) else None
            out = None
            if kind == "identity":
                out = spec.get("size") or isz
            elif kind in ("dotmul", "scaling", "dotmul_op"):
                out = isz
            elif kind == "context":
                out = isz * spec["context_len"]
            if out is not None and out != size:
                raise ConfigError(
                    f"mixed_layer(size={size}): {kind} projection yields "
                    f"size {out} — all parts must produce the layer size")
            idx += 2 if kind in ("dotmul_op", "conv_op") else 1
        return size

    def init(self, rng, cfg, in_sizes):
        p = {}
        idx = 0
        rngs = jax.random.split(rng, len(cfg["parts"]) + 1)
        for k, (kind, spec) in enumerate(cfg["parts"]):
            isz = in_sizes[idx]
            if kind == "full_matrix":
                p[f"w{k}"] = _winit(spec.get("param_attr"))(rngs[k], (isz, cfg["size"]))
            elif kind == "trans_full_matrix":
                p[f"w{k}"] = _winit(spec.get("param_attr"))(rngs[k], (cfg["size"], isz))
            elif kind == "table":
                p[f"w{k}"] = _winit(spec.get("param_attr"))(
                    rngs[k], (spec["vocab"], cfg["size"]))
            elif kind == "dotmul":
                p[f"w{k}"] = jnp.ones((cfg["size"],), dtypes.param_dtype())
            elif kind == "scaling":
                p[f"w{k}"] = jnp.ones((1,), dtypes.param_dtype())
            elif kind == "context" and spec.get("trainable_padding"):
                pad_rows = max(0, -spec["context_start"]) + max(
                    0, spec["context_start"] + spec["context_len"] - 1)
                p[f"w{k}"] = _winit(spec.get("param_attr"))(rngs[k], (pad_rows, isz))
            elif kind == "conv_proj":
                fh, fw = spec["filter_size"]
                g = spec.get("groups", 1) or 1
                p[f"w{k}"] = _winit(spec.get("param_attr"))(
                    rngs[k], (fh, fw, spec["channels"] // g,
                              spec["num_filters"]))
            idx += 2 if kind in ("dotmul_op", "conv_op") else 1
        b = _maybe_bias(rngs[-1], cfg.get("bias_attr", False), cfg["size"])
        if b is not None:
            p["b"] = b
        return p

    def apply(self, ctx, cfg, params, *inputs):
        from paddle_tpu.ops import conv as conv_ops
        total = None
        idx = 0
        for k, (kind, spec) in enumerate(cfg["parts"]):
            if kind == "dotmul_op":
                a, b2 = inputs[idx], inputs[idx + 1]
                part = map_rows(lambda x, y: spec.get("scale", 1.0) * x * y, a, b2)
                idx += 2
            elif kind == "conv_op":
                # reference ConvOperator.cpp:58-83: per-sample conv, each
                # row of input(1) is that sample's own filter -> vmap
                img, filt = inputs[idx], inputs[idx + 1]
                idx += 2
                c, (h, w) = spec["channels"], spec["in_shape"]
                fh, fw = spec["filter_size"]
                nf = spec["num_filters"]

                def one(img_row, filt_row):
                    x = img_row.reshape(c, h, w).transpose(1, 2, 0)[None]
                    wgt = filt_row.reshape(nf, c, fh, fw).transpose(2, 3, 1, 0)
                    y = conv_ops.conv2d(x, wgt, stride=spec["stride"],
                                        padding=spec["padding"])
                    return y.transpose(0, 3, 1, 2).reshape(-1)

                part = map_rows(
                    lambda im, fl: jax.vmap(one)(im, fl), img, filt)
            else:
                v = inputs[idx]
                idx += 1
                if kind == "full_matrix":
                    part = map_rows(lambda d: linear.matmul(d, params[f"w{k}"]), v)
                elif kind == "trans_full_matrix":
                    part = map_rows(lambda d: linear.matmul(d, params[f"w{k}"].T), v)
                elif kind == "table":
                    part = map_rows(lambda d: emb_ops.embedding_lookup(
                        params[f"w{k}"], d.astype(jnp.int32)), v)
                elif kind == "identity":
                    off = spec.get("offset", 0)
                    sz = spec.get("size")
                    part = map_rows(
                        lambda d: d if sz is None else d[..., off:off + sz], v)
                elif kind == "dotmul":
                    part = map_rows(lambda d: d * params[f"w{k}"], v)
                elif kind == "scaling":
                    part = map_rows(lambda d: d * params[f"w{k}"].reshape(()), v)
                elif kind == "context":
                    part = seq_ops.context_projection(
                        as_seq(v), spec["context_len"], spec["context_start"],
                        params.get(f"w{k}"))
                elif kind == "conv_proj":
                    c, (h, w) = spec["channels"], spec["in_shape"]

                    def conv_rows(d):
                        x = d.reshape(d.shape[0], c, h, w).transpose(0, 2, 3, 1)
                        y = conv_ops.conv2d(x, params[f"w{k}"],
                                            stride=spec["stride"],
                                            padding=spec["padding"],
                                            groups=spec.get("groups", 1) or 1)
                        return y.transpose(0, 3, 1, 2).reshape(d.shape[0], -1)

                    part = map_rows(conv_rows, v)
                else:
                    raise ConfigError(f"unknown mixed part {kind}")
            total = part if total is None else map_rows(
                lambda a, b3: a + b3, total, part)
        if "b" in params:
            total = map_rows(lambda d: d + params["b"], total)
        out = map_rows(activations.get(cfg.get("act")), total)
        return _dropout(ctx, cfg, out)


register_layer("mixed")(_MixedImpl)


class _Part:
    """A projection/operator awaiting inclusion in mixed_layer."""

    def __init__(self, kind, input_nodes, spec, out_size):
        self.kind = kind
        self.inputs = input_nodes
        self.spec = spec
        self.out_size = out_size


def full_matrix_projection(input, size=0, param_attr=None):
    return _Part("full_matrix", [input], {"param_attr": param_attr}, size)


def trans_full_matrix_projection(input, size=0, param_attr=None):
    return _Part("trans_full_matrix", [input], {"param_attr": param_attr}, size)


def identity_projection(input, offset=None, size=None):
    if offset is None:
        return _Part("identity", [input], {}, input.size)
    out = size if size is not None else input.size - offset
    return _Part("identity", [input], {"offset": offset, "size": out}, out)


def slice_projection(input, slices):
    """Reference slice_projection: concat of [start, end) column slices."""
    parts = []
    for s, e in slices:
        parts.append(_Part("identity", [input], {"offset": s, "size": e - s}, e - s))
    return parts


def dotmul_projection(input, param_attr=None):
    return _Part("dotmul", [input], {"param_attr": param_attr}, input.size)


def scaling_projection(input, param_attr=None):
    return _Part("scaling", [input], {"param_attr": param_attr}, input.size)


def context_projection(input, context_len, context_start=None,
                       padding_attr=False):
    start = context_start if context_start is not None else -(context_len // 2)
    return _Part("context", [input],
                 {"context_len": context_len, "context_start": start,
                  "trainable_padding": bool(padding_attr),
                  "param_attr": padding_attr if isinstance(padding_attr, dict) else None},
                 input.size * context_len)


def dotmul_operator(a, b, scale=1.0):
    return _Part("dotmul_op", [a, b], {"scale": scale}, a.size)


def _collect_parts(input):
    parts = []
    for item in _inputs_list(input):
        if isinstance(item, list):
            parts.extend(item)
        elif isinstance(item, _Part):
            parts.append(item)
        elif isinstance(item, LayerOutput):
            parts.append(identity_projection(item))
        else:
            raise ConfigError(f"bad mixed_layer input {item!r}")
    return parts


def _finalize_mixed(node, parts, size):
    if size == 0:
        size = max(p.out_size for p in parts)
    nodes = []
    cfg_parts = []
    for p in parts:
        spec = dict(p.spec)
        if p.kind == "table":
            spec["vocab"] = p.inputs[0].size
        cfg_parts.append((p.kind, spec))
        nodes.extend(p.inputs)
    node.size = int(size)
    node.inputs = nodes
    # inputs arrive after construction: recompute sequence-ness propagation
    node.is_seq = any(getattr(n, "is_seq", False) for n in nodes)
    node.cfg.update({"size": size, "parts": cfg_parts})
    return node


class MixedLayer(LayerOutput):
    """Deferred mixed layer supporting the reference's builder protocol:

        with mixed_layer(size=d) as m:
            m += full_matrix_projection(input=a)
            m += identity_projection(input=b)

    The `as` target IS the LayerOutput (used downstream after the with);
    projections accumulate via += and the node finalizes on __exit__."""

    def __init__(self, size, name, act, bias_attr, layer_attr):
        super().__init__(name or auto_name("mixed"), "mixed", max(size, 1),
                         [], {"size": size, "act": act,
                              "bias_attr": bias_attr, "parts": []})
        self.cfg.update(layer_attr or {})
        self._parts = []
        self._decl_size = size
        self._finalized = False

    def __iadd__(self, part):
        if self._finalized:
            raise ConfigError("mixed_layer already finalized")
        self._parts.extend(_collect_parts(part))
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            if not self._parts:
                raise ConfigError("empty mixed_layer: add projections "
                                  "with += inside the with block")
            _finalize_mixed(self, self._parts, self._decl_size)
            self._finalized = True
        return False


def mixed_layer(size=0, input=None, name=None, act=None, bias_attr=False,
                layer_attr=None):
    if input is None:
        return MixedLayer(size, name, act, bias_attr, layer_attr)
    parts = _collect_parts(input)
    cfg = {"size": size, "act": act, "bias_attr": bias_attr, "parts": []}
    cfg.update(layer_attr or {})
    node = LayerOutput(name or auto_name("mixed"), "mixed", max(size, 1),
                       [], cfg)
    return _finalize_mixed(node, parts, size)


# ------------------------------------------------------- elementwise layers

def _simple_layer(type_name, infer_fn, apply_fn, needs=None):
    class Impl:
        def infer(self, cfg, in_sizes):
            return infer_fn(cfg, in_sizes)

        def apply(self, ctx, cfg, params, *inputs):
            return apply_fn(ctx, cfg, *inputs)
    register_layer(type_name)(Impl)


_simple_layer("addto", lambda cfg, s: s[0],
              lambda ctx, cfg, *ins: map_rows(
                  lambda *ds: activations.get(cfg.get("act"))(
                      sum(ds[1:], ds[0])), *ins))


def addto_layer(input, act=None, name=None, bias_attr=False):
    ins = _inputs_list(input)
    return LayerOutput(name or auto_name("addto"), "addto", ins[0].size, ins,
                       {"act": act})


_simple_layer("concat", lambda cfg, s: sum(s),
              lambda ctx, cfg, *ins: map_rows(
                  lambda *ds: jnp.concatenate(ds, axis=-1), *ins))


def concat_layer(input, act=None, name=None, bias_attr=False,
                 layer_attr=None):
    # the reference concat accepts projections too (concat_layer(input=
    # [identity_projection(a), ...])) — realize each as a one-part mixed
    ins = [mixed_layer(size=item.out_size, input=[item], act=None)
           if isinstance(item, _Part) else item
           for item in _inputs_list(input)]
    return LayerOutput(name or auto_name("concat"), "concat",
                       sum(i.size for i in ins), ins, {"act": act})


_simple_layer("interpolation", lambda cfg, s: s[1],
              lambda ctx, cfg, w, a, b: map_rows(math_ops.interpolation, w, a, b))


def interpolation_layer(input, weight, name=None):
    a, b = input
    return LayerOutput(name or auto_name("interpolation"), "interpolation",
                       a.size, [weight, a, b], {})


_simple_layer("power", lambda cfg, s: s[1],
              lambda ctx, cfg, p, x: map_rows(math_ops.power, p, x))


def power_layer(input, weight, name=None):
    return LayerOutput(name or auto_name("power"), "power", input.size,
                       [weight, input], {})


_simple_layer("scaling", lambda cfg, s: s[1],
              lambda ctx, cfg, w, x: map_rows(math_ops.scaling, w, x))


def scaling_layer(input, weight, name=None):
    return LayerOutput(name or auto_name("scaling"), "scaling", input.size,
                       [weight, input], {})


_simple_layer("slope_intercept", lambda cfg, s: s[0],
              lambda ctx, cfg, x: map_rows(
                  lambda d: cfg["slope"] * d + cfg["intercept"], x))


def slope_intercept_layer(input, slope=1.0, intercept=0.0, name=None):
    return LayerOutput(name or auto_name("slope_intercept"), "slope_intercept",
                       input.size, [input],
                       {"slope": slope, "intercept": intercept})


_simple_layer("linear_comb", lambda cfg, s: cfg["size"],
              lambda ctx, cfg, w, x: map_rows(
                  lambda wd, xd: linear.linear_comb(xd, wd, cfg["size"]), w, x))


def linear_comb_layer(weights, vectors, size=None, name=None):
    if size is None:
        # reference default: vectors holds `weights.size` rows of width size
        if vectors.size % max(weights.size, 1) == 0:
            size = vectors.size // weights.size
        else:
            raise ConfigError("linear_comb_layer needs size")
    return LayerOutput(name or auto_name("linear_comb"), "linear_comb", size,
                       [weights, vectors], {"size": size})


convex_comb_layer = linear_comb_layer


_simple_layer("sum_to_one_norm", lambda cfg, s: s[0],
              lambda ctx, cfg, x: map_rows(math_ops.sum_to_one_norm, x))


def sum_to_one_norm_layer(input, name=None):
    return LayerOutput(name or auto_name("sum_to_one_norm"), "sum_to_one_norm",
                       input.size, [input], {})


_simple_layer("cos_sim", lambda cfg, s: 1,
              lambda ctx, cfg, a, b: map_rows(
                  lambda x, y: math_ops.cos_sim(x, y, cfg.get("scale", 1.0)), a, b))


def cos_sim(a, b, scale=1.0, size=1, name=None):
    if size > 1:
        return LayerOutput(name or auto_name("cos_vm"), "cos_sim_vec_mat", size,
                           [a, b], {"scale": scale, "size": size})
    return LayerOutput(name or auto_name("cos_sim"), "cos_sim", 1, [a, b],
                       {"scale": scale})


_simple_layer("cos_sim_vec_mat", lambda cfg, s: cfg["size"],
              lambda ctx, cfg, a, b: map_rows(
                  lambda v, m: math_ops.cos_sim_vec_mat(
                      v, m.reshape(m.shape[0], cfg["size"], -1),
                      cfg.get("scale", 1.0)), a, b))


_simple_layer("out_prod", lambda cfg, s: s[0] * s[1],
              lambda ctx, cfg, a, b: map_rows(math_ops.outer_prod, a, b))


def out_prod_layer(a, b, name=None):
    return LayerOutput(name or auto_name("out_prod"), "out_prod",
                       a.size * b.size, [a, b], {})


_simple_layer("trans", lambda cfg, s: s[0],
              lambda ctx, cfg, x: math_ops.trans(value_data(x)))


def trans_layer(input, name=None):
    return LayerOutput(name or auto_name("trans"), "trans", input.size,
                       [input], {})


_simple_layer("rotate", lambda cfg, s: s[0],
              lambda ctx, cfg, x: map_rows(
                  lambda d: math_ops.rotate(d, cfg["height"], cfg["width"]), x))


def rotate_layer(input, height, width, name=None):
    return LayerOutput(name or auto_name("rotate"), "rotate", input.size,
                       [input], {"height": height, "width": width})


class _TensorImpl:
    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        return {"w": _winit(cfg.get("param_attr"))(
            rng, (cfg["size"], in_sizes[0], in_sizes[1]))}

    def apply(self, ctx, cfg, params, a, b):
        return map_rows(lambda x, y: math_ops.tensor_product(
            x, y, params["w"], cfg.get("act")), a, b)


register_layer("tensor")(_TensorImpl)


def tensor_layer(a, b, size, act=None, name=None, param_attr=None,
                 bias_attr=False):
    return LayerOutput(name or auto_name("tensor"), "tensor", size, [a, b],
                       {"size": size, "act": act, "param_attr": param_attr})


_simple_layer("multiplex", lambda cfg, s: s[1],
              lambda ctx, cfg, idx, *xs: map_rows(
                  lambda i, *ds: math_ops.multiplex(i, *ds), idx, *xs))


def multiplex_layer(input, name=None):
    idx, *rest = input
    return LayerOutput(name or auto_name("multiplex"), "multiplex",
                       rest[0].size, [idx] + rest, {})


_simple_layer("conv_shift", lambda cfg, s: s[0],
              lambda ctx, cfg, a, b: map_rows(math_ops.conv_shift, a, b))


def conv_shift_layer(a, b, name=None):
    return LayerOutput(name or auto_name("conv_shift"), "conv_shift", a.size,
                       [a, b], {})


_simple_layer("featmap_expand", lambda cfg, s: s[0] * cfg["num_filters"],
              lambda ctx, cfg, x: map_rows(
                  lambda d: math_ops.feature_map_expand(
                      d, cfg["num_filters"], cfg.get("as_row_vector", True)), x))


def featmap_expand_layer(input, num_filters, as_row_vector=True, name=None):
    return LayerOutput(name or auto_name("featmap_expand"), "featmap_expand",
                       input.size * num_filters, [input],
                       {"num_filters": num_filters, "as_row_vector": as_row_vector})


_simple_layer("resize", lambda cfg, s: cfg["size"],
              lambda ctx, cfg, x: math_ops.resize(value_data(x), cfg["size"]))


_simple_layer("repeat", lambda cfg, s: s[0] * cfg["n"],
              lambda ctx, cfg, v: map_rows(
                  lambda d: jnp.tile(d, (1,) * (d.ndim - 1) + (cfg["n"],)), v))


class AggregateLevel:
    """Reference AggregateLevel (layers.py:227)."""
    EACH_TIMESTEP = "non-seq"
    EACH_SEQUENCE = "seq"
    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"


class ExpandLevel:
    """Reference ExpandLevel (layers.py:1456)."""
    FROM_TIMESTEP = AggregateLevel.EACH_TIMESTEP
    FROM_SEQUENCE = AggregateLevel.EACH_SEQUENCE
    FROM_NO_SEQUENCE = AggregateLevel.EACH_TIMESTEP


def repeat_layer(input, num_repeats, name=None, layer_attr=None):
    """Reference repeat_layer: y = [x, x, ..., x] (concat num_repeats
    copies, layers.py:1514)."""
    return LayerOutput(name or auto_name("repeat"), "repeat",
                       input.size * num_repeats, [input], {"n": num_repeats})


def resize_layer(input, size, name=None):
    return LayerOutput(name or auto_name("resize"), "resize", size, [input],
                       {"size": size}, is_seq=False)


class _PreluImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def init(self, rng, cfg, in_sizes):
        n = cfg.get("partial_sum", 1)
        return {"alpha": jnp.full((in_sizes[0] // n if n else in_sizes[0],),
                                  0.25, dtypes.param_dtype())}

    def apply(self, ctx, cfg, params, x):
        n = cfg.get("partial_sum", 1)
        def fn(d):
            alpha = jnp.repeat(params["alpha"], n) if n > 1 else params["alpha"]
            return math_ops.prelu(d, alpha)
        return map_rows(fn, x)


register_layer("prelu")(_PreluImpl)


def prelu_layer(input, partial_sum=1, name=None, param_attr=None):
    return LayerOutput(name or auto_name("prelu"), "prelu", input.size,
                       [input], {"partial_sum": partial_sum,
                                 "param_attr": param_attr})


class _SelectiveFcImpl:
    """Reference SelectiveFullyConnectedLayer: fc over the full class matrix,
    but only selected columns are computed/returned when a selection input is
    given.  Dense fallback multiplies then masks (MXU-friendly)."""

    def infer(self, cfg, in_sizes):
        return cfg["size"]

    def init(self, rng, cfg, in_sizes):
        r1, r2 = jax.random.split(rng)
        p = {"w": _winit(cfg.get("param_attr"))(r1, (in_sizes[0], cfg["size"]))}
        b = _maybe_bias(r2, cfg.get("bias_attr", True), cfg["size"])
        if b is not None:
            p["b"] = b
        return p

    def apply(self, ctx, cfg, params, x, sel=None):
        def fn(d):
            y = linear.matmul(d, params["w"])
            if "b" in params:
                y = y + params["b"]
            return activations.get(cfg.get("act"))(y)
        out = map_rows(fn, x)
        if sel is not None:
            out = map_rows(lambda o, s: o * s, out, sel)
        return out


register_layer("selective_fc")(_SelectiveFcImpl)


def selective_fc_layer(input, size, select=None, act="tanh", name=None,
                       param_attr=None, bias_attr=True):
    ins = [input] + ([select] if select is not None else [])
    return LayerOutput(name or auto_name("selective_fc"), "selective_fc", size,
                       ins, {"size": size, "act": act, "param_attr": param_attr,
                             "bias_attr": bias_attr})


# ------------------------------------------------------- dropout

def dropout_layer(input, dropout_rate, name=None):
    return LayerOutput(name or auto_name("dropout"), "dropout", input.size,
                       [input], {"drop_rate": dropout_rate})


class _DropoutImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def apply(self, ctx, cfg, params, x):
        return _dropout(ctx, cfg, x)


register_layer("dropout")(_DropoutImpl)


# ------------------------------------------------------- sequence layers

class _SeqPoolImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def apply(self, ctx, cfg, params, x):
        from paddle_tpu.core.sequence import NestedSequenceBatch
        if isinstance(x, NestedSequenceBatch):
            # reference sequence levels over sub-sequenced input:
            # TO_SEQUENCE pools within each sub-sequence (-> sequence of
            # pooled rows); default pools the whole (flattened) sequence
            each = cfg.get("agg_level") == "seq"
            return seq_ops.nested_seq_pool(x, cfg["pooling"],
                                           each_sequence=each)
        stride = cfg.get("stride", -1)
        if stride and stride > 0:
            return seq_ops.seq_strided_pool(as_seq(x), cfg["pooling"],
                                            int(stride))
        return seq_ops.seq_pool(as_seq(x), cfg["pooling"])


register_layer("seq_pool")(_SeqPoolImpl)


class pooling:
    """Pooling type markers (reference poolings.py MaxPooling/AvgPooling...)."""
    class Max:  # noqa: N801
        name = "max"

    class Avg:  # noqa: N801
        name = "avg"

    class Sum:  # noqa: N801
        name = "sum"

    class SqrtN:  # noqa: N801
        name = "sqrt"


def pooling_layer(input, pooling_type=None, name=None, agg_level=None):
    pt = getattr(pooling_type, "name", pooling_type) or "max"
    return LayerOutput(name or auto_name("seq_pool"), "seq_pool", input.size,
                       [input], {"pooling": pt, "agg_level": agg_level},
                       is_seq=agg_level == "seq")


def last_seq(input, name=None, agg_level=None, stride=-1):
    """stride > 0 (reference seqlastins stride): last instance of each
    non-overlapping stride window — output stays a (shorter) sequence.
    agg_level='seq' over a nested input pools each sub-sequence."""
    return LayerOutput(name or auto_name("last_seq"), "seq_pool", input.size,
                       [input], {"pooling": "last", "stride": stride,
                                 "agg_level": agg_level},
                       is_seq=stride > 0 or agg_level == "seq")


def first_seq(input, name=None, agg_level=None, stride=-1):
    return LayerOutput(name or auto_name("first_seq"), "seq_pool", input.size,
                       [input], {"pooling": "first", "stride": stride,
                                 "agg_level": agg_level},
                       is_seq=stride > 0 or agg_level == "seq")


_simple_layer("expand", lambda cfg, s: s[0],
              lambda ctx, cfg, vec, like: seq_ops.expand(
                  value_data(vec), as_seq(like)))


def expand_layer(input, expand_as, name=None, expand_level=None):
    out = LayerOutput(name or auto_name("expand"), "expand", input.size,
                      [input, expand_as], {}, is_seq=True)
    return out


_simple_layer("seq_concat", lambda cfg, s: s[0],
              lambda ctx, cfg, a, b: seq_ops.seq_concat(as_seq(a), as_seq(b)))


def seq_concat_layer(a, b, name=None):
    return LayerOutput(name or auto_name("seq_concat"), "seq_concat", a.size,
                       [a, b], {}, is_seq=True)


_simple_layer("seq_reshape", lambda cfg, s: cfg["size"],
              lambda ctx, cfg, x: seq_ops.seq_reshape(as_seq(x), cfg["size"]))


def seq_reshape_layer(input, reshape_size, name=None):
    return LayerOutput(name or auto_name("seq_reshape"), "seq_reshape",
                       reshape_size, [input], {"size": reshape_size},
                       is_seq=True)


class _SubSeqImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def apply(self, ctx, cfg, params, x, offsets, sizes):
        sb = as_seq(x)
        off = value_data(offsets).reshape(-1).astype(jnp.int32)
        sz = value_data(sizes).reshape(-1).astype(jnp.int32)
        return seq_ops.sub_seq(sb, off, sz, sb.max_len)


register_layer("sub_seq")(_SubSeqImpl)


def sub_seq_layer(input, offsets, sizes, name=None):
    return LayerOutput(name or auto_name("sub_seq"), "sub_seq", input.size,
                       [input, offsets, sizes], {}, is_seq=True)


def seq_slice_layer(input, starts=None, ends=None, name=None):
    ins = [input] + [x for x in (starts, ends) if x is not None]
    return LayerOutput(name or auto_name("seq_slice"), "seq_slice", input.size,
                       ins, {"has_starts": starts is not None,
                             "has_ends": ends is not None}, is_seq=True)


class _SeqSliceImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def apply(self, ctx, cfg, params, x, *rest):
        sb = as_seq(x)
        i = 0
        starts = ends = None
        if cfg["has_starts"]:
            starts = value_data(rest[i]).reshape(-1).astype(jnp.int32)
            i += 1
        if cfg["has_ends"]:
            ends = value_data(rest[i]).reshape(-1).astype(jnp.int32)
        return seq_ops.seq_slice(sb, starts, ends)


register_layer("seq_slice")(_SeqSliceImpl)


_simple_layer("maxid", lambda cfg, s: 1,
              lambda ctx, cfg, x: map_rows(seq_ops.max_id, x))


def maxid_layer(input, name=None):
    return LayerOutput(name or auto_name("maxid"), "maxid", 1, [input], {})


_simple_layer("eos", lambda cfg, s: 1,
              lambda ctx, cfg, x: map_rows(
                  lambda d: seq_ops.eos_check(d, cfg["eos_id"]), x))


def eos_layer(input, eos_id, name=None):
    return LayerOutput(name or auto_name("eos"), "eos", 1, [input],
                       {"eos_id": eos_id})


class _SamplingIdImpl:
    def infer(self, cfg, in_sizes):
        return 1

    def apply(self, ctx, cfg, params, x):
        return map_rows(lambda d: seq_ops.sampling_id(ctx.next_rng(), d), x)


register_layer("sampling_id")(_SamplingIdImpl)


def sampling_id_layer(input, name=None):
    return LayerOutput(name or auto_name("sampling_id"), "sampling_id", 1,
                       [input], {})


class _PrintImpl:
    def infer(self, cfg, in_sizes):
        return in_sizes[0]

    def apply(self, ctx, cfg, params, x):
        jax.debug.print(cfg.get("format", "{}"), value_data(x))
        return x


register_layer("print")(_PrintImpl)


def print_layer(input, format=None, name=None):
    return LayerOutput(name or auto_name("print"), "print", input.size,
                       [input], {"format": format or "{}"})


# ------------------------------------------------------- cost layers

def _seq_or_row_mean(loss, like):
    """Per-token losses on sequences average over valid tokens per sample."""
    if isinstance(like, SequenceBatch):
        return losses.masked_seq_mean(loss, like.mask(loss.dtype))
    return loss


class _CostImpl:
    def __init__(self, fn, needs_logits=True):
        self.fn = fn

    def infer(self, cfg, in_sizes):
        return 1

    def apply(self, ctx, cfg, params, *ins):
        return self.fn(ctx, cfg, *ins)


def _register_cost(type_name, fn):
    class Impl:
        def infer(self, cfg, in_sizes):
            return 1

        def apply(self, ctx, cfg, params, *ins):
            if cfg.get("weighted"):
                # reference: cost layers accept a per-sample weight input
                # (CostLayer::forward weights_, e.g. classification_cost
                # (input, label, weight))
                *core, w = ins
                val = fn(ctx, cfg, *core)
                wd = value_data(w)
                return val * wd.reshape(wd.shape[0], -1)[:, 0]
            return fn(ctx, cfg, *ins)
    register_layer(type_name)(Impl)


def _ce_cost(ctx, cfg, pred, label):
    pd, ld = value_data(pred), value_data(label)
    ids = ld.reshape(ld.shape[:-1] if ld.shape[-1] == 1 else ld.shape)
    per = losses.classification_cost(pd, ids, from_logits=cfg.get("from_logits", True))
    return _seq_or_row_mean(per, pred)


_register_cost("classification_cost", _ce_cost)


def _logits_view(node):
    """If `node` is a softmax-activated layer, build a logits alias: same
    type/inputs/params (shared via param_name) with act=None.  This fuses
    softmax+CE the way the reference's MultiClassCrossEntropy backward
    writes (p - y) straight into the softmax layer
    (gserver/layers/CostLayer.cpp) — the log(max(p, eps)) formulation has
    zero gradient once a probability underflows eps, which kills training;
    log_softmax(logits) never saturates."""
    if node.cfg.get("act") != "softmax" or node.cfg.get("drop_rate"):
        # dropout runs after the activation; CE(log_softmax(dropout(z)))
        # would differ from the documented CE over dropout(softmax(z)), so a
        # softmax layer with dropout keeps the unfused probability path.
        return None
    cfg = dict(node.cfg)
    cfg["act"] = None
    # alias key must match Topology._param_key exactly (explicit param_name,
    # else param_attr name, else layer name) or the alias layer inits and
    # trains a second parameter set while prediction reads the original
    if "param_name" in node.cfg:
        key = node.cfg["param_name"]
    else:
        pa = node.cfg.get("param_attr")
        key = pa["name"] if isinstance(pa, dict) and pa.get("name") else node.name
    cfg["param_name"] = key
    return LayerOutput(auto_name(node.name + "_logits"), node.layer_type,
                       node.size, node.inputs, cfg, is_seq=node.is_seq,
                       num_filters=node.num_filters, img_shape=node.img_shape)


def classification_cost(input, label, weight=None, name=None, evaluator=None,
                        from_logits=False):
    """Reference classification_cost: input is softmax output; here the
    graph usually ends with act='softmax', so from_logits defaults False.
    When the input is a softmax layer we rewire onto its logits (see
    _logits_view) for a numerically exact fused gradient.  weight: optional
    per-sample cost weight layer."""
    if not from_logits:
        logits = _logits_view(input)
        if logits is not None:
            input, from_logits = logits, True
    ins = [input, label] + ([weight] if weight is not None else [])
    return LayerOutput(name or auto_name("cost"), "classification_cost", 1,
                       ins, {"from_logits": from_logits,
                             "weighted": weight is not None},
                       is_seq=False)


def cross_entropy(input, label, name=None, from_logits=False):
    return classification_cost(input, label, name=name, from_logits=from_logits)


_register_cost("mse", lambda ctx, cfg, p, l: _seq_or_row_mean(
    losses.square_error(value_data(p), value_data(l)), p))


def regression_cost(input, label, weight=None, name=None):
    ins = [input, label] + ([weight] if weight is not None else [])
    return LayerOutput(name or auto_name("mse"), "mse", 1, ins,
                       {"weighted": weight is not None}, is_seq=False)


mse_cost = regression_cost


_register_cost("ce_selfnorm", lambda ctx, cfg, p, l: _seq_or_row_mean(
    losses.cross_entropy_with_selfnorm(
        value_data(p), value_data(l).reshape(value_data(p).shape[:-1]),
        cfg.get("alpha", 0.1)), p))


def cross_entropy_with_selfnorm(input, label, alpha=0.1, name=None):
    return LayerOutput(name or auto_name("ce_selfnorm"), "ce_selfnorm", 1,
                       [input, label], {"alpha": alpha}, is_seq=False)


_register_cost("soft_bce", lambda ctx, cfg, p, l: _seq_or_row_mean(
    losses.soft_binary_class_cross_entropy(value_data(p), value_data(l)), p))


def soft_binary_class_cross_entropy(input, label, name=None):
    return LayerOutput(name or auto_name("soft_bce"), "soft_bce", 1,
                       [input, label], {}, is_seq=False)


_register_cost("multi_bce", lambda ctx, cfg, p, l: _seq_or_row_mean(
    losses.multi_binary_label_cross_entropy(value_data(p), value_data(l)), p))


def multi_binary_label_cross_entropy(input, label, name=None):
    return LayerOutput(name or auto_name("multi_bce"), "multi_bce", 1,
                       [input, label], {}, is_seq=False)


_register_cost("rank", lambda ctx, cfg, left, right, label, *w:
               losses.rank_cost(value_data(left), value_data(right),
                                value_data(label),
                                value_data(w[0]) if w else None))


def rank_cost(left, right, label, weight=None, name=None):
    ins = [left, right, label] + ([weight] if weight is not None else [])
    return LayerOutput(name or auto_name("rank"), "rank", 1, ins, {},
                       is_seq=False)


_register_cost("lambda", lambda ctx, cfg, score, rel: losses.lambda_cost(
    value_data(score)[..., 0] if value_data(score).ndim == 3 else value_data(score),
    value_data(rel)[..., 0] if value_data(rel).ndim == 3 else value_data(rel),
    as_seq(score).mask(), cfg.get("ndcg_num", 5)))


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, name=None):
    return LayerOutput(name or auto_name("lambda"), "lambda", 1,
                       [input, score], {"ndcg_num": NDCG_num}, is_seq=False)


def _huber_cost(ctx, cfg, p, l):
    return losses.huber_classification(value_data(p), value_data(l))


_register_cost("huber", _huber_cost)


def huber_cost(input, label, name=None):
    return LayerOutput(name or auto_name("huber"), "huber", 1, [input, label],
                       {}, is_seq=False)


_register_cost("smooth_l1", lambda ctx, cfg, p, l: _seq_or_row_mean(
    losses.smooth_l1(value_data(p), value_data(l)), p))


def smooth_l1_cost(input, label, name=None):
    return LayerOutput(name or auto_name("smooth_l1"), "smooth_l1", 1,
                       [input, label], {}, is_seq=False)


_register_cost("sum_cost", lambda ctx, cfg, x: losses.sum_cost(value_data(x)))


def sum_cost(input, name=None):
    return LayerOutput(name or auto_name("sum_cost"), "sum_cost", 1, [input],
                       {}, is_seq=False)


# structured costs ----------------------------------------------------------

class _CrfImpl:
    def infer(self, cfg, in_sizes):
        return 1

    def init(self, rng, cfg, in_sizes):
        n = cfg["size"]
        return {"w": _winit(cfg.get("param_attr"), default_std=0.1)(
            rng, (n + 2, n))}

    def apply(self, ctx, cfg, params, emissions, label):
        sb = as_seq(emissions)
        ld = value_data(label)
        tags = ld[..., 0] if ld.ndim == 3 else ld
        return crf_ops.crf_log_likelihood(sb.data, tags.astype(jnp.int32),
                                          sb.lengths, params["w"])


register_layer("crf")(_CrfImpl)


def crf_layer(input, label, size=None, param_attr=None, name=None,
              weight=None, layer_attr=None):
    n = size or input.size
    # transition weights share by ParamAttr(name=...) like any layer
    # (reference: crf + crf_decoding share 'crfw')
    pa_name = param_attr.get("name") if isinstance(param_attr, dict) else None
    return LayerOutput(name or auto_name("crf"), "crf", 1, [input, label],
                       {"size": n, "param_attr": param_attr,
                        "param_name": pa_name or name or auto_name("crf_w")},
                       is_seq=False)


class _CrfDecodingImpl:
    def infer(self, cfg, in_sizes):
        return 1

    def init(self, rng, cfg, in_sizes):
        n = cfg["size"]
        return {"w": _winit(cfg.get("param_attr"), default_std=0.1)(
            rng, (n + 2, n))}

    def apply(self, ctx, cfg, params, emissions, label=None):
        sb = as_seq(emissions)
        tags, _ = crf_ops.crf_decode(sb.data, sb.lengths, params["w"])
        if label is not None:
            # reference CRFDecodingLayer with a label input emits the
            # per-position 0/1 error indicator instead of the tags
            lab = as_seq(label)
            ld = lab.data.reshape(lab.data.shape[0], lab.data.shape[1], -1)
            err = (tags != ld[..., 0]).astype(jnp.float32)
            err = err * sb.mask(jnp.float32)
            return SequenceBatch(data=err[..., None], lengths=sb.lengths)
        return SequenceBatch(data=tags[..., None], lengths=sb.lengths)


register_layer("crf_decoding")(_CrfDecodingImpl)


def crf_decoding_layer(input, size=None, label=None, param_attr=None,
                       name=None, param_name=None, layer_attr=None):
    """param_name (or ParamAttr(name=...)) lets decode share the CRF weight
    learned by crf_layer."""
    n = size or input.size
    cfg = {"size": n, "param_attr": param_attr}
    pa_name = param_attr.get("name") if isinstance(param_attr, dict) else None
    if param_name or pa_name:
        cfg["param_name"] = param_name or pa_name
    ins = [input] + ([label] if label is not None else [])
    return LayerOutput(name or auto_name("crf_decoding"), "crf_decoding", 1,
                       ins, cfg, is_seq=True)


def _ctc_cost(ctx, cfg, probs, label):
    sb = as_seq(probs)
    lab = as_seq(label)
    logp = jnp.log(jnp.maximum(sb.data, 1e-20)) if not cfg.get("from_logits") \
        else jax.nn.log_softmax(sb.data, axis=-1)
    ids = lab.data[..., 0] if lab.data.ndim == 3 else lab.data
    return ctc_ops.ctc_loss(logp, sb.lengths, ids.astype(jnp.int32),
                            lab.lengths, blank=cfg.get("blank", 0))


_register_cost("ctc", _ctc_cost)


def ctc_layer(input, label, size=None, blank=None, norm_by_times=False,
              name=None):
    """Reference CTCLayer: blank = size-1 by default (warpctc uses 0)."""
    n = size or input.size
    return LayerOutput(name or auto_name("ctc"), "ctc", 1, [input, label],
                       {"blank": blank if blank is not None else n - 1},
                       is_seq=False)


def warp_ctc_layer(input, label, size=None, blank=0, norm_by_times=False,
                   name=None):
    return LayerOutput(name or auto_name("warp_ctc"), "ctc", 1, [input, label],
                       {"blank": blank, "from_logits": True}, is_seq=False)


class _NceImpl:
    def infer(self, cfg, in_sizes):
        return 1

    def init(self, rng, cfg, in_sizes):
        r1, r2 = jax.random.split(rng)
        return {"w": _winit(cfg.get("param_attr"))(
            r1, (cfg["num_classes"], in_sizes[0])),
            "b": jnp.zeros((cfg["num_classes"],), dtypes.param_dtype())}

    def apply(self, ctx, cfg, params, x, label):
        xd, ld = value_data(x), value_data(label)
        ids = ld.reshape(ld.shape[0]).astype(jnp.int32)
        k = cfg.get("num_neg_samples", 10)
        neg = sampling_ops.uniform_neg_samples(
            ctx.next_rng(), (xd.shape[0], k), cfg["num_classes"])
        return sampling_ops.nce_loss(xd, params["w"], params["b"], ids, neg,
                                     cfg["num_classes"])


register_layer("nce")(_NceImpl)


def nce_layer(input, label, num_classes, num_neg_samples=10, name=None,
              param_attr=None):
    return LayerOutput(name or auto_name("nce"), "nce", 1, [input, label],
                       {"num_classes": num_classes,
                        "num_neg_samples": num_neg_samples,
                        "param_attr": param_attr}, is_seq=False)


class _HsigmoidImpl:
    def infer(self, cfg, in_sizes):
        return 1

    def init(self, rng, cfg, in_sizes):
        return {"w": _winit(cfg.get("param_attr"))(
            rng, (cfg["num_classes"] - 1, in_sizes[0])),
            "b": jnp.zeros((cfg["num_classes"] - 1,), dtypes.param_dtype())}

    def apply(self, ctx, cfg, params, x, label):
        xd, ld = value_data(x), value_data(label)
        ids = ld.reshape(ld.shape[0]).astype(jnp.int32)
        return sampling_ops.hsigmoid_loss(xd, params["w"], params["b"], ids,
                                          cfg["num_classes"])


register_layer("hsigmoid")(_HsigmoidImpl)


def hsigmoid(input, label, num_classes, name=None, param_attr=None,
             bias_attr=True):
    return LayerOutput(name or auto_name("hsigmoid"), "hsigmoid", 1,
                       [input, label],
                       {"num_classes": num_classes, "param_attr": param_attr},
                       is_seq=False)
