"""Arithmetic sugar over LayerOutput (reference trainer_config_helpers/
layer_math.py): unary math ops as identity-projection mixed layers with the
matching activation, plus +,-,* operator semantics including size-1
broadcast via repeat/scaling layers."""

from paddle_tpu.layers import api as _api
from paddle_tpu.layers.graph import LayerOutput
from paddle_tpu.utils.error import ConfigError

__all__ = ["exp", "log", "abs", "sigmoid", "tanh", "square", "relu",
           "sqrt"]


def _unary(act_name):
    def op(input, name=None):
        return _api.mixed_layer(size=input.size,
                                input=[_api.identity_projection(input)],
                                act=act_name, name=name)
    return op


exp = _unary("exponential")
log = _unary("log")
abs = _unary("abs")            # noqa: A001 - reference name
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
square = _unary("square")
relu = _unary("relu")
sqrt = _unary("sqrt")


def _add(a, b):
    if isinstance(b, (int, float)):
        return _api.slope_intercept_layer(input=a, slope=1.0, intercept=b)
    if not isinstance(b, LayerOutput):
        raise ConfigError("LayerOutput + needs a LayerOutput or a number")
    if a.size == b.size:
        return _api.mixed_layer(size=a.size,
                                input=[_api.identity_projection(a),
                                       _api.identity_projection(b)])
    if a.size != 1 and b.size != 1:
        raise ConfigError(f"cannot add sizes {a.size} and {b.size}")
    if a.size == 1:
        a, b = b, a
    b = _api.repeat_layer(b, a.size)
    return _api.mixed_layer(size=a.size,
                            input=[_api.identity_projection(a),
                                   _api.identity_projection(b)])


def _sub(a, b):
    if isinstance(b, (int, float)):
        return _api.slope_intercept_layer(input=a, slope=1.0, intercept=-b)
    return _add(a, _api.slope_intercept_layer(input=b, slope=-1.0,
                                              intercept=0.0))


def _rsub(a, b):
    return _add(_api.slope_intercept_layer(input=a, slope=-1.0,
                                           intercept=0.0), b)


def _mul(a, b):
    if isinstance(b, (int, float)):
        return _api.slope_intercept_layer(input=a, slope=b, intercept=0.0)
    if not isinstance(b, LayerOutput):
        raise ConfigError("LayerOutput * needs a LayerOutput or a number")
    if a.size == 1:
        return _api.scaling_layer(input=b, weight=a)
    if b.size == 1:
        return _api.scaling_layer(input=a, weight=b)
    raise ConfigError("'*' needs a number or a size-1 LayerOutput operand")


LayerOutput.__add__ = _add
LayerOutput.__radd__ = _add
LayerOutput.__sub__ = _sub
LayerOutput.__rsub__ = _rsub
LayerOutput.__mul__ = _mul
LayerOutput.__rmul__ = _mul
