from paddle_tpu.core import dtypes
from paddle_tpu.core.sequence import SequenceBatch

__all__ = ["dtypes", "SequenceBatch"]
