from paddle_tpu.core import dtypes
from paddle_tpu.core.sequence import (SequenceBatch, pack_sequences,
                                      pad_sequences)

__all__ = ["dtypes", "SequenceBatch", "pack_sequences", "pad_sequences"]
