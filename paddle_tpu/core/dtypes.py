"""dtype policy.

The reference compiles with ``real`` = float or double (WITH_DOUBLE,
cmake flag; SURVEY.md §2.10).  On TPU the equivalent policy is: parameters
and optimizer state in float32, matmul/conv compute in bfloat16 (MXU-native),
reductions/softmax in float32.
"""

import jax.numpy as jnp

_param_dtype = jnp.float32
# None = auto: bfloat16 when the default backend is a TPU (MXU-native),
# float32 otherwise (XLA-CPU lacks bf16 kernels for some fused dots).
_compute_dtype = None

_NAMES = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def set_policy(param_dtype="float32", compute_dtype=None):
    """compute_dtype=None restores the platform-auto policy."""
    global _param_dtype, _compute_dtype
    _param_dtype = _NAMES[str(param_dtype)] if isinstance(param_dtype, str) else param_dtype
    if compute_dtype is None:
        _compute_dtype = None
    else:
        _compute_dtype = _NAMES[str(compute_dtype)] if isinstance(compute_dtype, str) else compute_dtype


def param_dtype():
    return _param_dtype


def _auto_compute_dtype():
    import jax
    try:
        platform = jax.default_backend()
    except Exception:
        platform = "cpu"
    return jnp.bfloat16 if platform == "tpu" else jnp.float32


def compute_dtype():
    if _compute_dtype is None:
        return _auto_compute_dtype()
    return _compute_dtype


def to_compute(x):
    """Cast activations to the compute dtype (bf16 on the MXU path)."""
    if x.dtype in (jnp.float32, jnp.float64, jnp.bfloat16, jnp.float16):
        return x.astype(compute_dtype())
    return x


def to_param(x):
    return x.astype(_param_dtype)


def cast_tree(tree, dtype):
    """float32 leaves -> dtype; ids/lengths/masks (ints, bools) and other
    dtypes pass through.  The one shared implementation of the
    mixed-precision boundary cast (trainer step, eval, inference)."""
    import jax

    def cast(x):
        if hasattr(x, "dtype") and x.dtype == jnp.float32:
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)
