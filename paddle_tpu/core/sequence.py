"""Ragged sequence batches, TPU-style.

The reference represents variable-length batches padding-free as a flat
value matrix plus ``sequenceStartPositions`` / ``subSequenceStartPositions``
(reference: paddle/parameter/Argument.h:29-100).  XLA wants static shapes, so
the TPU-native design is *padded dense + lengths*, with bucketing-by-length at
the data feeder to bound padding waste (SURVEY.md §5 "Long-context").

``SequenceBatch``  — data [B, T, ...] + lengths [B]   (one sequence level)
``NestedSequenceBatch`` — data [B, S, T, ...] + outer/inner lengths
(two levels, the reference's sub-sequences).

Both are pytrees (NamedTuples), so they flow through jit/grad/scan/pjit.
"""

from typing import NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np


class SequenceBatch(NamedTuple):
    data: jnp.ndarray      # [B, T, ...] padded values (or int ids)
    lengths: jnp.ndarray   # [B] int32 true lengths

    @property
    def batch_size(self):
        return self.data.shape[0]

    @property
    def max_len(self):
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32):
        """[B, T] 1.0 where valid, 0.0 at padding."""
        t = jnp.arange(self.max_len, dtype=jnp.int32)
        return (t[None, :] < self.lengths[:, None]).astype(dtype)

    def bool_mask(self):
        t = jnp.arange(self.max_len, dtype=jnp.int32)
        return t[None, :] < self.lengths[:, None]

    def with_data(self, data):
        return SequenceBatch(data=data, lengths=self.lengths)

    @property
    def total_tokens(self):
        return jnp.sum(self.lengths)


class NestedSequenceBatch(NamedTuple):
    data: jnp.ndarray           # [B, S, T, ...]
    outer_lengths: jnp.ndarray  # [B]    number of valid sub-sequences
    inner_lengths: jnp.ndarray  # [B, S] length of each sub-sequence

    def outer_mask(self, dtype=jnp.float32):
        s = jnp.arange(self.data.shape[1], dtype=jnp.int32)
        return (s[None, :] < self.outer_lengths[:, None]).astype(dtype)

    def inner_mask(self, dtype=jnp.float32):
        t = jnp.arange(self.data.shape[2], dtype=jnp.int32)
        m = (t[None, None, :] < self.inner_lengths[:, :, None]).astype(dtype)
        return m * self.outer_mask(dtype)[:, :, None]

    def flatten_outer(self) -> SequenceBatch:
        """View each sub-sequence as an independent sequence: [B*S, T, ...]."""
        b, s = self.data.shape[:2]
        data = self.data.reshape((b * s,) + self.data.shape[2:])
        lengths = jnp.where(
            self.outer_mask(jnp.int32).reshape(-1) > 0,
            self.inner_lengths.reshape(-1), 0)
        return SequenceBatch(data=data, lengths=lengths)


def pad_sequences(seqs: Sequence[np.ndarray], max_len: Optional[int] = None,
                  pad_value=0, dtype=None) -> SequenceBatch:
    """Host-side: list of per-sequence arrays -> padded SequenceBatch."""
    lengths = np.array([len(s) for s in seqs], dtype=np.int32)
    tmax = int(max_len or (lengths.max() if len(seqs) else 1))
    first = np.asarray(seqs[0])
    trailing = first.shape[1:]
    dtype = dtype or first.dtype
    out = np.full((len(seqs), tmax) + trailing, pad_value, dtype=dtype)
    for i, s in enumerate(seqs):
        n = min(len(s), tmax)
        out[i, :n] = np.asarray(s)[:n]
    return SequenceBatch(data=jnp.asarray(out), lengths=jnp.asarray(np.minimum(lengths, tmax)))


def pad_nested_sequences(seqs, max_outer=None, max_inner=None, pad_value=0,
                         dtype=None) -> NestedSequenceBatch:
    """list (per sample) of lists (sub-seqs) of arrays -> NestedSequenceBatch."""
    outer = np.array([len(s) for s in seqs], dtype=np.int32)
    smax = int(max_outer or max(outer.max(), 1))
    inner = np.zeros((len(seqs), smax), dtype=np.int32)
    for i, s in enumerate(seqs):
        for j, sub in enumerate(s[:smax]):
            inner[i, j] = len(sub)
    tmax = int(max_inner or max(int(inner.max()), 1))
    probe = np.asarray(seqs[0][0])
    trailing = probe.shape[1:]
    dtype = dtype or probe.dtype
    out = np.full((len(seqs), smax, tmax) + trailing, pad_value, dtype=dtype)
    for i, s in enumerate(seqs):
        for j, sub in enumerate(s[:smax]):
            n = min(len(sub), tmax)
            out[i, j, :n] = np.asarray(sub)[:n]
    return NestedSequenceBatch(
        data=jnp.asarray(out),
        outer_lengths=jnp.asarray(np.minimum(outer, smax)),
        inner_lengths=jnp.asarray(np.minimum(inner, tmax)))


def bucket_boundaries(lengths, num_buckets=4, multiple=8):
    """Pick padded-length buckets (quantiles rounded up to `multiple`).

    Replaces the reference's batch-shrinking dynamic shapes
    (RecurrentGradientMachine.cpp:642) with a small static-shape set so XLA
    compiles one program per bucket.
    """
    lengths = np.asarray(lengths)
    qs = np.quantile(lengths, np.linspace(0, 1, num_buckets + 1)[1:])
    bounds = sorted({int(-(-q // multiple) * multiple) for q in qs})
    return bounds


def bucket_for(length: int, bounds) -> int:
    for b in bounds:
        if length <= b:
            return b
    return bounds[-1]


def pack_sequences(seqs, max_len, pad_value=0):
    """Greedy first-fit packing of ragged sequences into [B, max_len] rows
    — the ragged-attention half of the reference's no-padding claim
    (Argument.sequenceStartPositions, parameter/Argument.h:84-93): several
    short sequences share one row, and segment labels keep attention
    block-diagonal per original sequence
    (ops.attention.chunked_attention(q_segment_ids=...) / segment_mask).

    Returns (data [B, max_len], segment_ids [B, max_len] — 1-based per
    row, 0 = padding — and positions [B, max_len], the within-segment
    token index for positional embeddings).  Sequences longer than
    max_len are truncated.
    """
    rows = []          # list of (free, [seq, ...])
    for s in seqs:
        s = np.asarray(s)[:max_len]
        placed = False
        for row in rows:
            if row[0] >= len(s):
                row[1].append(s)
                row[0] -= len(s)
                placed = True
                break
        if not placed:
            rows.append([max_len - len(s), [s]])
    b = len(rows)
    data = np.full((b, max_len), pad_value,
                   rows[0][1][0].dtype if rows else np.int32)
    seg = np.zeros((b, max_len), np.int32)
    pos = np.zeros((b, max_len), np.int32)
    for i, (_, members) in enumerate(rows):
        t = 0
        for j, s in enumerate(members):
            data[i, t:t + len(s)] = s
            seg[i, t:t + len(s)] = j + 1
            pos[i, t:t + len(s)] = np.arange(len(s))
            t += len(s)
    return data, seg, pos
