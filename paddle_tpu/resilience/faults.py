"""Deterministic fault injection: named fault points in the hot paths.

Crash-only-software practice says the recovery path must be exercised as
routinely as the happy path — but ad-hoc monkeypatching (what the serving
tests did until now) cannot reach a subprocess, cannot be replayed
bit-for-bit, and cannot fire inside a production-shaped binary.  This
module compiles a small registry of NAMED fault points into the hot
paths as near-zero-cost hooks:

    from paddle_tpu.resilience import faults
    ...
    faults.hit("serving.decode_step")     # one global check when idle

With no plan installed (the default, and the only state production ever
runs in) ``hit()`` is a function call plus one ``is None`` test — it
cannot retrace, allocate, or touch a lock.  The hooks live strictly in
HOST code (never inside a jit-traced body), so an installed plan changes
no XLA program either: ``bench.py --analytic-diff`` stays clean by
construction.

A ``FaultPlan`` is a set of per-point rules, each fully deterministic:

* ``at=N``      fire on the Nth hit of that point (1-based), once
* ``every=K``   fire on every Kth hit
* ``p=0.25``    fire with probability p from a ``random.Random(seed)``
                stream private to the point — the same seed replays the
                same fire pattern bit-for-bit
* ``times=T``   cap total fires of the rule (default: 1 for ``at``,
                unbounded otherwise)
* ``action=error`` (default) raises ``InjectedFault`` (a
  ``TransientError`` — the retry helpers treat it as retryable);
  ``action=hang`` sleeps ``hang_s`` seconds then RETURNS — the hook's
  caller proceeds normally, simulating a hung/slow device step for the
  watchdog deadline to catch.

Spec strings (the ``resilience_fault_spec`` flag and the chaos CLIs):

    point:key=val,key=val[;point:key=val...]
    e.g.  serving.decode_step:at=4
          trainer.step:every=3,times=2
          batcher.submit:p=0.5,seed=7,action=error

Install with ``install_spec(spec)`` / ``install(plan)``; ``clear()``
removes it.  ``fired_counts()`` exposes per-point fire totals — the
serving ``/metrics`` page renders them as
``fault_injections_total{point=...}``.
"""

import random
import threading
import time

from paddle_tpu.utils.error import ConfigError

# The registered fault points.  Each name is compiled into exactly one
# host-side hot path; installing a rule for an unknown name is a
# ConfigError (a typo'd chaos plan must fail loudly, not silently never
# fire).
FAULT_POINTS = (
    "serving.engine.execute",      # InferenceEngine._infer_bucketed
    "serving.prefill",             # DecodeEngine.prefill
    "serving.decode_step",         # DecodeEngine.step (host wrapper)
    "batcher.submit",              # Batcher.submit / GenerationBatcher.submit
    "data.prefetch.h2d",           # ShardedPrefetcher producer placement
    "trainer.step",                # SGD.train hot loop, before dispatch
    "trainer.checkpoint.write",    # checkpoint.save_checkpoint mid-write
    "router.dispatch",             # Router._dispatch, the router->replica
    #                                network boundary (serving/router.py)
    "fleet.spawn",                 # ReplicaSupervisor._spawn, before the
    #                                subprocess exists (serving/fleet.py):
    #                                a replica that fails/hangs AT spawn,
    #                                before it could ever answer /readyz
    "autoscaler.scale",            # Autoscaler actuation (serving/
    #                                autoscaler.py): a scale decision
    #                                whose execution fails — the control
    #                                loop must retry with backoff, never
    #                                count an unready replica as capacity
)


class TransientError(RuntimeError):
    """Base for failures a bounded retry may legitimately absorb."""


class InjectedFault(TransientError):
    """Raised by a firing fault point.  Carries the point name and the
    1-based hit index it fired on, so a chaos test can assert exactly
    which occurrence tripped."""

    def __init__(self, point, hit_index):
        super().__init__(f"injected fault at {point} (hit #{hit_index})")
        self.point = point
        self.hit_index = hit_index


class _Rule:
    __slots__ = ("point", "at", "every", "p", "seed", "times", "action",
                 "hang_s", "hits", "fired", "_rng")

    def __init__(self, point, at=None, every=None, p=None, seed=0,
                 times=None, action="error", hang_s=0.5):
        if point not in FAULT_POINTS:
            raise ConfigError(
                f"unknown fault point {point!r}; registered points: "
                f"{', '.join(FAULT_POINTS)}")
        if sum(x is not None for x in (at, every, p)) != 1:
            raise ConfigError(
                f"fault rule for {point}: exactly one of at=/every=/p= "
                "must be given")
        if action not in ("error", "hang"):
            raise ConfigError(f"fault rule for {point}: action={action!r} "
                              "(supported: error, hang)")
        self.point = point
        self.at = int(at) if at is not None else None
        self.every = int(every) if every is not None else None
        self.p = float(p) if p is not None else None
        self.seed = int(seed)
        # at= is a one-shot by default; every=/p= fire unbounded
        self.times = (int(times) if times is not None
                      else (1 if at is not None else None))
        self.action = action
        self.hang_s = float(hang_s)
        self.hits = 0
        self.fired = 0
        self._rng = random.Random(self.seed)

    def should_fire(self):
        """Advance the rule's deterministic schedule by one hit."""
        self.hits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.at is not None:
            return self.hits == self.at
        if self.every is not None:
            return self.hits % self.every == 0
        return self._rng.random() < self.p


class FaultPlan:
    """A seeded, replayable set of fault rules, one per point at most."""

    def __init__(self, rules=()):
        self._rules = {}
        self._lock = threading.Lock()
        for r in rules:
            if r.point in self._rules:
                raise ConfigError(f"duplicate fault rule for {r.point}")
            self._rules[r.point] = r

    @classmethod
    def from_spec(cls, spec):
        """Parse ``point:k=v,k=v[;point:...]`` into a plan."""
        rules = []
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            if ":" not in part:
                raise ConfigError(
                    f"fault spec entry {part!r}: expected point:key=val,...")
            point, _, kvs = part.partition(":")
            kw = {}
            for kv in filter(None, (s.strip() for s in kvs.split(","))):
                if "=" not in kv:
                    raise ConfigError(
                        f"fault spec for {point}: bad key=val {kv!r}")
                k, _, v = kv.partition("=")
                k = k.strip()
                if k in ("at", "every", "seed", "times"):
                    kw[k] = int(v)
                elif k in ("p", "hang_s"):
                    kw[k] = float(v)
                elif k == "action":
                    kw[k] = v.strip()
                else:
                    raise ConfigError(
                        f"fault spec for {point}: unknown key {k!r}")
            rules.append(_Rule(point.strip(), **kw))
        return cls(rules)

    def hit(self, point):
        rule = self._rules.get(point)
        if rule is None:
            return
        with self._lock:
            fire = rule.should_fire()
            if fire:
                rule.fired += 1
                idx = rule.hits
                action, hang_s = rule.action, rule.hang_s
        if not fire:
            return
        if action == "hang":
            time.sleep(hang_s)
            return
        raise InjectedFault(point, idx)

    def snapshot(self):
        """{point: {"hits": n, "fired": n}} for every rule in the plan."""
        with self._lock:
            return {p: {"hits": r.hits, "fired": r.fired}
                    for p, r in self._rules.items()}


# the globally installed plan; None (the default) makes hit() a no-op
_plan = None


def install(plan):
    """Install a FaultPlan process-wide; returns it (chainable)."""
    global _plan
    _plan = plan
    return plan


def install_spec(spec):
    """Parse + install a spec string; empty/None clears instead."""
    if not spec:
        clear()
        return None
    return install(FaultPlan.from_spec(spec))


def clear():
    global _plan
    _plan = None


def active_plan():
    return _plan


def hit(point):
    """The hook compiled into the hot paths.  Near-zero cost when no
    plan is installed (one global read + ``is None``).  The local
    snapshot makes a concurrent clear() benign — the racing hit sees
    either the old plan or none, never a half-torn-down one."""
    plan = _plan
    if plan is None:
        return
    plan.hit(point)


def fired_counts():
    """{point: fires} of the active plan ({} when none) — the /metrics
    ``fault_injections_total`` source."""
    plan = _plan
    if plan is None:
        return {}
    return {p: s["fired"] for p, s in plan.snapshot().items()}
