"""Resilience layer: deterministic fault injection + supervised recovery.

    faults.py      named fault points compiled into the hot paths
                   (serving execute/prefill/decode step, batcher submit,
                   prefetch H2D, trainer step, checkpoint write), driven
                   by seeded FaultPlan schedules — every chaos run
                   replays bit-for-bit; strict no-op when disabled
    supervisor.py  per-step watchdog (deadline -> rebuild from the AOT
                   cache), decode-slot re-prefill recovery (greedy
                   streams bit-identical across a mid-stream rebuild),
                   circuit breaker (fast 503 + Retry-After), bounded
                   retry with backoff+jitter for transient submits
    __main__.py    chaos smoke CLI (healthy_window.sh phase 9): serving
                   under an injected decode fault + kill-9 trainer
                   resume, one JSON line

Docs: docs/serving.md §6.  Flags: resilience_* in utils/flags.py.
"""

from paddle_tpu.resilience.faults import (FAULT_POINTS, FaultPlan,
                                          InjectedFault, TransientError)
from paddle_tpu.resilience.supervisor import (BreakerOpenError,
                                              CircuitBreaker, Supervisor,
                                              WatchdogTimeout,
                                              retry_transient)

__all__ = [
    "FAULT_POINTS", "FaultPlan", "InjectedFault", "TransientError",
    "BreakerOpenError", "CircuitBreaker", "Supervisor",
    "WatchdogTimeout", "retry_transient",
]
