"""Supervised recovery: watchdog, circuit breaker, bounded retry,
decode-slot re-prefill.

The serving stack (PR 4/5) isolates failures — a bad step fails its
batch and the loop keeps serving — but isolation alone drops the
victims' work on the floor and keeps admitting traffic into a possibly
sick engine.  This module adds the supervision layer:

* ``Supervisor.run_step(engine)`` — a per-step WATCHDOG: when
  ``step_deadline_s`` is set, the slab decode step runs on a sacrificial
  thread and a step that neither returns nor raises within the deadline
  trips ``WatchdogTimeout``.  The hung thread cannot be killed (Python),
  but the engine's epoch guard (``DecodeEngine.reset`` bumps an epoch;
  ``step`` refuses to commit across a reset) guarantees a late finisher
  can never poison the rebuilt slab.

* ``Supervisor.reprefill(engine, items)`` — SLOT RECOVERY: interrupted
  requests are reconstructed by re-prefilling the longest ladder-covered
  prefix of ``prompt + tokens-so-far`` (same-bucket victims as ONE
  engine batch) and teacher-force-replaying the remainder through the
  shared slab step — byte-for-byte the state each slot held before the
  failure, so a recovered greedy stream stays bit-identical to
  ``lm_generate`` even across a mid-stream engine rebuild.  Recovery
  runs entirely over warm executables: zero new traces beyond the
  rebuild (pinned by tests/test_resilience.py).

* ``CircuitBreaker`` — ``threshold`` CONSECUTIVE step failures open the
  breaker: new submits shed fast (HTTP 503 + ``Retry-After``) instead of
  queueing into a sick engine.  After ``cooldown_s`` the breaker goes
  half-open and admits ONE probe request; the next step success closes
  it, another failure re-opens and restarts the cooldown.

* ``retry_transient(fn)`` — bounded retry with exponential backoff plus
  seeded jitter for TRANSIENT submit failures (``faults.TransientError``
  and subclasses).  Callers must only wrap idempotent calls — the
  instrumented submit fault point fires BEFORE any queue mutation, so a
  failed attempt provably admitted nothing (asserted by test).

``Supervisor`` is engine-agnostic: it holds policy (deadline, breaker,
recovery budget); the ``GenerationBatcher`` owns the slot bookkeeping
and the metrics recording.
"""

import queue
import random
import threading
import time

from paddle_tpu.obs import trace as obstrace
from paddle_tpu.resilience.faults import TransientError
from paddle_tpu.utils.logging import logger


class WatchdogTimeout(RuntimeError):
    """The supervised device step neither returned nor raised within
    the deadline — treated like a step failure (recover + rebuild)."""


class BreakerOpenError(RuntimeError):
    """The circuit breaker is shedding load (HTTP 503); retry after
    ``retry_after_s``."""

    def __init__(self, msg, retry_after_s=1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """closed -> (threshold consecutive failures) -> open -> (cooldown)
    -> half_open -> one probe -> closed | open.  Thread-safe; all state
    is host-side counters, so an always-closed breaker costs nothing."""

    def __init__(self, threshold=5, cooldown_s=5.0, clock=None):
        if int(threshold) < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        # injectable monotonic clock (default real): cooldown tests run
        # on a simulated clock instead of sleeping the cooldown out
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._failures = 0
        self._state = "closed"
        self._opened_at = 0.0
        self._probe_out = False
        self._probe_at = 0.0
        self.opened_total = 0       # times the breaker tripped open

    @property
    def state(self):
        with self._lock:
            return self._state_locked()

    def _state_locked(self):
        if self._state == "open" and not self._probe_out \
                and self._clock() - self._opened_at >= self.cooldown_s:
            self._state = "half_open"
        return self._state

    def record_failure(self):
        """One step failure; returns True when this one OPENED the
        breaker (the transition, for logging/metrics)."""
        with self._lock:
            self._failures += 1
            self._probe_out = False
            if self._state_locked() == "half_open":
                # the probe failed: straight back to open, fresh
                # cooldown.  This IS a fresh open transition — counting
                # (and reporting) it keeps a flapping
                # open/half-open/open node visible in breaker_open_total
                # instead of looking like one long-ago blip.
                self._state = "open"
                self._opened_at = self._clock()
                self.opened_total += 1
                return True
            if self._state == "closed" and self._failures >= self.threshold:
                self._state = "open"
                self._opened_at = self._clock()
                self.opened_total += 1
                return True
            return False

    def record_success(self):
        """A healthy step.  Closes from half-open (the probe — or any
        post-cooldown success — proved the engine recovered).  From OPEN
        it only resets the failure streak: in-flight recovered work
        stepping fine must not bypass the cooldown on a flapping engine
        (the documented open -> cooldown -> half-open -> close path)."""
        with self._lock:
            self._failures = 0
            st = self._state_locked()
            if st == "half_open":
                self._probe_out = False
                self._state = "closed"

    def release_probe(self):
        """Hand an unused half-open probe slot back (the probing request
        failed synchronously before it could ever reach a step)."""
        with self._lock:
            self._probe_out = False

    def seconds_until_probe(self):
        """Read-only: how long until the next probe could be admitted
        (0 when closed) — the /readyz Retry-After source.  Never
        consumes the probe slot."""
        with self._lock:
            if self._state_locked() == "closed":
                return 0.0
            return max(0.05, self.cooldown_s
                       - (self._clock() - self._opened_at))

    def admit(self):
        """Admission check: (True, None) to admit; (False, retry_after_s)
        to shed.  In half-open state exactly ONE caller gets the probe
        slot; the rest shed until the probe resolves."""
        with self._lock:
            st = self._state_locked()
            if st == "closed":
                return True, None
            now = self._clock()
            # half-open: one probe per cooldown window.  A probe that
            # never resolves through a step (e.g. it finished at
            # prefill) must not wedge admissions forever — after a
            # further cooldown a fresh probe is handed out.
            if st == "half_open" and (
                    not self._probe_out
                    or now - self._probe_at >= self.cooldown_s):
                self._probe_out = True
                self._probe_at = now
                return True, None
            remain = max(0.0, self.cooldown_s - (now - self._opened_at))
            return False, max(remain, 0.05)


def retry_transient(fn, budget=3, base_delay_s=0.01, max_delay_s=0.5,
                    seed=None, on_retry=None):
    """Call ``fn()``; on ``TransientError`` retry up to ``budget`` times
    with exponential backoff (``base_delay_s * 2**k``, capped) plus
    full jitter from a seeded stream (deterministic replays under test;
    de-synchronized thundering herds in production).  Non-transient
    exceptions propagate immediately.  ``on_retry(attempt, exc)`` is the
    metrics hook.  IDEMPOTENCE: only wrap calls whose failed attempts
    left no state behind (the batcher submit fault points fire before
    any queue mutation)."""
    rng = random.Random(seed)
    attempt = 0
    while True:
        try:
            return fn()
        except TransientError as e:
            attempt += 1
            if attempt > budget:
                raise
            delay = min(base_delay_s * (2 ** (attempt - 1)), max_delay_s)
            delay *= rng.random()
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(delay)


class Supervisor:
    """Per-engine supervision policy for a ``GenerationBatcher``.

    step_deadline_s: watchdog deadline for one slab step (None = off,
    the step runs inline with zero overhead).  breaker_threshold /
    breaker_cooldown_s: circuit-breaker tuning (docs/serving.md §6).
    max_request_recoveries: how many times ONE request may be re-
    prefilled before it is failed (bounds the work a permanently
    poisoned step can burn).
    """

    def __init__(self, step_deadline_s=None, breaker_threshold=5,
                 breaker_cooldown_s=5.0, max_request_recoveries=5):
        self.step_deadline_s = (float(step_deadline_s)
                                if step_deadline_s else None)
        self.breaker = CircuitBreaker(breaker_threshold, breaker_cooldown_s)
        self.max_request_recoveries = int(max_request_recoveries)
        self.watchdog_trips = 0
        # persistent supervised-step worker (lazy): one long-lived thread
        # serves every deadline-guarded step — the per-token hot path
        # pays a queue handoff, not a thread create/teardown.  A worker
        # wedged by a hung step is abandoned (told to exit once it
        # unwedges) and replaced.
        self._worker = None

    # ------------------------------------------------------------ watchdog

    def _step_worker(self):
        if self._worker is None or not self._worker[0].is_alive():
            inq, outq = queue.Queue(), queue.Queue()

            def loop():
                while True:
                    eng = inq.get()
                    if eng is None:     # abandoned after a timeout: exit
                        return
                    try:
                        outq.put(("r", eng.step()))
                    except BaseException as e:   # noqa: BLE001 — crosses
                        outq.put(("e", e))       # threads

            t = threading.Thread(target=loop, daemon=True,
                                 name="supervised-decode-step")
            t.start()
            self._worker = (t, inq, outq)
        return self._worker

    def run_step(self, engine):
        """One supervised slab step.  Without a deadline this is a plain
        call; with one, the step runs on the persistent worker thread and
        a deadline miss raises ``WatchdogTimeout`` (the wedged worker is
        abandoned and replaced on the next step).  A late finisher is
        harmless: the engine's epoch guard discards its commit after the
        recovery path resets the slab."""
        if self.step_deadline_s is None:
            return engine.step()
        _t, inq, outq = self._step_worker()
        inq.put(engine)
        try:
            kind, val = outq.get(timeout=self.step_deadline_s)
        except queue.Empty:
            self.watchdog_trips += 1
            inq.put(None)       # exit once the hung step unwedges
            self._worker = None
            obstrace.instant("supervisor.watchdog_trip",
                             deadline_s=self.step_deadline_s)
            logger.warning("watchdog: decode step exceeded %.3fs deadline; "
                           "abandoning it and rebuilding",
                           self.step_deadline_s)
            raise WatchdogTimeout(
                f"decode step exceeded the {self.step_deadline_s:.3f}s "
                "deadline") from None
        if kind == "e":
            raise val
        return val

    # ------------------------------------------------------------ recovery

    def reprefill(self, engine, items):
        """Rebuild interrupted requests' slots on a freshly reset
        engine.  ``items`` is a list of ``(prompt, tokens)``; for each,
        the lost cache held K/V for ``full[0:R]`` with the last delivered
        token armed at position R, where ``full = prompt + tokens`` and
        ``R = len(full) - 1``.  Rebuild in two warm-executable legs:

        1. re-PREFILL the longest prefix the ladder covers (all of
           ``full[:R]`` when R fits; the ladder-top prefix otherwise) —
           same-bucket victims prefill as ONE engine batch, so a full
           slab recovers in a handful of prefill executions, not one
           per slot — and seat each in a fresh slot;
        2. teacher-force-REPLAY the remainder through the shared slab
           step: each replay step feeds the RECORDED stream and its
           re-derived emission is swallowed by the batcher (the
           ``replay_feed`` returned here), never re-delivered.

        Greedy decode is deterministic, so after the replay drains each
        slot is byte-for-byte its pre-failure state and the stream
        continues bit-identically — pinned by tests/test_resilience.py.
        Returns a list aligned with ``items``: ``(slot, replay_feed)``
        per recovered request, or the exception that failed it (one
        victim's failure never blocks the others).

        The mechanics live in ``DecodeEngine.seat_prefilled`` — the ONE
        seat-prefix helper this path shares with the batcher's
        continuation-``replay`` leg, paged prefix-cache admission, and
        pool-pressure re-seating (serving/kv_pool.py).  On a CHUNKED
        engine (prefill_chunk > 0) recovery rides chunks: leg 1's
        ladder re-prefill disappears and the whole context returns as
        the feed, drained up to K lanes per step through the one
        unified executable (docs/serving.md "Chunked prefill") — K×
        fewer recovery steps than per-token teacher-forcing, still
        bit-identical, still zero new traces."""
        import numpy as np
        with obstrace.span("supervisor.reprefill", root=False,
                           n=len(items)):
            return engine.seat_prefilled(
                [np.concatenate([np.asarray(prompt, np.int32),
                                 np.asarray(tokens, np.int32)])
                 for prompt, tokens in items])
