"""Chaos smoke CLI — healthy_window.sh phase 9.

    python -m paddle_tpu.resilience --smoke

Two chaos legs at smoke scale, ONE JSON line, nonzero rc on any failed
check (the same contract as the serving smokes):

1. SERVING under an injected decode-step fault: a tiny generation server
   (HTTP, supervised) first serves every prompt cleanly (greedy decode
   is deterministic — those token lists are the oracle), then re-serves
   them concurrently with a deterministic ``serving.decode_step`` fault
   installed.  The fault must fire, every stream must finish
   BIT-IDENTICAL to its clean run (slot re-prefill recovery), and
   /metrics must report the fault + recovery counters.

2. TRAINING kill -9 + resume: a subprocess victim
   (``--train-victim DIR``, deterministic tiny trainer) SIGKILLs itself
   mid-pass; the parent then resumes with ``train(resume=True)`` and
   asserts the final parameters are bit-identical to an uninterrupted
   run — with any partial ``.tmp-`` checkpoint dir left by the kill
   never picked up.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

from paddle_tpu.resilience import Supervisor, faults
from paddle_tpu.utils.logging import logger


# ------------------------------------------------------------ serving leg


def _chaos_serving(errs):
    import urllib.request
    import jax
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import ServingMetrics, make_server
    from paddle_tpu.serving.decode_engine import (DecodeEngine,
                                                  GenerationBatcher)

    params = transformer.init(jax.random.PRNGKey(0), src_vocab=256,
                              trg_vocab=1, d_model=32, num_heads=2,
                              dff=64, enc_layers=2, dec_layers=0,
                              max_len=48)
    engine = DecodeEngine(params, num_heads=2, num_slots=4, max_len=48,
                          prefill_buckets=(8, 16), name="chaos_lm")
    sup = Supervisor(step_deadline_s=2.0, breaker_threshold=5)
    gen = GenerationBatcher(engine, default_max_tokens=8, supervisor=sup)
    httpd = make_server(None, port=0, gen_batcher=gen)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, 3 + 2 * i).tolist() for i in range(6)]

    def post(body):
        req = urllib.request.Request(
            f"{base}/v1/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    out = {"serving_ok": False, "bit_identical": False,
           "faults_fired": 0, "reprefills": 0}
    try:
        # clean pass: greedy determinism makes these the oracle
        ref = [post({"prompt": p, "max_tokens": 8})["tokens"]
               for p in prompts]
        # chaos pass: deterministic mid-flight decode-step fault
        engine.metrics = gen.metrics = ServingMetrics()
        tr0 = engine.step_trace_count
        faults.install_spec("serving.decode_step:at=5")
        results = [None] * len(prompts)

        def hit(i):
            try:
                time.sleep(0.004 * i)   # staggered: admissions mid-decode
                results[i] = post({"prompt": prompts[i], "max_tokens": 8})
            except Exception as e:      # noqa: BLE001
                errs.append(f"chaos generate: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        fired = faults.fired_counts().get("serving.decode_step", 0)
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            mtext = r.read().decode()
        faults.clear()
        snap = engine.metrics.snapshot()
        out.update(
            serving_ok=all(r is not None for r in results),
            bit_identical=all(r is not None and r["tokens"] == ref[i]
                              for i, r in enumerate(results)),
            faults_fired=fired,
            reprefills=snap["slot_reprefills_total"],
            no_retrace=engine.step_trace_count == tr0,
            metrics_sane='fault_injections_total{'
                         'point="serving.decode_step"}' in mtext
                         and snap["slot_reprefills_total"] >= 1)
    except Exception as e:      # noqa: BLE001 — a leg failure must become
        errs.append(f"serving leg: {type(e).__name__}: {e}")
    finally:
        faults.clear()
        httpd.shutdown()
        gen.close()
    return out


# ------------------------------------------------------------ training leg


def _build_trainer():
    """Deterministic tiny classifier trainer — shared by the victim
    subprocess and the parent's resume/uninterrupted runs, so all three
    see identical topology, seed, and per-pass batches."""
    import paddle_tpu.optim as optim
    from paddle_tpu.data.provider import dense_vector, integer_value
    from paddle_tpu.layers import api as L
    from paddle_tpu.layers.graph import reset_names
    from paddle_tpu.trainer.trainer import SGD
    reset_names()
    x = L.data_layer("chaos_x", size=4)
    lab = L.data_layer("chaos_lab", size=1)
    h = L.fc_layer(input=x, size=8, act="tanh")
    y = L.fc_layer(input=h, size=2, act="softmax")
    cost = L.classification_cost(y, lab)
    trainer = SGD(cost=cost,
                  update_equation=optim.Momentum(learning_rate=0.1,
                                                 momentum=0.9),
                  seed=7)
    feeding = {"chaos_x": dense_vector(4), "chaos_lab": integer_value(2)}

    def reader():
        rng = np.random.RandomState(0)      # fresh per pass: every pass
        xs = rng.randn(24, 4).astype(np.float32)   # sees the same batches
        ys = (xs[:, 0] > 0).astype(np.int64)
        for i in range(0, 24, 8):
            yield [(xs[j], int(ys[j])) for j in range(i, i + 8)]

    return trainer, feeding, reader


def _victim_main(save_dir):
    """Train 3 passes, checkpointing each — and SIGKILL ourselves mid
    pass 2, after the pass-1 checkpoint landed (kill -9: no atexit, no
    cleanup, exactly the crash the atomic writer must survive)."""
    from paddle_tpu.trainer import events
    trainer, feeding, reader = _build_trainer()

    def handler(e):
        if isinstance(e, events.EndIteration) and e.pass_id == 2 \
                and e.batch_id == 1:
            from paddle_tpu.trainer import checkpoint
            checkpoint.wait_pending()       # pass-1's async save is real
            os.kill(os.getpid(), signal.SIGKILL)

    trainer.train(reader, num_passes=3, feeding=feeding,
                  event_handler=handler, log_period=0, buffered_batches=0,
                  save_dir=save_dir)
    return 1        # unreachable when the kill lands — rc 1 flags it


def _chaos_train(errs):
    import jax
    out = {"victim_killed": False, "resume_bit_identical": False}
    tmp = tempfile.mkdtemp(prefix="chaos_resume_")
    save_dir = os.path.join(tmp, "ckpt")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.resilience",
             "--train-victim", save_dir],
            capture_output=True, text=True, timeout=600)
        out["victim_killed"] = proc.returncode in (-signal.SIGKILL, 137)
        if not out["victim_killed"]:
            errs.append(f"victim rc={proc.returncode}: "
                        f"{proc.stderr[-500:]}")
        complete = sorted(d for d in os.listdir(save_dir)
                          if d.startswith("pass-"))
        out["complete_passes"] = complete

        # resume: latest complete pass -> bit-identical final params
        trainer, feeding, reader = _build_trainer()
        trainer.train(reader, num_passes=3, feeding=feeding, log_period=0,
                      buffered_batches=0, save_dir=save_dir, resume=True)
        resumed = jax.device_get(trainer.parameters)

        clean, feeding, reader = _build_trainer()
        clean.train(reader, num_passes=3, feeding=feeding, log_period=0,
                    buffered_batches=0)
        ref = jax.device_get(clean.parameters)
        leaves_r = jax.tree_util.tree_leaves(resumed)
        leaves_c = jax.tree_util.tree_leaves(ref)
        out["resume_bit_identical"] = (
            len(leaves_r) == len(leaves_c)
            and all(np.array_equal(a, b)
                    for a, b in zip(leaves_r, leaves_c)))
    except Exception as e:      # noqa: BLE001
        errs.append(f"training leg: {type(e).__name__}: {e}")
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# ------------------------------------------------------------------- CLI


def _smoke():
    errs = []
    serving = _chaos_serving(errs)
    training = _chaos_train(errs)
    checks = [
        bool(serving.get("serving_ok")),
        bool(serving.get("bit_identical")) and serving.get("faults_fired",
                                                           0) >= 1
        and bool(serving.get("no_retrace"))
        and bool(serving.get("metrics_sane")),
        bool(training.get("victim_killed")),
        bool(training.get("resume_bit_identical")),
    ]
    out = {
        "metric": "chaos smoke (fault injection + supervised recovery)",
        "value": sum(checks), "unit": f"checks_ok/{len(checks)}",
        "vs_baseline": None,
    }
    out.update(serving)
    out.update(training)
    if errs:
        out["errors"] = errs[:5]
    print(json.dumps(out), flush=True)
    return 0 if all(checks) else 2


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.resilience",
        description="chaos smoke: fault injection + supervised recovery")
    ap.add_argument("--smoke", action="store_true",
                    help="run both chaos legs, print one JSON line, exit")
    ap.add_argument("--train-victim", metavar="SAVE_DIR",
                    help="(internal) train + SIGKILL self mid-pass")
    args = ap.parse_args(argv)
    if args.train_victim:
        return _victim_main(args.train_victim)
    if args.smoke:
        return _smoke()
    ap.error("pass --smoke (or the internal --train-victim)")


if __name__ == "__main__":
    logger.setLevel("WARNING")
    sys.exit(main())
