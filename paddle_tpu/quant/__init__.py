"""Quantized serving (docs/serving.md "Quantized serving").

Every bench family in the committed analytic snapshot is MEMORY-bound
(BENCH_ANALYTIC_r06.json names bytes, not FLOPs, as the #1 bottleneck
for all 22 families), so after PR 10/13 fused the decode hot path the
next independent attack on the memory wall is shrinking the bytes
themselves.  Two composable halves:

* ``quant.weights`` — per-channel symmetric int8 quantization of the LM
  trunk's matmul weights: the params pytree stores int8 data + small
  f32 scale sidecars, and every model entry point dequantizes at the
  matmul boundary (``(int8_w * scale) @ x``, fused into the MXU operand
  read by XLA on TPU) — no fp32 weight copy is ever fed to or carried
  by the jitted step.

* ``quant.kv`` — int8 KV cache with per-(position, head) scales: the
  decode cache (slab rows or paged blocks) stores int8 K/V plus an
  ``[..., Hkv]`` f32 scale sidecar, scatter-writes quantize on the way
  in, and the fused decode kernels (ops/pallas/decode_attention.py)
  DMA the quantized blocks HBM -> VMEM and widen IN REGISTERS inside
  the online-softmax accumulator.  On the paged layout the ~4x smaller
  blocks double the effective slot count at a fixed pool-byte budget
  (DecodeEngine(kv_dtype="int8") auto-doubles ``kv_num_blocks``).
"""

from paddle_tpu.quant.weights import (dequant_tree, is_quantized_leaf,
                                      is_quantized_tree, maybe_dequant,
                                      param_bytes, quantize_lm,
                                      weight_shape)
from paddle_tpu.quant.kv import (GREEDY_PREFIX_MIN, GREEDY_PREFIX_MIN_FULL,
                                 KV_DTYPES, LOGIT_ERR_BUDGET,
                                 dequantize_heads, greedy_prefix_len,
                                 kv_bytes_per_position, quantize_heads)

__all__ = [
    "quantize_lm", "maybe_dequant", "dequant_tree", "is_quantized_leaf",
    "is_quantized_tree", "weight_shape", "param_bytes",
    "quantize_heads", "dequantize_heads", "kv_bytes_per_position",
    "greedy_prefix_len", "KV_DTYPES", "GREEDY_PREFIX_MIN",
    "GREEDY_PREFIX_MIN_FULL", "LOGIT_ERR_BUDGET",
]
