"""Int8 KV cache: per-(position, head) symmetric scales.

Scale granularity — per written POSITION per KV HEAD — chosen over the
alternatives deliberately (docs/serving.md "Quantized serving"):

* per-tensor / per-layer static scales need a calibration pass and go
  stale as traffic shifts; a wrong static scale clips silently.
  Per-position scales are computed FROM the value being written, so no
  calibration exists to go stale and the quantize math is a pure
  deterministic function of the written K/V — exactly what the engine's
  replay machinery (recovery re-prefill, CoW re-seating, continuation
  teacher-forcing) needs to land a rebuilt slot bit-identically.
* per-block scales (one scale per paged block) would couple a
  position's quantization to its neighbors: a later write into the
  block would have to re-quantize earlier positions (or accept stale
  scales), breaking the scatter-write-once contract.
* per-head (not per-position-only) keeps outlier heads from crushing
  quiet heads' resolution, and the ``[.., Hkv]`` sidecar slots directly
  into the fused kernels' per-KV-head group loop — the in-register
  dequant is one broadcast multiply per group panel.

Cost: the sidecar is ``4 / head_dim`` of the int8 data (2 f32 scales
per 2·head_dim int8 values), so k+v at head_dim 16 stream at ~0.31x
the f32 bytes — and a paged block shrinks enough that DOUBLING the
block count stays inside the f32 byte budget for head_dim >= 4
(serving/kv_pool.slab_equivalent_blocks).

Identity-scale exactness: with scale 1 and integer values in
[-127, 127], quantize->dequantize is BIT-exact (round half-to-even,
clip, convert, multiply by 1.0) — tests/test_quant.py pins it, so the
quantize/dequant math itself is proven bias-free.
"""

import numpy as np

import jax.numpy as jnp

KV_DTYPES = ("float32", "int8")

# Quality budget (committed; tests/test_quant.py + the --smoke-quant
# phase assert against these): an int8-KV greedy stream must match its
# fp32 twin for at least GREEDY_PREFIX_MIN tokens on the seeded test
# trunks (measured: the full 32-token streams match — 2x headroom), an
# int8-KV + int8-WEIGHT stream for at least GREEDY_PREFIX_MIN_FULL
# (random-init test trunks babble with near-tied logits, so the full-
# quant argmax flips earlier than any trained trunk's would; measured
# 6-12), and the max |logit error| of a quantized prefill vs the fp32
# twin must stay under LOGIT_ERR_BUDGET (measured 0.004-0.012 — ~5x
# headroom).
GREEDY_PREFIX_MIN = 16
GREEDY_PREFIX_MIN_FULL = 4
LOGIT_ERR_BUDGET = 0.06


def _split_heads(x, hkv):
    dkv = x.shape[-1]
    if hkv < 1 or dkv % hkv:
        raise ValueError(f"Dkv={dkv} not divisible by Hkv={hkv}")
    return x.reshape(x.shape[:-1] + (hkv, dkv // hkv))


def quantize_heads(x, hkv):
    """Quantize ``x`` [..., Dkv] f32 per (leading index, KV head):
    returns ``(q int8 [..., Dkv], s f32 [..., Hkv])`` with
    ``s = amax_over_head / 127`` (0 for an all-zero head — dequant
    rebuilds exact zeros).  The math the reference step, the fused
    kernels' producers, and the quantized prefill all share."""
    xh = _split_heads(x, hkv)
    amax = jnp.max(jnp.abs(xh), axis=-1)
    s = amax / 127.0
    safe = jnp.where(s > 0, s, 1.0)[..., None]
    q = jnp.clip(jnp.round(xh / safe), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), s.astype(jnp.float32)


def dequantize_heads(q, s):
    """Widen ``q`` [..., Dkv] int8 by its per-head scales ``s``
    [..., Hkv] -> f32 [..., Dkv] — the reference (XLA) read path; the
    fused kernels do the same multiply in registers per group panel."""
    hkv = s.shape[-1]
    qh = _split_heads(q.astype(jnp.float32), hkv)
    return (qh * s[..., None]).reshape(q.shape)


def greedy_prefix_len(a, b):
    """Length of the common leading run of two token streams — THE
    comparison the greedy-prefix quality budget (GREEDY_PREFIX_MIN*)
    is defined over, shared by tests/test_quant.py, the serving_quant
    bench, and the --smoke-quant phase so all three measure the same
    thing."""
    n = 0
    if a is None or b is None:
        return 0
    for x, y in zip(a, b):
        if int(x) != int(y):
            break
        n += 1
    return n


def logit_err(ref_logits, logits, lens=None):
    """Per-stream max |logit error| of a quantized forward against its
    fp32 twin — THE comparison the LOGIT_ERR_BUDGET is defined over,
    shared by tests/test_quant.py, the serving_quant* benches and the
    ``--smoke-quant*`` phases so every consumer measures the same
    thing.  ``ref_logits``/``logits``: [..., T, vocab]; ``lens``
    (optional, [...]): valid positions per stream — padded tail
    positions are masked out of the max.  Returns the per-stream max
    as an ndarray (one value per leading index)."""
    err = np.abs(np.asarray(ref_logits, np.float32)
                 - np.asarray(logits, np.float32)).max(axis=-1)
    if lens is not None:
        t = err.shape[-1]
        valid = np.arange(t) < np.asarray(lens)[..., None]
        err = np.where(valid, err, 0.0)
    return err.max(axis=-1)


def kv_bytes_per_position(dkv, hkv, kv_dtype):
    """HBM bytes one cached position costs (K and V, sidecar included)
    — the KV term of the serving_quant predicted-bytes model and the
    pool-sizing math in serving/kv_pool.py."""
    if kv_dtype == "int8":
        return 2 * dkv * 1 + 2 * hkv * 4
    return 2 * dkv * 4
