"""Per-channel symmetric int8 weight quantization for the LM trunk.

The serving decode step reads every trunk weight once per step and the
analytic layer says bytes set the step time, so int8 storage quarters
the weight stream.  The scheme is ``export.quantize_params``'s (weight-
only, symmetric, per-OUT-channel scales over the last axis) specialized
for the decode hot path:

* only 2-D float32 matmul weights with >= ``min_size`` elements
  quantize (attention projections, FFN, the tied embedding); biases,
  layer norms and the positional table stay f32 — their bytes are
  noise and their precision is not;
* a quantized leaf is ``{"q": int8 [.., dout], "s": f32 [1, dout]}``
  (same marker-free shape either way: ``is_quantized_leaf`` keys on the
  dict structure), so the params pytree fed to the jitted step holds
  int8 data + small scale sidecars and NO fp32 weight copy is ever
  resident between steps;
* dequantization happens at the matmul boundary inside the step
  (``maybe_dequant`` at each model entry point): XLA sees
  ``convert(int8) * scale`` feeding each consuming matmul, which the
  TPU backend fuses into the MXU operand read — the int8 bytes stream
  from HBM and widen in registers.  (The CPU backend materializes the
  widened operand as a transient fusion output; its cost model
  therefore cannot show the win — perf/analytic's serving_quant row
  predicts it compositionally instead, the PR-10 methodology.)

Identity-scale exactness (pinned by tests/test_quant.py): with scale 1
and integer values in [-127, 127] the round-trip ``dequant(quantize)``
is BIT-exact — ``jnp.round`` half-to-even, clip, convert — so the
quantize/dequant math itself carries no hidden bias.
"""

import numpy as np

import jax
import jax.numpy as jnp

# leaf formats recognized everywhere below: this module's {"q","s"} and
# export.quantize_params' {"__int8__","__scale__"} (same per-out-channel
# symmetric scheme — an artifact-exported int8 tree feeds the serving
# engine directly)
_LEAF_KEYS = (("q", "s"), ("__int8__", "__scale__"))


def _leaf_keys(leaf):
    if isinstance(leaf, dict):
        for qk, sk in _LEAF_KEYS:
            if qk in leaf and sk in leaf \
                    and getattr(leaf[qk], "dtype", None) == jnp.int8:
                return qk, sk
    return None


def is_quantized_leaf(leaf):
    """True for a quantized-weight leaf — this module's ``{"q", "s"}``
    or ``export.quantize_params``' ``{"__int8__", "__scale__"}``."""
    return _leaf_keys(leaf) is not None


def quantize_leaf(w, axis=None):
    """Symmetric per-channel int8: scales over every axis but the last
    (``axis=None``) -> ``{"q", "s"}``.  A zero channel quantizes to
    zeros with scale 0 (dequant rebuilds exact zeros)."""
    w = jnp.asarray(w)
    axes = axis if axis is not None else tuple(range(w.ndim - 1))
    amax = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    s = amax / 127.0
    safe = jnp.where(s > 0, s, 1.0)
    q = jnp.clip(jnp.round(w / safe), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(jnp.float32)}


def dequantize_leaf(leaf):
    qk, sk = _leaf_keys(leaf)
    return leaf[qk].astype(jnp.float32) * leaf[sk]


def quantize_lm(params, min_size=1024):
    """Quantize a ``models/transformer`` decoder-only trunk: every 2-D
    f32 weight with >= ``min_size`` elements becomes a ``{"q", "s"}``
    pair; everything else (biases, norms, ``pos``) passes through.
    Returns the quantized pytree — feed it anywhere the f32 tree went
    (``DecodeEngine``, ``lm_prefill``, ``lm_logits``): the model entry
    points dequantize at the matmul boundary via ``maybe_dequant``.

    The learned positional table (``params["pos"]``) stays f32: it is
    added to activations, not consumed by a matmul, so quantizing it
    would buy no fused dequant — and it is one row-gather per step."""

    def q(x):
        if getattr(x, "dtype", None) != jnp.float32 or x.ndim != 2 \
                or int(np.prod(x.shape)) < min_size:
            return x
        return quantize_leaf(x)

    pos = params.get("pos") if isinstance(params, dict) else None
    if pos is not None:
        params = dict(params, pos=None)
    out = jax.tree_util.tree_map(q, params)
    if pos is not None:
        out["pos"] = pos
    return out


def quantize_tree(params, min_size=1024):
    """``quantize_lm`` for a GENERIC params pytree (the trainer's int8
    weight-streaming mode): every 2-D f32 leaf with >= ``min_size``
    elements becomes a ``{"q", "s"}`` pair, everything else passes
    through.  No ``pos`` special case — a topology params dict has no
    reserved keys.  Deterministic (round-half-to-even, clip), so
    requantizing the same masters always rebuilds the same tree —
    kill-9 resume bit-identity rides on it."""

    def q(x):
        if getattr(x, "dtype", None) != jnp.float32 or x.ndim != 2 \
                or int(np.prod(x.shape)) < min_size:
            return x
        return quantize_leaf(x)

    return jax.tree_util.tree_map(q, params)


# Committed training-quality budget for the int8 weight-streaming step
# (tests/test_trainer_quant.py, bench trainer_int8, --smoke-quant-prefill):
# max per-step |loss_int8 - loss_f32| / max(|loss_f32|, 1) over a short
# run on the shared fixtures.  Measured headroom: the smallnet fixture
# tracks within ~1e-3 relative; the budget is deliberately loose enough
# to stay meaningful across seeds without masking a broken dequant
# boundary (which shows up as O(1) divergence).
TRAIN_LOSS_BUDGET = 0.05


def dequant_tree(params):
    """Rebuild the float tree: quantized leaves widen at their consuming
    matmul (XLA fuses the convert+scale into the operand read on TPU);
    float leaves pass through untouched."""
    return jax.tree_util.tree_map(
        lambda l: dequantize_leaf(l) if is_quantized_leaf(l) else l,
        params, is_leaf=is_quantized_leaf)


def is_quantized_tree(params):
    """True when any leaf of ``params`` is a quantized weight."""
    found = [False]

    def visit(l):
        if is_quantized_leaf(l):
            found[0] = True
        return l

    jax.tree_util.tree_map(visit, params, is_leaf=is_quantized_leaf)
    return found[0]


def maybe_dequant(params):
    """THE model-entry-point hook (``models/transformer`` lm_* paths):
    dequantize a quantized tree, pass a float tree through untouched —
    one ``is_quantized_tree`` walk, zero cost on the f32 path."""
    if is_quantized_tree(params):
        return dequant_tree(params)
    return params


def weight_shape(leaf):
    """Logical (pre-quantization) shape of a weight leaf — quantized or
    not — for the host-side config reads (vocab/d_model/Dkv)."""
    keys = _leaf_keys(leaf)
    if keys is not None:
        return tuple(leaf[keys[0]].shape)
    return tuple(np.shape(leaf))


def quantized_weight_shapes(params):
    """Shapes of every quantized weight in the tree — the analytic
    gate's target list (perf/analytic.assert_weights_quantized checks
    the compiled step feeds each as int8 and none as f32)."""
    shapes = []

    def visit(l):
        if is_quantized_leaf(l):
            shapes.append(weight_shape(l))
        return l

    jax.tree_util.tree_map(visit, params, is_leaf=is_quantized_leaf)
    return shapes


def float_leaf_shapes(params):
    """Shapes of the tree's NON-quantized array leaves — the float
    parameters the compiled step legitimately takes.  The analytic
    weights gate's allow-list: a float entry param whose shape happens
    to collide with a quantized weight's (e.g. the positional table
    [max_len, d] vs an FFN weight when max_len == dff) must not read
    as a widened weight copy."""
    shapes = []

    def visit(l):
        if not is_quantized_leaf(l) and hasattr(l, "dtype") \
                and np.issubdtype(l.dtype, np.floating):
            shapes.append(tuple(np.shape(l)))
        return l

    jax.tree_util.tree_map(visit, params, is_leaf=is_quantized_leaf)
    return shapes


def param_bytes(params):
    """Total resident bytes of a params pytree as STORED (int8 data +
    scale sidecars for a quantized tree) — the weight-stream term of the
    serving_quant predicted-bytes model."""
    total = [0]

    def visit(l):
        keys = _leaf_keys(l)
        if keys is not None:
            total[0] += l[keys[0]].size * 1 + l[keys[1]].size * 4
        elif hasattr(l, "dtype"):
            total[0] += int(np.prod(np.shape(l))) * np.dtype(l.dtype).itemsize
        return l

    jax.tree_util.tree_map(visit, params, is_leaf=is_quantized_leaf)
    return total[0]
