"""Evaluator implementations (see package docstring; reference
gserver/evaluators/Evaluator.cpp + ChunkEvaluator.cpp + CTCErrorEvaluator.cpp).

Contract:
  ev.init() -> state (pytree of arrays; additive across batches/devices)
  ev.update(state, **batch outputs) -> state  (pure, jittable)
  ev.result(state) -> float | dict
"""

import numpy as np
import jax
import jax.numpy as jnp


class Evaluator:
    name = "evaluator"

    def init(self):
        raise NotImplementedError

    def update(self, state, **kw):
        raise NotImplementedError

    def result(self, state):
        raise NotImplementedError


class ClassificationError(Evaluator):
    """Reference ClassificationErrorEvaluator: fraction of rows whose argmax
    != label (with optional per-row weight)."""
    name = "classification_error"

    def init(self):
        return {"wrong": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, state, pred=None, label=None, weight=None, mask=None):
        ids = jnp.argmax(pred, axis=-1)
        lab = label.reshape(ids.shape)
        err = (ids != lab).astype(jnp.float32)
        w = jnp.ones_like(err) if weight is None else weight.reshape(err.shape)
        if mask is not None:
            w = w * mask.reshape(err.shape)
        return {"wrong": state["wrong"] + jnp.sum(err * w),
                "total": state["total"] + jnp.sum(w)}

    def result(self, state):
        t = float(state["total"])
        return float(state["wrong"]) / t if t else 0.0


class SumEvaluator(Evaluator):
    name = "sum"

    def init(self):
        return {"sum": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, state, value=None, weight=None, **_):
        # sequence-valued inputs (e.g. crf_decoding error indicators): sum
        # valid positions only
        if hasattr(value, "lengths"):
            d = value.data.reshape(value.data.shape[0],
                                   value.data.shape[1], -1)
            d = d * value.mask(d.dtype)[..., None]
            value = d.reshape(d.shape[0], -1).sum(-1)
        w = jnp.ones(value.shape[0]) if weight is None else weight.reshape(-1)
        return {"sum": state["sum"] + jnp.sum(value.reshape(value.shape[0], -1).sum(-1) * w),
                "total": state["total"] + jnp.sum(w)}

    def result(self, state):
        return float(state["sum"])


class ColumnSum(Evaluator):
    name = "column_sum"

    def __init__(self, size):
        self.size = size

    def init(self):
        return {"sum": jnp.zeros((self.size,)), "total": jnp.zeros(())}

    def update(self, state, value=None, weight=None, **_):
        w = jnp.ones(value.shape[0]) if weight is None else weight.reshape(-1)
        return {"sum": state["sum"] + jnp.sum(value * w[:, None], axis=0),
                "total": state["total"] + jnp.sum(w)}

    def result(self, state):
        return np.asarray(state["sum"])


class Auc(Evaluator):
    """Reference AucEvaluator: histogram-bucketed ROC AUC (the reference
    uses a fixed-resolution discretization too)."""
    name = "auc"

    def __init__(self, buckets=1024):
        self.buckets = buckets

    def init(self):
        return {"pos": jnp.zeros((self.buckets,)),
                "neg": jnp.zeros((self.buckets,))}

    def update(self, state, pred=None, label=None, weight=None, **_):
        # pred: [B, 2] softmax or [B, 1]/[B] positive-class prob
        p = pred[:, 1] if (pred.ndim == 2 and pred.shape[1] == 2) else pred.reshape(-1)
        lab = label.reshape(-1).astype(jnp.float32)
        w = jnp.ones_like(p) if weight is None else weight.reshape(-1)
        idx = jnp.clip((p * self.buckets).astype(jnp.int32), 0, self.buckets - 1)
        pos = state["pos"].at[idx].add(lab * w)
        neg = state["neg"].at[idx].add((1 - lab) * w)
        return {"pos": pos, "neg": neg}

    def result(self, state):
        pos = np.asarray(state["pos"])[::-1]  # descending threshold
        neg = np.asarray(state["neg"])[::-1]
        tp = np.cumsum(pos)
        fp = np.cumsum(neg)
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        # trapezoid over ROC points
        tpr = np.concatenate([[0.0], tp / tot_pos])
        fpr = np.concatenate([[0.0], fp / tot_neg])
        return float(np.trapezoid(tpr, fpr))


class PrecisionRecall(Evaluator):
    """Reference PrecisionRecallEvaluator: per-class TP/FP/FN -> macro F1
    (or binary stats when positive_label given)."""
    name = "precision_recall"

    def __init__(self, num_classes, positive_label=None):
        self.num_classes = num_classes
        self.positive_label = positive_label

    def init(self):
        n = self.num_classes
        return {"tp": jnp.zeros((n,)), "fp": jnp.zeros((n,)),
                "fn": jnp.zeros((n,))}

    def update(self, state, pred=None, label=None, **_):
        ids = jnp.argmax(pred, axis=-1)
        lab = label.reshape(ids.shape).astype(jnp.int32)
        n = self.num_classes
        oh_pred = jax.nn.one_hot(ids, n)
        oh_lab = jax.nn.one_hot(lab, n)
        tp = jnp.sum(oh_pred * oh_lab, axis=0)
        fp = jnp.sum(oh_pred * (1 - oh_lab), axis=0)
        fn = jnp.sum((1 - oh_pred) * oh_lab, axis=0)
        return {"tp": state["tp"] + tp, "fp": state["fp"] + fp,
                "fn": state["fn"] + fn}

    def result(self, state):
        tp, fp, fn = (np.asarray(state[k]) for k in ("tp", "fp", "fn"))
        if self.positive_label is not None:
            i = self.positive_label
            prec = tp[i] / max(tp[i] + fp[i], 1e-9)
            rec = tp[i] / max(tp[i] + fn[i], 1e-9)
            f1 = 2 * prec * rec / max(prec + rec, 1e-9)
            return {"precision": float(prec), "recall": float(rec), "f1": float(f1)}
        prec = tp / np.maximum(tp + fp, 1e-9)
        rec = tp / np.maximum(tp + fn, 1e-9)
        f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-9)
        return {"macro_f1": float(f1.mean()),
                "precision": float(prec.mean()), "recall": float(rec.mean())}


class PnPair(Evaluator):
    """Reference PnpairEvaluator: counts correctly-ordered (pos before neg)
    prediction pairs within query groups.  Host-side accumulation (pairwise
    over variable-size groups is not worth a kernel)."""
    name = "pnpair"

    def init(self):
        return {"records": []}

    def update(self, state, pred=None, label=None, query_id=None, **_):
        p = np.asarray(pred).reshape(-1)
        l = np.asarray(label).reshape(-1)
        q = np.asarray(query_id).reshape(-1) if query_id is not None \
            else np.zeros_like(l)
        state["records"].append((p, l, q))
        return state

    def result(self, state):
        if not state["records"]:
            return 0.0
        p = np.concatenate([r[0] for r in state["records"]])
        l = np.concatenate([r[1] for r in state["records"]])
        q = np.concatenate([r[2] for r in state["records"]])
        pos_cnt = neg_cnt = spe = 0.0
        for qid in np.unique(q):
            m = q == qid
            pi, li = p[m], l[m]
            diff_l = li[:, None] - li[None, :]
            diff_p = pi[:, None] - pi[None, :]
            pairs = diff_l > 0
            pos_cnt += np.sum(pairs & (diff_p > 0))
            neg_cnt += np.sum(pairs & (diff_p < 0))
            spe += np.sum(pairs & (diff_p == 0))
        denom = neg_cnt + spe / 2.0
        return float(pos_cnt / max(denom, 1e-9))


class RankAuc(Auc):
    name = "rankauc"


class ChunkEvaluator(Evaluator):
    """Reference ChunkEvaluator.cpp: chunk (NER span) F1 over plain/IOB/IOE/
    IOBES tagging — the exact isChunkBegin/isChunkEnd state machine
    (ChunkEvaluator.cpp:186-245).  Host-side decode of spans.

    Label encoding (reference :33-35): tag = label % numTagTypes,
    chunk type = label // numTagTypes; label == numChunkTypes*numTagTypes
    is the 'other' (O) tag."""
    name = "chunk"

    _SCHEMES = {
        #            nTag  B   I   E   S
        "IOB":       (2,   0,  1, -1, -1),
        "IOE":       (2,  -1,  0,  1, -1),
        "IOBES":     (4,   0,  1,  2,  3),
        "plain":     (1,  -1, -1, -1, -1),
    }

    def __init__(self, scheme="IOB", num_chunk_types=None,
                 excluded_chunk_types=()):
        if scheme not in self._SCHEMES:
            raise ValueError(f"unknown chunk scheme {scheme!r} "
                             f"(have {sorted(self._SCHEMES)})")
        self.scheme = scheme
        self.num_chunk_types = num_chunk_types
        self.excluded = set(excluded_chunk_types)

    def init(self):
        return {"correct": 0, "pred": 0, "gold": 0}

    def _segments(self, tags, num_chunk_types):
        n_tag, t_b, t_i, t_e, t_s = self._SCHEMES[self.scheme]
        other = num_chunk_types

        def is_end(ptag, ptype, tag, typ):
            if ptype == other:
                return False
            if typ == other or typ != ptype:
                return True
            if ptag in (t_e, t_s):
                return True
            if ptag in (t_b, t_i):
                return tag in (t_b, t_s)
            return False

        def is_begin(ptag, ptype, tag, typ):
            if ptype == other:
                return typ != other
            if typ == other:
                return False
            if typ != ptype or tag == t_b or tag == t_s:
                return True
            if tag in (t_i, t_e):
                return ptag in (t_e, t_s)
            return False

        segments = []
        start, in_chunk = 0, False
        tag, typ = -1, other
        for i, lab in enumerate(tags):
            if lab < 0:        # negative padding without lengths=: stop
                tags = tags[:i]
                break
            ptag, ptype = tag, typ
            tag, typ = lab % n_tag, lab // n_tag
            if in_chunk and is_end(ptag, ptype, tag, typ):
                segments.append((start, i - 1, ptype))
                in_chunk = False
            if is_begin(ptag, ptype, tag, typ):
                start, in_chunk = i, True
        if in_chunk:
            segments.append((start, len(tags) - 1, typ))
        return {s for s in segments if s[2] not in self.excluded}

    def _num_types(self, *arrays):
        if self.num_chunk_types is None:
            # the reference REQUIRES num_chunk_types (ChunkEvaluator.cpp:108
            # CHECK); inferring it from data is ambiguous because the same
            # max label can be a typed tag or the O tag
            raise ValueError("ChunkEvaluator needs num_chunk_types= "
                             "(reference chunk evaluator config field)")
        return self.num_chunk_types

    def update(self, state, pred=None, label=None, lengths=None, **_):
        p = np.asarray(pred)
        l = np.asarray(label)
        lens = np.asarray(lengths) if lengths is not None else \
            np.full(p.shape[0], p.shape[1])
        nct = self._num_types(p, l)
        for i in range(p.shape[0]):
            ps = self._segments(list(p[i, :lens[i]]), nct)
            gs = self._segments(list(l[i, :lens[i]]), nct)
            state["correct"] += len(ps & gs)
            state["pred"] += len(ps)
            state["gold"] += len(gs)
        return state

    def result(self, state):
        prec = state["correct"] / max(state["pred"], 1e-9)
        rec = state["correct"] / max(state["gold"], 1e-9)
        return {"precision": prec, "recall": rec,
                "f1": 2 * prec * rec / max(prec + rec, 1e-9)}


class CTCError(Evaluator):
    """Reference CTCErrorEvaluator: edit distance between greedy-decoded
    output and label, normalized by label length."""
    name = "ctc_error"

    def init(self):
        return {"dist": 0.0, "len": 0.0}

    @staticmethod
    def _edit_distance(a, b):
        dp = np.arange(len(b) + 1, dtype=np.int32)
        for i in range(1, len(a) + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, len(b) + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (a[i - 1] != b[j - 1]))
        return int(dp[-1])

    def update(self, state, decoded=None, decoded_lengths=None, label=None,
               label_lengths=None, **_):
        d = np.asarray(decoded)
        dl = np.asarray(decoded_lengths)
        l = np.asarray(label)
        ll = np.asarray(label_lengths)
        for i in range(d.shape[0]):
            state["dist"] += self._edit_distance(
                list(d[i, :dl[i]]), list(l[i, :ll[i]]))
            state["len"] += float(ll[i])
        return state

    def result(self, state):
        return state["dist"] / max(state["len"], 1e-9)


_REGISTRY = {
    "classification_error": ClassificationError,
    "sum": SumEvaluator,
    "column_sum": ColumnSum,
    "auc": Auc,
    "rankauc": RankAuc,
    "precision_recall": PrecisionRecall,
    "pnpair": PnPair,
    "chunk": ChunkEvaluator,
    "ctc_error": CTCError,
}


def get(name, **kw):
    try:
        return _REGISTRY[name](**kw)
    except KeyError:
        raise KeyError(f"unknown evaluator {name!r}; have {sorted(_REGISTRY)}")
