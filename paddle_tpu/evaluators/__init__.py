"""Evaluator suite.

Reference: gserver/evaluators/Evaluator.{h,cpp}:172-1346 REGISTER_EVALUATOR
zoo — classification_error, sum, column_sum, rankauc, precision_recall,
pnpair, auc, chunk (NER F1), ctc_error, printers.

Design: each evaluator is (init, update, result) with jittable additive
statistics where possible (the reference's distributed merge of evaluator
counters becomes a psum over the same statistics).
"""

from paddle_tpu.evaluators.evaluators import (
    Evaluator, ClassificationError, Auc, PrecisionRecall, PnPair, RankAuc,
    SumEvaluator, ColumnSum, ChunkEvaluator, CTCError, get,
)
from paddle_tpu.evaluators.dsl import *          # noqa: F401,F403
from paddle_tpu.evaluators import dsl as _dsl

__all__ = [
    "Evaluator", "ClassificationError", "Auc", "PrecisionRecall", "PnPair",
    "RankAuc", "SumEvaluator", "ColumnSum", "ChunkEvaluator", "CTCError",
    "get",
] + _dsl.__all__
