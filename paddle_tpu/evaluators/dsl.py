"""Evaluator DSL ctors (the reference's trainer_config_helpers/evaluators.py
surface: @evaluator-decorated config functions wiring REGISTER_EVALUATOR'd
C++ evaluators, Evaluator.cpp:172-1346).

Here each ctor returns an EvaluatorSpec binding an evaluator implementation
(evaluators.evaluators.*, jittable additive state) to graph layers; the
trainer fetches the bound layers every batch, updates the state, and logs
`result()` every log_period and at pass end — the reference's print flow.

Printer evaluators print host-side (the reference's printer evaluators are
likewise host prints in Evaluator.cpp)."""

import numpy as np

from paddle_tpu.evaluators import evaluators as ev_impls

__all__ = [
    "EvaluatorSpec", "evaluator_base",
    "classification_error_evaluator", "auc_evaluator", "sum_evaluator",
    "column_sum_evaluator", "precision_recall_evaluator", "pnpair_evaluator",
    "chunk_evaluator", "ctc_error_evaluator",
    "value_printer_evaluator", "gradient_printer_evaluator",
    "maxid_printer_evaluator", "maxframe_printer_evaluator",
    "seqtext_printer_evaluator", "classification_error_printer_evaluator",
]


class EvaluatorSpec:
    """Binds an evaluator to layers.  kind='metric' accumulates via the
    impl's (init/update/result); kind='printer' prints the fetched value."""

    def __init__(self, name, impl, input, label=None, weight=None,
                 kind="metric", printer=None, value_mode=False, adapter=None,
                 extra_inputs=None, **update_kw):
        self.name = name
        self.impl = impl
        self.input = input
        self.label = label
        self.weight = weight
        self.kind = kind
        self.printer = printer
        self.value_mode = value_mode   # impl.update takes value= not pred=
        # adapter(pred, label, weight, extra) -> kwargs for impl.update, for
        # impls whose signature differs from pred/label/weight (chunk, ctc)
        self.adapter = adapter
        # {update_kw_name: LayerOutput} resolved by the trainer each batch
        # (e.g. pnpair's query_id)
        self.extra_inputs = dict(extra_inputs or {})
        self.update_kw = update_kw
        self.state = impl.init() if impl is not None else None

    def reset(self):
        if self.impl is not None:
            self.state = self.impl.init()

    def update(self, pred, label=None, weight=None, extra=None):
        if self.kind == "printer":
            self.printer(self.name, pred, label)
            return
        kw = dict(self.update_kw)
        kw.update(extra or {})
        if self.adapter is not None:
            kw.update(self.adapter(pred, label, weight, extra or {}))
            self.state = self.impl.update(self.state, **kw)
        elif self.value_mode:
            self.state = self.impl.update(self.state, value=pred,
                                          weight=weight, **kw)
        else:
            self.state = self.impl.update(self.state, pred=pred, label=label,
                                          weight=weight, **kw)

    def result(self):
        return self.impl.result(self.state) if self.impl is not None else None


def evaluator_base(input, type, label=None, weight=None, name=None, **kw):
    """Generic ctor (reference evaluator_base): type names an implementation
    registered in evaluators.get."""
    impl = ev_impls.get(type, **kw)
    return EvaluatorSpec(name or type, impl, input, label=label, weight=weight)


def classification_error_evaluator(input, label, weight=None, name=None,
                                   **_):
    return EvaluatorSpec(name or "classification_error",
                         ev_impls.ClassificationError(), input, label, weight)


def auc_evaluator(input, label, weight=None, name=None, **_):
    return EvaluatorSpec(name or "auc", ev_impls.Auc(), input, label, weight)


def sum_evaluator(input, weight=None, name=None, **_):
    return EvaluatorSpec(name or "sum", ev_impls.SumEvaluator(), input,
                         weight=weight, value_mode=True)


def column_sum_evaluator(input, weight=None, name=None, **_):
    return EvaluatorSpec(name or "column_sum",
                         ev_impls.ColumnSum(size=input.size), input,
                         weight=weight, value_mode=True)


def precision_recall_evaluator(input, label, positive_label=None,
                               weight=None, name=None, **_):
    return EvaluatorSpec(
        name or "precision_recall",
        ev_impls.PrecisionRecall(num_classes=input.size,
                                 positive_label=positive_label),
        input, label, weight)


def pnpair_evaluator(input, label, query_id, weight=None, name=None, **_):
    """query_id: a data layer of per-sample query ids; fetched from the feed
    every batch and forwarded to PnPair.update."""
    return EvaluatorSpec(name or "pnpair", ev_impls.PnPair(), input, label,
                         weight, extra_inputs={"query_id": query_id})


def _seq_parts(v):
    """(data, lengths) from a SequenceBatch or a plain array."""
    if hasattr(v, "data") and hasattr(v, "lengths"):
        return np.asarray(v.data), np.asarray(v.lengths)
    arr = np.asarray(v)
    return arr, None


def chunk_evaluator(input, label, chunk_scheme="IOB", num_chunk_types=None,
                    excluded_chunk_types=(), name=None, **_):
    def adapt(pred, label, weight, extra):
        p, plens = _seq_parts(pred)
        l, _ = _seq_parts(label)
        if p.ndim == 3:                     # tag probs -> tag ids
            p = np.argmax(p, -1)
        return {"pred": p.reshape(p.shape[0], -1),
                "label": l.reshape(l.shape[0], -1),
                "lengths": plens}
    return EvaluatorSpec(
        name or "chunk",
        ev_impls.ChunkEvaluator(scheme=chunk_scheme,
                                num_chunk_types=num_chunk_types,
                                excluded_chunk_types=excluded_chunk_types),
        input, label, adapter=adapt)


def ctc_error_evaluator(input, label, blank=0, name=None, **_):
    """input: per-frame class probs/logits [B, T, C]; greedy CTC decode
    (argmax, collapse repeats, drop blanks — reference CTCErrorEvaluator)
    then edit distance against the label sequences."""
    def adapt(pred, label, weight, extra):
        p, plens = _seq_parts(pred)
        frames = np.argmax(p, -1)           # [B, T]
        if plens is None:
            plens = np.full(frames.shape[0], frames.shape[1])
        dec = np.full_like(frames, -1)
        dlen = np.zeros(frames.shape[0], np.int32)
        for i in range(frames.shape[0]):
            prev = -1
            k = 0
            for t in range(int(plens[i])):
                f = int(frames[i, t])
                if f != prev and f != blank:
                    dec[i, k] = f
                    k += 1
                prev = f
            dlen[i] = k
        l, llens = _seq_parts(label)
        l = l.reshape(l.shape[0], -1)
        if llens is None:
            llens = np.full(l.shape[0], l.shape[1])
        return {"decoded": dec, "decoded_lengths": dlen,
                "label": l, "label_lengths": llens}
    return EvaluatorSpec(name or "ctc_error", ev_impls.CTCError(), input,
                         label, adapter=adapt)


# --------------------------------------------------------------- printers

def _print_value(name, pred, label):
    print(f"[{name}] value:\n{np.asarray(pred)}")


def _print_maxid(name, pred, label):
    print(f"[{name}] argmax ids: {np.argmax(np.asarray(pred), -1)}")


def _print_maxframe(name, pred, label):
    arr = np.asarray(pred)
    print(f"[{name}] max frame idx: {np.argmax(arr.reshape(arr.shape[0], -1), -1)}")


def value_printer_evaluator(input, name=None, **_):
    return EvaluatorSpec(name or "value_printer", None, input,
                         kind="printer", printer=_print_value)


def gradient_printer_evaluator(input, name=None, **_):
    """Prints the layer value (gradients are not materialized per layer in
    the functional IR; the reference printed both)."""
    return EvaluatorSpec(name or "gradient_printer", None, input,
                         kind="printer", printer=_print_value)


def maxid_printer_evaluator(input, name=None, **_):
    return EvaluatorSpec(name or "maxid_printer", None, input,
                         kind="printer", printer=_print_maxid)


def maxframe_printer_evaluator(input, name=None, **_):
    return EvaluatorSpec(name or "maxframe_printer", None, input,
                         kind="printer", printer=_print_maxframe)


def seqtext_printer_evaluator(input, result_file=None, id_input=None,
                              dict_file=None, name=None, **_):
    """Reference seqtext_printer_evaluator: write generated token ids (or
    dict-mapped words) one sequence per line."""
    vocab = None
    if dict_file:
        with open(dict_file) as f:
            vocab = [line.rstrip("\n") for line in f]

    def printer(nm, pred, label):
        arr = np.asarray(pred.data if hasattr(pred, "data") else pred)
        lens = np.asarray(pred.lengths) if hasattr(pred, "lengths") else None
        lines = []
        for i, row in enumerate(arr.reshape(arr.shape[0], -1)):
            ids = row[:int(lens[i])] if lens is not None else row
            toks = ([vocab[t] if 0 <= t < len(vocab) else str(t)
                     for t in ids] if vocab else [str(t) for t in ids])
            lines.append(" ".join(toks))
        text = "\n".join(lines)
        if result_file:
            with open(result_file, "a") as f:
                f.write(text + "\n")
        else:
            print(f"[{nm}]\n{text}")

    return EvaluatorSpec(name or "seqtext_printer", None, input,
                         kind="printer", printer=printer)


def classification_error_printer_evaluator(input, label, name=None, **_):
    def printer(nm, pred, lab):
        ids = np.argmax(np.asarray(pred), -1)
        err = (ids != np.asarray(lab).reshape(ids.shape)).astype(np.float32)
        print(f"[{nm}] per-sample error: {err}")
    return EvaluatorSpec(name or "classification_error_printer", None, input,
                         label=label, kind="printer", printer=printer)
