"""jit-purity pass: nothing host-side is reachable from a jitted step.

Walks the call graph from every registered root (analysis/roots.py)
and flags any reachable call whose resolved dotted target lands in a
FORBIDDEN namespace — the host-side subsystems the repo's discipline
keeps OUT of jit bodies (PR 6 "never inside a jit body", PR 9's
host-side-only tracer) — plus any FLAGS read that is not on the
documented trace-time allow-list (roots.py TRACE_TIME_FLAGS).

Nested defs and lambdas of a reachable function are themselves
reachable (scan bodies, tree_map lambdas — they run under the trace).
Resolution is optimistic (callgraph.py): an unresolvable call is
skipped, so the pass can under-report but never invents an edge; the
reverse-gate fixtures prove it catches every rule it claims to.

Rules (docs/analysis.md):
  jit-forbidden-call   reachable call into obs/, resilience/faults,
                       serving/metrics, utils/logging, time, random,
                       threading
  jit-flags-read       reachable FLAGS read off the trace-time
                       allow-list (or a dynamic getattr(FLAGS, expr))
"""

from paddle_tpu.analysis import callgraph
from paddle_tpu.analysis.baseline import Finding
from paddle_tpu.analysis.roots import TRACE_TIME_FLAGS

import ast

# namespace -> why it may never run under a trace
FORBIDDEN = [
    ("paddle_tpu.obs", "host-side tracing (obs/) is host-only by design"),
    ("paddle_tpu.resilience.faults",
     "fault hooks are compiled into HOST hot paths only (PR 6)"),
    ("paddle_tpu.serving.metrics",
     "metrics mutate host state under a lock — a trace would bake one "
     "observation in and sync the device"),
    ("paddle_tpu.utils.logging", "logging is host I/O"),
    ("time", "wall clocks read at trace time are frozen into the trace"),
    ("random", "stdlib RNG is untraceable host state (use jax.random)"),
    ("threading", "thread primitives cannot exist inside a jit body"),
]


def _forbidden(dotted):
    if dotted is None:
        return None
    for ns, why in FORBIDDEN:
        if dotted == ns or dotted.startswith(ns + "."):
            return ns, why
    return None


def _uid(fi):
    """Visit identity: qualname ALONE would merge the qualname-sharing
    variants (e.g. the four DecodeEngine ``_step_fn`` layout closures)
    and silently skip all but the first — the line disambiguates."""
    return (fi.module.name, fi.qualname, fi.line)


def _chain(parents, func):
    k = _uid(func)
    seen_keys = []
    while k is not None:
        seen_keys.append(f"{k[0]}:{k[1]}")
        k = parents.get(k)
    return tuple(reversed(seen_keys))


def run(project, roots):
    """-> [Finding].  ``roots`` is an iterable of roots.Root (or any
    object with ``.ref``); every qualname sharer of a ref is walked."""
    findings = []
    seen = {}          # _uid -> FuncInfo (visited)
    parents = {}       # _uid -> parent _uid (shortest via BFS)
    queue = []
    missing = []
    for r in roots:
        infos = project.function(r.ref)
        if not infos:
            missing.append(r.ref)
        for fi in infos:
            if _uid(fi) not in seen:
                seen[_uid(fi)] = fi
                parents[_uid(fi)] = None
                queue.append(fi)
    for ref in missing:
        findings.append(Finding(
            check="jit", rule="jit-root-missing",
            key=f"jit:jit-root-missing:{ref}",
            path="paddle_tpu/analysis/roots.py", line=1, func=ref,
            message=f"registered jit root {ref!r} does not resolve in "
                    "the AST index — the registry drifted from the code"))

    reported = set()
    while queue:
        fi = queue.pop(0)

        # nested defs/lambda-enclosing scopes run under the trace too
        for child in fi.children:
            if _uid(child) not in seen:
                seen[_uid(child)] = child
                parents[_uid(child)] = _uid(fi)
                queue.append(child)

        for node in callgraph.walk_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            dotted, targets = project.resolve_call(fi, node)
            hit = _forbidden(dotted)
            if hit is not None:
                ns, why = hit
                key = f"jit:jit-forbidden-call:{fi.module.name}:" \
                      f"{fi.qualname}:{dotted}"
                if key not in reported:
                    reported.add(key)
                    findings.append(Finding(
                        check="jit", rule="jit-forbidden-call", key=key,
                        path=fi.path, line=node.lineno, func=fi.key,
                        message=f"call to {dotted} is reachable from a "
                                f"jitted step — {why}",
                        chain=_chain(parents, fi)))
                continue
            for t in targets:
                # stay inside the scanned project; foreign bodies are
                # opaque (their dotted name was already prefix-checked)
                if _uid(t) not in seen:
                    seen[_uid(t)] = t
                    parents[_uid(t)] = _uid(fi)
                    queue.append(t)

        for flag, lineno in project.flags_reads(fi):
            if flag in TRACE_TIME_FLAGS:
                continue
            detail = flag if flag is not None else "<dynamic>"
            key = f"jit:jit-flags-read:{fi.module.name}:" \
                  f"{fi.qualname}:{detail}"
            if key in reported:
                continue
            reported.add(key)
            what = (f"FLAGS.{flag}" if flag is not None
                    else "a dynamic getattr(FLAGS, ...)")
            findings.append(Finding(
                check="jit", rule="jit-flags-read", key=key,
                path=fi.path, line=lineno, func=fi.key,
                message=f"{what} is read on a jit-reachable path but is "
                        "not on the documented trace-time allow-list "
                        "(analysis/roots.py TRACE_TIME_FLAGS)",
                chain=_chain(parents, fi)))
    return findings
