"""CLI gate: ``python -m paddle_tpu.analysis --check all --json``.

Exit codes: 0 = clean (every finding baselined or none), 1 = new
findings (or stale baseline entries under --strict), 2 = usage /
internal error (unknown check, unreadable baseline).

The healthy-window playbook runs this as phase 17 and fails the window
on rc != 0; tests/test_analysis.py runs the same entry in-process
(reverse gates against analysis/fixtures/, clean-tree gate on HEAD).

Fixture/reverse-gate plumbing: ``--root mod:qualname`` replaces the
registered jit roots (all params data), ``--lock-paths`` replaces the
lock pass's scan set, ``--no-baseline`` ignores the committed
allow-list — so one seeded-violation module can prove every rule fires.
"""

import argparse
import json
import os
import sys

from paddle_tpu.analysis import baseline as baseline_mod
from paddle_tpu.analysis import callgraph, locks, purity, retrace
from paddle_tpu.analysis.roots import Root, all_roots

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(
    _REPO, "paddle_tpu", "analysis", "baseline.json")

CHECKS = ("all", "jit", "retrace", "locks")


def run_checks(check="all", roots=None, lock_paths=None, repo=_REPO,
               extra_paths=(), package="paddle_tpu"):
    """-> (project, [Finding]) — the in-process API the tests use."""
    project = callgraph.Project(repo, package=package,
                                extra_paths=extra_paths)
    roots = list(roots) if roots is not None else all_roots()
    findings = []
    if check in ("all", "jit"):
        findings += purity.run(project, roots)
    if check in ("all", "retrace"):
        findings += retrace.run(project, roots)
    if check in ("all", "locks"):
        findings += locks.run(project, lock_paths or locks.DEFAULT_SCAN)
    findings.sort(key=lambda f: (f.check, f.rule, f.path, f.line, f.key))
    return project, findings


def main(argv=None):
    try:
        from paddle_tpu.utils.flags import FLAGS
        flag_baseline = getattr(FLAGS, "analysis_baseline", None)
        flag_strict = bool(getattr(FLAGS, "analysis_strict", False))
    except Exception:   # noqa: BLE001 — the gate must not need the runtime
        flag_baseline, flag_strict = None, False

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="static invariant analyzer (docs/analysis.md): "
                    "jit-purity, retrace-hazard and lock-order passes")
    ap.add_argument("--check", default="all", choices=CHECKS)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=flag_baseline or DEFAULT_BASELINE,
                    help="allow-list path (default: the committed "
                         "paddle_tpu/analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the allow-list (fixture/reverse gates)")
    ap.add_argument("--strict", action="store_true", default=flag_strict,
                    help="stale baseline entries fail the gate too")
    ap.add_argument("--root", action="append", default=None,
                    metavar="MOD:QUALNAME",
                    help="replace the registered jit roots (repeatable; "
                         "every param is data)")
    ap.add_argument("--lock-paths", nargs="+", default=None,
                    metavar="PATH",
                    help="replace the lock pass scan set (repo-relative)")
    ap.add_argument("--scan-package", default="paddle_tpu",
                    metavar="DIR",
                    help="restrict the AST scan to this repo-relative "
                         "subtree (fixture gates keep the fast test "
                         "lane lean; the real gate scans the default)")
    ap.add_argument("--write-baseline", metavar="PATH", default=None,
                    help="write every CURRENT finding as a baseline to "
                         "PATH (reasons stubbed 'TODO: justify') and "
                         "exit 0 — a bootstrapping aid, never the gate")
    args = ap.parse_args(argv)

    roots = None
    if args.root:
        bad = [r for r in args.root if ":" not in r]
        if bad:
            print(f"[analysis] --root needs MOD:QUALNAME, got {bad}",
                  file=sys.stderr)
            return 2
        roots = [Root(name=r.split(":", 1)[1], ref=r) for r in args.root]
    # fixture refs live under paddle_tpu/, already scanned; --lock-paths
    # outside the package (none today) would need extra_paths
    project, findings = run_checks(check=args.check, roots=roots,
                                   lock_paths=args.lock_paths,
                                   package=args.scan_package)

    if args.write_baseline:
        entries = {f.key: "TODO: justify" for f in findings}
        baseline_mod.dump(args.write_baseline, entries)
        print(f"wrote {len(entries)} entries to {args.write_baseline}",
              file=sys.stderr)
        return 0

    stale = []
    if args.no_baseline:
        new = list(findings)
    else:
        try:
            bl = (baseline_mod.load(args.baseline)
                  if os.path.exists(args.baseline) else {})
        except (ValueError, OSError) as e:
            print(f"[analysis] unusable baseline: {e}", file=sys.stderr)
            return 2
        # staleness is judged only against the checks that RAN: a
        # single-pass invocation must not flag the other passes'
        # still-valid entries as stale (nor fail them under --strict)
        scope = (("jit", "retrace", "locks") if args.check == "all"
                 else (args.check,))
        bl = {k: v for k, v in bl.items()
              if k.split(":", 1)[0] in scope}
        new, stale = baseline_mod.apply(findings, bl)

    if args.json:
        counts = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print(json.dumps({
            "schema": 1,
            "kind": "paddle_tpu static-analysis report",
            "check": args.check,
            "findings": [f.to_json() for f in findings],
            "counts": counts,
            "new": len(new),
            "baselined": len(findings) - len(new),
            "stale_baseline_keys": stale,
            "roots": [r.ref for r in (roots or all_roots())],
        }, indent=1, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        if stale:
            print("[analysis] stale baseline entries (violation no "
                  "longer exists — delete them):", file=sys.stderr)
            for k in stale:
                print(f"    {k}", file=sys.stderr)
        print(f"[analysis] check={args.check}: {len(findings)} "
              f"finding(s), {len(new)} new, "
              f"{len(findings) - len(new)} baselined, "
              f"{len(stale)} stale baseline entr(ies)", file=sys.stderr)
    if new:
        return 1
    if stale and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
