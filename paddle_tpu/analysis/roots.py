"""The jitted-root registry: ONE place that names every jitted step.

Two consumers, kept joined by tests/test_analysis.py's drift test:

* `perf/analytic.py` iterates ``FAMILIES`` (moved here from there) to
  AOT-compile every bench family's step and gate its HLO structure;
* the static analyzer (`python -m paddle_tpu.analysis`) walks the call
  graph reachable from ``JIT_ROOTS`` — the Python functions those same
  lowered steps trace — and enforces jit-purity + retrace discipline.

``FAMILY_ROOTS`` maps every FAMILIES name to the JIT_ROOTS entries its
``extras["lower"]`` hook traces, so a NEW bench family cannot add a
jitted step the analyzer doesn't see: the drift test fails until the
family is mapped here (and its roots exist in the AST index).

Nothing here imports jax or bench machinery — the analyzer must stay a
parse-only gate, and analytic.py imports FAMILIES from here (never the
other way around).
"""

import dataclasses

# ---------------------------------------------------------------- FAMILIES
# snapshot name -> (bench.py model, batch override or None = family
# default).  Covers every bench family class (RNN, conv/image, seq2seq,
# transformer train/packed/moe, LM + beam decode, serving, trainer loop)
# plus the large-batch rows the round-5 verdict asked for: ResNet-50 at
# bs 256, the 8k-slot packed transformer, LSTM h=2048.  (The per-family
# commentary lives with the FAMILY_ROOTS mapping below and in
# perf/analytic.py's capture docstring.)
FAMILIES = [
    ("lstm", "lstm", None),
    ("lstm2048", "lstm2048", None),
    ("smallnet", "smallnet", None),
    ("alexnet", "alexnet", None),
    ("resnet50", "resnet50", None),
    ("resnet50@bs256", "resnet50", 256),
    ("seq2seq", "seq2seq", None),
    ("transformer", "transformer", None),
    ("transformer_packed", "transformer_packed", None),
    ("transformer_packed_8k", "transformer_packed_8k", None),
    ("transformer_moe", "transformer_moe", None),
    ("transformer_lm_decode", "transformer_lm_decode", None),
    ("transformer_decode", "transformer_decode", None),
    ("transformer_serving", "transformer_serving", None),
    ("serving", "serving", None),
    ("serving_generate", "serving_generate", None),
    ("serving_fleet", "serving_fleet", None),
    ("serving_autoscale", "serving_autoscale", None),
    ("serving_paged", "serving_paged", None),
    ("serving_decode_fused", "serving_decode_fused", None),
    ("serving_chunked_prefill", "serving_chunked_prefill", None),
    ("serving_quant", "serving_quant", None),
    ("serving_quant_prefill", "serving_quant_prefill", None),
    ("serving_speculative", "serving_speculative", None),
    ("serving_sharded", "serving_sharded", None),
    ("serving_kv_spill", "serving_kv_spill", None),
    ("serving_disagg", "serving_disagg", None),
    ("trainer_prefetch", "trainer_prefetch", None),
    ("trainer_int8", "trainer_int8", None),
]


# ---------------------------------------------------------------- JIT roots

@dataclasses.dataclass(frozen=True)
class Root:
    """One jitted step's Python entry point.

    ``ref`` is ``"dotted.module:qualname"`` with ``<locals>`` segments
    for closures (e.g. the trainer step).  ``static_args`` names the
    parameters that are TRACE-TIME constants (shapes, head counts,
    mode strings) — every other parameter is DATA (a tracer), and the
    retrace pass taints from exactly those.
    """
    name: str
    ref: str
    static_args: tuple = ()
    note: str = ""


JIT_ROOTS = {r.name: r for r in [
    # ---- training: the ONE jitted train step (SGD._build_step wraps
    # dense_step/sparse_step in the trace-counting `step` closure)
    Root("trainer_step",
         "paddle_tpu.trainer.trainer:SGD._build_step.<locals>.step",
         static_args=(),
         note="the jitted train step (loss + grads + optimizer update)"),
    # ---- LM trunk entry points (models/transformer.py) — what the
    # serving engines' _step_fn closures and lm_generate trace
    Root("lm_logits", "paddle_tpu.models.transformer:lm_logits",
         static_args=("num_heads", "return_aux", "encode_kw"),
         note="batched LM forward (training families + serving infer)"),
    Root("lm_prefill", "paddle_tpu.models.transformer:lm_prefill",
         static_args=("max_len", "num_heads", "moe_top_k", "pos_type",
                      "kv_dtype"),
         note="batched causal prefill writing the decode cache"),
    Root("lm_decode_step", "paddle_tpu.models.transformer:lm_decode_step",
         static_args=("num_heads", "moe_top_k", "pos_type"),
         note="single-stream incremental decode step"),
    Root("lm_decode_step_slots",
         "paddle_tpu.models.transformer:lm_decode_step_slots",
         static_args=("num_heads", "moe_top_k", "pos_type",
                      "shard_axis"),
         note="slab continuous-batching decode step (DecodeEngine); "
              "shard_axis is the tensor-parallel mesh-axis name — a "
              "trace-time constant like num_heads"),
    Root("lm_decode_step_paged",
         "paddle_tpu.models.transformer:lm_decode_step_paged",
         static_args=("num_heads", "moe_top_k", "pos_type"),
         note="paged-KV decode step (block tables fed as data)"),
    Root("lm_decode_chunk_slots",
         "paddle_tpu.models.transformer:lm_decode_chunk_slots",
         static_args=("num_heads", "moe_top_k", "pos_type", "all_lanes",
                      "shard_axis"),
         note="unified chunked-prefill step, slab layout (all_lanes is "
              "the spec-verify projection switch, shard_axis the "
              "tensor-parallel mesh axis — both trace-time only)"),
    Root("lm_decode_chunk_paged",
         "paddle_tpu.models.transformer:lm_decode_chunk_paged",
         static_args=("num_heads", "moe_top_k", "pos_type", "all_lanes",
                      "shard_axis"),
         note="unified chunked-prefill step, paged layout (all_lanes is "
              "the spec-verify projection switch, shard_axis the "
              "tensor-parallel mesh axis — both trace-time only)"),
    # ---- engine-side jitted closures (serving/): the slot-step wrapper
    # plus the admission/write/fork device ops around it
    Root("decode_engine_step",
         "paddle_tpu.serving.decode_engine:"
         "DecodeEngine.__init__.<locals>._step_fn",
         static_args=(),
         note="DecodeEngine's jitted step wrapper (all 4 layout/chunk "
              "variants share the qualname; every variant is analyzed)"),
    Root("draft_rollout",
         "paddle_tpu.serving.speculative:"
         "DraftTrunk.__init__.<locals>._draft_fn",
         static_args=(),
         note="DraftTrunk's jitted k-token rollout (speculative "
              "decoding); k/chunk are constructor constants baked into "
              "the closure, feed lengths/positions are data"),
    Root("serving_fwd",
         "paddle_tpu.serving.engine:"
         "InferenceEngine.from_inferencer.<locals>.fwd",
         static_args=(),
         note="InferenceEngine's jitted bucket forward"),
    # ---- fused Pallas kernels (ops/pallas/): what `maybe_*` dispatches
    # into — the kernel WRAPPERS trace host Python around pallas_call
    Root("decode_attention_slab",
         "paddle_tpu.ops.pallas.decode_attention:decode_attention_slab",
         static_args=("num_heads", "block_k", "interpret"),
         note="fused slab decode-attention kernel"),
    Root("decode_attention_paged",
         "paddle_tpu.ops.pallas.decode_attention:decode_attention_paged",
         static_args=("num_heads", "interpret"),
         note="fused paged decode-attention kernel"),
    Root("decode_attention_slab_chunk",
         "paddle_tpu.ops.pallas.decode_attention:"
         "decode_attention_slab_chunk",
         static_args=("num_heads", "block_k", "interpret"),
         note="Tq=chunk slab kernel (unified chunked prefill)"),
    Root("decode_attention_paged_chunk",
         "paddle_tpu.ops.pallas.decode_attention:"
         "decode_attention_paged_chunk",
         static_args=("num_heads", "interpret"),
         note="Tq=chunk paged kernel (unified chunked prefill)"),
    Root("flash_attention",
         "paddle_tpu.ops.pallas.flash_attention:flash_attention",
         static_args=("scale", "causal", "block_q", "block_k",
                      "interpret"),
         note="flash prefill kernel (pallas_prefill routing)"),
    Root("flash_attention_quant",
         "paddle_tpu.ops.pallas.flash_attention:flash_attention_quant",
         static_args=("num_heads", "scale", "causal", "block_q",
                      "block_k", "interpret"),
         note="int8 flash prefill kernel (pallas_prefill_quant "
              "routing): int8 K/V + per-(position, head) scale "
              "sidecars stream block-by-block, widen in registers"),
    # ---- int8 weight-streaming train step (SGD quant_weights=True):
    # a SEPARATE closure from dense_step — the {master, q} bundle step
    # with the in-step requantize
    Root("trainer_quant_step",
         "paddle_tpu.trainer.trainer:"
         "SGD._build_step.<locals>.quant_step",
         static_args=(),
         note="the int8 weight-streaming train step (dequant at the "
              "matmul boundary, f32 masters optimizer-side, in-step "
              "requantize)"),
]}


# Every FAMILIES name -> the JIT_ROOTS its extras["lower"] hook traces.
# Training families all lower SGD.lower_step -> the trainer step; the
# serving families lower the engine step for their layout.  The drift
# test (tests/test_analysis.py) fails when a FAMILIES entry is missing
# here, when a mapping names an unknown root, or when a root's ref no
# longer resolves in the AST index.
FAMILY_ROOTS = {
    "lstm": ("trainer_step",),
    "lstm2048": ("trainer_step",),
    "smallnet": ("trainer_step",),
    "alexnet": ("trainer_step",),
    "resnet50": ("trainer_step",),
    "resnet50@bs256": ("trainer_step",),
    "seq2seq": ("trainer_step",),
    "transformer": ("trainer_step",),
    "transformer_packed": ("trainer_step",),
    "transformer_packed_8k": ("trainer_step",),
    "transformer_moe": ("trainer_step",),
    "transformer_lm_decode": ("lm_prefill", "lm_decode_step"),
    "transformer_decode": ("trainer_step",),
    "transformer_serving": ("lm_logits",),
    "serving": ("serving_fwd", "lm_logits"),
    "serving_generate": ("decode_engine_step", "lm_decode_step_slots",
                         "lm_prefill"),
    "serving_fleet": ("decode_engine_step", "lm_decode_step_slots",
                      "lm_prefill"),
    "serving_autoscale": ("decode_engine_step", "lm_decode_step_slots",
                          "lm_prefill"),
    "serving_paged": ("decode_engine_step", "lm_decode_step_paged",
                      "lm_prefill"),
    "serving_decode_fused": ("decode_engine_step", "lm_decode_step_paged",
                             "decode_attention_paged",
                             "decode_attention_slab"),
    "serving_chunked_prefill": ("decode_engine_step",
                                "lm_decode_chunk_slots",
                                "lm_decode_chunk_paged", "lm_prefill",
                                "decode_attention_slab_chunk",
                                "decode_attention_paged_chunk",
                                "flash_attention"),
    "serving_quant": ("decode_engine_step", "lm_decode_step_paged",
                      "decode_attention_paged", "lm_prefill"),
    # serving_quant_prefill lowers the int8-KV lm_prefill with the
    # quant kernel forced ON — the per-layer seam dispatches into
    # flash_attention_quant (the f32 twin it gates falls back through
    # flash_attention).
    "serving_quant_prefill": ("lm_prefill", "flash_attention_quant",
                              "flash_attention"),
    "serving_speculative": ("decode_engine_step", "draft_rollout",
                            "lm_decode_chunk_slots",
                            "lm_decode_chunk_paged",
                            "lm_decode_step_slots", "lm_prefill",
                            "decode_attention_slab_chunk",
                            "decode_attention_paged_chunk",
                            "flash_attention"),
    # serving_sharded traces the SAME engine/draft closures as the
    # speculative family — the shard_map wrapper lives inside
    # decode_engine_step/draft_rollout's `_model` body, so the analyzer
    # walks it through the existing refs; no new qualnames appear.
    "serving_sharded": ("decode_engine_step", "draft_rollout",
                        "lm_decode_chunk_slots",
                        "lm_decode_chunk_paged",
                        "lm_decode_step_slots", "lm_prefill",
                        "decode_attention_slab_chunk",
                        "decode_attention_paged_chunk",
                        "flash_attention"),
    # serving_kv_spill runs the SAME one chunked step as
    # serving_chunked_prefill — the host tier adds no jitted code (spill
    # gathers with NumPy on the worker thread; the restore lands through
    # the already-warm block-write donation path), so the family traces
    # exactly the chunked-prefill root set.
    "serving_kv_spill": ("decode_engine_step",
                         "lm_decode_chunk_slots",
                         "lm_decode_chunk_paged", "lm_prefill",
                         "decode_attention_slab_chunk",
                         "decode_attention_paged_chunk",
                         "flash_attention"),
    # serving_disagg (cross-replica KV handoff, serving/transfer.py)
    # adds NO jitted code either: the export gathers with NumPy on the
    # source's worker thread, the blob crosses a plain socket, and the
    # receive lands through the SAME claim/stage/commit restore pipeline
    # serving_kv_spill exercises — so the receive/commit path traces
    # exactly the chunked-prefill root set, and the analyzer covers the
    # handoff by covering these.
    "serving_disagg": ("decode_engine_step",
                       "lm_decode_chunk_slots",
                       "lm_decode_chunk_paged", "lm_prefill",
                       "decode_attention_slab_chunk",
                       "decode_attention_paged_chunk",
                       "flash_attention"),
    "trainer_prefetch": ("trainer_step",),
    # trainer_int8 lowers SGD(quant_weights=True).lower_step — the
    # quant_step closure (NOT dense_step) wrapped by the same
    # trace-counting `step`.
    "trainer_int8": ("trainer_step", "trainer_quant_step"),
}


# FLAGS fields the jitted paths may legitimately read AT TRACE TIME
# (each is documented "read at trace time" in utils/flags.py): kernel
# dispatch + tiling.  Any other FLAGS read reachable from a root is a
# jit-purity finding — runtime flag reads inside a traced body are
# invisible to the compiled program (the trace bakes one value in) and
# a classic source of "works until the flag changes" bugs.
TRACE_TIME_FLAGS = frozenset({
    "pallas_decode",
    "pallas_decode_block_k",
    "pallas_prefill",
    "pallas_prefill_quant",
})


def all_roots():
    """Every registered Root, in a stable order."""
    return [JIT_ROOTS[k] for k in sorted(JIT_ROOTS)]


def roots_for_family(name):
    """The Root entries a FAMILIES name traces (drift test's subject)."""
    return [JIT_ROOTS[r] for r in FAMILY_ROOTS[name]]
