"""AST project index + best-effort static name/call resolution.

Parses every ``.py`` under the package root ONCE (no imports are
executed, jax is never touched) and answers the questions the three
passes ask:

* what function does this ``ast.Call`` target?  (``resolve_call``)
* what dotted name does this expression denote?  (``resolve_dotted``)
* which class attribute / module global is a ``threading.Lock``?
* what project class does ``self._x`` hold?  (constructor-assignment
  type inference: ``self._x = SomeClass(...)`` in ``__init__``)

Resolution is deliberately OPTIMISTIC: a call the index cannot resolve
(callbacks, dynamic dispatch, foreign objects) is skipped, never
guessed — the passes built on top prefer missing an edge to inventing
one, the same trade every practical linter makes.  What IS resolvable
statically — module imports, local defs, ``self.method``, constructor-
typed attributes, closure scopes — covers the hot paths the invariants
live on.

Qualnames follow Python's own convention: ``C.m`` for methods,
``f.<locals>.g`` for closures.  Several functions may share a qualname
(e.g. the four ``DecodeEngine.__init__.<locals>._step_fn`` layout
variants); the index keeps ALL of them and reachability walks visit
every variant.
"""

import ast
import os


LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
}


def walk_scope(node):
    """Yield every AST node in ``node``'s own scope: descends through
    statements and lambdas but NOT into nested FunctionDef/ClassDef
    bodies (those are separate scopes, indexed as their own entities)."""
    stack = [node]
    first = True
    while stack:
        n = stack.pop()
        if not first and isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef)):
            continue
        first = False
        yield n
        stack.extend(ast.iter_child_nodes(n))


class FuncInfo:
    def __init__(self, module, qualname, node, cls=None, parent=None):
        self.module = module
        self.qualname = qualname
        self.node = node
        self.cls = cls              # enclosing ClassInfo for methods
        self.parent = parent        # enclosing FuncInfo for closures
        self.children = []          # nested FuncInfos
        self._locals = None

    @property
    def key(self):
        return f"{self.module.name}:{self.qualname}"

    @property
    def dotted(self):
        return f"{self.module.name}.{self.qualname}"

    @property
    def path(self):
        return self.module.relpath

    @property
    def line(self):
        return self.node.lineno

    def params(self):
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    # ---- scope-local bindings: imports, defs, simple aliases ----------

    def local_bindings(self):
        if self._locals is None:
            by_name = {}
            for c in self.children:
                by_name.setdefault(c.node.name, []).append(c)
            self._locals = _collect_bindings(self.module, self.node.body,
                                             local_funcs=by_name)
        return self._locals


class ClassInfo:
    def __init__(self, module, qualname, node):
        self.module = module
        self.qualname = qualname
        self.node = node
        self.methods = {}       # name -> [FuncInfo]
        self.lock_attrs = {}    # attr -> "lock" | "rlock" | "condition"
        self.attr_types = {}    # attr -> ClassInfo (constructor-typed)
        self.base_exprs = list(node.bases)

    @property
    def key(self):
        return f"{self.module.name}.{self.qualname}"


class Module:
    def __init__(self, name, path, relpath, tree):
        self.name = name
        self.path = path
        self.relpath = relpath
        self.tree = tree
        self.funcs = {}         # qualname -> [FuncInfo]
        self.classes = {}       # qualname -> ClassInfo
        self.bindings = {}      # module-level name -> Binding tuple
        self.lock_globals = {}  # global name -> lock kind


def _bind_import(bindings, module, node):
    if isinstance(node, ast.Import):
        for a in node.names:
            if a.asname:
                bindings[a.asname] = ("module", a.name)
            else:
                bindings[a.name.split(".")[0]] = \
                    ("module", a.name.split(".")[0])
    elif isinstance(node, ast.ImportFrom):
        base = node.module or ""
        if node.level:
            pkg = module.name
            if not module.path.endswith("__init__.py"):
                pkg = pkg.rsplit(".", 1)[0] if "." in pkg else ""
            for _ in range(node.level - 1):
                pkg = pkg.rsplit(".", 1)[0] if "." in pkg else ""
            base = f"{pkg}.{base}" if base else pkg
        for a in node.names:
            if a.name == "*":
                continue
            target = f"{base}.{a.name}" if base else a.name
            bindings[a.asname or a.name] = ("dotted", target)


def _collect_bindings(module, body, local_funcs=None):
    """Name bindings visible in a statement list (one scope level):
    imports anywhere in the scope's statements, local function defs,
    and simple ``x = y`` / ``x = a if c else b`` function aliases."""
    bindings = {}
    local_funcs = local_funcs or {}

    def visit(stmts):
        for st in stmts:
            if isinstance(st, (ast.Import, ast.ImportFrom)):
                _bind_import(bindings, module, st)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                infos = local_funcs.get(st.name)
                if infos:
                    bindings[st.name] = ("func", list(infos))
                continue                 # separate scope: don't descend
            if isinstance(st, ast.ClassDef):
                continue
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                name = st.targets[0].id
                cands = _alias_candidates(st.value, bindings, local_funcs)
                if cands:
                    bindings[name] = ("func", cands)
            for field in ("body", "orelse", "finalbody"):
                visit(getattr(st, field, None) or [])
            for h in getattr(st, "handlers", None) or []:
                visit(h.body)
    visit(body)
    return bindings


def _alias_candidates(value, bindings, local_funcs):
    """Function aliases: ``x = f``, ``x = f if c else g``."""
    if isinstance(value, ast.Name):
        b = bindings.get(value.id)
        if b and b[0] == "func":
            return list(b[1])
        return list(local_funcs.get(value.id, []))
    if isinstance(value, ast.IfExp):
        return (_alias_candidates(value.body, bindings, local_funcs)
                + _alias_candidates(value.orelse, bindings, local_funcs))
    return []


def _ctor_exprs(value):
    """Call expressions that may produce the assigned value:
    ``C(...)``, ``x or C(...)``, ``C(...) if cond else D(...)``."""
    if isinstance(value, ast.Call):
        return [value]
    if isinstance(value, ast.BoolOp):
        return [c for v in value.values for c in _ctor_exprs(v)]
    if isinstance(value, ast.IfExp):
        return _ctor_exprs(value.body) + _ctor_exprs(value.orelse)
    return []


class Project:
    """The parsed tree.  ``root`` is the repository root; ``package``
    the import root scanned (default paddle_tpu).  ``extra_paths`` adds
    loose files/dirs (fixture scans) outside the package."""

    def __init__(self, root, package="paddle_tpu", extra_paths=()):
        self.root = os.path.abspath(root)
        self.modules = {}
        pkg_dir = os.path.join(self.root, package)
        paths = []
        if os.path.isdir(pkg_dir):
            for dirpath, dirnames, filenames in os.walk(pkg_dir):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        paths.append(os.path.join(dirpath, fn))
        for p in extra_paths:
            p = os.path.join(self.root, p)
            if os.path.isdir(p):
                for dirpath, _dn, filenames in os.walk(p):
                    paths.extend(os.path.join(dirpath, fn)
                                 for fn in sorted(filenames)
                                 if fn.endswith(".py"))
            elif os.path.isfile(p):
                paths.append(p)
        for path in paths:
            self._load(path)
        for m in self.modules.values():
            self._infer_class_attrs(m)

    # ------------------------------------------------------------- loading

    def _module_name(self, path):
        rel = os.path.relpath(path, self.root)
        parts = rel[:-3].replace(os.sep, ".")
        if parts.endswith(".__init__"):
            parts = parts[:-len(".__init__")]
        return parts

    def _load(self, path):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return
        name = self._module_name(path)
        if name in self.modules:
            return
        mod = Module(name, path, os.path.relpath(path, self.root), tree)
        self.modules[name] = mod
        self._index(mod, tree.body, prefix="", cls=None, parent=None,
                    toplevel=True)

    def _index(self, mod, body, prefix, cls, parent, toplevel=False):
        for st in body:
            if isinstance(st, (ast.Import, ast.ImportFrom)) and toplevel:
                _bind_import(mod.bindings, mod, st)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{st.name}"
                fi = FuncInfo(mod, qual, st, cls=cls, parent=parent)
                mod.funcs.setdefault(qual, []).append(fi)
                if parent is not None:
                    parent.children.append(fi)
                if cls is not None and parent is None:
                    cls.methods.setdefault(st.name, []).append(fi)
                if toplevel:
                    mod.bindings.setdefault(st.name, ("func", []))
                    if mod.bindings[st.name][0] == "func":
                        mod.bindings[st.name][1].append(fi)
                self._index(mod, st.body, prefix=f"{qual}.<locals>.",
                            cls=None, parent=fi)
            elif isinstance(st, ast.ClassDef):
                qual = f"{prefix}{st.name}"
                ci = ClassInfo(mod, qual, st)
                mod.classes[qual] = ci
                if toplevel:
                    mod.bindings[st.name] = ("class", ci)
                self._index(mod, st.body, prefix=f"{qual}.", cls=ci,
                            parent=parent)
            elif isinstance(st, ast.Assign) and toplevel \
                    and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                name = st.targets[0].id
                kind = self._lock_kind(mod, st.value)
                if kind:
                    mod.lock_globals[name] = kind
                mod.bindings.setdefault(
                    name, ("dotted", f"{mod.name}.{name}"))
            else:
                # defs nested in if/try/for/while/with bodies (e.g. the
                # DecodeEngine layout-variant _step_fn closures) belong
                # to the SAME scope
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(st, field, None)
                    if sub and isinstance(sub, list):
                        self._index(mod, sub, prefix, cls, parent,
                                    toplevel=toplevel)
                for h in getattr(st, "handlers", None) or []:
                    self._index(mod, h.body, prefix, cls, parent,
                                toplevel=toplevel)

    def _lock_kind(self, mod, value, func=None):
        if not isinstance(value, ast.Call):
            return None
        dotted = self.resolve_dotted(mod, value.func, func=func)
        return LOCK_FACTORIES.get(dotted)

    def _infer_class_attrs(self, mod):
        for ci in mod.classes.values():
            for infos in ci.methods.values():
                for fi in infos:
                    for n in walk_scope(fi.node):
                        if not (isinstance(n, ast.Assign)
                                and len(n.targets) == 1):
                            continue
                        t = n.targets[0]
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            continue
                        kind = self._lock_kind(mod, n.value, func=fi)
                        if kind:
                            ci.lock_attrs[t.attr] = kind
                            continue
                        # constructor typing, incl. the `x or C(...)` /
                        # ternary defaulting idioms
                        for cand in _ctor_exprs(n.value):
                            target = self.resolve_class(
                                mod, cand.func, func=fi)
                            if target is not None:
                                ci.attr_types.setdefault(t.attr, target)
                                break

    # ---------------------------------------------------------- resolution

    def _binding(self, mod, name, func):
        f = func
        while f is not None:
            b = f.local_bindings().get(name)
            if b is not None:
                return b
            f = f.parent
        return mod.bindings.get(name)

    def resolve_dotted(self, mod, expr, func=None):
        """Expression -> dotted name ("time.sleep",
        "paddle_tpu.resilience.faults.hook") or None."""
        attrs = []
        while isinstance(expr, ast.Attribute):
            attrs.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        attrs.reverse()
        b = self._binding(mod, expr.id, func)
        if b is None:
            return None
        kind, val = b[0], b[1]
        if kind == "module":
            return ".".join([val] + attrs) if attrs else val
        if kind == "dotted":
            return ".".join([val] + attrs)
        if kind == "class" and attrs:
            return ".".join([val.key] + attrs)
        if kind == "func" and not attrs and val:
            return val[0].dotted
        return None

    def dotted_function(self, dotted):
        """Project FuncInfos for a dotted name, or []."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.modules.get(".".join(parts[:i]))
            if mod is not None:
                qual = ".".join(parts[i:])
                infos = mod.funcs.get(qual)
                if infos:
                    return infos
                ci = mod.classes.get(qual)
                if ci is not None:
                    return ci.methods.get("__init__", [])
                return []
        return []

    def dotted_class(self, dotted):
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.modules.get(".".join(parts[:i]))
            if mod is not None:
                return mod.classes.get(".".join(parts[i:]))
        return None

    def resolve_class(self, mod, expr, func=None):
        """Expression (in a constructor-call position) -> ClassInfo."""
        if isinstance(expr, ast.Name):
            b = self._binding(mod, expr.id, func)
            if b and b[0] == "class":
                return b[1]
        dotted = self.resolve_dotted(mod, expr, func=func)
        return self.dotted_class(dotted) if dotted else None

    def attr_chain_class(self, ci, attrs):
        """Walk ``self.a.b`` constructor-typed attributes: ClassInfo of
        the object at the end of the chain (the chain may be empty)."""
        for a in attrs:
            if ci is None:
                return None
            ci = ci.attr_types.get(a)
        return ci

    def class_method(self, ci, name, _seen=None):
        """Method lookup incl. project base classes."""
        _seen = _seen or set()
        if ci is None or ci.key in _seen:
            return []
        _seen.add(ci.key)
        infos = ci.methods.get(name)
        if infos:
            return infos
        for b in ci.base_exprs:
            base = self.resolve_class(ci.module, b)
            got = self.class_method(base, name, _seen)
            if got:
                return got
        return []

    def local_var_class(self, func, name):
        """Class of a local constructed in the same scope:
        ``x = SomeClass(...)``."""
        for n in walk_scope(func.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and n.targets[0].id == name \
                    and isinstance(n.value, ast.Call):
                ci = self.resolve_class(func.module, n.value.func,
                                        func=func)
                if ci is not None:
                    return ci
        return None

    def resolve_call(self, func, call):
        """(dotted_name_or_None, [FuncInfo] targets) for a Call seen
        inside ``func``.  Either element may be empty — the dotted name
        serves prefix checks (purity) even when the body is external."""
        target = call.func
        mod = func.module
        # self.method() / self.attr.method()
        if isinstance(target, ast.Attribute):
            chain = []
            base = target
            while isinstance(base, ast.Attribute):
                chain.append(base.attr)
                base = base.value
            chain.reverse()
            if isinstance(base, ast.Name) and base.id == "self" \
                    and func.cls is not None:
                owner = self.attr_chain_class(func.cls, chain[:-1])
                if owner is not None:
                    infos = self.class_method(owner, chain[-1])
                    return (f"{owner.key}.{chain[-1]}", infos)
                return (None, [])
            if isinstance(base, ast.Name):
                ci = self.local_var_class(func, base.id)
                owner = self.attr_chain_class(ci, chain[:-1]) \
                    if ci is not None else None
                if owner is not None:
                    infos = self.class_method(owner, chain[-1])
                    return (f"{owner.key}.{chain[-1]}", infos)
        if isinstance(target, ast.Name):
            b = self._binding(mod, target.id, func)
            if b is not None:
                kind, val = b[0], b[1]
                if kind == "func":
                    return (val[0].dotted if val else None, list(val))
                if kind == "class":
                    return (val.key, val.methods.get("__init__", []))
                if kind == "dotted":
                    return (val, self.dotted_function(val))
                if kind == "module":
                    return (val, [])
            return (None, [])
        dotted = self.resolve_dotted(mod, target, func=func)
        if dotted is not None:
            return (dotted, self.dotted_function(dotted))
        return (None, [])

    def function(self, ref):
        """``"dotted.module:qualname"`` -> [FuncInfo] (all qualname
        sharers), or []."""
        modname, _, qual = ref.partition(":")
        mod = self.modules.get(modname)
        if mod is None:
            return []
        return list(mod.funcs.get(qual, []))

    def flags_reads(self, func):
        """(flag_name_or_None, lineno) for every FLAGS attribute read /
        getattr(FLAGS, ...) in ``func``'s scope.  None = dynamic."""
        out = []
        for n in walk_scope(func.node):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and isinstance(n.ctx, ast.Load):
                b = self._binding(func.module, n.value.id, func)
                if b and b[0] == "dotted" \
                        and b[1] == "paddle_tpu.utils.flags.FLAGS":
                    out.append((n.attr, n.lineno))
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "getattr" and n.args:
                b = None
                if isinstance(n.args[0], ast.Name):
                    b = self._binding(func.module, n.args[0].id, func)
                if b and b[0] == "dotted" \
                        and b[1] == "paddle_tpu.utils.flags.FLAGS":
                    name = None
                    if len(n.args) > 1 and isinstance(n.args[1],
                                                      ast.Constant):
                        name = n.args[1].value
                    out.append((name, n.lineno))
        return out
