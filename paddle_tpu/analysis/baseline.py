"""Findings + the committed allow-list (baseline) format.

A finding's ``key`` is STABLE across unrelated edits — it names the
rule, the function (module:qualname), and the offending detail (callee
dotted name / flag name / lock pair / attribute), never a line number.
The committed baseline (`paddle_tpu/analysis/baseline.json`) is a list
of ``{"key": ..., "reason": ...}`` entries: intentional, justified
exceptions.  An empty reason is rejected — a baseline entry without a
WHY is just a suppressed bug.

Semantics at gate time (``python -m paddle_tpu.analysis``):

* finding with a matching baseline entry  -> reported as baselined,
  does NOT fail the gate;
* finding without an entry               -> fails the gate (rc 1);
* entry matching no finding (stale)      -> warned; fails only under
  ``--strict`` (the entry documents a violation that no longer exists
  and should be deleted).
"""

import dataclasses
import json


SCHEMA = 1


@dataclasses.dataclass
class Finding:
    """One rule violation.

    ``check``: jit | retrace | locks; ``rule``: the specific rule id
    (docs/analysis.md's inventory); ``key``: stable baseline key;
    ``path``/``line``: where to look; ``func``: module:qualname;
    ``message``: human sentence; ``chain``: how the analyzer got there
    (root -> ... -> offender), empty for non-reachability rules.
    """
    check: str
    rule: str
    key: str
    path: str
    line: int
    func: str
    message: str
    chain: tuple = ()
    baselined: bool = False
    reason: str = ""

    def to_json(self):
        d = dataclasses.asdict(self)
        d["chain"] = list(self.chain)
        return d

    def render(self):
        tag = f" [baselined: {self.reason}]" if self.baselined else ""
        out = (f"{self.path}:{self.line}: [{self.check}:{self.rule}] "
               f"{self.message}{tag}\n    key: {self.key}")
        if self.chain:
            out += "\n    via: " + " -> ".join(self.chain)
        return out


def load(path):
    """Parse a baseline file -> {key: reason}.  Raises ValueError on a
    malformed file (the git gate test asserts the committed one parses)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
        raise ValueError(f"{path}: not a schema-{SCHEMA} analysis baseline")
    entries = doc.get("entries")
    if not isinstance(entries, list):
        raise ValueError(f"{path}: 'entries' must be a list")
    out = {}
    for e in entries:
        key, reason = e.get("key"), e.get("reason", "")
        if not key or not str(reason).strip():
            raise ValueError(
                f"{path}: every entry needs a key AND a non-empty reason "
                f"(offender: {e!r})")
        if key in out:
            raise ValueError(f"{path}: duplicate baseline key {key!r}")
        out[key] = str(reason)
    return out


def dump(path, entries):
    """Write {key: reason} as a committed-friendly baseline file."""
    doc = {
        "schema": SCHEMA,
        "kind": "paddle_tpu static-analysis allow-list (docs/analysis.md)",
        "entries": [{"key": k, "reason": entries[k]}
                    for k in sorted(entries)],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def apply(findings, baseline):
    """Mark baselined findings in place; return (new, stale_keys)."""
    matched = set()
    new = []
    for f in findings:
        if f.key in baseline:
            f.baselined, f.reason = True, baseline[f.key]
            matched.add(f.key)
        else:
            new.append(f)
    stale = sorted(set(baseline) - matched)
    return new, stale
