"""Static invariant analyzer (docs/analysis.md).

Three load-bearing disciplines hold this codebase together, and until
now only runtime tests and review hardening enforced them:

* **jit-purity** — host-side subsystems (obs/ tracing, resilience fault
  hooks, serving metrics, logging, wall clocks, stdlib RNG, threads)
  never run inside a jit body; the only FLAGS the jitted steps may read
  are the documented trace-time ones.
* **retrace discipline** — every serving/training step is 1-trace/
  0-retrace: all variation is fed as DATA, never as Python-level
  branching on runtime values, host syncs (`.item()`, `int(tracer)`),
  or shape keys built from non-static args.
* **lock order** — the threaded serving tier (batcher/engine/router/
  fleet/autoscaler/supervisor) acquires its locks in a consistent
  global order (no cycles), and attributes guarded by a lock are not
  also mutated outside it.

This package checks all three STATICALLY, by AST, on every commit —
before any chip or chaos test runs, the same way `perf/analytic.py`
gates HLO structure.  Nothing here imports jax: the gate costs a parse,
not a trace.

    python -m paddle_tpu.analysis --check all|jit|retrace|locks [--json]

Non-zero exit on findings not covered by the committed allow-list
(`paddle_tpu/analysis/baseline.json`).  Every rule is proven in
REVERSE against a seeded-violation fixture (`analysis/fixtures/`,
pinned by tests/test_analysis.py) — the analytic-gate discipline.

Modules:
  roots.py      the jitted-root registry (shared with perf/analytic.py's
                FAMILIES — the drift test keeps them joined)
  callgraph.py  AST project index + best-effort call/name resolution
  purity.py     jit-purity pass
  retrace.py    retrace-hazard pass (taint from the roots' data args)
  locks.py      lock-order + mixed-guard-mutation pass
  baseline.py   finding keys + committed allow-list round-trip
"""

from paddle_tpu.analysis.baseline import Finding  # noqa: F401
