"""lock-order pass: the threaded serving tier stays deadlock-free.

Scans the threaded modules (serving/, resilience/, data/prefetch.py by
default) for lock ACQUISITIONS — ``with self._lock:`` on a class attr
assigned ``threading.Lock()``/``RLock()``/``Condition()`` in
``__init__``, ``with _global_lock:`` on a module-global, and the same
through constructor-typed attributes (``self._fleet._lock``) — and
builds the inter-lock ORDERING graph: an edge A -> B means some thread
may acquire B while holding A, either lexically (a nested ``with``) or
through a call made under A whose transitive callees acquire B
(callgraph.py resolution; unresolvable calls are skipped).

Rules (docs/analysis.md):
  lock-order-cycle   a cycle in the ordering graph — two threads taking
                     the locks in opposite orders can deadlock (the
                     PR 12 remove_replica bug class)
  lock-reacquire     a non-reentrant lock re-acquired while already
                     held on the same path — self-deadlock (RLocks and
                     the ``*_locked``-suffix callee convention are
                     exempt by construction: ``*_locked`` helpers don't
                     acquire, they document an already-held lock)
  lock-mixed-guard   a class attribute mutated both UNDER one of the
                     class's locks and OUTSIDE any of them (``__init__``
                     and ``*_locked`` helpers count as guarded) — the
                     exact bug class PR 12's remove_replica hardening
                     fixed by hand

Lock identity is ``module.Class.attr`` for instance locks (two classes'
``_lock`` attrs are DIFFERENT locks) and ``module.name`` for globals.
"""

import ast
import os

from paddle_tpu.analysis import callgraph
from paddle_tpu.analysis.baseline import Finding

DEFAULT_SCAN = ("paddle_tpu/serving", "paddle_tpu/resilience",
                "paddle_tpu/data/prefetch.py")


class LockRef:
    def __init__(self, key, kind, display):
        self.key = key          # stable identity
        self.kind = kind        # "lock" | "rlock" | "condition"
        self.display = display

    @property
    def reentrant(self):
        return self.kind == "rlock"


def _resolve_lock(project, fi, expr):
    """A with-item context expression -> LockRef, or None when it is
    not (recognizably) a lock."""
    mod = fi.module
    # bare name: module-global lock, or a local assigned threading.Lock()
    if isinstance(expr, ast.Name):
        kind = mod.lock_globals.get(expr.id)
        if kind:
            return LockRef(f"{mod.name}.{expr.id}", kind,
                           f"{mod.name}.{expr.id}")
        for n in callgraph.walk_scope(fi.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and n.targets[0].id == expr.id:
                kind = project._lock_kind(mod, n.value, func=fi)
                if kind:
                    return LockRef(
                        f"{mod.name}:{fi.qualname}.{expr.id}", kind,
                        f"{fi.qualname}'s local {expr.id}")
        return None
    if not isinstance(expr, ast.Attribute):
        return None
    chain = []
    base = expr
    while isinstance(base, ast.Attribute):
        chain.append(base.attr)
        base = base.value
    chain.reverse()
    if not isinstance(base, ast.Name):
        return None
    if base.id == "self" and fi.cls is not None:
        owner = project.attr_chain_class(fi.cls, chain[:-1])
        if owner is not None:
            kind = owner.lock_attrs.get(chain[-1])
            if kind:
                key = f"{owner.key}.{chain[-1]}"
                return LockRef(key, kind, key)
        return None
    ci = project.local_var_class(fi, base.id)
    owner = project.attr_chain_class(ci, chain[:-1]) \
        if ci is not None else None
    if owner is not None:
        kind = owner.lock_attrs.get(chain[-1])
        if kind:
            key = f"{owner.key}.{chain[-1]}"
            return LockRef(key, kind, key)
    return None


def _scan_function(project, fi):
    """Per-function lock facts:
      acquires:   [(LockRef, lineno, held_keys_tuple)]
      calls_held: [(held LockRef, call node, lineno)]
    plus, for the mixed-guard rule, self-attribute mutations with the
    set of held instance locks at the site."""
    acquires, calls_held, mutations = [], [], []

    def visit(stmts, held):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue       # separate scope/thread entry: not "held"
            if isinstance(st, ast.With):
                new_held = list(held)
                for item in st.items:
                    ref = _resolve_lock(project, fi, item.context_expr)
                    if ref is not None:
                        acquires.append(
                            (ref, item.context_expr.lineno,
                             tuple(h.key for h in new_held)))
                        new_held = new_held + [ref]
                visit(st.body, new_held)
                continue
            # calls made while holding something
            for n in _scope_exprs(st):
                if isinstance(n, ast.Call) and held:
                    calls_held.append((list(held), n))
            # self-attribute mutations
            for tgt, aug in _mutation_targets(st):
                attr = _self_attr(tgt)
                if attr is not None:
                    mutations.append((attr, st.lineno,
                                      tuple(h.key for h in held)))
            # recurse into compound statements
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if sub:
                    visit(sub, held)
            for h in getattr(st, "handlers", None) or []:
                visit(h.body, held)

    visit(fi.node.body, [])
    return acquires, calls_held, mutations


def _scope_exprs(st):
    """Expression nodes of one statement, not descending into nested
    statement bodies (those are visited with their own held-set) nor
    nested def/class scopes."""
    skip_fields = {"body", "orelse", "finalbody", "handlers"}
    out = []
    stack = [(st, True)]
    while stack:
        node, is_root = stack.pop()
        if not is_root:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            out.append(node)
        for field, value in ast.iter_fields(node):
            if is_root and field in skip_fields:
                continue
            if isinstance(value, list):
                stack.extend((v, False) for v in value
                             if isinstance(v, ast.AST))
            elif isinstance(value, ast.AST):
                stack.append((value, False))
    return out


def _mutation_targets(st):
    if isinstance(st, ast.Assign):
        return [(t, False) for t in st.targets]
    if isinstance(st, ast.AugAssign):
        return [(st.target, True)]
    if isinstance(st, ast.AnnAssign) and st.value is not None:
        return [(st.target, False)]
    return []


def _self_attr(tgt):
    """``self.X = ...`` or ``self.X[k] = ...`` -> "X" (the attribute
    whose value/contents mutate)."""
    if isinstance(tgt, ast.Subscript):
        tgt = tgt.value
    if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
            and tgt.value.id == "self":
        return tgt.attr
    return None


def run(project, scan_paths=DEFAULT_SCAN):
    """-> [Finding] for the lock-order pass over modules under
    ``scan_paths`` (repo-relative files or directories)."""
    scan_paths = tuple(os.path.normpath(p) for p in scan_paths)

    def in_scope(mod):
        rel = os.path.normpath(mod.relpath)
        return any(rel == p or rel.startswith(p + os.sep)
                   for p in scan_paths)

    mods = [m for m in project.modules.values() if in_scope(m)]
    funcs = [fi for m in mods for infos in m.funcs.values()
             for fi in infos]

    facts = {}                  # id(fi) -> (acquires, calls_held, muts)
    for fi in funcs:
        facts[id(fi)] = _scan_function(project, fi)

    # ---- transitive "locks acquired by calling f" closure -------------
    direct = {}                 # id(fi) -> {lock key -> LockRef}
    for fi in funcs:
        direct[id(fi)] = {ref.key: ref
                          for ref, _ln, _held in facts[id(fi)][0]}

    def closure(fi, _stack=None):
        if _stack is None:
            _stack = set()
        if id(fi) in _stack:
            return {}                # cycle back-edge: ancestor's frame
            #                          already unions its own locks
        got = closure_memo.get(id(fi))
        if got is not None:
            return got
        _stack.add(id(fi))
        acc = dict(direct.get(id(fi), {}))
        for n in callgraph.walk_scope(fi.node):
            if isinstance(n, ast.Call):
                _dotted, targets = project.resolve_call(fi, n)
                for t in targets:
                    if id(t) in facts:       # stay inside the scan set
                        acc.update(closure(t, _stack))
        _stack.discard(id(fi))
        # memoize ONLY the outermost frame: a result computed while an
        # ancestor sits on the recursion stack is PARTIAL (its pruned
        # back-edges omit the ancestor's locks) — caching it would
        # permanently hide lock acquisitions behind any call cycle
        # (verified: a deadlock routed through a mutual-recursion pair
        # went unreported with the naive memo)
        if not _stack:
            closure_memo[id(fi)] = acc
        return acc

    closure_memo = {}

    # ---- ordering edges ----------------------------------------------
    # edge (A, B) -> list of (path, line, how) provenance
    edges = {}
    refs = {}
    for fi in funcs:                   # every acquired lock, with kind
        for ref, _ln, _held in facts[id(fi)][0]:
            refs.setdefault(ref.key, ref)

    def add_edge(a, b, fi, line, how):
        refs.setdefault(a.key, a)
        refs.setdefault(b.key, b)
        edges.setdefault((a.key, b.key), []).append(
            (fi.path, line, how))

    findings = []
    for fi in funcs:
        acquires, calls_held, _muts = facts[id(fi)]
        for ref, line, held_keys in acquires:
            for hk in held_keys:
                add_edge(refs.get(hk) or LockRef(hk, "lock", hk), ref,
                         fi, line, f"nested with in {fi.qualname}")
        for held, call in calls_held:
            _dotted, targets = project.resolve_call(fi, call)
            for t in targets:
                if id(t) not in facts:
                    continue
                for ref in closure(t).values():
                    for h in held:
                        add_edge(h, ref, fi, call.lineno,
                                 f"{fi.qualname} calls {t.qualname} "
                                 f"holding {h.display}")

    # ---- rule: self-reacquire ----------------------------------------
    for (a, b), prov in sorted(edges.items()):
        if a != b:
            continue
        ref = refs[a]
        if ref.reentrant:
            continue
        path, line, how = prov[0]
        key = f"locks:lock-reacquire:{a}"
        findings.append(Finding(
            check="locks", rule="lock-reacquire", key=key, path=path,
            line=line, func=how.split(" calls ")[0],
            message=f"non-reentrant lock {ref.display} may be acquired "
                    f"again while already held ({how}) — self-deadlock",
        ))

    # ---- rule: cycles (Tarjan SCC over the edge graph) ---------------
    graph = {}
    for (a, b) in edges:
        if a != b:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
    for scc in _sccs(graph):
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        prov = []
        for (a, b), pv in sorted(edges.items()):
            if a in scc and b in scc and a != b:
                p = pv[0]
                prov.append(f"{refs[a].display} -> {refs[b].display} "
                            f"({p[0]}:{p[1]}: {p[2]})")
        path, line = "", 0
        for (a, b), pv in sorted(edges.items()):
            if a in scc and b in scc and a != b:
                path, line = pv[0][0], pv[0][1]
                break
        key = "locks:lock-order-cycle:" + "<->".join(cyc)
        findings.append(Finding(
            check="locks", rule="lock-order-cycle", key=key, path=path,
            line=line, func=cyc[0],
            message="lock-ordering cycle among {" + ", ".join(cyc)
                    + "} — threads taking these in different orders can "
                    "deadlock", chain=tuple(prov)))

    # ---- rule: mixed-guard mutations ---------------------------------
    by_class = {}
    for fi in funcs:
        if fi.cls is None or not fi.cls.lock_attrs:
            continue
        method = fi.qualname.split(".")[-1] if fi.parent is None else None
        if method in (None, "__init__", "__new__", "__del__"):
            continue
        locked_by_convention = method.endswith("_locked")
        for attr, line, held_keys in facts[id(fi)][2]:
            if attr in fi.cls.lock_attrs:
                continue             # rebinding the lock itself
            own_held = any(hk.startswith(fi.cls.key + ".")
                           for hk in held_keys)
            rec = by_class.setdefault((fi.cls, attr),
                                      {"locked": [], "unlocked": []})
            if own_held or locked_by_convention:
                rec["locked"].append((fi, line))
            else:
                rec["unlocked"].append((fi, line))
    for (ci, attr), rec in sorted(by_class.items(),
                                  key=lambda kv: (kv[0][0].key, kv[0][1])):
        if not rec["locked"] or not rec["unlocked"]:
            continue
        fi, line = rec["unlocked"][0]
        lcount, ucount = len(rec["locked"]), len(rec["unlocked"])
        where = ", ".join(sorted({f.qualname for f, _l
                                  in rec["unlocked"]}))
        key = f"locks:lock-mixed-guard:{ci.key}.{attr}"
        findings.append(Finding(
            check="locks", rule="lock-mixed-guard", key=key, path=fi.path,
            line=line, func=fi.key,
            message=f"self.{attr} is mutated {lcount}x under "
                    f"{ci.qualname}'s lock but {ucount}x with no lock "
                    f"held ({where}) — guard every mutation or document "
                    "why the unguarded site is single-threaded"))
    return findings


def _sccs(graph):
    """Tarjan strongly-connected components, iterative."""
    index = {}
    low = {}
    on_stack = set()
    stack = []
    out = []
    counter = [0]

    def strongconnect(v0):
        work = [(v0, iter(sorted(graph.get(v0, ()))))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on_stack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                out.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return out
