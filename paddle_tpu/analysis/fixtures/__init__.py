"""Seeded-violation fixtures: the analyzer's reverse gates.

Each module here contains KNOWN violations — one per rule — that
tests/test_analysis.py proves the analyzer catches (non-zero exit,
every seeded rule id present).  A gate that cannot fail is no gate:
this mirrors the perf/analytic.py discipline where every structural
detector is also run against a twin that must TRIP it.

These modules are PARSED by the analyzer, never imported by runtime
code, and live outside the lock pass's default scan set — the
violations are invisible to the real gate unless a test points the
analyzer at them (``--root`` / ``--lock-paths``).
"""
