"""retrace-hazard reverse-gate fixture: one seeded violation per rule,
in a fake jitted step whose every parameter is data (the --root CLI
path treats all params as data).

    python -m paddle_tpu.analysis --check retrace --no-baseline \
        --root paddle_tpu.analysis.fixtures.retrace_hazards:hazard_step

``branchy_step``/``masked_step`` double as the RUNTIME confirmation
pair (tests/test_analysis.py): the statically-flagged shape of
``branchy_step`` really does retrace per value when the varying input
rides as a static arg, while ``masked_step`` — same computation, the
variation fed as data — warms up in one trace and never retraces
(testing/trace.forbid_retrace pins both).
"""


def hazard_step(params, tokens, positions, lengths):
    acc = tokens
    if positions[0] > 0:                     # V: retrace-data-branch (if)
        acc = acc + 1
    while lengths[0]:                        # V: retrace-data-branch (while)
        break
    n = int(tokens[0])                       # V: retrace-host-sync (int)
    p = positions.item()                     # V: retrace-host-sync (.item)
    key = f"bucket_{positions[0]}"           # V: retrace-shape-key
    for b in {8, 16, 32}:                    # V: retrace-unordered-iter
        acc = acc * 1
    if tokens[1] in (0, 1):                  # V: data-branch — a tainted
        acc = acc + 1                        # MEMBER is a value compare,
        #                                      not a structure probe
    return _hazard_helper(params, acc), (n, p, key)


def _hazard_helper(params, x):
    """Transitive taint: ``x`` arrives tainted from the root — the
    branch here must be found through the call graph."""
    if x[0] == 0:                            # V: data-branch (transitive)
        return x
    return x + 1


def clean_step(params, tokens, positions, lengths):
    """The control: variation handled as data / laundered statically —
    the retrace pass must report NOTHING when rooted here."""
    t = tokens.shape[0]                      # .shape launders
    if t > 1:                                # static branch: fine
        tokens = tokens + 0
    if positions is None:                    # identity test launders
        return tokens
    if "ks" in params:                       # CONTAINER-side membership:
        pass                                 # pytree structure is static
    return tokens * (positions >= 0)         # masked, not branched


# --- runtime-confirmation pair (see module docstring) -----------------

def branchy_step(x, n):
    """``n`` should be data; branching on it forces it static -> one
    compiled program PER VALUE.  The static pass flags the ``if``; the
    runtime test proves the retrace with jit(static_argnums=(1,))."""
    if n > 0:                                # V: retrace-data-branch
        return x * 2.0
    return x


def masked_step(x, keep):
    """The fixed twin: the same choice fed as a data mask — one trace,
    zero retraces across every value of ``keep``."""
    return x * 2.0 * keep + x * (1.0 - keep)
