"""jit-purity reverse-gate fixture: every forbidden namespace, reached
from one fake "jitted step" root (``bad_step``) — some directly, one
through a helper to prove the call-graph walk is transitive.

NEVER import this from runtime code; the analyzer only parses it.
Run the gate against it with:

    python -m paddle_tpu.analysis --check jit --no-baseline \
        --root paddle_tpu.analysis.fixtures.jit_impure:bad_step
"""

import random
import threading
import time

from paddle_tpu.obs import trace as _obs_trace
from paddle_tpu.resilience import faults as _faults
from paddle_tpu.serving import metrics as _metrics
from paddle_tpu.utils import logging as _logging
from paddle_tpu.utils.flags import FLAGS


def bad_step(params, tokens):
    """One seeded violation per jit-purity rule."""
    t0 = time.perf_counter()                 # V: time.*
    jitter = random.random()                 # V: random.*
    tid = threading.get_ident()              # V: threading.*
    _faults.hit("fixture.step")              # V: resilience.faults
    _metrics.ServingMetrics()                # V: serving.metrics
    _obs_trace.enable()                      # V: obs.*
    _logging.get_logger("fixture")           # V: utils.logging
    slots = FLAGS.serving_gen_slots          # V: non-trace-time FLAGS read
    return _impure_helper(params, tokens), (t0, jitter, tid, slots)


def _impure_helper(params, tokens):
    """Transitive reach: the violation sits one call away from the
    root — a walk that only checks the root body misses it."""
    time.sleep(0)                            # V: time.* (transitive)
    return tokens


def clean_step(params, tokens):
    """The control: fully pure — the jit pass must report NOTHING when
    rooted here (tests pin both directions)."""
    return tokens


# --- regression: qualname-sharing variants (review finding) -----------
# Like DecodeEngine's four layout _step_fn closures, both defs below
# share ONE qualname; the violation lives only in the SECOND, so a
# visited-set keyed on qualname alone would silently skip it.

if bool(int("0")):                           # parsed, branch irrelevant
    def variant_step(params, tokens):
        return tokens
else:
    def variant_step(params, tokens):
        time.sleep(0)                        # V: only in variant #2
        return tokens
