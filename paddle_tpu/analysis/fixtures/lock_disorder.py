"""lock-order reverse-gate fixture: a seeded ordering cycle, a seeded
self-deadlock, and a seeded mixed-guard mutation.

The wiring below (mutually-constructing classes) is nonsense at
runtime — it exists to be PARSED: the analyzer's constructor-typed
attribute inference resolves ``self._peer.poke()`` to the class whose
lock it takes.  The lock pass only sees this file when a test passes
``--lock-paths paddle_tpu/analysis/fixtures/lock_disorder.py``.
"""

import threading


class LockA:
    def __init__(self):
        self._lock = threading.Lock()
        self._peer = LockB()

    def forward(self):
        with self._lock:                    # holds A...
            self._peer.poke()               # ...acquires B: edge A -> B


class LockB:
    def __init__(self):
        self._lock = threading.Lock()
        self._back = LockA()                # parsed, never run

    def poke(self):
        with self._lock:
            pass

    def reverse(self):
        with self._lock:                    # holds B...
            self._back.forward()            # ...acquires A: edge B -> A
            # V: lock-order-cycle {LockA._lock, LockB._lock}


class Reacquirer:
    def __init__(self):
        self._lock = threading.Lock()       # NOT an RLock

    def outer(self):
        with self._lock:
            self.inner()                    # V: lock-reacquire

    def inner(self):
        with self._lock:
            pass


class MixedGuard:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def locked_inc(self):
        with self._lock:
            self.count += 1                 # guarded...

    def racy_inc(self):
        self.count += 1                     # V: lock-mixed-guard

    def _bump_locked(self):
        self.count += 1                     # *_locked convention: guarded


# --- regression: an acquisition hidden behind a CALL CYCLE -------------
# A naive closure memo caches the partial result computed while an
# ancestor is on the recursion stack (the a<->b cycle), permanently
# hiding _la from every later caller — the driver below forces that
# poisoned-order computation first, and the H->X ordering cycle through
# the hidden edge must STILL be reported (review finding, fixed in
# locks.py: only outermost closure frames are memoized).

class CycleInner:
    def __init__(self):
        self._la = threading.Lock()

    def a(self):
        with self._la:
            pass
        self.b()                            # a -> b

    def b(self):
        self.a()                            # b -> a: the back edge


class CycleDriverEarly:
    def __init__(self):
        self._ld = threading.Lock()
        self._inner = CycleInner()

    def d(self):
        with self._ld:
            self._inner.a()                 # forces closure(a) FIRST —
            #                                 the memo-poisoning order


class CycleHolderH:
    def __init__(self):
        self._lh = threading.Lock()
        self._inner = CycleInner()

    def h(self):
        with self._lh:
            self._inner.b()                 # _lh -> _la THROUGH the cycle


class CycleHolderX:
    def __init__(self):
        self._inner = CycleInner()
        self._hold = CycleHolderH()

    def x(self):
        with self._inner._la:               # _la -> _lh: closes the
            with self._hold._lh:            # V: lock-order-cycle
                pass
