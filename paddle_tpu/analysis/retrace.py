"""retrace-hazard pass: every serving/training step is 1-trace/0-retrace.

Inside the jitted roots (analysis/roots.py) all variation must be DATA.
This pass taints each root's data arguments (everything not declared
``static_args``) and propagates forward through assignments, subscripts
and attribute access; trace-static constructs LAUNDER the taint
(``.shape``/``.dtype``/``.ndim``, ``len()``, ``isinstance()``,
``is``/``is not``, ``in``/``not in`` — pytree STRUCTURE is static even
when leaf values are tracers).  Call results are untainted (optimistic,
like callgraph resolution), but calls into project functions propagate
the taint INTO the callee's matching parameters, so a hazard buried two
helpers deep under a data argument is still found.

Rules (docs/analysis.md):
  retrace-data-branch    ``if``/``while``/ternary/``assert`` on a
                         tainted value — Python control flow on a
                         tracer either crashes or bakes one branch in
                         (and shape-dependent variants retrace per
                         value)
  retrace-host-sync      ``.item()``/``.tolist()`` anywhere, or
                         ``int()``/``float()``/``bool()``/
                         ``np.asarray()`` on a tainted value — a
                         device sync inside the traced body
  retrace-unordered-iter iteration over a ``set`` — dict/pytree order
                         is insertion-stable, set order is not; a
                         traced program must not depend on it
  retrace-shape-key      f-string interpolating a tainted value —
                         the "shape key built from non-static args"
                         cache-key bug class
"""

import ast

from paddle_tpu.analysis import callgraph
from paddle_tpu.analysis.baseline import Finding

STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "aval",
                "sharding", "names"}
SYNC_METHODS = {"item", "tolist"}
SYNC_BUILTINS = {"int", "float", "bool"}
SYNC_DOTTED = {"numpy.asarray", "numpy.array"}
MAX_DEPTH = 20


class _Pass:
    def __init__(self, project):
        self.project = project
        self.findings = []
        self.reported = set()
        self.memo = set()

    # ------------------------------------------------------------ plumbing

    def _emit(self, rule, fi, node, detail, message, chain):
        key = f"retrace:{rule}:{fi.module.name}:{fi.qualname}:{detail}"
        if key in self.reported:
            return
        self.reported.add(key)
        self.findings.append(Finding(
            check="retrace", rule=rule, key=key, path=fi.path,
            line=node.lineno, func=fi.key, message=message, chain=chain))

    @staticmethod
    def _tainted_names(expr, env):
        names = sorted({n.id for n in ast.walk(expr)
                        if isinstance(n, ast.Name) and env.get(n.id)})
        return ",".join(names) or "<expr>"

    # ------------------------------------------------------- function body

    def analyze(self, fi, tainted_params, chain=(), depth=0):
        key = (fi.module.name, fi.qualname, fi.line,
               frozenset(tainted_params))
        if key in self.memo or depth > MAX_DEPTH:
            return
        self.memo.add(key)
        chain = chain + (fi.key,)
        env = {p: (p in tainted_params) for p in fi.params()}
        # two passes: loop-carried taint settles, the reported-set
        # dedupes re-emitted findings
        for _ in range(2):
            self._visit_body(fi, fi.node.body, env, chain, depth)

    def _visit_body(self, fi, body, env, chain, depth):
        for st in body:
            self._visit_stmt(fi, st, env, chain, depth)

    def _visit_stmt(self, fi, st, env, chain, depth):
        p = self.project
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: closure vars keep their taint, own params are
            # untainted HERE — a call site with tainted actuals
            # propagates through the normal interprocedural path
            inner = dict(env)
            child = next((c for c in fi.children
                          if c.node is st), None)
            scope = child if child is not None else fi
            for prm in ([a.arg for a in st.args.posonlyargs
                         + st.args.args + st.args.kwonlyargs]
                        + ([st.args.vararg.arg] if st.args.vararg else [])
                        + ([st.args.kwarg.arg] if st.args.kwarg else [])):
                inner[prm] = False
            self._visit_body(scope, st.body, inner, chain, depth)
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, (ast.Assign, ast.AnnAssign)):
            value = st.value
            if value is None:
                return
            t = self._expr(fi, value, env, chain, depth)
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            for tgt in targets:
                self._bind(tgt, t, env)
            return
        if isinstance(st, ast.AugAssign):
            t = self._expr(fi, st.value, env, chain, depth)
            if isinstance(st.target, ast.Name):
                env[st.target.id] = env.get(st.target.id, False) or t
            return
        if isinstance(st, ast.If):
            # the repo-idiomatic concrete-only guard:
            #   if isinstance(x, jax.core.Tracer): return
            # launders x for the code below — inside a trace the body
            # returns before anything concrete-only runs
            guarded = self._tracer_guard(st)
            if guarded is not None:
                self._visit_body(fi, st.body, env, chain, depth)
                env[guarded] = False
                self._visit_body(fi, st.orelse, env, chain, depth)
                return
        if isinstance(st, (ast.If, ast.While)):
            t = self._expr(fi, st.test, env, chain, depth)
            if t:
                kind = "if" if isinstance(st, ast.If) else "while"
                detail = f"{kind}:{self._tainted_names(st.test, env)}"
                self._emit(
                    "retrace-data-branch", fi, st, detail,
                    f"Python `{kind}` on runtime value(s) "
                    f"{self._tainted_names(st.test, env)} — branch on "
                    "data must be lax.cond/where or fed as data",
                    chain)
            self._visit_body(fi, st.body, env, chain, depth)
            self._visit_body(fi, st.orelse, env, chain, depth)
            return
        if isinstance(st, ast.Assert):
            if self._expr(fi, st.test, env, chain, depth):
                detail = f"assert:{self._tainted_names(st.test, env)}"
                self._emit(
                    "retrace-data-branch", fi, st, detail,
                    "assert on runtime value(s) "
                    f"{self._tainted_names(st.test, env)} inside a "
                    "jitted step — a tracer assert concretizes",
                    chain)
            return
        if isinstance(st, ast.For):
            self._check_unordered(fi, st.iter, env, chain)
            t = self._expr(fi, st.iter, env, chain, depth)
            self._bind(st.target, t, env)
            self._visit_body(fi, st.body, env, chain, depth)
            self._visit_body(fi, st.orelse, env, chain, depth)
            return
        if isinstance(st, ast.With):
            for item in st.items:
                self._expr(fi, item.context_expr, env, chain, depth)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, False, env)
            self._visit_body(fi, st.body, env, chain, depth)
            return
        if isinstance(st, ast.Try):
            self._visit_body(fi, st.body, env, chain, depth)
            for h in st.handlers:
                self._visit_body(fi, h.body, env, chain, depth)
            self._visit_body(fi, st.orelse, env, chain, depth)
            self._visit_body(fi, st.finalbody, env, chain, depth)
            return
        if isinstance(st, (ast.Return, ast.Expr, ast.Raise, ast.Delete)):
            for v in ast.iter_child_nodes(st):
                if isinstance(v, ast.expr):
                    self._expr(fi, v, env, chain, depth)
            return
        # Pass/Break/Continue/Global/Nonlocal/Import: nothing to do

    @staticmethod
    def _tracer_guard(st):
        """``if isinstance(NAME, ...Tracer): return/raise`` -> NAME."""
        t = st.test
        if not (isinstance(t, ast.Call) and isinstance(t.func, ast.Name)
                and t.func.id == "isinstance" and len(t.args) == 2
                and isinstance(t.args[0], ast.Name)):
            return None
        cls = t.args[1]
        name = cls.attr if isinstance(cls, ast.Attribute) else \
            (cls.id if isinstance(cls, ast.Name) else "")
        if not str(name).endswith("Tracer"):
            return None
        if st.body and isinstance(st.body[-1], (ast.Return, ast.Raise)):
            return t.args[0].id
        return None

    @staticmethod
    def _bind(target, taint, env):
        if isinstance(target, ast.Name):
            env[target.id] = taint
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                _Pass._bind(e, taint, env)
        elif isinstance(target, ast.Starred):
            _Pass._bind(target.value, taint, env)
        # Subscript/Attribute targets: container mutation, no binding

    # --------------------------------------------------------- expressions

    def _check_unordered(self, fi, it, env, chain):
        bad = isinstance(it, ast.Set)
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("set", "frozenset"):
            bad = True
        if bad:
            self._emit(
                "retrace-unordered-iter", fi, it, "set-iteration",
                "iteration over a set inside a jitted step — set order "
                "is not deterministic across processes; sort it or use "
                "a dict/list", chain)

    def _expr(self, fi, e, env, chain, depth):
        if e is None or isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Name):
            return env.get(e.id, False)
        if isinstance(e, ast.Attribute):
            base = self._expr(fi, e.value, env, chain, depth)
            return False if e.attr in STATIC_ATTRS else base
        if isinstance(e, ast.Subscript):
            return (self._expr(fi, e.value, env, chain, depth)
                    or self._expr(fi, e.slice, env, chain, depth))
        if isinstance(e, ast.Call):
            return self._call(fi, e, env, chain, depth)
        if isinstance(e, (ast.BinOp,)):
            return (self._expr(fi, e.left, env, chain, depth)
                    | self._expr(fi, e.right, env, chain, depth))
        if isinstance(e, ast.UnaryOp):
            return self._expr(fi, e.operand, env, chain, depth)
        if isinstance(e, ast.BoolOp):
            return any([self._expr(fi, v, env, chain, depth)
                        for v in e.values])
        if isinstance(e, ast.Compare):
            left_t = self._expr(fi, e.left, env, chain, depth)
            comp_ts = [self._expr(fi, v, env, chain, depth)
                       for v in e.comparators]
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False          # identity: static at trace time
            if all(isinstance(op, (ast.In, ast.NotIn)) for op in e.ops):
                # membership launders the CONTAINER side only (pytree
                # structure is static) — a tainted MEMBER (`tokens[0]
                # in (0, 1)`) is a value comparison and stays tainted
                # (review finding)
                return left_t
            return left_t or any(comp_ts)
        if isinstance(e, ast.IfExp):
            if self._expr(fi, e.test, env, chain, depth):
                detail = f"ifexp:{self._tainted_names(e.test, env)}"
                self._emit(
                    "retrace-data-branch", fi, e, detail,
                    "ternary on runtime value(s) "
                    f"{self._tainted_names(e.test, env)} — use "
                    "jnp.where/lax.cond", chain)
            return (self._expr(fi, e.body, env, chain, depth)
                    | self._expr(fi, e.orelse, env, chain, depth))
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any([self._expr(fi, v, env, chain, depth)
                        for v in e.elts])
        if isinstance(e, ast.Dict):
            return any([self._expr(fi, v, env, chain, depth)
                        for v in list(e.keys) + list(e.values)
                        if v is not None])
        if isinstance(e, ast.JoinedStr):
            for part in e.values:
                if isinstance(part, ast.FormattedValue) \
                        and self._expr(fi, part.value, env, chain, depth):
                    detail = "fstring:" \
                        + self._tainted_names(part.value, env)
                    self._emit(
                        "retrace-shape-key", fi, part, detail,
                        "f-string interpolates runtime value(s) "
                        f"{self._tainted_names(part.value, env)} — a "
                        "key/label built from non-static args retraces "
                        "per value", chain)
            return False
        if isinstance(e, ast.Starred):
            return self._expr(fi, e.value, env, chain, depth)
        if isinstance(e, ast.NamedExpr):
            t = self._expr(fi, e.value, env, chain, depth)
            self._bind(e.target, t, env)
            return t
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            inner = dict(env)
            for gen in e.generators:
                self._check_unordered(fi, gen.iter, env, chain)
                t = self._expr(fi, gen.iter, inner, chain, depth)
                self._bind(gen.target, t, inner)
                for cond in gen.ifs:
                    self._expr(fi, cond, inner, chain, depth)
            if isinstance(e, ast.DictComp):
                return (self._expr(fi, e.key, inner, chain, depth)
                        | self._expr(fi, e.value, inner, chain, depth))
            return self._expr(fi, e.elt, inner, chain, depth)
        if isinstance(e, ast.Lambda):
            inner = dict(env)
            for prm in ([a.arg for a in e.args.posonlyargs + e.args.args
                         + e.args.kwonlyargs]
                        + ([e.args.vararg.arg] if e.args.vararg else [])
                        + ([e.args.kwarg.arg] if e.args.kwarg else [])):
                inner[prm] = False
            self._expr(fi, e.body, inner, chain, depth)
            return False
        if isinstance(e, ast.Await):
            return self._expr(fi, e.value, env, chain, depth)
        return False

    def _call(self, fi, call, env, chain, depth):
        # sink: .item()/.tolist() — a device sync, tainted base or not
        # (an optimistically-untainted jnp result still syncs)
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in SYNC_METHODS and not call.args:
            self._expr(fi, call.func.value, env, chain, depth)
            self._emit(
                "retrace-host-sync", fi, call, f"{call.func.attr}()",
                f".{call.func.attr}() inside a jitted step forces a "
                "host sync — keep the value on device or feed it as "
                "data", chain)
            return False
        arg_taints = [self._expr(fi, a, env, chain, depth)
                      for a in call.args]
        kw_taints = {kw.arg: self._expr(fi, kw.value, env, chain, depth)
                     for kw in call.keywords}
        # sink: int()/float()/bool()/np.asarray() on a tainted value
        if isinstance(call.func, ast.Name) \
                and call.func.id in SYNC_BUILTINS and any(arg_taints):
            self._emit(
                "retrace-host-sync", fi, call,
                f"{call.func.id}:{self._tainted_names(call, env)}",
                f"{call.func.id}() on runtime value(s) "
                f"{self._tainted_names(call, env)} concretizes a "
                "tracer — feed it as data instead", chain)
            return False
        dotted, targets = self.project.resolve_call(fi, call)
        if dotted in SYNC_DOTTED and (any(arg_taints)
                                      or any(kw_taints.values())):
            self._emit(
                "retrace-host-sync", fi, call,
                f"{dotted}:{self._tainted_names(call, env)}",
                f"{dotted}() on runtime value(s) "
                f"{self._tainted_names(call, env)} pulls the array to "
                "host", chain)
            return False
        # interprocedural: push taint into project callees' params
        if targets and (any(arg_taints) or any(kw_taints.values())):
            for t in targets:
                params = t.params()
                formal = params[1:] if (t.cls is not None
                                        and params[:1] == ["self"]) \
                    else list(params)
                tainted = set()
                for i, taint in enumerate(arg_taints):
                    if taint and i < len(formal):
                        tainted.add(formal[i])
                for name, taint in kw_taints.items():
                    if taint and name in formal:
                        tainted.add(name)
                if tainted:
                    self.analyze(t, frozenset(tainted), chain, depth + 1)
        return False


def run(project, roots):
    """-> [Finding] for the retrace-hazard pass over the given roots.
    Every parameter not named in a root's ``static_args`` (and not
    ``self``) is data.  A root ref that does not resolve is itself a
    finding — a retrace-only invocation must never go vacuously green
    because the registry drifted (purity.run reports the same drift
    under its own rule for jit runs)."""
    p = _Pass(project)
    for r in roots:
        infos = project.function(r.ref)
        if not infos:
            p.findings.append(Finding(
                check="retrace", rule="retrace-root-missing",
                key=f"retrace:retrace-root-missing:{r.ref}",
                path="paddle_tpu/analysis/roots.py", line=1, func=r.ref,
                message=f"registered jit root {r.ref!r} does not "
                        "resolve in the AST index — the registry "
                        "drifted from the code"))
            continue
        for fi in infos:
            static = set(getattr(r, "static_args", ()) or ())
            data = frozenset(prm for prm in fi.params()
                             if prm not in static and prm != "self")
            p.analyze(fi, data)
    return p.findings
