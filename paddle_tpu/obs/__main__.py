"""Trace smoke CLI — healthy_window.sh phase 12.

    python -m paddle_tpu.obs --smoke [--chrome-out PATH]

End-to-end proof of the tracing subsystem over the REAL fleet topology
(docs/observability.md): two tiny demo replicas (tracing enabled via
``--obs-trace``) behind an in-process router (tracing enabled), paced
concurrent streaming ``/v1/generate`` clients, then ``kill -9`` one
replica once every stream is visibly mid-decode.  The checks:

* every stream still finishes (the router's continuation failover);
* ONE trace_id stitches router -> the KILLED replica (its spans come
  from a ``/debug/traces`` snapshot taken while it was alive — the ring
  dies with the process) -> the failover continuation on the surviving
  replica (a ``slot`` span with ``mode="continuation"``);
* the merged Chrome trace-event dump ``json.load``s and names all three
  processes (router + both replicas).

ONE JSON line on stdout; nonzero rc on any failed check (the same
contract as the serving/chaos/fleet smokes).
"""

import argparse
import http.client
import json
import signal
import sys
import tempfile
import threading
import time
import urllib.request

from paddle_tpu.obs import trace
from paddle_tpu.utils.logging import logger


def _get_json(url, timeout=20):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _merge_spans(snapshots):
    """Merge span lists from several /debug/traces payloads, newest
    completed version of each span_id winning (a pre-kill snapshot and a
    post-run snapshot overlap for the surviving replica)."""
    by_id = {}
    for spans in snapshots:
        for s in spans:
            cur = by_id.get(s["span_id"])
            if cur is None or (cur["t_end"] is None
                               and s["t_end"] is not None):
                by_id[s["span_id"]] = s
    return list(by_id.values())


def _smoke(chrome_out=None):
    from paddle_tpu.serving.fleet import ReplicaSupervisor
    from paddle_tpu.serving.router import Router

    errs = []
    out = {"metric": "trace smoke (cross-process request tracing, "
                     "kill -9 mid-stream)",
           "vs_baseline": None}
    n_clients, n_tokens = 4, 24
    # the injected decode-step hang paces tokens (~25ms each) so the
    # kill reliably lands MID-stream, exactly like the fleet smoke
    extra = ["--gen-slots", "4", "--gen-max-len", "64",
             "--gen-prefill-buckets", "8,16",
             "--gen-max-tokens", str(n_tokens),
             "--obs-trace", "1",
             "--fault-spec",
             "serving.decode_step:every=1,action=hang,hang_s=0.025"]
    trace.enable(sample=1.0, capacity=4096, process="router")
    sup = ReplicaSupervisor(n_replicas=2, extra_args=extra,
                            backoff_base_s=0.3, seed=0,
                            name="trace_smoke")
    router = Router(supervisor=sup, poll_interval_s=0.1,
                    eject_threshold=2, eject_cooldown_s=1.0,
                    retry_budget=3, name="router_trace_smoke")
    httpd = None
    checks = []
    try:
        sup.start()
        if not sup.wait_ready(timeout=240):
            raise RuntimeError("replicas never became ready")
        httpd = router.start(port=0)
        deadline = time.monotonic() + 30
        while not router.ready() and time.monotonic() < deadline:
            time.sleep(0.05)
        import numpy as np
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, 256, 3 + 2 * i).tolist()
                   for i in range(n_clients)]
        results = [None] * n_clients
        first_token = threading.Barrier(n_clients + 1, timeout=120)

        def hit(i):
            armed = True
            try:
                conn = http.client.HTTPConnection("127.0.0.1", httpd.port,
                                                  timeout=120)
                conn.request(
                    "POST", "/v1/generate",
                    json.dumps({"prompt": prompts[i],
                                "max_tokens": n_tokens,
                                "stream": True}).encode(),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                toks, done = [], None
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    rec = json.loads(line)
                    if "token" in rec:
                        toks.append(rec["token"])
                        if armed and len(toks) >= 2:
                            armed = False
                            first_token.wait()
                    if rec.get("done"):
                        done = rec
                        break
                conn.close()
                if armed:
                    first_token.wait()
                results[i] = {"tokens": toks, "done": done}
            except Exception as e:      # noqa: BLE001
                errs.append(f"client {i}: {type(e).__name__}: {e}")
                if armed:
                    try:
                        first_token.wait()
                    except threading.BrokenBarrierError:
                        pass

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        first_token.wait()      # every stream is mid-decode now

        # the victim's span ring dies with its process: snapshot every
        # replica's /debug/traces BEFORE the kill (in-flight spans show
        # with t_end null — the victim still holds its streams' slots)
        pre = {}
        for rid, url in sup.endpoints():
            try:
                pre[rid] = _get_json(f"{url}/debug/traces")
            except Exception as e:      # noqa: BLE001
                errs.append(f"pre-kill /debug/traces {rid}: {e}")
        sup.kill("r0", signal.SIGKILL)
        out["victim_killed"] = True

        for t in threads:
            t.join(180)
        streams_ok = sum(1 for r in results
                         if r is not None and r["done"])
        out["streams_ok"] = streams_ok

        # post-run snapshots: router (in-process) + whoever answers now
        snapshots = [trace.debug_payload()["spans"]]
        processes_seen = {"router"}
        for payload in pre.values():
            snapshots.append(payload.get("spans", []))
            if payload.get("process"):
                processes_seen.add(payload["process"])
        for rid, url in sup.endpoints():
            try:
                payload = _get_json(f"{url}/debug/traces")
            except Exception:   # noqa: BLE001 — a replica mid-restart
                continue
            snapshots.append(payload.get("spans", []))
            if payload.get("process"):
                processes_seen.add(payload["process"])
        merged = _merge_spans(snapshots)
        out["spans_merged"] = len(merged)

        # a stream that failed over mid-decode: its router root span
        # carries the midstream_failover event; the same trace_id must
        # show spans from the router AND (at least) both original
        # replicas — the kill victim's half from the pre-kill snapshot
        failover_tids = {
            s["trace_id"] for s in merged
            if s["process"] == "router" and s["name"] == "router.request"
            and any(e["name"] == "midstream_failover"
                    for e in s.get("events", ()))}
        out["failover_traces"] = len(failover_tids)
        stitched = False
        stitched_detail = {}
        for tid in failover_tids:
            tspans = [s for s in merged if s["trace_id"] == tid]
            procs = {s["process"] for s in tspans}
            router_names = {s["name"] for s in tspans
                            if s["process"] == "router"}
            # the FIRST replica held the original seat (a slot span with
            # mode="prefill", captured pre-kill); the survivor holds the
            # failover seat (mode="continuation")
            first_proc = next((s["process"] for s in tspans
                               if s["name"] == "slot"
                               and s["attrs"].get("mode") == "prefill"),
                              None)
            cont_proc = next((s["process"] for s in tspans
                              if s["name"] == "slot"
                              and s["attrs"].get("mode")
                              == "continuation"), None)
            first_names = {s["name"] for s in tspans
                           if s["process"] == first_proc}
            if (len(procs) >= 3 and first_proc and cont_proc
                    and first_proc != cont_proc
                    and {"router.request", "router.dispatch",
                         "router.leg"} <= router_names
                    and {"server.request", "gen.queue_wait",
                         "slot"} <= first_names):
                stitched = True
                stitched_detail = {
                    "trace_id": tid,
                    "processes": sorted(procs),
                    "n_spans": len(tspans),
                }
                break
        out["stitched"] = bool(stitched)
        out.update(stitched_detail)

        # the merged Chrome dump must parse and name all three processes
        if chrome_out is None:
            with tempfile.NamedTemporaryFile(
                    prefix="trace_smoke_", suffix=".json",
                    delete=False) as f:
                chrome_out = f.name
        trace.dump_chrome_trace(chrome_out, merged)
        with open(chrome_out) as f:
            chrome = json.load(f)
        proc_names = {e["args"]["name"] for e in chrome["traceEvents"]
                      if e.get("ph") == "M"
                      and e.get("name") == "process_name"}
        out["chrome_out"] = chrome_out
        out["chrome_parses"] = True
        out["chrome_processes"] = len(proc_names)
        checks = [
            streams_ok == n_clients,
            bool(stitched),
            len(proc_names) >= 3,
            bool(chrome["traceEvents"]),
        ]
    except Exception as e:      # noqa: BLE001 — a harness failure must
        errs.append(f"smoke: {type(e).__name__}: {e}")
        checks = [False]
    finally:
        try:
            router.close()
        finally:
            sup.stop()
    out["value"] = sum(bool(c) for c in checks)
    out["unit"] = f"checks_ok/{len(checks)}"
    if errs:
        out["errors"] = errs[:5]
    print(json.dumps(out), flush=True)
    return 0 if all(checks) else 2


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.obs",
        description="trace smoke: cross-process request tracing over a "
                    "2-replica fleet with a kill -9 mid-stream failover")
    ap.add_argument("--smoke", action="store_true",
                    help="run the trace smoke, print one JSON line, exit")
    ap.add_argument("--chrome-out",
                    help="where the merged Chrome trace-event JSON is "
                         "written (default: a temp file)")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke(chrome_out=args.chrome_out)
    ap.error("pass --smoke")


if __name__ == "__main__":
    logger.setLevel("WARNING")
    sys.exit(main())
