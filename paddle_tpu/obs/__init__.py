"""Host-side observability: end-to-end request tracing (obs/trace.py).

The third pillar next to aggregate metrics (serving/metrics.py,
``/metrics``) and device profiling (utils/profiler.py): per-request
spans, propagated across the router/replica fleet, exported as Chrome
trace-event JSON.  See docs/observability.md.
"""

from paddle_tpu.obs.trace import (NULL, Span, Tracer, chrome_trace,
                                  current, current_trace_id,
                                  debug_payload, disable,
                                  dump_chrome_trace, enable, enabled,
                                  extract, get_tracer, inject, instant,
                                  set_process, slowest, snapshot, span,
                                  start_span)

__all__ = [
    "NULL", "Span", "Tracer", "chrome_trace", "current",
    "current_trace_id", "debug_payload", "disable", "dump_chrome_trace",
    "enable", "enabled", "extract", "get_tracer", "inject", "instant",
    "set_process", "slowest", "snapshot", "span", "start_span",
]
