"""End-to-end request tracing: span recorder, context propagation,
Chrome-trace export (docs/observability.md).

The reference framework's only observability was aggregate host timers
(utils/Stat.h REGISTER_TIMER) and barrier skew stats; our rebuild added
aggregate metrics (serving/metrics.py) and device profiling
(utils/profiler.py).  None of those can show ONE request's journey —
after the serving tier grew a router, replica fleet, continuous-batching
slots, paged-KV preemption, and cross-replica mid-stream failover, a p99
TTFT regression is a needle in eight counters.  This module is the
Dapper-style third pillar: per-request SPANS, propagated across
processes, exported as Chrome trace-event JSON.

Discipline (shared with resilience/faults.py):

* strictly HOST-side — no hook ever sits inside a jit-traced body, so an
  enabled tracer changes no XLA program (``bench.py --analytic-diff``
  stays clean by construction) and can never cause a retrace;
* near-zero cost when disabled (the default): every hook is one global
  read plus an ``is None`` test returning the ``NULL`` span singleton —
  no allocation, no lock, no contextvar touch;
* deterministic head sampling keyed on a hash of the trace_id
  (``obs_trace_sample``): every process in a distributed request derives
  the SAME keep/drop verdict from the propagated id, so a sampled trace
  is complete or absent, never partial.

Core surface:

* ``enable(sample=, capacity=, process=)`` / ``disable()`` — install /
  remove the process-wide ``Tracer`` (a bounded ring of completed spans;
  the oldest fall off, a long-running server holds RECENT traces).
* ``span(name, **attrs)`` — context manager: starts a span parented to
  the context-local current span (or a fresh root), makes it current for
  the ``with`` body, records it on exit.
* ``start_span`` / ``Span.end`` — the explicit pair for ASYNC seams
  (queue waits, slot lifetimes, futures) where begin and end live on
  different threads; these never touch the context variable.
* ``extract(header)`` / ``inject(headers)`` — W3C-traceparent-style
  cross-process propagation (``00-<trace_id>-<span_id>-01``): the router
  injects on its upstream dispatches, the replica server extracts, and
  one trace_id stitches router, both replicas of a failover, and the
  slot timeline.
* ``snapshot()`` / ``debug_payload()`` — the ``/debug/traces`` JSON.
* ``chrome_trace(spans)`` / ``dump_chrome_trace(path, spans)`` — valid
  Chrome trace-event JSON (loadable in Perfetto): processes = router /
  replicas, tracks = decode slots.
* ``slowest(n)`` — trace_ids of the worst recent wall/TTFT requests, so
  the tail the percentiles report becomes a trace you can open.
"""

import collections
import contextvars
import json
import os
import threading
import time
import zlib

# the process-wide tracer; None (the default) makes every hook a no-op
_tracer = None

# context-local (trace_id, span_id) of the innermost active span() —
# per-thread AND per-async-context, so concurrent HTTP handler threads
# never cross their traces
_CTX = contextvars.ContextVar("paddle_tpu_trace_ctx", default=None)

_TRACEPARENT_VERSION = "00"


def new_trace_id():
    return os.urandom(16).hex()


def new_span_id():
    return os.urandom(8).hex()


def _hash01(trace_id):
    """trace_id -> [0, 1): the deterministic head-sampling key.  Every
    process hashing the same propagated id reaches the same verdict."""
    return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) / 2**32


class _NullSpan:
    """The disabled-path singleton: every method is a no-op and every
    derived id is empty.  Identity-comparable (``span is NULL``) so the
    strict-no-op test can pin that the disabled path allocates nothing."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    recording = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def event(self, name, **attrs):
        return self

    def end(self, **attrs):
        return self


NULL = _NullSpan()


class Span:
    """One timed operation.  ``recording=False`` spans (head-sampling
    drop) still carry ids — propagation and response echo stay coherent
    on unsampled traces — but never reach the ring."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t_start",
                 "t_end", "attrs", "events", "recording", "_token")

    def __init__(self, name, trace_id, parent_id, recording, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.t_start = time.time()
        self.t_end = None
        self.attrs = attrs
        self.events = []
        self.recording = recording
        self._token = None

    # ---- context-manager protocol: span() parents the with-body ----

    def __enter__(self):
        self._token = _CTX.set((self.trace_id, self.span_id))
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _CTX.reset(self._token)
            self._token = None
        if exc_type is not None:
            self.attrs["error"] = f"{exc_type.__name__}: {exc}"
        self.end()
        return False

    # ---- mutation (all no-ops on a non-recording span) ----

    def set(self, **attrs):
        if self.recording:
            self.attrs.update(attrs)
        return self

    def event(self, name, **attrs):
        """A timestamped point event inside this span (TTFT, a recovery
        re-prefill, a failover leg...)."""
        if self.recording:
            self.events.append({"t": time.time(), "name": name,
                                **({"attrs": attrs} if attrs else {})})
        return self

    def end(self, **attrs):
        if not self.recording:
            return self
        t = _tracer
        if t is None:                   # tracer torn down mid-flight
            self.t_end = self.t_end or time.time()
            return self
        # claim-the-end and ring insertion are ONE atomic section: the
        # async-seam contract allows double-end from different threads
        # (an owner racing a cleanup path), and a span must never reach
        # the ring twice
        with t._lock:
            if self.t_end is not None:  # idempotent (e.g. a request
                return self             # resolved through two paths)
            if attrs:
                self.attrs.update(attrs)
            self.t_end = time.time()
            t._active.pop(self.span_id, None)
            if len(t._done) == t._done.maxlen:
                t.dropped_total += 1
            t._done.append(self)
        return self

    def to_dict(self, process):
        # may run on the /debug/traces thread while the owning request
        # thread is still mutating an ACTIVE span.  dict(d)/list(l) are
        # single C-level copies (atomic under the GIL), event records are
        # appended whole and never mutated, and attrs values are
        # scalars — so the copy below is a coherent point-in-time view
        # without a per-span lock on the hot path.
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "process": process,
            "t_start": self.t_start,
            "t_end": self.t_end,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class Tracer:
    """Bounded ring buffer of completed spans + the live-span registry
    (in-flight spans show in snapshots with ``t_end: null`` — a replica
    about to be killed still shows the request it was serving)."""

    def __init__(self, sample=1.0, capacity=4096, process=None):
        if int(capacity) < 1:
            raise ValueError("obs_trace_ring must be >= 1")
        self.sample = float(sample)
        self.capacity = int(capacity)
        self.process = process or f"pid:{os.getpid()}"
        self._lock = threading.Lock()
        self._done = collections.deque(maxlen=self.capacity)
        self._active = {}
        self.started_total = 0
        self.dropped_total = 0      # ring overwrites (oldest span lost)

    def sampled(self, trace_id):
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return _hash01(trace_id) < self.sample

    def _start(self, span):
        with self._lock:
            self.started_total += 1
            self._active[span.span_id] = span

    def snapshot(self, include_active=True):
        """All held spans as dicts (completed ring + in-flight)."""
        with self._lock:
            spans = [s.to_dict(self.process) for s in self._done]
            if include_active:
                spans += [s.to_dict(self.process)
                          for s in self._active.values()]
        return spans

    def slowest(self, n=5):
        """The worst recent requests by wall time and by TTFT:
        ``{"wall": [...], "ttft": [...]}``, each entry carrying the
        trace_id — the percentiles' tail, openable as a trace."""
        with self._lock:
            roots = [s for s in self._done if s.attrs.get("root")]
        rows = []
        for s in roots:
            ttft = s.attrs.get("ttft_ms")
            if ttft is None:
                first = next((e for e in s.events
                              if e["name"] == "first_token"), None)
                if first is not None:
                    ttft = round((first["t"] - s.t_start) * 1e3, 3)
            rows.append({
                "trace_id": s.trace_id,
                "name": s.name,
                "route": s.attrs.get("route"),
                "t_start": s.t_start,
                "wall_ms": round((s.t_end - s.t_start) * 1e3, 3),
                "ttft_ms": ttft,
            })
        by_wall = sorted(rows, key=lambda r: -r["wall_ms"])[:n]
        by_ttft = sorted((r for r in rows if r["ttft_ms"] is not None),
                         key=lambda r: -r["ttft_ms"])[:n]
        return {"wall": by_wall, "ttft": by_ttft}


# ------------------------------------------------------------ module API


def enable(sample=None, capacity=None, process=None):
    """Install a process-wide ``Tracer`` (defaults from utils/flags.py
    ``obs_trace_*``); returns it.  Idempotent re-enable replaces the
    tracer (fresh ring)."""
    global _tracer
    if sample is None or capacity is None:
        from paddle_tpu.utils.flags import FLAGS
        if sample is None:
            sample = FLAGS.obs_trace_sample
        if capacity is None:
            capacity = FLAGS.obs_trace_ring
    _tracer = Tracer(sample=sample, capacity=capacity, process=process)
    return _tracer


def disable():
    global _tracer
    _tracer = None


def enabled():
    return _tracer is not None


def get_tracer():
    return _tracer


def set_process(name):
    """Rename the tracer's process label (a replica learns its bound
    port after enable())."""
    t = _tracer
    if t is not None:
        t.process = str(name)


def current():
    """The context-local (trace_id, span_id) pair, or None."""
    return _CTX.get()


def current_trace_id():
    ctx = _CTX.get()
    return ctx[0] if ctx else ""


def _make_span(name, ctx, new_trace, attrs):
    """Shared constructor behind span()/start_span().  The hot disabled
    path returns the NULL singleton before touching anything else."""
    t = _tracer
    if t is None:
        return NULL
    parent_id = None
    if ctx is None and not new_trace:
        ctx = _CTX.get()
    if ctx is not None:
        trace_id, parent_id = ctx
    else:
        trace_id = new_trace_id()
        attrs.setdefault("root", True)
    span = Span(name, trace_id, parent_id, t.sampled(trace_id), attrs)
    if span.recording:
        t._start(span)
    return span


def span(name, ctx=None, new_trace=False, **attrs):
    """Context-manager span: parents to ``ctx`` (an explicit
    ``(trace_id, span_id)``), else to the context-local current span,
    else starts a new root trace (``new_trace=True`` skips the ambient
    context and forces a fresh one).  The with-body sees it as current.
    An attr ``root=True`` marks a request root for ``slowest()``
    (auto-set when a fresh trace starts here)."""
    return _make_span(name, ctx, new_trace, attrs)


def start_span(name, ctx=None, **attrs):
    """Async-seam span: like ``span()`` but never touches the context
    variable — begin here, carry the object across threads/futures, and
    ``.end()`` it where the operation really finishes."""
    return _make_span(name, ctx, False, attrs)


def instant(name, ctx=None, **attrs):
    """Zero-duration marker span (a CoW fork, a watchdog trip).  Never
    counts as a request root for ``slowest()``."""
    attrs.setdefault("root", False)
    s = _make_span(name, ctx, False, attrs)
    s.end()
    return s


# ------------------------------------------------------------ propagation


def extract(header):
    """Parse a traceparent-style header into a ``(trace_id, span_id)``
    context, or None when absent/malformed (a malformed header starts a
    fresh trace rather than failing the request)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 3:
        return None
    _ver, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return trace_id, span_id


def inject(headers=None, ctx=None):
    """Add the traceparent header for ``ctx`` (default: the current
    context) into ``headers`` (created if None); returns the dict
    unchanged when there is nothing to propagate."""
    headers = headers if headers is not None else {}
    if ctx is None:
        ctx = _CTX.get()
    if ctx is not None:
        headers["traceparent"] = (f"{_TRACEPARENT_VERSION}-{ctx[0]}-"
                                  f"{ctx[1]}-01")
    return headers


# ------------------------------------------------------------ export


def snapshot(include_active=True):
    t = _tracer
    return t.snapshot(include_active) if t is not None else []


def slowest(n=5):
    t = _tracer
    return t.slowest(n) if t is not None else {"wall": [], "ttft": []}


def debug_payload(n_slowest=5):
    """The ``/debug/traces`` JSON body (server.py and router.py GET)."""
    t = _tracer
    if t is None:
        return {"enabled": False, "process": None, "spans": [],
                "slowest": {"wall": [], "ttft": []}}
    return {
        "enabled": True,
        "process": t.process,
        "sample": t.sample,
        "capacity": t.capacity,
        "started_total": t.started_total,
        "dropped_total": t.dropped_total,
        "spans": t.snapshot(),
        "slowest": t.slowest(n_slowest),
    }


def chrome_trace(spans=None):
    """Span dicts -> a Chrome trace-event JSON object (the
    ``chrome://tracing`` / Perfetto format): one "X" complete event per
    span, "i" instants for span events, and metadata naming processes
    (router / each replica) and tracks (decode slots).  ``spans`` may be
    a MERGED list from several processes' ``/debug/traces`` — that is
    the point: one file shows the whole fleet on one timeline."""
    if spans is None:
        spans = snapshot()
    pids = {}
    tid_names = {}          # (pid, tid) -> track name
    events = []
    for s in spans:
        proc = s.get("process") or "unknown"
        pid = pids.setdefault(proc, len(pids) + 1)
        slot = s.get("attrs", {}).get("slot")
        if slot is not None:
            tid = 100 + int(slot)
            tid_names[(pid, tid)] = f"slot {int(slot)}"
        else:
            tid = 1
            tid_names.setdefault((pid, tid), "host")
        t0 = s["t_start"]
        t1 = s["t_end"] if s["t_end"] is not None else t0
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"]}
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        args.update(s.get("attrs", {}))
        events.append({
            "name": s["name"], "cat": "obs", "ph": "X",
            "ts": round(t0 * 1e6, 3),
            "dur": round(max(0.0, t1 - t0) * 1e6, 3),
            "pid": pid, "tid": tid, "args": args,
        })
        for ev in s.get("events", ()):
            events.append({
                "name": ev["name"], "cat": "obs", "ph": "i", "s": "t",
                "ts": round(ev["t"] * 1e6, 3), "pid": pid, "tid": tid,
                "args": dict(ev.get("attrs", {}),
                             trace_id=s["trace_id"]),
            })
    meta = []
    for proc, pid in pids.items():
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": proc}})
    for (pid, tid), label in tid_names.items():
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": label}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def dump_chrome_trace(path, spans=None):
    """Write ``chrome_trace(spans)`` to ``path``; returns the object."""
    obj = chrome_trace(spans)
    with open(path, "w") as f:
        json.dump(obj, f)
    return obj
