"""CLI driver: the `paddle train|test|merge_model|version` surface
(reference trainer/TrainerMain.cpp:32-65 + scripts/submit_local.sh.in).

Usage:
  python -m paddle_tpu train --config my_config.py [--num_passes N]
       [--save_dir DIR] [--start_pass K] [--data_parallel N --model_parallel M]
  python -m paddle_tpu test  --config my_config.py --model_dir DIR
  python -m paddle_tpu merge_model --model_dir DIR --out model.npz
  python -m paddle_tpu version

The config file is a Python script defining `get_config()` returning a dict:
  {"cost": LayerOutput, "optimizer": optim.Optimizer,
   "train_reader": reader, "test_reader": reader (optional),
   "feeding": {name: InputType}, "batch_size": int (reader already batched)}
(reference --config=trainer_config.conf scripts, with config_args available
as CONFIG_ARGS in the script's namespace).
"""

import argparse
import os
import runpy
import sys


def _load_config(path, config_args):
    """Native configs define get_config(); reference-style v1 configs
    (`from paddle.trainer_config_helpers import *` + settings/outputs) run
    through the config compiler (paddle_tpu.compat) unchanged."""
    src = open(path).read()
    if "def get_config" in src:
        # fresh layer-name registry per invocation: a second cli.main()
        # in the same process (train then test) must mint the SAME layer
        # names, or loaded params won't match the rebuilt graph (the
        # compat path already resets inside parse_config)
        from paddle_tpu.layers.graph import reset_names
        reset_names()
        ns = runpy.run_path(path, init_globals={"CONFIG_ARGS": config_args})
        if "get_config" in ns:
            return ns["get_config"]()
    from paddle_tpu.compat import parse_config, config_to_runtime
    return config_to_runtime(parse_config(path, config_args))


def _resolve_feeder(feeding, seq_buckets=None, pad_batch=None):
    """feeding may be a DataFeeder, an input-types dict, or None.

    seq_buckets: allowed padded sequence lengths (XLA compiles one program
    per bucket instead of one per distinct batch shape — essential for
    variable-length data on TPU); pad_batch: fixed batch size."""
    from paddle_tpu.data.feeder import DataFeeder
    if isinstance(feeding, DataFeeder):
        return feeding
    if not feeding:
        return None
    return DataFeeder(feeding, bucket_bounds=seq_buckets,
                      pad_batch_to=pad_batch)


def _seq_buckets_arg(value):
    """argparse type for --seq_buckets: sorted positive ints."""
    try:
        bounds = sorted(int(b) for b in value.split(",") if b.strip())
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--seq_buckets wants comma-separated ints, got {value!r}")
    if not bounds or any(b < 1 for b in bounds):
        raise argparse.ArgumentTypeError(
            f"--seq_buckets wants positive lengths, got {value!r}")
    return bounds


def _feeder_from_args(args, cfg, allow_pad=True):
    """The job's DataFeeder honoring --seq_buckets/--pad_batch (jobs whose
    parsers don't register the flags fall back to plain resolution).

    allow_pad=False for the test job: batch padding duplicates the last
    sample, which would bias an unmasked test metric."""
    from paddle_tpu.data.feeder import DataFeeder
    from paddle_tpu.utils.logging import logger
    buckets = getattr(args, "seq_buckets", None)
    want_pad = getattr(args, "pad_batch", False) and allow_pad
    if isinstance(cfg.get("feeding"), DataFeeder):
        if buckets or want_pad:
            logger.warning(
                "--seq_buckets/--pad_batch ignored: the config supplies a "
                "ready-made DataFeeder; set bucket_bounds/pad_batch_to on "
                "it instead")
        return cfg["feeding"]
    pad = None
    if want_pad:
        pad = cfg.get("batch_size")
        if not pad:
            logger.warning(
                "--pad_batch ignored: the config declares no batch_size")
    if getattr(args, "pad_batch", False) and not allow_pad:
        logger.info("--pad_batch not applied to the test job (padding "
                    "duplicates samples, biasing the metric)")
    return _resolve_feeder(cfg.get("feeding"), seq_buckets=buckets,
                           pad_batch=pad)


def _resolve_prefetch(args):
    """--prefetch, defaulting to the FLAGS pair the reference shipped:
    async_load_data (DoubleBuffer on/off) × prefetch_depth."""
    p = getattr(args, "prefetch", None)
    if p is not None:
        return p
    from paddle_tpu.utils.flags import FLAGS
    return FLAGS.prefetch_depth if FLAGS.async_load_data else 0


def _parse_config_args(s):
    out = {}
    if s:
        for kv in s.split(","):
            k, _, v = kv.partition("=")
            out[k.strip()] = v.strip()
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(prog="paddle_tpu")
    sub = parser.add_subparsers(dest="job", required=True)

    def add_common(p):
        p.add_argument("--config", required=True)
        p.add_argument("--config_args", default="",
                       help="k=v,k=v passed to the config script")
        p.add_argument("--data_parallel", type=int, default=0)
        p.add_argument("--model_parallel", type=int, default=1)
        p.add_argument("--seq_parallel", type=int, default=1)
        p.add_argument("--profile_dir", default=None,
                       help="capture an xprof device trace of the run")
        p.add_argument("--debug_nans", action="store_true",
                       help="fail fast on the op producing a NaN "
                            "(reference feenableexcept)")
        p.add_argument("--comment", default="",
                       help="freeform run annotation, logged once")
        p.add_argument("--seq_buckets", default=None,
                       type=_seq_buckets_arg,
                       help="comma-separated allowed padded sequence "
                            "lengths, e.g. 32,64,128: bounds XLA "
                            "recompilation to one program per bucket "
                            "(recommended for variable-length data on "
                            "TPU).  Sequences longer than the largest "
                            "bucket are truncated to it (warned)")
        p.add_argument("--pad_batch", action="store_true",
                       help="pad the final short batch to the full batch "
                            "size (one more shape avoided)")
        p.add_argument("--dtype", default="auto",
                       choices=["auto", "float32", "bfloat16"],
                       help="compute dtype for forward+backward; master "
                            "params and the optimizer stay float32.  "
                            "auto = platform policy (bf16 matmul inputs "
                            "on TPU, f32 elsewhere); float32 FORCES full "
                            "f32 even on TPU (numerics debugging); "
                            "bfloat16 forces bf16 everywhere and also "
                            "casts params+feeds at the step boundary "
                            "(half-width HBM reads; no loss scaling "
                            "needed)")

    t = sub.add_parser("train")
    add_common(t)
    t.add_argument("--num_passes", type=int, default=1)
    t.add_argument("--prefetch", type=int, default=None,
                   help="overlapped input pipeline: convert + H2D-transfer "
                        "N batches ahead on a background thread so the "
                        "step never waits on input (0 = off; costs ~N+1 "
                        "batches of extra HBM).  Default comes from FLAGS: "
                        "prefetch_depth when async_load_data (the "
                        "reference DoubleBuffer default), else 0")
    t.add_argument("--jax_compilation_cache_dir", default=None,
                   help="persist XLA compilations here and reuse them "
                        "across restarts (opt-in; pairs with seq_buckets "
                        "so warm starts skip every bucket compile)")
    t.add_argument("--grad_accum_steps", type=int, default=1,
                   help="sum grads over N micro-batches, apply their mean "
                        "every Nth step (large effective batch in fixed "
                        "HBM)")
    t.add_argument("--quant-train", dest="quant_train",
                   action="store_true",
                   help="int8 weight-streaming training: the jitted step "
                        "reads per-out-channel int8 weights + f32 scale "
                        "sidecars at the matmul boundary, f32 masters "
                        "update optimizer-side and requantize each step; "
                        "checkpoints carry both trees (quant_train flag)")
    t.add_argument("--save_dir", default=None)
    t.add_argument("--saving_period", type=int, default=1)
    t.add_argument("--save_only_one", action="store_true")
    t.add_argument("--start_pass", type=int, default=0)
    t.add_argument("--log_period", type=int, default=100)
    t.add_argument("--test_period", type=int, default=0)
    t.add_argument("--show_parameter_stats_period", type=int, default=0)
    t.add_argument("--init_model_path", default=None,
                   help="warm-start parameters from this checkpoint dir")
    t.add_argument("--load_missing_parameter_strategy", default="fail",
                   choices=["fail", "rand", "zero"])
    t.add_argument("--show_layer_stat", action="store_true",
                   help="log per-layer output stats on the first batch of "
                        "each pass")

    te = sub.add_parser("test")
    add_common(te)
    te.add_argument("--model_dir", required=True)
    te.add_argument("--test_pass", type=int, default=None)

    tm = sub.add_parser("time",
                        help="time the train step (reference --job=time, "
                             "TrainerBenchmark.cpp): warm up, then report "
                             "ms/batch percentiles over --num_batches")
    add_common(tm)
    tm.add_argument("--num_batches", type=int, default=20)
    tm.add_argument("--warmup", type=int, default=2)

    cg = sub.add_parser("checkgrad",
                        help="finite-difference gradient check "
                             "(reference --job=checkgrad; single-device, "
                             "parallel flags are ignored)")
    cg.add_argument("--config", required=True)
    cg.add_argument("--config_args", default="")
    cg.add_argument("--eps", type=float, default=1e-3)

    m = sub.add_parser("merge_model")
    m.add_argument("--model_dir", required=True)
    m.add_argument("--out", required=True)
    m.add_argument("--pass_id", type=int, default=None)

    sub.add_parser("version")

    args = parser.parse_args(argv)

    # honor JAX_PLATFORMS even where a sitecustomize hook pins the
    # jax_platforms *config* at interpreter startup (env var alone loses)
    from paddle_tpu._platform import honor_jax_platforms_env
    honor_jax_platforms_env()

    if args.job == "version":
        from paddle_tpu.version import __version__
        import jax
        print(f"paddle_tpu {__version__} (jax {jax.__version__})",
              flush=True)
        # device discovery can hang indefinitely when a remote TPU backend
        # is wedged — version must still answer (bounded probe, reference
        # `paddle version` prints with no device touch at all)
        import threading
        res = {}

        def _probe():
            try:
                res["devices"] = jax.devices()
            except Exception as e:   # noqa: BLE001
                res["devices"] = f"unavailable: {type(e).__name__}"

        try:
            t_probe = float(os.environ.get("PADDLE_TPU_PROBE_TIMEOUT", "20"))
        except ValueError:
            t_probe = 20.0
        if not (t_probe > 0):          # rejects <=0 and NaN
            t_probe = 20.0
        th = threading.Thread(target=_probe, daemon=True)
        th.start()
        th.join(timeout=t_probe)
        print(f"devices: {res.get('devices', 'probe timed out (backend wedged?)')}")
        return 0

    if args.job == "merge_model":
        from paddle_tpu.trainer.checkpoint import merge_model
        out = merge_model(args.model_dir, args.out, args.pass_id)
        print("wrote", out)
        return 0

    if getattr(args, "debug_nans", False):
        import jax
        jax.config.update("jax_debug_nans", True)
    if getattr(args, "jax_compilation_cache_dir", None):
        from paddle_tpu.utils.flags import set_compilation_cache_dir
        set_compilation_cache_dir(args.jax_compilation_cache_dir)
    if getattr(args, "comment", ""):
        from paddle_tpu.utils.logging import logger
        logger.info("comment: %s", args.comment)

    # launched by scripts/launch_cluster (PADDLE_TPU_* rendezvous) or on a
    # Cloud-TPU pod (platform fan-out; jax autodetects the coordinator):
    # connect the multi-controller runtime BEFORE first device use — here,
    # ahead of the config exec — or every rank would silently train an
    # independent full copy.  Deliberately AFTER the version/merge_model
    # early returns: those are built to answer even with a wedged backend
    # and must never block in a rendezvous.
    # pod detection must require MULTIPLE workers: single-host TPU images
    # (incl. this repo's axon tunnel) set TPU_WORKER_HOSTNAMES=localhost
    # via sitecustomize, and a 1-host rendezvous would add latency for
    # nothing
    _hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    _multihost_pod = ("," in _hostnames
                      or "MEGASCALE_COORDINATOR_ADDRESS" in os.environ)
    if os.environ.get("PADDLE_TPU_COORDINATOR") or _multihost_pod:
        from paddle_tpu.parallel import distributed as dist
        dist.init_distributed()

    cfg = _load_config(args.config, _parse_config_args(args.config_args))

    if args.job == "checkgrad":
        from paddle_tpu.layers.graph import Topology
        from paddle_tpu.testing import check_topology_grads
        feeder = _feeder_from_args(args, cfg)
        batch = next(iter(cfg["train_reader"]()))
        feed = feeder(batch) if feeder else batch
        costs = cfg["cost"]
        topo = Topology(costs if isinstance(costs, (list, tuple))
                        else [costs])
        results = check_topology_grads(topo, feed, eps=args.eps,
                                       raise_on_fail=False)
        bad = False
        for path, err, ok in results:
            print(f"  {path}: max rel err {err:.3g}"
                  + ("" if ok else "  MISMATCH"))
            bad = bad or not ok
        print("checkgrad FAILED" if bad else "checkgrad PASSED")
        return 1 if bad else 0

    from paddle_tpu.trainer import SGD
    mesh = None
    if args.model_parallel > 1 or args.seq_parallel > 1 or args.data_parallel > 1:
        from paddle_tpu.parallel import MeshConfig, make_mesh, megatron_rules
        mesh = make_mesh(MeshConfig(data=args.data_parallel,
                                    model=args.model_parallel,
                                    seq=args.seq_parallel))
    else:
        import jax as _jax
        if _jax.process_count() > 1:
            # multi-process launch with no explicit parallel flags: the
            # only sane default is data-parallel over every device in the
            # job (a per-rank local mesh would train N independent copies)
            from paddle_tpu.parallel import MeshConfig, make_mesh
            mesh = make_mesh(MeshConfig(data=_jax.device_count()))
            logger_note = (f"multi-process job: defaulting to "
                           f"data_parallel={_jax.device_count()}")
            from paddle_tpu.utils.logging import logger
            logger.info(logger_note)
    optimizer = cfg.get("optimizer")
    if optimizer is None:
        # same default as the v1 settings() compat path (compat/v1.py:
        # MomentumOptimizer(momentum=0) at learning_rate=1e-3) so the two
        # config styles train identically when no optimizer is named
        from paddle_tpu import optim
        optimizer = optim.Momentum(learning_rate=1e-3, momentum=0.0)
    import jax.numpy as jnp
    if args.dtype != "auto":
        # op-level policy: explicit float32 must ALSO be asserted (the
        # auto policy would keep feeding the MXU bf16 inputs on TPU);
        # bfloat16 additionally casts params + feeds at the step boundary
        # via SGD(compute_dtype=...) so HBM reads are half-width
        from paddle_tpu.core import dtypes as _dtypes
        _dtypes.set_policy(compute_dtype=args.dtype)
    from paddle_tpu.utils.flags import FLAGS
    quant_train = bool(getattr(args, "quant_train", False)
                       or getattr(FLAGS, "quant_train", False))
    if quant_train:
        FLAGS.quant_train = True
    trainer = SGD(cost=cfg["cost"], update_equation=optimizer,
                  mesh=mesh,
                  sharding_rules=cfg.get("sharding_rules"),
                  evaluators=cfg.get("evaluators"),
                  compute_dtype=(jnp.bfloat16
                                 if args.dtype == "bfloat16" else None),
                  grad_accum_steps=getattr(args, "grad_accum_steps", 1),
                  quant_weights=quant_train)

    if args.job == "train":
        save_dir = args.save_dir or cfg.get("save_dir")
        if args.init_model_path:
            trainer.load_parameters(
                args.init_model_path,
                missing_strategy=args.load_missing_parameter_strategy)
        if args.start_pass:
            if not save_dir:
                raise SystemExit("--start_pass needs --save_dir (or a "
                                 "save_dir in the config)")
            trainer.load(save_dir, args.start_pass - 1)
        ev_handler = None
        if args.show_layer_stat:
            from paddle_tpu.trainer import events as _ev
            feeder = _feeder_from_args(args, cfg)

            def ev_handler(ev, _tr=trainer, _cfg=cfg, _feeder=feeder):
                if isinstance(ev, _ev.BeginPass):
                    batch = next(iter(_cfg["train_reader"]()), None)
                    if batch is None:   # empty (or one-shot, drained) reader
                        return
                    _tr.log_layer_stats(_feeder(batch) if _feeder else batch)
        if args.profile_dir:
            from paddle_tpu.utils import profiler
            profiler.start(args.profile_dir)
        try:
            trainer.train(cfg["train_reader"],
                          num_passes=args.num_passes,
                          event_handler=ev_handler,
                          feeding=_feeder_from_args(args, cfg),
                          save_dir=save_dir,
                          saving_period=args.saving_period,
                          save_only_one=args.save_only_one,
                          test_reader=cfg.get("test_reader"),
                          test_period=args.test_period,
                          log_period=args.log_period,
                          show_parameter_stats_period=
                          args.show_parameter_stats_period,
                          prefetch=_resolve_prefetch(args))
        finally:
            # flush the trace even on a mid-pass failure — crashed runs are
            # the ones you most want a profile of
            if args.profile_dir:
                from paddle_tpu.utils import profiler
                profiler.stop()
        return 0

    if args.job == "test":
        trainer.load(args.model_dir, args.test_pass)
        cost = trainer.test(cfg.get("test_reader") or cfg["train_reader"],
                            feeding=_feeder_from_args(args, cfg,
                                                      allow_pad=False))
        print(f"test cost: {cost:.5f}")
        return 0

    if args.job == "time":
        import time as _time
        feeder = _feeder_from_args(args, cfg)
        reader = cfg["train_reader"]
        batches = []
        for b in reader():
            batches.append(b)
            if len(batches) >= args.num_batches + args.warmup:
                break
        if len(batches) <= args.warmup:
            print(f"time: need more than --warmup={args.warmup} batches, "
                  f"reader yielded {len(batches)}", file=sys.stderr)
            return 2
        import jax as _jax
        durs = []
        for i, b in enumerate(batches):
            t0 = _time.perf_counter()
            cost = trainer.train_one_batch(b, feeder=feeder)
            _jax.block_until_ready(cost)    # real step time, not dispatch
            if i >= args.warmup:
                durs.append((_time.perf_counter() - t0) * 1e3)
        durs.sort()
        n = len(durs)
        if n < 100:
            # with few samples a "p99" is just the max — don't overstate
            # fidelity with percentile labels
            print(f"time: {n} batches  min={durs[0]:.2f}ms  "
                  f"mean={sum(durs) / n:.2f}ms  max={durs[-1]:.2f}ms")
        else:
            import numpy as _np
            # same estimator as utils.stats.Histogram so the trainer's
            # pass-end log and this job agree on what "p99" means
            p50, p90, p99 = _np.percentile(durs, [50, 90, 99])
            print(f"time: {n} batches  p50={p50:.2f}ms  "
                  f"p90={p90:.2f}ms  p99={p99:.2f}ms  "
                  f"mean={sum(durs) / n:.2f}ms")
        return 0



if __name__ == "__main__":
    sys.exit(main())
