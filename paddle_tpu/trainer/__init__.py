"""Training driver (reference §2.7 trainer + v2 trainer API)."""

from paddle_tpu.trainer.trainer import SGD, Trainer, Inferencer, infer
from paddle_tpu.trainer import events
from paddle_tpu.trainer.checkpoint import (
    save_checkpoint, load_checkpoint, merge_model, load_merged)

__all__ = ["SGD", "Trainer", "Inferencer", "infer", "events",
           "save_checkpoint", "load_checkpoint", "merge_model",
           "load_merged"]
