"""v2-style top-level API (paddle.init / paddle.infer equivalents)."""


def init(**kwargs):
    """Reference: paddle.v2.init(use_gpu=, trainer_count=).  Device-count
    knobs become mesh flags here (parallel.MeshConfig)."""
    from paddle_tpu.utils.flags import FLAGS
    for k, v in kwargs.items():
        if hasattr(FLAGS, k):
            setattr(FLAGS, k, v)
    return FLAGS


def infer(output_layer=None, parameters=None, input=None, feeding=None):
    """Reference: paddle.v2.infer(output_layer=, parameters=, input=)."""
    from paddle_tpu.trainer.trainer import infer as _infer
    return _infer(output_layer, parameters, input, feeding=feeding)
