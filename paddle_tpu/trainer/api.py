"""v2-style top-level API (paddle.init / paddle.infer equivalents)."""


def init(**kwargs):
    """Reference: paddle.v2 init(use_gpu=, trainer_count=) -> here mesh/flags."""
    from paddle_tpu.utils.flags import FLAGS
    for k, v in kwargs.items():
        if hasattr(FLAGS, k):
            setattr(FLAGS, k, v)
    return FLAGS


def infer(*args, **kwargs):
    raise NotImplementedError("paddle_tpu.infer arrives with the inference module")
