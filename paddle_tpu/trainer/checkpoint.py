"""Checkpoint save/load.

Reference: per-pass dirs output/pass-%05d with one binary file per Parameter
(header {version, sizeof(real), size} + raw floats, Parameter.cpp:281-307),
ParamUtil save/load, --saving_period, --save_only_one; v2 tar-of-numpy
(v2/parameters.py to_tar); optimizer state NOT saved in the reference —
here it IS (orbax-style full train-state snapshot), fixing resume semantics.

Format: msgpack-free portable .npz per pytree + a JSON manifest; directory
layout keeps the reference's pass-%05d convention so --start_pass resume
works the same way.
"""

import atexit
import json
import os
import shutil
import tempfile
import threading

import numpy as np
import jax
import jax.numpy as jnp

# single-flight async writer PER SAVE DIR: at most one background save in
# flight per directory; a failure surfaces on that directory's next
# save/wait instead of dying silently, and independent trainers saving to
# different dirs never serialize on (or crash from) each other
_pending = {}        # realpath(save_dir) -> Thread
_pending_exc = {}    # realpath(save_dir) -> BaseException
_pending_lock = threading.Lock()


def wait_pending(save_dir=None):
    """Block until in-flight async saves have landed — for one directory,
    or all of them when save_dir is None — and re-raise their failure
    here (the caller's next sync point) if they had one."""
    with _pending_lock:
        if save_dir is None:
            keys = list(_pending) + [k for k in _pending_exc
                                     if k not in _pending]
        else:
            keys = [os.path.realpath(save_dir)]
        threads = [(_pending.get(k), k) for k in keys]
    first_exc = None
    for t, k in threads:
        if t is not None:
            t.join()
        with _pending_lock:
            exc = _pending_exc.pop(k, None)
            _pending.pop(k, None)
        if exc is not None:
            if first_exc is None:
                first_exc = exc
            else:
                # don't drop the rest on the floor: the first one is
                # re-raised, the others at least leave a trace
                from paddle_tpu.utils.logging import logger
                logger.error("async checkpoint save to %s also failed: %r",
                             k, exc)
    if first_exc is not None:
        raise first_exc


# interpreter shutdown kills daemon threads AFTER atexit callbacks run, so
# this makes every scheduled async save land (or report its failure) even
# when an exception unwinds straight out of the train loop — the crash
# case checkpoints exist for
atexit.register(wait_pending)


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
        out[f"{prefix}__len__"] = np.asarray(
            [len(tree), 1 if isinstance(tree, tuple) else 0])
    elif tree is None:
        out[f"{prefix}__none__"] = np.zeros(0)
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten(flat):
    # rebuild nested dict first
    root = {}
    for key, val in flat.items():
        parts = key.split("/")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = val

    def rebuild(node):
        if isinstance(node, dict):
            if "__none__" in node and len(node) == 1:
                return None
            if "__len__" in node:
                n, is_tuple = (int(x) for x in node["__len__"])
                items = [rebuild(node[str(i)]) for i in range(n)]
                return tuple(items) if is_tuple else items
            return {k: rebuild(v) for k, v in node.items()}
        return node
    return rebuild(root)


def save_checkpoint(save_dir, pass_id, params, opt_state=None, model_state=None,
                    extra=None, save_only_one=False, block=True):
    """Write output/pass-%05d/{params,opt_state,model_state}.npz + meta.

    Crash-atomic: everything lands in a hidden .tmp- dir first and is
    renamed into place, so a crash mid-save can never leave a partial
    pass dir for load_checkpoint's latest-pass pick to trip on.

    block=False: the device->host snapshot still happens NOW (the values
    written are this exact pass), but the disk write runs on a background
    thread so the train loop overlaps I/O with the next pass.  Single
    flight — a new async save first joins the previous one; call
    wait_pending() before reading the checkpoint back or exiting."""
    final = os.path.join(save_dir, f"pass-{pass_id:05d}")
    host_params = jax.device_get(params)
    host_opt = jax.device_get(opt_state) if opt_state is not None else None
    host_mstate = (jax.device_get(model_state)
                   if model_state is not None else None)
    meta = {"pass_id": pass_id, "format_version": 1}
    meta.update(extra or {})

    def write():
        from paddle_tpu.obs import trace as _obstrace
        _ckpt_span = _obstrace.start_span("trainer.checkpoint.write",
                                          root=False, pass_id=pass_id)
        try:
            os.makedirs(save_dir, exist_ok=True)
            tmp = tempfile.mkdtemp(prefix=f".tmp-pass-{pass_id:05d}-",
                                   dir=save_dir)
            # mkdtemp makes 0700; inherit the parent's perms so renamed
            # pass dirs stay readable by whatever can read save_dir (as
            # makedirs used to give)
            os.chmod(tmp, os.stat(save_dir).st_mode & 0o777)
        except BaseException as e:  # unwritable/full save_dir: the span
            _ckpt_span.end(error=f"{type(e).__name__}: {e}")  # must not
            raise                                       # leak as active
        try:
            np.savez(os.path.join(tmp, "params.npz"), **_flatten(host_params))
            # chaos hook MID-WRITE (resilience/faults.py): arrays are on
            # disk but the dir is still the hidden .tmp- staging name.  An
            # injected error unwinds into the rmtree below; an injected
            # hang holds the window open for a kill -9 — either way the
            # partial can never be renamed into a pass dir, which is
            # exactly what the crash-resume tests prove load never picks.
            from paddle_tpu.resilience import faults as _faults
            _faults.hit("trainer.checkpoint.write")
            if host_opt is not None:
                np.savez(os.path.join(tmp, "opt_state.npz"),
                         **_flatten(host_opt))
            if host_mstate is not None:
                np.savez(os.path.join(tmp, "model_state.npz"),
                         **_flatten(host_mstate))
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f)
            old = None
            if os.path.isdir(final):
                # rename the predecessor aside (microseconds) instead of
                # rmtree-ing it first (arbitrarily long): the only window
                # with no pass dir is between the two renames, and
                # load_checkpoint falls back to .old- dirs for exactly
                # that window
                old = tempfile.mkdtemp(prefix=f".old-pass-{pass_id:05d}-",
                                       dir=save_dir)
                os.rmdir(old)
                os.rename(final, old)
            os.rename(tmp, final)
            if old is not None:
                shutil.rmtree(old, ignore_errors=True)
        except BaseException as e:
            shutil.rmtree(tmp, ignore_errors=True)
            _ckpt_span.end(error=f"{type(e).__name__}: {e}")
            raise
        _ckpt_span.end(path=final)
        if save_only_one:
            for name in os.listdir(save_dir):
                if (name.startswith("pass-")
                        and name != f"pass-{pass_id:05d}"):
                    shutil.rmtree(os.path.join(save_dir, name),
                                  ignore_errors=True)

    key = os.path.realpath(save_dir)
    if block:
        wait_pending(save_dir)   # don't interleave with an async predecessor
        write()
        return final

    wait_pending(save_dir)

    def run():
        try:
            write()
        except BaseException as e:   # surfaces at the next wait_pending
            with _pending_lock:
                _pending_exc[key] = e

    t = threading.Thread(target=run, daemon=True,
                         name=f"ckpt-save-{pass_id}")
    with _pending_lock:
        _pending[key] = t
    t.start()
    return final


def _load_npz(path):
    if not os.path.exists(path):
        return None
    with np.load(path, allow_pickle=False) as z:
        flat = {k: z[k] for k in z.files}
    return _unflatten(flat)


def load_checkpoint(save_dir, pass_id=None):
    """Load a pass dir (latest if pass_id is None).  Returns
    (params, opt_state, model_state, meta)."""
    if pass_id is None:
        passes = sorted(n for n in os.listdir(save_dir) if n.startswith("pass-"))
        if not passes:
            # crash window during an overwrite-save: the predecessor was
            # renamed aside but the replacement didn't land — recover it
            passes = sorted(n for n in os.listdir(save_dir)
                            if n.startswith(".old-pass-")
                            and os.path.exists(
                                os.path.join(save_dir, n, "meta.json")))
        if not passes:
            raise FileNotFoundError(f"no pass-* checkpoints in {save_dir}")
        path = os.path.join(save_dir, passes[-1])
    else:
        path = os.path.join(save_dir, f"pass-{pass_id:05d}")
    params = _load_npz(os.path.join(path, "params.npz"))
    opt_state = _load_npz(os.path.join(path, "opt_state.npz"))
    model_state = _load_npz(os.path.join(path, "model_state.npz"))
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    to_dev = lambda t: jax.tree_util.tree_map(jnp.asarray, t) if t is not None else None
    return to_dev(params), to_dev(opt_state), to_dev(model_state), meta


def merge_model(save_dir, out_path, pass_id=None):
    """paddle_merge_model equivalent: single deployable file
    (params + model_state + meta) for inference."""
    params, _, model_state, meta = load_checkpoint(save_dir, pass_id)
    blob = _flatten({"params": params, "model_state": model_state or {}})
    np.savez_compressed(out_path, __meta__=np.frombuffer(
        json.dumps(meta).encode(), np.uint8), **blob)
    return out_path


def load_merged(path):
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        flat = {k: z[k] for k in z.files if k != "__meta__"}
    tree = _unflatten(flat)
    return tree["params"], tree.get("model_state"), meta
