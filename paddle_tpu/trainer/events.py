"""Trainer event stream (reference: python/paddle/v2/event.py)."""

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class BeginPass:
    pass_id: int


@dataclasses.dataclass
class EndPass:
    pass_id: int
    evaluator_results: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class BeginIteration:
    pass_id: int
    batch_id: int


@dataclasses.dataclass
class EndIteration:
    pass_id: int
    batch_id: int
    # a device scalar (lazy; float(e.cost) syncs) — keeps the train loop
    # free of per-batch host round-trips
    cost: Any
    evaluator_results: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class EndTesting:
    pass_id: int
    cost: float
    evaluator_results: Dict[str, Any] = dataclasses.field(default_factory=dict)
