"""Parameter updater hooks — the static pruning hook.

Reference: paddle/parameter/ParameterUpdaterHook.cpp:36 (StaticPruningHook):
a 0/1 mask is applied to the parameter VALUE once at init and to the
GRADIENT on every update, so pruned weights stay exactly zero through
training.  The reference loads the mask from a packed-bit file
(StaticMaskHeader {uint32 version; size_t size} then MSB-first bits,
ParameterUpdaterHook.cpp:106-126); later API revisions instead derive it
from the smallest-magnitude fraction of the initialized weights
(HookAttribute(type='pruning', sparsity_ratio=r)).  Both forms are
supported here.

TPU-first shape: masks are plain bf16/f32 0/1 arrays closed over by the
jitted train step — the multiply fuses into the grad computation, and the
mask shards with whatever PartitionSpec the parameter uses.
"""

import struct

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.utils.error import ConfigError

_MASK_VERSION = 0


def write_mask_file(path, mask_flat):
    """Write the reference's packed-bit mask format (for tests/tools)."""
    bits = np.asarray(mask_flat).reshape(-1) != 0
    size = bits.size
    packed = np.packbits(bits)        # MSB-first, zero-padded — matches ref
    with open(path, "wb") as f:
        f.write(struct.pack("<IQ", _MASK_VERSION, size))
        f.write(packed.tobytes())


def load_mask_file(path, expect_size=None):
    """Read the reference mask file -> float32 0/1 flat array."""
    with open(path, "rb") as f:
        header = f.read(12)
        if len(header) < 12:
            raise ConfigError(f"pruning mask {path!r}: truncated header")
        version, size = struct.unpack("<IQ", header)
        if version != _MASK_VERSION:
            raise ConfigError(
                f"pruning mask {path!r}: unsupported version {version}")
        payload = f.read((size + 7) // 8)
    if len(payload) < (size + 7) // 8:
        raise ConfigError(
            f"pruning mask {path!r}: truncated payload ({len(payload)} bytes "
            f"for {size} bits)")
    bits = np.unpackbits(np.frombuffer(payload, np.uint8))[:size]
    if expect_size is not None and size != expect_size:
        raise ConfigError(
            f"pruning mask {path!r}: mask size {size} != parameter size "
            f"{expect_size}")
    return bits.astype(np.float32)


def _normalize_hooks(update_hooks):
    hooks = update_hooks if isinstance(update_hooks, (list, tuple)) \
        else [update_hooks]
    out = []
    for h in hooks:
        if h is None:
            continue
        if not isinstance(h, dict):
            raise ConfigError(f"unsupported update hook {h!r}")
        if h.get("type") != "pruning":
            raise ConfigError(
                f"unsupported update hook type {h.get('type')!r} "
                "(only 'pruning' exists — reference "
                "ParameterUpdaterHook.cpp:168)")
        out.append(h)
    return out


def _ratio_mask(leaf, ratio):
    """Zero the `ratio` fraction of smallest-|w| entries (per leaf)."""
    flat = jnp.abs(leaf).reshape(-1)
    k = int(round(float(ratio) * flat.size))
    if k <= 0:
        return jnp.ones_like(leaf, jnp.float32)
    if k >= flat.size:
        return jnp.zeros_like(leaf, jnp.float32)
    threshold = jnp.sort(flat)[k - 1]
    return (jnp.abs(leaf) > threshold).astype(jnp.float32)


def _is_bias_leaf(path):
    last = str(path[-1].key) if path and hasattr(path[-1], "key") else ""
    return last == "b" or last.startswith("bias")


def _leaf_mask(leaf, hook, where):
    if hook.get("mask_filename"):
        flat = load_mask_file(hook["mask_filename"], expect_size=leaf.size)
        return jnp.asarray(flat.reshape(leaf.shape))
    if hook.get("sparsity_ratio") is not None:
        return _ratio_mask(leaf, hook["sparsity_ratio"])
    raise ConfigError(
        f"pruning hook on {where!r} needs sparsity_ratio= or mask_filename=")


def _collect_hooked_attrs(topology):
    """Yield (param_key, leaf_name_or_None, hooks) for every attr carrying
    update_hooks.  leaf_name None = all weight leaves of the parameter;
    'w{i}' = the i-th input's weight (fc param_attr list / mixed-layer
    projection spec)."""
    for node in topology.order:
        key = topology._param_key(node)
        pa = node.cfg.get("param_attr")
        if isinstance(pa, dict) and pa.get("update_hooks"):
            yield key, None, _normalize_hooks(pa["update_hooks"])
        elif isinstance(pa, (list, tuple)):
            for i, p in enumerate(pa):
                if isinstance(p, dict) and p.get("update_hooks"):
                    yield (key, f"w{i}",
                           _normalize_hooks(p["update_hooks"]))
        for k, part in enumerate(node.cfg.get("parts") or ()):
            spec = part[1] if isinstance(part, (list, tuple)) else {}
            sp = spec.get("param_attr") if isinstance(spec, dict) else None
            if isinstance(sp, dict) and sp.get("update_hooks"):
                yield key, f"w{k}", _normalize_hooks(sp["update_hooks"])


def build_masks(topology, params):
    """Collect pruning masks for every parameter whose param_attr carries
    update_hooks.  Returns {param_key: mask-pytree} (possibly empty)."""
    hook_cfg = {}   # (key, leaf): hooks — detects conflicting shares
    for key, leaf_name, hooks in _collect_hooked_attrs(topology):
        if not hooks:
            continue
        prev = hook_cfg.get((key, leaf_name))
        if prev is not None and prev != hooks:
            raise ConfigError(
                f"parameter {key!r} is shared with conflicting update_hooks")
        hook_cfg[(key, leaf_name)] = hooks

    masks = {}
    for (key, leaf_name), hooks in hook_cfg.items():
        if key not in params:
            raise ConfigError(f"update_hooks on {key!r}: no such parameter")
        paths, treedef = jax.tree_util.tree_flatten_with_path(params[key])
        named = None
        if leaf_name is not None:
            named = {str(p[-1].key) if p and hasattr(p[-1], "key") else ""
                     for p, _ in paths}
            if leaf_name not in named:
                raise ConfigError(
                    f"update_hooks on {key!r}: no weight leaf "
                    f"{leaf_name!r} (has {sorted(named)})")
        for h in hooks:
            leaves = []
            for path, leaf in paths:
                last = str(path[-1].key) if path and hasattr(
                    path[-1], "key") else ""
                if leaf_name is not None:
                    hit = last == leaf_name
                else:
                    # attr-level hook governs the WEIGHTS; a bias is its own
                    # parameter in the reference (bias_attr), never pruned
                    hit = not _is_bias_leaf(path)
                leaves.append(_leaf_mask(leaf, h, key)
                              if hit else jnp.ones_like(leaf, jnp.float32))
            m = jax.tree_util.tree_unflatten(treedef, leaves)
            masks[key] = m if key not in masks else jax.tree_util.tree_map(
                jnp.multiply, masks[key], m)
    return masks


def apply_masks(tree, masks):
    """Multiply masked entries of a params-shaped pytree (values or grads).
    Non-hooked keys pass through untouched."""
    if not masks:
        return tree
    out = dict(tree)
    for key, mask in masks.items():
        out[key] = jax.tree_util.tree_map(
            lambda t, m: t * m.astype(t.dtype), tree[key], mask)
    return out
