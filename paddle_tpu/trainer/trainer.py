"""The training driver: v2-style `SGD.train(reader, event_handler)`.

Reference: python/paddle/v2/trainer.py:30-175 (SGD class, event loop),
trainer/Trainer.cpp:261-492 (pass/batch loops, periodic save/test),
trainer/TrainerInternal.cpp:66-170 (the hot loop: forward/backward/update +
eval + log).

TPU redesign: the entire hot loop — forward, backward, optimizer update,
evaluator statistics — is ONE jitted (and mesh-sharded) function.  The
reference's updater pipeline (grad-ready callbacks overlapping backward with
pserver sends, RemoteParameterUpdater.h:37-54) is subsumed by XLA scheduling
collectives inside the step; async host-side data feeding comes from
reader.buffered (the DoubleBuffer equivalent).
"""

import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.obs import trace as _obstrace
from paddle_tpu.core.sequence import (NestedSequenceBatch,
                                      SequenceBatch)
from paddle_tpu.resilience import faults as _faults
from paddle_tpu.data.feeder import DataFeeder
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.layers.graph import Topology, LayerOutput
from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.trainer import events
from paddle_tpu.trainer import hooks as param_hooks
from paddle_tpu.trainer.checkpoint import save_checkpoint, load_checkpoint
from paddle_tpu.utils.error import ConfigError
from paddle_tpu.utils.logging import logger
from paddle_tpu.utils.stats import timer, global_stats
from paddle_tpu.parallel import (
    make_mesh, param_shardings, batch_shardings, replicated_shardings,
    shard_params)



def _normalize_feed(feed):
    """Device-ready feed: (Nested)SequenceBatch pass through, everything
    else becomes a jnp array."""
    return {k: v if isinstance(v, (SequenceBatch, NestedSequenceBatch))
            else jnp.asarray(v)
            for k, v in feed.items()}


def _feed_signature(feed):
    """Hashable (treedef, leaf shapes/dtypes) key for a feed pytree — the
    dispatch key of the AOT-precompiled step executables (one per length
    bucket).  Works for concrete arrays and jax.ShapeDtypeStructs alike."""
    leaves, treedef = jax.tree_util.tree_flatten(feed)
    return (treedef,
            tuple((tuple(l.shape), np.dtype(l.dtype).name) for l in leaves))


def _abstract_feed(feed):
    """Feed pytree -> same pytree of jax.ShapeDtypeStructs (leaves that
    already are ShapeDtypeStructs pass through)."""
    return jax.tree_util.tree_map(
        lambda l: l if isinstance(l, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(np.shape(l), l.dtype), feed)


class SGD:
    """paddle.v2.trainer.SGD equivalent.

    cost: LayerOutput (or list) whose value is a per-sample loss [B].
    update_equation: an optim.Optimizer.
    extra_layers: additional LayerOutputs to evaluate each batch (for
    metrics; reference SGD(extra_layers=) used for evaluators).
    mesh: jax Mesh (None = single device); sharding_rules: parallel.ShardingRules.
    """

    def __init__(self, cost, parameters=None, update_equation=None,
                 extra_layers=None, is_local=True, mesh=None,
                 sharding_rules=None, seed=1, donate=True, evaluators=None,
                 compute_dtype=None, grad_accum_steps=1,
                 quant_weights=False, quant_min_size=1024):
        self.costs = cost if isinstance(cost, (list, tuple)) else [cost]
        self.extra_layers = list(extra_layers or [])
        # evaluator specs (evaluators.dsl): fetch their bound layers as
        # extra outputs; labels/weights come straight from the feed
        self.evaluators = list(evaluators or [])
        self._eval_slots = []
        self._eval_extra_slots = []   # per spec: {kw: ('feed', name)|('extra', i)}

        def slot_for(layer):
            if layer.layer_type == "data":
                return ("feed", layer.name)
            if layer in self.extra_layers:
                return ("extra", self.extra_layers.index(layer))
            self.extra_layers.append(layer)
            return ("extra", len(self.extra_layers) - 1)

        for spec in self.evaluators:
            self._eval_slots.append(slot_for(spec.input))
            self._eval_extra_slots.append(
                {kw: slot_for(l) for kw, l in spec.extra_inputs.items()})
        self.topology = Topology(list(self.costs) + self.extra_layers)
        if update_equation is None:
            raise ValueError(
                "SGD needs update_equation=, e.g. "
                "optim.Momentum(learning_rate=0.01)")
        self.optimizer: Optimizer = update_equation
        # mixed precision, the TPU-native way: master params stay f32 (the
        # optimizer state/update precision), forward+backward run in
        # compute_dtype (jnp.bfloat16) — halves HBM traffic and feeds the
        # MXU its native input width.  bf16's f32-equal exponent range
        # makes loss scaling unnecessary (unlike fp16).  The cast happens
        # inside the loss, so autodiff returns f32 master grads.
        self.compute_dtype = compute_dtype
        self.mesh = mesh
        self.sharding_rules = sharding_rules
        rng = jax.random.PRNGKey(seed)
        self.rng, init_rng = jax.random.split(rng)
        self.parameters = parameters if parameters is not None \
            else self.topology.init(init_rng)
        self._sparse_specs = self._find_sparse_specs()
        # static pruning hooks (reference ParameterUpdaterHook.cpp:36):
        # mask values once at init, mask grads every step
        self._prune_masks = param_hooks.build_masks(
            self.topology, self.parameters)
        for k in self._prune_masks:
            if k in self._sparse_specs:
                raise ConfigError(
                    f"pruning hook on {k!r}: sparse_update tables can't be "
                    "statically pruned (the row path rewrites the table)")
        if self._prune_masks:
            self.parameters = param_hooks.apply_masks(
                self.parameters, self._prune_masks)
        # validate BEFORE allocating optimizer slots (a sparse-incompatible
        # setting must not first build full-vocab [V, D] slot tables)
        self.grad_accum_steps = int(grad_accum_steps)
        if self.grad_accum_steps < 1:
            raise ConfigError("grad_accum_steps must be >= 1")
        if self.grad_accum_steps > 1 and self._sparse_specs:
            raise ConfigError(
                "grad_accum_steps > 1 is unsupported with sparse_update "
                "embeddings (touched-row sets differ per micro-batch)")
        # int8 weight-streaming training (quant/weights.py, the serving
        # quant_weights scheme turned on the train step): the jitted
        # step is fed {"master": f32 tree, "q": int8+scale tree},
        # forward/backward run over the dequantized view (widening fuses
        # into each matmul's operand read), the optimizer updates the
        # f32 masters and the step requantizes them before returning —
        # so between steps the weight STREAM the forward pass reads is
        # int8 bytes + scale sidecars, and the f32 masters are touched
        # once, optimizer-side.  Deterministic requantization is what
        # makes kill-9 resume bit-identical.
        self._quant = bool(quant_weights)
        self._quant_min_size = int(quant_min_size)
        if self._quant:
            if self._sparse_specs:
                raise ConfigError(
                    "quant_weights=True is unsupported with sparse_update "
                    "embeddings (row-sliced tables have no per-out-channel "
                    "scale home)")
            if mesh is not None:
                raise ConfigError(
                    "quant_weights=True is single-chip for now (sharding "
                    "the int8+scale pair tree is the named residual)")
            if self.grad_accum_steps > 1:
                raise ConfigError(
                    "quant_weights=True with grad_accum_steps > 1 is "
                    "unsupported (the held-grads window would read stale "
                    "quantized weights)")
            if self.compute_dtype is not None:
                raise ConfigError(
                    "quant_weights=True already streams int8 weights; "
                    "combining it with compute_dtype is unsupported")
        dense_params = {k: v for k, v in self.parameters.items()
                        if k not in self._sparse_specs}
        self.opt_state = self.optimizer.init(dense_params) \
            if self.optimizer else None
        if self._sparse_specs and self.optimizer:
            # full-table optimizer slots for sparse embeddings; only touched
            # rows are gathered/updated/scattered each step (reference
            # SparseRowMatrix semantics)
            self.opt_state = {
                "dense": self.opt_state,
                "sparse": {k: self.optimizer.row_init(self.parameters[k])
                           for k in self._sparse_specs}}
        # gradient accumulation (reference num_batches_per_send_parameter's
        # local-accumulate, RemoteParameterUpdater.h:37-54): grads sum over
        # N micro-batches, the optimizer applies their mean every Nth —
        # still ONE jitted step (lax.cond-gated apply), so a big effective
        # batch fits any HBM.  Checkpointed with opt_state: resume keeps
        # mid-accumulation progress.
        if self.grad_accum_steps > 1:
            self.opt_state = {
                "inner": self.opt_state,
                "gsum": jax.tree_util.tree_map(jnp.zeros_like, dense_params),
                "tick": jnp.zeros((), jnp.int32)}
        self.model_state = self.topology.init_state()
        # multi-controller SPMD: the mesh spans devices owned by OTHER
        # processes (jax.distributed bring-up).  Every process must then
        # run the same program on the same host batches; feeds and rng are
        # assembled into global arrays (see _globalize) and checkpoints
        # gather-then-write on process 0 only.
        self._multiprocess = mesh is not None and any(
            d.process_index != jax.process_index()
            for d in np.asarray(mesh.devices).flat)
        # latest cross-rank straggler report (parallel.distributed.
        # step_skew_report), refreshed at each pass end in multi-process
        # runs (pass end is the only point every rank reaches
        # unconditionally, so the collective cannot deadlock there)
        self.last_skew_report = None
        if mesh is not None:
            rules = sharding_rules
            if self._multiprocess:
                # device_put cannot target non-addressable devices; build
                # global arrays from the (identical-per-process) host values
                ps = param_shardings(self.parameters, mesh, rules)
                self.parameters = self._globalize(self.parameters, ps)
            else:
                self.parameters = shard_params(self.parameters, mesh, rules)
        # the int8 twin of self.parameters: ONLY the quantized leaves
        # (masters carry the small f32 leaves — duplicating them in the
        # bundle would donate the same buffer twice).  Always the
        # masters' deterministic requantization; rebuilt by the step
        # every update.
        self._qtree = None
        if self._quant:
            self._qtree = self._requant(self.parameters)
        self._step_fn = None
        self._eval_fn = None
        self._gather_cache = {}   # jitted replicate-gathers (save path)
        self._compiled = {}       # feed signature -> AOT step executable
        # incremented each time the step's Python body is traced — the
        # trace-count hook: after precompile() covers every bucket, a
        # whole training pass must leave this unchanged
        self.trace_count = 0
        self._donate = donate

    # ------------------------------------------------------------ build

    def _find_sparse_specs(self):
        """Embedding layers flagged sparse_update=True whose ids come
        straight from a data layer (reference sparse-remote-update
        constraint: the sparse table's input slot).  Returns
        {param_key: {"feeds": [...], "vocab": V, "budget": K}}."""
        from paddle_tpu.ops.sparse import default_row_budget
        from paddle_tpu.utils.error import ConfigError
        specs = {}
        for node in self.topology.order:
            if node.layer_type != "embedding" \
                    or not node.cfg.get("sparse_update"):
                continue
            src = node.inputs[0]
            if src.layer_type != "data":
                raise ConfigError(
                    f"sparse_update embedding {node.name!r} needs a data "
                    "layer input (ids straight from the feed)")
            consumers = [n for n in self.topology.order
                         if src in n.inputs and n is not node]
            if consumers:
                raise ConfigError(
                    f"sparse_update embedding {node.name!r}: its id input "
                    f"{src.name!r} also feeds {consumers[0].name!r}; the "
                    "sparse path rewrites that feed and would corrupt it")
            key = self.topology._param_key(node)
            spec = specs.setdefault(
                key, {"feeds": [], "vocab": node.cfg["vocab"],
                      "budget": node.cfg.get("sparse_budget"),
                      "_nodes": set()})
            spec["feeds"].append(src.name)
            spec["_nodes"].add(id(node))
        # a sparse param key must not be shared with any NON-sparse layer:
        # sparse_step swaps params[key] for the gathered row block, which
        # would silently corrupt another reader of the full table
        for node in self.topology.order:
            key = self.topology._param_key(node)
            if key in specs and id(node) not in specs[key]["_nodes"] \
                    and node.layer_type != "data":
                raise ConfigError(
                    f"sparse_update table {key!r} is shared with layer "
                    f"{node.name!r} ({node.layer_type}), which would read "
                    "the gathered row block instead of the full table; "
                    "share only among sparse_update embeddings")
        return specs

    def _cast_compute(self, tree):
        """float32 leaves -> compute_dtype (ids, masks, lengths untouched).
        SequenceBatch data casts; lengths stay int."""
        from paddle_tpu.core.dtypes import cast_tree
        return cast_tree(tree, self.compute_dtype)

    def _loss_and_extras(self, params, state, feed, rng):
        if self.compute_dtype is not None:
            params = self._cast_compute(params)
            feed = self._cast_compute(feed)
        out, new_state = self.topology.apply(
            params, feed, mode="train", rng=rng, state=state,
            return_state=True)
        outs = out if isinstance(out, tuple) else (out,)
        n_cost = len(self.costs)
        cost_vals = outs[:n_cost]
        extra_vals = outs[n_cost:]
        # reductions in f32 regardless of compute dtype (bf16 has ~8 bits
        # of mantissa; a batch-mean in bf16 loses the loss signal)
        total = sum(jnp.mean(c.astype(jnp.float32)) for c in cost_vals)
        return total, (new_state, extra_vals)

    def _build_step(self, feed_example):
        specs = self._sparse_specs
        if specs:
            from paddle_tpu.ops import sparse as sparse_ops

            def budget_for(k, feed):
                """Static row budget derived from the TRACED feed shapes —
                jit retraces per batch shape, so a later, larger batch gets
                a larger budget instead of silently truncating the
                jnp.unique id set."""
                if specs[k]["budget"]:
                    return specs[k]["budget"]
                n = 0
                for f in specs[k]["feeds"]:
                    v = feed[f]
                    d = v.data if isinstance(v, SequenceBatch) else v
                    n += int(np.prod(d.shape))
                return sparse_ops.default_row_budget(n)

        prune_masks = self._prune_masks

        accum = self.grad_accum_steps

        def dense_step(params, opt_state, state, feed, rng):
            (loss, (new_state, extras)), grads = jax.value_and_grad(
                self._loss_and_extras, has_aux=True)(params, state, feed, rng)
            if prune_masks:
                grads = param_hooks.apply_masks(grads, prune_masks)
            if accum > 1:
                gsum = jax.tree_util.tree_map(
                    jnp.add, opt_state["gsum"], grads)
                tick = opt_state["tick"] + 1

                def apply(_):
                    mean_g = jax.tree_util.tree_map(
                        lambda s: s / accum, gsum)
                    p2, o2 = self.optimizer.update(
                        mean_g, opt_state["inner"], params)
                    return (p2, o2,
                            jax.tree_util.tree_map(jnp.zeros_like, gsum),
                            jnp.zeros((), jnp.int32))

                def hold(_):
                    return params, opt_state["inner"], gsum, tick

                new_params, inner, gsum, tick = jax.lax.cond(
                    tick >= accum, apply, hold, None)
                new_opt = {"inner": inner, "gsum": gsum, "tick": tick}
            else:
                new_params, new_opt = self.optimizer.update(
                    grads, opt_state, params)
            merged_state = {**state, **new_state}
            return new_params, new_opt, merged_state, loss, extras

        def sparse_step(params, opt_state, state, feed, rng):
            """The large-vocab path: differentiate w.r.t. the gathered
            touched-row blocks, not the [V, D] tables — the id feeds are
            rewritten to positions into those blocks so the graph runs
            unchanged (reference SparseRowMatrix + sparse remote update,
            RemoteParameterUpdater.h:265)."""
            feed = dict(feed)
            uids_map, rows_map = {}, {}
            for k, spec in specs.items():
                flats, places = [], []
                for f in spec["feeds"]:
                    v = feed[f]
                    d = v.data if isinstance(v, SequenceBatch) else v
                    flats.append(d.reshape(-1))
                    places.append((f, v, d.shape))
                allids = (jnp.concatenate(flats) if len(flats) > 1
                          else flats[0])
                uids, inv = sparse_ops.unique_touched(
                    allids, budget_for(k, feed), spec["vocab"])
                off = 0
                for f, v, shp in places:
                    n = int(np.prod(shp))
                    iv = inv[off:off + n].reshape(shp)
                    off += n
                    feed[f] = (SequenceBatch(data=iv, lengths=v.lengths)
                               if isinstance(v, SequenceBatch) else iv)
                uids_map[k] = uids
                rows_map[k] = jax.tree_util.tree_map(
                    lambda t, u=uids: sparse_ops.gather_rows(t, u),
                    params[k])

            dense_params = {k2: v for k2, v in params.items()
                            if k2 not in specs}

            def loss_fn(dp, rp):
                return self._loss_and_extras({**dp, **rp}, state, feed, rng)

            (loss, (new_state, extras)), (dg, rg) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(dense_params, rows_map)
            dstate = opt_state["dense"]
            # global-norm clipping must see ONE norm across the split grad
            # tree (dense + row blocks) or sparse/dense training diverge;
            # and like the dense path it measures AFTER the elementwise
            # clip_threshold (optim._clip applies threshold before norm)
            clip_scale = None
            if getattr(self.optimizer, "clip_norm", None):
                ct = getattr(self.optimizer, "clip_threshold", None)
                leaves = jax.tree_util.tree_leaves((dg, rg))
                if ct:
                    leaves = [jnp.clip(g, -ct, ct) for g in leaves]
                gn = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                  for g in leaves) + 1e-12)
                clip_scale = jnp.minimum(1.0, self.optimizer.clip_norm / gn)
            new_dense, new_dstate = self.optimizer.update(
                dg, dstate, dense_params, clip_scale=clip_scale)
            new_params = dict(new_dense)
            new_sparse = {}
            for k in specs:
                u = uids_map[k]
                slot_rows = jax.tree_util.tree_map(
                    lambda t, u=u: sparse_ops.gather_rows(t, u),
                    opt_state["sparse"][k])
                new_rows, new_slot_rows = self.optimizer.row_update(
                    rg[k], slot_rows, rows_map[k], dstate["step"],
                    clip_scale=clip_scale)
                new_params[k] = jax.tree_util.tree_map(
                    lambda t, nr, u=u: sparse_ops.scatter_rows(t, u, nr),
                    params[k], new_rows)
                new_sparse[k] = jax.tree_util.tree_map(
                    lambda t, nr, u=u: sparse_ops.scatter_rows(t, u, nr),
                    opt_state["sparse"][k], new_slot_rows)
            merged_state = {**state, **new_state}
            return (new_params, {"dense": new_dstate, "sparse": new_sparse},
                    merged_state, loss, extras)

        def quant_step(params, opt_state, state, feed, rng):
            """The int8 weight-streaming step: params is the {"master",
            "q"} bundle — q holds the int8+scale pairs for the big 2-D
            weights, master the f32 tree.  Forward/backward
            differentiate the DEQUANTIZED view (straight-through: the
            int8 grid is piecewise-constant, so grads at the dequantized
            values are the estimator — the mixed-precision master-weight
            recipe with int8 in place of bf16); the optimizer applies
            them to the f32 masters and the new masters requantize
            IN-step, so the returned bundle is self-consistent and
            checkpoint/resume carries both trees."""
            from paddle_tpu.quant import weights as qw
            from jax.tree_util import keystr, tree_map_with_path
            masters, qtree = params["master"], params["q"]
            # forward tree: the dequantized int8 view overlaid on the
            # masters' small f32 leaves (biases/norms — their bytes are
            # noise; this is what keeps the weight STREAM int8)
            fwd = tree_map_with_path(
                lambda path, x: qw.dequantize_leaf(qtree[keystr(path)])
                if keystr(path) in qtree else x, masters)
            (loss, (new_state, extras)), grads = jax.value_and_grad(
                self._loss_and_extras, has_aux=True)(fwd, state, feed, rng)
            if prune_masks:
                grads = param_hooks.apply_masks(grads, prune_masks)
            new_masters, new_opt = self.optimizer.update(
                grads, opt_state, masters)
            new_q = {}
            tree_map_with_path(
                lambda path, x: new_q.update(
                    {keystr(path): qw.quantize_leaf(x)})
                if keystr(path) in qtree else x, new_masters)
            merged_state = {**state, **new_state}
            return ({"master": new_masters, "q": new_q}, new_opt,
                    merged_state, loss, extras)

        base_step = quant_step if self._quant else (
            sparse_step if specs else dense_step)

        def step(params, opt_state, state, feed, rng):
            # Python body runs only under tracing: this is the trace-count
            # hook precompile()'s no-retrace guarantee is asserted against
            self.trace_count += 1
            return base_step(params, opt_state, state, feed, rng)

        if self.mesh is None:
            self._step_fn = jax.jit(
                step, donate_argnums=(0, 1) if self._donate else ())
            return

        ps = param_shardings(self.parameters, self.mesh, self.sharding_rules)
        # optimizer slots are params-shaped: inherit the param shardings
        # (the reference keeps momentum etc. sharded in the pserver the same
        # way, ParameterServer2 block-indexed buffers)
        def dense_state_shardings(dstate, dense_ps):
            if isinstance(dstate, dict) and "gsum" in dstate:
                # grad-accumulation wrapper: the accumulator shards like
                # the grads it sums (= the params), the tick replicates
                return {"inner": dense_state_shardings(dstate["inner"],
                                                       dense_ps),
                        "gsum": dense_ps,
                        "tick": replicated_shardings(dstate["tick"],
                                                     self.mesh)}
            if isinstance(dstate, dict) and "slots" in dstate:
                return {"step": replicated_shardings(dstate["step"],
                                                     self.mesh),
                        "slots": {k: dense_ps for k in dstate["slots"]}}
            return replicated_shardings(dstate, self.mesh)

        if specs:
            dense_ps = {k: v for k, v in ps.items() if k not in specs}
            os_ = {"dense": dense_state_shardings(self.opt_state["dense"],
                                                  dense_ps),
                   "sparse": {k: {slot: ps[k]
                                  for slot in self.opt_state["sparse"][k]}
                              for k in specs}}
        else:
            os_ = dense_state_shardings(self.opt_state, ps)
        ss = replicated_shardings(self.model_state, self.mesh)
        fs = batch_shardings(feed_example, self.mesh)
        rs = replicated_shardings(jnp.zeros(2, jnp.uint32), self.mesh)
        self._step_fn = jax.jit(
            step,
            in_shardings=(ps, os_, ss, fs, rs),
            out_shardings=(ps, os_, ss,
                           replicated_shardings(0.0, self.mesh),
                           None),
            donate_argnums=(0, 1) if self._donate else ())

    # ------------------------------------------------------------ train

    def _globalize(self, tree, shardings):
        """Host pytree -> global jax.Arrays (parallel.sharding.
        globalize_pytree).  Already-global leaves (e.g. fresh-init params
        kept by a load_parameters 'rand' merge) are gathered to host
        first."""
        from paddle_tpu.parallel.sharding import globalize_pytree
        return globalize_pytree(tree, shardings,
                                gather=self._devget_replicated)

    def _globalize_step_inputs(self, feed, step_rng):
        if not self._multiprocess:
            return feed, step_rng
        feed = self._globalize(feed, batch_shardings(feed, self.mesh))
        return feed, self._globalize_rng(step_rng)

    def _globalize_rng(self, step_rng):
        """rng half of _globalize_step_inputs — the prefetch path already
        globalized the feed on the producer thread."""
        if not self._multiprocess:
            return step_rng
        return self._globalize(
            step_rng, replicated_shardings(step_rng, self.mesh))

    # ------------------------------------------------------------ warm-up

    def precompile(self, batch_specs):
        """AOT warm-up: compile the train step once per feed spec so a
        bucketed pass never pays an XLA compile inside the timed loop.

        batch_specs: iterable of feed dicts {data_layer_name: leaf} where
        a leaf is a concrete array, a ``jax.ShapeDtypeStruct``, or a
        SequenceBatch of either — one spec per length bucket.
        ``DataFeeder.feed_specs(batch_size, bucket_bounds)`` builds them
        from the feeding types + ``core.sequence.bucket_boundaries``.

        Each spec is lowered and compiled via ``jax.jit(step).lower(...)
        .compile()`` and the executable is dispatched by feed shape in
        ``train()``/``train_one_batch()`` — a subsequent pass over those
        buckets triggers no new traces (assert with ``trace_count``).
        Returns the number of NEW executables compiled.  Pair with the
        ``jax_compilation_cache_dir`` flag (utils/flags.py) to persist
        the compilations across process restarts.
        """
        n_new = 0
        for spec in batch_specs:
            feed = _abstract_feed(spec)
            sig = _feed_signature(feed)
            if sig in self._compiled:
                continue
            self._compiled[sig] = self.lower_step(feed).compile()
            n_new += 1
        if n_new:
            logger.info("precompiled %d step executable(s) (%d cached)",
                        n_new, len(self._compiled))
        return n_new

    def lower_step(self, feed_spec):
        """Lower (not compile, never execute) the jitted train step for
        one feed spec — the AOT building block behind ``precompile`` and
        the hook the analytic perf layer (``paddle_tpu/perf``) uses to
        read XLA's cost model for a trainer step without a device run.

        feed_spec: one feed dict of concrete arrays or
        ``jax.ShapeDtypeStruct`` leaves (``DataFeeder.feed_specs``
        builds them).  Returns the ``jax.stages.Lowered``.
        """
        feed = _abstract_feed(feed_spec)
        if self._step_fn is None:
            self._build_step(feed)
        rng_spec = jax.ShapeDtypeStruct(np.shape(self.rng), self.rng.dtype)
        return self._step_fn.lower(
            self._step_params(), self.opt_state, self.model_state, feed,
            rng_spec)

    def _requant(self, params):
        """The masters' int8 twin: quantize every eligible 2-D f32
        weight (quant/weights.quantize_tree's predicate) into a
        path-keyed flat dict {tree path: {"q", "s"}} — ONLY the
        quantized leaves (the bundle must not duplicate the small f32
        leaves, or the step would donate the same buffer twice).
        Deterministic, so rebuilding it from loaded masters is
        bit-exact."""
        from paddle_tpu.quant import weights as qw
        from jax.tree_util import keystr, tree_map_with_path
        out = {}

        def visit(path, x):
            q = qw.quantize_tree(x, min_size=self._quant_min_size)
            if qw.is_quantized_leaf(q):
                out[keystr(path)] = q
            return x

        tree_map_with_path(visit, params)
        return out

    def _step_params(self):
        """The jitted step's first operand: the plain params tree, or —
        in quant_weights mode — the {"master": f32, "q": int8+scale}
        bundle (both donated together)."""
        if self._quant:
            return {"master": self.parameters, "q": self._qtree}
        return self.parameters

    def _absorb_step_params(self, p):
        """Unpack what the step returned back into self.parameters (+
        the int8 twin in quant mode) — `_step_params`' inverse."""
        if self._quant:
            self.parameters, self._qtree = p["master"], p["q"]
        else:
            self.parameters = p

    def _dispatch_step(self, feed):
        """The executable for this feed shape: a precompiled bucket
        program if one exists, else the jitted step (which traces on new
        shapes)."""
        if self._compiled:
            fn = self._compiled.get(_feed_signature(feed))
            if fn is not None:
                return fn
        return self._step_fn

    def log_parameter_stats(self):
        """Per-parameter value abs-max/avg dump (the reference's
        --show_parameter_stats_period, TrainerInternal.cpp:210-214)."""
        for path, leaf in jax.tree_util.tree_leaves_with_path(self.parameters):
            a = jnp.abs(leaf)
            logger.info("  param %s shape=%s absmax=%.5g absavg=%.5g",
                        jax.tree_util.keystr(path), tuple(leaf.shape),
                        float(jnp.max(a)), float(jnp.mean(a)))

    def train(self, reader, num_passes=1, event_handler=None, feeding=None,
              save_dir=None, saving_period=1, save_only_one=False,
              test_reader=None, test_period=0, log_period=100,
              buffered_batches=4, show_parameter_stats_period=0,
              save_on_signal=True, prefetch=0, progress_timeout_s=600.0,
              resume=False):
        """reader: callable -> iterator of batches (lists of samples).
        feeding: {data_layer_name: InputType} or a DataFeeder.

        resume: crash-resume (resilience layer).  When True and save_dir
        holds checkpoints, load the latest COMPLETE pass dir (the atomic
        writer guarantees a kill -9 mid-save can only ever leave a
        hidden ``.tmp-`` staging dir, which is never eligible), restore
        params/opt/model state AND the training rng stream from it, and
        continue at the following pass — so a killed-and-restarted run's
        final parameters are bit-identical to an uninterrupted one
        (tests/test_resilience.py pins it, kill -9 included).  A SIGTERM
        preemption checkpoint is MID-pass: its meta carries
        ``batches_done``, and resume re-enters that same pass skipping
        exactly those batches (no step, no rng split), so preemption
        resume is bit-identical too — provided the reader replays the
        same batches per pass (a deterministic reader, the same contract
        the pass loop already assumes).  With no checkpoint yet,
        training starts fresh — ``resume=True`` is safe as the default
        posture of a supervised job.

        prefetch: run feeder conversion AND the H2D transfer on a bounded
        background thread, `prefetch` batches ahead of the step
        (data.prefetch.ShardedPrefetcher — the DoubleBuffer story
        completed to the device side).  The hot loop then dequeues
        device-resident, mesh-sharded feeds, so step wall time excludes
        input time; the per-period log line's h2d_wait column shows the
        residual input wait (~0 when the pipeline keeps up).  Numerically
        identical to prefetch=0 (same batches, same order, donation-safe).
        Costs ~prefetch+1 extra batches of HBM; supersedes
        buffered_batches (the host-only half) when set.

        Multi-process note: every rank's reader must yield the SAME number
        of batches per pass — cross-rank collectives (the step's psums,
        the pass-end skew report) hang otherwise.  The pass-end
        equal-progress check (parallel.distributed.check_equal_progress)
        runs over the coordination service's HOST-side channel, so at
        PASS END a violation surfaces as a hard error — ConfigError
        naming each rank's count, or a barrier timeout when a rank is
        already wedged mid-pass — instead of a silent deadlock.  (A rank
        that stops mid-pass can still wedge peers at the next device
        sync point inside THEIR pass — e.g. the log-period cost mean —
        before they reach this guard; that is inherent to SPMD and the
        cluster runtime's reap timeout is the backstop there.)
        progress_timeout_s
        bounds that pass-end barrier: a rank stopping early on SIGTERM
        waits there for its peers to finish the pass, so on long passes
        raise it above the worst-case pass remainder or the preempted
        rank times out before the peers arrive (and before its
        checkpoint).

        save_on_signal: when save_dir is set and train() runs on the main
        thread, SIGTERM requests a graceful stop — the loop finishes the
        current batch, writes a checkpoint (meta carries preempted=true
        and the interrupted pass), and returns instead of dying mid-pass.
        That is the TPU-preemption story: the maintenance event's TERM
        becomes a resumable pass boundary (reference recovery was
        checkpoint/restart only, Trainer.cpp:245-249)."""
        event_handler = event_handler or (lambda e: None)
        feeder = feeding if isinstance(feeding, DataFeeder) else (
            DataFeeder(feeding) if feeding else None)

        first_pass = 0
        resume_skip_batches = 0
        if resume:
            if not save_dir:
                raise ConfigError("train(resume=True) needs save_dir=")
            try:
                meta = self.load(save_dir)
            except FileNotFoundError:
                meta = None     # nothing saved yet: a fresh run
            if meta is not None:
                if meta.get("preempted") and meta.get("batches_done") \
                        is not None:
                    # a preemption checkpoint is MID-pass: re-enter that
                    # pass and skip exactly the batches it already
                    # trained (no step, no rng split), so the remainder
                    # replays bit-identically
                    first_pass = int(meta["pass_id"])
                    resume_skip_batches = int(meta["batches_done"])
                else:
                    first_pass = int(meta["pass_id"]) + 1
                if meta.get("rng") is not None:
                    # the per-batch rng stream continues exactly where
                    # the checkpointed pass left it — resumed training
                    # is bit-identical to uninterrupted
                    self.rng = jnp.asarray(np.asarray(meta["rng"],
                                                      np.uint32))
                logger.info(
                    "resume: loaded pass %d from %s%s; continuing at "
                    "pass %d%s", meta["pass_id"], save_dir,
                    " (preemption checkpoint)" if meta.get("preempted")
                    else "", first_pass,
                    f" batch {resume_skip_batches}"
                    if resume_skip_batches else "")

        self._stop_signal = None
        prev_handler = None
        handler_armed = False
        # multi-process too, and there even WITHOUT save_dir: skewed
        # signal delivery diverges per-rank batch counts, but the
        # pass-end equal-progress gather coordinates the ranks — a
        # preempted rank reports its count as preempted, every rank
        # stops together, and host syncs/the checkpoint are skipped when
        # the decoded counts show wedged device queues.  An unhandled
        # SIGTERM would instead kill the rank instantly and strand its
        # peers at the barrier; only the checkpoint WRITE needs save_dir
        if save_on_signal and (save_dir or self._multiprocess):
            import signal as _signal

            def _request_stop(signum, frame):
                self._stop_signal = signum
                logger.info("SIGTERM: finishing current batch, then %s",
                            f"checkpointing to {save_dir}" if save_dir
                            else "stopping at pass end (no save_dir)")
            try:
                prev_handler = _signal.signal(_signal.SIGTERM, _request_stop)
                handler_armed = True
            except ValueError:      # not the main thread — feature off
                prev_handler = None

        def resolve(slot, extras, feed):
            kind, key = slot
            return feed.get(key) if kind == "feed" else extras[key]

        def update_evaluators(extras, feed):
            for spec, slot, eslots in zip(self.evaluators, self._eval_slots,
                                          self._eval_extra_slots):
                lab = feed.get(spec.label.name) if spec.label is not None else None
                wgt = feed.get(spec.weight.name) if spec.weight is not None else None
                extra = {kw: resolve(s, extras, feed)
                         for kw, s in eslots.items()}
                spec.update(resolve(slot, extras, feed), lab, wgt,
                            extra=extra)

        def eval_log_suffix():
            parts = []
            for spec in self.evaluators:
                r = spec.result()
                if r is not None:
                    parts.append(f"{spec.name}={r:.5f}" if isinstance(r, float)
                                 else f"{spec.name}={r}")
            return (" Eval: " + " ".join(parts)) if parts else ""

        try:
            for pass_id in range(first_pass, num_passes):
                event_handler(events.BeginPass(pass_id))
                for spec in self.evaluators:
                    spec.reset()
                batch_reader = reader
                if buffered_batches and not prefetch:
                    # host-only double buffering; with prefetch the device
                    # pipeline's own thread covers it
                    batch_reader = reader_mod.buffered(reader, buffered_batches)
                prefetcher = None
                # ONE conversion fn for both paths — the bit-identical
                # guarantee between prefetch=N and prefetch=0 rests on it
                convert = (lambda b: _normalize_feed(feeder(b)
                                                     if feeder else b))
                if prefetch:
                    from paddle_tpu.data.prefetch import (ShardedPrefetcher,
                                                          device_placer)
                    prefetcher = ShardedPrefetcher(
                        batch_reader, depth=prefetch, convert=convert,
                        place=device_placer(self.mesh, self._multiprocess))
                # running device-side sums: no host sync in the hot loop —
                # cost only crosses to the host every log_period (and for the
                # event stream, whose .cost is the device scalar; float() it
                # lazily in your handler if you need the number immediately)
                cost_sum = jnp.zeros(())
                if self._multiprocess:
                    # keep the accumulator global-replicated so per-step
                    # arithmetic stays on-device (no host sync in the hot loop)
                    cost_sum = self._globalize(
                        cost_sum, replicated_shardings(cost_sum, self.mesh))
                n_batches = 0
                # preemption resume: the first resumed pass consumes-and-
                # skips the batches the checkpoint already trained
                skip_left, resume_skip_batches = resume_skip_batches, 0
                pass_skip = skip_left   # already-trained prefix of this
                #                         pass (for a re-preemption's
                #                         batches_done accounting)
                window = []
                skew_window = []     # host-side step wall times this pass
                h2d_window = 0.0     # input wait this log period (seconds)
                t0 = time.time()
                feed_iter = iter(prefetcher) if prefetcher is not None \
                    else iter(batch_reader())
                batch_id = -1
                try:
                    while True:
                        # h2d_wait: host time blocked acquiring the next
                        # device-ready feed — with prefetch this is the queue
                        # wait (~0 when the pipeline keeps up), without it the
                        # reader + feeder conversion run inline here
                        t_in = time.perf_counter()
                        try:
                            item = next(feed_iter)
                        except StopIteration:
                            break
                        if skip_left > 0:
                            # already trained before the preemption: no
                            # step, no rng split, no events — the
                            # checkpointed rng/params sit exactly here
                            skip_left -= 1
                            batch_id += 1
                            continue
                        feed = item if prefetcher is not None else \
                            convert(item)
                        h2d_dt = time.perf_counter() - t_in
                        batch_id += 1
                        event_handler(events.BeginIteration(pass_id, batch_id))
                        self.rng, step_rng = jax.random.split(self.rng)
                        if self._step_fn is None:
                            self._build_step(feed)
                        if prefetcher is None:
                            # multi-process: the synchronous path's global-
                            # array H2D assembly counts into h2d_wait too —
                            # otherwise the prefetch 0-vs-N comparison the
                            # column exists for is apples-to-oranges.
                            # (Single-process this is a no-op; there the
                            # sync path's transfer happens lazily inside
                            # the jit call and lands in step time.)
                            t_g = time.perf_counter()
                            feed, step_rng = self._globalize_step_inputs(
                                feed, step_rng)
                            h2d_dt += time.perf_counter() - t_g
                        else:       # feed was placed on the producer thread;
                            # rng assembly still runs here and counts like
                            # the synchronous path's (same per-step work on
                            # both sides of the 0-vs-N comparison)
                            t_g = time.perf_counter()
                            step_rng = self._globalize_rng(step_rng)
                            h2d_dt += time.perf_counter() - t_g
                        global_stats.get("h2d_wait").add(h2d_dt)
                        h2d_window += h2d_dt
                        # chaos hook (resilience/faults.py), host-side so
                        # the compiled step is untouched; an injected
                        # fault unwinds like any real step crash (the
                        # finally blocks still close the prefetcher,
                        # land pending saves, restore the handler)
                        _faults.hit("trainer.step")
                        step_fn = self._dispatch_step(feed)
                        t_step = time.perf_counter()
                        # tracing hook (obs/trace.py), host-side like the
                        # chaos hook above: the span wraps the step
                        # DISPATCH and carries this batch's input wait,
                        # so a Chrome trace shows train steps next to
                        # h2d stalls; strict no-op when tracing is off
                        with _obstrace.span(
                                "trainer.step", root=False,
                                pass_id=pass_id, batch=batch_id,
                                h2d_wait_ms=round(h2d_dt * 1e3, 3)), \
                                timer("train_step"):
                            (new_p, self.opt_state, self.model_state,
                             cost, extras) = step_fn(
                                self._step_params(), self.opt_state,
                                self.model_state, feed, step_rng)
                            self._absorb_step_params(new_p)
                        # per-step distribution (BarrierStat skew-profiling role):
                        # record this step's own delta, not the cumulative timer
                        from paddle_tpu.utils.stats import step_histogram
                        step_dt = time.perf_counter() - t_step
                        step_histogram.add(step_dt)
                        cost_sum = cost_sum + cost
                        if self._multiprocess and len(skew_window) < 10000:
                            # consumed by the PASS-END cross-rank report (a
                            # collective can only live where every rank is
                            # guaranteed to arrive); bounded like step_histogram
                            skew_window.append(step_dt)
                        n_batches += 1
                        if log_period:      # only the log line consumes it;
                            window.append(cost)  # log_period=0 must not pin
                        if self.evaluators:      # a device scalar per batch
                            update_evaluators(extras, feed)
                        if log_period and (batch_id + 1) % log_period == 0:
                            c = float(jnp.mean(jnp.stack(window)))
                            window = []
                            dt = (time.time() - t0) / log_period
                            logger.info("Pass %d Batch %d Cost %.5f (%.1f ms/batch"
                                        " h2d_wait=%.2fms)%s",
                                        pass_id, batch_id + 1, c, dt * 1e3,
                                        h2d_window / log_period * 1e3,
                                        eval_log_suffix())
                            h2d_window = 0.0
                            t0 = time.time()
                        if (show_parameter_stats_period
                                and (batch_id + 1) % show_parameter_stats_period == 0):
                            self.log_parameter_stats()
                        event_handler(events.EndIteration(
                            pass_id, batch_id, cost=cost,
                            evaluator_results={f"extra_{i}": e
                                               for i, e in enumerate(extras)}))
                        if self._stop_signal is not None:
                            break
                finally:
                    if prefetcher is not None:
                        prefetcher.close()
                sync_safe = True
                if self._multiprocess:
                    # pass end is the ONE point every rank reaches no
                    # matter how many batches its reader produced, so the
                    # cross-rank collectives live here: first the
                    # equal-progress guard (unequal batch counts raise a
                    # ConfigError instead of deadlocking the job), then
                    # the straggler/skew report (reference BarrierStat).
                    # On SIGTERM a rank still participates but marks its
                    # count preempted: signal delivery is not
                    # synchronized across ranks, so unequal counts are
                    # expected then, a silently-skipping rank would
                    # strand the others at the barrier, and the
                    # preemption checkpoint below must still run.  A
                    # preempted peer also means WE must stop after this
                    # pass — it will not join the next pass's collectives
                    from paddle_tpu.parallel.distributed import (
                        check_equal_progress, step_skew_report)
                    common, preempted = check_equal_progress(
                        n_batches, name=f"pass {pass_id}",
                        timeout_s=progress_timeout_s,
                        skip=self._stop_signal is not None)
                    # common=None: counts diverged (preempted mid-step
                    # skew) — a rank dispatched steps whose collectives
                    # will never complete, so ANY host sync on device
                    # values (pass cost, skew report, checkpoint gather)
                    # could hang; skip them all, consistently on every
                    # rank (all ranks see the same counts)
                    sync_safe = common is not None
                    if not preempted:
                        self.last_skew_report = step_skew_report(skew_window)
                    elif self._stop_signal is None:
                        import signal as _sig
                        logger.warning(
                            "a peer rank was preempted; stopping after "
                            "pass %d too (continuing would wedge on its "
                            "missing collectives)", pass_id)
                        self._stop_signal = int(_sig.SIGTERM)
                # sync_safe=False: evaluator results are device scalars from
                # the same possibly-wedged steps as cost_sum — no host syncs
                pass_cost = (float(cost_sum) / n_batches
                             if n_batches and sync_safe else float("nan"))
                logger.info("Pass %d done, mean cost %.5f%s", pass_id, pass_cost,
                            eval_log_suffix() if sync_safe else "")
                # per-pass step-time distribution (the BarrierStat successor:
                # in synchronous SPMD the skew diagnostic is p99/p50 spread)
                from paddle_tpu.utils.stats import step_histogram
                if step_histogram.samples:
                    logger.info("  %s", step_histogram.summary())
                    step_histogram.reset()
                if test_reader is not None and self._stop_signal is None and (
                        not test_period or (pass_id + 1) % test_period == 0):
                    tc = self.test(test_reader, feeding=feeder)
                    event_handler(events.EndTesting(pass_id, tc))
                if save_dir and self._stop_signal is not None:
                    if not sync_safe:
                        # parameters depend on dispatched steps whose
                        # collectives will never complete — the gather
                        # inside save() would hang, not checkpoint
                        logger.warning(
                            "preempted with unequal per-rank batch counts; "
                            "device state is unrecoverable — SKIPPING the "
                            "preemption checkpoint (last periodic "
                            "checkpoint remains the restart point)")
                    else:
                        # preemption checkpoint: blocking (the process is
                        # about to be reaped — there may be no later sync
                        # point)
                        # batches_done lets train(resume=True) re-enter
                        # THIS pass skipping exactly the trained prefix
                        # (bit-identical preemption resume)
                        path = self.save(save_dir, pass_id,
                                         save_only_one=save_only_one,
                                         block=True,
                                         extra={"preempted": True,
                                                "signal":
                                                int(self._stop_signal),
                                                "batches_done":
                                                pass_skip + n_batches})
                        if path:
                            logger.info("preemption checkpoint %s; stopping "
                                        "after pass %d", path, pass_id)
                elif save_dir and (pass_id + 1) % saving_period == 0:
                    # single-process saves overlap the disk write with the
                    # next pass (the snapshot itself is taken synchronously);
                    # multi-process stays blocking for the barrier guarantee
                    path = self.save(save_dir, pass_id,
                                     save_only_one=save_only_one,
                                     block=self._multiprocess)
                    if path:
                        # async schedule is not persistence yet; don't claim it
                        logger.info("saved checkpoint %s" if self._multiprocess
                                    else "saving checkpoint %s (async)", path)
                event_handler(events.EndPass(pass_id))
                if self._stop_signal is not None:
                    break
        finally:
            # durability + handler restoration even when an exception
            # unwinds out of the loop (a leaked handler would make the
            # process unkillable by SIGTERM)
            try:
                if save_dir:
                    from paddle_tpu.trainer import checkpoint as _ckpt
                    _ckpt.wait_pending(save_dir)
            finally:
                # restore even when wait_pending re-raises a save failure;
                # signal.signal() returns None when the prior handler was
                # installed outside Python, so gate on the armed flag, not
                # the returned value
                if handler_armed:
                    import signal as _signal
                    _signal.signal(_signal.SIGTERM,
                                   prev_handler if prev_handler is not None
                                   else _signal.SIG_DFL)


    def train_one_batch(self, batch, feeder=None):
        """One jitted train step on one host batch; returns the device
        cost scalar (reference TrainerInternal::trainOneBatch:66 at API
        level — the CLI `time` job and custom loops use this)."""
        feeder = feeder if isinstance(feeder, DataFeeder) else (
            DataFeeder(feeder) if feeder else None)
        feed = _normalize_feed(feeder(batch) if feeder else batch)
        self.rng, step_rng = jax.random.split(self.rng)
        if self._step_fn is None:
            self._build_step(feed)
        feed, step_rng = self._globalize_step_inputs(feed, step_rng)
        (new_p, self.opt_state, self.model_state,
         cost, _extras) = self._dispatch_step(feed)(
            self._step_params(), self.opt_state, self.model_state,
            feed, step_rng)
        self._absorb_step_params(new_p)
        return cost

    # ------------------------------------------------------------ test

    def _build_eval(self):
        def ev(params, state, feed):
            if self.compute_dtype is not None:
                params = self._cast_compute(params)
                feed = self._cast_compute(feed)
            out = self.topology.apply(params, feed, mode="test", state=state)
            outs = out if isinstance(out, tuple) else (out,)
            cost_vals = outs[:len(self.costs)]
            # f32 reduction regardless of compute dtype (same rationale as
            # the train path: a bf16 batch-mean loses the cost signal)
            return (sum(jnp.mean(c.astype(jnp.float32))
                        for c in cost_vals), outs[len(self.costs):])
        self._eval_fn = jax.jit(ev)

    def test(self, reader, feeding=None):
        feeder = feeding if isinstance(feeding, DataFeeder) else (
            DataFeeder(feeding) if feeding else None)
        if self._eval_fn is None:
            self._build_eval()
        total, n = 0.0, 0
        for batch in reader():
            feed = _normalize_feed(feeder(batch) if feeder else batch)
            if self._multiprocess:
                feed = self._globalize(feed,
                                       batch_shardings(feed, self.mesh))
            cost, _ = self._eval_fn(self.parameters, self.model_state, feed)
            total += float(cost)
            n += 1
        mean = total / max(n, 1)
        logger.info("Test cost %.5f over %d batches", mean, n)
        return mean

    # ------------------------------------------------------------ io

    def save(self, save_dir, pass_id=0, save_only_one=False, block=True,
             extra=None):
        params, opt_state = self.parameters, self.opt_state
        if self._quant and self._qtree:
            # checkpoint BOTH trees (kill-9 resume must be
            # bit-identical; requantizing on load would also be exact —
            # quantize_tree is deterministic — but carrying the int8
            # twin keeps the resumed step operand byte-equal by
            # construction, no recompute in the restore path)
            params = {"master": self.parameters, "q": self._qtree}
        if self._multiprocess:
            block = True    # the barrier promise needs the file on disk
            # model-sharded leaves are not process-0-addressable: gather to
            # replicated (a jitted identity re-sharding), then only the
            # coordinator writes; everyone waits so a crash right after
            # the pass boundary can always resume from this checkpoint
            from paddle_tpu.parallel import barrier
            params = self._devget_replicated(params, "params")
            opt_state = self._devget_replicated(opt_state, "opt")
            if jax.process_index() != 0:
                barrier(f"save{pass_id}")
                return None
        extra = dict(extra or {})
        extra.setdefault("grad_accum_steps", self.grad_accum_steps)
        try:
            # the rng stream rides in meta so train(resume=True) can
            # continue it bit-identically (raw uint32 keys; typed-key
            # arrays would fail the cast and simply skip the field)
            extra.setdefault("rng", np.asarray(
                jax.device_get(self.rng), np.uint32).tolist())
        except (TypeError, ValueError):
            pass
        path = save_checkpoint(save_dir, pass_id, params,
                               opt_state, self.model_state, extra=extra,
                               save_only_one=save_only_one, block=block)
        if self._multiprocess:
            from paddle_tpu.parallel import barrier
            barrier(f"save{pass_id}")
        return path

    def _devget_replicated(self, tree, cache_key=None):
        if tree is None:
            return None
        gather = self._gather_cache.get(cache_key) if cache_key else None
        if gather is None:
            shardings = replicated_shardings(tree, self.mesh)
            gather = jax.jit(lambda t: t, out_shardings=shardings)
            if cache_key:
                self._gather_cache[cache_key] = gather
        return jax.device_get(gather(tree))

    def load(self, save_dir, pass_id=None):
        params, opt_state, model_state, meta = load_checkpoint(save_dir, pass_id)
        bundled = isinstance(params, dict) and set(params) == {"master", "q"}
        if self._quant:
            if bundled:
                self.parameters, self._qtree = params["master"], params["q"]
            else:
                # plain (f32) checkpoint into a quant trainer: adopt the
                # masters and requantize deterministically
                self.parameters = params
                self._qtree = self._requant(params)
        elif bundled:
            # quant checkpoint into a plain trainer: the masters ARE the
            # f32 params; the int8 twin is dropped
            self.parameters = params["master"]
        else:
            self.parameters = params
        if opt_state is not None:
            opt_state = self._adapt_accum_state(opt_state, meta)
            self.opt_state = opt_state
        if model_state is not None:
            self.model_state = model_state
        self._refresh_prune_masks()
        self._reglobalize_after_load()
        return meta

    def _adapt_accum_state(self, opt_state, meta):
        """Reconcile a checkpoint's grad-accumulation wrapper with THIS
        trainer's grad_accum_steps.  Clean boundaries (tick 0) convert
        freely in both directions — a test job or an accum-setting change
        just works; only a checkpoint holding genuinely mid-accumulation
        grads under a DIFFERENT accum value is an error (replaying those
        grads at another denominator would mis-scale the next step)."""
        wrapped = isinstance(opt_state, dict) and "gsum" in opt_state
        want = self.grad_accum_steps > 1
        tick = int(opt_state["tick"]) if wrapped else 0
        stored = meta.get("grad_accum_steps")
        if wrapped and not want:
            if tick:
                logger.warning(
                    "checkpoint holds %d accumulated micro-batch grads "
                    "(grad_accum_steps=%s) — discarded, this trainer "
                    "doesn't accumulate", tick, stored or ">1")
            return opt_state["inner"]
        if want and not wrapped:
            dense = {k: v for k, v in self.parameters.items()
                     if k not in self._sparse_specs}
            return {"inner": opt_state,
                    "gsum": jax.tree_util.tree_map(jnp.zeros_like, dense),
                    "tick": jnp.zeros((), jnp.int32)}
        if wrapped and want and stored and stored != self.grad_accum_steps:
            if tick:
                raise ConfigError(
                    f"checkpoint is mid-accumulation (tick={tick}) under "
                    f"grad_accum_steps={stored}; this trainer has "
                    f"{self.grad_accum_steps} — resume with the matching "
                    "setting (or from a pass boundary)")
            # clean boundary: gsum is zeros, the wrapper carries over
        return opt_state

    def _reglobalize_after_load(self):
        """Checkpoint leaves are host arrays; on a process-spanning mesh
        they must become global arrays again (jit cannot device_put host
        values onto non-addressable devices).  Params take their rule
        shardings; opt/model state re-enter replicated — the next step's
        explicit in_shardings reshards them to their true layout."""
        if not self._multiprocess:
            return
        ps = param_shardings(self.parameters, self.mesh,
                             self.sharding_rules)
        self.parameters = self._globalize(self.parameters, ps)
        if self.opt_state is not None:
            self.opt_state = self._globalize(
                self.opt_state,
                replicated_shardings(self.opt_state, self.mesh))
        if self.model_state:
            self.model_state = self._globalize(
                self.model_state,
                replicated_shardings(self.model_state, self.mesh))

    def load_parameters(self, save_dir, pass_id=None,
                        missing_strategy="fail"):
        """Warm-start parameters only (reference --init_model_path +
        --load_missing_parameter_strategy, ParamUtil.cpp loadParameters):
        params present in the checkpoint are taken; params absent follow
        missing_strategy = fail | rand | zero (rand keeps this trainer's
        fresh initialization, the reference's 'rand' semantics)."""
        params, _opt, model_state, _ = load_checkpoint(save_dir, pass_id)
        merged = {}
        for key, init_val in self.parameters.items():
            if key in params:
                merged[key] = params[key]
            elif missing_strategy == "rand":
                merged[key] = init_val
            elif missing_strategy == "zero":
                merged[key] = jax.tree_util.tree_map(jnp.zeros_like, init_val)
            else:
                raise ConfigError(
                    f"parameter {key!r} missing from {save_dir} "
                    "(load_missing_parameter_strategy=fail)")
        extra = set(params) - set(self.parameters)
        if extra:
            logger.warning("checkpoint parameters not in this model "
                           "(ignored): %s", sorted(extra))
        self.parameters = merged
        if model_state:
            self.model_state = {**self.model_state, **model_state}
        self._refresh_prune_masks()
        self._reglobalize_after_load()

    def _refresh_prune_masks(self):
        """Re-derive pruning masks after self.parameters was replaced
        (checkpoint load / warm start): a sparsity_ratio mask must reflect
        the LOADED weights, not the discarded random init (a resumed pruned
        model re-masks to exactly its checkpointed zeros), and the value
        mask is re-applied.  The cached step closure holds the old masks,
        so it is invalidated too."""
        if not self._prune_masks:
            return
        self._prune_masks = param_hooks.build_masks(
            self.topology, self.parameters)
        self.parameters = param_hooks.apply_masks(
            self.parameters, self._prune_masks)
        self._step_fn = None
        self._compiled = {}     # AOT executables hold the old masks too

    def log_layer_stats(self, feed):
        """Per-layer output abs-mean/abs-max on one batch (reference
        --show_layer_stat, TrainerInternal.cpp showParameterStats's layer
        twin: printAllStatus each log_period)."""
        from paddle_tpu.layers.graph import value_data
        feed = _normalize_feed(feed)
        vals = self.topology.apply(
            self.parameters, feed, mode="test", state=self.model_state,
            extra_outputs=[n for n in self.topology.order
                           if n.layer_type != "data"])
        vals = vals if isinstance(vals, tuple) else (vals,)
        nodes = [n for n in self.topology.order if n.layer_type != "data"]
        n_named = len(self.topology.outputs)
        for node, v in zip(nodes, vals[n_named:]):
            d = value_data(v)
            if hasattr(d, "astype"):
                a = jnp.abs(d.astype(jnp.float32))
                logger.info("  layer %s [%s] absavg=%.5g absmax=%.5g",
                            node.name, node.layer_type,
                            float(jnp.mean(a)), float(jnp.max(a)))


class Inferencer:
    """paddle.v2.inference equivalent: run a topology in test mode.

    compute_dtype=jnp.bfloat16 runs the forward in bf16 (params cast at
    the jit boundary; outputs returned in f32) — the serving-side half of
    the trainer's mixed-precision option.  quantize="int8" stores the
    weights int8 with per-channel scales (export.quantize_params): ~4x
    less weight-stream HBM per request, dequant fused into the matmuls."""

    def __init__(self, output_layer, parameters, model_state=None,
                 compute_dtype=None, quantize=None):
        outs = output_layer if isinstance(output_layer, (list, tuple)) \
            else [output_layer]
        self.topology = Topology(list(outs))
        dequant = None
        # .parameters stays the caller's FLOAT pytree in every mode (other
        # consumers — export_inference, a second Inferencer — rely on it);
        # the int8 representation is an internal execution detail
        self.parameters = parameters
        self._exec_params = parameters
        if quantize is not None:
            from paddle_tpu.export import quantize_params
            if quantize != "int8":
                raise ValueError(
                    f"quantize={quantize!r} (supported: None, 'int8')")
            self._exec_params, dequant = quantize_params(parameters)
        self.model_state = model_state or {}

        def fwd(p, s, feed):
            if dequant is not None:
                p = dequant(p)
            if compute_dtype is not None:
                from paddle_tpu.core.dtypes import cast_tree
                p = cast_tree(p, compute_dtype)
                feed = cast_tree(feed, compute_dtype)
            out = self.topology.apply(p, feed, mode="test", state=s)
            if compute_dtype is not None:
                out = jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.float32)
                    if hasattr(x, "dtype") and x.dtype == compute_dtype
                    else x, out)
            return out
        # the raw (un-jitted) forward is the hook serving.InferenceEngine
        # wraps to AOT-compile one executable per batch bucket
        self._fwd = fwd
        self._fn = jax.jit(fwd)

    def infer(self, feed_or_batch, feeding=None):
        if feeding is not None and not isinstance(feed_or_batch, dict):
            feeder = feeding if isinstance(feeding, DataFeeder) else DataFeeder(feeding)
            feed = feeder(feed_or_batch)
        else:
            feed = feed_or_batch
        feed = _normalize_feed(feed)
        return self._fn(self._exec_params, self.model_state, feed)


def infer(output_layer, parameters, input, feeding=None):
    return Inferencer(output_layer, parameters).infer(input, feeding=feeding)


# the modern name for the training driver (SGD is the v2-compat spelling):
# Trainer.train(prefetch=...), Trainer.precompile(...) read naturally
Trainer = SGD
