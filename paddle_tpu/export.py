"""Portable model export: serialize inference to StableHLO.

The reference's deployment story was `merge_model` (config + weights packed
into one file, paddle/trainer/MergeModel.cpp) consumed by the C API
(capi/) from C++ services.  The TPU-native equivalent: `jax.export` lowers
the jitted inference function — with the trained parameters baked in as
constants — to serialized StableHLO, a single self-contained artifact any
XLA runtime (Python, C++, TF serving via PJRT) can load and execute
without this framework installed.  SURVEY §7 stage 11.

    from paddle_tpu import export as pexport
    art = pexport.export_inference(out_layer, trainer.parameters,
                                   feed_spec={"x": np.zeros((1, 784))},
                                   model_state=trainer.model_state,
                                   path="model.shlo")
    run = pexport.load_inference("model.shlo")
    probs = run({"x": batch})

feed_spec values may be example arrays, ShapeDtypeStructs, or
SequenceBatch-wrapped versions of either.  Exports are single-platform by
default (the current backend); pass platforms=("tpu", "cpu") for a
multi-platform artifact.
"""

import jax
import jax.numpy as jnp
from jax import export as _jx

from paddle_tpu.core.sequence import NestedSequenceBatch, SequenceBatch
from paddle_tpu.layers.graph import Topology

# the serialized artifact must encode the feed pytree structure; register
# the framework's NamedTuple batch types once (idempotent across reimports)
for _nt, _name in ((SequenceBatch, "paddle_tpu.SequenceBatch"),
                   (NestedSequenceBatch, "paddle_tpu.NestedSequenceBatch")):
    try:
        _jx.register_namedtuple_serialization(_nt, serialized_name=_name)
    except ValueError:
        pass


def _as_aval(v):
    import numpy as np
    if isinstance(v, (SequenceBatch, NestedSequenceBatch)):
        return jax.tree_util.tree_map(_as_aval, v)
    if isinstance(v, jax.ShapeDtypeStruct):
        return v
    arr = np.asarray(v)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def quantize_params(params, min_size=1024):
    """Weight-only symmetric int8 quantization with per-output-channel
    scales (last axis): float32 leaves with >= min_size elements become
    (int8, f32 scale) pairs; small leaves (biases, norms) stay f32 —
    their bytes are noise and their precision is not.

    TPU rationale: serving is usually HBM-bandwidth-bound on the weight
    stream; int8 storage quarters it (and the artifact size).  The
    dequant (convert + scale multiply) fuses into the consuming matmul's
    read under XLA, so compute stays bf16/f32 on the MXU.

    Returns (qtree, dequant) where dequant(qtree) rebuilds a float
    params pytree; both halves are jit-traceable."""
    import numpy as np

    def q(x):
        if getattr(x, "dtype", None) != jnp.float32 \
                or np.prod(np.shape(x)) < min_size:
            return x
        axes = tuple(range(x.ndim - 1)) if x.ndim > 1 else (0,)
        s = jnp.max(jnp.abs(x), axis=axes, keepdims=True) / 127.0
        s = jnp.maximum(s, 1e-12)
        return {"__int8__": jnp.clip(jnp.round(x / s), -127, 127)
                            .astype(jnp.int8),
                "__scale__": s.astype(jnp.float32)}

    def is_q(leaf):
        return isinstance(leaf, dict) and "__int8__" in leaf

    def dequant(tree):
        return jax.tree_util.tree_map(
            lambda l: (l["__int8__"].astype(jnp.float32) * l["__scale__"])
            if is_q(l) else l,
            tree, is_leaf=is_q)

    qtree = jax.tree_util.tree_map(q, params)
    return qtree, dequant


def export_inference(output_layer, parameters, feed_spec, path=None,
                     model_state=None, platforms=None, quantize=None):
    """Lower test-mode inference of `output_layer` (or a list of outputs)
    to StableHLO with `parameters` embedded as constants.

    feed_spec: {data_layer_name: example array | ShapeDtypeStruct |
    SequenceBatch thereof} — fixes the exported input shapes (TPU serving
    wants static shapes; export one artifact per bucket for ragged input).
    quantize="int8" bakes weight-only int8 constants + fused dequant into
    the artifact (~4x smaller, ~4x less weight-stream HBM; see
    quantize_params).  Returns the jax.export.Exported; with `path`, also
    writes the serialized bytes there."""
    outs = list(output_layer) if isinstance(output_layer, (list, tuple)) \
        else [output_layer]
    topo = Topology(outs)
    state = model_state
    if state is None:
        state = topo.init_state()
        if state:
            # a trained BN model's moving stats live in trainer.model_state;
            # baking fresh init stats in would silently change predictions
            from paddle_tpu.utils.logging import logger
            logger.warning(
                "export_inference: model has state (%s) but model_state= "
                "was not passed — exporting with INITIAL statistics. Pass "
                "trainer.model_state for a trained model.",
                ", ".join(sorted(state)))

    if quantize is None:
        def fwd(feed):
            return topo.apply(parameters, feed, mode="test", state=state)
    elif quantize == "int8":
        qparams, dequant = quantize_params(parameters)

        def fwd(feed):
            return topo.apply(dequant(qparams), feed, mode="test",
                              state=state)
    else:
        raise ValueError(f"quantize={quantize!r} (supported: None, 'int8')")

    spec = {k: jax.tree_util.tree_map(_as_aval, v)
            for k, v in feed_spec.items()}
    kwargs = {}
    if platforms:
        kwargs["platforms"] = tuple(platforms)
    exp = _jx.export(jax.jit(fwd), **kwargs)(spec)
    if path:
        with open(path, "wb") as f:
            f.write(exp.serialize())
    return exp


def export_bucketed(output_layer, parameters, feed_spec, buckets,
                    path_prefix, model_state=None, platforms=None,
                    quantize=None):
    """One artifact per batch bucket — the export half of the serving
    runtime's bucket ladder (serving/engine.py).

    feed_spec leaves carry a LEADING batch axis (any size); it is replaced
    by each bucket.  Artifacts land at the documented naming convention
    ``{path_prefix}.b{N}.shlo`` (one per bucket N), which
    ``serving.InferenceEngine.from_artifacts(f"{path_prefix}.b*.shlo")``
    loads back as a ladder.  Returns {bucket: path}."""
    spec = {k: jax.tree_util.tree_map(_as_aval, v)
            for k, v in feed_spec.items()}

    def rebatch(n):
        return {k: jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct((n,) + tuple(l.shape[1:]),
                                           l.dtype), v)
            for k, v in spec.items()}

    paths = {}
    for n in sorted({int(b) for b in buckets}):
        if n < 1:
            raise ValueError(f"bucket {n} < 1")
        path = f"{path_prefix}.b{n}.shlo"
        export_inference(output_layer, parameters, rebatch(n), path=path,
                         model_state=model_state, platforms=platforms,
                         quantize=quantize)
        paths[n] = path
    return paths


def load_inference(path_or_bytes):
    """Deserialize an exported artifact -> callable(feed_dict)."""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    exp = _jx.deserialize(data)

    def run(feed):
        return exp.call(feed)

    run.exported = exp
    return run
