"""GoogleNet (Inception v1) — the reference's heaviest image benchmark
(benchmark/paddle/image/googlenet.py: 3x224x224, stem 7x7s2 + pools, nine
inception modules 3a..5b, global avg pool, fc1000; BASELINE.md GoogleNet
bs=64 -> 613 ms/batch on K40m).

Functional NHWC implementation.  The four inception branches are independent
convs concatenated on the channel axis — XLA fuses the elementwise tails and
the MXU takes the (large, batched) conv contractions.  No batch norm, as in
the reference config (Inception v1 predates BN); the auxiliary classifiers
the paper describes (and the reference omits) are likewise omitted.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linear, losses

# name -> (1x1, 3x3reduce, 3x3, 5x5reduce, 5x5, poolproj)
_INCEPTION = [
    ("3a", 64, 96, 128, 16, 32, 32),
    ("3b", 128, 128, 192, 32, 96, 64),
    ("pool3",),
    ("4a", 192, 96, 208, 16, 48, 64),
    ("4b", 160, 112, 224, 24, 64, 64),
    ("4c", 128, 128, 256, 24, 64, 64),
    ("4d", 112, 144, 288, 32, 64, 64),
    ("4e", 256, 160, 320, 32, 128, 128),
    ("pool4",),
    ("5a", 256, 160, 320, 32, 128, 128),
    ("5b", 384, 192, 384, 48, 128, 128),
]


def _conv_init(rng, k, cin, cout):
    fan = k * k * cin
    return {"w": (2.0 / fan) ** 0.5 * jax.random.normal(
        rng, (k, k, cin, cout), jnp.float32), "b": jnp.zeros((cout,))}


def init(rng, num_classes=1000):
    keys = iter(jax.random.split(rng, 128))
    params = {
        "stem1": _conv_init(next(keys), 7, 3, 64),
        "stem2": _conv_init(next(keys), 1, 64, 64),
        "stem3": _conv_init(next(keys), 3, 64, 192),
    }
    cin = 192
    for row in _INCEPTION:
        if len(row) == 1:
            continue
        name, c1, c3r, c3, c5r, c5, cp = row
        params[name] = {
            "b1": _conv_init(next(keys), 1, cin, c1),
            "b3r": _conv_init(next(keys), 1, cin, c3r),
            "b3": _conv_init(next(keys), 3, c3r, c3),
            "b5r": _conv_init(next(keys), 1, cin, c5r),
            "b5": _conv_init(next(keys), 5, c5r, c5),
            "bp": _conv_init(next(keys), 1, cin, cp),
        }
        cin = c1 + c3 + c5 + cp
    params["head"] = {"w": 0.01 * jax.random.normal(next(keys),
                                                    (cin, num_classes)),
                      "b": jnp.zeros((num_classes,))}
    return params, {}


def _cv(x, p, stride=1, pad=0):
    return conv_ops.conv2d(x, p["w"], p["b"], stride=(stride, stride),
                           padding=(pad, pad), act="relu")


def _inception(x, p):
    b1 = _cv(x, p["b1"])
    b3 = _cv(_cv(x, p["b3r"]), p["b3"], pad=1)
    b5 = _cv(_cv(x, p["b5r"]), p["b5"], pad=2)
    bp = _cv(conv_ops.max_pool2d(x, (3, 3), (1, 1), (1, 1)), p["bp"])
    return jnp.concatenate([b1, b3, b5, bp], axis=-1)


def forward(params, state, images, train=True, rng=None, drop_rate=0.4):
    """images: [B, 224, 224, 3] NHWC.  Returns (logits, state)."""
    x = _cv(images, params["stem1"], stride=2, pad=3)
    x = conv_ops.max_pool2d(x, (3, 3), (2, 2), (1, 1))
    x = _cv(x, params["stem2"])
    x = _cv(x, params["stem3"], pad=1)
    x = conv_ops.max_pool2d(x, (3, 3), (2, 2), (1, 1))
    for row in _INCEPTION:
        if len(row) == 1:
            x = conv_ops.max_pool2d(x, (3, 3), (2, 2), (1, 1))
        else:
            x = _inception(x, params[row[0]])
    x = jnp.mean(x, axis=(1, 2))
    if train and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - drop_rate, x.shape)
        x = jnp.where(keep, x / (1.0 - drop_rate), 0.0)
    return linear.fc(x, params["head"]["w"], params["head"]["b"]), state


def loss(params, state, images, labels, train=True, rng=None):
    logits, new_state = forward(params, state, images, train=train, rng=rng)
    return jnp.mean(losses.classification_cost(logits, labels)), new_state
