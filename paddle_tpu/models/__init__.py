"""Model zoo — functional TPU-first implementations of the reference's demo
families (demo/mnist, image_classification, seqToseq, sentiment,
recommendation, benchmark/rnn) plus the Transformer stretch config.

The DSL-based demo scripts (v1-config parity) live in /demo; these modules
are the fast path used by bench.py and __graft_entry__.py.
"""

from paddle_tpu.models import alexnet
from paddle_tpu.models import googlenet
from paddle_tpu.models import lenet
from paddle_tpu.models import resnet
from paddle_tpu.models import smallnet
from paddle_tpu.models import text_lstm
from paddle_tpu.models import seq2seq
from paddle_tpu.models import transformer
from paddle_tpu.models import recommendation

__all__ = ["alexnet", "googlenet", "lenet", "resnet", "smallnet",
           "text_lstm", "seq2seq", "transformer", "recommendation"]
