"""AlexNet — the reference's image benchmark config
(benchmark/paddle/image/alexnet.py: 3x227x227, conv1 96@11s4p1 + cmrnorm +
pool, conv2 256@5p2 + cmrnorm + pool, conv3/4 384@3p1, conv5 256@3p1 + pool,
fc4096 x2 with dropout 0.5, fc1000 softmax; BASELINE.md AlexNet bs=64 ->
195 ms/batch on K40m).

Functional NHWC implementation; LRN is the cross-map variant the reference's
img_cmrnorm_layer uses.  Dropout only applies when an rng is passed.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linear, losses


_CONVS = [
    # name, k, cin, cout, stride, pad, lrn_after, pool_after
    ("c1", 11, 3, 96, 4, 1, True, True),
    ("c2", 5, 96, 256, 1, 2, True, True),
    ("c3", 3, 256, 384, 1, 1, False, False),
    ("c4", 3, 384, 384, 1, 1, False, False),
    ("c5", 3, 384, 256, 1, 1, False, True),
]


def _conv_init(rng, k, cin, cout):
    fan = k * k * cin
    return (2.0 / fan) ** 0.5 * jax.random.normal(
        rng, (k, k, cin, cout), jnp.float32)


def init(rng, num_classes=1000, fc_dim=4096):
    keys = iter(jax.random.split(rng, 16))
    params = {}
    for name, k, cin, cout, *_ in _CONVS:
        params[name] = {"w": _conv_init(next(keys), k, cin, cout),
                        "b": jnp.zeros((cout,))}
    # 227 -> conv s4 p1 -> 55 -> pool3s2 -> 27 -> pool -> 13 -> pool -> 6
    flat = 6 * 6 * 256
    params["fc1"] = {"w": 0.01 * jax.random.normal(next(keys), (flat, fc_dim)),
                     "b": jnp.zeros((fc_dim,))}
    params["fc2"] = {"w": 0.01 * jax.random.normal(next(keys), (fc_dim, fc_dim)),
                     "b": jnp.zeros((fc_dim,))}
    params["out"] = {"w": 0.01 * jax.random.normal(next(keys),
                                                   (fc_dim, num_classes)),
                     "b": jnp.zeros((num_classes,))}
    return params, {}


def forward(params, state, images, train=True, rng=None, drop_rate=0.5):
    """images: [B, 227, 227, 3] NHWC.  Returns (logits, state)."""
    x = images
    for name, k, cin, cout, stride, pad, lrn, pool in _CONVS:
        p = params[name]
        x = conv_ops.conv2d(x, p["w"], p["b"], stride=(stride, stride),
                            padding=(pad, pad), act="relu")
        if lrn:
            x = conv_ops.lrn_cross_map(x, size=5, scale=1e-4, power=0.75)
        if pool:
            x = conv_ops.max_pool2d(x, (3, 3), (2, 2))
    x = x.reshape(x.shape[0], -1)
    for fc in ("fc1", "fc2"):
        x = linear.fc(x, params[fc]["w"], params[fc]["b"], act="relu")
        if train and rng is not None:
            rng, sub = jax.random.split(rng)
            keep = jax.random.bernoulli(sub, 1.0 - drop_rate, x.shape)
            x = jnp.where(keep, x / (1.0 - drop_rate), 0.0)
    return linear.fc(x, params["out"]["w"], params["out"]["b"]), state


def loss(params, state, images, labels, train=True, rng=None):
    logits, new_state = forward(params, state, images, train=train, rng=rng)
    return jnp.mean(losses.classification_cost(logits, labels)), new_state
