"""MovieLens recommender (reference demo/recommendation: user/movie feature
towers -> cos-sim rating regression; the sparse-CTR acceptance config in
BASELINE.json).  Embedding tables are the sparse-parameter path — sharded
over the 'model' mesh axis at scale (parallel.megatron_rules matches the
'emb' names)."""

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import linear, losses, embedding as emb_ops
from paddle_tpu.ops import sequence as seq_ops
from paddle_tpu.ops import math_ops
from paddle_tpu.ops import initializers


def init(rng, max_user=6040, max_movie=3952, ages=7, jobs=21, genders=2,
         categories=18, title_vocab=5174, emb=256, hidden=256):
    ks = iter(jax.random.split(rng, 20))
    u = initializers.uniform(0.05)
    n = initializers.normal()
    return {
        "user_emb": u(next(ks), (max_user + 1, emb)),
        "gender_emb": u(next(ks), (genders, emb // 8)),
        "age_emb": u(next(ks), (ages, emb // 8)),
        "job_emb": u(next(ks), (jobs, emb // 8)),
        "user_fc": {"w": n(next(ks), (emb + 3 * (emb // 8), hidden)),
                    "b": jnp.zeros((hidden,))},
        "movie_emb": u(next(ks), (max_movie + 1, emb)),
        "cat_emb": u(next(ks), (categories, emb // 4)),
        "title_emb": u(next(ks), (title_vocab, emb // 2)),
        "movie_fc": {"w": n(next(ks), (emb + emb // 4 + emb // 2, hidden)),
                     "b": jnp.zeros((hidden,))},
    }


def forward(params, uid, gender, age, job, mid, categories, title):
    """categories: multi-hot [B, n_cat]; title: SequenceBatch of word ids.
    Returns predicted rating [B] in [1, 5] (reference: 5 * cos_sim scale)."""
    uf = jnp.concatenate([
        emb_ops.embedding_lookup(params["user_emb"], uid),
        emb_ops.embedding_lookup(params["gender_emb"], gender),
        emb_ops.embedding_lookup(params["age_emb"], age),
        emb_ops.embedding_lookup(params["job_emb"], job),
    ], axis=-1)
    user_vec = jnp.tanh(linear.matmul(uf, params["user_fc"]["w"])
                        + params["user_fc"]["b"])

    cat_vec = linear.matmul(categories, params["cat_emb"])
    title_emb = emb_ops.embedding_lookup(params["title_emb"], title.data)
    title_vec = seq_ops.seq_avg_pool(SequenceBatch(title_emb, title.lengths))
    mf = jnp.concatenate([
        emb_ops.embedding_lookup(params["movie_emb"], mid), cat_vec, title_vec,
    ], axis=-1)
    movie_vec = jnp.tanh(linear.matmul(mf, params["movie_fc"]["w"])
                         + params["movie_fc"]["b"])
    return 5.0 * math_ops.cos_sim(user_vec, movie_vec)[:, 0]


def loss(params, uid, gender, age, job, mid, categories, title, score):
    pred = forward(params, uid, gender, age, job, mid, categories, title)
    return jnp.mean(0.5 * jnp.square(pred - score))
