"""LSTM text classifier — the reference's RNN benchmark model
(benchmark/paddle/rnn/rnn.py: IMDB, embedding 128 -> N stacked LSTM h=H ->
max-pool over time -> fc 2; BASELINE.md LSTM rows: h=512 bs=64 -> 184
ms/batch on K40m).

Functional implementation; the per-layer input projections for ALL timesteps
run as single big MXU matmuls outside the scan (ops.rnn design).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import rnn, linear, losses, embedding as emb_ops
from paddle_tpu.ops import sequence as seq_ops
from paddle_tpu.ops import initializers


def init(rng, vocab=30000, emb_dim=128, hidden=512, num_layers=2,
         num_classes=2):
    ks = iter(jax.random.split(rng, 4 + 3 * num_layers))
    ninit = initializers.normal()
    params = {"emb": initializers.uniform(0.1)(next(ks), (vocab, emb_dim))}
    d_in = emb_dim
    for i in range(num_layers):
        params[f"l{i}"] = {
            "w_in": ninit(next(ks), (d_in, 4 * hidden)),
            "w_r": ninit(next(ks), (hidden, 4 * hidden)),
            "b": jnp.zeros((7 * hidden,)),
        }
        d_in = hidden
    params["out"] = {"w": ninit(next(ks), (hidden, num_classes)),
                     "b": jnp.zeros((num_classes,))}
    return params


def forward(params, ids: SequenceBatch, num_layers=2, hidden=512):
    x = emb_ops.embedding_lookup(params["emb"], ids.data)
    sb = SequenceBatch(data=x, lengths=ids.lengths)
    for i in range(num_layers):
        p = params[f"l{i}"]
        proj = linear.matmul(sb.data, p["w_in"])
        d = hidden
        sb, _ = rnn.lstm(SequenceBatch(proj, sb.lengths), p["w_r"],
                         bias=p["b"][:4 * d], check_i=p["b"][4 * d:5 * d],
                         check_f=p["b"][5 * d:6 * d], check_o=p["b"][6 * d:])
    pooled = seq_ops.seq_max_pool(sb)
    return linear.fc(pooled, params["out"]["w"], params["out"]["b"])


def loss(params, ids, labels, num_layers=2, hidden=512):
    logits = forward(params, ids, num_layers, hidden)
    return jnp.mean(losses.classification_cost(logits, labels))
