"""Attention NMT encoder-decoder — the reference's demo/seqToseq
(seqToseq_net.py: bi-GRU encoder, Bahdanau attention, GRU decoder with
gru_step inside a recurrent_group, beam-search generation) rebuilt
functionally: teacher-forced training is one lax.scan over target steps;
generation is ops.beam.beam_search with the decoder step as the lane-major
step function.  Encoder projections are hoisted out of the decode loop
(one MXU matmul for all source positions, as the reference hoists
encoded_proj).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import rnn, linear, losses, embedding as emb_ops
from paddle_tpu.ops import attention as attn_ops
from paddle_tpu.ops import beam as beam_ops
from paddle_tpu.ops import initializers


def init(rng, src_vocab=30000, trg_vocab=30000, emb_dim=512, hidden=512,
         att_dim=None):
    att_dim = att_dim or hidden
    ks = iter(jax.random.split(rng, 24))
    ninit = initializers.normal()
    uinit = initializers.uniform(0.1)
    h = hidden
    return {
        "src_emb": uinit(next(ks), (src_vocab, emb_dim)),
        "trg_emb": uinit(next(ks), (trg_vocab, emb_dim)),
        # encoder bi-GRU
        "enc_fwd": {"w_in": ninit(next(ks), (emb_dim, 3 * h)),
                    "w_gate": ninit(next(ks), (h, 2 * h)),
                    "w_state": ninit(next(ks), (h, h)),
                    "b": jnp.zeros((3 * h,))},
        "enc_bwd": {"w_in": ninit(next(ks), (emb_dim, 3 * h)),
                    "w_gate": ninit(next(ks), (h, 2 * h)),
                    "w_state": ninit(next(ks), (h, h)),
                    "b": jnp.zeros((3 * h,))},
        # attention (additive): enc_proj once per sentence + dec proj per step
        "att_enc": ninit(next(ks), (2 * h, att_dim)),
        "att_dec": ninit(next(ks), (h, att_dim)),
        "att_v": ninit(next(ks), (att_dim,)),
        # decoder boot from encoder backward first state (reference decoder_boot)
        "boot": {"w": ninit(next(ks), (h, h)), "b": jnp.zeros((h,))},
        # decoder GRU: input = [trg_emb ; context(2h)] -> 3h projection
        "dec_in": ninit(next(ks), (emb_dim + 2 * h, 3 * h)),
        "dec_b": jnp.zeros((3 * h,)),
        "dec_gate": ninit(next(ks), (h, 2 * h)),
        "dec_state": ninit(next(ks), (h, h)),
        # readout: [state ; context ; emb] -> logits
        "out1": {"w": ninit(next(ks), (h + 2 * h + emb_dim, h)),
                 "b": jnp.zeros((h,))},
        "out2": {"w": ninit(next(ks), (h, trg_vocab)),
                 "b": jnp.zeros((trg_vocab,))},
    }


def encode(params, src: SequenceBatch):
    """-> (enc_states SequenceBatch [B,T,2H], enc_proj SequenceBatch
    [B,T,A], boot decoder state [B,H])."""
    x = emb_ops.embedding_lookup(params["src_emb"], src.data)
    pf, pb = params["enc_fwd"], params["enc_bwd"]
    fwd, _ = rnn.gru(SequenceBatch(linear.matmul(x, pf["w_in"]), src.lengths),
                     pf["w_gate"], pf["w_state"], bias=pf["b"])
    bwd, _ = rnn.gru(SequenceBatch(linear.matmul(x, pb["w_in"]), src.lengths),
                     pb["w_gate"], pb["w_state"], bias=pb["b"], reverse=True)
    enc = rnn.bidirectional(fwd, bwd)
    proj = SequenceBatch(linear.matmul(enc.data, params["att_enc"]),
                         enc.lengths)
    # reference decoder_boot: fc(tanh) of backward encoder's first step
    boot = jnp.tanh(linear.matmul(bwd.data[:, 0], params["boot"]["w"])
                    + params["boot"]["b"])
    return enc, proj, boot


def _dec_step(params, enc, enc_proj, state, emb_t):
    """One decoder step: attention + GRU + readout.  state: [B,H]."""
    dec_proj = linear.matmul(state, params["att_dec"])
    scores = attn_ops.additive_attention_scores(enc_proj, dec_proj,
                                                params["att_v"])
    context = attn_ops.attention_context(scores, enc)          # [B, 2H]
    x = jnp.concatenate([emb_t, context], axis=-1)
    x3 = linear.matmul(x, params["dec_in"]) + params["dec_b"]
    new_state = rnn.gru_cell(x3, state, params["dec_gate"], params["dec_state"])
    readout = jnp.tanh(linear.matmul(
        jnp.concatenate([new_state, context, emb_t], axis=-1),
        params["out1"]["w"]) + params["out1"]["b"])
    logits = linear.matmul(readout, params["out2"]["w"]) + params["out2"]["b"]
    return new_state, logits


def forward(params, src: SequenceBatch, trg_in: SequenceBatch):
    """Teacher-forced decode -> logits [B, T_trg, V]."""
    enc, enc_proj, boot = encode(params, src)
    emb = emb_ops.embedding_lookup(params["trg_emb"], trg_in.data)
    emb_tm = emb.transpose(1, 0, 2)
    mask_tm = trg_in.mask().transpose(1, 0)

    def body(state, xs):
        emb_t, m = xs
        new_state, logits = _dec_step(params, enc, enc_proj, state, emb_t)
        state = jnp.where(m[:, None] > 0, new_state, state)
        return state, logits

    _, logits_tm = jax.lax.scan(body, boot, (emb_tm, mask_tm))
    return logits_tm.transpose(1, 0, 2)


def loss(params, src: SequenceBatch, trg_in: SequenceBatch,
         trg_next: SequenceBatch):
    logits = forward(params, src, trg_in)
    labels = trg_next.data
    if labels.ndim == 3:
        labels = labels[..., 0]
    per_tok = losses.classification_cost(logits, labels)
    per_seq = losses.masked_seq_mean(per_tok, trg_in.mask(per_tok.dtype))
    return jnp.mean(per_seq)


def generate(params, src: SequenceBatch, beam_size=5, max_len=50, bos_id=0,
             eos_id=1, length_penalty=0.0):
    """Beam-search translation (reference gen_trans_file / SequenceGenerator)."""
    b = src.data.shape[0]
    enc, enc_proj, boot = encode(params, src)

    def tile(x):
        return jnp.repeat(x, beam_size, axis=0)

    enc_l = SequenceBatch(tile(enc.data), tile(enc.lengths))
    proj_l = SequenceBatch(tile(enc_proj.data), tile(enc_proj.lengths))

    def step_fn(state, prev_ids):
        emb_t = emb_ops.embedding_lookup(params["trg_emb"], prev_ids)
        new_state, logits = _dec_step(params, enc_l, proj_l, state, emb_t)
        return jax.nn.log_softmax(logits, axis=-1), new_state

    return beam_ops.beam_search(step_fn, tile(boot), b, beam_size, max_len,
                                bos_id, eos_id, length_penalty=length_penalty)


def greedy_generate(params, src: SequenceBatch, max_len=50, bos_id=0, eos_id=1):
    b = src.data.shape[0]
    enc, enc_proj, boot = encode(params, src)

    def step_fn(state, prev_ids):
        emb_t = emb_ops.embedding_lookup(params["trg_emb"], prev_ids)
        new_state, logits = _dec_step(params, enc, enc_proj, state, emb_t)
        return jax.nn.log_softmax(logits, axis=-1), new_state

    return beam_ops.greedy_search(step_fn, boot, b, max_len, bos_id, eos_id)
