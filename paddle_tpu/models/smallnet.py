"""SmallNet — the reference's CIFAR-quick benchmark config
(benchmark/paddle/image/smallnet_mnist_cifar.py: 3x32x32, conv 32@5p2 +
maxpool3s2p1, conv 32@5p2 + avgpool3s2p1, conv 64@3p1 + avgpool3s2p1,
fc64, fc10 softmax; BASELINE.md SmallNet bs=64 -> 10.463 ms/batch on K40m).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linear, losses


def _conv_init(rng, k, cin, cout):
    fan = k * k * cin
    return {"w": (2.0 / fan) ** 0.5 * jax.random.normal(
        rng, (k, k, cin, cout), jnp.float32), "b": jnp.zeros((cout,))}


def init(rng, num_classes=10, in_channels=3):
    keys = iter(jax.random.split(rng, 8))
    params = {
        "c1": _conv_init(next(keys), 5, in_channels, 32),
        "c2": _conv_init(next(keys), 5, 32, 32),
        "c3": _conv_init(next(keys), 3, 32, 64),
        # 32 -> pool s2 p1 -> 16 -> 8 -> 4
        "fc1": {"w": 0.1 * jax.random.normal(next(keys), (4 * 4 * 64, 64)),
                "b": jnp.zeros((64,))},
        "out": {"w": 0.1 * jax.random.normal(next(keys), (64, num_classes)),
                "b": jnp.zeros((num_classes,))},
    }
    return params, {}


def forward(params, state, images, train=True, rng=None):
    """images: [B, 32, 32, 3] NHWC.  Returns (logits, state)."""
    x = conv_ops.conv2d(images, params["c1"]["w"], params["c1"]["b"],
                        padding=(2, 2), act="relu")
    x = conv_ops.max_pool2d(x, (3, 3), (2, 2), (1, 1))
    x = conv_ops.conv2d(x, params["c2"]["w"], params["c2"]["b"],
                        padding=(2, 2), act="relu")
    x = conv_ops.avg_pool2d(x, (3, 3), (2, 2), (1, 1))
    x = conv_ops.conv2d(x, params["c3"]["w"], params["c3"]["b"],
                        padding=(1, 1), act="relu")
    x = conv_ops.avg_pool2d(x, (3, 3), (2, 2), (1, 1))
    x = x.reshape(x.shape[0], -1)
    x = linear.fc(x, params["fc1"]["w"], params["fc1"]["b"], act="relu")
    return linear.fc(x, params["out"]["w"], params["out"]["b"]), state


def loss(params, state, images, labels, train=True, rng=None):
    logits, new_state = forward(params, state, images, train=train, rng=rng)
    return jnp.mean(losses.classification_cost(logits, labels)), new_state
