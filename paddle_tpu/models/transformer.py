"""Transformer-base MT (BASELINE.json stretch config: "Transformer-base MT —
stretch gserver layers to attention stack").  The reference predates
attention; this is the TPU-era flagship: pre-LN encoder-decoder, bf16 MXU
matmuls, f32 softmax/layernorm, causal+padding masks, beam-search decode
sharing ops.beam with seq2seq.
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import linear, losses, embedding as emb_ops
from paddle_tpu.ops import attention as attn_ops
from paddle_tpu.ops import beam as beam_ops
from paddle_tpu.ops.norm import layer_norm
from paddle_tpu.quant import kv as kvq
from paddle_tpu.quant.weights import (is_quantized_leaf as _w_quantized,
                                      maybe_dequant as _maybe_dequant,
                                      weight_shape as _w_shape)


def _dense(rng, din, dout, scale=None):
    s = scale or (1.0 / math.sqrt(din))
    return s * jax.random.normal(rng, (din, dout), jnp.float32)


def _block_init(ks, d, dff, cross=False, moe_experts=0, d_kv=None):
    dkv = d_kv or d
    blk = {
        "ln1": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
        "attn": {"wq": _dense(next(ks), d, d),
                 "wk": _dense(next(ks), d, dkv),
                 "wv": _dense(next(ks), d, dkv),
                 "wo": _dense(next(ks), d, d)},
        "ln2": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
    }
    if moe_experts and moe_experts > 1:
        from paddle_tpu.ops import moe as moe_ops
        blk["moe"] = moe_ops.init_moe(next(ks), d, dff, moe_experts)
    else:
        blk["ffn"] = {"w1": _dense(next(ks), d, dff),
                      "b1": jnp.zeros((dff,)),
                      "w2": _dense(next(ks), dff, d),
                      "b2": jnp.zeros((d,))}
    if cross:
        blk["ln_x"] = {"g": jnp.ones((d,)), "b": jnp.zeros((d,))}
        blk["xattn"] = {"wq": _dense(next(ks), d, d),
                        "wk": _dense(next(ks), d, d),
                        "wv": _dense(next(ks), d, d),
                        "wo": _dense(next(ks), d, d)}
    return blk


def init(rng, src_vocab=30000, trg_vocab=30000, d_model=512, num_heads=8,
         dff=2048, enc_layers=6, dec_layers=6, max_len=512,
         moe_experts=0, pos_type="learned", num_kv_heads=None):
    """moe_experts > 1 replaces every ENC block's dense FFN with a
    top-k-gated mixture of that many expert FFNs (ops/moe.py: batched
    einsum over the expert dim, shardable over the 'expert' mesh axis
    via moe.expert_shardings) — the modern sparse-LM trunk.  Decoder
    blocks keep dense FFNs (the MoE plane targets the causal/encoder
    trunk lm_loss trains).

    num_kv_heads < num_heads gives the ENC/causal blocks grouped-query
    attention (GQA): wk/wv project to num_kv_heads*head_dim, each KV
    head serving a group of query heads — the KV cache (and its HBM
    stream at decode) shrinks by the same factor, the standard serving
    lever.  Carried entirely by the weight shapes; every path infers it.

    pos_type="rope" drops the learned positional table entirely: the
    trunk rotates q/k per position instead (ops.attention.rope), so
    max_len stops being a hard cap — a rope trunk can run sequences
    longer than anything trained on (relative-position attention).
    Callers pass the same pos_type to encode/lm_* (static config, like
    depth in models/resnet).  rope is a decoder-only-trunk feature:
    the seq2seq decoder stack needs the learned table, so
    pos_type='rope' requires dec_layers=0."""
    ks = iter(jax.random.split(rng, 16 + 9 * (enc_layers + dec_layers)))
    params = {
        "src_emb": _dense(next(ks), src_vocab, d_model, scale=0.02),
        "trg_emb": _dense(next(ks), trg_vocab, d_model, scale=0.02),
    }
    # the pos key is drawn in its historical slot EITHER WAY so a given
    # seed yields byte-identical weights for every other parameter
    # (golden generation tests pin exactly that)
    pos_key = next(ks)
    if pos_type == "rope" and dec_layers:
        raise ValueError(
            "pos_type='rope' is the decoder-only trunk configuration "
            "(lm_loss/lm_generate); the seq2seq decoder stack needs the "
            "learned table — use dec_layers=0 or pos_type='learned'")
    if pos_type == "learned":
        params["pos"] = 0.02 * jax.random.normal(pos_key,
                                                 (max_len, d_model))
    elif pos_type != "rope":
        raise ValueError(f"pos_type must be 'learned' or 'rope', got "
                         f"{pos_type!r}")
    d_kv = None
    if num_kv_heads is not None:
        if num_heads % num_kv_heads:
            raise ValueError(f"num_heads={num_heads} not divisible by "
                             f"num_kv_heads={num_kv_heads}")
        d_kv = (d_model // num_heads) * num_kv_heads
    params["enc"] = [_block_init(ks, d_model, dff, moe_experts=moe_experts,
                                 d_kv=d_kv)
                     for _ in range(enc_layers)]
    params["dec"] = [_block_init(ks, d_model, dff, cross=True)
                     for _ in range(dec_layers)]
    params["ln_f"] = {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))}
    params["out"] = _dense(next(ks), d_model, trg_vocab)
    return params


def moe_lm_shardings(mesh, params):
    """NamedShardings for a moe_experts trunk: everything replicated
    except each block's expert weights, which take the canonical
    moe.expert_shardings layout (wg replicated, w1/w2 over 'expert') —
    THE recipe the dryrun leg and the parity tests share."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.ops import moe as moe_ops
    repl = NamedSharding(mesh, P())
    sh = jax.tree_util.tree_map(lambda _: repl, params)
    for blk in sh["enc"]:
        if "moe" in blk:
            blk["moe"] = moe_ops.expert_shardings(mesh)
    return sh


def _mha(blk, xq, xkv, num_heads, key_mask=None, causal=False, mesh=None,
         zigzag=False, q_segment_ids=None, rope_positions=None):
    return attn_ops.multi_head_attention(
        xq, xkv, blk["wq"], blk["wk"], blk["wv"], blk["wo"], num_heads,
        key_mask=key_mask, causal=causal, mesh=mesh, zigzag=zigzag,
        q_segment_ids=q_segment_ids, rope_positions=rope_positions)


def _ffn(blk, x):
    h = jax.nn.relu(linear.matmul(x, blk["w1"]) + blk["b1"])
    return linear.matmul(h, blk["w2"]) + blk["b2"]


def _ln(p, x):
    return layer_norm(x, p["g"], p["b"])


def _zigzag_idx(t, mesh):
    """THE permutation decode's logits and loss's labels share — one
    definition so they can never misalign."""
    from paddle_tpu.parallel.ring_attention import zigzag_order
    return jnp.asarray(zigzag_order(t, mesh.shape["seq"]))


def _check_full(seq: SequenceBatch):
    """full_seq=True promises no padding; catch a broken promise when the
    lengths are concrete (outside jit) instead of silently attending
    padded keys."""
    lengths = seq.lengths
    if isinstance(lengths, jax.core.Tracer):
        return
    t = seq.data.shape[1]
    if bool(jnp.any(lengths != t)):
        import numpy as _np
        a = _np.asarray(lengths)
        raise ValueError(
            f"full_seq=True but batch has lengths "
            f"{(int(a.min()), int(a.max()))} < T={t}; drop full_seq or "
            "pack the batch")


def _block_ffn(blk, h, moe_top_k=2, valid=None):
    """Dense or mixture FFN, depending on how the block was initialized;
    returns (output, load-balance aux) with aux == 0 for dense.  relu
    for both so an identical-experts mixture reproduces the dense block
    exactly (the MoE equivalence test relies on it).  valid: [B, T] real-
    token mask — the aux statistics must not be skewed by padding rows
    that all route identically."""
    if "moe" in blk:
        from paddle_tpu.ops import moe as moe_ops
        return moe_ops.moe_ffn(h, blk["moe"], top_k=moe_top_k,
                               act=jax.nn.relu, return_aux=True,
                               valid=valid)
    return _ffn(blk["ffn"], h), jnp.zeros(())


def _enc_block(blk, x, key_mask, num_heads, mesh=None, segment_ids=None,
               causal=False, zigzag=False, moe_top_k=2, rope_pos=None):
    h = _ln(blk["ln1"], x)
    x = x + _mha(blk["attn"], h, h, num_heads, key_mask=key_mask,
                 causal=causal, mesh=mesh, zigzag=zigzag,
                 q_segment_ids=segment_ids, rope_positions=rope_pos)
    # real-token mask for the MoE aux: packed rows label padding 0,
    # unpacked rows carry key_mask; full_seq has no padding at all
    valid = (segment_ids > 0 if segment_ids is not None
             else (key_mask > 0 if key_mask is not None else None))
    y, aux = _block_ffn(blk, _ln(blk["ln2"], x), moe_top_k, valid)
    return x + y, aux


def _dec_block(blk, x, enc_out, self_km, cross_km, num_heads, mesh=None,
               zigzag=False):
    h = _ln(blk["ln1"], x)
    x = x + _mha(blk["attn"], h, h, num_heads, key_mask=self_km,
                 causal=True, mesh=mesh, zigzag=zigzag)
    x = x + _mha(blk["xattn"], _ln(blk["ln_x"], x), enc_out, num_heads,
                 key_mask=cross_km, mesh=mesh)
    return x + _ffn(blk["ffn"], _ln(blk["ln2"], x))


def encode(params, src: SequenceBatch, num_heads=8, remat=False,
           full_seq=False, mesh=None, segment_ids=None, positions=None,
           causal=False, zigzag=False, moe_top_k=2, return_aux=False,
           pos_type="learned"):
    """remat=True checkpoints each block (jax.checkpoint): backward
    recomputes activations instead of storing them — the HBM headroom for
    >=32k-token batches.

    mesh: a mesh whose `seq` axis is >1 runs every attention sequence-
    parallel via the ppermute ring (callers shard the T dim of the feeds
    over that axis) — long-context training across chips.

    segment_ids/positions: PACKED rows (core.sequence.pack_sequences —
    several short sequences per row): attention stays block-diagonal per
    segment and each token's positional row is its within-segment index,
    so the encoder behaves exactly as if every sequence ran alone.

    causal=True turns the stack into a decoder-only (GPT-style) trunk:
    every self-attention is causal — combined with segment_ids this is
    packed causal-LM training (see lm_loss).  zigzag=True (causal +
    seq>1 mesh only) processes the stream in zigzag storage order so the
    causal self-attention rides the balanced ring; the returned hidden
    states are in zigzag order (lm_loss aligns its labels the same way)."""
    # quantized trunks (quant/weights.py) dequantize at the matmul
    # boundary: XLA fuses convert(int8)*scale into each consuming
    # matmul's operand read — a float tree passes through untouched
    params = _maybe_dequant(params)
    t = src.data.shape[1]
    if (pos_type == "learned") != ("pos" in params):
        raise ValueError(
            f"pos_type={pos_type!r} but params were initialized "
            f"{'with' if 'pos' in params else 'without'} a learned "
            "positional table — pass the SAME pos_type used at init")
    block = (jax.checkpoint(_enc_block, static_argnums=(3, 4, 6, 7, 8))
             if remat else _enc_block)
    if (segment_ids is None) != (positions is None):
        raise ValueError("packed encode needs BOTH segment_ids and "
                         "positions (pack_sequences returns them "
                         "together)")
    ids, order = src.data, None
    if zigzag:
        if not causal or mesh is None or mesh.shape.get("seq", 1) <= 1:
            raise ValueError("zigzag encode needs causal=True and a mesh "
                             "with seq > 1")
        order = _zigzag_idx(t, mesh)
        ids = ids[:, order]
        if segment_ids is not None:
            segment_ids = segment_ids[:, order]
            positions = positions[:, order]
    x = emb_ops.embedding_lookup(params["src_emb"], ids)
    if positions is not None and pos_type == "learned" \
            and not isinstance(positions, jax.core.Tracer):
        try:
            max_pos = int(jnp.max(positions))
        except jax.errors.ConcretizationTypeError:
            # inside a jit trace even closed-over constants are staged;
            # the eager-path check below is best-effort only
            max_pos = -1
        if max_pos >= params["pos"].shape[0]:
            # fail fast like the unpacked path and init_decode_cache do;
            # the gather would otherwise silently clamp to the last row
            raise ValueError(
                f"packed position {max_pos} exceeds the positional table "
                f"({params['pos'].shape[0]}); re-init with a larger "
                "max_len or pack shorter rows")
    rope_pos = None
    if pos_type == "rope":
        # rotary positions ride q/k inside attention; nothing is added
        # to the embeddings and no table caps the length.  Packed rows
        # use within-segment positions (relative attention per segment);
        # zigzag uses the permuted global positions.
        x = x * math.sqrt(x.shape[-1])
        if positions is not None:
            rope_pos = positions
        else:
            rope_pos = jnp.arange(t)
            if order is not None:
                rope_pos = rope_pos[order]
    elif positions is not None:
        pos_rows = params["pos"][positions]
        x = x * math.sqrt(x.shape[-1]) + pos_rows
    else:
        pos_rows = params["pos"][:t]
        if order is not None:
            pos_rows = pos_rows[order]
        x = x * math.sqrt(x.shape[-1]) + pos_rows[None]
    # key validity stays O(T) ([B, T]); full_seq=True promises every
    # sequence is max-length (packed/bucketed batches) and drops the mask
    # entirely so the flash/chunked O(T)-memory paths engage — validated
    # when lengths are concrete (a jit-traced batch is trusted)
    key_mask = None if full_seq or segment_ids is not None else src.mask()
    if key_mask is not None and order is not None:
        key_mask = key_mask[:, order]
    if full_seq:
        _check_full(src)
    aux_total = jnp.zeros(())
    for blk in params["enc"]:
        x, aux = block(blk, x, key_mask, num_heads, mesh, segment_ids,
                       causal, zigzag, moe_top_k, rope_pos)
        aux_total = aux_total + aux
    return (x, aux_total) if return_aux else x


def decode(params, enc_out, src_mask, trg_in: SequenceBatch, num_heads=8,
           pos_offset=0, remat=False, full_seq=False, mesh=None,
           zigzag=False):
    """zigzag=True (mesh with seq>1 only): the decoder stream — ids,
    positions, masks — is processed in zigzag storage order so the causal
    self-attention rides the BALANCED ring (ring_attention_zigzag); the
    non-causal cross-attention doesn't care about q order.  Returned
    logits are in zigzag order: permute labels the same way (loss() does)
    rather than unpermuting — masked CE is permutation-invariant."""
    t = trg_in.data.shape[1]
    block = (jax.checkpoint(_dec_block, static_argnums=(5, 6, 7)) if remat
             else _dec_block)
    ids, pos_rows = trg_in.data, params["pos"][pos_offset:pos_offset + t]
    self_km = None if full_seq else trg_in.mask()
    if zigzag:
        if mesh is None or mesh.shape.get("seq", 1) <= 1:
            raise ValueError("zigzag decode needs a mesh with seq > 1")
        if pos_offset:
            raise ValueError("zigzag is a training-path layout; "
                             "incremental decode uses the cache path")
        order = _zigzag_idx(t, mesh)
        ids = ids[:, order]
        pos_rows = pos_rows[order]
        if self_km is not None:
            self_km = self_km[:, order]
    x = emb_ops.embedding_lookup(params["trg_emb"], ids)
    x = x * math.sqrt(x.shape[-1]) + pos_rows[None]
    cross_km = None if full_seq else src_mask
    if full_seq:
        _check_full(trg_in)
    for blk in params["dec"]:
        x = block(blk, x, enc_out, self_km, cross_km, num_heads, mesh,
                  zigzag)
    x = _ln(params["ln_f"], x)
    return linear.matmul(x, params["out"])


def forward(params, src: SequenceBatch, trg_in: SequenceBatch, num_heads=8,
            remat=False, full_seq=False, mesh=None, zigzag=False,
            return_aux=False, moe_top_k=2):
    enc = encode(params, src, num_heads, remat=remat,
                 full_seq=full_seq, mesh=mesh, return_aux=return_aux,
                 moe_top_k=moe_top_k)
    enc_out, aux = enc if return_aux else (enc, None)
    logits = decode(params, enc_out, src.mask(), trg_in, num_heads,
                    remat=remat, full_seq=full_seq, mesh=mesh,
                    zigzag=zigzag)
    return (logits, aux) if return_aux else logits


def loss(params, src, trg_in, trg_next, num_heads=8, label_smoothing=0.1,
         remat=False, full_seq=False, mesh=None, zigzag=False,
         moe_aux_weight=0.01, moe_top_k=2):
    logits, aux = forward(params, src, trg_in, num_heads, remat=remat,
                          full_seq=full_seq, mesh=mesh, zigzag=zigzag,
                          return_aux=True, moe_top_k=moe_top_k)
    labels = trg_next.data
    if labels.ndim == 3:
        labels = labels[..., 0]
    tok_mask = trg_in.mask(jnp.float32)
    if zigzag:
        # logits are in zigzag order; align labels + mask the same way
        # (masked CE is permutation-invariant, so no unpermute needed)
        order = _zigzag_idx(labels.shape[1], mesh)
        labels = labels[:, order]
        tok_mask = tok_mask[:, order]
    per_tok = _token_ce(logits, labels, label_smoothing)
    per_seq = losses.masked_seq_mean(per_tok, tok_mask.astype(per_tok.dtype))
    return jnp.mean(per_seq) + moe_aux_weight * aux


def _token_ce(logits, labels, label_smoothing):
    """Per-token (optionally label-smoothed) cross-entropy — the ONE
    definition loss() and lm_loss() share."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    if label_smoothing:
        v = logits.shape[-1]
        onehot = jax.nn.one_hot(labels, v)
        smoothed = onehot * (1 - label_smoothing) + label_smoothing / v
        return -jnp.sum(smoothed * logp, axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]


def lm_loss(params, tokens: SequenceBatch, num_heads=8, segment_ids=None,
            positions=None, mesh=None, zigzag=False, remat=False,
            label_smoothing=0.0, moe_aux_weight=0.01, moe_top_k=2,
            pos_type="learned"):
    """Decoder-only (GPT-style) causal LM: the encoder stack run causal,
    next-token cross-entropy with the input embedding tied as the output
    projection.  Token-mean objective (the standard LM loss — every real
    token weighs the same regardless of how rows were packed).

    segment_ids/positions (pack_sequences layout) train PACKED rows with
    every segment isolated: label t is token t+1 of the SAME segment, so
    each segment's last token — and padding — carries no label.  mesh
    (seq>1) runs the causal attention sequence-parallel; zigzag=True
    additionally balances the causal ring (labels are aligned to the
    zigzag order internally — masked token-mean is permutation-
    invariant).  The modern training plane the reference's
    Argument.sequenceStartPositions pointed toward: no-padding batches,
    long-context sharding, one loss call."""
    ids = tokens.data
    b, t = ids.shape
    if segment_ids is not None:
        seg = segment_ids
        valid = jnp.concatenate(
            [(seg[:, :-1] > 0) & (seg[:, :-1] == seg[:, 1:]),
             jnp.zeros((b, 1), bool)], axis=1)
    else:
        m = tokens.mask() > 0
        # label for position t exists iff position t+1 is a real token
        valid = jnp.concatenate([m[:, 1:], jnp.zeros((b, 1), bool)],
                                axis=1)
    labels = jnp.roll(ids, -1, axis=1)      # wrap at T-1 is masked out
    logits, aux = lm_logits(params, tokens, num_heads, remat=remat,
                            mesh=mesh, segment_ids=segment_ids,
                            positions=positions, zigzag=zigzag,
                            moe_top_k=moe_top_k, pos_type=pos_type,
                            return_aux=True)
    if zigzag:
        order = _zigzag_idx(t, mesh)
        labels, valid = labels[:, order], valid[:, order]
    per_tok = _token_ce(logits, labels, label_smoothing)
    w = valid.astype(per_tok.dtype)
    ce = jnp.sum(per_tok * w) / jnp.maximum(jnp.sum(w), 1.0)
    # MoE load-balance aux (exactly 0 for a dense trunk, so the weight
    # is inert there)
    return ce + moe_aux_weight * aux


def _lm_project(params, h, shard_axis=None):
    """Final LN + tied-embedding projection (the GPT/pre-LN convention,
    same ln_f as decode): without the LN the un-normalized residual
    stream's depth-growing magnitude would set the softmax temperature.
    Accepts a quantized tree too (idempotent dequant — external callers
    like the prefill ladder hand it raw engine params).

    shard_axis (trace-time, like num_heads): inside the serving
    shard_map, src_emb is a LOCAL vocab stripe [V/n, d] — each chip
    computes its logit columns exactly as the single chip would (a
    column slice of a matmul touches no other column's contraction) and
    the tiled all-gather concatenates them back in device order, i.e.
    the original column order.  This is the LOGITS seam of the sharded
    decode step (docs/serving.md "Sharded decode")."""
    params = _maybe_dequant(params)
    local = linear.matmul(_ln(params["ln_f"], h), params["src_emb"].T)
    if shard_axis is None:
        return local
    return jax.lax.all_gather(local, shard_axis, axis=-1, tiled=True)


def _lm_embed(params, ids, shard_axis=None):
    """Input-embedding gather, vocab-sharded under ``shard_axis``: each
    chip looks up ``ids - its_stripe_offset`` against its local [V/n, d]
    stripe — ``embedding_lookup`` returns EXACT zero rows for the
    out-of-stripe (now out-of-range) ids, so the psum adds ``n-1`` exact
    zeros to the one real row and reproduces the replicated gather
    bit-for-bit (x + 0.0 == x).  The single-chip convention that
    out-of-vocab ids embed to zeros is preserved: such ids miss EVERY
    stripe.  This is the (cheap) third collective of the sharded step,
    [tokens, d]-sized."""
    emb = params["src_emb"]
    if shard_axis is None:
        return emb_ops.embedding_lookup(emb, ids)
    off = jax.lax.axis_index(shard_axis) * emb.shape[0]
    return jax.lax.psum(emb_ops.embedding_lookup(emb, ids - off),
                        shard_axis)


def lm_logits(params, tokens: SequenceBatch, num_heads=8,
              return_aux=False, **encode_kw):
    """Full-sequence LM logits [B, T, V]: the lm_generate oracle and the
    building block lm_loss uses via encode(causal=True) + _lm_project.
    return_aux=True additionally returns the MoE load-balance aux (0 for
    a dense trunk)."""
    out = encode(params, tokens, num_heads, causal=True,
                 return_aux=return_aux, **encode_kw)
    if return_aux:
        h, aux = out
        return _lm_project(params, h), aux
    return _lm_project(params, out)


# --------------------------------------------------------- cached decode

def init_decode_cache(params, enc_out, max_len):
    """Per-decoder-layer self-attention K/V buffers ([B, max_len, D],
    written one position per step).  A plain pytree, so beam search's lane
    reordering (ops/beam.py gather_state) reindexes it for free.  The
    cross-attention K/V are NOT here — they never change during decode, so
    they stay out of the scan state (see cross_kv) and are closed over
    instead of being re-gathered every step."""
    if max_len > params["pos"].shape[0]:
        # fail fast like the full-decode oracle would; dynamic_slice would
        # otherwise silently clamp and reuse the last position row
        raise ValueError(
            f"decode max_len {max_len} exceeds the positional table "
            f"({params['pos'].shape[0]}); re-init the model with a larger "
            "max_len")
    b, _, d = enc_out.shape
    return [{"k": jnp.zeros((b, max_len, d), enc_out.dtype),
             "v": jnp.zeros((b, max_len, d), enc_out.dtype)}
            for _ in params["dec"]]


def cross_kv(params, enc_out):
    """Per-decoder-layer cross-attention K/V, computed once per source."""
    return [{"xk": linear.matmul(enc_out, blk["xattn"]["wk"]),
             "xv": linear.matmul(enc_out, blk["xattn"]["wv"])}
            for blk in params["dec"]]


def _attend(q, k, v, num_heads, mask):
    """q: [B, Tq, D] against k/v: [B, T, Dkv] with mask [B, T] (shared
    by every query lane) or [B, Tq, T] (per-lane — the chunked-prefill
    step, where lane i of row r attends cols <= positions[r] + i) ->
    [B, Tq, D].  Tiny-Tq attention: always the masked XLA path (flash
    needs big tiles).  Dkv < D means grouped KV heads (GQA) — repeated
    up to full heads here, so the CACHE stays small."""
    b, tq, d = q.shape
    tk, dkv = k.shape[1], k.shape[2]
    dh = d // num_heads
    hkv = dkv // dh
    qh = q.reshape(b, tq, num_heads, dh).transpose(0, 2, 1, 3)
    kh = attn_ops.repeat_kv_heads(
        k.reshape(b, tk, hkv, dh).transpose(0, 2, 1, 3), num_heads)
    vh = attn_ops.repeat_kv_heads(
        v.reshape(b, tk, hkv, dh).transpose(0, 2, 1, 3), num_heads)
    mh = (mask[:, None, None, :] if mask.ndim == 2
          else mask[:, None, :, :])
    out = attn_ops.dot_product_attention(
        qh, kh, vh, mask=mh, use_flash=False)
    return out.transpose(0, 2, 1, 3).reshape(b, tq, d)


def decode_step_cached(params, src_mask, prev_ids, t, cache, cross,
                       num_heads=8):
    """One incremental decode position.

    prev_ids: [B] token at position t; t: scalar int32; cross: cross_kv()
    output; returns (logits [B, V], updated cache).  Equivalent to column
    t of the full decode() — proven by tests/test_transformer_decode.py."""
    b = prev_ids.shape[0]
    max_len = cache[0]["k"].shape[1]
    x = emb_ops.embedding_lookup(params["trg_emb"], prev_ids)[:, None]
    x = x * math.sqrt(x.shape[-1]) \
        + jax.lax.dynamic_slice_in_dim(params["pos"], t, 1)[None]
    pos_mask = jnp.arange(max_len)[None, :] <= t          # [1, max_len]
    pos_mask = jnp.broadcast_to(pos_mask, (b, max_len))
    new_cache = []
    for blk, c, cx in zip(params["dec"], cache, cross):
        x, nc = _cached_self_attn(blk, x, c, t, pos_mask, num_heads)
        hx = _ln(blk["ln_x"], x)
        xq = linear.matmul(hx, blk["xattn"]["wq"])
        xat = _attend(xq, cx["xk"], cx["xv"], num_heads, src_mask > 0)
        x = x + linear.matmul(xat, blk["xattn"]["wo"])
        x = x + _ffn(blk["ffn"], _ln(blk["ln2"], x))
        new_cache.append(nc)
    x = _ln(params["ln_f"], x)
    return linear.matmul(x, params["out"])[:, 0], new_cache


def _beam_setup(params, src, beam_size, num_heads, moe_top_k=2):
    """Shared oracle/serving preamble: encode once, tile lane-major."""
    b = src.data.shape[0]
    enc_out = encode(params, src, num_heads, moe_top_k=moe_top_k)
    enc_l = jnp.repeat(enc_out, beam_size, axis=0)
    src_mask_l = jnp.repeat(src.mask(), beam_size, axis=0)
    return b, b * beam_size, enc_l, src_mask_l


def generate_cached(params, src: SequenceBatch, beam_size=4, max_len=64,
                    bos_id=0, eos_id=1, num_heads=8, length_penalty=0.6,
                    moe_top_k=2):
    """Beam decode with KV-cached incremental steps: O(T) attention per new
    token instead of re-running the full decoder stack over the whole
    prefix (O(T^2) per token) — the serving-path decoder."""
    b, bk, enc_l, src_mask_l = _beam_setup(params, src, beam_size,
                                           num_heads, moe_top_k)
    # invariant across steps AND identical across a row's lanes: closed
    # over, not carried in the scan state (gather_state would re-copy it
    # per emitted token)
    cross = cross_kv(params, enc_l)

    def step_fn(state, prev_ids):
        cache, step = state
        logits, cache = decode_step_cached(
            params, src_mask_l, prev_ids, step[0], cache, cross, num_heads)
        return jax.nn.log_softmax(logits, axis=-1), (cache, step + 1)

    init_state = (init_decode_cache(params, enc_l, max_len),
                  jnp.zeros((bk,), jnp.int32))
    return beam_ops.beam_search(step_fn, init_state, b, beam_size, max_len,
                                bos_id, eos_id, length_penalty=length_penalty)


def generate(params, src: SequenceBatch, beam_size=4, max_len=64, bos_id=0,
             eos_id=1, num_heads=8, length_penalty=0.6, moe_top_k=2):
    """Beam decode, full-recompute step (the numerics oracle for
    generate_cached; prefer generate_cached for serving throughput)."""
    b, bk, enc_l, src_mask_l = _beam_setup(params, src, beam_size,
                                           num_heads, moe_top_k)

    def step_fn(state, prev_ids):
        toks, step = state           # toks: [BK, max_len]; step: [BK] (equal)
        t = step[0]
        toks = jax.vmap(lambda row, v: row.at[t].set(v))(toks, prev_ids)
        trg = SequenceBatch(toks, step + 1)
        logits = decode(params, enc_l, src_mask_l, trg, num_heads)
        last = jnp.take_along_axis(
            logits, jnp.broadcast_to(t.reshape(1, 1, 1),
                                     (bk, 1, logits.shape[-1])), axis=1)[:, 0]
        return jax.nn.log_softmax(last, axis=-1), (toks, step + 1)

    init_state = (jnp.full((bk, max_len), eos_id, jnp.int32),
                  jnp.zeros((bk,), jnp.int32))
    return beam_ops.beam_search(step_fn, init_state, b, beam_size, max_len,
                                bos_id, eos_id, length_penalty=length_penalty)


# ------------------------------------------------------ decoder-only LM

def _rope_flat(x_btd, positions, head_dim):
    """Apply rope to a flat [B, T, H*head_dim] projection: split heads,
    rotate, re-flatten — cached K is stored ROTATED (the standard
    KV-cache convention; old keys never need re-rotation).  Head count
    comes from the width, so grouped-KV projections rotate correctly."""
    b, t, d = x_btd.shape
    h = d // head_dim
    xh = x_btd.reshape(b, t, h, head_dim).transpose(0, 2, 1, 3)
    xh = attn_ops.rope(xh, positions)
    return xh.transpose(0, 2, 1, 3).reshape(b, t, d)


def _kv_writes(c, k_new, v_new):
    """The ONE quantize-on-write decision every cached-attn variant
    shares: an int8 cache (``"ks" in c`` — quant/kv sidecars) quantizes
    the new K/V per (position, head) and returns the int8 values plus
    their scales; a float cache passes through (scales None).  K and V
    each use their OWN sidecar's head count, matching
    ``_kv_layer_buffers``' per-projection sizing."""
    if "ks" in c:
        k_set, sk = kvq.quantize_heads(k_new, c["ks"].shape[-1])
        v_set, sv = kvq.quantize_heads(v_new, c["vs"].shape[-1])
        return k_set, v_set, sk, sv
    return k_new, v_new, None, None


def _kv_view(k, ks):
    """The matching read: dequantize an int8 buffer by its sidecar
    (``ks`` is None on the float path — identity).  Every position's
    K/V — including the step's own write — goes through the same
    quantize->dequantize round trip, so prefill/step composition and
    replay stay exact under quantization."""
    return kvq.dequantize_heads(k, ks) if ks is not None else k


def _kv_commit(c, upd, k_set, v_set, sk, sv):
    """Apply the K/V (+ sidecar) cache writes through the variant's
    ``upd(buffer, value)`` indexer — the ONE cache-update assembly all
    cached-attn variants and the prefill share.  Returns ``(nc, ks,
    vs)`` with ks/vs None on the float path (``_kv_writes``'s twin)."""
    nc = {"k": upd(c["k"], k_set), "v": upd(c["v"], v_set)}
    if sk is None:
        return nc, None, None
    ks, vs = upd(c["ks"], sk), upd(c["vs"], sv)
    nc.update(ks=ks, vs=vs)
    return nc, ks, vs


def _cached_self_attn(blk, x, c, t, pos_mask, num_heads, rope_pos=None):
    """Shared incremental self-attention block: write this position's K/V
    into the cache, attend over positions <= t, residual-add — ONE
    definition for decode_step_cached and lm_decode_step so the two
    cached steps cannot drift.  An int8 cache quantizes the write and
    attends over the dequantized view (``_kv_writes``/``_kv_view``)."""
    h = _ln(blk["ln1"], x)
    k_new = linear.matmul(h, blk["attn"]["wk"])
    q = linear.matmul(h, blk["attn"]["wq"])
    if rope_pos is not None:
        dh = q.shape[-1] // num_heads
        k_new = _rope_flat(k_new, rope_pos, dh)
        q = _rope_flat(q, rope_pos, dh)
    v_new = linear.matmul(h, blk["attn"]["wv"])
    k_set, v_set, sk, sv = _kv_writes(c, k_new, v_new)
    upd = lambda buf, val: jax.lax.dynamic_update_slice_in_dim(
        buf, val, t, axis=1)
    nc, ks, vs = _kv_commit(c, upd, k_set, v_set, sk, sv)
    att = _attend(q, _kv_view(nc["k"], ks), _kv_view(nc["v"], vs),
                  num_heads, pos_mask)
    return x + linear.matmul(att, blk["attn"]["wo"]), nc


def lm_prefill(params, prompt, max_len, num_heads=8, moe_top_k=2,
               pos_type="learned", kv_dtype=None):
    """Batched causal prefill: run the trunk over the WHOLE prompt in one
    pass (the MXU-friendly leg), writing every position's K/V into fresh
    decode caches.  Returns (per-position hidden states [B, Tp, D],
    cache) — the state lm_decode_step continues from; the caller
    gathers the position(s) it needs BEFORE the d_model x vocab
    projection (projecting every prompt position would multiply the
    most expensive matmul by Tp).  Equivalent to Tp sequential
    lm_decode_step calls (the generation oracle test covers the
    composition), ~Tp x fewer serial steps.  With ragged prompts
    causality keeps padding positions out of real ones.

    kv_dtype="int8" (quant/kv.py) quantizes each position's K/V on the
    way into the cache AND attends over the quantize->dequantize round
    trip — exactly what sequential quantized decode steps compute, so
    the prefill/step composition stays exact under quantization (slot
    recovery, CoW re-seating and continuation replay depend on it)."""
    b, tp = prompt.shape
    cache = init_lm_cache(params, b, max_len, kv_dtype=kv_dtype,
                          num_heads=num_heads)
    params = _maybe_dequant(params)
    if (pos_type == "learned") != ("pos" in params):
        raise ValueError(
            f"pos_type={pos_type!r} but params were initialized "
            f"{'with' if 'pos' in params else 'without'} a learned "
            "positional table — pass the SAME pos_type used at init")
    x = emb_ops.embedding_lookup(params["src_emb"], prompt)
    x = x * math.sqrt(x.shape[-1])
    if pos_type == "learned":
        x = x + params["pos"][:tp][None]
    new_cache = []
    for blk, c in zip(params["enc"], cache):
        h = _ln(blk["ln1"], x)
        k = linear.matmul(h, blk["attn"]["wk"])
        v = linear.matmul(h, blk["attn"]["wv"])
        q = linear.matmul(h, blk["attn"]["wq"])
        d = q.shape[-1]
        dh = d // num_heads
        if pos_type == "rope":
            # cache stores ROTATED keys (old keys never re-rotate)
            k = _rope_flat(k, jnp.arange(tp), dh)
            q = _rope_flat(q, jnp.arange(tp), dh)
        hkv = k.shape[-1] // dh
        k_set, v_set, sk, sv = _kv_writes(c, k, v)
        import importlib
        # importlib: the ops.pallas package re-exports the
        # flash_attention FUNCTION, shadowing the submodule attribute
        _flash_mod = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        # int8 caches first try the quant flash kernel (the
        # pallas_prefill_quant trace-time routing): the just-quantized
        # int8 bytes + scale sidecars stream straight into the kernel,
        # widened in registers — no dequantized f32 [Tp, Dkv] buffer
        # (perf/analytic.assert_prefill_kv_quantized pins its absence).
        # The quantization math above is IDENTICAL either way, so the
        # cache stays bit-exact to sequential quantized steps on every
        # path.
        att = _flash_mod.maybe_prefill_quant(q, k_set, v_set, sk, sv,
                                             num_heads)
        if att is None:
            if sk is not None:
                # quantize-on-write + attend over the round trip:
                # position p's K/V is quantized BEFORE any later
                # position attends it, so the batched pass equals
                # sequential quantized steps
                k, v = _kv_view(k_set, sk), _kv_view(v_set, sv)
            split = lambda a, hh: a.reshape(b, tp, hh, dh).transpose(
                0, 2, 1, 3)
            # batched causal pass: the pallas_prefill flag (trace-time,
            # like pallas_decode) routes it through
            # ops/pallas/flash_attention — O(Tp) HBM, no [Tp, Tp] score
            # matrix (perf/analytic.py's prefill-flash gate pins its
            # absence).  The CPU tier-1 default stays the masked XLA
            # reference so greedy bit-identity discipline is untouched;
            # flash_attention itself falls back on shapes its blocking
            # cannot cover.
            att = attn_ops.dot_product_attention(
                split(q, num_heads),
                attn_ops.repeat_kv_heads(split(k, hkv), num_heads),
                attn_ops.repeat_kv_heads(split(v, hkv), num_heads),
                causal=True, use_flash=_flash_mod.prefill_flash_enabled())
            att = att.transpose(0, 2, 1, 3).reshape(b, tp, d)
        x = x + linear.matmul(att, blk["attn"]["wo"])
        x = x + _block_ffn(blk, _ln(blk["ln2"], x), moe_top_k)[0]
        upd = lambda buf, val: jax.lax.dynamic_update_slice_in_dim(
            buf, val, 0, axis=1)
        new_cache.append(
            _kv_commit(c, upd, k_set, v_set, sk, sv)[0])
    return x, new_cache


def lm_decode_step(params, prev_ids, t, cache, num_heads=8,
                   moe_top_k=2, pos_type="learned"):
    """One incremental position of the decoder-only trunk (the enc stack
    run causal, lm_loss's twin): prev_ids [B] at position t -> (logits
    [B, V], updated cache).  cache: per-enc-layer K/V buffers
    [B, max_len, Dkv] where Dkv is each block's KV projection width —
    d_model normally, num_kv_heads*head_dim on a GQA trunk
    (init_lm_cache sizes off the weights)."""
    params = _maybe_dequant(params)
    b = prev_ids.shape[0]
    max_len = cache[0]["k"].shape[1]
    x = emb_ops.embedding_lookup(params["src_emb"], prev_ids)[:, None]
    x = x * math.sqrt(x.shape[-1])
    if pos_type == "learned":
        x = x + jax.lax.dynamic_slice_in_dim(params["pos"], t, 1)[None]
    rope_pos = (jnp.asarray(t)[None] if pos_type == "rope" else None)
    pos_mask = jnp.broadcast_to(jnp.arange(max_len)[None, :] <= t,
                                (b, max_len))
    new_cache = []
    for blk, c in zip(params["enc"], cache):
        x, nc = _cached_self_attn(blk, x, c, t, pos_mask, num_heads,
                                  rope_pos)
        x = x + _block_ffn(blk, _ln(blk["ln2"], x), moe_top_k)[0]
        new_cache.append(nc)
    return _lm_project(params, x)[:, 0], new_cache


def _shard_gather_att(att, shard_axis):
    """The ATTENTION-OUTPUT seam of the sharded decode step: inside the
    serving shard_map each chip's ``att`` is the contiguous head stripe
    its local wq/wk/wv columns produced — numerically identical to the
    same columns of the replicated computation (head h attends only to
    its own KV stripe; a column slice of a matmul reorders nothing).
    The tiled all-gather concatenates the stripes in device order =
    head order, so the replicated wo contraction that follows runs on a
    bit-identical [.., d] input.  No-op when unsharded."""
    if shard_axis is None:
        return att
    return jax.lax.all_gather(att, shard_axis, axis=-1, tiled=True)


def _cached_self_attn_slots(blk, x, c, positions, pos_mask, num_heads,
                            rope_pos=None, shard_axis=None):
    """``_cached_self_attn`` with a PER-ROW position vector: row r writes
    its K/V at its own ``positions[r]`` (scatter instead of a shared
    dynamic slice) and attends under its own mask row.  Row r's compute is
    exactly ``_cached_self_attn``'s at t=positions[r] — every matmul here
    is batched over the leading axis ([S, 1, D] @ [D, H]), so a row's
    numerics do not depend on what the other slots are doing.  The
    continuous-batching decode slab (serving/decode_engine.py) runs on
    this.

    shard_axis: set inside the serving shard_map — blk's wq/wk/wv are
    local head stripes, c local KV stripes, num_heads the LOCAL count;
    everything below computes the stripe exactly as the single chip
    computes those heads, and ``_shard_gather_att`` reassembles before
    the replicated wo."""
    h = _ln(blk["ln1"], x)
    k_new = linear.matmul(h, blk["attn"]["wk"])
    q = linear.matmul(h, blk["attn"]["wq"])
    if rope_pos is not None:
        dh = q.shape[-1] // num_heads
        k_new = _rope_flat(k_new, rope_pos, dh)
        q = _rope_flat(q, rope_pos, dh)
    v_new = linear.matmul(h, blk["attn"]["wv"])
    rows = jnp.arange(positions.shape[0])
    # quantize-on-write for an int8 cache (scales None on the f32 path)
    k_set, v_set, sk, sv = _kv_writes(c, k_new[:, 0], v_new[:, 0])
    upd = lambda buf, val: buf.at[rows, positions].set(val)
    nc, ks, vs = _kv_commit(c, upd, k_set, v_set, sk, sv)
    k, v = nc["k"], nc["v"]
    # fused Pallas decode kernel (ops/pallas/decode_attention.py): the
    # row's stripe streams HBM->VMEM once, no score matrix, grouped KV
    # expanded in registers (int8: + scale sidecars dequantized there
    # too).  None -> the reference XLA path (the CPU tier-1 default;
    # pallas_decode flag gates — see maybe_slab), which widens the
    # stripe via _kv_view — same math as the kernel's register dequant.
    from paddle_tpu.ops.pallas import decode_attention as _decode_kernels
    att = _decode_kernels.maybe_slab(q[:, 0], k, v, positions, num_heads,
                                     kscale=ks, vscale=vs)
    if att is None:
        att = _attend(q, _kv_view(k, ks), _kv_view(v, vs), num_heads,
                      pos_mask)
    else:
        att = att[:, None]
    att = _shard_gather_att(att, shard_axis)
    return x + linear.matmul(att, blk["attn"]["wo"]), nc


def lm_decode_step_slots(params, prev_ids, positions, cache, num_heads=8,
                         moe_top_k=2, pos_type="learned",
                         shard_axis=None):
    """One incremental decode position for EVERY row of a slot slab, each
    row at its OWN position — the continuous-batching twin of
    ``lm_decode_step`` (which advances the whole batch at one shared t).

    prev_ids [S], positions [S] int32; cache: per-enc-layer K/V
    [S, max_len, Dkv] (``init_lm_cache``) -> (logits [S, V], new cache).
    Row r computes exactly ``lm_decode_step``'s result at t=positions[r]:
    the position row is gathered instead of sliced, the K/V write is a
    per-row scatter, and the attention mask is per-row ``<= positions[r]``
    — same values, same masked-softmax width (masked logits sit at -1e30,
    whose exp is exactly 0.0, so cache width beyond a row's position never
    perturbs its numerics).  tests/test_decode_engine.py pins the
    per-request bit-identity against ``lm_generate``.

    shard_axis (trace-time): the tensor-parallel serving path — params/
    cache are local stripes and num_heads the LOCAL head count (src_emb
    shards its VOCAB axis, so the embedded x keeps the full width d and
    the sqrt(d) scale is untouched).  The draft trunk's rollout runs
    through here inside its own shard_map."""
    params = _maybe_dequant(params)
    s = prev_ids.shape[0]
    max_len = cache[0]["k"].shape[1]
    x = _lm_embed(params, prev_ids, shard_axis)[:, None]
    x = x * math.sqrt(x.shape[-1])
    if pos_type == "learned":
        x = x + params["pos"][positions][:, None]
    rope_pos = positions[:, None] if pos_type == "rope" else None
    pos_mask = jnp.arange(max_len)[None, :] <= positions[:, None]
    pos_mask = jnp.broadcast_to(pos_mask, (s, max_len))
    new_cache = []
    for blk, c in zip(params["enc"], cache):
        x, nc = _cached_self_attn_slots(blk, x, c, positions, pos_mask,
                                        num_heads, rope_pos, shard_axis)
        x = x + _block_ffn(blk, _ln(blk["ln2"], x), moe_top_k)[0]
        new_cache.append(nc)
    return _lm_project(params, x, shard_axis)[:, 0], new_cache


def _cached_self_attn_paged(blk, x, c, positions, tables, pos_mask,
                            num_heads, rope_pos=None):
    """``_cached_self_attn_slots`` over a PAGED KV pool: the cache is a
    shared pool of fixed-size blocks ``[num_blocks, block_size, Dkv]``
    and each row's K/V live wherever its block table says (``tables``
    [S, blocks_per_row] int32 of physical block ids).  Row r writes its
    new K/V into block ``tables[r, p // bs]`` at offset ``p % bs`` (host
    scheduling guarantees writer exclusivity: a block being written has
    pool refcount 1 — the copy-on-write fork in serving/kv_pool.py; free
    rows all target the reserved scratch block 0, whose contents are
    never attended) and attends over the GATHER of its own chain —
    ``pool[tables[r]]`` flattened back to a contiguous [S, T, Dkv] view.
    The gathered values at positions <= positions[r] are exactly what
    the slab holds at those logical positions, and masked positions
    contribute exp(-1e30) = 0.0, so row r's numerics are bit-identical
    to ``_cached_self_attn_slots`` — shared physical blocks and all."""
    s = positions.shape[0]
    block_size = c["k"].shape[1]
    h = _ln(blk["ln1"], x)
    k_new = linear.matmul(h, blk["attn"]["wk"])
    q = linear.matmul(h, blk["attn"]["wq"])
    if rope_pos is not None:
        dh = q.shape[-1] // num_heads
        k_new = _rope_flat(k_new, rope_pos, dh)
        q = _rope_flat(q, rope_pos, dh)
    v_new = linear.matmul(h, blk["attn"]["wv"])
    rows = jnp.arange(s)
    bids = tables[rows, positions // block_size]
    offs = positions % block_size
    # quantize-on-write for an int8 pool (scales None on the f32 path)
    k_set, v_set, sk, sv = _kv_writes(c, k_new[:, 0], v_new[:, 0])
    upd = lambda buf, val: buf.at[bids, offs].set(val)
    nc, ks, vs = _kv_commit(c, upd, k_set, v_set, sk, sv)
    k, v = nc["k"], nc["v"]
    # fused Pallas paged kernel (ops/pallas/decode_attention.py): the
    # block table rides as scalar-prefetch data and the kernel walks
    # each row's chain in place — no [S, T, Dkv] gathered copy, no
    # score matrix (perf/analytic.py's fusion-proof gate pins the
    # gather's absence; int8 sidecar blocks ride the same walk).
    # None -> the reference chain-gather path.
    from paddle_tpu.ops.pallas import decode_attention as _decode_kernels
    att = _decode_kernels.maybe_paged(q[:, 0], k, v, positions, tables,
                                      num_heads, kscale=ks, vscale=vs)
    if att is not None:
        att = att[:, None]
    else:
        # chain gather: [S, blocks_per_row, bs, Dkv] -> [S, T, Dkv]
        # where T = blocks_per_row * bs covers every position a row can
        # hold (int8: the gathered chain widens via its gathered scales)
        k_rows = _kv_view(k[tables],
                          None if ks is None else ks[tables]) \
            .reshape(s, -1, k.shape[-1])
        v_rows = _kv_view(v[tables],
                          None if vs is None else vs[tables]) \
            .reshape(s, -1, v.shape[-1])
        att = _attend(q, k_rows, v_rows, num_heads, pos_mask)
    return x + linear.matmul(att, blk["attn"]["wo"]), nc


def lm_decode_step_paged(params, prev_ids, positions, cache, tables,
                         num_heads=8, moe_top_k=2, pos_type="learned"):
    """One incremental decode position for every row of a PAGED slot
    slab — the block-pool twin of ``lm_decode_step_slots``.

    prev_ids [S], positions [S] int32; cache: per-enc-layer K/V pools
    ``[num_blocks, block_size, Dkv]`` (``init_lm_cache_paged``); tables:
    [S, blocks_per_row] int32 physical block ids (block 0 = the reserved
    scratch block free rows point at) -> (logits [S, V], new cache).
    Row r computes exactly ``lm_decode_step_slots``'s result at
    t=positions[r]: same gathered K/V values at every unmasked position,
    same masked-softmax width semantics (-1e30 logits exp to exactly
    0.0).  The block table is DATA, not shape: admission, eviction and
    copy-on-write forks churn ``tables`` between steps without ever
    retracing (tests/test_kv_pool.py pins 1 warm-up trace, 0 after)."""
    params = _maybe_dequant(params)
    s = prev_ids.shape[0]
    block_size = cache[0]["k"].shape[1]
    t_span = tables.shape[1] * block_size
    x = emb_ops.embedding_lookup(params["src_emb"], prev_ids)[:, None]
    x = x * math.sqrt(x.shape[-1])
    if pos_type == "learned":
        x = x + params["pos"][positions][:, None]
    rope_pos = positions[:, None] if pos_type == "rope" else None
    pos_mask = jnp.arange(t_span)[None, :] <= positions[:, None]
    pos_mask = jnp.broadcast_to(pos_mask, (s, t_span))
    new_cache = []
    for blk, c in zip(params["enc"], cache):
        x, nc = _cached_self_attn_paged(blk, x, c, positions, tables,
                                        pos_mask, num_heads, rope_pos)
        x = x + _block_ffn(blk, _ln(blk["ln2"], x), moe_top_k)[0]
        new_cache.append(nc)
    return _lm_project(params, x)[:, 0], new_cache


# ------------------------------------------------ chunked decode steps
#
# The unified chunked-prefill serving step (serving/decode_engine.py
# prefill_chunk > 0; docs/serving.md "Chunked prefill"): ONE jitted step
# advances a MIX of decode rows (1 token) and prompt-ingesting rows (up
# to K tokens — Sarathi-style chunked prefill on the Orca-style slot
# scheduler).  Row r feeds tokens[r, :lengths[r]] at positions
# positions[r] .. positions[r]+lengths[r]-1; lane i attends causally
# within the chunk AND over the row's live prefix (cols <= its own
# position), and the returned logits are each row's LAST fed lane —
# exactly what lm_prefill + lm_decode_step compose to, so greedy
# streams stay bit-identical to lm_generate.  lengths is DATA: the
# per-step chunk budget never retraces.


def _chunk_lanes(positions, lengths, kk):
    """(clamped lane indices [S, K], per-lane query positions [S, K]).
    Lanes past a row's ``lengths`` clamp to its LAST active lane: they
    re-compute (and re-write) the last real token's K/V — identical
    values at an identical target, so the duplicate scatter is
    deterministic and no garbage ever lands in the cache."""
    lane = jnp.arange(kk)[None, :]
    li = jnp.minimum(lane, lengths[:, None] - 1)
    return li, positions[:, None] + li


def _cached_self_attn_chunk(blk, x, c, li, qpos, pos_mask, num_heads,
                            rope_pos=None, shard_axis=None):
    """``_cached_self_attn_slots`` at Tq=K: row r writes lane i's K/V at
    its own ``qpos[r, i]`` and lane i attends under its own mask row
    (cols <= qpos[r, i] — causal within the chunk, clamped at the live
    prefix).  Writes happen BEFORE the attention, so within-chunk
    causality falls out of the ordinary masked cache read.  Lane
    numerics are position-local (batched matmuls over the flattened
    [S*K] leading axis), so each lane computes exactly what the Tq=1
    step computes at that position."""
    s, kk, _d = x.shape
    h = _ln(blk["ln1"], x)
    k_new = linear.matmul(h, blk["attn"]["wk"])
    q = linear.matmul(h, blk["attn"]["wq"])
    if rope_pos is not None:
        dh = q.shape[-1] // num_heads
        k_new = _rope_flat(k_new, rope_pos, dh)
        q = _rope_flat(q, rope_pos, dh)
    v_new = linear.matmul(h, blk["attn"]["wv"])
    # clamped-lane selection: inactive lanes take the last active lane's
    # values, so their (duplicate-target) writes are bit-identical
    k_sel = jnp.take_along_axis(k_new, li[:, :, None], axis=1)
    v_sel = jnp.take_along_axis(v_new, li[:, :, None], axis=1)
    rows = jnp.arange(s)[:, None]
    # quantize-on-write (int8 cache): duplicate clamped lanes quantize
    # identical values to identical targets, so the scatter stays
    # deterministic; scales None on the f32 path
    k_set, v_set, sk, sv = _kv_writes(c, k_sel, v_sel)
    upd = lambda buf, val: buf.at[rows, qpos].set(val)
    nc, ks, vs = _kv_commit(c, upd, k_set, v_set, sk, sv)
    k, v = nc["k"], nc["v"]
    # fused Tq=chunk Pallas kernel (ops/pallas/decode_attention.py):
    # each row's stripe streams HBM->VMEM once and every lane consumes
    # it in VMEM — no [S, K, T] score matrix.  None -> reference path.
    from paddle_tpu.ops.pallas import decode_attention as _decode_kernels
    att = _decode_kernels.maybe_slab_chunk(q, k, v, qpos, num_heads,
                                           kscale=ks, vscale=vs)
    if att is None:
        att = _attend(q, _kv_view(k, ks), _kv_view(v, vs), num_heads,
                      pos_mask)
    att = _shard_gather_att(att, shard_axis)
    return x + linear.matmul(att, blk["attn"]["wo"]), nc


def lm_decode_chunk_slots(params, tokens, positions, lengths, cache,
                          num_heads=8, moe_top_k=2, pos_type="learned",
                          all_lanes=False, shard_axis=None):
    """The Tq=chunk generalization of ``lm_decode_step_slots``: every
    row advances ``lengths[r]`` (1..K) positions in ONE step.

    tokens [S, K] int32 (row r's lanes < lengths[r] are fed; the rest
    are ignored — callers pad with anything in-vocab), positions [S]
    (lane 0's position), lengths [S] in [1, K]; cache as
    ``init_lm_cache`` -> (logits [S, V] at each row's LAST fed lane,
    new cache).  A row with lengths[r]=1 computes exactly
    ``lm_decode_step_slots``'s result; a row chunking through its prompt
    computes exactly what sequential steps would — tokens and lengths
    are DATA, so mixing decode and prefill rows never retraces.

    all_lanes=True (a TRACE-TIME constant, like num_heads) projects
    EVERY lane instead of only the last fed one -> logits [S, K, V]:
    the speculative-decoding verify surface (serving/speculative.py) —
    lane i's logits are the target's next-token distribution after the
    prefix through lane i, so host-side acceptance can take the longest
    matched greedy prefix from ONE step.

    shard_axis (trace-time): the tensor-parallel serving path
    (docs/serving.md "Sharded decode") — inside the engine's shard_map
    params/cache are local head/vocab stripes and num_heads the LOCAL
    count; the two all-gather seams (attention output, logits) plus the
    embedding psum reassemble bit-identically to the single chip."""
    params = _maybe_dequant(params)
    s, kk = tokens.shape
    max_len = cache[0]["k"].shape[1]
    li, qpos = _chunk_lanes(positions, lengths, kk)
    x = _lm_embed(params, tokens, shard_axis)
    x = x * math.sqrt(x.shape[-1])
    if pos_type == "learned":
        x = x + params["pos"][qpos]
    rope_pos = qpos if pos_type == "rope" else None
    pos_mask = jnp.arange(max_len)[None, None, :] <= qpos[:, :, None]
    new_cache = []
    for blk, c in zip(params["enc"], cache):
        x, nc = _cached_self_attn_chunk(blk, x, c, li, qpos, pos_mask,
                                        num_heads, rope_pos, shard_axis)
        x = x + _block_ffn(blk, _ln(blk["ln2"], x), moe_top_k)[0]
        new_cache.append(nc)
    if all_lanes:
        return _lm_project(params, x, shard_axis), new_cache
    h_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    return _lm_project(params, h_last, shard_axis)[:, 0], new_cache


def _cached_self_attn_chunk_paged(blk, x, c, li, qpos, tables, pos_mask,
                                  num_heads, rope_pos=None,
                                  shard_axis=None):
    """``_cached_self_attn_chunk`` over the paged block pool: lane i of
    row r scatter-writes into ``pool[tables[r, qpos//bs], qpos % bs]``
    (host scheduling provisions exclusive blocks for the WHOLE span
    before the step — ``PagedKVState.write_plan_span``) and attends over
    the gather of its own chain."""
    s = qpos.shape[0]
    block_size = c["k"].shape[1]
    h = _ln(blk["ln1"], x)
    k_new = linear.matmul(h, blk["attn"]["wk"])
    q = linear.matmul(h, blk["attn"]["wq"])
    if rope_pos is not None:
        dh = q.shape[-1] // num_heads
        k_new = _rope_flat(k_new, rope_pos, dh)
        q = _rope_flat(q, rope_pos, dh)
    v_new = linear.matmul(h, blk["attn"]["wv"])
    k_sel = jnp.take_along_axis(k_new, li[:, :, None], axis=1)
    v_sel = jnp.take_along_axis(v_new, li[:, :, None], axis=1)
    rows = jnp.arange(s)[:, None]
    bids = tables[rows, qpos // block_size]
    offs = qpos % block_size
    k_set, v_set, sk, sv = _kv_writes(c, k_sel, v_sel)
    upd = lambda buf, val: buf.at[bids, offs].set(val)
    nc, ks, vs = _kv_commit(c, upd, k_set, v_set, sk, sv)
    k, v = nc["k"], nc["v"]
    from paddle_tpu.ops.pallas import decode_attention as _decode_kernels
    att = _decode_kernels.maybe_paged_chunk(q, k, v, qpos, tables,
                                            num_heads, kscale=ks,
                                            vscale=vs)
    if att is None:
        k_rows = _kv_view(k[tables],
                          None if ks is None else ks[tables]) \
            .reshape(s, -1, k.shape[-1])
        v_rows = _kv_view(v[tables],
                          None if vs is None else vs[tables]) \
            .reshape(s, -1, v.shape[-1])
        att = _attend(q, k_rows, v_rows, num_heads, pos_mask)
    att = _shard_gather_att(att, shard_axis)
    return x + linear.matmul(att, blk["attn"]["wo"]), nc


def lm_decode_chunk_paged(params, tokens, positions, lengths, cache,
                          tables, num_heads=8, moe_top_k=2,
                          pos_type="learned", all_lanes=False,
                          shard_axis=None):
    """The Tq=chunk generalization of ``lm_decode_step_paged`` — the
    paged twin of ``lm_decode_chunk_slots`` (same lane semantics, block
    tables as DATA; ``all_lanes`` the same trace-time verify switch;
    ``shard_axis`` the same tensor-parallel switch — each chip walks
    the SAME replicated block tables over its local Hkv/n stripe of
    every pool block)."""
    params = _maybe_dequant(params)
    s, kk = tokens.shape
    block_size = cache[0]["k"].shape[1]
    t_span = tables.shape[1] * block_size
    li, qpos = _chunk_lanes(positions, lengths, kk)
    x = _lm_embed(params, tokens, shard_axis)
    x = x * math.sqrt(x.shape[-1])
    if pos_type == "learned":
        x = x + params["pos"][qpos]
    rope_pos = qpos if pos_type == "rope" else None
    pos_mask = jnp.arange(t_span)[None, None, :] <= qpos[:, :, None]
    new_cache = []
    for blk, c in zip(params["enc"], cache):
        x, nc = _cached_self_attn_chunk_paged(blk, x, c, li, qpos,
                                              tables, pos_mask,
                                              num_heads, rope_pos,
                                              shard_axis)
        x = x + _block_ffn(blk, _ln(blk["ln2"], x), moe_top_k)[0]
        new_cache.append(nc)
    if all_lanes:
        return _lm_project(params, x, shard_axis), new_cache
    h_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    return _lm_project(params, h_last, shard_axis)[:, 0], new_cache


def _kv_layer_buffers(params, lead_shape, kv_dtype, num_heads):
    """One layer list of K/V buffers shaped ``lead_shape + (Dkv,)`` —
    the shared core of ``init_lm_cache``/``init_lm_cache_paged``.
    ``kv_dtype="int8"`` adds the per-(position, head) f32 scale
    sidecars ``{"ks", "vs"}`` of ``lead_shape + (Hkv,)`` (quant/kv.py);
    None/"float32" keeps the float layout byte-identical to before.
    The sidecar width derives from ``num_heads``, so int8 REQUIRES the
    trunk's real head count — a defaulted/wrong one would silently
    quantize at the wrong granularity."""
    if kv_dtype not in (None, "float32", "int8"):
        raise ValueError(f"kv_dtype={kv_dtype!r} (supported: "
                         "'float32', 'int8')")
    emb = params["src_emb"]
    dt = jnp.float32 if _w_quantized(emb) else emb.dtype
    d = _w_shape(emb)[1]
    if kv_dtype == "int8":
        if num_heads is None:
            raise ValueError(
                "kv_dtype='int8' needs the trunk's num_heads: the "
                "per-(position, head) scale sidecar is sized Hkv = "
                "Dkv / (d_model / num_heads)")
        if d % num_heads:
            raise ValueError(f"num_heads={num_heads} does not divide "
                             f"d_model={d}")
    layers = []
    for blk in params["enc"]:
        dkv = _w_shape(blk["attn"]["wk"])[1]
        dkv_v = _w_shape(blk["attn"]["wv"])[1]
        c = {"k": jnp.zeros(lead_shape + (dkv,),
                            jnp.int8 if kv_dtype == "int8" else dt),
             "v": jnp.zeros(lead_shape + (dkv_v,),
                            jnp.int8 if kv_dtype == "int8" else dt)}
        if kv_dtype == "int8":
            dh = d // num_heads
            if dkv % dh or dkv_v % dh:
                raise ValueError(
                    f"head_dim {dh} (d_model {d} / num_heads "
                    f"{num_heads}) does not divide Dkv {dkv}/{dkv_v}")
            c["ks"] = jnp.zeros(lead_shape + (dkv // dh,), jnp.float32)
            c["vs"] = jnp.zeros(lead_shape + (dkv_v // dh,), jnp.float32)
        layers.append(c)
    return layers


def init_lm_cache_paged(params, num_blocks, block_size, max_len=None,
                        kv_dtype=None, num_heads=None):
    """K/V block pools for ``lm_decode_step_paged``: per enc layer
    ``{"k","v"}`` of ``[num_blocks, block_size, Dkv]`` — the paged twin
    of ``init_lm_cache`` (same per-block KV width inference, so GQA
    trunks get proportionally smaller blocks).  Block 0 is reserved as
    the scratch block free rows read/write; the allocator
    (serving/kv_pool.py BlockPool) hands out ids 1..num_blocks-1.
    ``max_len``: the logical per-row span, validated against the learned
    positional table exactly like ``init_lm_cache`` (a rope trunk has no
    cap).  ``kv_dtype="int8"``: int8 pools + per-(position, head) scale
    sidecar pools ``[num_blocks, block_size, Hkv]`` — ~4x smaller
    blocks, so a fixed byte budget holds ~2x the block count
    (serving/kv_pool.slab_equivalent_blocks)."""
    if num_blocks < 2 or block_size < 1:
        raise ValueError(
            f"paged cache needs num_blocks >= 2 (one is the reserved "
            f"scratch block) and block_size >= 1; got {num_blocks}, "
            f"{block_size}")
    if max_len is not None and "pos" in params \
            and max_len > _w_shape(params["pos"])[0]:
        raise ValueError(
            f"lm decode max_len {max_len} exceeds the positional table "
            f"({_w_shape(params['pos'])[0]}); re-init with a larger max_len "
            "or use pos_type='rope'")
    return _kv_layer_buffers(params, (num_blocks, block_size), kv_dtype,
                             num_heads)


def init_lm_cache(params, batch, max_len, kv_dtype=None,
                  num_heads=None):
    """K/V buffers for lm_decode_step (mirrors init_decode_cache, but for
    the enc stack the LM trunk runs).  ``kv_dtype="int8"``: int8 slab +
    per-(position, head) f32 scale sidecars (quant/kv.py)."""
    if "pos" in params and max_len > _w_shape(params["pos"])[0]:
        # learned table caps the length; a rope trunk has no cap
        raise ValueError(
            f"lm decode max_len {max_len} exceeds the positional table "
            f"({_w_shape(params['pos'])[0]}); re-init with a larger max_len "
            "or use pos_type='rope'")
    # per-block KV width from the projection itself: grouped-KV trunks
    # (init num_kv_heads=) get the proportionally smaller cache — the
    # point of GQA at serving time
    return _kv_layer_buffers(params, (batch, max_len), kv_dtype,
                             num_heads)


def lm_generate(params, prompt, max_len, num_heads=8, temperature=0.0,
                top_k=0, rng=None, eos_id=None, prompt_lengths=None,
                moe_top_k=2, pos_type="learned", kv_dtype=None):
    """Autoregressive sampling from the decoder-only LM (KV-cached, one
    jittable lax.scan): prompt [B, Tp] int ids -> ids [B, max_len]
    beginning with each row's prompt.  prompt_lengths [B] supports
    RAGGED prompts in one batch (rows padded to Tp; row i's generation
    starts at its own length — pad value never matters because causal
    attention keeps padding positions out of real ones and the scan
    rewrites each position's K/V as it passes).

    temperature=0 is greedy (deterministic argmax — the rollout the
    oracle test replays with full-sequence lm_logits); otherwise
    categorical over logits/temperature, optionally truncated to the
    top_k highest-probability tokens.  eos_id: rows that emit it keep
    emitting it (done-row pinning, matching beam-search semantics).

    The prompt is consumed by ONE batched causal pass (lm_prefill — the
    MXU-friendly leg that fills the KV cache for all Tp positions at
    once); the per-token scan starts at the SHORTEST row's length and
    re-feeds longer rows' remaining prompt tokens (their K/V rewrites
    are identical — projections are position-local).

    kv_dtype="int8": the scan runs on the quantized KV cache
    (quant/kv.py) — the single-batch oracle for the quantized serving
    engines, exactly as the f32 path is for theirs."""
    params = _maybe_dequant(params)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, tp = prompt.shape
    if not (0 < tp <= max_len):
        raise ValueError(f"prompt length {tp} must be in [1, {max_len}]")
    if temperature and rng is None:
        raise ValueError("temperature > 0 sampling needs rng=jax.random."
                         "PRNGKey(...)")
    vocab = params["src_emb"].shape[0]
    if top_k and not (0 < top_k <= vocab):
        # the negative gather index would silently clamp inside jit and
        # disable truncation entirely
        raise ValueError(f"top_k={top_k} must be in [1, vocab={vocab}]")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    if prompt_lengths is None:
        lengths = jnp.full((b,), tp, jnp.int32)
        t_start = tp
    else:
        lengths = jnp.asarray(prompt_lengths, jnp.int32)
        # static scan start: the shortest row's length when concrete
        # (the usual outside-jit call); under a trace fall back to
        # re-feeding from position 1 (still one prefill for the bulk).
        # Two traced shapes exist: an ARGUMENT is a Tracer (int() would
        # raise TracerIntegerConversionError), a closed-over constant
        # stages its ops (ConcretizationTypeError) — handle both.
        if isinstance(lengths, jax.core.Tracer):
            t_start = 1
        else:
            try:
                t_start = int(jnp.min(lengths))
            except jax.errors.ConcretizationTypeError:
                t_start = 1
            else:
                if t_start < 1 or int(jnp.max(lengths)) > tp:
                    raise ValueError(
                        f"prompt_lengths must be in [1, {tp}] (got "
                        f"[{t_start}, {int(jnp.max(lengths))}])")

    def sample(logits, key):
        if not temperature:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k:
            from paddle_tpu.ops.sampling import top_k as topk_op
            kvals, _ = topk_op(logits, top_k)       # lax.top_k, no sort
            logits = jnp.where(logits < kvals[:, -1:], -jnp.inf, logits)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    hidden, cache = lm_prefill(params, prompt, max_len, num_heads,
                               moe_top_k, pos_type, kv_dtype=kv_dtype)
    # each row's first generated token comes from ITS last real
    # position — gather the hidden state first, project ONE position
    # (the d_model x vocab matmul is the expensive part)
    h_last = jnp.take_along_axis(
        hidden, (lengths - 1)[:, None, None], axis=1)
    logits0 = _lm_project(params, h_last)[:, 0]
    rng, sub = jax.random.split(rng)
    first = sample(logits0, sub)
    ids0 = jnp.zeros((b, max_len), jnp.int32)
    ids0 = jax.lax.dynamic_update_slice(ids0, prompt, (0, 0))
    # seed each row's first generated slot; a row whose prompt already
    # fills max_len keeps its prompt value (clamped position, old value)
    seed_pos = jnp.minimum(lengths, max_len - 1)
    keep = jnp.take_along_axis(ids0, seed_pos[:, None], axis=1)[:, 0]
    ids0 = ids0.at[jnp.arange(b), seed_pos].set(
        jnp.where(lengths < max_len, first, keep))

    def step(carry, t):
        # token at t is generated for rows with lengths <= t, still
        # prompt for longer rows (re-fed; identical K/V rewrite)
        ids, cache, key, done = carry
        tok = jnp.take_along_axis(ids, t[None, None], axis=1)[:, 0]
        logits, cache = lm_decode_step(params, tok, t, cache,
                                       num_heads, moe_top_k, pos_type)
        key, sub = jax.random.split(key)
        nxt = sample(logits, sub)
        if eos_id is not None:
            # only GENERATED eos pins a row: a bos==eos vocab or an
            # eos-valued separator inside the prompt must not suppress
            # the whole continuation
            done = done | ((tok == eos_id) & (t >= lengths))
            nxt = jnp.where(done, eos_id, nxt)
        # rows whose prompt extends past t keep their given token; the
        # slot at a row's own `lengths` was seeded from prefill logits
        cur = jnp.take_along_axis(ids, (t + 1)[None, None], axis=1)[:, 0]
        nxt = jnp.where((t + 1) <= lengths, cur, nxt)
        ids = jax.vmap(lambda row, v: row.at[t + 1].set(v))(ids, nxt)
        return (ids, cache, key, done), None

    init = (ids0, cache, rng, jnp.zeros((b,), bool))
    (ids, _, _, _), _ = jax.lax.scan(step, init,
                                     jnp.arange(t_start, max_len - 1))
    return ids
