"""LeNet-ish MNIST CNN (reference demo/mnist: conv-pool x2 + fc, the PR1
end-to-end slice per SURVEY.md §7.4).  Functional NHWC/bf16 implementation."""

import jax
import jax.numpy as jnp

from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linear, losses, initializers


def init(rng, num_classes=10):
    ks = jax.random.split(rng, 8)
    cinit = initializers.conv_default()
    ninit = initializers.normal()
    return {
        "c1": {"w": cinit(ks[0], (5, 5, 1, 20)), "b": jnp.zeros((20,))},
        "c2": {"w": cinit(ks[1], (5, 5, 20, 50)), "b": jnp.zeros((50,))},
        "f1": {"w": ninit(ks[2], (4 * 4 * 50, 500)), "b": jnp.zeros((500,))},
        "f2": {"w": ninit(ks[3], (500, num_classes)),
               "b": jnp.zeros((num_classes,))},
    }


def forward(params, images):
    """images: [B, 784] in [-1, 1] -> logits [B, 10]."""
    x = images.reshape(-1, 28, 28, 1)
    x = conv_ops.conv2d(x, params["c1"]["w"], params["c1"]["b"], act="relu")
    x = conv_ops.max_pool2d(x, (2, 2))
    x = conv_ops.conv2d(x, params["c2"]["w"], params["c2"]["b"], act="relu")
    x = conv_ops.max_pool2d(x, (2, 2))
    x = x.reshape(x.shape[0], -1)
    x = linear.fc(x, params["f1"]["w"], params["f1"]["b"], act="relu")
    return linear.fc(x, params["f2"]["w"], params["f2"]["b"])


def loss(params, images, labels):
    return jnp.mean(losses.classification_cost(forward(params, images), labels))
