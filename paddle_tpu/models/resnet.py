"""ResNet family (reference demo/image_classification resnet configs +
BASELINE.json 'ResNet-50 images/sec/chip' headline metric).

Functional NHWC implementation designed for the MXU: bf16 conv compute with
f32 accumulation (ops.conv), BN as explicit state, identity downsample via
strided 1x1.  Supports CIFAR depths (20/32/56: 3 stages of n blocks) and
ImageNet bottleneck depths (50/101/152).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linear, losses
from paddle_tpu.ops.norm import batch_norm_train, batch_norm_infer


def _conv_init(rng, kh, kw, cin, cout):
    fan = kh * kw * cin
    return (2.0 / fan) ** 0.5 * jax.random.normal(
        rng, (kh, kw, cin, cout), jnp.float32)


def _bn_params(c):
    return {"gamma": jnp.ones((c,)), "beta": jnp.zeros((c,))}


def _bn_state(c):
    return {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}


def _apply_bn(x, p, s, train, momentum=0.9):
    if train:
        y, (nm, nv) = batch_norm_train(x, p["gamma"], p["beta"],
                                       s["mean"], s["var"], momentum)
        return y, {"mean": nm, "var": nv}
    return batch_norm_infer(x, p["gamma"], p["beta"], s["mean"], s["var"]), s


def init(rng, depth=50, num_classes=1000, in_channels=3, imagenet=None):
    """Returns (params, state)."""
    imagenet = imagenet if imagenet is not None else depth in (50, 101, 152)
    keys = iter(jax.random.split(rng, 512))
    params, state = {}, {}

    if imagenet:
        blocks_per = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3),
                      152: (3, 8, 36, 3)}[depth]
        widths = (256, 512, 1024, 2048)
        params["stem"] = {"w": _conv_init(next(keys), 7, 7, in_channels, 64),
                          "bn": _bn_params(64)}
        state["stem"] = _bn_state(64)
        cin = 64
        for si, (n, w) in enumerate(zip(blocks_per, widths)):
            mid = w // 4
            for bi in range(n):
                nm = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                blk = {
                    "c1": {"w": _conv_init(next(keys), 1, 1, cin, mid),
                           "bn": _bn_params(mid)},
                    "c2": {"w": _conv_init(next(keys), 3, 3, mid, mid),
                           "bn": _bn_params(mid)},
                    "c3": {"w": _conv_init(next(keys), 1, 1, mid, w),
                           "bn": _bn_params(w)},
                }
                st = {"c1": _bn_state(mid), "c2": _bn_state(mid),
                      "c3": _bn_state(w)}
                if cin != w or stride != 1:
                    blk["proj"] = {"w": _conv_init(next(keys), 1, 1, cin, w),
                                   "bn": _bn_params(w)}
                    st["proj"] = _bn_state(w)
                params[nm], state[nm] = blk, st
                cin = w
        params["head"] = {"w": 0.01 * jax.random.normal(
            next(keys), (cin, num_classes)), "b": jnp.zeros((num_classes,))}
    else:
        n = {20: 3, 32: 5, 56: 9, 110: 18}[depth]
        widths = (16, 32, 64)
        params["stem"] = {"w": _conv_init(next(keys), 3, 3, in_channels, 16),
                          "bn": _bn_params(16)}
        state["stem"] = _bn_state(16)
        cin = 16
        for si, w in enumerate(widths):
            for bi in range(n):
                nm = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                blk = {"c1": {"w": _conv_init(next(keys), 3, 3, cin, w),
                              "bn": _bn_params(w)},
                       "c2": {"w": _conv_init(next(keys), 3, 3, w, w),
                              "bn": _bn_params(w)}}
                st = {"c1": _bn_state(w), "c2": _bn_state(w)}
                if cin != w or stride != 1:
                    blk["proj"] = {"w": _conv_init(next(keys), 1, 1, cin, w),
                                   "bn": _bn_params(w)}
                    st["proj"] = _bn_state(w)
                params[nm], state[nm] = blk, st
                cin = w
        params["head"] = {"w": 0.01 * jax.random.normal(
            next(keys), (cin, num_classes)), "b": jnp.zeros((num_classes,))}
    return params, state


def _bottleneck(x, blk, st, stride, train):
    new_st = {}
    y = conv_ops.conv2d(x, blk["c1"]["w"], stride=(1, 1))
    y, new_st["c1"] = _apply_bn(y, blk["c1"]["bn"], st["c1"], train)
    y = jax.nn.relu(y)
    y = conv_ops.conv2d(y, blk["c2"]["w"], stride=(stride, stride),
                        padding=(1, 1))
    y, new_st["c2"] = _apply_bn(y, blk["c2"]["bn"], st["c2"], train)
    y = jax.nn.relu(y)
    y = conv_ops.conv2d(y, blk["c3"]["w"], stride=(1, 1))
    y, new_st["c3"] = _apply_bn(y, blk["c3"]["bn"], st["c3"], train)
    if "proj" in blk:
        x = conv_ops.conv2d(x, blk["proj"]["w"], stride=(stride, stride))
        x, new_st["proj"] = _apply_bn(x, blk["proj"]["bn"], st["proj"], train)
    return jax.nn.relu(x + y), new_st


def _basic(x, blk, st, stride, train):
    new_st = {}
    y = conv_ops.conv2d(x, blk["c1"]["w"], stride=(stride, stride),
                        padding=(1, 1))
    y, new_st["c1"] = _apply_bn(y, blk["c1"]["bn"], st["c1"], train)
    y = jax.nn.relu(y)
    y = conv_ops.conv2d(y, blk["c2"]["w"], stride=(1, 1), padding=(1, 1))
    y, new_st["c2"] = _apply_bn(y, blk["c2"]["bn"], st["c2"], train)
    if "proj" in blk:
        x = conv_ops.conv2d(x, blk["proj"]["w"], stride=(stride, stride))
        x, new_st["proj"] = _apply_bn(x, blk["proj"]["bn"], st["proj"], train)
    return jax.nn.relu(x + y), new_st


def forward(params, state, images, depth=50, train=True, imagenet=None,
            return_pool=False, remat=False):
    """images: NHWC float.  depth/imagenet are static config (must match
    init).  Returns (logits, new_state); with return_pool=True the first
    element is instead the global-average-pooled features [N, D] (the layer
    the reference model_zoo classify.py --job=extract dumps).

    remat=True checkpoints each residual block (jax.checkpoint): activations
    are recomputed in the backward pass instead of stored, trading ~33%
    FLOPs for the HBM that MXU-saturating batches (bs>=512) need."""
    imagenet = imagenet if imagenet is not None else depth in (50, 101, 152)
    bottleneck, basic = _bottleneck, _basic
    if remat:
        bottleneck = jax.checkpoint(_bottleneck, static_argnums=(3, 4))
        basic = jax.checkpoint(_basic, static_argnums=(3, 4))
    new_state = {}
    x = images
    if imagenet:
        x = conv_ops.conv2d(x, params["stem"]["w"], stride=(2, 2),
                            padding=(3, 3))
        x, new_state["stem"] = _apply_bn(x, params["stem"]["bn"],
                                         state["stem"], train)
        x = jax.nn.relu(x)
        x = conv_ops.max_pool2d(x, (3, 3), (2, 2), (1, 1))
        blocks_per = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3),
                      152: (3, 8, 36, 3)}[depth]
        for si, n in enumerate(blocks_per):
            for bi in range(n):
                nm = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                x, new_state[nm] = bottleneck(x, params[nm], state[nm],
                                              stride, train)
    else:
        x = conv_ops.conv2d(x, params["stem"]["w"], padding=(1, 1))
        x, new_state["stem"] = _apply_bn(x, params["stem"]["bn"],
                                         state["stem"], train)
        x = jax.nn.relu(x)
        n = {20: 3, 32: 5, 56: 9, 110: 18}[depth]
        for si in range(3):
            for bi in range(n):
                nm = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                x, new_state[nm] = basic(x, params[nm], state[nm], stride,
                                         train)
    x = jnp.mean(x, axis=(1, 2))
    if return_pool:
        return x, new_state
    logits = linear.fc(x, params["head"]["w"], params["head"]["b"])
    return logits, new_state


def features(params, state, images, depth=50, imagenet=None):
    """Global-average-pooled features before the classifier head (reference
    demo/model_zoo/resnet/classify.py --job=extract): the exact pooled
    tensor, no head matmul, no compute-dtype round trip."""
    feats, _ = forward(params, state, images, depth, train=False,
                       imagenet=imagenet, return_pool=True)
    return feats


def loss(params, state, images, labels, depth=50, train=True, imagenet=None,
         remat=False):
    logits, new_state = forward(params, state, images, depth, train, imagenet,
                                remat=remat)
    return jnp.mean(losses.classification_cost(logits, labels)), new_state
