"""Runtime object API (reference paddle/api/PaddleAPI.h SWIG surface:
GradientMachine, SequenceGenerator, Arguments, Trainer — the classes
`py_paddle`/gan_trainer drove directly).

The SWIG layer existed to reach the C++ runtime from Python; here the
runtime is jitted JAX, so these are thin stateful wrappers over
Topology/optim that keep the reference's imperative call shapes:

    gm = GradientMachine.createFromTopology(cost)
    outs = gm.forward(feed)                       # inference
    cost, outs = gm.forwardBackward(feed)         # accumulate grads
    gm.updateParameters(optimizer_state_applied_internally)
"""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.layers.graph import LayerOutput, Topology


class GradientMachine:
    """Reference GradientMachine::forward/backward/forwardBackward
    (gserver/gradientmachines/GradientMachine.h:72-129) as a stateful
    wrapper: holds params, caches jitted fwd / value_and_grad fns, and
    accumulates gradients until updateParameters."""

    def __init__(self, topology: Topology, params, seed=1):
        self.topology = topology
        self.parameters = params
        self.model_state = topology.init_state()
        self._rng = jax.random.PRNGKey(seed)
        self._grads = None
        # inference must see the moving BN stats accumulated by
        # forwardBackward, so state threads through here too
        self._fwd = jax.jit(
            lambda p, feed, state: topology.apply(p, feed, mode="test",
                                                  state=state))

        # the reference GradientMachine::forwardBackward runs PASS_TRAIN:
        # dropout active, batch-norm stats updated — so thread mode='train'
        # with an rng and the mutable model state here too
        def loss_fn(p, feed, state, rng):
            out, new_state = topology.apply(p, feed, mode="train", rng=rng,
                                            state=state, return_state=True)
            outs = out if isinstance(out, tuple) else (out,)
            total = sum(jnp.mean(o.data if hasattr(o, "data") else o)
                        for o in outs)
            return total, (outs, new_state)
        self._vag = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))

    @classmethod
    def createFromTopology(cls, outputs, seed=1):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        topo = Topology(list(outs))
        return cls(topo, topo.init(jax.random.PRNGKey(seed)))

    createFromConfigProto = createFromTopology  # reference-name alias

    @classmethod
    def create(cls, outputs, mode=None, seed=1, **kw):
        """Mode-dispatched construction, the reference Trainer's entry
        (Trainer.cpp:150-156: ask GradientMachineMode's registry first,
        fall back to the built-in machines).  mode=None builds the
        standard machine; a registered mode name dispatches to its
        factory(outputs, seed=..., **kw)."""
        if mode is None:
            if kw:
                raise TypeError(
                    f"GradientMachine.create got {sorted(kw)} without "
                    "mode=; extra kwargs only reach a registered mode's "
                    "factory")
            return cls.createFromTopology(outputs, seed=seed)
        return GradientMachineMode.create(mode, outputs, seed=seed, **kw)

    def _feedify(self, feed):
        return {k: v if isinstance(v, SequenceBatch) else jnp.asarray(v)
                for k, v in feed.items()}

    def forward(self, feed):
        return self._fwd(self.parameters, self._feedify(feed),
                         self.model_state)

    forwardTest = forward

    def forwardBackward(self, feed):
        """Accumulates gradients (reference PASS_TRAIN forwardBackward);
        returns (cost, outputs)."""
        self._rng, step_rng = jax.random.split(self._rng)
        (cost, (outs, new_state)), grads = self._vag(
            self.parameters, self._feedify(feed), self.model_state, step_rng)
        self.model_state = new_state
        if self._grads is None:
            self._grads = grads
        else:
            self._grads = jax.tree_util.tree_map(jnp.add, self._grads,
                                                 grads)
        return float(cost), outs

    def getGradients(self):
        return self._grads

    def resetGradients(self):
        self._grads = None

    def getParameters(self):
        return self.parameters

    def setParameters(self, params):
        self.parameters = params

    def randParameters(self, seed=1):
        self.parameters = self.topology.init(jax.random.PRNGKey(seed))

    def applyOptimizer(self, optimizer, opt_state):
        """One update from the accumulated gradients; returns new state."""
        if self._grads is None:
            raise RuntimeError("no gradients accumulated; call "
                               "forwardBackward first")
        self.parameters, opt_state = optimizer.update(
            self._grads, opt_state, self.parameters)
        self._grads = None
        return opt_state


class GradientMachineMode:
    """Plugin registry for custom training-machine modes (reference
    gserver/gradientmachines/GradientMachineMode.h: link-time-registered
    modes the Trainer tries before its built-ins, Trainer.cpp:150-156).

    The reference existed so C++ plugins could add machines without
    patching the Trainer; the Python-native equivalent is a name-keyed
    factory registry feeding GradientMachine.create(mode=...):

        @GradientMachineMode.register("averaged")
        def make(outputs, seed=1, **kw):
            return MyAveragedMachine(outputs, seed)

        gm = GradientMachine.create(cost, mode="averaged")

    Factories return anything honoring the GradientMachine call surface
    (forward/forwardBackward/applyOptimizer...)."""

    _registry = {}

    @classmethod
    def register(cls, mode, factory=None):
        """Register `factory` under `mode` (usable as a decorator).
        Re-registering an existing mode raises — shadowing a plugin
        silently was the reference's mode-id collision failure."""
        if factory is None:
            return lambda f: cls.register(mode, f)
        if mode in cls._registry:
            raise ValueError(f"GradientMachineMode {mode!r} already "
                             "registered")
        cls._registry[mode] = factory
        return factory

    @classmethod
    def is_registered(cls, mode):
        return mode in cls._registry

    @classmethod
    def registered(cls):
        return tuple(sorted(cls._registry))

    @classmethod
    def create(cls, mode, outputs, **kw):
        """tryCreateGradientMachine: build via the registered factory;
        unknown modes fail fast naming what IS registered."""
        if mode not in cls._registry:
            raise KeyError(
                f"no GradientMachineMode {mode!r}; registered: "
                f"{list(cls.registered()) or 'none'}")
        return cls._registry[mode](outputs, **kw)

    @classmethod
    def unregister(cls, mode):
        cls._registry.pop(mode, None)


class MultiNetwork:
    """Several sub-networks trained jointly with parameters shared by name
    (reference gserver/gradientmachines/MultiNetwork.{h,cpp}: model_type
    'multi_nn' holding sub-NeuralNetworks; forward runs every sub-net, the
    cost is their sum).

    Functionally each sub-net is a Topology; one merged params dict is
    initialized across all of them (Topology's name-keyed param sharing
    makes cross-network weight tying automatic, like the reference's
    parameter sharing across sub-models), and forward/forwardBackward fan
    out to every sub-net — or to one selected sub-net, the GAN-style
    alternating-update pattern the reference drove through the API."""

    def __init__(self, sub_outputs, seed=1):
        """sub_outputs: list of per-subnetwork outputs (LayerOutput or
        list)."""
        self.topologies = [
            Topology(list(o) if isinstance(o, (list, tuple)) else [o])
            for o in sub_outputs]
        rng = jax.random.PRNGKey(seed)
        params = {}
        for topo in self.topologies:
            rng = topo._init_into(params, rng)
        self.parameters = params
        self.machines = [GradientMachine(t, self.parameters, seed=seed)
                         for t in self.topologies]
        for m in self.machines:   # all share ONE params dict view
            m.parameters = self.parameters

    def getSubNetworks(self):
        return self.machines

    def forward(self, feed, subnet=None):
        if subnet is not None:
            return self.machines[subnet].forward(feed)
        return [m.forward(feed) for m in self.machines]

    def forwardBackward(self, feed, subnet=None):
        """Accumulate grads on one sub-net (GAN alternation) or all
        (joint training: costs sum, like the reference's combined
        backward)."""
        if subnet is not None:
            m = self.machines[subnet]
            m.parameters = self.parameters
            return m.forwardBackward(feed)
        results = []
        for m in self.machines:
            m.parameters = self.parameters
            results.append(m.forwardBackward(feed))
        return results

    def _subnet_keys(self, subnet):
        topo = self.topologies[subnet]
        return {topo._param_key(n) for n in topo.order
                if topo._param_key(n) in self.parameters}

    def applyOptimizer(self, optimizer, opt_state, subnet=None):
        """One update of the shared parameters: with subnet given, from that
        machine's grads alone (GAN alternation); otherwise from the SUM of
        every machine's accumulated grads (the reference's joint backward —
        sub-net costs add).

        A subnet update touches ONLY that sub-net's parameter keys: the
        optimizer step runs on the full tree (one jit signature) but
        momentum decay / weight decay on the other sub-nets' zero-grad
        params is discarded — a frozen discriminator must not drift while
        the generator trains."""
        machines = ([self.machines[subnet]] if subnet is not None
                    else self.machines)
        grads = None
        for m in machines:
            if m._grads is None:
                continue
            grads = m._grads if grads is None else jax.tree_util.tree_map(
                jnp.add, grads, m._grads)
            m._grads = None
        if grads is None:
            raise RuntimeError("no gradients accumulated; call "
                               "forwardBackward first")
        new_params, new_state = optimizer.update(grads, opt_state,
                                                 self.parameters)
        if subnet is not None:
            keep = self._subnet_keys(subnet)
            new_params = {k: (v if k in keep else self.parameters[k])
                          for k, v in new_params.items()}
            if isinstance(new_state, dict) and "slots" in new_state \
                    and isinstance(opt_state, dict):
                new_state = dict(new_state)
                new_state["slots"] = {
                    slot: {k: (v if k in keep
                               else opt_state["slots"][slot][k])
                           for k, v in tree.items()}
                    for slot, tree in new_state["slots"].items()}
        self.parameters = new_params
        for m in self.machines:
            m.parameters = self.parameters
        return new_state


class SequenceGenerator:
    """Reference api/SequenceGenerator.cpp: beam-search wrapper over a
    generation layer (layers.beam_search node) with dict decoding."""

    def __init__(self, gen_layer: LayerOutput, params, vocab=None):
        self.topology = Topology(gen_layer)
        self.params = params
        self.vocab = vocab
        self._fn = jax.jit(
            lambda p, feed: self.topology.apply(p, feed, mode="test"))

    def setDict(self, words):
        self.vocab = list(words)

    def generate(self, feed, num_results=1):
        """-> per input row: [(score, [tokens or words])] best-first."""
        res = self._fn(self.params, {
            k: v if isinstance(v, SequenceBatch) else jnp.asarray(v)
            for k, v in feed.items()})
        tokens = np.asarray(res.tokens)
        scores = np.asarray(res.scores)
        lengths = np.asarray(res.lengths)
        out = []
        for b in range(tokens.shape[0]):
            rows = []
            for k in range(min(num_results, tokens.shape[1])):
                ids = list(tokens[b, k, :lengths[b, k]])
                if self.vocab is not None:
                    ids = [self.vocab[t] if 0 <= t < len(self.vocab)
                           else str(t) for t in ids]
                rows.append((float(scores[b, k]), ids))
            out.append(rows)
        return out
