"""Build the native data-path library: python -m paddle_tpu.native.build"""

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))


def build(verbose=True):
    src = os.path.join(_DIR, "src", "dataio.cpp")
    out = os.path.join(_DIR, "libpaddle_tpu_dataio.so")
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
           "-Wall", src, "-o", out]
    if verbose:
        print(" ".join(cmd))
    subprocess.check_call(cmd)
    return out


if __name__ == "__main__":
    path = build()
    print("built", path)
    sys.exit(0)
