"""Build the native data-path library: python -m paddle_tpu.native.build"""

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))


def build(verbose=True):
    src = os.path.join(_DIR, "src", "dataio.cpp")
    out = os.path.join(_DIR, "libpaddle_tpu_dataio.so")
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
           "-Wall", src, "-o", tmp]
    if verbose:
        print(" ".join(cmd))
    subprocess.check_call(cmd)
    os.replace(tmp, out)   # atomic: concurrent builders never see a torn .so
    return out


def _python_flags():
    """Embed flags for THE RUNNING interpreter (a PATH python3-config could
    belong to a different version/ABI than the one importing paddle_tpu)."""
    import sysconfig
    inc = ["-I" + sysconfig.get_path("include")]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        f"{sys.version_info.major}.{sys.version_info.minor}"
    return inc, ([f"-L{libdir}"] if libdir else []) + [f"-lpython{ver}",
                                                      "-ldl", "-lm"]


def build_capi(verbose=True):
    """C inference API (embeds CPython; reference paddle/capi role)."""
    src = os.path.join(_DIR, "src", "capi.cpp")
    out = os.path.join(_DIR, "libpaddle_tpu_capi.so")
    tmp = out + f".tmp{os.getpid()}"
    inc, ld = _python_flags()
    cmd = (["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-Wall", src]
           + inc + ["-o", tmp] + ld)
    if verbose:
        print(" ".join(cmd))
    subprocess.check_call(cmd)
    os.replace(tmp, out)
    return out


def ensure(which="dataio", verbose=False):
    """Build `which` ('dataio' or 'capi') if its .so is missing or older
    than its source.  Best-effort: returns the .so path on success, None
    when the toolchain is unavailable or the build fails.  The binaries
    are intentionally NOT committed — they are rebuilt on demand here.
    Disable with PADDLE_TPU_NO_NATIVE_BUILD=1 (e.g. images without g++)."""
    if os.environ.get("PADDLE_TPU_NO_NATIVE_BUILD"):
        return None
    if which in _FAILED:   # a persistent toolchain failure must not be
        return None        # re-paid per call (e.g. per feeder batch)
    name = {"dataio": "libpaddle_tpu_dataio.so",
            "capi": "libpaddle_tpu_capi.so"}[which]
    src = os.path.join(_DIR, "src", which + ".cpp")
    out = os.path.join(_DIR, name)
    try:
        if (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        return (build if which == "dataio" else build_capi)(verbose=verbose)
    except Exception:   # noqa: BLE001 — missing g++/headers: fall back
        _FAILED.add(which)
        return None


_FAILED = set()   # libs whose build failed this process; see ensure()


def capi_header_dir():
    return os.path.join(_DIR, "include")


if __name__ == "__main__":
    path = build()
    print("built", path)
    path = build_capi()
    print("built", path)
    sys.exit(0)
