"""Build the native data-path library: python -m paddle_tpu.native.build"""

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))


def build(verbose=True):
    src = os.path.join(_DIR, "src", "dataio.cpp")
    out = os.path.join(_DIR, "libpaddle_tpu_dataio.so")
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
           "-Wall", src, "-o", out]
    if verbose:
        print(" ".join(cmd))
    subprocess.check_call(cmd)
    return out


def _python_flags():
    """Embed flags for THE RUNNING interpreter (a PATH python3-config could
    belong to a different version/ABI than the one importing paddle_tpu)."""
    import sysconfig
    inc = ["-I" + sysconfig.get_path("include")]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        f"{sys.version_info.major}.{sys.version_info.minor}"
    return inc, ([f"-L{libdir}"] if libdir else []) + [f"-lpython{ver}",
                                                      "-ldl", "-lm"]


def build_capi(verbose=True):
    """C inference API (embeds CPython; reference paddle/capi role)."""
    src = os.path.join(_DIR, "src", "capi.cpp")
    out = os.path.join(_DIR, "libpaddle_tpu_capi.so")
    inc, ld = _python_flags()
    cmd = (["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-Wall", src]
           + inc + ["-o", out] + ld)
    if verbose:
        print(" ".join(cmd))
    subprocess.check_call(cmd)
    return out


def capi_header_dir():
    return os.path.join(_DIR, "include")


if __name__ == "__main__":
    path = build()
    print("built", path)
    path = build_capi()
    print("built", path)
    sys.exit(0)
