/* Multi-threaded C deployment example (reference capi/examples/
 * model_inference/multi_thread/main.c: N pthreads, each with a machine
 * created by paddle_gradient_machine_create_shared_param over one loaded
 * parameter set).  Here each thread runs on its own pt_capi_clone handle —
 * shared parameters and jitted program, private input/output slots — and
 * the main thread re-runs every thread's input afterwards to check the
 * concurrent results bit-for-bit.
 *
 * Build:
 *   gcc infer_multi_thread.c -I../include -L.. -lpaddle_tpu_capi \
 *       -Wl,-rpath,.. -lpthread -o infer_multi_thread
 * Run:
 *   ./infer_multi_thread <repo_root> <config.py> <model.npz>
 */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "paddle_tpu_capi.h"

enum { NUM_THREAD = 4, NUM_ITER = 25, IN_DIM = 4, OUT_DIM = 2 };

typedef struct {
  int64_t handle;
  int tid;
  float input[IN_DIM];        /* last-iteration input            */
  float prob[OUT_DIM];        /* last-iteration output           */
  int failed;
  char err[512];              /* last_error is thread-local: snapshot it
                                 on the failing thread, not in main */
} thread_ctx;

static void fill_input(float* dst, int tid, int iter) {
  /* deterministic per-(thread, iter) input so the main thread can replay */
  for (int i = 0; i < IN_DIM; ++i)
    dst[i] = (float)((tid * 131 + iter * 17 + i * 7) % 23) / 23.0f - 0.5f;
}

static void* thread_main(void* p) {
  thread_ctx* ctx = (thread_ctx*)p;
  for (int iter = 0; iter < NUM_ITER; ++iter) {
    fill_input(ctx->input, ctx->tid, iter);
    if (pt_capi_set_input_dense(ctx->handle, "x", ctx->input, 1, IN_DIM) !=
            0 ||
        pt_capi_run(ctx->handle) < 1 ||
        pt_capi_get_output(ctx->handle, 0, ctx->prob, OUT_DIM) != OUT_DIM) {
      snprintf(ctx->err, sizeof(ctx->err), "%s", pt_capi_last_error());
      ctx->failed = 1;
      return NULL;
    }
  }
  return NULL;
}

int main(int argc, char** argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <repo_root> <config.py> <model.npz>\n",
            argv[0]);
    return 2;
  }
  if (pt_capi_init(argv[1]) != 0) {
    fprintf(stderr, "init failed: %s\n", pt_capi_last_error());
    return 1;
  }
  int64_t m = pt_capi_create(argv[2], argv[3]);
  if (m < 0) {
    fprintf(stderr, "create failed: %s\n", pt_capi_last_error());
    return 1;
  }

  pthread_t threads[NUM_THREAD];
  thread_ctx ctx[NUM_THREAD];
  for (int i = 0; i < NUM_THREAD; ++i) {
    ctx[i].tid = i;
    ctx[i].failed = 0;
    ctx[i].err[0] = 0;
    ctx[i].handle = pt_capi_clone(m);
    if (ctx[i].handle < 0) {
      fprintf(stderr, "clone failed: %s\n", pt_capi_last_error());
      return 1;
    }
    pthread_create(&threads[i], NULL, thread_main, &ctx[i]);
  }
  for (int i = 0; i < NUM_THREAD; ++i) pthread_join(threads[i], NULL);

  /* replay each thread's final input on the original machine; the
   * concurrent result must match the serial one */
  int rc = 0;
  for (int i = 0; i < NUM_THREAD; ++i) {
    if (ctx[i].failed) {
      fprintf(stderr, "thread %d failed: %s\n", i, ctx[i].err);
      rc = 1;
      continue;
    }
    float ref[OUT_DIM];
    if (pt_capi_set_input_dense(m, "x", ctx[i].input, 1, IN_DIM) != 0 ||
        pt_capi_run(m) < 1 ||
        pt_capi_get_output(m, 0, ref, OUT_DIM) != OUT_DIM) {
      fprintf(stderr, "replay failed: %s\n", pt_capi_last_error());
      rc = 1;
      continue;
    }
    int ok = 1;
    for (int j = 0; j < OUT_DIM; ++j) {
      float d = ctx[i].prob[j] - ref[j];
      if (d < -1e-6f || d > 1e-6f) ok = 0;
    }
    printf("thread %d %s:", i, ok ? "OK" : "MISMATCH");
    for (int j = 0; j < OUT_DIM; ++j) printf(" %.4f", ctx[i].prob[j]);
    printf("\n");
    if (!ok) rc = 1;
    pt_capi_destroy(ctx[i].handle);
  }
  pt_capi_destroy(m);
  return rc;
}
